"""Numeric ops: jnp reference implementations + Pallas TPU kernels.

Every Pallas kernel has a jnp twin with identical semantics; the engine picks
via ``tpu.use_pallas`` (kernel unit tests compare the two, per SURVEY.md
section 4's strategy for kernel testing).
"""
