"""Attention reference implementations (jnp).

These are the semantic ground truth the Pallas kernels are tested against
(SURVEY.md section 4: kernel unit tests compare Pallas outputs vs jnp).  The
engine uses them directly on CPU test meshes and as the `use_pallas=False`
fallback on TPU.

Replaces the capability the reference delegates to vLLM's CUDA
paged-attention (SURVEY.md section 2.1, vllm_backend.py:51 — opaque there,
first-party here).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from vgate_tpu.ops.kv_quant import gather_pages


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Broadcast KV heads across query-head groups (GQA). x: [..., KV, hd]."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=-2)


def _softcap(scores: jnp.ndarray, cap: float) -> jnp.ndarray:
    """Gemma-2 style tanh soft-capping of attention/logit scores (fp32)."""
    if not cap:
        return scores
    return jnp.tanh(scores / cap) * cap


def causal_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, hd]
    k: jnp.ndarray,  # [B, S, KV, hd]
    v: jnp.ndarray,  # [B, S, KV, hd]
    seq_lens: jnp.ndarray,  # [B] real lengths (tokens beyond are padding)
    softcap: float = 0.0,
    window=None,  # int32 scalar; >0 => attend only to the last `window` keys
    scale=None,  # query scale; default hd**-0.5
) -> jnp.ndarray:
    """Causal self-attention over a padded prompt batch. Returns [B, S, H, hd].

    fp32 softmax accumulation; padded key positions are masked out so garbage
    in the padding region cannot leak into real tokens.
    """
    B, S, H, hd = q.shape
    n_rep = H // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    # [B, H, S, S]
    scores = jnp.einsum(
        "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
    ) * scale
    scores = _softcap(scores, softcap)
    pos = jnp.arange(S)
    causal = pos[None, :] <= pos[:, None]  # [S(q), S(k)] keys <= query pos
    key_valid = pos[None, :] < seq_lens[:, None]  # [B, S]
    mask = causal[None, None, :, :] & key_valid[:, None, None, :]
    if window is not None:
        dist = pos[:, None] - pos[None, :]  # q_pos - k_pos, [S, S]
        win_ok = (window <= 0) | (dist < window)
        mask = mask & win_ok[None, None, :, :]
    scores = jnp.where(mask, scores, -1e30)
    probs = jnp.exp(
        scores - jnp.max(scores, axis=-1, keepdims=True)
    )
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum(
        "bhst,bthd->bshd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def flash_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, hd]
    k: jnp.ndarray,  # [B, S, KV, hd]
    v: jnp.ndarray,  # [B, S, KV, hd]
    seq_lens: jnp.ndarray,  # [B] real lengths (tokens beyond are padding)
    block_k: int = 256,
    q_offset=None,  # [B] int32: global position of q[:, 0] (chunked prefill)
    softcap: float = 0.0,
    window=None,  # int32 scalar; >0 => attend only to the last `window` keys
    scale=None,  # query scale; default hd**-0.5
) -> jnp.ndarray:
    """Blockwise causal attention with online softmax. Returns [B, S, H, hd].

    Same semantics as ``causal_prefill_attention`` (the test oracle) but
    scans over key blocks, so peak memory is O(B·H·S·block_k) instead of the
    O(B·H·S²) score materialization — at the 2048 bucket that is ~25 MB per
    block vs ~200 MB (fp32, H=12).  This is the default prefill path; the
    Pallas kernel (ops/pallas/flash_prefill.py) goes further by streaming KV
    through VMEM.

    With ``q_offset`` the queries are a chunk starting at a nonzero global
    position attending to keys laid out from position ``0`` — the
    chunked-prefill path where ``k``/``v`` cover history + current chunk.
    """
    B, S, H, hd = q.shape
    Sk = k.shape[1]
    n_rep = H // k.shape[2]
    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    q32 = q.astype(jnp.float32) * scale

    block_k = min(block_k, Sk)  # buckets are powers of two
    if Sk % block_k:
        raise ValueError(f"key length {Sk} not divisible by {block_k}")
    n_blocks = Sk // block_k

    q_pos = jnp.arange(S)[None, :]  # [1, S]
    if q_offset is not None:
        q_pos = q_pos + q_offset[:, None]  # [B, S]

    def body(carry, blk):
        acc, m, l = carry
        k_blk = jax.lax.dynamic_slice_in_dim(k, blk * block_k, block_k, 1)
        v_blk = jax.lax.dynamic_slice_in_dim(v, blk * block_k, block_k, 1)
        k_blk = repeat_kv(k_blk, n_rep).astype(jnp.float32)
        v_blk = repeat_kv(v_blk, n_rep).astype(jnp.float32)
        k_pos = blk * block_k + jnp.arange(block_k)  # [block_k]
        # [B, S(q), block_k]
        mask = (k_pos[None, None, :] <= q_pos[:, :, None]) & (
            k_pos[None, None, :] < seq_lens[:, None, None]
        )
        if window is not None:
            dist = q_pos[:, :, None] - k_pos[None, None, :]
            mask = mask & ((window <= 0) | (dist < window))
        scores = jnp.einsum(
            "bshd,bthd->bsth", q32, k_blk,
            preferred_element_type=jnp.float32,
        )  # [B, S, block_k, H]
        scores = _softcap(scores, softcap)
        scores = jnp.where(mask[..., None], scores, -1e30)
        m_cur = jnp.max(scores, axis=2)  # [B, S, H]
        m_new = jnp.maximum(m, m_cur)
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[:, :, None, :])
        l = alpha * l + jnp.sum(p, axis=2)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bsth,bthd->bshd", p, v_blk, preferred_element_type=jnp.float32,
        )
        return (acc, m_new, l), None

    acc = jnp.zeros((B, S, H, hd), jnp.float32)
    m = jnp.full((B, S, H), -1e30, jnp.float32)
    l = jnp.zeros((B, S, H), jnp.float32)
    (acc, m, l), _ = jax.lax.scan(
        body, (acc, m, l), jnp.arange(n_blocks)
    )
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, H, hd] one query token per slot
    k_pages: jnp.ndarray,  # [KV, P, page_size, hd] (head-major, kv_cache.py)
    v_pages: jnp.ndarray,  # [KV, P, page_size, hd]
    page_tables: jnp.ndarray,  # [B, pages_per_seq] int32
    seq_lens: jnp.ndarray,  # [B] context length per slot (incl. current token)
    softcap: float = 0.0,
    window=None,  # int32 scalar; >0 => attend only to the last `window` keys
    scale=None,  # query scale; default hd**-0.5
    layer=None,  # int32 scalar: pool layer index — k/v_pages then carry a
    #              leading [L] dim (the carry-threaded decode path)
) -> jnp.ndarray:
    """Decode-step attention over the paged KV cache. Returns [B, H, hd].

    Reference semantics for the Pallas paged kernel: gathers each slot's
    pages into a contiguous [ctx_max] view, masks positions >= seq_len, and
    runs fp32 softmax.  The Pallas version streams only the live pages
    through VMEM instead of materializing the gather.
    """
    B, H, hd = q.shape
    KV = k_pages.shape[1] if layer is not None else k_pages.shape[0]
    page_size = k_pages.shape[-2]
    n_rep = H // KV
    ctx_max = page_tables.shape[1] * page_size

    # gather_pages (ops/kv_quant.py) composes the (layer, head, page)
    # gather so only live pages are read, and DEQUANTIZES int8 pools to
    # f32 on the way (the same f32 the Pallas kernel folds scales in)
    k_sel = gather_pages(k_pages, page_tables, layer=layer)
    v_sel = gather_pages(v_pages, page_tables, layer=layer)

    # [KV, B, pages_per_seq, page_size, hd] -> [B, ctx, KV, hd]
    k = jnp.moveaxis(k_sel.reshape(KV, B, ctx_max, hd), 0, 2)
    v = jnp.moveaxis(v_sel.reshape(KV, B, ctx_max, hd), 0, 2)
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)

    if scale is None:
        scale = 1.0 / (hd ** 0.5)
    scores = jnp.einsum(
        "bhd,bthd->bht", q, k, preferred_element_type=jnp.float32
    ) * scale
    scores = _softcap(scores, softcap)
    t = jnp.arange(ctx_max)[None, :]
    valid = t < seq_lens[:, None]  # [B, ctx]
    if window is not None:
        # the query sits at position seq_len-1: its window covers
        # (seq_len-1-window, seq_len-1]
        valid = valid & (
            (window <= 0) | (t > seq_lens[:, None] - 1 - window)
        )
    scores = jnp.where(valid[:, None, :], scores, -1e30)
    probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum(
        "bht,bthd->bhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def paged_suffix_attention(
    q: jnp.ndarray,  # [B, S, H, hd] suffix queries
    k_pages: jnp.ndarray,  # [KV, P, page_size, hd] (head-major)
    v_pages: jnp.ndarray,
    page_tables: jnp.ndarray,  # [B, ctx_pages] int32 (context window row)
    prefix_lens: jnp.ndarray,  # [B] global position of q[:, 0]
    seq_lens: jnp.ndarray,  # [B] total context (prefix + real suffix)
    softcap: float = 0.0,
    window=None,  # int32 scalar; >0 => attend only to the last `window` keys
    scale=None,  # query scale; default hd**-0.5
    layer=None,  # int32 scalar: pool layer index (carry-threaded prefill)
) -> jnp.ndarray:
    """Prompt-suffix attention over resident paged KV (prefix caching).

    The suffix tokens' KV has already been written into the page pool; this
    gathers each slot's page window — shared prefix pages plus the fresh
    suffix, bounded by the caller-bucketed ``ctx_pages`` — and runs the
    same blockwise online-softmax as flash_prefill_attention (its
    ``q_offset`` mode IS the suffix mask: ``k_pos <= prefix + s`` and
    ``k_pos < seq_len``), so no [B, H, S, ctx] score materialization.
    A Pallas kernel streaming only live pages is the natural follow-up.
    Returns [B, S, H, hd].
    """
    B = q.shape[0]
    KV = k_pages.shape[1] if layer is not None else k_pages.shape[0]
    hd = k_pages.shape[-1]
    page_size = k_pages.shape[-2]
    ctx = page_tables.shape[1] * page_size

    # dequantizing live-page gather, exactly like paged_decode_attention
    k_sel = gather_pages(k_pages, page_tables, layer=layer)
    v_sel = gather_pages(v_pages, page_tables, layer=layer)
    k = jnp.moveaxis(k_sel.reshape(KV, B, ctx, hd), 0, 2)
    v = jnp.moveaxis(v_sel.reshape(KV, B, ctx, hd), 0, 2)
    # key blocks must divide the window; fall back to page-sized blocks
    # for windows that aren't a multiple of 256 tokens
    block_k = 256 if ctx % 256 == 0 else page_size
    return flash_prefill_attention(
        q, k, v, seq_lens, block_k=block_k, q_offset=prefix_lens,
        softcap=softcap, window=window, scale=scale,
    )
