"""Attention reference implementations (jnp).

These are the semantic ground truth the Pallas kernels are tested against
(SURVEY.md section 4: kernel unit tests compare Pallas outputs vs jnp).  The
engine uses them directly on CPU test meshes and as the `use_pallas=False`
fallback on TPU.

Replaces the capability the reference delegates to vLLM's CUDA
paged-attention (SURVEY.md section 2.1, vllm_backend.py:51 — opaque there,
first-party here).
"""

from __future__ import annotations

import jax.numpy as jnp


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """Broadcast KV heads across query-head groups (GQA). x: [..., KV, hd]."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=-2)


def causal_prefill_attention(
    q: jnp.ndarray,  # [B, S, H, hd]
    k: jnp.ndarray,  # [B, S, KV, hd]
    v: jnp.ndarray,  # [B, S, KV, hd]
    seq_lens: jnp.ndarray,  # [B] real lengths (tokens beyond are padding)
) -> jnp.ndarray:
    """Causal self-attention over a padded prompt batch. Returns [B, S, H, hd].

    fp32 softmax accumulation; padded key positions are masked out so garbage
    in the padding region cannot leak into real tokens.
    """
    B, S, H, hd = q.shape
    n_rep = H // k.shape[2]
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)
    scale = 1.0 / (hd ** 0.5)
    # [B, H, S, S]
    scores = jnp.einsum(
        "bshd,bthd->bhst", q, k, preferred_element_type=jnp.float32
    ) * scale
    pos = jnp.arange(S)
    causal = pos[None, :] <= pos[:, None]  # [S(q), S(k)] keys <= query pos
    key_valid = pos[None, :] < seq_lens[:, None]  # [B, S]
    mask = causal[None, None, :, :] & key_valid[:, None, None, :]
    scores = jnp.where(mask, scores, -1e30)
    probs = jnp.exp(
        scores - jnp.max(scores, axis=-1, keepdims=True)
    )
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum(
        "bhst,bthd->bshd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)


def paged_decode_attention(
    q: jnp.ndarray,  # [B, H, hd] one query token per slot
    k_pages: jnp.ndarray,  # [KV, P, page_size, hd] (head-major, kv_cache.py)
    v_pages: jnp.ndarray,  # [KV, P, page_size, hd]
    page_tables: jnp.ndarray,  # [B, pages_per_seq] int32
    seq_lens: jnp.ndarray,  # [B] context length per slot (incl. current token)
) -> jnp.ndarray:
    """Decode-step attention over the paged KV cache. Returns [B, H, hd].

    Reference semantics for the Pallas paged kernel: gathers each slot's
    pages into a contiguous [ctx_max] view, masks positions >= seq_len, and
    runs fp32 softmax.  The Pallas version streams only the live pages
    through VMEM instead of materializing the gather.
    """
    B, H, hd = q.shape
    KV = k_pages.shape[0]
    page_size = k_pages.shape[2]
    n_rep = H // KV
    ctx_max = page_tables.shape[1] * page_size

    # Gather pages: [KV, B, pages_per_seq, page_size, hd] -> [B, ctx, KV, hd]
    k = jnp.moveaxis(
        k_pages[:, page_tables].reshape(KV, B, ctx_max, hd), 0, 2
    )
    v = jnp.moveaxis(
        v_pages[:, page_tables].reshape(KV, B, ctx_max, hd), 0, 2
    )
    k = repeat_kv(k, n_rep)
    v = repeat_kv(v, n_rep)

    scale = 1.0 / (hd ** 0.5)
    scores = jnp.einsum(
        "bhd,bthd->bht", q, k, preferred_element_type=jnp.float32
    ) * scale
    valid = jnp.arange(ctx_max)[None, :] < seq_lens[:, None]  # [B, ctx]
    scores = jnp.where(valid[:, None, :], scores, -1e30)
    probs = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    probs = probs / jnp.sum(probs, axis=-1, keepdims=True)
    out = jnp.einsum(
        "bht,bthd->bhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
