"""int8 paged-KV quantization: quantize-on-write, dequantize-on-read.

Decode is HBM-bound (ROADMAP "Attack the decode roofline"): the dominant
per-token HBM traffic is reading resident KV pages, so halving page
bytes is a direct roofline lever AND doubles resident-sequence capacity
for the same HBM budget.  With ``kv_cache.dtype: int8`` the page pools
store int8 K/V plus per-(page, head, slot) bf16 scales; quantization
happens at every KV WRITE site (batched prefill, suffix/chunked
prefill, the decode chunk body, spec-verify, the radix COW copy /
unaligned scatter — all in models/decoder.py + engine_core.py) and
dequantization happens where KV is READ: inside the Pallas
paged-attention VMEM online-softmax loop (ops/pallas/paged_attention.py)
and in its jnp twin (ops/attention.py).  HBM only ever moves int8.

Design choices:

* **Per-token-per-head symmetric scales** (one bf16 scale per
  (layer, kv_head, page, slot), stored in a page-indexed pool next to
  the K/V pools so pages stay the unit of sharing — the radix tree and
  COW copy page ids, and the scales travel with them for free).
  Per-token granularity makes quantization *path-independent*: a token
  quantizes identically whether written by batched prefill, a mid-page
  COW scatter or a decode step, so shared pages never need rescaling
  and there is no read-modify-write on the decode hot path (a per-page
  running-max scale would require requantizing resident slots on every
  decode write, compounding rounding error).
* **bf16 scale storage**: per token-head the page costs
  ``head_dim + 2`` bytes vs bf16's ``2 * head_dim`` — a 1.94x
  capacity gain at head_dim 64 and 1.97x at 128 (the >= 1.9x
  acceptance floor holds for every registered serving family).
* **Linearity-exact in-kernel dequant**: ``q . (k_q * s_k) =
  (q . k_q) * s_k`` and ``sum_t p_t * (v_q_t * s_v_t) =
  sum_t (p_t * s_v_t) . v_q_t`` — the Pallas kernels fold scales into
  the score row / softmax weights and never materialize a dequantized
  KV tile.

The pool rides through jit/scan/donation as a ``QuantPages`` NamedTuple
(an automatic JAX pytree), so the engine's threading — xs/ys layer
scan slices, carry threading, buffer donation — is unchanged.
"""

from __future__ import annotations

from typing import NamedTuple, Union

import jax
import jax.numpy as jnp

# Scale storage dtype: bf16's ~0.4% relative step is far below int8's
# own ~0.8%-of-absmax quantization step, and halves scale bytes vs f32
# (the capacity-ratio floor needs the narrow scale at small head_dim).
SCALE_DTYPE = jnp.bfloat16
# symmetric int8: +-127 (not -128, so dequant is sign-symmetric)
QMAX = 127.0
# bytes one token-slot of one kv head spends on its scale
SCALE_BYTES = jnp.dtype(SCALE_DTYPE).itemsize


class QuantPages(NamedTuple):
    """An int8 KV page pool + its per-(page, head, slot) scale pool.

    ``data``: int8 ``[(L,) KV, P, ps, hd]``; ``scale``: bf16 with the
    same shape minus the trailing ``hd``.  Registered as a pytree by
    virtue of being a NamedTuple, so lax.scan threads it as xs/ys or
    carry and jit donation covers both leaves.  ``shape``/``dtype``
    mirror the data pool so geometry probes (``k_pages.shape[3]``)
    keep working unchanged.
    """

    data: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype

    @property
    def ndim(self):
        return self.data.ndim


KVPool = Union[jax.Array, QuantPages]


def is_quantized(pool) -> bool:
    return isinstance(pool, QuantPages)


def dtype_short_name(dtype) -> str:
    """Reporting name for /stats, drills and bench artifacts — one
    definition site (engine_core stamps KVGeometry.kv_dtype with it)."""
    return (
        str(jnp.dtype(dtype).name)
        .replace("bfloat16", "bf16")
        .replace("float32", "f32")
        .replace("float16", "f16")
    )


def quantize(x: jax.Array):
    """Symmetric per-token-per-head int8 quantization over the trailing
    head_dim: returns ``(q int8 [..., hd], s SCALE_DTYPE [...])``.

    The scale is computed in f32, STORED narrow, and the quantization
    divides by the *stored* (rounded) scale so ``q * s`` reconstructs
    against exactly what the reader will see.  absmax-0 rows (zero
    pages, padding) get scale 1 so dequant stays exactly 0.
    """
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    s = jnp.where(amax > 0, amax / QMAX, 1.0).astype(SCALE_DTYPE)
    q = jnp.clip(
        jnp.round(x32 / s.astype(jnp.float32)[..., None]), -QMAX, QMAX
    ).astype(jnp.int8)
    return q, s


def dequantize(q: jax.Array, s: jax.Array) -> jax.Array:
    """f32 reconstruction; ``s`` broadcasts over the trailing head_dim."""
    return q.astype(jnp.float32) * s.astype(jnp.float32)[..., None]


def kv_write(pool: KVPool, idx: tuple, value: jax.Array) -> KVPool:
    """``pool.at[idx].set(value)`` for every KV write site, quantizing
    on write for int8 pools.

    ``idx`` indexes every pool dim except the trailing head_dim (the
    update value carries it); the scale pool — same shape minus hd —
    takes the identical index, so one expression serves whole-page
    prefill sets, the COW/spec per-token scatters and the decode
    single-slot write.  For plain pools this is exactly the original
    ``.at[...].set(...)``.
    """
    if is_quantized(pool):
        q, s = quantize(value)
        return QuantPages(
            pool.data.at[idx].set(q), pool.scale.at[idx].set(s)
        )
    return pool.at[idx].set(value)


def gather_pages(pool: KVPool, page_tables: jax.Array, layer=None):
    """Gather each slot's page window from the pool — the shared front
    half of the jnp paged-attention twins (ops/attention.py).

    Returns ``[KV, B, n_pages, ps, hd]``: raw dtype for plain pools,
    dequantized f32 for int8 pools (the same f32 the Pallas kernel
    computes its dots in).  With ``layer`` (a traced scalar) the pool
    carries a leading [L] dim and the gather composes (layer, head,
    page) in ONE fancy index — only the live pages of that layer are
    ever read, never a full per-layer slice.
    """
    quant = is_quantized(pool)
    data = pool.data if quant else pool
    if layer is not None:
        L, KV = data.shape[0], data.shape[1]
        head_idx = (layer * KV + jnp.arange(KV))[:, None, None]  # [KV,1,1]
        flat = data.reshape(L * KV, *data.shape[2:])
        sel = flat[head_idx, page_tables[None]]  # [KV, B, n, ps, hd]
        if quant:
            s_flat = pool.scale.reshape(L * KV, *pool.scale.shape[2:])
            s_sel = s_flat[head_idx, page_tables[None]]  # [KV, B, n, ps]
            return dequantize(sel, s_sel)
        return sel
    sel = data[:, page_tables]
    if quant:
        return dequantize(sel, pool.scale[:, page_tables])
    return sel


def copy_page_prefix(
    pool: KVPool, src, dst, keep_mask: jax.Array
) -> KVPool:
    """Radix copy-on-write page copy (engine_core._cow_copy_pages):
    overwrite the first slots of page ``dst`` with page ``src``'s where
    ``keep_mask`` ([ps] bool) holds, across every layer and head.  For
    int8 pools the SCALES copy with the data — a shared head keeps the
    exact quantization it was written with, so a COW'd page dequantizes
    bit-identically to the page it was copied from."""
    if is_quantized(pool):
        keep_d = keep_mask[:, None]  # [ps, 1] broadcasts over hd
        data = pool.data.at[..., dst, :, :].set(
            jnp.where(
                keep_d, pool.data[..., src, :, :], pool.data[..., dst, :, :]
            )
        )
        scale = pool.scale.at[..., dst, :].set(
            jnp.where(
                keep_mask, pool.scale[..., src, :], pool.scale[..., dst, :]
            )
        )
        return QuantPages(data, scale)
    keep_d = keep_mask[:, None]
    return pool.at[..., dst, :, :].set(
        jnp.where(
            keep_d, pool[..., src, :, :], pool[..., dst, :, :]
        )
    )
