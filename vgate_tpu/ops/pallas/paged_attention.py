"""Pallas paged-attention decode kernel.

The TPU-native replacement for the CUDA paged-attention the reference gets
opaquely through vLLM (SURVEY.md section 2.1; technique family: "Ragged
Paged Attention", PAPERS.md).  Semantics are pinned by the jnp twin
``vgate_tpu.ops.attention.paged_decode_attention`` (kernel tests compare the
two); the kernel's advantage is the memory path:

* the jnp twin gathers every slot's full ``pages_per_seq`` window into a
  contiguous HBM buffer (write + re-read), touching ``ctx_max`` tokens even
  for short sequences;
* this kernel DMAs **only the live pages** of each sequence directly from the
  HBM page pool into VMEM, double-buffered in chunks of
  ``CHUNK_PAGES`` pages, and runs an online-softmax
  accumulation entirely in VMEM — no gathered copy, no dead-token traffic.

Grid: one program per (slot, kv_head); each program serves the G = H/KV
query heads of that group (GQA).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vgate_tpu.ops.pallas._compat import CompilerParams as _CompilerParams

from vgate_tpu.utils.math import cdiv

# pages DMA'd per double-buffer slot (VGT_CHUNK_PAGES sweeps on-device:
# wider chunks amortize per-page DMA issue overhead for long contexts)
CHUNK_PAGES = int(os.environ.get("VGT_CHUNK_PAGES", 8))
if CHUNK_PAGES <= 0:
    raise ValueError(
        f"VGT_CHUNK_PAGES must be a positive integer, got {CHUNK_PAGES}"
    )



def _chunk_dma(
    page_tables_ref, k_pages_ref, v_pages_ref, k_buf, v_buf, sems,
    b, g, n_pages, page_size, layer=None,
    k_scale_ref=None, v_scale_ref=None, sk_buf=None, sv_buf=None,
):
    """Shared double-buffered page-DMA machinery for the paged kernels.

    Returns ``(start_chunk, wait_chunk)`` closures: ``start_chunk(c, slot)``
    kicks off the async copies of chunk ``c``'s live pages into buffer
    ``slot`` (zero-filling pages beyond the sequence — stale VMEM could
    hold NaNs, and softmax-weight 0 x NaN would poison the accumulator);
    ``wait_chunk`` blocks on those copies.

    With ``layer`` (a traced scalar) the page pools carry a leading
    layer dim ``[L, KV, P, ps, hd]`` and the DMA indexes it — the
    carry-threaded decode path (models/decoder.py) passes the FULL
    stacked buffer instead of a per-layer slice, so no 2x67MB slice
    materialization per layer feeds the kernel.

    int8 KV (``k_scale_ref`` et al. given — ops/kv_quant.py): each
    page's per-(head, slot) bf16 scale row ``[ps]`` rides its own tiny
    DMA into ``sk_buf``/``sv_buf`` ``[2, 1, chunk_tokens]`` alongside
    the int8 page tile; the scale sems live at indices 2/3 (the sem
    array widens to ``[2, 4, CHUNK]``).  Dead-page scale slots zero-fill
    like the data tiles — stale-VMEM NaN times an exactly-0 softmax
    weight would still poison the accumulator."""
    quant = k_scale_ref is not None

    def src(ref, page_id):
        if layer is None:
            return ref.at[g, page_id]
        return ref.at[layer, g, page_id]

    def start_chunk(c, slot):
        for j in range(CHUNK_PAGES):  # static unroll
            page_pos = c * CHUNK_PAGES + j

            @pl.when(page_pos < n_pages)
            def _():
                page_id = page_tables_ref[b, page_pos]
                pltpu.make_async_copy(
                    src(k_pages_ref, page_id),
                    k_buf.at[slot, pl.ds(j * page_size, page_size), :],
                    sems.at[slot, 0, j],
                ).start()
                pltpu.make_async_copy(
                    src(v_pages_ref, page_id),
                    v_buf.at[slot, pl.ds(j * page_size, page_size), :],
                    sems.at[slot, 1, j],
                ).start()
                if quant:
                    pltpu.make_async_copy(
                        src(k_scale_ref, page_id),
                        sk_buf.at[
                            slot, 0, pl.ds(j * page_size, page_size)
                        ],
                        sems.at[slot, 2, j],
                    ).start()
                    pltpu.make_async_copy(
                        src(v_scale_ref, page_id),
                        sv_buf.at[
                            slot, 0, pl.ds(j * page_size, page_size)
                        ],
                        sems.at[slot, 3, j],
                    ).start()

            @pl.when(page_pos >= n_pages)
            def _():
                k_buf[slot, pl.ds(j * page_size, page_size), :] = jnp.zeros(
                    (page_size, k_buf.shape[-1]), k_buf.dtype
                )
                v_buf[slot, pl.ds(j * page_size, page_size), :] = jnp.zeros(
                    (page_size, v_buf.shape[-1]), v_buf.dtype
                )
                if quant:
                    sk_buf[
                        slot, 0, pl.ds(j * page_size, page_size)
                    ] = jnp.zeros((page_size,), sk_buf.dtype)
                    sv_buf[
                        slot, 0, pl.ds(j * page_size, page_size)
                    ] = jnp.zeros((page_size,), sv_buf.dtype)

    def wait_chunk(c, slot):
        for j in range(CHUNK_PAGES):
            page_pos = c * CHUNK_PAGES + j

            @pl.when(page_pos < n_pages)
            def _():
                pltpu.make_async_copy(
                    src(k_pages_ref, 0),
                    k_buf.at[slot, pl.ds(j * page_size, page_size), :],
                    sems.at[slot, 0, j],
                ).wait()
                pltpu.make_async_copy(
                    src(v_pages_ref, 0),
                    v_buf.at[slot, pl.ds(j * page_size, page_size), :],
                    sems.at[slot, 1, j],
                ).wait()
                if quant:
                    pltpu.make_async_copy(
                        src(k_scale_ref, 0),
                        sk_buf.at[
                            slot, 0, pl.ds(j * page_size, page_size)
                        ],
                        sems.at[slot, 2, j],
                    ).wait()
                    pltpu.make_async_copy(
                        src(v_scale_ref, 0),
                        sv_buf.at[
                            slot, 0, pl.ds(j * page_size, page_size)
                        ],
                        sems.at[slot, 3, j],
                    ).wait()

    return start_chunk, wait_chunk


def _scale_row(buf, slot):
    """The active double-buffer's scale row as f32 ``[1, chunk_tokens]``
    (broadcasts over the score rows)."""
    return jax.lax.cond(
        slot == 0, lambda: buf[0], lambda: buf[1]
    ).astype(jnp.float32)


def _kernel(
    # scalar prefetch
    page_tables_ref,  # [B, pages_per_seq] int32 (SMEM)
    seq_lens_ref,  # [B] int32 (SMEM)
    window_ref,  # [1] int32 (SMEM); >0 => attend only to the last `window`
    layer_ref,  # [1] int32 (SMEM); pool layer index (-1 => no layer dim)
    # inputs: q_ref [1, 1, G, hd] VMEM block for (b, g); k/v_pages_ref
    # [KV, P, ps, hd] in ANY/HBM (head-major: one page of one head is a
    # contiguous (ps, hd) DMA tile), or [L, KV, P, ps, hd] when
    # has_layer (carry decode).  `quant` (int8 KV) adds k/v_scale_ref
    # [KV, P, ps] bf16 pools after them.
    # outputs: out_ref [1, 1, G, hd]
    # scratch: k_buf/v_buf [2, CHUNK*ps, hd] VMEM (+ sk/sv_buf
    # [2, 1, CHUNK*ps] when quant), acc [G, hd] f32, m/l [G, 128] f32
    # running max/denom (col-broadcast), DMA sems [2, 2 or 4, CHUNK]
    *refs,
    page_size: int,
    softcap: float,
    scale: float,
    has_layer: bool = False,
    quant: bool = False,
):
    if quant:
        (
            q_ref, k_pages_ref, v_pages_ref, k_scale_ref, v_scale_ref,
            out_ref, k_buf, v_buf, sk_buf, sv_buf, acc_ref, m_ref, l_ref,
            sems,
        ) = refs
    else:
        (
            q_ref, k_pages_ref, v_pages_ref,
            out_ref, k_buf, v_buf, acc_ref, m_ref, l_ref, sems,
        ) = refs
        k_scale_ref = v_scale_ref = sk_buf = sv_buf = None
    b = pl.program_id(0)
    g = pl.program_id(1)
    seq_len = seq_lens_ref[b]
    n_pages = jax.lax.div(seq_len + page_size - 1, page_size)
    n_chunks = jax.lax.div(n_pages + CHUNK_PAGES - 1, CHUNK_PAGES)
    chunk_tokens = CHUNK_PAGES * page_size
    # Sliding window: tokens below `lo` contribute nothing, so whole chunks
    # below the window start are never DMA'd at all — the kernel's traffic
    # is O(window), not O(context), for local-attention layers.
    window = window_ref[0]
    lo = jnp.where(
        window > 0, jnp.maximum(seq_len - window, 0), 0
    )
    lo_chunk = jax.lax.div(lo, chunk_tokens)

    start_chunk, wait_chunk = _chunk_dma(
        page_tables_ref, k_pages_ref, v_pages_ref, k_buf, v_buf, sems,
        b, g, n_pages, page_size,
        layer=layer_ref[0] if has_layer else None,
        k_scale_ref=k_scale_ref, v_scale_ref=v_scale_ref,
        sk_buf=sk_buf, sv_buf=sv_buf,
    )

    q = q_ref[0, 0].astype(jnp.float32) * scale  # [G, hd]

    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, -1e30)
    l_ref[...] = jnp.zeros_like(l_ref)

    start_chunk(lo_chunk, jax.lax.rem(lo_chunk, 2))

    def body(c, _):
        slot = jax.lax.rem(c, 2)
        next_slot = jax.lax.rem(c + 1, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            start_chunk(c + 1, next_slot)

        wait_chunk(c, slot)

        k = jax.lax.cond(
            slot == 0, lambda: k_buf[0], lambda: k_buf[1]
        ).astype(jnp.float32)  # [chunk_tokens, hd]
        v = jax.lax.cond(
            slot == 0, lambda: v_buf[0], lambda: v_buf[1]
        ).astype(jnp.float32)

        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [G, chunk_tokens]
        if quant:
            # linearity-exact in-VMEM dequant (ops/kv_quant.py): the
            # per-token scale is constant over hd, so q . (k_q * s) ==
            # (q . k_q) * s — fold it into the score row instead of
            # materializing a dequantized K tile.  Applied BEFORE
            # softcap/masking: those act on real scores.
            scores = scores * _scale_row(sk_buf, slot)
        if softcap:
            scores = jnp.tanh(scores / softcap) * softcap
        token_pos = c * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        )
        valid = (token_pos >= lo) & (token_pos < seq_len)
        scores = jnp.where(valid, scores, -1e30)

        m_prev = m_ref[:, :1]  # [G, 1]
        m_cur = jnp.max(scores, axis=-1, keepdims=True)  # [G, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)  # [G, 1]
        p = jnp.exp(scores - m_new)  # [G, chunk_tokens]
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        if quant:
            # V-side twin: sum_t p_t * (v_q_t * s_t) == sum_t
            # (p_t * s_t) . v_q_t — weight the softmax row, dot int8 V.
            # The denominator l uses the UNWEIGHTED p (it normalizes
            # probabilities, not values).
            p = p * _scale_row(sv_buf, slot)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
        return 0

    jax.lax.fori_loop(lo_chunk, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[:, :1], 1e-30)
    out_ref[0, 0] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("interpret", "softcap", "scale")
)
def paged_decode_attention_pallas(
    q: jnp.ndarray,  # [B, H, hd]
    k_pages: jnp.ndarray,  # [KV, P, ps, hd] (head-major, kv_cache.py)
    v_pages: jnp.ndarray,  # or [L, KV, P, ps, hd] with `layer` given
    page_tables: jnp.ndarray,  # [B, pages_per_seq]
    seq_lens: jnp.ndarray,  # [B]
    window=None,  # int32 scalar; >0 => attend only to the last `window`
    layer=None,  # int32 scalar: pool layer index (carry-threaded decode)
    interpret: bool = False,
    softcap: float = 0.0,
    scale=None,  # static query scale; default hd**-0.5
) -> jnp.ndarray:
    from vgate_tpu.ops.kv_quant import is_quantized

    B, H, hd = q.shape
    has_layer = layer is not None
    quant = is_quantized(k_pages)
    k_data, k_scale = (
        (k_pages.data, k_pages.scale) if quant else (k_pages, None)
    )
    v_data, v_scale = (
        (v_pages.data, v_pages.scale) if quant else (v_pages, None)
    )
    KV, P, ps, _ = k_data.shape[1:] if has_layer else k_data.shape
    G = H // KV
    chunk_tokens = CHUNK_PAGES * ps

    if window is None:
        window_arr = jnp.zeros((1,), jnp.int32)
    else:
        window_arr = jnp.asarray(window, jnp.int32).reshape(1)
    layer_arr = (
        jnp.asarray(layer, jnp.int32).reshape(1)
        if has_layer
        else jnp.full((1,), -1, jnp.int32)
    )
    kernel = functools.partial(
        _kernel,
        page_size=ps,
        softcap=float(softcap),
        scale=float(scale) if scale is not None else hd ** -0.5,
        has_layer=has_layer,
        quant=quant,
    )
    # q is laid out [B, KV, G, hd] so each program's block covers the FULL
    # trailing (G, hd) dims — Mosaic requires trailing block dims either
    # tile-aligned (8, 128) or equal to the array dims, and G (q heads per
    # kv group, e.g. 6 or 7) is rarely tile-aligned.
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    scratch = [
        pltpu.VMEM((2, chunk_tokens, hd), k_data.dtype),
        pltpu.VMEM((2, chunk_tokens, hd), v_data.dtype),
    ]
    if quant:
        # per-token bf16 scale rows ride their own chunk buffers; the
        # extra sem pair (indices 2/3) covers their DMAs
        scratch += [
            pltpu.VMEM((2, 1, chunk_tokens), k_scale.dtype),
            pltpu.VMEM((2, 1, chunk_tokens), v_scale.dtype),
        ]
    scratch += [
        pltpu.VMEM((G, hd), jnp.float32),
        pltpu.VMEM((G, 128), jnp.float32),
        pltpu.VMEM((G, 128), jnp.float32),
        pltpu.SemaphoreType.DMA((2, 4 if quant else 2, CHUNK_PAGES)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, KV),
        in_specs=[
            pl.BlockSpec(
                (1, 1, G, hd), lambda b, g, *prefetch: (b, g, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            any_spec,
            any_spec,
        ]
        + ([any_spec, any_spec] if quant else []),
        out_specs=pl.BlockSpec(
            (1, 1, G, hd), lambda b, g, *prefetch: (b, g, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=scratch,
    )
    inputs = [q.reshape(B, KV, G, hd), k_data, v_data]
    if quant:
        inputs += [k_scale, v_scale]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, G, hd), q.dtype),
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
    )(page_tables, seq_lens, window_arr, layer_arr, *inputs)
    return out.reshape(B, H, hd)


def _blocked_kernel(
    # scalar prefetch
    page_tables_ref,  # [B, pages_per_seq] int32 (SMEM)
    seq_lens_ref,  # [B] int32 (SMEM)
    window_ref,  # [1] int32 (SMEM)
    layer_ref,  # [1] int32 (SMEM); -1 => no layer dim
    # inputs
    q_ref,  # [1, 1, BS, G, hd] VMEM block for (bb, g)
    k_pages_ref,  # [KV, P, ps, hd] ANY/HBM ([L, KV, ...] when has_layer)
    v_pages_ref,
    # output
    out_ref,  # [1, 1, BS, G, hd]
    # scratch
    k_buf,  # [2, BS, CHUNK*ps, hd] VMEM
    v_buf,
    acc_ref,  # [BS*G, hd] f32
    m_ref,  # [BS*G, 128] f32
    l_ref,  # [BS*G, 128] f32
    sems,  # DMA semaphores [2, 2, BS, CHUNK]
    *,
    page_size: int,
    softcap: float,
    scale: float,
    block_slots: int,
    has_layer: bool = False,
):
    """Multi-slot decode attention: ``block_slots`` sequences per program.

    The per-(slot, kv_head) kernel above runs B*KV tiny programs per
    layer (7,168 grid steps per decode step at B=128, KV=2, 28 layers);
    per-program iteration overhead is a prime suspect for the measured
    gap to the HBM roofline (RESULTS_r3.md decision tree item 4).  This
    variant serves ``BS`` slots per program — grid B/BS x KV — with the
    same double-buffered live-page DMA per slot and a static unroll of
    the per-slot 2D dots (Mosaic-safe; no batched dot_general).  The
    fori_loop runs to the block's MAX chunk count; shorter slots mask.
    """
    BS = block_slots
    bb = pl.program_id(0)
    g = pl.program_id(1)
    window = window_ref[0]
    chunk_tokens = CHUNK_PAGES * page_size
    G = q_ref.shape[3]

    # per-slot page counts; loop bound is the block max
    n_pages_j = [
        jax.lax.div(
            seq_lens_ref[bb * BS + j] + page_size - 1, page_size
        )
        for j in range(BS)
    ]
    n_chunks = jax.lax.div(
        n_pages_j[0] + CHUNK_PAGES - 1, CHUNK_PAGES
    )
    for j in range(1, BS):
        n_chunks = jnp.maximum(
            n_chunks,
            jax.lax.div(n_pages_j[j] + CHUNK_PAGES - 1, CHUNK_PAGES),
        )
    # sliding window: chunks wholly below the BLOCK's earliest window
    # start are skipped (per-slot masks handle the rest)
    lo_block = jnp.where(
        window > 0,
        jnp.maximum(seq_lens_ref[bb * BS] - window, 0),
        0,
    )
    for j in range(1, BS):
        lo_block = jnp.minimum(
            lo_block,
            jnp.where(
                window > 0,
                jnp.maximum(seq_lens_ref[bb * BS + j] - window, 0),
                0,
            ),
        )
    lo_chunk = jax.lax.div(lo_block, chunk_tokens)

    def src(ref, page_id):
        if has_layer:
            return ref.at[layer_ref[0], g, page_id]
        return ref.at[g, page_id]

    def start_chunk(c, slot):
        for j in range(BS):
            b = bb * BS + j
            for i in range(CHUNK_PAGES):  # static unroll
                page_pos = c * CHUNK_PAGES + i

                @pl.when(page_pos < n_pages_j[j])
                def _():
                    page_id = page_tables_ref[b, page_pos]
                    pltpu.make_async_copy(
                        src(k_pages_ref, page_id),
                        k_buf.at[
                            slot, j, pl.ds(i * page_size, page_size), :
                        ],
                        sems.at[slot, 0, j, i],
                    ).start()
                    pltpu.make_async_copy(
                        src(v_pages_ref, page_id),
                        v_buf.at[
                            slot, j, pl.ds(i * page_size, page_size), :
                        ],
                        sems.at[slot, 1, j, i],
                    ).start()

                @pl.when(page_pos >= n_pages_j[j])
                def _():
                    k_buf[
                        slot, j, pl.ds(i * page_size, page_size), :
                    ] = jnp.zeros(
                        (page_size, k_buf.shape[-1]), k_buf.dtype
                    )
                    v_buf[
                        slot, j, pl.ds(i * page_size, page_size), :
                    ] = jnp.zeros(
                        (page_size, v_buf.shape[-1]), v_buf.dtype
                    )

    def wait_chunk(c, slot):
        for j in range(BS):
            for i in range(CHUNK_PAGES):
                page_pos = c * CHUNK_PAGES + i

                @pl.when(page_pos < n_pages_j[j])
                def _():
                    pltpu.make_async_copy(
                        src(k_pages_ref, 0),
                        k_buf.at[
                            slot, j, pl.ds(i * page_size, page_size), :
                        ],
                        sems.at[slot, 0, j, i],
                    ).wait()
                    pltpu.make_async_copy(
                        src(v_pages_ref, 0),
                        v_buf.at[
                            slot, j, pl.ds(i * page_size, page_size), :
                        ],
                        sems.at[slot, 1, j, i],
                    ).wait()

    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, -1e30)
    l_ref[...] = jnp.zeros_like(l_ref)

    start_chunk(lo_chunk, jax.lax.rem(lo_chunk, 2))

    def body(c, _):
        slot = jax.lax.rem(c, 2)
        next_slot = jax.lax.rem(c + 1, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            start_chunk(c + 1, next_slot)

        wait_chunk(c, slot)

        k_all = jax.lax.cond(
            slot == 0, lambda: k_buf[0], lambda: k_buf[1]
        )  # [BS, chunk_tokens, hd]
        v_all = jax.lax.cond(
            slot == 0, lambda: v_buf[0], lambda: v_buf[1]
        )
        token_base = c * chunk_tokens
        for j in range(BS):  # static unroll: 2D dots only
            b = bb * BS + j
            q = q_ref[0, 0, j].astype(jnp.float32) * scale  # [G, hd]
            k = k_all[j].astype(jnp.float32)  # [chunk_tokens, hd]
            v = v_all[j].astype(jnp.float32)
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [G, chunk_tokens]
            if softcap:
                scores = jnp.tanh(scores / softcap) * softcap
            token_pos = token_base + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 1
            )
            sl = seq_lens_ref[b]
            lo = jnp.where(
                window > 0, jnp.maximum(sl - window, 0), 0
            )
            valid = (token_pos >= lo) & (token_pos < sl)
            scores = jnp.where(valid, scores, -1e30)
            r = slice(j * G, (j + 1) * G)
            m_prev = m_ref[r, :1]
            m_cur = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(scores - m_new)
            l_new = alpha * l_ref[r, :1] + jnp.sum(
                p, axis=-1, keepdims=True
            )
            acc_ref[r, :] = acc_ref[r, :] * alpha + jax.lax.dot_general(
                p, v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            m_ref[r, :] = jnp.broadcast_to(m_new, (G, 128))
            l_ref[r, :] = jnp.broadcast_to(l_new, (G, 128))
        return 0

    jax.lax.fori_loop(lo_chunk, n_chunks, body, 0)
    for j in range(BS):
        r = slice(j * G, (j + 1) * G)
        denom = jnp.maximum(l_ref[r, :1], 1e-30)
        out_ref[0, 0, j] = (acc_ref[r, :] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("interpret", "softcap", "scale", "block_slots"),
)
def paged_decode_attention_pallas_blocked(
    q: jnp.ndarray,  # [B, H, hd]
    k_pages: jnp.ndarray,  # [KV, P, ps, hd] ([L, KV, ...] with `layer`)
    v_pages: jnp.ndarray,
    page_tables: jnp.ndarray,  # [B, pages_per_seq]
    seq_lens: jnp.ndarray,  # [B]
    window=None,
    layer=None,
    interpret: bool = False,
    softcap: float = 0.0,
    scale=None,
    block_slots: int = 8,
) -> jnp.ndarray:
    """Multi-slot-blocked variant of ``paged_decode_attention_pallas``:
    grid (B/block_slots, KV) instead of (B, KV).  Opt-in via
    ``tpu.decode_block_slots`` until its win is measured on hardware
    (the r3 lesson: no unmeasured default flips).  Falls back to the
    per-slot kernel when ``B % block_slots != 0`` — and for int8 KV
    pools: the blocked grid is itself unmeasured, so it doesn't carry
    the scale-DMA plumbing yet (the per-slot kernel dequantizes
    in-VMEM; revisit if the hardware A/B picks the blocked grid)."""
    from vgate_tpu.ops.kv_quant import is_quantized

    B, H, hd = q.shape
    has_layer = layer is not None
    BS = block_slots
    if BS <= 1 or B % BS or is_quantized(k_pages):
        return paged_decode_attention_pallas(
            q, k_pages, v_pages, page_tables, seq_lens, window=window,
            layer=layer, interpret=interpret, softcap=softcap,
            scale=scale,
        )
    KV, P, ps, _ = k_pages.shape[1:] if has_layer else k_pages.shape
    G = H // KV
    chunk_tokens = CHUNK_PAGES * ps

    if window is None:
        window_arr = jnp.zeros((1,), jnp.int32)
    else:
        window_arr = jnp.asarray(window, jnp.int32).reshape(1)
    layer_arr = (
        jnp.asarray(layer, jnp.int32).reshape(1)
        if has_layer
        else jnp.full((1,), -1, jnp.int32)
    )
    kernel = functools.partial(
        _blocked_kernel,
        page_size=ps,
        softcap=float(softcap),
        scale=float(scale) if scale is not None else hd ** -0.5,
        block_slots=BS,
        has_layer=has_layer,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B // BS, KV),
        in_specs=[
            pl.BlockSpec(
                (1, 1, BS, G, hd),
                lambda bb, g, *prefetch: (bb, g, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, BS, G, hd),
            lambda bb, g, *prefetch: (bb, g, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((2, BS, chunk_tokens, hd), k_pages.dtype),
            pltpu.VMEM((2, BS, chunk_tokens, hd), v_pages.dtype),
            pltpu.VMEM((BS * G, hd), jnp.float32),
            pltpu.VMEM((BS * G, 128), jnp.float32),
            pltpu.VMEM((BS * G, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2, BS, CHUNK_PAGES)),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B // BS, KV, BS, G, hd), q.dtype),
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=96 * 1024 * 1024,
        ),
    )(
        page_tables, seq_lens, window_arr, layer_arr,
        # q [B, H, hd] = [NB*BS, KV*G, hd] -> [NB, KV, BS, G, hd]
        jnp.swapaxes(q.reshape(B // BS, BS, KV, G, hd), 1, 2),
        k_pages, v_pages,
    )
    # out [NB, KV, BS, G, hd] -> [B, H, hd]
    return jnp.swapaxes(out, 1, 2).reshape(B, H, hd)


def _mt_kernel(
    # scalar prefetch
    page_tables_ref,  # [B, pages_per_seq] int32 (SMEM)
    positions0_ref,  # [B] int32 — global position of query row 0
    input_lens_ref,  # [B] int32 — real query rows this slot (<= S)
    window_ref,  # [1] int32; >0 => attend only to the last `window`
    layer_ref,  # [1] int32; pool layer index (-1 => no layer dim)
    # inputs: q_ref [1, 1, S, G, hd] VMEM block for (b, g); k/v_pages_ref
    # [KV, P, ps, hd] ANY/HBM ([L, KV, ...] when has_layer); `quant`
    # adds k/v_scale_ref [KV, P, ps] bf16 after them (int8 KV).
    # outputs: out_ref [1, 1, S, G, hd]
    # scratch: k_buf/v_buf [2, CHUNK*ps, hd] (+ sk/sv_buf
    # [2, 1, CHUNK*ps] when quant), acc [S*G, hd] f32, m/l [S*G, 128]
    # f32, DMA sems
    *refs,
    page_size: int,
    softcap: float,
    scale: float,
    has_layer: bool = False,
    quant: bool = False,
):
    """Multi-token decode attention: S candidate tokens per slot attend
    the slot's paged context in one program (the speculative-decoding
    verify step; runtime/speculative.py).  Same double-buffered per-page
    DMA as the single-token kernel — query row s sees keys up to
    ``positions0 + s`` (causal within the candidates) intersected with
    the sliding window when one applies."""
    if quant:
        (
            q_ref, k_pages_ref, v_pages_ref, k_scale_ref, v_scale_ref,
            out_ref, k_buf, v_buf, sk_buf, sv_buf, acc_ref, m_ref, l_ref,
            sems,
        ) = refs
    else:
        (
            q_ref, k_pages_ref, v_pages_ref,
            out_ref, k_buf, v_buf, acc_ref, m_ref, l_ref, sems,
        ) = refs
        k_scale_ref = v_scale_ref = sk_buf = sv_buf = None
    b = pl.program_id(0)
    g = pl.program_id(1)
    pos0 = positions0_ref[b]
    input_len = input_lens_ref[b]
    seq_len = pos0 + input_len  # keys written incl. all candidates
    n_pages = jax.lax.div(seq_len + page_size - 1, page_size)
    n_chunks = jax.lax.div(n_pages + CHUNK_PAGES - 1, CHUNK_PAGES)
    chunk_tokens = CHUNK_PAGES * page_size
    window = window_ref[0]
    # the FIRST query row (position pos0) has the lowest window start, so
    # chunks entirely below ITS window are dead for every row
    lo = jnp.where(window > 0, jnp.maximum(pos0 - window + 1, 0), 0)
    lo_chunk = jax.lax.div(lo, chunk_tokens)

    start_chunk, wait_chunk = _chunk_dma(
        page_tables_ref, k_pages_ref, v_pages_ref, k_buf, v_buf, sems,
        b, g, n_pages, page_size,
        layer=layer_ref[0] if has_layer else None,
        k_scale_ref=k_scale_ref, v_scale_ref=v_scale_ref,
        sk_buf=sk_buf, sv_buf=sv_buf,
    )

    S, G, hd = q_ref.shape[-3], q_ref.shape[-2], q_ref.shape[-1]
    q = q_ref[0, 0].astype(jnp.float32).reshape(S * G, hd) * scale
    # per-row global query position: row r = (s, g') -> pos0 + s
    row_pos = pos0 + jax.lax.broadcasted_iota(
        jnp.int32, (S * G, 1), 0
    ) // G  # [S*G, 1]

    acc_ref[...] = jnp.zeros_like(acc_ref)
    m_ref[...] = jnp.full_like(m_ref, -1e30)
    l_ref[...] = jnp.zeros_like(l_ref)

    start_chunk(lo_chunk, jax.lax.rem(lo_chunk, 2))

    def body(c, _):
        slot = jax.lax.rem(c, 2)
        next_slot = jax.lax.rem(c + 1, 2)

        @pl.when(c + 1 < n_chunks)
        def _():
            start_chunk(c + 1, next_slot)

        wait_chunk(c, slot)

        k = jax.lax.cond(
            slot == 0, lambda: k_buf[0], lambda: k_buf[1]
        ).astype(jnp.float32)
        v = jax.lax.cond(
            slot == 0, lambda: v_buf[0], lambda: v_buf[1]
        ).astype(jnp.float32)

        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [S*G, chunk_tokens]
        if quant:
            # fold the per-token K scale into the score row (exact:
            # the scale is constant over hd) — see _kernel
            scores = scores * _scale_row(sk_buf, slot)
        if softcap:
            scores = jnp.tanh(scores / softcap) * softcap
        token_pos = c * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        )
        valid = (token_pos <= row_pos) & (token_pos < seq_len)
        valid = valid & (
            (window <= 0) | (row_pos - token_pos < window)
        )
        scores = jnp.where(valid, scores, -1e30)

        m_prev = m_ref[:, :1]
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        # fully-masked chunks (possible for early rows) must not pollute
        # the accumulator with exp(-1e30 - (-1e30)) = 1 weights
        p = jnp.where(valid, p, 0.0)
        l_new = alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        if quant:
            # weight the softmax row by the per-token V scale; l stays
            # unweighted (it normalizes probabilities, not values)
            p = p * _scale_row(sv_buf, slot)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)
        return 0

    jax.lax.fori_loop(lo_chunk, n_chunks, body, 0)
    denom = jnp.maximum(l_ref[:, :1], 1e-30)
    out = (acc_ref[...] / denom).astype(out_ref.dtype)
    out_ref[0, 0] = out.reshape(S, G, hd)


@functools.partial(
    jax.jit, static_argnames=("interpret", "softcap", "scale")
)
def paged_multitok_attention_pallas(
    q: jnp.ndarray,  # [B, S, H, hd] candidate-token queries
    k_pages: jnp.ndarray,  # [KV, P, ps, hd] ([L, KV, ...] with `layer`)
    v_pages: jnp.ndarray,
    page_tables: jnp.ndarray,  # [B, pages_per_seq]
    positions0: jnp.ndarray,  # [B] global position of q[:, 0]
    input_lens: jnp.ndarray,  # [B] real candidate rows (<= S)
    window=None,
    layer=None,  # int32 scalar: pool layer index (carry-threaded verify)
    interpret: bool = False,
    softcap: float = 0.0,
    scale=None,
) -> jnp.ndarray:
    """Speculative-verify attention over paged KV. Returns [B, S, H, hd].

    The candidates' KV must already be written into the pages (the
    verify layer scatters before attending).  Rows past ``input_lens``
    return unspecified values (their garbage queries attend the real
    context) — callers must mask by ``input_lens``, as the engine and
    the tests do."""
    from vgate_tpu.ops.kv_quant import is_quantized

    B, S, H, hd = q.shape
    has_layer = layer is not None
    quant = is_quantized(k_pages)
    k_data, k_scale = (
        (k_pages.data, k_pages.scale) if quant else (k_pages, None)
    )
    v_data, v_scale = (
        (v_pages.data, v_pages.scale) if quant else (v_pages, None)
    )
    KV, P, ps, _ = k_data.shape[1:] if has_layer else k_data.shape
    G = H // KV
    chunk_tokens = CHUNK_PAGES * ps

    if window is None:
        window_arr = jnp.zeros((1,), jnp.int32)
    else:
        window_arr = jnp.asarray(window, jnp.int32).reshape(1)
    layer_arr = (
        jnp.asarray(layer, jnp.int32).reshape(1)
        if has_layer
        else jnp.full((1,), -1, jnp.int32)
    )
    kernel = functools.partial(
        _mt_kernel,
        page_size=ps,
        softcap=float(softcap),
        scale=float(scale) if scale is not None else hd ** -0.5,
        has_layer=has_layer,
        quant=quant,
    )
    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    scratch = [
        pltpu.VMEM((2, chunk_tokens, hd), k_data.dtype),
        pltpu.VMEM((2, chunk_tokens, hd), v_data.dtype),
    ]
    if quant:
        scratch += [
            pltpu.VMEM((2, 1, chunk_tokens), k_scale.dtype),
            pltpu.VMEM((2, 1, chunk_tokens), v_scale.dtype),
        ]
    scratch += [
        pltpu.VMEM((S * G, hd), jnp.float32),
        pltpu.VMEM((S * G, 128), jnp.float32),
        pltpu.VMEM((S * G, 128), jnp.float32),
        pltpu.SemaphoreType.DMA((2, 4 if quant else 2, CHUNK_PAGES)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(B, KV),
        in_specs=[
            pl.BlockSpec(
                (1, 1, S, G, hd),
                lambda b, g, *pf: (b, g, 0, 0, 0),
                memory_space=pltpu.VMEM,
            ),
            any_spec,
            any_spec,
        ]
        + ([any_spec, any_spec] if quant else []),
        out_specs=pl.BlockSpec(
            (1, 1, S, G, hd),
            lambda b, g, *pf: (b, g, 0, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=scratch,
    )
    # [B, S, H, hd] -> [B, KV, S, G, hd]: KV-major so one program's block
    # covers its group's rows contiguously
    qt = jnp.transpose(
        q.reshape(B, S, KV, G, hd), (0, 2, 1, 3, 4)
    )
    inputs = [qt, k_data, v_data]
    if quant:
        inputs += [k_scale, v_scale]
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, S, G, hd), q.dtype),
        interpret=interpret,
        compiler_params=_CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
    )(
        page_tables, positions0, input_lens, window_arr, layer_arr,
        *inputs,
    )
    return jnp.transpose(out, (0, 2, 1, 3, 4)).reshape(B, S, H, hd)
