"""Pallas TPU kernels (Mosaic-compiled); jnp twins live in vgate_tpu.ops."""
