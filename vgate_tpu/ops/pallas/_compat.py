"""Pallas API compatibility shims shared by the kernel modules.

jax 0.5 renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``;
resolving it here (once) keeps the kernels — and their interpret-mode
tests — running on either toolchain without per-file shims drifting.
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)
