"""Pallas fused int4-dequant matmul.

The r2 int4 path lost 3x to bf16 (benchmarks/RESULTS_r2.md:33-34): XLA
materializes the two sign-extended nibble planes of ``packed_einsum``
(ops/quant.py) as full-size bf16 tensors in HBM, so the "4-bit" weights
moved MORE bytes than bf16.  This kernel keeps the dequant inside the
matmul tiles: each grid step DMAs one **packed uint8 tile** into VMEM,
sign-extends the nibbles in-register (VPU), and feeds both half-planes
straight to the MXU — HBM traffic is the packed bytes, period.  That is
the TPU-native equivalent of the fused AWQ dequant-GEMM the reference
gets opaquely through vLLM's CUDA kernels (vgate/config.py:46).

Layout contract (ops/quant.py PackedQTensor, half-split): byte
``p[i, o]`` holds ``w[i, o]`` in its low nibble and ``w[in/2 + i, o]``
in its high nibble.  The kernel therefore contracts ``x[:, :in/2]``
against the low planes and ``x[:, in/2:]`` against the high planes —
the same array is passed twice with index maps offset by ``in/2``.

Grid: ``(rows, out_tiles, in_tiles)`` with the in-tile axis innermost
accumulating into a VMEM f32 scratch; the per-output-channel scale
multiplies once on the last in-tile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vgate_tpu.ops.pallas._compat import CompilerParams as _CompilerParams

from vgate_tpu.utils.math import cdiv


def _pick_tile(dim: int, candidates=(512, 256, 128)) -> int:
    """Largest MXU-friendly tile dividing ``dim`` (whole-dim fallback for
    the tiny CPU-interpret test shapes)."""
    for t in candidates:
        if dim % t == 0:
            return t
    return dim


def _kernel(
    x_lo_ref,  # [T_r, T_in] VMEM — x columns [i*T_in, (i+1)*T_in)
    x_hi_ref,  # [T_r, T_in] VMEM — x columns in/2 + [i*T_in, (i+1)*T_in)
    p_ref,  # [T_in, T_out] uint8 VMEM — packed nibble tile
    scale_ref,  # [1, T_out] f32 VMEM
    out_ref,  # [T_r, T_out]
    acc_ref,  # [T_r, T_out] f32 scratch
    *,
    n_in_tiles: int,
):
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # sign-extend both nibbles in-register (two's complement 4-bit)
    p = p_ref[...].astype(jnp.int32)
    lo = ((p & 0x0F) ^ 8) - 8
    hi = ((p >> 4) ^ 8) - 8
    dtype = x_lo_ref.dtype
    acc_ref[...] += jax.lax.dot(
        x_lo_ref[...], lo.astype(dtype),
        preferred_element_type=jnp.float32,
    )
    acc_ref[...] += jax.lax.dot(
        x_hi_ref[...], hi.astype(dtype),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == n_in_tiles - 1)
    def _():
        out_ref[...] = (acc_ref[...] * scale_ref[...]).astype(
            out_ref.dtype
        )


def _int8_kernel(
    x_ref,  # [T_r, T_in] VMEM
    w_ref,  # [T_in, T_out] int8 VMEM
    scale_ref,  # [1, T_out] f32 VMEM
    out_ref,  # [T_r, T_out]
    acc_ref,  # [T_r, T_out] f32 scratch
    *,
    n_in_tiles: int,
):
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot(
        x_ref[...], w_ref[...].astype(x_ref.dtype),
        preferred_element_type=jnp.float32,
    )

    @pl.when(i == n_in_tiles - 1)
    def _():
        out_ref[...] = (acc_ref[...] * scale_ref[...]).astype(
            out_ref.dtype
        )


def _tiled_matmul(
    x, tile_in_dim: int, out: int, out_dtype, interpret: bool, build
):
    """Shared host-side wrapper for the fused-dequant kernels: flatten
    the lead dims, pad rows to an MXU-friendly tile, size the grid, run,
    unpad.  ``build(xf, T_r, T_in, T_out, n_in_tiles)`` returns
    ``(kernel_fn, in_specs, operands)`` — the only parts that differ
    between the int8 and packed-int4 variants."""
    *lead, in_dim = x.shape
    R = 1
    for s in lead:
        R *= s
    xf = x.reshape(R, in_dim)

    T_in = _pick_tile(tile_in_dim)
    T_out = _pick_tile(out)
    # rows tile at 128 (the MXU sublane sweet spot); small batches pad
    # to one 8-aligned tile
    T_r = 128 if R >= 128 else max(8, cdiv(R, 8) * 8)
    Rp = cdiv(R, T_r) * T_r
    if Rp != R:
        xf = jnp.pad(xf, ((0, Rp - R), (0, 0)))
    n_in_tiles = tile_in_dim // T_in

    kernel, in_specs, operands = build(xf, T_r, T_in, T_out, n_in_tiles)
    out_mat = pl.pallas_call(
        kernel,
        grid=(Rp // T_r, out // T_out, n_in_tiles),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((T_r, T_out), lambda r, o, i: (r, o)),
        out_shape=jax.ShapeDtypeStruct((Rp, out), out_dtype),
        scratch_shapes=[pltpu.VMEM((T_r, T_out), jnp.float32)],
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(*operands)
    if Rp != R:
        out_mat = out_mat[:R]
    return out_mat.reshape(*lead, out)


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "interpret")
)
def int8_matmul_pallas(
    x: jnp.ndarray,  # [..., in]
    q: jnp.ndarray,  # [in, out] int8
    scale: jnp.ndarray,  # [out] f32 per-output-channel scale
    out_dtype=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """``x @ q.astype * scale`` with the int8->activation convert inside
    the matmul tiles: HBM weight traffic is the int8 bytes.  The int8
    sibling of ``int4_matmul_pallas`` (XLA usually fuses the convert on
    its own; this kernel removes the 'usually' and gives the A/B handle).
    """
    in_dim, out = q.shape
    if x.shape[-1] != in_dim:
        raise ValueError(f"x in-dim {x.shape[-1]} != weight rows {in_dim}")

    def build(xf, T_r, T_in, T_out, n_in_tiles):
        return (
            functools.partial(_int8_kernel, n_in_tiles=n_in_tiles),
            [
                pl.BlockSpec((T_r, T_in), lambda r, o, i: (r, i)),
                pl.BlockSpec((T_in, T_out), lambda r, o, i: (i, o)),
                pl.BlockSpec((1, T_out), lambda r, o, i: (0, o)),
            ],
            (xf, q, scale.reshape(1, out).astype(jnp.float32)),
        )

    return _tiled_matmul(
        x, in_dim, out, out_dtype or x.dtype, interpret, build
    )


@functools.partial(
    jax.jit, static_argnames=("out_dtype", "interpret")
)
def int4_matmul_pallas(
    x: jnp.ndarray,  # [..., in]
    q_packed: jnp.ndarray,  # [in/2, out] uint8 (half-split nibbles)
    scale: jnp.ndarray,  # [out] f32 per-output-channel scale
    out_dtype=None,
    interpret: bool = False,
) -> jnp.ndarray:
    """``x @ dequant(q_packed) * scale`` with in-tile dequantization.

    Semantics twin: ``packed_einsum(..., x, w) * w.scale``
    (ops/quant.py) — the kernel applies the scale in f32 before the
    output cast, so it is the numerically stronger of the two.
    Returns [..., out] in ``out_dtype`` (default: x.dtype).
    """
    half, out = q_packed.shape
    if x.shape[-1] != 2 * half:
        raise ValueError(
            f"x in-dim {x.shape[-1]} != 2 * packed rows {half}"
        )

    def build(xf, T_r, T_in, T_out, n_in_tiles):
        return (
            functools.partial(_kernel, n_in_tiles=n_in_tiles),
            [
                pl.BlockSpec((T_r, T_in), lambda r, o, i: (r, i)),
                pl.BlockSpec(
                    (T_r, T_in),
                    lambda r, o, i, n=n_in_tiles: (r, i + n),
                ),
                pl.BlockSpec((T_in, T_out), lambda r, o, i: (i, o)),
                pl.BlockSpec((1, T_out), lambda r, o, i: (0, o)),
            ],
            (xf, xf, q_packed, scale.reshape(1, out).astype(jnp.float32)),
        )

    return _tiled_matmul(
        x, half, out, out_dtype or x.dtype, interpret, build
    )
