"""Pallas flash-attention prefill kernel.

Completes the kernel pair the reference gets opaquely from vLLM (SURVEY.md
section 2.1): ``paged_attention.py`` covers decode, this kernel covers the
prompt pass.  Semantics are pinned by the jnp oracle
``vgate_tpu.ops.attention.causal_prefill_attention`` (and its blockwise twin
``flash_prefill_attention``); the kernel's advantage is that no score matrix
ever exists in HBM — each (batch, head, q-block) program streams key/value
blocks through VMEM with an online-softmax accumulator, so peak memory is
O(block_q · block_k) per core instead of the O(S²) per-head score
materialization of the naive path (~200 MB fp32 at the 2048 bucket).

Grid: ``(B, H, n_q_blocks, n_k_blocks)`` with the key-block axis innermost —
TPU grids execute sequentially over the trailing axis, so the accumulator
lives in VMEM scratch across the k-sweep of one q-block.  Causally dead
k-blocks (entirely above the diagonal) skip their compute via ``pl.when``.

Supports chunked prefill via ``q_offsets``: the query rows may start at a
nonzero global position while keys cover the context from position 0.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vgate_tpu.ops.pallas._compat import CompilerParams as _CompilerParams


def _kernel(
    # scalar prefetch (SMEM)
    seq_lens_ref,  # [B] int32 — real key length per batch row
    q_offsets_ref,  # [B] int32 — global position of query row 0
    window_ref,  # [1] int32; >0 => attend only to the last `window` keys
    # inputs (VMEM blocks)
    q_ref,  # [1, 1, block_q, hd]
    k_ref,  # [1, 1, block_k, hd]
    v_ref,  # [1, 1, block_k, hd]
    # output
    out_ref,  # [1, 1, block_q, hd]
    # scratch
    acc_ref,  # [block_q, hd] f32
    m_ref,  # [block_q, 128] f32 running max (column-broadcast)
    l_ref,  # [block_q, 128] f32 running denom
    *,
    block_q: int,
    block_k: int,
    n_k: int,
    softcap: float,
    scale: float,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    seq_len = seq_lens_ref[b]
    q_off = q_offsets_ref[b]
    window = window_ref[0]

    @pl.when(ki == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)

    # global positions of this block's queries and keys
    q_start = q_off + qi * block_q
    k_start = ki * block_k

    # a k-block strictly above the causal diagonal — or entirely below the
    # sliding window of every query row in the block — contributes nothing
    causal_live = k_start <= q_start + block_q - 1
    window_live = (window <= 0) | (
        k_start + block_k - 1 >= q_start - window + 1
    )

    @pl.when(causal_live & window_live)
    def _():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # [block_q, hd]
        k = k_ref[0, 0].astype(jnp.float32)  # [block_k, hd]
        v = v_ref[0, 0].astype(jnp.float32)

        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if softcap:
            scores = jnp.tanh(scores / softcap) * softcap
        q_pos = q_start + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0
        )
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        )
        mask = (k_pos <= q_pos) & (k_pos < seq_len)
        mask = mask & ((window <= 0) | (q_pos - k_pos < window))
        scores = jnp.where(mask, scores, -1e30)

        m_prev = m_ref[:, :1]  # [block_q, 1]
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)  # [block_q, block_k]
        l_ref[...] = jnp.broadcast_to(
            alpha * l_ref[:, :1] + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape,
        )
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(ki == n_k - 1)
    def _():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        out_ref[0, 0] = (acc_ref[...] / denom).astype(out_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_q", "block_k", "interpret", "softcap", "scale"),
)
def flash_prefill_attention_pallas(
    q: jnp.ndarray,  # [B, S, H, hd]
    k: jnp.ndarray,  # [B, Sk, KV, hd]
    v: jnp.ndarray,  # [B, Sk, KV, hd]
    seq_lens: jnp.ndarray,  # [B] real key lengths
    q_offsets: jnp.ndarray | None = None,  # [B] global pos of q[:, 0]
    block_q: int = 256,
    block_k: int = 256,
    interpret: bool = False,
    softcap: float = 0.0,
    window=None,  # int32 scalar; >0 => attend only to the last `window`
    scale=None,  # static query scale; default hd**-0.5
) -> jnp.ndarray:
    """Causal (optionally offset) attention. Returns [B, S, H, hd]."""
    B, S, H, hd = q.shape
    Sk, KV = k.shape[1], k.shape[2]
    G = H // KV
    block_q = min(block_q, S)
    block_k = min(block_k, Sk)
    if S % block_q or Sk % block_k:
        raise ValueError(
            f"S={S}/Sk={Sk} must divide block_q={block_q}/block_k={block_k}"
        )
    n_q, n_k = S // block_q, Sk // block_k
    if q_offsets is None:
        q_offsets = jnp.zeros((B,), jnp.int32)
    if window is None:
        window_arr = jnp.zeros((1,), jnp.int32)
    else:
        window_arr = jnp.asarray(window, jnp.int32).reshape(1)

    # head-major layout so each block's trailing dims are (seq_block, hd)
    qt = jnp.transpose(q, (0, 2, 1, 3))  # [B, H, S, hd]
    kt = jnp.transpose(k, (0, 2, 1, 3))  # [B, KV, Sk, hd]
    vt = jnp.transpose(v, (0, 2, 1, 3))

    kernel = functools.partial(
        _kernel, block_q=block_q, block_k=block_k, n_k=n_k,
        softcap=float(softcap),
        scale=float(scale) if scale is not None else hd ** -0.5,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, H, n_q, n_k),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, hd),
                lambda b, h, qi, ki, *pf: (b, h, qi, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, hd),
                lambda b, h, qi, ki, *pf: (b, h // G, ki, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                (1, 1, block_k, hd),
                lambda b, h, qi, ki, *pf: (b, h // G, ki, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, hd),
            lambda b, h, qi, ki, *pf: (b, h, qi, 0),
            memory_space=pltpu.VMEM,
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, hd), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        interpret=interpret,
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
            vmem_limit_bytes=64 * 1024 * 1024,
        ),
    )(
        seq_lens.astype(jnp.int32), q_offsets.astype(jnp.int32),
        window_arr, qt, kt, vt,
    )
    return jnp.transpose(out, (0, 2, 1, 3))
