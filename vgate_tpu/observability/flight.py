"""The engine flight recorder: a post-mortem record of engine execution.

Two lock-cheap ring buffers (CPython ``deque.append`` is atomic under
the GIL, so the engine thread's hot path takes no lock; snapshot readers
copy defensively):

* **ticks** — one entry per engine dispatch/readback (prefill group,
  decode chunk readback, speculative round) plus event entries
  (recompile, shed, abort, preempt, crash), each carrying batch size,
  bucket, step time, KV-page occupancy and queue depth;
* **requests** — one bounded record per completed request with
  per-phase durations (queue → prefill → decode), admission bucket,
  token counts and final status, plus a live view of in-flight
  requests.

The supervisor dumps ``crash_snapshot()`` as structured JSON on every
crash classification (and keeps it for ``/stats → engine.last_crash``);
the gateway serves the live rings through ``/debug/flight`` and
``/debug/requests``.  Prompt *text* never enters a record unless
``observability.redact_prompts`` is explicitly disabled (then a short
preview is kept); token counts and fingerprints are always safe to log.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from typing import Any, Dict, List, Optional


def _now_wall() -> float:
    return time.time()


class FlightRecorder:
    """Owned by one EngineCore; rebuilt fresh on supervised restart like
    the scheduler (the pre-crash rings live on in the supervisor's
    last-crash snapshot)."""

    def __init__(self, cfg: Optional[Any] = None) -> None:
        # cfg is the config's observability section; default-construct
        # one when absent so direct EngineCore tests need no config.
        if cfg is None:
            from vgate_tpu.config import ObservabilityConfig

            cfg = ObservabilityConfig()
        self.enabled = bool(cfg.enabled)
        self.redact_prompts = bool(cfg.redact_prompts)
        self.preview_chars = int(cfg.prompt_preview_chars)
        self.crash_dump_ticks = int(cfg.crash_dump_ticks)
        self._ticks: "deque[Dict[str, Any]]" = deque(
            maxlen=max(1, int(cfg.flight_ticks))
        )
        self._requests: "deque[Dict[str, Any]]" = deque(
            maxlen=max(1, int(cfg.flight_requests))
        )
        # in-flight request records keyed by seq_id; engine-thread-owned
        # (admit and close both run there), snapshots copy defensively
        self._live: Dict[int, Dict[str, Any]] = {}
        self._tick_counter = itertools.count()

    # ------------------------------------------------------------- ticks

    def record_tick(self, kind: str, **fields: Any) -> None:
        """One engine dispatch/readback or event.  Standard fields the
        engine passes: batch, bucket, step_s, kv_used, kv_free,
        queue_depth; event entries add whatever identifies the event
        (seq_id, request_id, reason, error)."""
        if not self.enabled:
            return
        entry: Dict[str, Any] = {
            "n": next(self._tick_counter),
            "t": _now_wall(),
            "kind": kind,
        }
        entry.update(fields)
        self._ticks.append(entry)

    def ticks(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        out = list(self._ticks)
        if n is not None and n >= 0:
            out = out[-n:]
        return out

    # ---------------------------------------------------------- requests

    # Phase accounting is CUMULATIVE: a record is always "in" exactly
    # one phase (queue_s -> prefill_s -> decode_s, and back to queue_s
    # on preemption); transitions accrue the elapsed time into the
    # finished phase's bucket.  Plain first_token/admit subtraction
    # would go negative after a preemption (Sequence.first_token_t
    # survives reset_for_recompute while the admission time moves).

    @staticmethod
    def _accrue(rec: Dict[str, Any], now: float) -> None:
        phase = rec.get("_phase")
        start = rec.get("_phase_start")
        if phase is not None and start is not None:
            rec[phase] = round(rec.get(phase, 0.0) + (now - start), 6)

    @staticmethod
    def _enter(rec: Dict[str, Any], phase: str, now: float) -> None:
        rec["_phase"] = phase
        rec["_phase_start"] = now

    def on_admit(
        self,
        seq: Any,
        bucket: int,
        cached_len: int = 0,
        preview: Optional[str] = None,
    ) -> None:
        """Engine thread, at admission: opens the live record (or, for
        a preempted re-admission, folds the renewed queue wait into the
        existing one) and enters the prefill phase."""
        if not self.enabled:
            return
        now_pc = time.perf_counter()
        rec = self._live.get(seq.seq_id)
        if rec is None:
            rec = {
                "seq_id": seq.seq_id,
                "request_id": getattr(seq, "request_id", None),
                "trace_id": getattr(
                    getattr(seq, "trace", None), "trace_id", None
                ),
                "arrival_t": _now_wall() - (now_pc - seq.arrival_t),
                "queue_s": round(now_pc - seq.arrival_t, 6),
                "bucket": bucket,
                "cached_tokens": cached_len,
                "prompt_tokens": seq.num_prompt_tokens,
                "deadline_s": seq.params.timeout_s,
                "status": "running",
            }
            if preview is not None and not self.redact_prompts:
                rec["prompt_preview"] = preview[: self.preview_chars]
            self._live[seq.seq_id] = rec
        else:
            # re-admission after preemption: close the renewed queue
            # phase (opened by on_preempt) and note the new bucket
            self._accrue(rec, now_pc)
            rec["bucket"] = bucket
            rec["cached_tokens"] = cached_len
        rec["preemptions"] = seq.preempt_count
        self._enter(rec, "prefill_s", now_pc)

    def on_first_token(self, seq: Any) -> None:
        """Engine thread, when a prefill's sampled token lands: accrue
        the prefill phase and enter decode."""
        rec = self._live.get(seq.seq_id)
        if rec is None:
            return
        now = time.perf_counter()
        self._accrue(rec, now)
        self._enter(rec, "decode_s", now)

    def on_preempt(self, seq: Any) -> None:
        """Engine thread, KV-pressure preemption: the sequence left its
        slot for the waiting queue — accrue the interrupted compute
        phase and re-enter queue time."""
        rec = self._live.get(seq.seq_id)
        if rec is None:
            return
        now = time.perf_counter()
        self._accrue(rec, now)
        self._enter(rec, "queue_s", now)

    def phases_of(self, seq: Any) -> Dict[str, float]:
        """Per-phase durations so far for a LIVE sequence — attached to
        deadline-shed 504 metadata so clients see where the budget
        went.  Empty when the recorder is disabled (a bare
        ``queue_s = elapsed`` would misattribute decode time)."""
        if not self.enabled:
            return {}
        now = time.perf_counter()
        rec = self._live.get(seq.seq_id)
        if rec is None:
            return {"queue_s": round(now - seq.arrival_t, 6)}
        view = dict(rec)
        self._accrue(view, now)
        return {
            key: view[key]
            # transfer_s exists only on disaggregated-pod records (the
            # gateway grafts the KV-handoff wall time onto the merged
            # view); include it so shed metadata decomposes the same
            # way /debug/requests does
            for key in ("queue_s", "prefill_s", "transfer_s", "decode_s")
            if key in view
        }

    def on_close(self, seq: Any) -> None:
        """Engine thread (plus stop/fail paths), when a sequence
        settles: accrues the final phase and moves the record to the
        completed ring.  A sequence that settles WITHOUT ever being
        admitted (deadline/admission shed from the waiting queue, drain
        sweep, crash containment) still gets a queue-only record — the
        queued-forever case is exactly what operators diagnose."""
        if not self.enabled:
            return
        end = seq.finish_t or time.perf_counter()
        rec = self._live.pop(seq.seq_id, None)
        if rec is None:
            rec = {
                "seq_id": seq.seq_id,
                "request_id": getattr(seq, "request_id", None),
                "trace_id": getattr(
                    getattr(seq, "trace", None), "trace_id", None
                ),
                "arrival_t": _now_wall() - (end - seq.arrival_t),
                "queue_s": round(end - seq.arrival_t, 6),
                "bucket": None,
                "cached_tokens": 0,
                "prompt_tokens": seq.num_prompt_tokens,
                "deadline_s": seq.params.timeout_s,
            }
        self._accrue(rec, end)
        rec.pop("_phase", None)
        rec.pop("_phase_start", None)
        rec.setdefault("prefill_s", 0.0)
        rec.setdefault("decode_s", 0.0)
        rec["total_s"] = round(end - seq.arrival_t, 6)
        rec["generated_tokens"] = seq.num_generated
        rec["preemptions"] = seq.preempt_count
        if seq.error is not None:
            rec["status"] = "failed"
            rec["error"] = (
                f"{type(seq.error).__name__}: {seq.error}"
            )
        else:
            rec["status"] = "finished"
            rec["finish_reason"] = seq.finish_reason
        self._requests.append(rec)

    def requests(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """Completed request records, oldest first."""
        out = list(self._requests)
        if n is not None and n >= 0:
            out = out[-n:]
        return out

    def live_requests(self) -> List[Dict[str, Any]]:
        """In-flight records (defensive copies; the in-progress phase
        accrued to now, bookkeeping keys stripped)."""
        out = []
        now = time.perf_counter()
        for rec in list(self._live.values()):
            rec = dict(rec)
            self._accrue(rec, now)
            rec.pop("_phase", None)
            rec.pop("_phase_start", None)
            out.append(rec)
        return out

    def find_request(self, ident: str) -> Optional[Dict[str, Any]]:
        """Lookup by request_id, trace_id, or seq_id (newest match wins
        so a retried request id returns its latest attempt)."""
        pools = [self.live_requests(), self.requests()]
        for pool in pools:
            for rec in reversed(pool):
                if ident in (
                    rec.get("request_id"),
                    rec.get("trace_id"),
                    str(rec.get("seq_id")),
                ):
                    return rec
        return None

    # ------------------------------------------------------------- crash

    def crash_snapshot(self, error: Optional[BaseException] = None) -> Dict[str, Any]:
        """Structured post-mortem: the last ``crash_dump_ticks`` ticks
        plus whatever was in flight.  The supervisor logs this on every
        crash classification and keeps it for /stats."""
        return {
            "time": _now_wall(),
            "error": (
                f"{type(error).__name__}: {error}" if error else None
            ),
            "ticks": self.ticks(self.crash_dump_ticks),
            "in_flight": self.live_requests(),
        }

    def get_stats(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "ticks_recorded": len(self._ticks),
            "requests_recorded": len(self._requests),
            "in_flight": len(self._live),
        }
