"""Device peak table + HBM roofline / MFU math — the single definition
site.

Promoted from ``benchmarks/_roofline.py`` (now a re-export shim) so the
offline benches (bench.py's headline roofline fraction,
bench_decode_ablate's per-row achieved-GB/s columns) and the engine's
LIVE gauges (observability/perf.py -> ``vgt_decode_mfu`` /
``vgt_decode_hbm_roofline_pct``) can never disagree on what a device's
peak is.  Peaks are per chip; unknown device kinds return None so
callers omit the roofline fields rather than mislabel them.

Modeling conventions (shared by bench.py and the live gauges):

* one decode step streams the weights once (untied embedding tables are
  GATHERED row-wise, not streamed — callers exclude them via
  :func:`stream_weight_bytes`) plus reads every resident token's K+V;
* MFU charges 2 FLOPs per parameter per generated token;
* both are optimistic lower bounds on traffic/compute, which is exactly
  what a roofline denominator should be.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

# device_kind -> (bf16 FLOP/s, HBM GB/s) per chip
DEVICE_PEAKS = {
    "TPU v5 lite": (197e12, 819.0),
    "TPU v5e": (197e12, 819.0),
    "TPU v6 lite": (918e12, 1640.0),
    "TPU v6e": (918e12, 1640.0),
    "TPU v5p": (459e12, 2765.0),
    "TPU v5": (459e12, 2765.0),
    "TPU v4": (275e12, 1228.0),
}


def peaks_for(device_kind: str) -> Optional[Tuple[float, float]]:
    return DEVICE_PEAKS.get(device_kind)


def kv_bytes_per_token(
    num_layers: int,
    kv_heads: int,
    head_dim: int,
    dtype_bytes: int = 2,
    scale_bytes: int = 0,
) -> int:
    """HBM bytes one resident token's K+V occupies across all layers —
    what every later decode step must READ back per context token.
    ``scale_bytes`` is the int8-KV per-token-per-head overhead
    (runtime/kv_cache._page_bytes uses the identical formula per page)."""
    return 2 * num_layers * kv_heads * (head_dim * dtype_bytes + scale_bytes)


def decode_step_bytes(
    weight_bytes: int,
    batch: int,
    ctx_tokens: int,
    kv_token_bytes: int,
) -> int:
    """Approximate HBM traffic of ONE decode step: stream the weights
    once plus read every slot's live KV context (writes are one token
    per slot — noise).  An optimistic lower bound (no re-reads, perfect
    caching), which is exactly what a roofline denominator should be."""
    return weight_bytes + batch * ctx_tokens * kv_token_bytes


def roofline_row(
    ms_per_step: float,
    step_bytes: int,
    device_kind: str,
) -> dict:
    """The per-row roofline fields bench_decode_ablate attaches:
    achieved HBM GB/s over the step's modeled traffic, and the percent
    of the device's HBM peak that represents.  Empty for unknown
    devices or non-timed rows."""
    if ms_per_step <= 0:
        return {}
    peaks = peaks_for(device_kind)
    achieved_gbps = step_bytes / (ms_per_step / 1e3) / 1e9
    row = {"achieved_hbm_gbps": round(achieved_gbps, 1)}
    if peaks is not None:
        row["pct_of_hbm_roofline"] = round(
            100.0 * achieved_gbps / peaks[1], 1
        )
    return row


def stream_weight_bytes(params: Any, tie_embeddings: bool) -> int:
    """Bytes of weights one decode step must STREAM from HBM: the full
    tree minus an untied embedding table (gathered one row per token,
    not read fully; tied models read it as lm_head so it stays in).
    Accepts any jax pytree whose leaves expose .size/.dtype (the
    engine's placed params)."""
    import jax

    total = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params)
    )
    if not tie_embeddings and isinstance(params, dict) and "embed" in params:
        total -= sum(
            x.size * x.dtype.itemsize
            for x in jax.tree.leaves(params["embed"])
        )
    return total


@dataclasses.dataclass(frozen=True)
class EngineRoofline:
    """The static geometry the live gauges need, captured once at engine
    build (observability/perf.py holds one; bench.py derives the same
    numbers ad hoc).  ``num_chips`` scales the per-chip peaks to the
    serving mesh; dp replicas each carry their own (their meshes are
    disjoint)."""

    device_kind: str
    num_chips: int
    num_params: int
    # weights streamed per decode step (stream_weight_bytes)
    weight_stream_bytes: int
    kv_token_bytes: int

    def peaks(self) -> Optional[Tuple[float, float]]:
        return peaks_for(self.device_kind)

    def step_bytes(self, ctx_tokens: int) -> int:
        """Modeled HBM traffic of one decode step over ``ctx_tokens``
        TOTAL resident context tokens (already summed over the batch)."""
        return decode_step_bytes(
            self.weight_stream_bytes, 1, ctx_tokens, self.kv_token_bytes
        )

    def mfu(self, tokens_per_s: float) -> Optional[float]:
        """Achieved FLOP/s over the mesh's peak at 2 FLOPs per param per
        generated token; None off the peak table."""
        peaks = self.peaks()
        if peaks is None or tokens_per_s <= 0:
            return None
        return (2.0 * self.num_params * tokens_per_s) / (
            peaks[0] * max(1, self.num_chips)
        )

    def hbm_roofline_pct(
        self, bytes_moved: float, device_s: float
    ) -> Optional[float]:
        """Percent of the mesh's HBM peak the modeled decode traffic
        achieved over ``device_s`` seconds of host-observed device time;
        None off the peak table or without timed device work."""
        peaks = self.peaks()
        if peaks is None or device_s <= 0 or bytes_moved <= 0:
            return None
        achieved_gbps = bytes_moved / device_s / 1e9
        return 100.0 * achieved_gbps / (
            peaks[1] * max(1, self.num_chips)
        )

    def to_dict(self) -> Dict[str, Any]:
        peaks = self.peaks()
        return {
            "device_kind": self.device_kind,
            "num_chips": self.num_chips,
            "num_params": self.num_params,
            "weight_stream_bytes": self.weight_stream_bytes,
            "kv_token_bytes": self.kv_token_bytes,
            "peak_flops": peaks[0] if peaks else None,
            "peak_hbm_gbps": peaks[1] if peaks else None,
        }
