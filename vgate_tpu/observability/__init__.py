"""Engine observability: flight recorder, cross-thread request tracing
and the in-memory span recorder (docs/observability.md).

Three pieces, all degrading to no-ops when disabled or when the OTel API
is absent:

* :mod:`~vgate_tpu.observability.flight` — a lock-cheap ring buffer of
  engine ticks plus bounded per-request records, dumped as a structured
  snapshot on every crash and served live via ``/debug``;
* :mod:`~vgate_tpu.observability.reqtrace` — per-request phase spans
  (``queue`` → ``prefill`` → ``decode`` → ``detokenize``) parented on
  the HTTP request span across the batcher/engine thread boundary;
* :mod:`~vgate_tpu.observability.memtrace` — a minimal recording tracer
  provider built on the OTel *API* alone, so span trees are testable
  (and debuggable in dev) without the OTel SDK installed;
* :mod:`~vgate_tpu.observability.perf` — per-tick phase attribution
  (host/dispatch/device/readback/detok), the compile ledger, and the
  rolling-window MFU / HBM-roofline / host-overhead gauges served via
  ``/debug/perf``;
* :mod:`~vgate_tpu.observability.roofline` — the device peak table and
  roofline/MFU math shared with the benches (benchmarks/_roofline.py is
  a re-export shim of it).
"""

from vgate_tpu.observability.flight import FlightRecorder
from vgate_tpu.observability.perf import PerfRecorder
from vgate_tpu.observability.reqtrace import RequestMeta, RequestTrace

__all__ = [
    "FlightRecorder", "PerfRecorder", "RequestMeta", "RequestTrace",
]
