"""Engine observability: flight recorder, cross-thread request tracing
and the in-memory span recorder (docs/observability.md).

Three pieces, all degrading to no-ops when disabled or when the OTel API
is absent:

* :mod:`~vgate_tpu.observability.flight` — a lock-cheap ring buffer of
  engine ticks plus bounded per-request records, dumped as a structured
  snapshot on every crash and served live via ``/debug``;
* :mod:`~vgate_tpu.observability.reqtrace` — per-request phase spans
  (``queue`` → ``prefill`` → ``decode`` → ``detokenize``) parented on
  the HTTP request span across the batcher/engine thread boundary;
* :mod:`~vgate_tpu.observability.memtrace` — a minimal recording tracer
  provider built on the OTel *API* alone, so span trees are testable
  (and debuggable in dev) without the OTel SDK installed.
"""

from vgate_tpu.observability.flight import FlightRecorder
from vgate_tpu.observability.reqtrace import RequestMeta, RequestTrace

__all__ = ["FlightRecorder", "RequestMeta", "RequestTrace"]
