"""Cross-thread request tracing: one span tree per request.

The HTTP span lives on the event loop; the engine runs on its own
thread.  The batcher captures the request's OTel context at submission
(:class:`RequestMeta`) and threads it through the backend seam to the
engine, which emits **phase spans** parented on it:

    POST /v1/chat/completions          (gateway, server/app.py)
      ├── batcher.submit               (gateway)
      ├── engine.queue                 (submission → admission)
      ├── engine.prefill               (bucket/compile attributes)
      ├── engine.decode                (shed/abort/preempt events)
      └── engine.detokenize            (final text assembly)

Spans are created with explicit timestamps from the engine's
perf_counter anchors, so the tree is exact even though it is assembled
off the request thread.  Everything degrades to no-ops when the OTel
API is absent, no provider is installed, or
``observability.enabled=false`` — exactly the contract tracing.py keeps.

Backends that cannot accept :class:`RequestMeta` (dry-run, external
vLLM/SGLang adapters) still produce the same tree: the batcher emits
approximate phase spans from the backend's reported ttft/gen_time
(:func:`emit_gateway_phases`), attributed ``approximate: true``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from vgate_tpu import tracing

_TRACER_NAME = "vgate_tpu.engine"


@dataclass
class RequestMeta:
    """Per-request identity + trace context crossing the backend seam."""

    request_id: Optional[str] = None
    trace_ctx: Any = None  # captured OTel context, or None


class _NsClock:
    """Maps perf_counter readings onto epoch nanoseconds using one
    anchor pair, so spans built from engine timings carry real
    timestamps."""

    __slots__ = ("wall_ns", "pc")

    def __init__(self) -> None:
        self.wall_ns = time.time_ns()
        self.pc = time.perf_counter()

    def ns(self, pc: Optional[float] = None) -> int:
        if pc is None:
            pc = time.perf_counter()
        return self.wall_ns + int((pc - self.pc) * 1e9)


class RequestTrace:
    """Engine-side phase-span emitter attached to a runtime Sequence.

    All methods are cheap no-ops when the request carried no trace
    context (or observability is disabled); call sites stay
    unconditional.  Phases may restart (preemption re-queues and
    re-prefills) — each ``start`` opens a fresh span, so the trace
    shows the true execution history."""

    def __init__(self, meta: RequestMeta, enabled: bool = True) -> None:
        self.request_id = meta.request_id
        self.trace_id = tracing.context_trace_id(meta.trace_ctx)
        self._ctx = meta.trace_ctx
        # gate on a VALID trace id, not just a context object: the OTel
        # API's get_current() returns an (empty) Context even with no
        # active span, and building no-op span objects per phase on the
        # engine hot path would be pure waste when tracing is off
        self._emit = bool(enabled and self.trace_id is not None)
        self._clock = _NsClock() if self._emit else None
        self._tracer = (
            tracing.get_tracer(_TRACER_NAME) if self._emit else None
        )
        self._open: Dict[str, Any] = {}

    def start(
        self,
        phase: str,
        start_pc: Optional[float] = None,
        **attrs: Any,
    ) -> None:
        if not self._emit:
            return
        span = self._tracer.start_span(
            f"engine.{phase}",
            context=self._ctx,
            start_time=self._clock.ns(start_pc),
        )
        if attrs:
            span.set_attributes(attrs)
        if self.request_id:
            span.set_attribute("request.id", self.request_id)
        self._open[phase] = span

    def end(
        self,
        phase: str,
        end_pc: Optional[float] = None,
        **attrs: Any,
    ) -> None:
        if not self._emit:
            return
        span = self._open.pop(phase, None)
        if span is None:
            return
        if attrs:
            span.set_attributes(attrs)
        span.end(end_time=self._clock.ns(end_pc))

    def event(self, name: str, **attrs: Any) -> None:
        """Attach an event to the most relevant open phase span
        (decode > prefill > queue)."""
        if not self._emit:
            return
        for phase in ("decode", "prefill", "queue"):
            span = self._open.get(phase)
            if span is not None:
                span.add_event(name, attrs or None)
                return

    def preempted(self) -> None:
        """KV-pressure preemption: the sequence leaves its slot and
        re-enters the waiting queue — close the active compute phase
        and open a fresh queue span."""
        if not self._emit:
            return
        self.event("preempted")
        self.end("decode", preempted=True)
        self.end("prefill", preempted=True)
        self.start("queue", preempted=True)

    def resumed(self) -> None:
        """Engine crash/stall checkpoint: the sequence survives into a
        rebuilt (or surviving dp) engine — close the active compute
        phase and re-enter queue, like preemption, so the span tree
        shows the restart gap truthfully instead of one decode span
        silently spanning two engine incarnations."""
        if not self._emit:
            return
        self.event("engine_restart")
        if "queue" in self._open:
            # checkpointed while still WAITING: the queue span simply
            # keeps running across the restart
            return
        self.end("decode", resumed=True)
        self.end("prefill", resumed=True)
        self.start("queue", resumed=True)

    def migrated(self) -> None:
        """Planned live migration (replica drain / rebalance /
        scale-down): the sequence moves to another replica — span
        continuation mirrors :meth:`resumed`, with a ``migrated``
        event instead of ``engine_restart`` so a trace reads as an
        operational move, not a crash."""
        if not self._emit:
            return
        self.event("migrated")
        if "queue" in self._open:
            # evacuated while still WAITING: the queue span simply
            # keeps running across the move
            return
        self.end("decode", migrated=True)
        self.end("prefill", migrated=True)
        self.start("queue", migrated=True)

    def close(self, error: Optional[BaseException] = None) -> None:
        """Settle: end every open phase span.  Idempotent; later
        detokenize spans may still be emitted."""
        if not self._emit:
            return
        for phase in list(self._open):
            span = self._open.pop(phase)
            if error is not None:
                span.record_exception(error)
                span.set_attribute("error.type", type(error).__name__)
            span.end(end_time=self._clock.ns())

    def span(self, phase: str, **attrs: Any):
        """Context manager for a synchronous phase (detokenize)."""
        return _PhaseSpan(self, phase, attrs)


class _PhaseSpan:
    __slots__ = ("_trace", "_phase", "_attrs")

    def __init__(self, trace: RequestTrace, phase: str, attrs) -> None:
        self._trace = trace
        self._phase = phase
        self._attrs = attrs

    def __enter__(self):
        self._trace.start(self._phase, **self._attrs)
        return self

    def __exit__(self, *exc):
        self._trace.end(self._phase)
        return False


def emit_gateway_phases(
    meta: Optional[RequestMeta],
    enqueued_pc: float,
    dispatched_pc: float,
    result_metrics: Dict[str, Any],
    end_pc: float,
) -> None:
    """Approximate phase spans for black-box backends (dry-run, vLLM,
    SGLang): the batcher knows when the request queued and dispatched,
    and the backend reports ttft/gen_time — enough to attribute queue
    vs prefill vs decode without engine cooperation.  The jax_tpu
    backend never reaches this path (it accepts RequestMeta and the
    engine emits exact spans instead)."""
    if meta is None or tracing.context_trace_id(meta.trace_ctx) is None:
        return
    tracer = tracing.get_tracer(_TRACER_NAME)
    clock = _NsClock()

    def _span(name: str, start_pc: float, stop_pc: float, **attrs):
        span = tracer.start_span(
            f"engine.{name}",
            context=meta.trace_ctx,
            start_time=clock.ns(start_pc),
        )
        span.set_attribute("approximate", True)
        if meta.request_id:
            span.set_attribute("request.id", meta.request_id)
        for key, val in attrs.items():
            span.set_attribute(key, val)
        span.end(end_time=clock.ns(stop_pc))

    ttft = float(result_metrics.get("ttft") or 0.0)
    prefill_end = min(dispatched_pc + ttft, end_pc)
    _span("queue", enqueued_pc, dispatched_pc)
    _span("prefill", dispatched_pc, prefill_end)
    _span("decode", prefill_end, end_pc)
