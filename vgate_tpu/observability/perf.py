"""Per-tick perf attribution for the engine loop: where did the time go?

The ROADMAP's decode-roofline item (13.2% -> >=40%) rests on the claim
that per-token host dispatch + readback + Python scheduler overhead
dominates the loss.  This module is the measurement that proves or
sizes that claim — and the evidence base every later perf PR (the
tick -> megatick refactor first) is judged against.

Three pieces, all owned by one :class:`PerfRecorder` that lives next to
the engine's FlightRecorder (engine-thread hot path takes no locks;
snapshot readers copy defensively under the GIL):

* **TickProfile** — every engine tick decomposed into phases:

  - ``host_s``     scheduler/admission/bookkeeping between dispatches
                   (derived: tick wall minus the measured phases, so the
                   five phases sum to the tick wall by construction);
  - ``dispatch_s`` jitted-call return, i.e. trace + enqueue (a FIRST
                   dispatch of a program variant includes its XLA
                   compile — the compile ledger records that share);
  - ``device_s``   host blocked on device execution, measured at the
                   readback boundary the hot path already has
                   (``block_until_ready`` before the existing
                   ``device_get`` — no new sync is added, the one sync
                   is split into wait-for-compute + transfer);
  - ``readback_s`` the device->host transfer (``device_get``);
  - ``detok_s``    token append, stop detection, stream callbacks.

  Host-side KV swap traffic (runtime/kv_swap.py) is currently left in
  ``host_s`` — it is host-paid recovery work, not steady-state decode.

* **Compile ledger** — one entry per compiled program variant
  (program family, signature, trigger, count, seconds), hooked exactly
  where the engine already stamps ``compiling=True`` heartbeats.  In
  steady state the ledger is frozen; entries appearing under load are a
  recompile storm (``VgtRecompileStorm``).

* **Rolling window** — live tok/s, MFU and %-of-HBM-roofline computed
  from the engine's own geometry (observability/roofline.py — the same
  peak table the benches use) plus the host-overhead ratio
  (host_s / wall over the window): the single number the megatick
  refactor exists to drive down.

Surfaces: ``GET /debug/perf`` (auth-gated, drain-uncounted), the
``/stats`` engine block (``perf``), metrics
``vgt_tick_phase_seconds{phase}`` / ``vgt_recompiles_total{variant}`` /
``vgt_decode_mfu`` / ``vgt_decode_hbm_roofline_pct`` /
``vgt_host_overhead_ratio``, and the loadlab artifact's per-cell
``perf`` block (loadlab/runner.py scrapes ``/debug/perf`` around every
QPS cell).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional

from vgate_tpu import metrics
from vgate_tpu.observability.roofline import EngineRoofline

# the fixed phase taxonomy (docs/observability.md "Perf attribution")
PHASES = ("host", "dispatch", "device", "readback", "detok")

# gauges + ledger-size trims run at most this often (engine thread)
_FLUSH_INTERVAL_S = 0.5


class TickProfile:
    """One engine tick's phase decomposition (mutable accumulator while
    the tick runs; frozen by :meth:`PerfRecorder.tick_end`)."""

    __slots__ = (
        "t", "wall", "host", "dispatch", "device", "readback", "detok",
        "tokens", "decode_steps", "decode_bytes", "decode_device_s",
    )

    def __init__(self, t: float) -> None:
        self.t = t
        self.wall = 0.0
        self.host = 0.0
        self.dispatch = 0.0
        self.device = 0.0
        self.readback = 0.0
        self.detok = 0.0
        self.tokens = 0
        self.decode_steps = 0
        self.decode_bytes = 0
        self.decode_device_s = 0.0

    def measured(self) -> float:
        return self.dispatch + self.device + self.readback + self.detok

    def phases(self) -> Dict[str, float]:
        return {
            "host": self.host,
            "dispatch": self.dispatch,
            "device": self.device,
            "readback": self.readback,
            "detok": self.detok,
        }

    def to_dict(self) -> Dict[str, Any]:
        out = {
            f"{name}_s": round(value, 6)
            for name, value in self.phases().items()
        }
        out["wall_s"] = round(self.wall, 6)
        out["tokens"] = self.tokens
        return out


class PerfRecorder:
    """Owned by one EngineCore, rebuilt fresh on supervised restart like
    the flight recorder.  All mutation happens on the engine thread; the
    per-0.5s flush keeps gauge math off the per-tick path."""

    def __init__(
        self,
        cfg: Optional[Any] = None,
        roofline: Optional[EngineRoofline] = None,
        clock: Any = time.perf_counter,
    ) -> None:
        # injectable clock (tests pin window math on a fake clock; the
        # engine always uses perf_counter)
        self._clock = clock
        if cfg is None:
            from vgate_tpu.config import ObservabilityConfig

            cfg = ObservabilityConfig()
        self.enabled = bool(cfg.enabled) and bool(cfg.perf_enabled)
        self.window_s = max(1.0, float(cfg.perf_window_s))
        self.roofline = roofline
        self._ring: "deque[TickProfile]" = deque(
            maxlen=max(16, int(cfg.perf_ticks))
        )
        self._ledger_max = max(16, int(cfg.perf_compile_ledger_max))
        # (program, signature) -> ledger entry, insertion-ordered
        self._ledger: Dict[tuple, Dict[str, Any]] = {}
        self._cur: Optional[TickProfile] = None
        self._next_flush = 0.0
        self._last_profile: Optional[Dict[str, Any]] = None
        # lifetime totals (snapshot deltas drive the loadlab artifact)
        self.total_ticks = 0
        self.total_idle_ticks = 0
        self.total_tokens = 0
        self.total_decode_steps = 0
        self.total_wall_s = 0.0
        self.total_compile_s = 0.0
        self._phase_totals = {name: 0.0 for name in PHASES}
        # monotone per-program compile counters — NOT derived from the
        # evicting ledger, so a recompile storm (which evicts old
        # entries) can never make the loadlab delta go negative
        self._compile_counts: Dict[str, int] = {}
        # label children resolved once: .labels() takes the registry
        # lock per call, and this runs on the loop this module measures
        self._phase_counters = {
            name: metrics.TICK_PHASE_SECONDS.labels(phase=name)
            for name in PHASES
        }

    # ------------------------------------------------- engine hot path

    def tick_begin(self) -> None:
        if not self.enabled:
            return
        self._cur = TickProfile(self._clock())

    def phase(self, name: str, seconds: float) -> None:
        """Accrue measured time into the current tick's ``name`` phase
        (dispatch/device/readback/detok; host is derived)."""
        cur = self._cur
        if cur is None or seconds <= 0:
            return
        setattr(cur, name, getattr(cur, name) + seconds)

    def note_tokens(self, n: int) -> None:
        """Tokens delivered to sequences this tick (decode appends,
        prefill first tokens, accepted speculative runs)."""
        cur = self._cur
        if cur is not None and n > 0:
            cur.tokens += n

    def note_decode(
        self, steps: int, ctx_tokens: int, device_s: float
    ) -> None:
        """One decode-chunk (or spec-verify) readback: ``steps`` fused
        steps over ``ctx_tokens`` total resident context tokens, with
        ``device_s`` of host-observed device time — feeds the modeled
        HBM traffic the roofline gauge divides by."""
        cur = self._cur
        if cur is None:
            return
        cur.decode_steps += steps
        cur.decode_device_s += device_s
        if self.roofline is not None:
            cur.decode_bytes += steps * self.roofline.step_bytes(
                ctx_tokens
            )

    def tick_end(self, worked: bool) -> None:
        """Close the tick: derive ``host_s`` as the unexplained wall
        remainder (clamped at 0 — the explained phases can overshoot
        the wall only by clock noise), push the profile into the
        rolling ring, and feed the phase counters."""
        cur = self._cur
        self._cur = None
        if cur is None:
            return
        now = self._clock()
        cur.wall = now - cur.t
        if not worked and cur.measured() == 0.0 and cur.tokens == 0:
            # no-work ticks are idle polls, not attribution evidence —
            # but the gauge flush still runs on cadence, so an engine
            # going idle decays its window gauges instead of freezing
            # them at the last loaded value
            self.total_idle_ticks += 1
            if now >= self._next_flush:
                self._next_flush = now + _FLUSH_INTERVAL_S
                self._flush_gauges(now)
            return
        cur.host = max(0.0, cur.wall - cur.measured())
        self._ring.append(cur)
        self.total_ticks += 1
        self.total_tokens += cur.tokens
        self.total_decode_steps += cur.decode_steps
        self.total_wall_s += cur.wall
        for name, value in cur.phases().items():
            self._phase_totals[name] += value
            if value > 0:
                self._phase_counters[name].inc(value)
        if now >= self._next_flush:
            self._next_flush = now + _FLUSH_INTERVAL_S
            self._flush_gauges(now)

    def record_compile(
        self,
        program: str,
        signature: Any,
        seconds: float,
        trigger: str,
    ) -> None:
        """One XLA compile observed at a fresh-variant first dispatch
        (the dispatch's duration IS the trace+compile cost — jit
        compiles synchronously at call).  The engine's compiled-variant
        sets gate the call, so each variant lands here exactly once per
        core incarnation; ``count`` > 1 therefore means the SAME
        signature compiled again (it should not, short of a rebuild)."""
        if not self.enabled:
            return
        key = (program, str(signature))
        entry = self._ledger.get(key)
        now = time.time()
        if entry is None:
            if len(self._ledger) >= self._ledger_max:
                # bound the ledger: drop the oldest entry (insertion
                # order ~ compile order; steady state never gets here)
                self._ledger.pop(next(iter(self._ledger)))
            entry = {
                "program": program,
                "signature": str(signature),
                "trigger": trigger,
                "count": 0,
                "seconds": 0.0,
                "first_t": now,
            }
            self._ledger[key] = entry
        entry["count"] += 1
        entry["seconds"] = round(entry["seconds"] + seconds, 6)
        entry["last_t"] = now
        self.total_compile_s += seconds
        self._compile_counts[program] = (
            self._compile_counts.get(program, 0) + 1
        )
        metrics.RECOMPILES_BY_VARIANT.labels(variant=program).inc()

    def note_profile(self, info: Dict[str, Any]) -> None:
        """Link a ``POST /v1/profile`` JAX trace capture to this layer:
        /debug/perf reports the last capture so operators can correlate
        attribution windows with device timelines."""
        self._last_profile = {
            **info, "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
        }

    # ------------------------------------------------------ aggregates

    def _window_profiles(self, now: float) -> List[TickProfile]:
        # copy before iterating: reader threads (/stats, /debug/perf)
        # walk this while the engine thread appends, and a deque
        # iterator raises on concurrent mutation (list() is atomic
        # enough under the GIL)
        profs = list(self._ring)
        cutoff = now - self.window_s
        out: List[TickProfile] = []
        for prof in reversed(profs):
            if prof.t < cutoff:
                break
            out.append(prof)
        out.reverse()
        return out

    def window(self) -> Dict[str, Any]:
        """Rolling-window aggregates: live tok/s, MFU, %-of-HBM-roofline
        and the host-overhead ratio.  Safe from any thread."""
        now = self._clock()
        profs = self._window_profiles(now)
        phases = {name: 0.0 for name in PHASES}
        wall = 0.0
        tokens = 0
        decode_steps = 0
        decode_bytes = 0
        decode_device_s = 0.0
        for prof in profs:
            for name, value in prof.phases().items():
                phases[name] += value
            wall += prof.wall
            tokens += prof.tokens
            decode_steps += prof.decode_steps
            decode_bytes += prof.decode_bytes
            decode_device_s += prof.decode_device_s
        # offered span: from the oldest in-window tick to now (the
        # engine may have gone idle — tok/s decays over real time)
        span = (now - profs[0].t) if profs else 0.0
        tok_s = tokens / span if span > 0 else 0.0
        mfu = hbm_pct = None
        if self.roofline is not None:
            mfu = self.roofline.mfu(tok_s)
            hbm_pct = self.roofline.hbm_roofline_pct(
                decode_bytes, decode_device_s
            )
        return {
            "window_s": self.window_s,
            "span_s": round(span, 3),
            "ticks": len(profs),
            "tokens": tokens,
            "tokens_per_s": round(tok_s, 2),
            "decode_steps": decode_steps,
            "decode_device_s": round(decode_device_s, 6),
            "phase_seconds": {
                k: round(v, 6) for k, v in phases.items()
            },
            "wall_s": round(wall, 6),
            "host_overhead_ratio": (
                round(phases["host"] / wall, 4) if wall > 0 else None
            ),
            "mfu": None if mfu is None else round(mfu, 4),
            "hbm_roofline_pct": (
                None if hbm_pct is None else round(hbm_pct, 2)
            ),
        }

    def _flush_gauges(self, now: float) -> None:
        # None (no in-window work / device off the peak table) exports
        # as 0 so an engine going idle decays the gauges instead of
        # freezing them at the last loaded value
        win = self.window()
        metrics.HOST_OVERHEAD_RATIO.set(
            win["host_overhead_ratio"] or 0.0
        )
        metrics.DECODE_MFU.set(win["mfu"] or 0.0)
        metrics.DECODE_HBM_ROOFLINE_PCT.set(
            win["hbm_roofline_pct"] or 0.0
        )

    def compile_ledger(self) -> List[Dict[str, Any]]:
        return [dict(entry) for entry in list(self._ledger.values())]

    def totals(self) -> Dict[str, Any]:
        """Lifetime counters — monotone, so the loadlab runner can
        difference two scrapes into a per-cell attribution delta.
        ``compiles`` comes from the dedicated counters, NOT the ledger:
        ledger eviction under a recompile storm must never make a
        delta go negative."""
        compiles = dict(self._compile_counts)
        return {
            "ticks": self.total_ticks,
            "idle_ticks": self.total_idle_ticks,
            "tokens": self.total_tokens,
            "decode_steps": self.total_decode_steps,
            "wall_s": round(self.total_wall_s, 6),
            "phase_seconds": {
                k: round(v, 6) for k, v in self._phase_totals.items()
            },
            "compiles": compiles,
            "compile_seconds": round(self.total_compile_s, 6),
        }

    def snapshot(self) -> Dict[str, Any]:
        """The full /debug/perf payload for one engine core."""
        if not self.enabled:
            return {"enabled": False}
        last = list(self._ring)[-1:]
        return {
            "enabled": True,
            "window": self.window(),
            "totals": self.totals(),
            "last_tick": last[0].to_dict() if last else None,
            "compile_ledger": self.compile_ledger(),
            "roofline": (
                self.roofline.to_dict()
                if self.roofline is not None
                else None
            ),
            "last_profile": self._last_profile,
        }

    def get_stats(self) -> Dict[str, Any]:
        """The compact /stats ``perf`` block."""
        if not self.enabled:
            return {"enabled": False}
        win = self.window()
        return {
            "enabled": True,
            "tokens_per_s": win["tokens_per_s"],
            "mfu": win["mfu"],
            "hbm_roofline_pct": win["hbm_roofline_pct"],
            "host_overhead_ratio": win["host_overhead_ratio"],
            "phase_seconds": self.totals()["phase_seconds"],
            "ticks": self.total_ticks,
            "compiles": self.totals()["compiles"],
            "compile_seconds": round(self.total_compile_s, 6),
        }


# ------------------------------------------------------- dp aggregation

def _weighted_ratio(parts: List[tuple]) -> Optional[float]:
    """Weighted mean of (value, weight) pairs, None-tolerant."""
    num = den = 0.0
    for value, weight in parts:
        if value is None or weight <= 0:
            continue
        num += value * weight
        den += weight
    return round(num / den, 4) if den > 0 else None


def merge_snapshots(
    snaps: List[Dict[str, Any]]
) -> Dict[str, Any]:
    """Fold per-replica /debug/perf snapshots into one pod view
    (runtime/dp_engine.py — the _MergedFlight pattern): additive fields
    sum, ratios average weighted by each replica's measured wall, and
    the per-replica payloads stay attached under ``replicas`` with
    their index."""
    enabled = [s for s in snaps if s.get("enabled")]
    out: Dict[str, Any] = {
        "enabled": bool(enabled),
        "replicas": [
            {"replica": i, **s} for i, s in enumerate(snaps)
        ],
    }
    if not enabled:
        return out
    windows = [s["window"] for s in enabled]
    totals = [s["totals"] for s in enabled]
    agg_window: Dict[str, Any] = {
        "window_s": max(w["window_s"] for w in windows),
        "ticks": sum(w["ticks"] for w in windows),
        "tokens": sum(w["tokens"] for w in windows),
        "tokens_per_s": round(
            sum(w["tokens_per_s"] for w in windows), 2
        ),
        "decode_steps": sum(w["decode_steps"] for w in windows),
        "phase_seconds": {
            name: round(
                sum(w["phase_seconds"][name] for w in windows), 6
            )
            for name in PHASES
        },
        "wall_s": round(sum(w["wall_s"] for w in windows), 6),
        "host_overhead_ratio": _weighted_ratio(
            [(w["host_overhead_ratio"], w["wall_s"]) for w in windows]
        ),
        # replicas are symmetric meshes: fleet MFU/roofline is the
        # token-weighted mean of the per-replica fractions
        "mfu": _weighted_ratio(
            [(w["mfu"], max(1, w["tokens"])) for w in windows]
        ),
        "hbm_roofline_pct": _weighted_ratio(
            [
                (w["hbm_roofline_pct"], w["decode_device_s"])
                for w in windows
            ]
        ),
    }
    agg_compiles: Dict[str, int] = {}
    for t in totals:
        for program, count in t["compiles"].items():
            agg_compiles[program] = (
                agg_compiles.get(program, 0) + count
            )
    agg_totals = {
        "ticks": sum(t["ticks"] for t in totals),
        "idle_ticks": sum(t["idle_ticks"] for t in totals),
        "tokens": sum(t["tokens"] for t in totals),
        "decode_steps": sum(t["decode_steps"] for t in totals),
        "wall_s": round(sum(t["wall_s"] for t in totals), 6),
        "phase_seconds": {
            name: round(
                sum(t["phase_seconds"][name] for t in totals), 6
            )
            for name in PHASES
        },
        "compiles": agg_compiles,
        "compile_seconds": round(
            sum(t["compile_seconds"] for t in totals), 6
        ),
    }
    out["window"] = agg_window
    out["totals"] = agg_totals
    return out


def merge_stats(blocks: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The dp /stats ``perf`` aggregate from per-replica get_stats
    blocks (additive sums; ratio gauges wall/token-weighted like
    merge_snapshots)."""
    enabled = [b for b in blocks if b.get("enabled")]
    if not enabled:
        return {"enabled": False}
    compiles: Dict[str, int] = {}
    for b in enabled:
        for program, count in b.get("compiles", {}).items():
            compiles[program] = compiles.get(program, 0) + count
    wall_of = [
        sum(b["phase_seconds"].values()) for b in enabled
    ]
    # efficiency ratios weight by each replica's live throughput so a
    # near-idle replica cannot drag the pod number — the same weighting
    # family merge_snapshots uses for /debug/perf, keeping the two
    # surfaces consistent
    tok_of = [max(b["tokens_per_s"], 1e-9) for b in enabled]
    return {
        "enabled": True,
        "tokens_per_s": round(
            sum(b["tokens_per_s"] for b in enabled), 2
        ),
        "mfu": _weighted_ratio(
            [(b["mfu"], w) for b, w in zip(enabled, tok_of)]
        ),
        "hbm_roofline_pct": _weighted_ratio(
            [
                (b["hbm_roofline_pct"], w)
                for b, w in zip(enabled, tok_of)
            ]
        ),
        "host_overhead_ratio": _weighted_ratio(
            [
                (b["host_overhead_ratio"], w)
                for b, w in zip(enabled, wall_of)
            ]
        ),
        "phase_seconds": {
            name: round(
                sum(b["phase_seconds"][name] for b in enabled), 6
            )
            for name in PHASES
        },
        "ticks": sum(b["ticks"] for b in enabled),
        "compiles": compiles,
        "compile_seconds": round(
            sum(b["compile_seconds"] for b in enabled), 6
        ),
    }
