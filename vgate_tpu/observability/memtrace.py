"""A minimal in-memory span recorder built on the OpenTelemetry *API*.

This environment ships the OTel API but not the SDK, and ``tracing.py``
degrades to no-op spans in that case — which leaves the span taxonomy
untestable.  This module implements just enough of the API's
``TracerProvider``/``Tracer``/``Span`` surface to record real span trees
(ids, parents, attributes, events, timestamps) into a bounded in-memory
list, with correct context propagation via the API's contextvars
runtime.  Installed through
:func:`vgate_tpu.tracing.set_tracer_provider_override`, so it wins over
the global provider without touching OTel's set-once global state.

Test/dev tooling only — production tracing goes through ``init_tracing``
and the real SDK when present.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

try:
    from opentelemetry import trace as _trace
    from opentelemetry.trace import (
        INVALID_SPAN,
        SpanContext,
        TraceFlags,
    )
except ImportError:  # pragma: no cover - OTel API absent
    _trace = None

_ids = random.Random()
_ids_lock = threading.Lock()


def _gen_ids(parent_sc) -> "SpanContext":
    with _ids_lock:
        trace_id = (
            parent_sc.trace_id
            if parent_sc is not None and parent_sc.is_valid
            else _ids.getrandbits(128)
        )
        span_id = _ids.getrandbits(64)
    return SpanContext(
        trace_id=trace_id,
        span_id=span_id,
        is_remote=False,
        trace_flags=TraceFlags(TraceFlags.SAMPLED),
    )


class MemorySpan(_trace.Span if _trace is not None else object):
    """Recording span: attributes/events/status land on the object; the
    recorder keeps every started span (ended or not) in order."""

    def __init__(
        self,
        name: str,
        context: "SpanContext",
        parent: Optional["SpanContext"],
        attributes: Optional[Dict[str, Any]] = None,
        start_time: Optional[int] = None,
    ) -> None:
        self.name = name
        self._context = context
        self.parent = parent
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.events: List[tuple] = []
        self.status: Optional[Any] = None
        self.recorded_exceptions: List[BaseException] = []
        self.start_time = (
            start_time if start_time is not None else time.time_ns()
        )
        self.end_time: Optional[int] = None

    # -- OTel API Span surface --

    def get_span_context(self) -> "SpanContext":
        return self._context

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def set_attributes(self, attributes: Dict[str, Any]) -> None:
        self.attributes.update(attributes)

    def add_event(
        self, name: str, attributes: Any = None, timestamp: Any = None
    ) -> None:
        self.events.append(
            (name, dict(attributes or {}), timestamp or time.time_ns())
        )

    def add_link(self, context: Any, attributes: Any = None) -> None:
        pass

    def update_name(self, name: str) -> None:
        self.name = name

    def is_recording(self) -> bool:
        return self.end_time is None

    def set_status(self, status: Any, description: Any = None) -> None:
        self.status = status

    def record_exception(
        self,
        exception: BaseException,
        attributes: Any = None,
        timestamp: Any = None,
        escaped: bool = False,
    ) -> None:
        self.recorded_exceptions.append(exception)
        self.add_event(
            "exception", {"exception.type": type(exception).__name__}
        )

    def end(self, end_time: Optional[int] = None) -> None:
        if self.end_time is None:
            self.end_time = (
                end_time if end_time is not None else time.time_ns()
            )

    # -- convenience for assertions --

    @property
    def trace_id_hex(self) -> str:
        return format(self._context.trace_id, "032x")

    @property
    def span_id_hex(self) -> str:
        return format(self._context.span_id, "016x")

    @property
    def parent_span_id_hex(self) -> Optional[str]:
        if self.parent is None or not self.parent.is_valid:
            return None
        return format(self.parent.span_id, "016x")

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (
            f"MemorySpan({self.name!r}, trace={self.trace_id_hex[:8]}, "
            f"span={self.span_id_hex[:8]}, "
            f"parent={(self.parent_span_id_hex or 'root')[:8]})"
        )


class MemoryTracer(_trace.Tracer if _trace is not None else object):
    def __init__(self, recorder: "MemorySpanRecorder") -> None:
        self._recorder = recorder

    def start_span(
        self,
        name: str,
        context: Any = None,
        kind: Any = None,
        attributes: Any = None,
        links: Any = None,
        start_time: Optional[int] = None,
        record_exception: bool = True,
        set_status_on_exception: bool = True,
    ) -> MemorySpan:
        parent_span = _trace.get_current_span(context)
        parent_sc = None
        if parent_span is not None and parent_span is not INVALID_SPAN:
            sc = parent_span.get_span_context()
            if sc is not None and sc.is_valid:
                parent_sc = sc
        span = MemorySpan(
            name,
            _gen_ids(parent_sc),
            parent_sc,
            attributes=attributes,
            start_time=start_time,
        )
        self._recorder._record(span)
        return span

    @contextmanager
    def start_as_current_span(
        self,
        name: str,
        context: Any = None,
        kind: Any = None,
        attributes: Any = None,
        links: Any = None,
        start_time: Optional[int] = None,
        record_exception: bool = True,
        set_status_on_exception: bool = True,
        end_on_exit: bool = True,
    ):
        span = self.start_span(
            name, context=context, attributes=attributes,
            start_time=start_time,
        )
        with _trace.use_span(
            span,
            end_on_exit=end_on_exit,
            record_exception=record_exception,
            set_status_on_exception=set_status_on_exception,
        ) as active:
            yield active


class MemorySpanRecorder:
    """TracerProvider + span store.  ``install()`` routes every
    ``vgate_tpu.tracing.get_tracer`` through it; ``uninstall()`` (or the
    test harness's ``reset_tracing``) restores the default path."""

    def __init__(self, max_spans: int = 10_000) -> None:
        self._spans: "deque[MemorySpan]" = deque(maxlen=max_spans)
        self._lock = threading.Lock()

    # -- TracerProvider surface --

    def get_tracer(self, name: str, *a: Any, **k: Any) -> MemoryTracer:
        return MemoryTracer(self)

    # -- recording --

    def _record(self, span: MemorySpan) -> None:
        with self._lock:
            self._spans.append(span)

    def spans(self, name: Optional[str] = None) -> List[MemorySpan]:
        """Started spans in start order (optionally filtered by name)."""
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        return out

    def finished_spans(self) -> List[MemorySpan]:
        return [s for s in self.spans() if s.end_time is not None]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- lifecycle --

    def install(self) -> "MemorySpanRecorder":
        from vgate_tpu import tracing

        if _trace is None:  # pragma: no cover - OTel API absent
            raise RuntimeError(
                "the opentelemetry API is required for span recording"
            )
        tracing.set_tracer_provider_override(self)
        return self

    def uninstall(self) -> None:
        from vgate_tpu import tracing

        tracing.set_tracer_provider_override(None)
