"""Fault-injection registry: named failure points the runtime probes.

Serving-system comparisons judge frameworks on recovery-under-failure
(PAPERS.md, vLLM-vs-TGI), and an in-house engine cannot delegate crash
handling to an external one the way the reference V-Gate did — so the
failure paths must be *testable*.  This module gives deterministic tests
and a chaos mode a way to make any probed site raise, delay, or corrupt
on demand, without monkeypatching engine internals.

Probed sites (each calls :func:`check` with the point name):

==================  ====================================================
``decode_step``     engine_core dispatching a decode chunk / spec round
``prefill``         engine_core dispatching a prefill (payload = the
                    request's original prompt token ids, so a fault can
                    target one poison request via ``match``)
``weight_load``     runtime.weights.load_or_init_params
``kv_alloc``        runtime.kv_cache.PageAllocator.allocate
``backend_generate``  backends.jax_backend generate entry points
``stall``           engine_core tick, probed only while work is resident
                    — arm with ``mode="delay"`` and a ``delay_s`` past
                    ``recovery.step_stall_s`` to simulate a wedged loop
                    (stuck decode step / Mosaic hang) for the hang
                    watchdog; ``raise`` mode is a plain tick crash
``weight_corrupt``  integrity idle sweep (vgate_tpu/integrity.py) —
                    ``corrupt`` mode XOR-flips the bits of one
                    device-resident weight shard (a TRUE silent
                    corruption: the checksum sweep detects it, the
                    canary genuinely fails, and the supervisor/dp
                    repair reloads weights); ``raise`` mode with
                    ``kind=corrupt`` drills the classification path
                    without touching weights
``logit_corrupt``   decode-chunk readback — ``corrupt`` mode scrambles
                    the on-device logit-guard flag word so the output
                    sentinels trip exactly as they would on NaN logits
                    (requires ``integrity.logit_guard``)
``rpc_send``        worker RPC plane (runtime/rpc.py), outbound frame —
                    wire modes apply: ``drop`` discards the frame
                    unsent, ``garble`` scrambles its bytes on the wire
                    (the peer sees a framing violation → typed error +
                    connection teardown), ``delay`` stalls the send,
                    ``raise`` fails it
``rpc_recv``        worker RPC plane, inbound frame — same wire modes,
                    applied after a frame decodes (``garble`` instead
                    corrupts the raw bytes before decoding)
``kv_transfer``     disaggregated prefill→decode KV handoff (runtime/
                    pod_engine.py), probed per transfer chunk — wire
                    modes apply: ``drop`` loses the chunk (coverage gap
                    at commit → typed error → bounded retry), ``garble``
                    corrupts its bytes (digest mismatch at commit),
                    ``duplicate`` ships the chunk twice (idempotent-put
                    drill), ``delay`` widens the kill window, ``raise``
                    fails the transfer call outright
==================  ====================================================

Arming — programmatic (tests)::

    from vgate_tpu import faults
    faults.arm("decode_step", mode="raise", kind="transient", times=1)
    faults.arm("prefill", kind="poison", times=-1,
               match=lambda ids: 666 in ids)

or env-driven (chaos / ops drills), parsed once at import and on demand
via :func:`arm_from_env`::

    VGT_FAULTS="decode_step:raise:times=2,prefill:delay:delay=0.1"
    VGT_CHAOS="0.02"        # every point, raise, 2% per probe

``kind`` feeds the supervisor's error classifier
(vgate_tpu/runtime/supervisor.py): ``transient`` faults trigger a
supervised restart, ``poison`` quarantines the matched request, and
``unrecoverable`` sends the health state machine straight to ``DEAD``.

The disarmed fast path is one module-global boolean read — safe to leave
in hot loops (the kv allocator probes on every page allocation).
"""

from __future__ import annotations

import hashlib
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from vgate_tpu.logging_config import get_logger
from vgate_tpu.analysis.witness import named_lock

logger = get_logger(__name__)

FAULT_POINTS = (
    "decode_step",
    "prefill",
    "weight_load",
    "kv_alloc",
    "backend_generate",
    "stall",
    "weight_corrupt",
    "logit_corrupt",
    "rpc_send",
    "rpc_recv",
    "kv_transfer",
)

# wire modes only make sense where there is a wire: the RPC plane probes
# via wire_action(), everything else probes via check()/corrupt_array()
WIRE_POINTS = ("rpc_send", "rpc_recv", "kv_transfer")
WIRE_MODES = ("drop", "garble", "duplicate")
# frame duplication only makes sense on the chunked KV-handoff plane —
# the request/reply RPC verbs have no idempotent-redelivery semantics
DUPLICATE_POINTS = ("kv_transfer",)

# `corrupt` routes the supervisor/dp repair to the RELOAD rebuild path
# (weights-kept restarts would preserve the corruption) — see
# vgate_tpu/integrity.py and runtime/supervisor.py classify_fatal
FAULT_KINDS = ("transient", "poison", "unrecoverable", "corrupt")

FAULTS_ENV = "VGT_FAULTS"
CHAOS_ENV = "VGT_CHAOS"


class InjectedFault(RuntimeError):
    """Raised by an armed ``raise``-mode fault.  ``fault_kind`` drives the
    supervisor's classification; ``fingerprint`` (when the probe passed a
    payload) names the request the fault targeted."""

    def __init__(
        self,
        point: str,
        kind: str = "transient",
        fingerprint: Optional[str] = None,
    ) -> None:
        super().__init__(f"injected {kind} fault at {point!r}")
        self.point = point
        self.fault_kind = kind
        self.fingerprint = fingerprint


def fingerprint(payload: Any) -> str:
    """Stable identity used by fault matching and the poison quarantine.
    Token-id sequences (the prefill probe's payload) hash by value so a
    list and tuple of the same prompt collide; scalar/string payloads
    (kv_alloc passes a page count, weight_load a checkpoint path) hash
    by repr — check() must never crash on a probe's payload type."""
    if isinstance(payload, (str, bytes)):
        data = payload.encode() if isinstance(payload, str) else payload
    else:
        try:
            data = " ".join(str(int(t)) for t in payload).encode()
        except (TypeError, ValueError):
            data = repr(payload).encode()
    return hashlib.sha1(data).hexdigest()[:16]


@dataclass
class FaultSpec:
    point: str
    mode: str = "raise"  # raise | delay | corrupt | drop | garble
    kind: str = "transient"  # transient | poison | unrecoverable
    times: int = 1  # fires remaining; -1 = unlimited
    probability: float = 1.0
    delay_s: float = 0.05
    # payload predicate: only probes whose payload satisfies it fire
    # (e.g. target one poison prompt).  None matches every probe.
    match: Optional[Callable[[Any], bool]] = None
    fired: int = 0
    _rng: random.Random = field(default_factory=random.Random, repr=False)


_lock = named_lock("faults._lock")
_specs: Dict[str, List[FaultSpec]] = {}
# fast-path guard: hot probe sites read one boolean when nothing is armed
_active = False


def is_active() -> bool:
    """True when any fault is armed — hot probe sites whose *payload* is
    costly to build should gate on this before constructing it (check()
    itself already fast-paths, but its arguments are evaluated first)."""
    return _active


def arm(
    point: str,
    mode: str = "raise",
    kind: str = "transient",
    times: int = 1,
    probability: float = 1.0,
    delay_s: float = 0.05,
    match: Optional[Callable[[Any], bool]] = None,
    seed: Optional[int] = None,
) -> FaultSpec:
    """Arm one fault at ``point``.  Returns the spec (its ``fired``
    counter is live, so tests can assert the probe actually tripped)."""
    global _active
    if point not in FAULT_POINTS:
        raise ValueError(
            f"unknown fault point {point!r}; valid: {FAULT_POINTS}"
        )
    if mode not in ("raise", "delay", "corrupt") + WIRE_MODES:
        raise ValueError(f"unknown fault mode {mode!r}")
    if mode in WIRE_MODES and point not in WIRE_POINTS:
        raise ValueError(
            f"mode {mode!r} is wire-only; valid points: {WIRE_POINTS}"
        )
    if mode == "duplicate" and point not in DUPLICATE_POINTS:
        raise ValueError(
            f"mode 'duplicate' is chunk-transfer-only; valid points: "
            f"{DUPLICATE_POINTS}"
        )
    if kind not in FAULT_KINDS:
        raise ValueError(f"unknown fault kind {kind!r}")
    spec = FaultSpec(
        point=point,
        mode=mode,
        kind=kind,
        times=times,
        probability=probability,
        delay_s=delay_s,
        match=match,
    )
    if seed is not None:
        spec._rng.seed(seed)
    with _lock:
        _specs.setdefault(point, []).append(spec)
        _active = True
    logger.warning(
        "fault armed",
        extra={
            "extra_data": {
                "point": point, "mode": mode, "kind": kind,
                "times": times, "probability": probability,
            }
        },
    )
    return spec


def disarm(point: Optional[str] = None) -> None:
    """Disarm every fault at ``point`` (all points when None)."""
    global _active
    with _lock:
        if point is None:
            _specs.clear()
        else:
            _specs.pop(point, None)
        _active = any(_specs.values())


def reset() -> None:
    """Full reset (tests call this between cases)."""
    disarm(None)


def snapshot() -> List[Dict[str, Any]]:
    """Armed-fault inventory for /stats and operator introspection."""
    with _lock:
        return [
            {
                "point": s.point,
                "mode": s.mode,
                "kind": s.kind,
                "times": s.times,
                "probability": s.probability,
                "fired": s.fired,
            }
            for specs in _specs.values()
            for s in specs
        ]


def _take(point: str, payload: Any, modes) -> Optional[FaultSpec]:
    """Pick the first armed spec at ``point`` whose mode is in ``modes``,
    matches, and fires — consuming one charge.  Called with the registry
    lock held.  The mode filter splits the probe families: ``check``
    consumes raise/delay specs, ``corrupt_array``/``take_corrupt``
    consume corrupt specs, and ``wire_action`` (the RPC plane) consumes
    raise/delay/drop/garble specs."""
    global _active
    for spec in _specs.get(point, ()):
        if spec.mode not in modes:
            continue
        if spec.times == 0:
            continue
        if spec.match is not None:
            try:
                if not spec.match(payload):
                    continue
            except Exception:  # a broken predicate must not mask serving
                continue
        if spec.probability < 1.0 and spec._rng.random() >= spec.probability:
            continue
        spec.fired += 1
        if spec.times > 0:
            spec.times -= 1
            if spec.times == 0:
                # prune exhausted one-shots so the hot-path probes get
                # their one-boolean fast path back once nothing is armed
                remaining = [s for s in _specs[point] if s is not spec]
                if remaining:
                    _specs[point] = remaining
                else:
                    del _specs[point]
                _active = any(_specs.values())
        return spec
    return None


def check(point: str, payload: Any = None) -> None:
    """Probe call threaded through the runtime.  No-op unless a matching
    fault is armed; otherwise sleeps (``delay``) or raises
    :class:`InjectedFault` (``raise``).  ``corrupt`` specs are consumed
    by :func:`corrupt_array` at readback sites, not here."""
    if not _active:
        return
    with _lock:
        spec = _take(point, payload, modes=("raise", "delay"))
    if spec is None:
        return
    from vgate_tpu import metrics

    metrics.FAULTS_INJECTED.labels(point=point, mode=spec.mode).inc()
    if spec.mode == "delay":
        time.sleep(spec.delay_s)
        return
    fp = fingerprint(payload) if payload is not None else None
    raise InjectedFault(point, kind=spec.kind, fingerprint=fp)


def wire_action(point: str, payload: Any = None) -> Optional[str]:
    """Probe call for the worker RPC plane (vgate_tpu/runtime/rpc.py).
    Returns the wire verdict for one frame: ``None`` (send/deliver it
    untouched, the overwhelmingly common disarmed fast path), ``"drop"``
    (discard the frame silently — the peer sees a missing reply and its
    call deadline fires), ``"garble"`` (the caller scrambles the raw
    frame bytes so the peer hits a framing violation and tears the
    connection down), or ``"duplicate"`` (kv_transfer only: the caller
    ships the chunk twice to drill idempotent redelivery).  ``delay``
    specs sleep here and then deliver;
    ``raise`` specs raise :class:`InjectedFault` at the wire call site."""
    if not _active:
        return None
    if point not in WIRE_POINTS:
        raise ValueError(
            f"wire_action probed at non-wire point {point!r}; "
            f"valid: {WIRE_POINTS}"
        )
    with _lock:
        spec = _take(point, payload, modes=("raise", "delay") + WIRE_MODES)
    if spec is None:
        return None
    from vgate_tpu import metrics

    metrics.FAULTS_INJECTED.labels(point=point, mode=spec.mode).inc()
    if spec.mode == "delay":
        time.sleep(spec.delay_s)
        return None
    if spec.mode in WIRE_MODES:
        return spec.mode
    fp = fingerprint(payload) if payload is not None else None
    raise InjectedFault(point, kind=spec.kind, fingerprint=fp)


def corrupt_array(point: str, array):
    """Value-corruption hook for readback sites: when a ``corrupt`` fault
    is armed at ``point`` and fires, returns a deterministically
    scrambled copy of ``array`` (token ids XOR 0x55 — garbage but valid
    int32) so downstream token handling sees corrupted data without the
    probe site knowing array semantics."""
    if not _active:
        return array
    with _lock:
        spec = _take(point, None, modes=("corrupt",))
        if spec is None:
            return array
    from vgate_tpu import metrics

    metrics.FAULTS_INJECTED.labels(point=point, mode="corrupt").inc()
    return array ^ 0x55


def take_corrupt(point: str) -> bool:
    """Consume one armed ``corrupt``-mode charge at ``point`` WITHOUT
    transforming an array — for sites whose corruption payload is not a
    simple int-XOR (the integrity sweep bit-flips a float weight shard
    via bitcast; vgate_tpu/integrity.py).  Returns True when a spec
    fired; the caller performs the corruption itself."""
    if not _active:
        return False
    with _lock:
        spec = _take(point, None, modes=("corrupt",))
    if spec is None:
        return False
    from vgate_tpu import metrics

    metrics.FAULTS_INJECTED.labels(point=point, mode="corrupt").inc()
    return True


def arm_from_env(environ: Optional[Dict[str, str]] = None) -> int:
    """Parse ``VGT_FAULTS`` / ``VGT_CHAOS`` and arm accordingly; returns
    the number of specs armed.

    ``VGT_FAULTS`` is comma-separated entries ``point:mode[:key=value...]``
    with keys ``kind``, ``times``, ``p`` (probability), ``delay``::

        VGT_FAULTS="decode_step:raise:kind=transient:times=2,kv_alloc:delay:delay=0.01"

    ``VGT_CHAOS=<probability>`` arms an unlimited transient ``raise`` at
    every point with that per-probe probability (the chaos-mode knob the
    chaos test suite and ops drills use)."""
    env = environ if environ is not None else os.environ
    armed = 0
    chaos = env.get(CHAOS_ENV, "").strip()
    if chaos:
        try:
            p = float(chaos)
        except ValueError:
            logger.error("invalid %s=%r (want a probability)", CHAOS_ENV, chaos)
        else:
            if p > 0:
                for point in FAULT_POINTS:
                    arm(point, mode="raise", kind="transient",
                        times=-1, probability=p)
                    armed += 1
    raw = env.get(FAULTS_ENV, "").strip()
    if not raw:
        return armed
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) < 2:
            logger.error("invalid %s entry %r (want point:mode[:k=v])",
                         FAULTS_ENV, entry)
            continue
        point, mode = parts[0], parts[1]
        kwargs: Dict[str, Any] = {}
        bad = False
        for kv in parts[2:]:
            key, _, val = kv.partition("=")
            try:
                if key == "kind":
                    kwargs["kind"] = val
                elif key == "times":
                    kwargs["times"] = int(val)
                elif key == "p":
                    kwargs["probability"] = float(val)
                elif key == "delay":
                    kwargs["delay_s"] = float(val)
                else:
                    raise ValueError(f"unknown key {key!r}")
            except ValueError as exc:
                logger.error("invalid %s entry %r: %s", FAULTS_ENV, entry, exc)
                bad = True
                break
        if bad:
            continue
        try:
            arm(point, mode=mode, **kwargs)
            armed += 1
        except ValueError as exc:
            logger.error("invalid %s entry %r: %s", FAULTS_ENV, entry, exc)
    return armed


# env-armed faults apply process-wide from first import (the engine
# imports this module before any probe can run)
arm_from_env()
