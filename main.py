"""Server entrypoint: ``python main.py`` (reference: main.py:389-391).

All app construction lives in vgate_tpu/server/app.py; engine + batcher init
happens inside the aiohttp startup hooks (the reference's lifespan lesson:
heavyweight engine init must occur inside the app lifecycle, main.py:48-66).
"""

from vgate_tpu.server.app import main

if __name__ == "__main__":
    main()
