"""Per-engine benchmark CLI.

Parity with the reference's benchmarks/bench_compare.py:42-178 — same stat
shape (latency mean/p50/p95, TTFT, TPOT, tokens/sec; table or JSON output;
warmup + timed rounds over a prompt set; engine constructed directly so the
batcher and cache stay out of the measurement) — plus the per-chip
normalization BASELINE.md requires (tokens/sec/chip) and a concurrent mode
that exercises continuous batching, which the reference's blocking engines
could not express.

Usage:
  python -m benchmarks.bench_compare --engines dry_run jax_tpu \
      --rounds 3 --max-tokens 64 --concurrency 8 --output json
"""

from __future__ import annotations

import argparse
import json
import statistics
import time
from typing import Any, Dict, List

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import get_config, load_config, set_config
from vgate_tpu.engine import VGTEngine

DEFAULT_PROMPTS = [
    "Explain the benefits of systolic arrays in two sentences.",
    "Write a haiku about high-bandwidth memory.",
    "What is sequence parallelism?",
]


def _percentile(sorted_vals: List[float], p: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1, int(len(sorted_vals) * p))
    return sorted_vals[idx]


def run_benchmark(
    engine_type: str,
    prompts: List[str],
    rounds: int,
    warmup_rounds: int,
    max_tokens: int,
    concurrency: int = 1,
) -> Dict[str, Any]:
    """Benchmark one engine type (reference: bench_compare.py:42-108)."""
    config = load_config(model={"engine_type": engine_type})
    set_config(config)
    engine = VGTEngine(config)
    try:
        import jax

        num_chips = (
            1
            if engine_type == "dry_run"
            else max(1, len(getattr(engine.backend, "core", None).mesh.devices.flat)
                     if getattr(engine.backend, "core", None) else 1)
        )

        for _ in range(warmup_rounds):
            for prompt in prompts:
                engine.chat_completions(prompt, max_tokens=max_tokens)

        latencies: List[float] = []
        ttfts: List[float] = []
        tpots: List[float] = []
        total_tokens = 0
        bench_start = time.perf_counter()
        for _ in range(rounds):
            if concurrency <= 1:
                for prompt in prompts:
                    start = time.perf_counter()
                    result = engine.chat_completions(
                        prompt, max_tokens=max_tokens
                    )
                    latencies.append(time.perf_counter() - start)
                    ttfts.append(result["metrics"].get("ttft", 0.0))
                    tpots.append(result["metrics"].get("tpot", 0.0))
                    total_tokens += result["num_tokens"]
            else:
                # concurrent round: fan prompts through the backend batch API
                batch = (prompts * ((concurrency // len(prompts)) + 1))[
                    :concurrency
                ]
                params = [
                    engine.backend.create_sampling_params(
                        max_tokens=max_tokens,
                        temperature=config.inference.temperature,
                        top_p=config.inference.top_p,
                    )
                    for _ in batch
                ]
                start = time.perf_counter()
                results = engine.generate_batch(batch, params)
                wall = time.perf_counter() - start
                latencies.append(wall)
                for result in results:
                    ttfts.append(result.metrics.get("ttft", 0.0))
                    tpots.append(result.metrics.get("tpot", 0.0))
                    total_tokens += result.num_tokens
        bench_wall = time.perf_counter() - bench_start

        lat_ms = sorted(x * 1000 for x in latencies)
        ttft_ms = sorted(x * 1000 for x in ttfts)
        tpot_ms = sorted(x * 1000 for x in tpots)
        toks_per_s = total_tokens / bench_wall if bench_wall else 0.0
        return {
            "engine": engine_type,
            "rounds": rounds,
            "concurrency": concurrency,
            "total_tokens": total_tokens,
            "latency_ms": {
                "mean": statistics.mean(lat_ms) if lat_ms else 0.0,
                "p50": _percentile(lat_ms, 0.5),
                "p95": _percentile(lat_ms, 0.95),
            },
            "ttft_ms": {
                "mean": statistics.mean(ttft_ms) if ttft_ms else 0.0,
                "p50": _percentile(ttft_ms, 0.5),
                "p95": _percentile(ttft_ms, 0.95),
            },
            "tpot_ms": {
                "mean": statistics.mean(tpot_ms) if tpot_ms else 0.0,
                "p50": _percentile(tpot_ms, 0.5),
            },
            "tokens_per_second": toks_per_s,
            "tokens_per_second_per_chip": toks_per_s / num_chips,
            "num_chips": num_chips,
        }
    finally:
        engine.shutdown()


def print_table(results: List[Dict[str, Any]]) -> None:
    cols = (
        f"{'engine':<12} {'lat p50 ms':>11} {'lat p95 ms':>11} "
        f"{'ttft p50 ms':>12} {'tok/s':>9} {'tok/s/chip':>11}"
    )
    print(cols)
    print("-" * len(cols))
    for r in results:
        print(
            f"{r['engine']:<12} {r['latency_ms']['p50']:>11.1f} "
            f"{r['latency_ms']['p95']:>11.1f} "
            f"{r['ttft_ms']['p50']:>12.1f} "
            f"{r['tokens_per_second']:>9.1f} "
            f"{r['tokens_per_second_per_chip']:>11.1f}"
        )


def main() -> None:
    parser = argparse.ArgumentParser(description="vgate-tpu engine benchmark")
    parser.add_argument(
        "--engines", nargs="+", default=["dry_run"],
        choices=["dry_run", "jax_tpu", "vllm", "sglang"],
    )
    parser.add_argument("--rounds", type=int, default=3)
    parser.add_argument("--warmup-rounds", type=int, default=1)
    parser.add_argument("--max-tokens", type=int, default=64)
    parser.add_argument("--concurrency", type=int, default=1)
    parser.add_argument("--prompts", nargs="*", default=None)
    parser.add_argument(
        "--output", choices=["table", "json"], default="table"
    )
    args = parser.parse_args()

    config = get_config()
    prompts = args.prompts or config.benchmark.prompts or DEFAULT_PROMPTS
    results = [
        run_benchmark(
            engine,
            prompts,
            args.rounds,
            args.warmup_rounds,
            args.max_tokens,
            args.concurrency,
        )
        for engine in args.engines
    ]
    if args.output == "json":
        print(json.dumps(results, indent=2))
    else:
        print_table(results)


if __name__ == "__main__":
    main()
