"""Prefix-cache reuse-rate sweep: TTFT and prefill-token reduction vs
shared-prefix fraction.

Two workload shapes from the million-user serving mix the radix cache
(vgate_tpu/runtime/radix_cache.py) targets:

* ``multi_turn`` — each user's request extends their own previous
  transcript (prompt + generated answer), the chat/agent-loop shape;
  the measured turn re-sends the warm turn's GENERATED answer, hitting
  transcript pages only the radix tree indexes.
* ``rag`` — every request shares one global preamble (system prompt +
  retrieved corpus) plus a unique tail, the RAG shape; whole-page
  sharing across unrelated users, with mid-page COW at the preamble
  boundary (multi-turn divergence lands past the last indexed
  transcript page, so COW shows up here).

Each (shape, reuse in {0, 0.5, 0.9}) cell runs the same requests
through a cache-ON and a cache-OFF engine (same process, same seeded
random-init weights), reporting mean TTFT, prefilled tokens (submitted
prompt tokens minus prefix hits) and greedy output identity.  One JSON
row per cell, same JSON-lines convention as the other benches.

Run on hardware:

    python benchmarks/bench_prefix.py

or dry-sized on CPU (CI smoke / local verification):

    python benchmarks/bench_prefix.py --cpu
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from benchmarks._tpu_probe import wait_for_tpu  # noqa: E402

CPU_MODE = "--cpu" in sys.argv
if not CPU_MODE:
    wait_for_tpu()

import jax  # noqa: E402

from vgate_tpu.backends.base import SamplingParams  # noqa: E402
from vgate_tpu.config import load_config  # noqa: E402
from vgate_tpu.runtime.engine_core import EngineCore  # noqa: E402

REUSE_RATES = (0.0, 0.5, 0.9)
SHAPES = ("multi_turn", "rag")

if CPU_MODE:
    PROMPT_LEN = 192  # tokens per measured request
    N_REQUESTS = 6
    MODEL = {
        "model_id": "tiny-dense", "engine_type": "jax_tpu",
        "dtype": "float32", "max_model_len": 512,
    }
    TPU = {
        "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
        "kv_num_pages": 2048, "kv_page_size": 4,
        "max_batch_slots": 8, "prefill_buckets": [16, 32, 64],
        "use_pallas": False,
    }
else:
    PROMPT_LEN = 1008
    N_REQUESTS = 16
    MODEL = {
        "model_id": "Qwen/Qwen2.5-1.5B-Instruct",
        "engine_type": "jax_tpu", "dtype": "bfloat16",
        "max_model_len": 2048,
    }
    TPU = {
        "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
        "kv_num_pages": 0, "kv_page_size": 16,
        "max_batch_slots": 16, "prefill_buckets": [64, 1024],
        "decode_chunk": 8, "decode_pipeline": 2,
    }

GEN_TOKENS = 8
GREEDY = SamplingParams(max_tokens=GEN_TOKENS, temperature=0.0)


def make_engine(prefix_cache: bool) -> EngineCore:
    # CPU smoke uses 4-token pages, where the default cow_min_tokens=8
    # could never fire (max partial share is page_size - 1)
    pc = {"enabled": prefix_cache}
    if CPU_MODE:
        pc["cow_min_tokens"] = 2
    config = load_config(
        model=MODEL,
        tpu={**TPU, "prefix_cache": pc},
        scheduler={"max_queue_size": 256},
        logging={"level": "ERROR"},
    )
    core = EngineCore(config, devices=jax.devices()[:1])
    core.start()
    return core


_MAX_TOK = 500 if CPU_MODE else 4000  # inside each model's vocab


def _tokens(seed: str, n: int):
    """A unique pseudo-random token stream per logical role — seeded so
    runs are reproducible, and free of the periodic structure that a
    linear-congruential shortcut would leak across cells (which shows
    up as spurious prefix matches)."""
    import random

    rng = random.Random(seed)
    return [rng.randrange(3, _MAX_TOK) for _ in range(n)]


def build_requests(shape: str, reuse: float, salt: int, extra: int = 0):
    """Per measured request: (warm_prefix_tokens or None, base, tail).
    The warm prefix is submitted first (unmeasured) so the measured
    request's first ``reuse`` fraction is resident; the measured prompt
    is composed in ``run_cell`` AFTER the warm phase — multi_turn
    re-sends the warm turn's GENERATED answer between base and tail
    (the real chat shape, whose generated pages only the radix tree
    indexes), rag shares only the static preamble.  ``extra`` appends
    shakeout requests of the same shape (compile warmup)."""
    shared_len = int(PROMPT_LEN * reuse)
    if shared_len:
        # land the divergence point mid-page so the sweep also
        # exercises the copy-on-write partial-page path (page-aligned
        # splits would only ever take whole-page sharing)
        shared_len += 2
    out = []
    if shape == "rag":
        preamble = _tokens(f"rag-pre-{salt}", shared_len)
        for r in range(N_REQUESTS + extra):
            tail = _tokens(
                f"rag-tail-{salt}-{r}", PROMPT_LEN - shared_len
            )
            warm = preamble if r == 0 and shared_len else None
            out.append((warm, preamble, tail))
    else:  # multi_turn: per-user transcript, measured turn extends it
        for r in range(N_REQUESTS + extra):
            base = _tokens(f"mt-base-{salt}-{r}", shared_len)
            tail = _tokens(
                f"mt-tail-{salt}-{r}", PROMPT_LEN - shared_len
            )
            out.append((base if shared_len else None, base, tail))
    return out


def run_cell(core: EngineCore, shape: str, reuse: float, salt: int):
    requests = build_requests(shape, reuse, salt, extra=1)
    # warm phase: prior turns / the shared preamble pass through the
    # engine first.  multi_turn keeps each warm turn's generated answer
    # and re-sends it inside the measured prompt (base + answer + tail)
    # — identical on the cache-off engine because greedy decode over
    # the same seeded weights generates the same answer there.
    answers = {}
    for i, (warm, _base, _tail) in enumerate(requests):
        if warm is not None and len(warm) > 1:
            seq = core.submit_tokens(list(warm), GREEDY)
            seq.done_event.wait(timeout=600)
            if shape == "multi_turn":
                answers[i] = list(seq.generated_ids)
    prompts = [
        base + answers.get(i, []) + tail
        for i, (_warm, base, tail) in enumerate(requests)
    ]
    # shakeout: the last request (not measured, not reported) compiles
    # every program variant this cell's shape selects, so the measured
    # means compare prefill work, not first-contact XLA compiles
    seq = core.submit_tokens(list(prompts.pop()), GREEDY)
    seq.done_event.wait(timeout=600)
    hits0 = core.scheduler.total_prefix_hit_tokens
    ttfts = []
    outputs = []
    submitted = 0
    for prompt in prompts:
        seq = core.submit_tokens(list(prompt), GREEDY)
        seq.done_event.wait(timeout=600)
        assert seq.error is None, seq.error
        ttfts.append(seq.ttft)
        outputs.append(list(seq.generated_ids))
        submitted += len(prompt)
    hit = core.scheduler.total_prefix_hit_tokens - hits0
    return {
        "mean_ttft_ms": round(1000 * sum(ttfts) / len(ttfts), 2),
        "hit_tokens": hit,
        "prefilled_tokens": submitted - hit,
        "submitted_tokens": submitted,
        "outputs": outputs,
    }


def main() -> None:
    if not CPU_MODE and jax.devices()[0].platform != "tpu":
        raise SystemExit("bench_prefix needs a real TPU (or --cpu)")
    platform = jax.devices()[0].platform
    on = make_engine(True)
    off = make_engine(False)
    try:
        # compile warmup on both engines (the sweep measures prefill
        # reuse, not first-contact XLA compiles)
        for core in (on, off):
            s = core.submit_tokens(
                _tokens("global-warmup", PROMPT_LEN), GREEDY
            )
            s.done_event.wait(timeout=600)
        salt = 0
        for shape in SHAPES:
            for reuse in REUSE_RATES:
                salt += 1
                cow0 = (
                    on.radix_cache.total_cow_copies
                    if on.radix_cache is not None
                    else 0
                )
                got_on = run_cell(on, shape, reuse, salt)
                got_off = run_cell(off, shape, reuse, salt)
                identical = got_on["outputs"] == got_off["outputs"]
                row = {
                    "metric": "prefix_reuse_sweep",
                    "platform": platform,
                    "model": MODEL["model_id"],
                    "shape": shape,
                    "reuse": reuse,
                    "prompt_len": PROMPT_LEN,
                    "requests": N_REQUESTS,
                    "cache_on_mean_ttft_ms": got_on["mean_ttft_ms"],
                    "cache_off_mean_ttft_ms": got_off["mean_ttft_ms"],
                    "ttft_speedup": round(
                        got_off["mean_ttft_ms"]
                        / max(got_on["mean_ttft_ms"], 1e-9),
                        2,
                    ),
                    "hit_tokens": got_on["hit_tokens"],
                    "prefilled_tokens_on": got_on["prefilled_tokens"],
                    "prefilled_tokens_off": got_off["submitted_tokens"],
                    "prefill_reduction": round(
                        got_off["submitted_tokens"]
                        / max(1, got_on["prefilled_tokens"]),
                        2,
                    ),
                    "cow_copies": (
                        on.radix_cache.total_cow_copies - cow0
                        if on.radix_cache is not None
                        else 0
                    ),
                    "outputs_identical": identical,
                }
                print(json.dumps(row), flush=True)
    finally:
        on.stop()
        off.stop()


if __name__ == "__main__":
    main()
