"""Prefix-cache benchmark: TTFT for long-shared-prefix workloads.

The chatbot/system-prompt pattern: every request carries the same long
prefix (system prompt + few-shot examples) plus a short unique tail.  With
automatic prefix caching the engine prefills only the tail after the first
request.  Run on hardware:

    python benchmarks/bench_prefix.py

Prints one JSON line comparing mean TTFT with the cache on vs off.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from benchmarks._tpu_probe import wait_for_tpu  # noqa: E402

wait_for_tpu()

import jax  # noqa: E402

from vgate_tpu.backends.base import SamplingParams  # noqa: E402
from vgate_tpu.config import load_config  # noqa: E402
from vgate_tpu.runtime.engine_core import EngineCore  # noqa: E402

PREFIX_LEN = 1008  # shared tokens (63 full 16-token pages)
TAIL_LEN = 12  # unique per request
N_REQUESTS = 16


def run(prefix_cache: bool) -> dict:
    config = load_config(
        model={
            "model_id": "Qwen/Qwen2.5-1.5B-Instruct",
            "engine_type": "jax_tpu",
            "dtype": "bfloat16",
            "max_model_len": 2048,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 0, "kv_page_size": 16,
            "max_batch_slots": 16,
            "prefill_buckets": [64, 1024],
            "decode_chunk": 8, "decode_pipeline": 2,
            "prefix_cache": prefix_cache,
        },
        scheduler={"max_queue_size": 256},
        logging={"level": "ERROR"},
    )
    core = EngineCore(config, devices=jax.devices()[:1])
    core.start()
    try:
        core.warmup(buckets=[64, 1024])
        shared = [3 + (i * 13) % 200 for i in range(PREFIX_LEN)]
        params = SamplingParams(max_tokens=8, temperature=0.0)
        # first request warms the prefix into the cache (not measured)
        seq = core.submit_tokens(shared + [7] * TAIL_LEN, params)
        seq.done_event.wait(timeout=600)
        ttfts = []
        for i in range(N_REQUESTS):
            tail = [11 + (i * 7 + j) % 150 for j in range(TAIL_LEN)]
            seq = core.submit_tokens(shared + tail, params)
            seq.done_event.wait(timeout=600)
            ttfts.append(seq.ttft)
        hit_tokens = core.scheduler.total_prefix_hit_tokens
    finally:
        core.stop()
    return {
        "mean_ttft_ms": round(1000 * sum(ttfts) / len(ttfts), 1),
        "hit_tokens": hit_tokens,
    }


def main() -> None:
    if jax.devices()[0].platform != "tpu":
        raise SystemExit("bench_prefix needs a real TPU")
    off = run(False)
    on = run(True)
    print(json.dumps({
        "metric": "shared_prefix_ttft_ms",
        "prefix_len": PREFIX_LEN,
        "tail_len": TAIL_LEN,
        "requests": N_REQUESTS,
        "cache_off_mean_ttft_ms": off["mean_ttft_ms"],
        "cache_on_mean_ttft_ms": on["mean_ttft_ms"],
        "speedup": round(
            off["mean_ttft_ms"] / max(on["mean_ttft_ms"], 1e-9), 2
        ),
        "hit_tokens": on["hit_tokens"],
    }))


if __name__ == "__main__":
    main()
