"""Speculative-decoding benchmark: acceptance rate + tok/s at k in {0,4,8}.

Puts a number on whether `tpu.speculative_k > 0` ever pays (VERDICT r3
next-8).  Two drafter configurations per k:

* ``oracle@p`` — an injected drafter that knows the model's true greedy
  continuation (pre-computed with k=0) and corrupts each drafted token
  independently with probability ``1-p``.  This measures the MECHANISM
  (multi-token verify cost vs accepted-run payoff) at a controlled
  acceptance, independent of weights — with random-init weights the
  n-gram drafter's acceptance is near zero, which says nothing about
  the verify path's cost model.
* ``ngram`` — the real prompt-lookup drafter on a repetitive prompt
  (speculation's home turf: boilerplate/code-completion shapes).

Read ``oracle@1`` acceptance as a LOWER bound: the k=0 continuation
comes from the chunked-decode program and the verify runs a different
compiled program, so near-tied logits can flip argmax at ulp level and
reject a "true" draft (greedy exactness of the OUTPUT is still
guaranteed — the engine always appends its own argmax).  The tok/s
rows are unaffected: they measure the verify mechanism's cost at the
achieved acceptance, which is what decides whether speculative_k pays.

Prints one JSON line per row: {"k", "drafter", "toks_per_s",
"acceptance", ...}.  Single-stream (B=1) plus a small batch row — the
speculative tick is host-synchronous, so its win shrinks as batching
amortizes dispatches (engine docstring _tick_speculative).

Run on TPU; falls back to CPU shapes for CI smoke.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")


def main() -> None:
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        model_id = os.environ.get(
            "VGT_BENCH_MODEL", "Qwen/Qwen2.5-1.5B-Instruct"
        )
        dtype = "bfloat16"
        max_tokens = 128
        n_stream = int(os.environ.get("VGT_SPEC_STREAMS", 8))
        prompt_len = 120
        page = 32
        use_pallas = True
    else:
        model_id, dtype = "tiny-dense", "float32"
        max_tokens, n_stream, prompt_len, page = 8, 2, 12, 4
        use_pallas = False

    base = {"model": model_id, "platform": jax.devices()[0].platform,
            "streams": n_stream, "max_tokens": max_tokens}

    def make_core(k: int):
        cfg = load_config(
            model={
                "model_id": model_id,
                "engine_type": "jax_tpu",
                "dtype": dtype,
                "max_model_len": 512 if on_tpu else 64,
            },
            tpu={
                "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
                "kv_num_pages": 0 if on_tpu else 256,
                "kv_page_size": page,
                "max_batch_slots": max(8, n_stream) if on_tpu else 4,
                "prefill_buckets": [128] if on_tpu else [16],
                "speculative_k": k,
                "use_pallas": use_pallas,
            },
            scheduler={"max_queue_size": 1024},
            logging={"level": "ERROR"},
        )
        core = EngineCore(cfg, devices=jax.devices()[:1])
        core.start()
        return core

    # deterministic prompts with recurring 8-grams (boilerplate shape)
    # so the prompt-lookup drafter has something to find
    phrase = [17, 42, 99, 7, 23, 56, 11, 88]
    prompts = []
    for i in range(n_stream):
        body = []
        while len(body) < prompt_len:
            body.extend([p + (i % 3) for p in phrase])
        prompts.append(body[:prompt_len])
    params = SamplingParams(max_tokens=max_tokens, temperature=0.0)

    def run(core, drafter=None):
        if drafter is not None:
            core.drafter = drafter
        t0 = time.perf_counter()
        seqs = [core.submit_tokens(p, params) for p in prompts]
        for s in seqs:
            s.done_event.wait(timeout=1800)
        wall = time.perf_counter() - t0
        out = sum(s.num_output_tokens for s in seqs)
        drafted = core.total_spec_drafted
        accepted = core.total_spec_accepted
        return {
            "toks_per_s": round(out / wall, 2),
            "acceptance": round(accepted / drafted, 3) if drafted else None,
            "output_tokens": out,
            "wall_s": round(wall, 2),
        }, [list(s.generated_ids) for s in seqs]

    # ---- baseline k=0 (also yields the oracle continuations)
    core = make_core(0)
    try:
        core.warmup()
        res0, oracle_out = run(core)
    finally:
        core.stop()
    print(json.dumps({**base, "k": 0, "drafter": "none", **res0}),
          flush=True)

    ks = [int(x) for x in os.environ.get("VGT_SPEC_KS", "4,8").split(",")]
    for k in ks:
        # ---- oracle drafter at controlled accuracy (two points bound
        # the win curve; each engine build pays a full warmup ladder)
        for p_correct in (1.0, 0.5):
            import random as _random

            rng = _random.Random(k * 1000 + int(p_correct * 100))
            core = make_core(k)
            try:
                core.warmup()
                # map each submitted sequence (by submission order) to
                # its true continuation; the drafter looks it up by the
                # sequence object's prompt row
                order = {}

                def drafter(seq, kk, _order=order, _rng=rng,
                            _p=p_correct):
                    row = _order.get(id(seq))
                    if row is None:
                        # identify by prompt (deterministic prompts)
                        for i, pr in enumerate(prompts):
                            if list(seq.prompt_ids) == pr:
                                row = i
                                break
                        _order[id(seq)] = row
                    truth = oracle_out[row]
                    # the next true token is truth[n_generated]
                    n_gen = seq.num_output_tokens
                    draft = []
                    for j in range(kk):
                        if n_gen + j >= len(truth):
                            break
                        t = truth[n_gen + j]
                        if _rng.random() > _p:
                            t = (t + 7) % 1000 + 3  # corrupted token
                        draft.append(int(t))
                    return draft

                res, _ = run(core, drafter)
            finally:
                core.stop()
            print(json.dumps({
                **base, "k": k, "drafter": f"oracle@{p_correct:g}", **res,
            }), flush=True)

        # ---- real prompt-lookup n-gram drafter
        core = make_core(k)
        try:
            core.warmup()
            res, _ = run(core)
        finally:
            core.stop()
        print(json.dumps({**base, "k": k, "drafter": "ngram", **res}),
              flush=True)


if __name__ == "__main__":
    main()
