"""Shared device-peak table + HBM roofline helpers for the benches.

One definition site (bench.py's headline roofline fraction and
bench_decode_ablate's per-row achieved-GB/s columns must agree on the
peaks, or a future part addition would silently skew one of them).
Peaks are per chip; unknown device kinds return None so callers omit
the roofline fields rather than mislabel them.
"""

from __future__ import annotations

from typing import Optional, Tuple

# device_kind -> (bf16 FLOP/s, HBM GB/s) per chip
DEVICE_PEAKS = {
    "TPU v5 lite": (197e12, 819.0),
    "TPU v5e": (197e12, 819.0),
    "TPU v6 lite": (918e12, 1640.0),
    "TPU v6e": (918e12, 1640.0),
    "TPU v5p": (459e12, 2765.0),
    "TPU v5": (459e12, 2765.0),
    "TPU v4": (275e12, 1228.0),
}


def peaks_for(device_kind: str) -> Optional[Tuple[float, float]]:
    return DEVICE_PEAKS.get(device_kind)


def kv_bytes_per_token(
    num_layers: int,
    kv_heads: int,
    head_dim: int,
    dtype_bytes: int = 2,
    scale_bytes: int = 0,
) -> int:
    """HBM bytes one resident token's K+V occupies across all layers —
    what every later decode step must READ back per context token.
    ``scale_bytes`` is the int8-KV per-token-per-head overhead
    (runtime/kv_cache._page_bytes uses the identical formula per page)."""
    return 2 * num_layers * kv_heads * (head_dim * dtype_bytes + scale_bytes)


def decode_step_bytes(
    weight_bytes: int,
    batch: int,
    ctx_tokens: int,
    kv_token_bytes: int,
) -> int:
    """Approximate HBM traffic of ONE decode step: stream the weights
    once plus read every slot's live KV context (writes are one token
    per slot — noise).  An optimistic lower bound (no re-reads, perfect
    caching), which is exactly what a roofline denominator should be."""
    return weight_bytes + batch * ctx_tokens * kv_token_bytes


def roofline_row(
    ms_per_step: float,
    step_bytes: int,
    device_kind: str,
) -> dict:
    """The per-row roofline fields bench_decode_ablate attaches:
    achieved HBM GB/s over the step's modeled traffic, and the percent
    of the device's HBM peak that represents.  Empty for unknown
    devices or non-timed rows."""
    if ms_per_step <= 0:
        return {}
    peaks = peaks_for(device_kind)
    achieved_gbps = step_bytes / (ms_per_step / 1e3) / 1e9
    row = {"achieved_hbm_gbps": round(achieved_gbps, 1)}
    if peaks is not None:
        row["pct_of_hbm_roofline"] = round(
            100.0 * achieved_gbps / peaks[1], 1
        )
    return row
