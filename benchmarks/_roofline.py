"""Thin re-export shim — the roofline definition site moved to
``vgate_tpu/observability/roofline.py`` so the engine's LIVE MFU /
HBM-roofline gauges (observability/perf.py) and the offline benches
(bench.py, bench_decode_ablate.py) share one peak table and one traffic
model.  Import from here or from the real module; they are the same
objects, so the two can never disagree on a device's peak."""

from __future__ import annotations

from vgate_tpu.observability.roofline import (  # noqa: F401
    DEVICE_PEAKS,
    EngineRoofline,
    decode_step_bytes,
    kv_bytes_per_token,
    peaks_for,
    roofline_row,
    stream_weight_bytes,
)

__all__ = [
    "DEVICE_PEAKS",
    "EngineRoofline",
    "decode_step_bytes",
    "kv_bytes_per_token",
    "peaks_for",
    "roofline_row",
    "stream_weight_bytes",
]
