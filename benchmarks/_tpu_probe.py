"""Shared wedge-aware TPU probe for benchmark scripts.

A killed mid-op process wedges the axon TPU grant for minutes
(`UNAVAILABLE` at backend init) and an in-process failed probe poisons
jax's backend cache, so availability is checked in a SUBPROCESS with
backoff before the benchmark imports jax (same recipe as bench.py's
_probe_accelerator)."""

from __future__ import annotations

import json
import subprocess
import sys
import time

_PROBE = (
    "import jax, json; d = jax.devices()[0]; "
    "print(json.dumps({'platform': d.platform}))"
)


def wait_for_tpu(attempts: int = 5, timeout_s: float = 240.0) -> None:
    """Block until a TPU backend initializes, or SystemExit."""
    last = ""
    for i in range(attempts):
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE],
                capture_output=True, text=True, timeout=timeout_s,
            )
            if out.returncode == 0 and out.stdout.strip():
                info = json.loads(out.stdout.strip().splitlines()[-1])
                if info.get("platform") == "tpu":
                    return
                raise SystemExit(
                    f"no TPU visible (platform={info.get('platform')})"
                )
            last = (out.stderr or out.stdout).strip()[-300:]
        except subprocess.TimeoutExpired:
            last = f"probe timed out after {timeout_s}s"
        if i < attempts - 1:
            time.sleep(120.0)
    raise SystemExit(f"TPU unavailable after {attempts} probes: {last}")
