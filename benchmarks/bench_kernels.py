"""Microbenchmark: Pallas kernels vs their jnp twins on real TPU.

Quantifies the memory-path claim in ops/pallas/paged_attention.py (the
kernel DMAs only live pages; the twin gathers the full page window) and
ops/pallas/flash_prefill.py (blockwise online softmax vs the jnp
blockwise twin).  Run on hardware:

    python benchmarks/bench_kernels.py

Prints one JSON line per (kernel, shape) with median step times and the
speedup.  CPU-safe fallback: refuses to run (the kernels need a TPU).
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from benchmarks._tpu_probe import wait_for_tpu  # noqa: E402

wait_for_tpu()

import jax
import jax.numpy as jnp
import numpy as np


LOOP = 8  # op invocations fused into one program


def _looped(op):
    """Scan the op LOOP times inside one jit so per-dispatch tunnel latency
    (~90 ms on the remote device) amortizes away; the q input depends on
    the previous output, which stops XLA hoisting the op out of the loop."""

    @jax.jit
    def run(q, *rest):
        def body(carry, _):
            out = op(q + 0 * carry.astype(q.dtype), *rest)
            return out.astype(jnp.float32), None

        out, _ = jax.lax.scan(
            body, jnp.zeros(q.shape, jnp.float32), None, length=LOOP
        )
        return out

    return run


def _sync(out):
    """Force completion via a host transfer of (a small leaf of) the
    output — on the axon tunnel ``block_until_ready`` returned instantly
    for multi-GB programs (r4 session), so only a device->host copy of
    real output bytes is a trustworthy sync."""
    leaves = [x for x in jax.tree.leaves(out) if hasattr(x, "shape")]
    small = min(leaves, key=lambda x: x.size)
    np.asarray(small)


def _median_time(fn, *args, iters: int = 10, warmup: int = 2) -> float:
    """Median per-op time of the looped program."""
    for _ in range(warmup):
        _sync(fn(*args))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args))
        times.append(time.perf_counter() - t0)
    return float(np.median(times)) / LOOP


def bench_paged_decode(B=128, H=12, KV=2, hd=128, ps=16, ctx=512):
    from vgate_tpu.ops.attention import paged_decode_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
    )

    pages_per_seq = ctx // ps
    P = 1 + B * pages_per_seq
    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (B, H, hd), jnp.bfloat16)
    k_pages = jax.random.normal(key, (KV, P, ps, hd), jnp.bfloat16)
    v_pages = jax.random.normal(key, (KV, P, ps, hd), jnp.bfloat16)
    page_tables = jnp.asarray(
        np.arange(B * pages_per_seq, dtype=np.int32).reshape(B, -1) + 1
    )
    # realistic mixed occupancy: sequence lengths spread over [ps, ctx]
    seq_lens = jnp.asarray(
        (np.arange(B) % pages_per_seq + 1) * ps, np.int32
    )

    np.testing.assert_allclose(
        np.asarray(
            jax.jit(paged_decode_attention)(
                q, k_pages, v_pages, page_tables, seq_lens
            ),
            np.float32,
        ),
        np.asarray(
            jax.jit(paged_decode_attention_pallas)(
                q, k_pages, v_pages, page_tables, seq_lens
            ),
            np.float32,
        ),
        rtol=2e-2, atol=2e-2,
    )
    twin = _looped(paged_decode_attention)
    kern = _looped(paged_decode_attention_pallas)
    t_twin = _median_time(twin, q, k_pages, v_pages, page_tables, seq_lens)
    t_kern = _median_time(kern, q, k_pages, v_pages, page_tables, seq_lens)
    return {
        "kernel": "paged_decode_attention",
        "shape": f"B{B} H{H} KV{KV} hd{hd} ps{ps} ctx{ctx}",
        "jnp_us": round(t_twin * 1e6, 1),
        "pallas_us": round(t_kern * 1e6, 1),
        "speedup": round(t_twin / t_kern, 2),
    }


def bench_flash_prefill(B=8, S=1024, H=12, KV=2, hd=128):
    from vgate_tpu.ops.attention import flash_prefill_attention
    from vgate_tpu.ops.pallas.flash_prefill import (
        flash_prefill_attention_pallas,
    )

    key = jax.random.PRNGKey(1)
    q = jax.random.normal(key, (B, S, H, hd), jnp.bfloat16)
    k = jax.random.normal(key, (B, S, KV, hd), jnp.bfloat16)
    v = jax.random.normal(key, (B, S, KV, hd), jnp.bfloat16)
    seq_lens = jnp.asarray(
        np.linspace(S // 4, S, B).astype(np.int32)
    )

    np.testing.assert_allclose(
        np.asarray(
            jax.jit(flash_prefill_attention)(q, k, v, seq_lens), np.float32
        ),
        np.asarray(
            jax.jit(flash_prefill_attention_pallas)(q, k, v, seq_lens),
            np.float32,
        ),
        rtol=3e-2, atol=3e-2,
    )
    twin = _looped(flash_prefill_attention)
    kern = _looped(flash_prefill_attention_pallas)
    t_twin = _median_time(twin, q, k, v, seq_lens)
    t_kern = _median_time(kern, q, k, v, seq_lens)
    return {
        "kernel": "flash_prefill_attention",
        "shape": f"B{B} S{S} H{H} KV{KV} hd{hd}",
        "jnp_us": round(t_twin * 1e6, 1),
        "pallas_us": round(t_kern * 1e6, 1),
        "speedup": round(t_twin / t_kern, 2),
    }


def bench_decode_window(B=128, H=8, KV=4, hd=256, ps=16, ctx=4096,
                        window=1024):
    """Sliding-window decode (Gemma-2 local layers): the kernel skips DMA
    below the window, so its time should track O(window) while the jnp
    twin still gathers O(ctx)."""
    from vgate_tpu.ops.attention import paged_decode_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
    )

    pages_per_seq = ctx // ps
    P = 1 + B * pages_per_seq
    key = jax.random.PRNGKey(2)
    q = jax.random.normal(key, (B, H, hd), jnp.bfloat16)
    k_pages = jax.random.normal(key, (KV, P, ps, hd), jnp.bfloat16)
    v_pages = jax.random.normal(key, (KV, P, ps, hd), jnp.bfloat16)
    page_tables = jnp.asarray(
        np.arange(B * pages_per_seq, dtype=np.int32).reshape(B, -1) + 1
    )
    seq_lens = jnp.full((B,), ctx, jnp.int32)  # worst case: full context
    w = jnp.asarray(window, jnp.int32)

    twin = _looped(
        functools.partial(paged_decode_attention, window=w)
    )
    kern = _looped(
        functools.partial(paged_decode_attention_pallas, window=w)
    )
    t_twin = _median_time(twin, q, k_pages, v_pages, page_tables, seq_lens)
    t_kern = _median_time(kern, q, k_pages, v_pages, page_tables, seq_lens)
    return {
        "kernel": "paged_decode_attention[window]",
        "shape": f"B{B} H{H} KV{KV} hd{hd} ctx{ctx} win{window}",
        "jnp_us": round(t_twin * 1e6, 1),
        "pallas_us": round(t_kern * 1e6, 1),
        "speedup": round(t_twin / t_kern, 2),
    }


def bench_multitok_verify(B=64, S=4, H=12, KV=2, hd=128, ps=16, ctx=512):
    """Speculative-verify attention: S candidate rows vs the jnp suffix
    gather path."""
    from vgate_tpu.ops.attention import paged_suffix_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_multitok_attention_pallas,
    )

    pages_per_seq = ctx // ps
    P = 1 + B * pages_per_seq
    key = jax.random.PRNGKey(3)
    q = jax.random.normal(key, (B, S, H, hd), jnp.bfloat16)
    k_pages = jax.random.normal(key, (KV, P, ps, hd), jnp.bfloat16)
    v_pages = jax.random.normal(key, (KV, P, ps, hd), jnp.bfloat16)
    page_tables = jnp.asarray(
        np.arange(B * pages_per_seq, dtype=np.int32).reshape(B, -1) + 1
    )
    positions0 = jnp.asarray(
        (np.arange(B) % (pages_per_seq - 1) + 1) * ps, np.int32
    )
    input_lens = jnp.full((B,), S, jnp.int32)

    twin = _looped(
        lambda q_, kp, vp, pt, p0: paged_suffix_attention(
            q_, kp, vp, pt, p0, p0 + S
        )
    )
    kern = _looped(
        lambda q_, kp, vp, pt, p0: paged_multitok_attention_pallas(
            q_, kp, vp, pt, p0, input_lens
        )
    )
    t_twin = _median_time(twin, q, k_pages, v_pages, page_tables, positions0)
    t_kern = _median_time(kern, q, k_pages, v_pages, page_tables, positions0)
    return {
        "kernel": "spec_verify_attention",
        "shape": f"B{B} S{S} H{H} KV{KV} hd{hd} ctx{ctx}",
        "jnp_us": round(t_twin * 1e6, 1),
        "pallas_us": round(t_kern * 1e6, 1),
        "speedup": round(t_twin / t_kern, 2),
    }


def main() -> None:
    device = jax.devices()[0]
    if device.platform != "tpu":
        raise SystemExit(
            "bench_kernels needs a real TPU (Pallas kernels don't run on "
            f"{device.platform}); CPU CI covers parity in interpret mode"
        )
    print(json.dumps(bench_paged_decode()))
    print(json.dumps(bench_paged_decode(ctx=2048)))
    print(json.dumps(bench_flash_prefill()))
    print(json.dumps(bench_flash_prefill(S=2048)))
    print(json.dumps(bench_decode_window()))
    print(json.dumps(bench_multitok_verify()))


if __name__ == "__main__":
    main()
