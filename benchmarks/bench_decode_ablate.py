"""Decode-step ablation: where does the time go? (run on real TPU)

Times each component of the serving decode step with amortized in-jit
loops (one dispatch per measurement, N iterations inside), so the ~70 ms
tunnel round-trip does not pollute per-step numbers the way the r2
per-dispatch kernel bench did (benchmarks/RESULTS_r2.md:54-60).

Components:
  chunk-pallas   full _decode_chunk (the serving program), Pallas attention
  chunk-jnp      full _decode_chunk, jnp gather-twin attention
  fwd-pallas     decode_forward only (argmax feedback, no sampler)
  fwd-jnp        same, jnp twin
  sample         sample_tokens alone on random logits (top-k path)
  argmax         plain argmax on the same logits (greedy floor)
  lmhead         final-norm + lm_head einsum alone
  attn-pallas    28x paged_decode_attention_pallas per iteration
  attn-jnp       28x jnp twin per iteration

Prints one JSON line per component: {"component", "ms_per_step", ...}.
Timed decode rows also carry the roofline columns (benchmarks/_roofline.py):
``kv_bytes_per_token`` (the resident-KV read cost this row's KV config
implies), ``achieved_hbm_gbps`` over the step's modeled traffic
(weights + live-context KV reads) and ``pct_of_hbm_roofline`` against
the device's HBM peak — so KV-quant and future roofline PRs carry a
roofline number automatically instead of a bare tok/s.

``VGT_ABLATE_KV=int8`` runs the KV-heavy rows (chunk/fwd/attn) on an
int8 QuantPages pool (kv_cache.dtype: int8 — ops/kv_quant.py): halved
KV read bytes per step is the capacity/roofline lever this ablation is
meant to price on hardware.  Results land in benchmarks/RESULTS_r3.md.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # the axon TPU plugin overrides the env var; the config knob is the
    # only reliable pin (same discipline as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np


def _sync(out):
    """Force completion via a HOST TRANSFER of (a leaf of) the output.

    On the remote-tunnel axon platform ``block_until_ready`` returned
    instantly for multi-GB programs in the r4 session (ms_per_step
    0.002-0.004 for a full 128-slot decode chunk — physically
    impossible), so timing trusts only an explicit device->host copy of
    real output bytes, the same sync the serving engine does.
    """
    leaves = [x for x in jax.tree.leaves(out) if hasattr(x, "shape")]
    small = min(leaves, key=lambda x: x.size)
    np.asarray(small)


def timed(fn, *args, iters_inside: int, reps: int = 3) -> float:
    """ms per inner iteration: best of ``reps`` timed dispatches."""
    _sync(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best / iters_inside * 1e3


def main() -> None:
    from vgate_tpu.models.decoder import decode_forward, init_params
    from vgate_tpu.models.specs import spec_for_model_id
    from vgate_tpu.ops.sampling import sample_tokens
    from vgate_tpu.runtime.engine_core import _decode_chunk

    from benchmarks._roofline import (
        decode_step_bytes,
        kv_bytes_per_token,
        roofline_row,
    )
    from vgate_tpu.ops.kv_quant import SCALE_BYTES, QuantPages

    model_id = os.environ.get("VGT_BENCH_MODEL", "Qwen/Qwen2.5-1.5B-Instruct")
    only = set(sys.argv[1:])  # optional component filter
    spec = spec_for_model_id(model_id)
    dtype = jnp.bfloat16
    B = int(os.environ.get("VGT_ABLATE_SLOTS", 128))
    ctx = int(os.environ.get("VGT_ABLATE_CTX", 512))
    ps = 16
    pages_per_seq = ctx // ps
    P = B * pages_per_seq + 1
    STEPS = 32
    # KV storage format for the KV-heavy rows: bf16 (default) or int8
    # (kv_cache.dtype: int8 — halved KV read bytes, the roofline lever)
    kv_mode = os.environ.get("VGT_ABLATE_KV", "bf16")
    kv_quant = kv_mode == "int8"
    kv_tok_bytes = kv_bytes_per_token(
        spec.num_layers, spec.num_kv_heads, spec.head_dim,
        dtype_bytes=1 if kv_quant else jnp.dtype(dtype).itemsize,
        scale_bytes=SCALE_BYTES if kv_quant else 0,
    )

    platform = jax.devices()[0].platform
    device_kind = getattr(jax.devices()[0], "device_kind", "unknown")
    base = {
        "model": spec.name, "B": B, "ctx": ctx, "platform": platform,
        "kv_dtype": "int8" if kv_quant else "bf16",
        "kv_bytes_per_token": kv_tok_bytes,
    }
    print(json.dumps({**base, "event": "start"}), flush=True)

    params = init_params(spec, jax.random.PRNGKey(0), dtype)
    weight_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(params)
    )
    # live context the decode rows actually read per slot (positions
    # start at ctx/2 and advance STEPS; midpoint of the sweep)
    ctx_live = ctx // 2 + STEPS // 2
    kv_shape = (spec.num_layers, spec.num_kv_heads, P, ps, spec.head_dim)

    def fresh_kv():
        if kv_quant:
            return QuantPages(
                jnp.zeros(kv_shape, jnp.int8),
                jnp.ones(kv_shape[:-1], jnp.bfloat16),
            )
        return jnp.zeros(kv_shape, dtype)

    k_pages = fresh_kv()
    v_pages = fresh_kv()
    page_tables = jnp.asarray(
        (np.arange(B * pages_per_seq, dtype=np.int32) % (P - 1) + 1)
        .reshape(B, pages_per_seq)
    )
    tokens = jnp.zeros((B,), jnp.int32)
    positions = jnp.full((B,), ctx // 2, jnp.int32)
    active = jnp.ones((B,), bool)
    temps = jnp.zeros((B,), jnp.float32)
    top_ps = jnp.ones((B,), jnp.float32)
    top_ks = jnp.zeros((B,), jnp.int32)
    seeds = jnp.full((B,), -1, jnp.int32)
    steps0 = jnp.zeros((B,), jnp.int32)
    key = jax.random.PRNGKey(0)
    counter = jnp.asarray(0, jnp.uint32)

    def step_bytes_for(component):
        """Modeled HBM traffic per step by component family: decode
        rows stream the weights once + read every slot's live KV
        window; attention-only rows read just the KV (their L layer
        calls compose to the same all-layer total).  Host-RTT and
        sampler rows have no meaningful HBM story — no columns."""
        if component.startswith(("chunk-", "fwd-")):
            return decode_step_bytes(weight_bytes, B, ctx_live, kv_tok_bytes)
        if component.startswith("attn-"):
            return B * ctx_live * kv_tok_bytes
        return None

    def report(component, ms):
        row = {**base, "component": component, "ms_per_step": round(ms, 3)}
        sb = step_bytes_for(component)
        if sb:
            row.update(roofline_row(ms, sb, device_kind))
        print(json.dumps(row), flush=True)

    # bare dispatch + host-readback round-trip (NOT divided by STEPS):
    # subtract this from `* 32` totals when comparing absolute floors
    if not only or "rtt" in only:
        @jax.jit
        def rtt_fn(t):
            return t + 1

        report("rtt", timed(rtt_fn, tokens, iters_inside=1))

    # --- full serving chunk (pallas / jnp x xs-ys / carry KV / blocked) ---
    import dataclasses

    spec_blocked = dataclasses.replace(spec, decode_block_slots=8)
    for name, use_pallas, kv_carry, chunk_spec in (
        ("chunk-pallas", True, False, spec),
        ("chunk-pallas-blocked", True, False, spec_blocked),
        ("chunk-pallas-carry", True, True, spec),
        ("chunk-jnp", False, False, spec),
        ("chunk-jnp-carry", False, True, spec),
    ):
        if only and name not in only:
            continue
        if use_pallas and platform != "tpu":
            continue

        def run(k_pages, v_pages, up=use_pallas, kc=kv_carry,
                sp_=chunk_spec):
            return _decode_chunk(
                params, sp_, tokens, positions, k_pages, v_pages,
                page_tables, active, temps, top_ps, top_ks, key, counter,
                num_steps=STEPS, use_pallas=up, max_position=ctx - 1,
                seeds=seeds, steps=steps0, kv_carry=kc,
            )[0]

        # donation consumes the caches: rebuild fresh copies per rep
        kp = fresh_kv()
        vp = fresh_kv()
        _sync(run(kp, vp))  # compile + warm
        best = float("inf")
        for _ in range(3):
            kp = fresh_kv()
            vp = fresh_kv()
            jax.block_until_ready((kp, vp))
            t0 = time.perf_counter()
            _sync(run(kp, vp))
            best = min(best, time.perf_counter() - t0)
        report(name, best / STEPS * 1e3)

    # --- model forward only (argmax feedback, no sampler) -----------------
    for name, use_pallas in (("fwd-pallas", True), ("fwd-jnp", False)):
        if only and name not in only:
            continue
        if use_pallas and platform != "tpu":
            continue

        # params passed explicitly: closing over them captures multi-GB
        # constants into the lowered program (3.09 GB observed r4), which
        # the tunnel then re-uploads per executable
        @functools.partial(jax.jit, donate_argnums=(1, 2),
                           static_argnums=(3,))
        def fwd_loop(params, k_pages, v_pages, up):
            def body(carry, _):
                toks, pos, kp, vp = carry
                logits, kp, vp = decode_forward(
                    params, spec, toks, pos, kp, vp, page_tables,
                    active=active, use_pallas=up,
                )
                toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                pos = jnp.minimum(pos + 1, ctx - 1)
                return (toks, pos, kp, vp), toks

            (_, _, kp, vp), ys = jax.lax.scan(
                body, (tokens, positions, k_pages, v_pages), None,
                length=STEPS,
            )
            return ys

        kp = fresh_kv()
        vp = fresh_kv()
        _sync(fwd_loop(params, kp, vp, use_pallas))
        best = float("inf")
        for _ in range(3):
            kp = fresh_kv()
            vp = fresh_kv()
            jax.block_until_ready((kp, vp))
            t0 = time.perf_counter()
            _sync(fwd_loop(params, kp, vp, use_pallas))
            best = min(best, time.perf_counter() - t0)
        report(name, best / STEPS * 1e3)

    # --- prefill: xs/ys vs carry KV threading -----------------------------
    PB, PS_LEN = 32, 128  # the bench serving prefill shape
    if B >= PB and pages_per_seq >= PS_LEN // ps:
        ptokens = jnp.asarray(
            (np.arange(PB * PS_LEN, dtype=np.int32) % 199 + 3).reshape(
                PB, PS_LEN
            )
        )
        plens = jnp.full((PB,), PS_LEN - 5, jnp.int32)
        ppt = page_tables[:PB, : PS_LEN // ps]
        for name, kc in (("prefill-xs", False), ("prefill-carry", True)):
            if only and name not in only:
                continue
            from vgate_tpu.models.decoder import prefill_forward

            @functools.partial(jax.jit, donate_argnums=(1, 2),
                               static_argnums=(3,))
            def prefill_loop(params, kp, vp, kc):
                def body(c, _):
                    kp, vp = c
                    logits, kp, vp = prefill_forward(
                        params, spec, ptokens, plens, kp, vp, ppt,
                        kv_carry=kc,
                    )
                    return (kp, vp), logits[0, 0]

                (kp, vp), ys = jax.lax.scan(
                    body, (kp, vp), None, length=4
                )
                return ys

            kp = fresh_kv()
            vp = fresh_kv()
            _sync(prefill_loop(params, kp, vp, kc))
            best = float("inf")
            for _ in range(3):
                kp = fresh_kv()
                vp = fresh_kv()
                jax.block_until_ready((kp, vp))
                t0 = time.perf_counter()
                _sync(prefill_loop(params, kp, vp, kc))
                best = min(best, time.perf_counter() - t0)
            # ms per prefill DISPATCH (B=32 x 128-token bucket)
            report(name, best / 4 * 1e3)

    # --- sampling / lm_head in isolation ----------------------------------
    V = spec.vocab_size
    logits = jax.random.normal(jax.random.PRNGKey(1), (B, V), jnp.float32)

    if not only or "sample" in only:
        @jax.jit
        def sample_loop(logits):
            def body(c, i):
                k = jax.random.fold_in(key, i)
                t = sample_tokens(logits + c[:, None].astype(jnp.float32),
                                  temps, top_ps, top_ks, k,
                                  seeds=seeds, steps=steps0)
                return t, ()
            out, _ = jax.lax.scan(body, tokens, jnp.arange(STEPS))
            return out

        report("sample", timed(sample_loop, logits, iters_inside=STEPS))

    if not only or "argmax" in only:
        @jax.jit
        def argmax_loop(logits):
            def body(c, _):
                t = jnp.argmax(
                    logits + c[:, None].astype(jnp.float32), axis=-1
                ).astype(jnp.int32)
                return t, ()
            out, _ = jax.lax.scan(body, tokens, None, length=STEPS)
            return out

        report("argmax", timed(argmax_loop, logits, iters_inside=STEPS))

    if not only or "lmhead" in only:
        from vgate_tpu.models.decoder import _logits as logits_fn

        x = jax.random.normal(
            jax.random.PRNGKey(2), (B, spec.hidden_size), dtype
        )

        @jax.jit
        def lmhead_loop(params, x):
            def body(c, _):
                lg = logits_fn(params, spec, x + c)
                return lg[:, 0].astype(dtype)[:, None] * 0 + c, ()
            out, _ = jax.lax.scan(
                body, jnp.zeros((B, 1), dtype), None, length=STEPS
            )
            return out

        report("lmhead", timed(lmhead_loop, params, x, iters_inside=STEPS))

    # --- attention only (28 layer calls per iteration) --------------------
    from vgate_tpu.ops.kv_quant import quantize

    q = jax.random.normal(
        jax.random.PRNGKey(3), (B, spec.num_heads, spec.head_dim), dtype
    )

    def attn_pool(seed):
        vals = jax.random.normal(
            jax.random.PRNGKey(seed),
            (spec.num_kv_heads, P, ps, spec.head_dim), dtype,
        ) * 0.1
        if kv_quant:
            return QuantPages(*quantize(vals))
        return vals

    kp1 = attn_pool(4)
    # independent V buffer: aliasing K/V would let XLA CSE the twin's two
    # page gathers and halve its apparent memory traffic
    vp1 = attn_pool(5)
    seq_lens = positions + 1
    L = spec.num_layers

    for name in ("attn-pallas", "attn-pallas-blocked", "attn-jnp"):
        if only and name not in only:
            continue
        if name == "attn-pallas":
            if platform != "tpu":
                continue
            from vgate_tpu.ops.pallas.paged_attention import (
                paged_decode_attention_pallas as attn,
            )
        elif name == "attn-pallas-blocked":
            if platform != "tpu":
                continue
            import functools as _ft

            from vgate_tpu.ops.pallas.paged_attention import (
                paged_decode_attention_pallas_blocked,
            )

            attn = _ft.partial(
                paged_decode_attention_pallas_blocked, block_slots=8
            )
        else:
            from vgate_tpu.ops.attention import (
                paged_decode_attention as attn,
            )

        @jax.jit
        def attn_loop(q, kp1, vp1):
            # outer scan amortizes the dispatch round-trip over STEPS
            # decode-steps; each step runs all L layer calls
            def step(c, _):
                def body(h, _):
                    o = attn(h, kp1, vp1, page_tables, seq_lens)
                    return o.astype(h.dtype), ()
                h, _ = jax.lax.scan(body, c, None, length=L)
                return h, ()
            out, _ = jax.lax.scan(step, q, None, length=STEPS)
            return out

        report(name, timed(attn_loop, q, kp1, vp1, iters_inside=STEPS))

    print(json.dumps({**base, "event": "done"}), flush=True)


if __name__ == "__main__":
    main()
