"""Standalone compile+execute probe for the blocked decode kernel.

Run DETACHED in its own process with a wall-clock budget enforced by
the CALLER (scripts/r5_session.sh): if Mosaic hangs (the r4 quant-
kernel failure mode), the caller skips the blocked A/B grid and leaves
this process alone — killing a device process wedges the grant (memory:
tpu-grant-discipline).

Compiles the Qwen2.5-1.5B serving decode shape (B=128, H=12, KV=2,
hd=128, page 32) at each block_slots the session grid would use, and
executes one call with a host readback.  Prints one JSON line:
``{"probe": "blocked_kernel", "ok": true, "seconds": ..., "per_bs":
{...}}``.
"""

import json
import time

import numpy as np

import jax
import jax.numpy as jnp


def main() -> int:
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas_blocked,
    )

    d = jax.devices()[0]
    if d.platform != "tpu":
        print(json.dumps({"probe": "blocked_kernel", "ok": False,
                          "error": f"not a tpu: {d.platform}"}))
        return 1

    B, H, KV, hd, ps = 128, 12, 2, 128, 32
    pages_per_seq, P = 16, 1 + 128 * 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.bfloat16)
    k_pages = jnp.asarray(
        rng.normal(size=(KV, P, ps, hd)) * 0.1, jnp.bfloat16
    )
    v_pages = jnp.asarray(
        rng.normal(size=(KV, P, ps, hd)) * 0.1, jnp.bfloat16
    )
    page_tables = jnp.asarray(
        np.arange(B * pages_per_seq, dtype=np.int32).reshape(B, -1) + 1
    )
    seq_lens = jnp.full((B,), 500, jnp.int32)

    t0 = time.time()
    per_bs = {}
    for bs in (4, 8, 16):
        t = time.time()
        out = paged_decode_attention_pallas_blocked(
            q, k_pages, v_pages, page_tables, seq_lens, block_slots=bs
        )
        np.asarray(out)  # host readback = the only reliable sync here
        per_bs[str(bs)] = round(time.time() - t, 1)
    print(json.dumps({
        "probe": "blocked_kernel", "ok": True,
        "seconds": round(time.time() - t0, 1), "per_bs": per_bs,
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
