"""Engine flight recorder (ISSUE 3 tentpole 2) + the /debug surface
(tentpole 3) + /stats and /v1/profile error paths (satellite).

Fast tier: recorder unit behavior, the dry-run gateway's /debug
responses, auth gating, drain accounting, and the profile/stats error
paths.  Slow tier: a decode_step fault through the real supervised
engine leaves a crash snapshot whose final tick is the faulting one.
"""

import asyncio
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu import faults
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import ObservabilityConfig, load_config
from vgate_tpu.observability.flight import FlightRecorder
from vgate_tpu.server.app import _drain_counted, create_app


class _FakeSeq:
    _ids = iter(range(10_000))

    def __init__(self, request_id=None, timeout_s=None):
        self.seq_id = next(self._ids)
        self.request_id = request_id
        self.trace = None
        self.arrival_t = time.perf_counter()
        self.first_token_t = None
        self.finish_t = None
        self.preempt_count = 0
        self.prompt_ids = [1, 2, 3]
        self.generated_ids = []
        self.error = None
        self.finish_reason = "stop"
        self.params = SamplingParams(timeout_s=timeout_s)

    @property
    def num_prompt_tokens(self):
        return len(self.prompt_ids)

    @property
    def num_generated(self):
        return len(self.generated_ids)


# ------------------------------------------------------------ unit tier


def test_tick_ring_is_bounded_and_ordered():
    rec = FlightRecorder(ObservabilityConfig(flight_ticks=4))
    for i in range(10):
        rec.record_tick("decode", chunk=i)
    ticks = rec.ticks()
    assert len(ticks) == 4
    assert [t["chunk"] for t in ticks] == [6, 7, 8, 9]
    assert [t["n"] for t in ticks] == sorted(t["n"] for t in ticks)
    assert rec.ticks(2)[0]["chunk"] == 8


def test_disabled_recorder_records_nothing():
    rec = FlightRecorder(ObservabilityConfig(enabled=False))
    rec.record_tick("decode")
    rec.on_admit(_FakeSeq(), bucket=8)
    assert rec.ticks() == []
    assert rec.live_requests() == []
    assert rec.get_stats()["enabled"] is False


def test_request_record_lifecycle_and_phases():
    rec = FlightRecorder(ObservabilityConfig())
    seq = _FakeSeq(request_id="abc123", timeout_s=9.0)
    rec.on_admit(seq, bucket=16, cached_len=8)
    assert rec.live_requests()[0]["status"] == "running"
    # phases while live: queue known, prefill running
    phases = rec.phases_of(seq)
    assert "queue_s" in phases and "prefill_s" in phases
    seq.first_token_t = time.perf_counter()
    rec.on_first_token(seq)
    seq.generated_ids = [4, 5]
    phases = rec.phases_of(seq)
    assert "decode_s" in phases
    seq.finish_t = time.perf_counter()
    rec.on_close(seq)
    assert rec.live_requests() == []
    (record,) = rec.requests()
    assert record["request_id"] == "abc123"
    assert record["bucket"] == 16
    assert record["cached_tokens"] == 8
    assert record["deadline_s"] == 9.0
    assert record["status"] == "finished"
    assert record["generated_tokens"] == 2
    for key in ("queue_s", "prefill_s", "decode_s", "total_s"):
        assert record[key] >= 0.0
    assert rec.find_request("abc123") == record
    assert rec.find_request(str(seq.seq_id)) == record
    assert rec.find_request("nope") is None


def test_preempted_request_keeps_nonnegative_cumulative_phases():
    """A preemption moves the sequence back to the queue while
    first_token_t survives — phase accounting must stay cumulative and
    non-negative across re-admission (code-review regression)."""
    rec = FlightRecorder(ObservabilityConfig())
    seq = _FakeSeq(request_id="pre1")
    rec.on_admit(seq, bucket=16)
    time.sleep(0.01)
    seq.first_token_t = time.perf_counter()
    rec.on_first_token(seq)
    time.sleep(0.01)
    # preempted mid-decode: back to the queue, then re-admitted
    seq.preempt_count = 1
    rec.on_preempt(seq)
    time.sleep(0.01)
    rec.on_admit(seq, bucket=32)
    time.sleep(0.01)
    rec.on_first_token(seq)  # re-prefill's token (first_token_t stale)
    seq.generated_ids = [1, 2, 3]
    seq.finish_t = time.perf_counter()
    rec.on_close(seq)
    (record,) = rec.requests()
    assert record["preemptions"] == 1
    assert record["bucket"] == 32  # the re-admission's bucket
    for key in ("queue_s", "prefill_s", "decode_s"):
        assert record[key] >= 0.0, (key, record)
    # queue includes the post-preempt wait; prefill both prompt passes
    assert record["queue_s"] >= 0.01
    assert record["prefill_s"] >= 0.02
    assert record["total_s"] >= (
        record["queue_s"] + record["prefill_s"] + record["decode_s"]
    ) - 1e-3


def test_failed_sequence_records_error():
    rec = FlightRecorder(ObservabilityConfig())
    seq = _FakeSeq()
    rec.on_admit(seq, bucket=8)
    seq.error = RuntimeError("boom")
    rec.on_close(seq)
    (record,) = rec.requests()
    assert record["status"] == "failed"
    assert "RuntimeError: boom" in record["error"]


def test_never_admitted_sequence_still_gets_a_queue_only_record():
    """A request shed from the waiting queue (deadline, drain, crash)
    settles without ever being admitted — it must still leave a record;
    queued-forever is the case operators most need to see."""
    rec = FlightRecorder(ObservabilityConfig())
    seq = _FakeSeq(request_id="queued-only", timeout_s=0.05)
    time.sleep(0.01)
    seq.error = RuntimeError("deadline passed in queue")
    seq.finish_t = time.perf_counter()
    rec.on_close(seq)
    (record,) = rec.requests()
    assert record["request_id"] == "queued-only"
    assert record["status"] == "failed"
    assert record["bucket"] is None  # never admitted
    assert record["queue_s"] >= 0.01
    assert record["prefill_s"] == 0.0 and record["decode_s"] == 0.0
    assert rec.find_request("queued-only") == record


def test_prompt_text_redacted_by_default():
    rec = FlightRecorder(ObservabilityConfig())
    seq = _FakeSeq()
    rec.on_admit(seq, bucket=8, preview="secret prompt text")
    assert "prompt_preview" not in rec.live_requests()[0]
    # explicit opt-out keeps a clamped preview
    rec2 = FlightRecorder(
        ObservabilityConfig(redact_prompts=False, prompt_preview_chars=6)
    )
    seq2 = _FakeSeq()
    rec2.on_admit(seq2, bucket=8, preview="secret prompt text")
    assert rec2.live_requests()[0]["prompt_preview"] == "secret"


def test_crash_snapshot_ends_with_latest_tick():
    rec = FlightRecorder(ObservabilityConfig(crash_dump_ticks=8))
    for i in range(20):
        rec.record_tick("decode", chunk=i)
    rec.record_tick("crash", error="InjectedFault: boom")
    seq = _FakeSeq(request_id="inflight")
    rec.on_admit(seq, bucket=8)
    snap = rec.crash_snapshot(RuntimeError("boom"))
    assert snap["error"] == "RuntimeError: boom"
    assert len(snap["ticks"]) == 8
    assert snap["ticks"][-1]["kind"] == "crash"
    assert snap["in_flight"][0]["request_id"] == "inflight"


def test_debug_paths_never_hold_a_drain_open():
    assert not _drain_counted("/debug/flight")
    assert not _drain_counted("/debug/requests")
    assert not _drain_counted("/debug/requests/abc")
    assert not _drain_counted("/stats")
    assert _drain_counted("/v1/chat/completions")


# ------------------------------------------------ gateway tier (dry run)


async def _client(**overrides):
    overrides.setdefault("model", {"engine_type": "dry_run"})
    overrides.setdefault(
        "batch", {"max_batch_size": 4, "max_wait_time_ms": 5.0}
    )
    overrides.setdefault("logging", {"level": "ERROR"})
    config = load_config(**overrides)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    return client


async def test_debug_endpoints_report_disabled_without_engine_core():
    client = await _client()
    try:
        body = await (await client.get("/debug/flight")).json()
        assert body == {
            "enabled": False, "ticks": [],
            "reason": "engine has no flight recorder",
        }
        body = await (await client.get("/debug/requests")).json()
        assert body["enabled"] is False
        resp = await client.get("/debug/requests/whatever")
        assert resp.status == 404
    finally:
        await client.close()


async def test_debug_endpoints_are_auth_gated():
    client = await _client(
        security={"enabled": True, "api_keys": ["k1"]}
    )
    try:
        assert (await client.get("/debug/flight")).status == 401
        assert (
            await client.get(
                "/debug/flight",
                headers={"Authorization": "Bearer k1"},
            )
        ).status == 200
        # probes stay exempt
        assert (await client.get("/health")).status == 200
    finally:
        await client.close()


async def test_profile_requires_jax_engine_as_400():
    client = await _client()
    try:
        resp = await client.post("/v1/profile", json={"duration_ms": 10})
        assert resp.status == 400
        body = await resp.json()
        assert body["error"]["type"] == "invalid_request_error"
        assert "jax_tpu" in body["error"]["message"]
    finally:
        await client.close()


async def test_profile_concurrent_capture_409():
    client = await _client()
    try:

        class _FakeCore:
            def capture_profile(self, duration_s, out_dir=None):
                time.sleep(0.3)
                return {"trace_dir": "/tmp/x", "duration_s": duration_s,
                        "files": 0}

        client.app["engine"].backend.core = _FakeCore()
        first, second = await asyncio.gather(
            client.post("/v1/profile", json={"duration_ms": 300}),
            client.post("/v1/profile", json={"duration_ms": 300}),
        )
        statuses = sorted((first.status, second.status))
        assert statuses == [200, 409]
    finally:
        await client.close()


async def test_profile_rejects_bad_bodies():
    client = await _client()
    try:

        class _FakeCore:
            def capture_profile(self, duration_s, out_dir=None):
                return {}

        client.app["engine"].backend.core = _FakeCore()
        resp = await client.post("/v1/profile", json=[1, 2, 3])
        assert resp.status == 422
        resp = await client.post(
            "/v1/profile", json={"duration_ms": "soon"}
        )
        assert resp.status == 422
        resp = await client.post(
            "/v1/profile", json={"out_dir": "/etc/definitely-not-tmp"}
        )
        assert resp.status == 422
    finally:
        await client.close()


async def test_stats_survives_backend_stats_failure():
    client = await _client()
    try:

        def explode():
            raise RuntimeError("mid-rebuild")

        client.app["engine"].backend.get_stats = explode
        resp = await client.get("/stats")
        assert resp.status == 200
        body = await resp.json()
        assert "RuntimeError" in body["engine"]["error"]
        assert body["batcher"]["running"] is True
    finally:
        await client.close()


# --------------------------------------------- real engine (slow tier)


@pytest.mark.slow
def test_decode_fault_crash_log_includes_flight_snapshot():
    """ISSUE 3 acceptance: with a fault armed at decode_step, the
    supervisor's crash handling captures a flight-recorder snapshot
    whose final tick is the faulting one, and /stats surfaces it under
    engine.last_crash."""
    from vgate_tpu.runtime.supervisor import EngineSupervisor

    config = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 4, "prefill_buckets": [8, 16, 32],
            "use_pallas": False,
        },
        recovery={
            "enabled": True, "max_restarts": 5,
            "restart_window_s": 120.0, "backoff_base_s": 0.02,
            "backoff_cap_s": 0.2, "degraded_probation_s": 0.25,
        },
        logging={"level": "ERROR"},
    )
    sup = EngineSupervisor(config)
    sup.start()
    try:
        faults.arm("decode_step", mode="raise", kind="transient", times=1)
        with pytest.raises(Exception):
            sup.generate(
                ["crash me"],
                [SamplingParams(max_tokens=4, temperature=0.0)],
            )
        deadline = time.monotonic() + 60
        while sup.last_crash is None and time.monotonic() < deadline:
            time.sleep(0.02)
        snap = sup.last_crash
        assert snap is not None, "supervisor never captured a snapshot"
        assert snap["classification"] == "transient"
        assert "decode_step" in snap["error"]
        # the ring's final tick is the faulting dispatch
        assert snap["ticks"], "snapshot carries no ticks"
        assert snap["ticks"][-1]["kind"] == "crash"
        assert "decode_step" in snap["ticks"][-1]["error"]
        # the prefill that preceded the faulting decode is in the ring
        assert any(t["kind"] == "prefill" for t in snap["ticks"])
        # the crashed request was resident at the time of death
        assert snap["in_flight"], "no in-flight records captured"
        # /stats surfaces the same snapshot
        assert sup.get_stats()["last_crash"] is snap
    finally:
        faults.reset()
        sup.stop()
