"""Disaggregated prefill→decode handoff unit tests (fast tier).

Covers the pure handoff plane from ISSUE 17: the explicit state machine
(every legal transition, idempotent duplicate-ACCEPT, illegal jumps),
the payload codec (roundtrip + every typed malformation), the chunk
assembler (out-of-order / duplicate / overlap / gap semantics), the
worker-side wire verbs against a process-free WorkerServer shell
(stale-epoch frame rejection, staged-fetch invalidation, duplicate
commit idempotence), the ``kv_transfer`` fault point, and a seeded fuzz
of the transfer framing — truncated/garbled/reordered chunks must
produce typed errors or byte-identical reassembly, never a hang.
"""

import base64
import random
import threading

import numpy as np
import pytest

from vgate_tpu import faults
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.errors import HandoffStaleError, HandoffTransferError
from vgate_tpu.ops.kv_quant import QuantPages
from vgate_tpu.runtime import handoff
from vgate_tpu.runtime import rpc
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.runtime.worker import WorkerServer, _Staged


# ------------------------------------------------------- state machine


class TestStateMachine:
    def test_happy_path(self):
        path = [
            handoff.PREFILLING, handoff.STAGED, handoff.TRANSFERRING,
            handoff.ACCEPTED, handoff.DECODING,
        ]
        for cur, nxt in zip(path, path[1:]):
            assert handoff.advance(cur, nxt) is True

    def test_every_legal_transition(self):
        for cur, nexts in handoff.TRANSITIONS.items():
            for nxt in nexts:
                assert handoff.advance(cur, nxt) is True

    def test_idempotent_reentry_is_noop(self):
        # a duplicated ACCEPT (or any re-delivered control frame) must
        # not double-apply: advance() reports "already there"
        for state in handoff.STATES:
            assert handoff.advance(state, state) is False

    @pytest.mark.parametrize("cur,nxt", [
        (handoff.PREFILLING, handoff.TRANSFERRING),
        (handoff.PREFILLING, handoff.ACCEPTED),
        (handoff.PREFILLING, handoff.DECODING),
        (handoff.STAGED, handoff.ACCEPTED),
        (handoff.STAGED, handoff.PREFILLING),
        (handoff.TRANSFERRING, handoff.DECODING),
        (handoff.TRANSFERRING, handoff.STAGED),
        (handoff.ACCEPTED, handoff.FALLBACK),
        (handoff.ACCEPTED, handoff.TRANSFERRING),
    ])
    def test_illegal_jumps_raise(self, cur, nxt):
        with pytest.raises(handoff.HandoffStateError):
            handoff.advance(cur, nxt)

    def test_terminal_states_have_no_exits(self):
        assert handoff.TERMINAL == {
            handoff.DECODING, handoff.FALLBACK,
            handoff.CANCELLED, handoff.FAILED,
        }
        for term in handoff.TERMINAL:
            for other in handoff.STATES:
                if other == term:
                    continue
                with pytest.raises(handoff.HandoffStateError):
                    handoff.advance(term, other)

    def test_unknown_states_raise(self):
        with pytest.raises(handoff.HandoffStateError):
            handoff.advance("BOGUS", handoff.STAGED)
        with pytest.raises(handoff.HandoffStateError):
            handoff.advance(handoff.STAGED, "BOGUS")


# ------------------------------------------------------- payload codec


def _payload():
    """A representative KV pytree: nested containers, several dtypes,
    a QuantPages NamedTuple leaf, scalars, and None."""
    rng = np.random.default_rng(7)
    return {
        "layers": [
            (
                rng.standard_normal((2, 4, 8)).astype(np.float32),
                rng.integers(-128, 127, (2, 4, 8), dtype=np.int8),
            ),
            QuantPages(
                data=rng.integers(-128, 127, (4, 8), dtype=np.int8),
                scale=rng.standard_normal((4, 1)).astype(np.float32),
            ),
        ],
        "meta": {"pages": 3, "ratio": 0.5, "tag": "kv", "ok": True},
        "hole": None,
    }


def _tree_equal(a, b):
    if isinstance(a, np.ndarray):
        return (
            isinstance(b, np.ndarray)
            and a.dtype == b.dtype
            and a.shape == b.shape
            and np.array_equal(a, b)
        )
    if isinstance(a, dict):
        return (
            isinstance(b, dict)
            and sorted(a) == sorted(b)
            and all(_tree_equal(a[k], b[k]) for k in a)
        )
    if isinstance(a, (list, tuple)):
        return (
            type(b) is type(a) or (
                isinstance(a, tuple) and isinstance(b, tuple)
            )
        ) and len(a) == len(b) and all(
            _tree_equal(x, y) for x, y in zip(a, b)
        )
    return type(a) is type(b) and a == b


class TestPayloadCodec:
    def test_roundtrip(self):
        payload = _payload()
        buf = handoff.pack_payload(payload)
        out = handoff.unpack_payload(buf)
        assert _tree_equal(payload, out)
        # the NamedTuple leaf reconstructs as the real class
        assert isinstance(out["layers"][1], QuantPages)

    def test_pack_is_deterministic(self):
        payload = _payload()
        assert handoff.pack_payload(payload) == handoff.pack_payload(payload)

    def test_non_string_dict_key_refused(self):
        with pytest.raises(HandoffTransferError, match="dict key"):
            handoff.pack_payload({1: np.zeros(2)})

    def test_unpackable_leaf_refused(self):
        with pytest.raises(HandoffTransferError, match="unpackable"):
            handoff.pack_payload({"x": object()})

    def test_bad_magic(self):
        buf = bytearray(handoff.pack_payload(_payload()))
        buf[:4] = b"NOPE"
        with pytest.raises(HandoffTransferError, match="magic"):
            handoff.unpack_payload(bytes(buf))

    def test_truncated_header(self):
        with pytest.raises(HandoffTransferError, match="truncated"):
            handoff.unpack_payload(b"VGK")

    def test_manifest_length_out_of_bounds(self):
        buf = bytearray(handoff.pack_payload(_payload()))
        buf[4:8] = (2 ** 31).to_bytes(4, "big")
        with pytest.raises(HandoffTransferError, match="manifest length"):
            handoff.unpack_payload(bytes(buf))

    def test_garbled_manifest_json(self):
        buf = bytearray(handoff.pack_payload({"x": 1}))
        buf[8] ^= 0xFF  # first manifest byte
        with pytest.raises(HandoffTransferError):
            handoff.unpack_payload(bytes(buf))

    def test_truncated_blob(self):
        buf = handoff.pack_payload(np.arange(64, dtype=np.int32))
        with pytest.raises(HandoffTransferError, match="out of bounds"):
            handoff.unpack_payload(buf[:-8])

    def test_foreign_namedtuple_refused(self):
        import collections

        Evil = collections.namedtuple("Evil", ["a"])
        buf = handoff.pack_payload(Evil(a=np.zeros(2)))
        # packs fine (it IS a tuple) but its import path is outside
        # vgate_tpu, so reconstruction is refused
        with pytest.raises(HandoffTransferError, match="vgate_tpu"):
            handoff.unpack_payload(buf)

    def test_digest_stable_and_sensitive(self):
        buf = handoff.pack_payload(_payload())
        d0 = handoff.payload_digest(buf)
        assert handoff.payload_digest(buf) == d0
        garbled = bytearray(buf)
        garbled[len(garbled) // 2] ^= 0x55
        assert handoff.payload_digest(bytes(garbled)) != d0


# ------------------------------------------------------ chunk assembler


class TestChunkAssembler:
    def test_ctor_bounds(self):
        with pytest.raises(HandoffTransferError):
            handoff.ChunkAssembler(0, 100)
        with pytest.raises(HandoffTransferError):
            handoff.ChunkAssembler(-4, 100)
        with pytest.raises(HandoffTransferError):
            handoff.ChunkAssembler(101, 100)
        assert handoff.ChunkAssembler(100, 100).total == 100

    def test_in_order_reassembly(self):
        blob = bytes(range(256)) * 4
        asm = handoff.ChunkAssembler(len(blob), 1 << 20)
        for off, n in handoff.chunk_offsets(len(blob), 100):
            asm.put(off, blob[off:off + n])
        assert asm.complete() == blob

    def test_out_of_order_reassembly(self):
        blob = bytes(range(256)) * 4
        asm = handoff.ChunkAssembler(len(blob), 1 << 20)
        offsets = handoff.chunk_offsets(len(blob), 96)
        for off, n in reversed(offsets):
            asm.put(off, blob[off:off + n])
        assert asm.complete() == blob

    def test_duplicate_chunk_idempotent(self):
        blob = b"abcdefgh" * 8
        asm = handoff.ChunkAssembler(len(blob), 1 << 20)
        got = asm.put(0, blob[:32])
        assert asm.put(0, blob[:32]) == got  # byte-identical redelivery
        asm.put(32, blob[32:])
        assert asm.complete() == blob

    def test_conflicting_overlap_raises(self):
        asm = handoff.ChunkAssembler(64, 1 << 20)
        asm.put(0, b"\x01" * 32)
        with pytest.raises(HandoffTransferError, match="conflicting"):
            asm.put(16, b"\x02" * 32)

    def test_out_of_bounds_chunk_raises(self):
        asm = handoff.ChunkAssembler(64, 1 << 20)
        with pytest.raises(HandoffTransferError, match="outside"):
            asm.put(60, b"\x00" * 8)
        with pytest.raises(HandoffTransferError, match="outside"):
            asm.put(-4, b"\x00" * 8)

    def test_empty_chunk_raises(self):
        asm = handoff.ChunkAssembler(64, 1 << 20)
        with pytest.raises(HandoffTransferError, match="empty"):
            asm.put(0, b"")

    def test_gaps_named_on_complete(self):
        asm = handoff.ChunkAssembler(100, 1 << 20)
        asm.put(0, b"\x00" * 10)
        asm.put(50, b"\x00" * 10)
        with pytest.raises(HandoffTransferError) as ei:
            asm.complete()
        assert "(10, 50)" in str(ei.value)
        assert "(60, 100)" in str(ei.value)

    def test_received_property(self):
        asm = handoff.ChunkAssembler(100, 1 << 20)
        assert asm.received == 0
        asm.put(0, b"\x00" * 40)
        assert asm.received == 40
        asm.put(20, b"\x00" * 40)  # overlapping extension, identical bytes
        assert asm.received == 60


class TestChunkOffsets:
    def test_partition_covers_total(self):
        for total, chunk in [(1, 1), (10, 3), (100, 100), (257, 64)]:
            offs = handoff.chunk_offsets(total, chunk)
            assert offs[0][0] == 0
            assert sum(n for _, n in offs) == total
            for (o1, n1), (o2, _) in zip(offs, offs[1:]):
                assert o1 + n1 == o2

    def test_zero_total_is_empty(self):
        assert handoff.chunk_offsets(0, 64) == []

    def test_bad_chunk_bytes(self):
        with pytest.raises(ValueError):
            handoff.chunk_offsets(100, 0)


# ------------------------------------------------- worker wire verbs


def _worker_shell(epoch=3):
    """A WorkerServer with no engine and no socket — just the wire-verb
    state, so the handoff verbs can be exercised in-process."""
    ws = WorkerServer.__new__(WorkerServer)
    ws.epoch = epoch
    ws.index = 0
    ws.max_frame_bytes = 1 << 20
    ws._seq_lock = threading.Lock()
    ws._seqs = {}
    ws._staged = {}
    ws._xfers = {}
    ws._xfer_committed = set()
    ws._xfer_committing = set()
    ws._staging_cap = 1 << 20
    ws._fenced_rejects = 0
    return ws


def _stage(ws, sid=7, payload=None):
    seq = Sequence(
        prompt_ids=[1, 2, 3, 4], params=SamplingParams(max_tokens=8)
    )
    seq._handoff_hold = True
    st = _Staged(
        sid=sid, seq=seq, payload=payload or _payload(),
        num_pages=3, nbytes=1234, epoch=seq.preempt_count,
    )
    ws._staged[sid] = st
    return seq, st


class TestWorkerHandoffVerbs:
    def test_fetch_serves_staged_blob_chunked(self):
        ws = _worker_shell()
        payload = _payload()
        _stage(ws, sid=7, payload=payload)
        want = handoff.pack_payload(payload)

        first = ws._verb_handoff_fetch({"sid": 7, "off": 0, "n": 100})
        assert first["total"] == len(want)
        assert first["pages"] == 3
        assert first["digest"] == handoff.payload_digest(want)

        asm = handoff.ChunkAssembler(first["total"], 1 << 24)
        off = 0
        while off < first["total"]:
            rep = ws._verb_handoff_fetch({"sid": 7, "off": off, "n": 999})
            data = base64.b64decode(rep["data"], validate=True)
            off = asm.put(off, data)
        assert asm.complete() == want

    def test_fetch_unknown_sid_is_stale(self):
        ws = _worker_shell()
        with pytest.raises(HandoffStaleError):
            ws._verb_handoff_fetch({"sid": 99, "off": 0})

    def test_fetch_after_fold_is_stale_and_pops_staging(self):
        # a supervisor replay (or any re-prefill) bumps preempt_count;
        # the staged bytes describe a dead incarnation of the KV and
        # must never leave the process
        ws = _worker_shell()
        seq, _ = _stage(ws, sid=7)
        seq.preempt_count += 1
        with pytest.raises(HandoffStaleError, match="invalidated"):
            ws._verb_handoff_fetch({"sid": 7, "off": 0})
        assert 7 not in ws._staged

    def test_fetch_after_hold_release_is_stale(self):
        ws = _worker_shell()
        seq, _ = _stage(ws, sid=7)
        seq._handoff_hold = False
        with pytest.raises(HandoffStaleError):
            ws._verb_handoff_fetch({"sid": 7, "off": 0})

    def test_fetch_on_running_seq_is_stale(self):
        ws = _worker_shell()
        seq, _ = _stage(ws, sid=7)
        seq.status = SeqStatus.RUNNING
        with pytest.raises(HandoffStaleError):
            ws._verb_handoff_fetch({"sid": 7, "off": 0})

    def test_fetch_offset_out_of_bounds(self):
        ws = _worker_shell()
        _stage(ws, sid=7)
        with pytest.raises(HandoffTransferError, match="out of bounds"):
            ws._verb_handoff_fetch({"sid": 7, "off": 10 ** 9})

    def test_put_reassembles(self):
        ws = _worker_shell()
        blob = b"kvkvkvkv" * 16
        for off, n in handoff.chunk_offsets(len(blob), 32):
            chunk = base64.b64encode(blob[off:off + n]).decode()
            rep = ws._verb_handoff_put({
                "xfer": "h7.1", "off": off, "total": len(blob),
                "data": chunk,
            })
        assert rep["got"] == len(blob)
        assert ws._xfers["h7.1"].complete() == blob

    def test_put_undecodable_b64_is_typed(self):
        ws = _worker_shell()
        with pytest.raises(HandoffTransferError, match="undecodable"):
            ws._verb_handoff_put({
                "xfer": "h7.1", "off": 0, "total": 8, "data": "!!!not-b64",
            })

    def test_put_total_mismatch_is_typed(self):
        ws = _worker_shell()
        chunk = base64.b64encode(b"abcd").decode()
        ws._verb_handoff_put(
            {"xfer": "h7.1", "off": 0, "total": 64, "data": chunk}
        )
        with pytest.raises(HandoffTransferError, match="mismatch"):
            ws._verb_handoff_put(
                {"xfer": "h7.1", "off": 4, "total": 65, "data": chunk}
            )

    def test_put_after_commit_is_dup_ack(self):
        ws = _worker_shell()
        ws._xfer_committed.add("h7.1")
        rep = ws._verb_handoff_put({
            "xfer": "h7.1", "off": 0, "total": 8,
            "data": base64.b64encode(b"x" * 8).decode(),
        })
        assert rep["dup"] is True

    def test_commit_retry_after_lost_reply_is_idempotent(self):
        # the duplicate-ACCEPT case: gateway retried a commit whose
        # reply was lost — the worker must ack, not double-admit
        ws = _worker_shell()
        ws._xfer_committed.add("h7.1")
        rep = ws._verb_handoff_commit({"xfer": "h7.1", "sid": 7})
        assert rep == {"accepted": True, "dup": True}

    def test_commit_with_live_seq_is_idempotent(self):
        ws = _worker_shell()
        ws._seqs[7] = object()  # sequence already admitted
        rep = ws._verb_handoff_commit({"xfer": "h7.2", "sid": 7})
        assert rep == {"accepted": True, "dup": True}

    def test_concurrent_duplicate_commit_refused(self):
        ws = _worker_shell()
        ws._xfer_committing.add("h7.1")
        with pytest.raises(HandoffTransferError, match="in progress"):
            ws._verb_handoff_commit({"xfer": "h7.1", "sid": 7})

    def test_commit_unknown_transfer_is_typed(self):
        ws = _worker_shell()
        with pytest.raises(HandoffTransferError, match="unknown transfer"):
            ws._verb_handoff_commit({"xfer": "h9.9", "sid": 9})

    def test_commit_incomplete_transfer_names_gaps(self):
        ws = _worker_shell()
        ws._verb_handoff_put({
            "xfer": "h7.1", "off": 0, "total": 64,
            "data": base64.b64encode(b"x" * 16).decode(),
        })
        with pytest.raises(HandoffTransferError, match="missing byte"):
            ws._verb_handoff_commit({"xfer": "h7.1", "sid": 7})

    def test_commit_digest_mismatch_drops_assembler(self):
        ws = _worker_shell()
        blob = handoff.pack_payload(_payload())
        ws._verb_handoff_put({
            "xfer": "h7.1", "off": 0, "total": len(blob),
            "data": base64.b64encode(blob).decode(),
        })
        with pytest.raises(HandoffTransferError, match="digest mismatch"):
            ws._verb_handoff_commit({
                "xfer": "h7.1", "sid": 7,
                "digest": handoff.payload_digest(blob) ^ 0xDEAD,
            })
        # the retry must rebuild from scratch — we can't tell which
        # chunk was garbled
        assert "h7.1" not in ws._xfers

    def test_abort_drops_partial_transfer(self):
        ws = _worker_shell()
        ws._verb_handoff_put({
            "xfer": "h7.1", "off": 0, "total": 64,
            "data": base64.b64encode(b"x" * 16).decode(),
        })
        assert ws._verb_handoff_abort({"xfer": "h7.1"}) == {"dropped": True}
        assert ws._verb_handoff_abort({"xfer": "h7.1"}) == {"dropped": False}

    def test_stale_epoch_frame_fenced_before_verb(self):
        # a frame stamped with a previous incarnation's fencing epoch
        # must be rejected typed at dispatch — the verb never runs
        ws = _worker_shell(epoch=5)
        errors = []
        ws._reply_err = lambda cid, exc: errors.append((cid, exc))
        ws._reply = lambda cid, data: pytest.fail("verb ran on stale frame")
        ws._dispatch({
            "op": "handoff_put", "id": 1, "e": 4,
            "xfer": "h7.1", "off": 0, "total": 8,
            "data": base64.b64encode(b"x" * 8).decode(),
        })
        assert ws._fenced_rejects == 1
        assert len(errors) == 1
        assert "stale fencing epoch 4" in str(errors[0][1])
        assert ws._xfers == {}  # the put never happened

    def test_missing_epoch_frame_rejected(self):
        ws = _worker_shell(epoch=5)
        with pytest.raises(rpc.FrameError, match="missing fencing epoch"):
            rpc.check_epoch({"op": "handoff_put"}, 5)


# ------------------------------------------------------- fault point


class TestKvTransferFaultPoint:
    def test_all_wire_modes_armable(self):
        for mode in ("drop", "garble", "duplicate"):
            faults.reset()
            faults.arm("kv_transfer", mode=mode, times=1)
            assert faults.is_active()
            assert faults.wire_action("kv_transfer") == mode
            # budget exhausted — subsequent traffic is clean
            assert faults.wire_action("kv_transfer") is None

    def test_duplicate_mode_rejected_elsewhere(self):
        with pytest.raises(ValueError):
            faults.arm("rpc_send", mode="duplicate")

    def test_unknown_point_rejected(self):
        with pytest.raises(ValueError):
            faults.arm("kv_teleport", mode="drop")


# ------------------------------------------------------- framing fuzz


class TestFramingFuzz:
    def test_reordered_and_duplicated_chunks_reassemble(self):
        rng = random.Random(0)
        payload = _payload()
        blob = handoff.pack_payload(payload)
        digest = handoff.payload_digest(blob)
        for _ in range(20):
            chunk = rng.randrange(64, 4096)
            offsets = handoff.chunk_offsets(len(blob), chunk)
            rng.shuffle(offsets)
            # duplicate a random prefix of the schedule (re-delivery)
            offsets += offsets[:rng.randrange(0, len(offsets))]
            asm = handoff.ChunkAssembler(len(blob), 1 << 24)
            for off, n in offsets:
                asm.put(off, blob[off:off + n])
            out = asm.complete()
            assert out == blob
            assert handoff.payload_digest(out) == digest
            assert _tree_equal(handoff.unpack_payload(out), payload)

    def test_dropped_chunks_are_typed_gaps(self):
        rng = random.Random(1)
        blob = handoff.pack_payload(_payload())
        for _ in range(10):
            offsets = handoff.chunk_offsets(
                len(blob), rng.randrange(128, 2048)
            )
            dropped = rng.randrange(len(offsets))
            asm = handoff.ChunkAssembler(len(blob), 1 << 24)
            for i, (off, n) in enumerate(offsets):
                if i != dropped:
                    asm.put(off, blob[off:off + n])
            with pytest.raises(HandoffTransferError, match="missing"):
                asm.complete()

    def test_garbled_chunks_never_escape_detection(self):
        # a garbled chunk either trips the assembler (conflicting
        # redelivery) or survives to a digest mismatch — both typed
        rng = random.Random(2)
        blob = handoff.pack_payload(_payload())
        digest = handoff.payload_digest(blob)
        for _ in range(10):
            offsets = handoff.chunk_offsets(
                len(blob), rng.randrange(128, 2048)
            )
            victim = rng.randrange(len(offsets))
            asm = handoff.ChunkAssembler(len(blob), 1 << 24)
            for i, (off, n) in enumerate(offsets):
                data = bytearray(blob[off:off + n])
                if i == victim:
                    data[rng.randrange(len(data))] ^= 0x55
                asm.put(off, bytes(data))
            out = asm.complete()
            assert handoff.payload_digest(out) != digest

    def test_byte_flip_fuzz_unpack_never_hangs_or_leaks(self):
        # single-byte corruptions anywhere in the wire buffer must
        # yield either a successful (different) unpack or a typed
        # HandoffTransferError — never any other exception type
        rng = random.Random(3)
        blob = handoff.pack_payload(_payload())
        for _ in range(300):
            garbled = bytearray(blob)
            pos = rng.randrange(len(garbled))
            garbled[pos] ^= rng.randrange(1, 256)
            try:
                handoff.unpack_payload(bytes(garbled))
            except HandoffTransferError:
                pass

    def test_truncation_fuzz_is_typed(self):
        rng = random.Random(4)
        blob = handoff.pack_payload(_payload())
        for _ in range(100):
            cut = rng.randrange(len(blob))
            try:
                handoff.unpack_payload(blob[:cut])
            except HandoffTransferError:
                pass
