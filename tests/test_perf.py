"""Decode-loop perf observatory (ISSUE 13): per-tick phase attribution,
the compile ledger, and the live roofline/MFU gauges.

Fast tier: the attribution math on a fake clock (phases sum to the tick
wall, idle ticks excluded, window MFU/roofline match roofline.py
hand-computed on a pinned geometry), compile-ledger bookkeeping, the
shared roofline definition site + its benchmarks shim, dp merge
aggregation, the /debug/perf gateway surface (disabled / auth-gated /
drain-uncounted), and the loadlab perf-delta schema.  Slow tier: a real
tiny-dense engine whose measured phases sum to tick wall within
tolerance and whose ledger counts each variant's first compile exactly
once.
"""

import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu.config import ObservabilityConfig, load_config
from vgate_tpu.observability import perf as perf_mod
from vgate_tpu.observability.perf import PHASES, PerfRecorder
from vgate_tpu.observability.roofline import (
    DEVICE_PEAKS,
    EngineRoofline,
    decode_step_bytes,
    kv_bytes_per_token,
    peaks_for,
    roofline_row,
)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def recorder(clock=None, roofline=None, **cfg):
    return PerfRecorder(
        ObservabilityConfig(**cfg),
        roofline=roofline,
        clock=clock or FakeClock(),
    )


PINNED = EngineRoofline(
    device_kind="TPU v4",
    num_chips=1,
    num_params=1_000_000_000,
    weight_stream_bytes=2_000_000_000,
    kv_token_bytes=kv_bytes_per_token(24, 8, 128, dtype_bytes=2),
)


# ------------------------------------------------- roofline definition


def test_peak_table_covers_tpu_v4_v5_v6():
    """ISSUE 13 satellite: the promoted peak table keeps the known
    per-chip numbers for every supported generation."""
    assert peaks_for("TPU v4") == (275e12, 1228.0)
    assert peaks_for("TPU v5e") == (197e12, 819.0)
    assert peaks_for("TPU v5 lite") == peaks_for("TPU v5e")
    assert peaks_for("TPU v5p") == (459e12, 2765.0)
    assert peaks_for("TPU v6e") == (918e12, 1640.0)
    assert peaks_for("TPU v6 lite") == peaks_for("TPU v6e")
    assert peaks_for("TPU v5") == (459e12, 2765.0)
    assert peaks_for("GPU H100") is None
    assert peaks_for("cpu") is None


def test_benchmarks_shim_reexports_the_same_objects():
    """benchmarks/_roofline.py is a re-export shim: the benches and the
    live gauges literally share the peak table, so they can never
    disagree on a device's peak."""
    from benchmarks import _roofline as shim

    assert shim.DEVICE_PEAKS is DEVICE_PEAKS
    assert shim.peaks_for is peaks_for
    assert shim.roofline_row is roofline_row
    assert shim.kv_bytes_per_token is kv_bytes_per_token


def test_roofline_row_and_step_bytes_unchanged_semantics():
    """The shim move must not change the bench-facing math."""
    kb = kv_bytes_per_token(2, 4, 8, dtype_bytes=2, scale_bytes=0)
    assert kb == 2 * 2 * 4 * 8 * 2
    assert decode_step_bytes(100, 2, 10, kb) == 100 + 2 * 10 * kb
    # 1.228e9 bytes in 1 ms = 1228 GB/s = exactly the v4 HBM peak
    row = roofline_row(1.0, 1_228_000_000, "TPU v4")
    assert row["achieved_hbm_gbps"] == pytest.approx(1228.0, abs=0.1)
    assert row["pct_of_hbm_roofline"] == pytest.approx(100.0, abs=0.1)
    assert roofline_row(0.0, 1, "TPU v4") == {}
    assert "pct_of_hbm_roofline" not in roofline_row(1.0, 1, "who?")


def test_engine_roofline_mfu_and_hbm_pct_hand_computed():
    flops, gbps = DEVICE_PEAKS["TPU v4"]
    # 1000 tok/s at 1B params = 2e12 FLOP/s over a 275e12 peak
    assert PINNED.mfu(1000.0) == pytest.approx(
        2.0 * 1e9 * 1000.0 / flops
    )
    # 61.4 GB moved over 0.1 s = 614 GB/s = 50% of the 1228 GB/s peak
    assert PINNED.hbm_roofline_pct(61.4e9, 0.1) == pytest.approx(50.0)
    assert PINNED.mfu(0.0) is None
    assert PINNED.hbm_roofline_pct(1.0, 0.0) is None
    unknown = EngineRoofline("cpu", 1, 1, 1, 1)
    assert unknown.mfu(100.0) is None
    assert unknown.hbm_roofline_pct(1e9, 1.0) is None


# ------------------------------------------------ per-tick attribution


def test_phases_sum_to_tick_wall_with_host_as_remainder():
    clock = FakeClock()
    rec = recorder(clock=clock)
    rec.tick_begin()
    rec.phase("dispatch", 0.010)
    rec.phase("device", 0.030)
    rec.phase("readback", 0.004)
    rec.phase("detok", 0.006)
    clock.advance(0.100)
    rec.tick_end(worked=True)
    totals = rec.totals()
    assert totals["ticks"] == 1
    phases = totals["phase_seconds"]
    assert phases["host"] == pytest.approx(0.050)
    assert sum(phases.values()) == pytest.approx(totals["wall_s"])
    assert set(phases) == set(PHASES)


def test_host_phase_clamps_at_zero_on_clock_noise():
    clock = FakeClock()
    rec = recorder(clock=clock)
    rec.tick_begin()
    rec.phase("device", 0.2)  # measured > wall (clock noise)
    clock.advance(0.1)
    rec.tick_end(worked=True)
    assert rec.totals()["phase_seconds"]["host"] == 0.0


def test_idle_ticks_are_counted_but_not_attributed():
    clock = FakeClock()
    rec = recorder(clock=clock)
    for _ in range(3):
        rec.tick_begin()
        clock.advance(0.005)
        rec.tick_end(worked=False)
    totals = rec.totals()
    assert totals["ticks"] == 0
    assert totals["idle_ticks"] == 3
    assert totals["wall_s"] == 0.0


def test_disabled_recorder_is_inert():
    rec = recorder(enabled=False)
    rec.tick_begin()
    rec.phase("device", 1.0)
    rec.note_tokens(5)
    rec.tick_end(worked=True)
    rec.record_compile("decode", ("k",), 1.0, trigger="x")
    assert rec.snapshot() == {"enabled": False}
    assert rec.get_stats() == {"enabled": False}
    assert rec.totals()["ticks"] == 0
    rec2 = recorder(perf_enabled=False)
    assert rec2.enabled is False


def test_window_gauges_match_roofline_hand_computed():
    """ISSUE 13 acceptance: the rolling-window MFU / roofline values
    equal roofline.py hand-computed on the pinned geometry."""
    clock = FakeClock()
    rec = recorder(clock=clock, roofline=PINNED, perf_window_s=60.0)
    # one decode tick: 8 fused steps over 500 resident ctx tokens,
    # 0.040 s of device time, 8 tokens delivered
    rec.tick_begin()
    rec.phase("dispatch", 0.002)
    rec.phase("device", 0.040)
    rec.note_decode(steps=8, ctx_tokens=500, device_s=0.040)
    rec.note_tokens(8)
    clock.advance(0.050)
    rec.tick_end(worked=True)
    clock.advance(1.950)  # window spans exactly 2 s since the tick began
    win = rec.window()
    assert win["ticks"] == 1
    tok_s = 8 / 2.0
    assert win["tokens_per_s"] == pytest.approx(tok_s, abs=0.01)
    assert win["mfu"] == pytest.approx(PINNED.mfu(tok_s), abs=1e-4)
    modeled = 8 * decode_step_bytes(
        PINNED.weight_stream_bytes, 1, 500, PINNED.kv_token_bytes
    )
    assert win["hbm_roofline_pct"] == pytest.approx(
        PINNED.hbm_roofline_pct(modeled, 0.040), abs=0.01
    )
    assert win["host_overhead_ratio"] == pytest.approx(
        (0.050 - 0.042) / 0.050, abs=1e-3
    )


def test_window_expires_old_ticks():
    clock = FakeClock()
    rec = recorder(clock=clock, perf_window_s=10.0)
    rec.tick_begin()
    rec.note_tokens(4)
    clock.advance(0.01)
    rec.tick_end(worked=True)
    clock.advance(60.0)  # tick now far outside the window
    win = rec.window()
    assert win["ticks"] == 0
    assert win["tokens"] == 0
    assert win["tokens_per_s"] == 0.0
    assert win["host_overhead_ratio"] is None
    # lifetime totals keep it
    assert rec.totals()["tokens"] == 4


# ------------------------------------------------------ compile ledger


def test_compile_ledger_one_entry_per_variant():
    rec = recorder()
    rec.record_compile("decode", (8, False), 1.5, trigger="chunk_variant")
    rec.record_compile("decode", (4, False), 0.5, trigger="chunk_variant")
    rec.record_compile("prefill", (16, 1), 2.0, trigger="bucket")
    ledger = rec.compile_ledger()
    assert len(ledger) == 3
    assert all(e["count"] == 1 for e in ledger)
    assert rec.totals()["compiles"] == {"decode": 2, "prefill": 1}
    assert rec.totals()["compile_seconds"] == pytest.approx(4.0)
    # the SAME signature again is a re-compile of a known variant:
    # count bumps on the one entry, no new entry appears
    rec.record_compile("decode", (8, False), 1.0, trigger="chunk_variant")
    ledger = rec.compile_ledger()
    assert len(ledger) == 3
    entry = next(
        e for e in ledger if e["signature"] == str((8, False))
    )
    assert entry["count"] == 2
    assert entry["seconds"] == pytest.approx(2.5)
    assert entry["trigger"] == "chunk_variant"


def test_compile_ledger_is_bounded():
    rec = recorder(perf_compile_ledger_max=16)
    for i in range(40):
        rec.record_compile("decode", ("sig", i), 0.01, trigger="t")
    assert len(rec.compile_ledger()) == 16
    # oldest evicted, newest kept
    sigs = {e["signature"] for e in rec.compile_ledger()}
    assert str(("sig", 39)) in sigs
    assert str(("sig", 0)) not in sigs


def test_profile_capture_links_into_snapshot():
    rec = recorder()
    rec.note_profile(
        {"trace_dir": "/tmp/vgt_profile_1", "duration_s": 0.5, "files": 3}
    )
    snap = rec.snapshot()
    assert snap["last_profile"]["trace_dir"] == "/tmp/vgt_profile_1"
    assert "ts" in snap["last_profile"]


# ------------------------------------------------------ dp aggregation


def _fake_snapshot(tokens=100, host=0.5, wall=1.0, mfu=0.1):
    phases = {name: 0.0 for name in PHASES}
    phases["host"] = host
    phases["device"] = wall - host
    return {
        "enabled": True,
        "window": {
            "window_s": 30.0,
            "span_s": 10.0,
            "ticks": 5,
            "tokens": tokens,
            "tokens_per_s": tokens / 10.0,
            "decode_steps": 50,
            "decode_device_s": wall - host,
            "phase_seconds": dict(phases),
            "wall_s": wall,
            "host_overhead_ratio": host / wall,
            "mfu": mfu,
            "hbm_roofline_pct": 10.0 * mfu,
        },
        "totals": {
            "ticks": 5,
            "idle_ticks": 2,
            "tokens": tokens,
            "decode_steps": 50,
            "wall_s": wall,
            "phase_seconds": dict(phases),
            "compiles": {"decode": 3, "prefill": 1},
            "compile_seconds": 2.0,
        },
        "last_tick": None,
        "compile_ledger": [],
        "roofline": None,
        "last_profile": None,
    }


def test_merge_snapshots_sums_and_weights():
    a = _fake_snapshot(tokens=100, host=0.5, wall=1.0, mfu=0.1)
    b = _fake_snapshot(tokens=300, host=0.1, wall=1.0, mfu=0.3)
    merged = perf_mod.merge_snapshots([a, b])
    assert merged["enabled"] is True
    assert [r["replica"] for r in merged["replicas"]] == [0, 1]
    win = merged["window"]
    assert win["tokens"] == 400
    assert win["tokens_per_s"] == pytest.approx(40.0)
    assert win["phase_seconds"]["host"] == pytest.approx(0.6)
    # token-weighted MFU: (0.1*100 + 0.3*300) / 400 = 0.25
    assert win["mfu"] == pytest.approx(0.25)
    # wall-weighted host ratio: equal walls -> plain mean
    assert win["host_overhead_ratio"] == pytest.approx(0.3)
    totals = merged["totals"]
    assert totals["compiles"] == {"decode": 6, "prefill": 2}
    assert totals["tokens"] == 400


def test_merge_snapshots_all_disabled():
    merged = perf_mod.merge_snapshots([{"enabled": False}])
    assert merged["enabled"] is False
    assert "window" not in merged


def test_merge_stats_aggregates_stats_blocks():
    blocks = [
        {
            "enabled": True, "tokens_per_s": 10.0, "mfu": 0.1,
            "hbm_roofline_pct": 5.0, "host_overhead_ratio": 0.5,
            "phase_seconds": {n: 1.0 for n in PHASES},
            "ticks": 4, "compiles": {"decode": 2},
            "compile_seconds": 1.0,
        },
        {"enabled": False},
    ]
    agg = perf_mod.merge_stats(blocks)
    assert agg["enabled"] is True
    assert agg["tokens_per_s"] == pytest.approx(10.0)
    assert agg["compiles"] == {"decode": 2}
    assert perf_mod.merge_stats([{"enabled": False}]) == {
        "enabled": False
    }


# ------------------------------------------------- gateway surface


def _dry_config(**overrides):
    return load_config(
        model={"engine_type": "dry_run"},
        logging={"level": "WARNING"},
        **overrides,
    )


async def _client(config=None):
    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(config or _dry_config())))
    await client.start_server()
    return client


async def test_debug_perf_reports_disabled_without_engine_core():
    client = await _client()
    try:
        resp = await client.get("/debug/perf")
        assert resp.status == 200
        body = await resp.json()
        assert body["enabled"] is False
    finally:
        await client.close()


async def test_debug_perf_is_auth_gated():
    client = await _client(
        _dry_config(security={"enabled": True, "api_keys": ["sk-test"]})
    )
    try:
        assert (await client.get("/debug/perf")).status == 401
        resp = await client.get(
            "/debug/perf",
            headers={"Authorization": "Bearer sk-test"},
        )
        assert resp.status == 200
    finally:
        await client.close()


def test_debug_perf_never_holds_a_drain_open():
    from vgate_tpu.server.app import _drain_counted

    assert not _drain_counted("/debug/perf")


# ------------------------------------------------- loadlab perf fields


def test_cell_schema_pins_the_perf_field():
    from vgate_tpu.loadlab import slo

    assert "perf" in slo.CELL_REQUIRED
    cell = slo.grade_cell([], {}, qps=1.0, duration_s=1.0)
    assert cell["perf"] is None  # placeholder the runner overwrites


def test_runner_perf_delta_math():
    from vgate_tpu.loadlab.runner import perf_delta

    def snap(ticks, tokens, host, device, compiles, window=None):
        phases = {n: 0.0 for n in PHASES}
        phases["host"] = host
        phases["device"] = device
        return {
            "enabled": True,
            "window": window or {
                "tokens_per_s": 12.0, "mfu": 0.2,
                "hbm_roofline_pct": 30.0, "host_overhead_ratio": 0.4,
            },
            "totals": {
                "ticks": ticks, "tokens": tokens,
                "wall_s": host + device,
                "phase_seconds": phases,
                "compiles": compiles,
                "compile_seconds": 0.5 * sum(compiles.values()),
            },
        }

    before = snap(10, 100, 1.0, 3.0, {"decode": 2})
    after = snap(30, 500, 2.0, 8.0, {"decode": 2, "prefill": 1})
    delta = perf_delta(before, after)
    assert delta["ticks"] == 20
    assert delta["tokens"] == 400
    assert delta["phase_seconds"]["host"] == pytest.approx(1.0)
    assert delta["phase_seconds"]["device"] == pytest.approx(5.0)
    assert delta["wall_s"] == pytest.approx(6.0)
    assert delta["host_overhead_ratio"] == pytest.approx(
        1.0 / 6.0, abs=1e-4
    )
    # only the variants that MOVED land in the cell (recompile storm
    # visibility, not a full inventory)
    assert delta["recompiles"] == {"prefill": 1}
    assert delta["window"]["mfu"] == 0.2
    assert perf_delta(None, after) is None
    assert perf_delta(before, None) is None


# --------------------------------------------- real engine (slow tier)


def _engine_config():
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 4, "prefill_buckets": [8, 16, 32],
            "use_pallas": False,
        },
        logging={"level": "WARNING"},
    )


@pytest.mark.slow
def test_engine_phase_attribution_sums_and_ledger_counts_once():
    """ISSUE 13 acceptance (engine half): on a real engine the per-phase
    decomposition sums to measured tick wall within 5%, the compile
    ledger counts each variant's first compile exactly once (repeating
    the same shape moves nothing), and /stats carries the perf block."""
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.runtime.engine_core import EngineCore

    core = EngineCore(_engine_config())
    core.start()
    try:
        params = [SamplingParams(max_tokens=8, temperature=0.0)] * 2
        core.generate(["perf probe one", "perf probe two"], params)
        snap = core.perf_snapshot()
        assert snap["enabled"] is True
        totals = snap["totals"]
        assert totals["ticks"] > 0
        assert totals["tokens"] >= 16
        phase_sum = sum(totals["phase_seconds"].values())
        assert phase_sum == pytest.approx(
            totals["wall_s"], rel=0.05
        )
        ledger = snap["compile_ledger"]
        assert ledger, "no compiles recorded"
        assert all(e["count"] == 1 for e in ledger)
        before = {
            (e["program"], e["signature"]): e["count"] for e in ledger
        }
        assert any(p == "decode" for p, _ in before)
        assert any(p == "prefill" for p, _ in before)

        # the same shapes again: no variant compiles TWICE (admission
        # timing may group the wave differently and touch a new batch/
        # chunk variant — that is a new entry with count 1, not a
        # recompile; the drill pins the exact bucket-change contract
        # with serial requests, scripts/perf_check.sh)
        core.generate(["perf probe three", "perf probe four"], params)
        after = {
            (e["program"], e["signature"]): e["count"]
            for e in core.perf_snapshot()["compile_ledger"]
        }
        assert all(count == 1 for count in after.values())
        assert set(before) <= set(after)

        stats = core.get_stats()["perf"]
        assert stats["enabled"] is True
        assert stats["compiles"] == core.perf.totals()["compiles"]
        # CPU test meshes are off the peak table: the gauges exist and
        # are honestly None rather than mislabeled
        assert "mfu" in stats and "hbm_roofline_pct" in stats

        # the /v1/profile link: a capture lands in the flight ring AND
        # /debug/perf's last_profile
        result = core.capture_profile(duration_s=0.05)
        snap = core.perf_snapshot()
        assert snap["last_profile"]["trace_dir"] == result["trace_dir"]
        assert any(
            t["kind"] == "profile" for t in core.flight.ticks()
        )
    finally:
        core.stop()


@pytest.mark.slow
def test_engine_decode_window_reports_live_throughput():
    """The rolling window reports a live tok/s while decoding (the
    gauge the megatick refactor will be judged against)."""
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.runtime.engine_core import EngineCore

    core = EngineCore(_engine_config())
    core.start()
    try:
        core.generate(
            ["throughput probe"],
            [SamplingParams(
                max_tokens=24, min_tokens=24, temperature=0.0
            )],
        )
        win = core.perf_snapshot()["window"]
        assert win["tokens"] >= 24
        assert win["tokens_per_s"] > 0
        assert win["decode_steps"] > 0
    finally:
        core.stop()
