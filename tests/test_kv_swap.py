"""Host-RAM KV swap tier (vgate_tpu/runtime/kv_swap.py).

Three layers, mirroring the subsystem's own split:

* **Manager units** against a fake device executor — budget/ticket
  accounting, epoch staleness, the seq-over-prefix priority under
  budget pressure, brownout demote gating.
* **Scheduler integration** (real allocator, fake executor) — preempt
  swaps out instead of folding, re-admission returns a SwapInPlan,
  pool-full falls back to recompute with the waste metric counted,
  exhaustion failures are typed KVCapacityError.
* **Engine e2e** (CPU tiny-dense, fast tier) — under forced KV
  pressure with the host pool on, preempted sequences resume via
  swap-in with ZERO recompute tokens and token-identical greedy
  output; the swap-off engine shows the recompute baseline.
"""

import logging

import pytest

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.errors import KVCapacityError
from vgate_tpu.runtime.kv_cache import PageAllocator
from vgate_tpu.runtime.kv_swap import KVSwapManager
from vgate_tpu.runtime.radix_cache import RadixCache
from vgate_tpu.runtime.scheduler import Scheduler, SwapInPlan
from vgate_tpu.runtime.sequence import Sequence, SeqStatus

PS = 4
PAGE_BYTES = 64


class FakeDevice:
    """Fake executor: page id -> opaque content, so tests can assert
    the swapped-back content is exactly what was swapped out."""

    def __init__(self):
        self.content = {}
        self.reads = 0
        self.writes = 0

    def read_pages(self, pages):
        self.reads += 1
        return [self.content.get(p) for p in pages]

    def write_pages(self, pages, payload):
        self.writes += 1
        assert len(pages) == len(payload)
        for p, c in zip(pages, payload):
            self.content[p] = c


def make_mgr(budget_pages=16, dev=None):
    dev = dev or FakeDevice()
    return KVSwapManager(budget_pages * PAGE_BYTES, PAGE_BYTES, dev), dev


def running_seq(n_prompt=6, n_out=3, pages=None):
    seq = Sequence(
        prompt_ids=list(range(2, 2 + n_prompt)),
        params=SamplingParams(max_tokens=16),
    )
    seq.output_ids = list(range(50, 50 + n_out))
    seq.generated_ids = list(seq.output_ids)
    seq.status = SeqStatus.RUNNING
    seq.pages = pages if pages is not None else [1, 2, 3]
    seq.slot = 0
    return seq


# -------------------------------------------------------- manager units


def test_swap_out_in_roundtrip_content_and_accounting():
    mgr, dev = make_mgr()
    for p in (1, 2, 3):
        dev.content[p] = ("kv", p)
    seq = running_seq(pages=[1, 2, 3])
    assert mgr.swap_out_seq(seq, [1, 2, 3])
    assert mgr.used_bytes == 3 * PAGE_BYTES
    assert seq.swap_count == 1
    # the scheduler resets the seq (epoch bump) and re-admits later
    seq.reset_for_swap()
    ticket = mgr.ticket_for(seq)
    assert ticket is not None and ticket.num_pages == 3
    # swap in to DIFFERENT pages: content must follow
    seq.status = SeqStatus.RUNNING
    seq.pages = [7, 8, 9]
    assert mgr.swap_in_seq(seq, [7, 8, 9]) == 3
    assert [dev.content[p] for p in (7, 8, 9)] == [
        ("kv", 1), ("kv", 2), ("kv", 3)
    ]
    assert mgr.used_bytes == 0
    assert getattr(seq, "_swap_ticket", None) is None


def test_swap_out_refused_over_budget():
    mgr, _ = make_mgr(budget_pages=2)
    seq = running_seq(pages=[1, 2, 3])
    assert not mgr.swap_out_seq(seq, [1, 2, 3])
    assert mgr.used_bytes == 0 and mgr.total_refused == 1
    assert seq.swap_count == 0


def test_stale_epoch_discards_ticket():
    """A containment/migration fold bumps preempt_count past the
    ticket's epoch: ticket_for must discard, not resume a dead epoch."""
    mgr, _ = make_mgr()
    seq = running_seq()
    assert mgr.swap_out_seq(seq, [1, 2, 3])
    seq.reset_for_swap()
    seq.reset_for_recompute()  # e.g. prepare_resume's fold
    assert mgr.ticket_for(seq) is None
    assert mgr.used_bytes == 0
    assert mgr.total_discard_pages.get("stale") == 3


def test_settled_seq_swept_to_make_room():
    mgr, _ = make_mgr(budget_pages=4)
    a = running_seq(pages=[1, 2, 3])
    assert mgr.swap_out_seq(a, [1, 2, 3])
    a.reset_for_swap()
    a.fail(RuntimeError("client gone"))  # settled elsewhere
    b = running_seq(pages=[4, 5, 6])
    assert mgr.swap_out_seq(b, [4, 5, 6])  # room made by the sweep
    assert mgr.total_discard_pages.get("settled") == 3
    assert mgr.used_bytes == 3 * PAGE_BYTES
    # regression: a late settle hook on the ALREADY-swept sequence must
    # not refund its bytes a second time (the registry, not the seq
    # attribute, is the accounting truth) — a double refund would let
    # the pool pin host RAM beyond the budget
    mgr.discard_for(a, "settled")
    assert mgr.used_bytes == 3 * PAGE_BYTES
    assert mgr.total_discard_pages.get("settled") == 3


def test_seq_swap_evicts_prefix_lru_but_not_vice_versa():
    """Client-owed work wins the budget: a preemption swap-out drops
    victim-cache (prefix) tickets LRU-first; a demotion never rotates
    other entries out."""
    mgr, dev = make_mgr(budget_pages=4)

    class Node:
        pass

    old, new = Node(), Node()
    t_old = mgr.demote_node(old, [11, 12, 13])
    assert t_old is not None
    # a second demotion that would need eviction is refused instead
    assert mgr.demote_node(new, [14, 15]) is None
    assert mgr.total_refused == 1
    # but a preemption swap-out takes the room, dropping the LRU ticket
    dropped = []
    mgr.on_drop_node = dropped.append
    seq = running_seq(pages=[1, 2, 3])
    assert mgr.swap_out_seq(seq, [1, 2, 3])
    assert dropped == [old]
    assert mgr.total_discard_pages.get("capacity") == 3


def test_demote_suspended_gates_demotions_not_promotions():
    mgr, dev = make_mgr()

    class Node:
        pass

    node = Node()
    ticket = mgr.demote_node(node, [11, 12])
    assert ticket is not None
    mgr.demote_suspended = True  # brownout L4
    assert mgr.demote_node(Node(), [13]) is None
    # promotions still served
    assert mgr.promote_node(ticket, [21, 22])
    assert mgr.total_swap_in_pages["prefix"] == 2
    assert mgr.used_bytes == 0


def test_signal_block_occupancy():
    mgr, _ = make_mgr(budget_pages=8)
    seq = running_seq(pages=[1, 2, 3, 4])
    assert mgr.swap_out_seq(seq, [1, 2, 3, 4])
    sig = mgr.signal_block()
    assert sig["kv_swap_enabled"] is True
    assert sig["kv_host_pool_bytes"] == 4 * PAGE_BYTES
    assert sig["kv_host_free_ratio"] == 0.5
    assert sig["kv_swapped_seqs"] == 1


# ------------------------------------------------- scheduler integration


def make_sched(num_pages=16, slots=2, budget_pages=32, radix=False):
    alloc = PageAllocator(num_pages)
    dev = FakeDevice()
    mgr = KVSwapManager(budget_pages * PAGE_BYTES, PAGE_BYTES, dev)
    rx = None
    if radix:
        rx = RadixCache(alloc, PS, cow_min_tokens=2)
        alloc.set_reclaimer(rx)
        rx.attach_swap(mgr)
    sched = Scheduler(
        allocator=alloc,
        max_slots=slots,
        page_size=PS,
        prefill_buckets=[8, 16],
        max_model_len=64,
        max_queue_size=8,
        prefix_cache=radix,
        radix=rx,
        swap=mgr,
    )
    return sched, alloc, mgr, dev


def admit_and_decode(sched, n_prompt=6, steps=8):
    """Admit one prompt, simulate its prefill + `steps` decode tokens."""
    seq = Sequence(
        prompt_ids=list(range(2, 2 + n_prompt)),
        params=SamplingParams(max_tokens=32),
    )
    sched.add(seq)
    plan = sched.try_admit()
    assert plan is not None and plan.seq is seq
    for t in range(steps):
        seq.append_token(100 + t)
    return seq


def test_preempt_swaps_out_and_swap_in_plan_on_readmission():
    sched, alloc, mgr, dev = make_sched(num_pages=16, slots=2)
    for p in range(1, 16):
        dev.content[p] = ("kv", p)
    a = admit_and_decode(sched, n_prompt=6, steps=4)
    b = admit_and_decode(sched, n_prompt=6, steps=4)
    # grow until the pool forces preemption of the youngest (b)
    assert sched.prepare_decode([a, b], horizon=32)
    assert b.status is SeqStatus.WAITING and a.status is SeqStatus.RUNNING
    assert sched.total_swap_preempts == 1
    assert b.output_ids, "swap keeps the prompt/output split intact"
    assert mgr.total_swap_out_pages["preempt"] > 0
    saved = mgr.ticket_for(b).num_pages
    # finish a -> b re-admits via swap-in
    a.status = SeqStatus.RUNNING
    sched.remove(a)
    a.finish("stop")
    plan = sched.try_admit()
    assert isinstance(plan, SwapInPlan)
    assert plan.seq is b and b.status is SeqStatus.RUNNING
    assert len(b.pages) == saved
    # engine-side consume: content lands in the new pages
    mgr.swap_in_seq(b, b.pages)
    assert mgr.total_swap_in_pages["preempt"] == saved
    assert sched.total_preempt_recompute_tokens == 0


def test_pool_full_falls_back_to_recompute_and_counts_waste():
    sched, alloc, mgr, dev = make_sched(num_pages=16, budget_pages=1)
    a = admit_and_decode(sched, n_prompt=6, steps=4)
    b = admit_and_decode(sched, n_prompt=6, steps=4)
    assert sched.prepare_decode([a, b], horizon=32)
    assert b.status is SeqStatus.WAITING
    # pool too small: classic recompute fold
    assert sched.total_swap_preempts == 0
    assert not b.output_ids and b.num_prompt_tokens == 10
    a.status = SeqStatus.RUNNING
    sched.remove(a)
    a.finish("stop")
    plan = sched.try_admit()
    assert plan is not None and not isinstance(plan, SwapInPlan)
    # the re-prefilled suffix is counted as preemption waste
    assert sched.total_preempt_recompute_tokens == 10


def test_kv_exhaustion_is_typed_kv_capacity():
    """The two seq.fail sites must surface KVCapacityError (-> 503 +
    Retry-After, body reason kv_capacity) instead of an opaque 500."""
    # site 1: preempt_on_oom off
    alloc = PageAllocator(6)
    sched = Scheduler(
        allocator=alloc, max_slots=2, page_size=PS,
        prefill_buckets=[8], max_model_len=64, max_queue_size=8,
        preempt_on_oom=False,
    )
    seq = Sequence(
        prompt_ids=list(range(2, 10)),
        params=SamplingParams(max_tokens=40),
    )
    sched.add(seq)
    assert sched.try_admit() is not None
    for t in range(12):
        seq.append_token(100 + t)
    sched.prepare_decode([seq], horizon=32)
    assert seq.status is SeqStatus.FAILED
    assert isinstance(seq.error, KVCapacityError)
    assert seq.error.reason == "kv_capacity"
    assert seq.error.retry_after >= 1.0
    # site 2: alone and the grown context can never fit
    alloc2 = PageAllocator(6)
    sched2 = Scheduler(
        allocator=alloc2, max_slots=2, page_size=PS,
        prefill_buckets=[8], max_model_len=64, max_queue_size=8,
    )
    seq2 = Sequence(
        prompt_ids=list(range(2, 10)),
        params=SamplingParams(max_tokens=40),
    )
    sched2.add(seq2)
    assert sched2.try_admit() is not None
    for t in range(12):
        seq2.append_token(100 + t)
    sched2.prepare_decode([seq2], horizon=32)
    assert seq2.status is SeqStatus.FAILED
    assert isinstance(seq2.error, KVCapacityError)


def test_has_admissible_waiting_uses_ticket_pages():
    sched, alloc, mgr, dev = make_sched(num_pages=16, slots=2)
    a = admit_and_decode(sched, n_prompt=6, steps=4)
    b = admit_and_decode(sched, n_prompt=6, steps=4)
    sched.prepare_decode([a, b], horizon=32)
    assert b.status is SeqStatus.WAITING
    ticket = mgr.ticket_for(b)
    assert ticket is not None
    # pool still hogged by a: not admissible
    assert sched.has_admissible_waiting() == (
        alloc.num_free >= ticket.num_pages
    )
    a.status = SeqStatus.RUNNING
    sched.remove(a)
    a.finish("stop")
    assert sched.has_admissible_waiting()


def test_abort_and_evacuate_discard_parked_kv():
    sched, alloc, mgr, dev = make_sched(num_pages=16, slots=2)
    a = admit_and_decode(sched, n_prompt=6, steps=4)
    b = admit_and_decode(sched, n_prompt=6, steps=4)
    sched.prepare_decode([a, b], horizon=32)
    assert mgr.used_bytes > 0
    b.request_abort()
    sched._reap_aborted()
    assert mgr.used_bytes == 0
    assert mgr.total_discard_pages.get("settled", 0) > 0


def test_gateway_503_body_for_kv_capacity():
    """KVCapacityError rides the generic RetryableError -> 503 mapping
    with its own body reason, so the SDK's typed KVCapacityError (and
    LBs) can tell 'this replica's KV is full' from an opaque 500."""
    import json

    from vgate_tpu.server.app import _unavailable_503

    exc = KVCapacityError("KV pages exhausted", retry_after=5)
    resp = _unavailable_503(exc, str(exc))
    assert resp.status == 503
    body = json.loads(resp.text)
    assert body["error"]["reason"] == "kv_capacity"
    assert resp.headers["Retry-After"] == "5"


# --------------------------------------------------------- admission


def test_admission_swap_relief_runs_pool_hotter():
    from vgate_tpu.admission import AdmissionController
    from vgate_tpu.config import load_config
    from vgate_tpu.errors import ServerOverloadedError

    cfg = load_config(
        admission={"kv_free_watermark": 0.2, "swap_kv_relief": 0.5}
    ).admission
    sig = {"kv_free_ratio": 0.15}
    ctl = AdmissionController(cfg, signals=lambda: dict(sig))
    # 0.15 < 0.2 watermark: shed without the swap tier
    with pytest.raises(ServerOverloadedError) as ei:
        ctl.admit(10, tier="interactive")
    assert ei.value.shed_reason == "kv_pressure"
    # swap tier healthy: watermark relieved to 0.1 -> admitted
    sig.update(kv_swap_enabled=True, kv_host_free_ratio=0.9)
    ctl.admit(10, tier="interactive")
    ctl.release(10)
    # exhausted host pool restores the full watermark
    sig.update(kv_host_free_ratio=0.1)
    with pytest.raises(ServerOverloadedError):
        ctl.admit(10, tier="interactive")


# --------------------------------------------------------- engine e2e


def _engine_cfg(num_pages, host_swap_bytes):
    from vgate_tpu.config import load_config

    return load_config(
        model={
            "model_id": "tiny-dense", "engine_type": "jax_tpu",
            "dtype": "float32", "max_model_len": 96,
        },
        kv_cache={"host_swap_bytes": host_swap_bytes},
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": num_pages, "kv_page_size": PS,
            "max_batch_slots": 4, "prefill_buckets": [8, 16, 32],
            "use_pallas": False,
            "prefix_cache": {"enabled": True, "cow_min_tokens": 2},
        },
        scheduler={"max_queue_size": 16},
        logging={"level": "ERROR"},
    )


def _drive(core, prompts, params):
    seqs = [core.submit_tokens(list(p), params) for p in prompts]
    outs = []
    for s in seqs:
        assert s.done_event.wait(timeout=300)
        assert s.status is SeqStatus.FINISHED, s.error
        outs.append(list(s.generated_ids))
    return outs


def test_engine_swap_zero_recompute_token_identity():
    """The acceptance contract: under forced KV pressure with the host
    pool on, preempted sequences resume via swap-in with zero
    recompute tokens and token-identical greedy output; the swap-off
    twin preempts the same way but pays recompute."""
    import jax

    from vgate_tpu.runtime.engine_core import EngineCore

    params = SamplingParams(max_tokens=40, temperature=0.0, min_tokens=40)
    prompts = [
        [7 + i, 3, 9, 4 + i, 11, 6, 2, 13 + i, 5, 8, 12, 10 + i]
        for i in range(4)
    ]

    big = EngineCore(_engine_cfg(200, 0), devices=jax.devices()[:1])
    big.start()
    try:
        base = _drive(big, prompts, params)
        assert big.scheduler.total_preemptions == 0, (
            "baseline must be unpressured"
        )
    finally:
        big.stop()

    on = EngineCore(_engine_cfg(40, 1 << 24), devices=jax.devices()[:1])
    on.start()
    try:
        outs = _drive(on, prompts, params)
        st = on.get_stats()
        sched = st["scheduler"]
        assert sched["preemptions"] > 0, "pool was never squeezed"
        assert sched["swap_preempts"] == sched["preemptions"]
        assert sched["preempt_recompute_tokens"] == 0
        assert st["kv_swap"]["swap_in_pages"]["preempt"] > 0
        assert outs == base
    finally:
        on.stop()

    off = EngineCore(_engine_cfg(40, 0), devices=jax.devices()[:1])
    off.start()
    try:
        outs = _drive(off, prompts, params)
        sched = off.get_stats()["scheduler"]
        assert sched["preemptions"] > 0
        assert sched["preempt_recompute_tokens"] > 0, (
            "the swap-off baseline must show the recompute waste"
        )
        assert "kv_swap" not in off.get_stats()
        assert outs == base, "recompute path is also token-identical"
    finally:
        off.stop()


def test_engine_swap_off_pressure_signals_unchanged():
    import jax

    from vgate_tpu.runtime.engine_core import EngineCore

    core = EngineCore(_engine_cfg(48, 0), devices=jax.devices()[:1])
    try:
        sig = core.pressure_signals()
        assert "kv_swap_enabled" not in sig
        assert core.kv_swap is None
    finally:
        core.stop()
