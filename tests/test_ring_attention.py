"""Ring attention (sequence parallel) vs single-device attention on the
virtual 8-device CPU mesh (SURVEY.md sections 2.2 / 5.7)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.config import load_config
from vgate_tpu.ops.attention import causal_prefill_attention
from vgate_tpu.parallel.mesh import build_mesh
from vgate_tpu.parallel.ring_attention import ring_prefill_attention


def sp_mesh(sp):
    return build_mesh(load_config(tpu={"dp": 1, "ep": 1, "sp": sp, "tp": 1,
                                       "num_devices": sp}).tpu)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_full_attention(sp):
    rng = np.random.default_rng(sp)
    B, S, H, hd = 2, 64, 4, 32
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    lens = jnp.asarray([64, 41], jnp.int32)

    expect = causal_prefill_attention(q, k, v, lens)
    got = ring_prefill_attention(q, k, v, lens, sp_mesh(sp))
    # padded-query rows are garbage in both; compare real tokens only
    for b, n in enumerate([64, 41]):
        np.testing.assert_allclose(
            np.asarray(got[b, :n]), np.asarray(expect[b, :n]),
            rtol=2e-5, atol=2e-5,
        )


def test_ring_gqa_expansion():
    rng = np.random.default_rng(9)
    B, S, H, KV, hd = 1, 32, 8, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    lens = jnp.asarray([30], jnp.int32)
    expect = causal_prefill_attention(q, k, v, lens)
    got = ring_prefill_attention(q, k, v, lens, sp_mesh(4))
    np.testing.assert_allclose(
        np.asarray(got[0, :30]), np.asarray(expect[0, :30]),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_sliding_window_softcap_scale(sp):
    """Gemma-2-style attention (sliding window + tanh softcap + custom
    query scale) through the ring must match the single-device oracle —
    the unlock for Gemma-2 x sp serving (VERDICT r2 next-10)."""
    rng = np.random.default_rng(17 + sp)
    B, S, H, hd = 2, 64, 4, 32
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    lens = jnp.asarray([64, 37], jnp.int32)
    window, softcap, scale = 8, 50.0, 16.0 ** -0.5

    expect = causal_prefill_attention(
        q, k, v, lens, softcap=softcap,
        window=jnp.asarray(window, jnp.int32), scale=scale,
    )
    got = ring_prefill_attention(
        q, k, v, lens, sp_mesh(sp),
        window=jnp.asarray(window, jnp.int32), softcap=softcap,
        scale=scale,
    )
    for b, n in enumerate([64, 37]):
        np.testing.assert_allclose(
            np.asarray(got[b, :n]), np.asarray(expect[b, :n]),
            rtol=2e-5, atol=2e-5,
        )
    # window=0 means global: must equal the plain causal path
    got_g = ring_prefill_attention(
        q, k, v, lens, sp_mesh(sp), window=jnp.asarray(0, jnp.int32),
    )
    expect_g = causal_prefill_attention(q, k, v, lens)
    np.testing.assert_allclose(
        np.asarray(got_g[0]), np.asarray(expect_g[0]),
        rtol=2e-5, atol=2e-5,
    )


def test_ring_rejects_indivisible_seq():
    mesh = sp_mesh(4)
    q = jnp.zeros((1, 30, 4, 16))
    with pytest.raises(ValueError):
        ring_prefill_attention(q, q, q, jnp.asarray([30]), mesh)
