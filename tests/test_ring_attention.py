"""Ring attention (sequence parallel) vs single-device attention on the
virtual 8-device CPU mesh (SURVEY.md sections 2.2 / 5.7)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.config import load_config
from vgate_tpu.ops.attention import causal_prefill_attention
from vgate_tpu.parallel.mesh import build_mesh
from vgate_tpu.parallel.ring_attention import ring_prefill_attention


def sp_mesh(sp):
    return build_mesh(load_config(tpu={"dp": 1, "ep": 1, "sp": sp, "tp": 1,
                                       "num_devices": sp}).tpu)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_full_attention(sp):
    rng = np.random.default_rng(sp)
    B, S, H, hd = 2, 64, 4, 32
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    lens = jnp.asarray([64, 41], jnp.int32)

    expect = causal_prefill_attention(q, k, v, lens)
    got = ring_prefill_attention(q, k, v, lens, sp_mesh(sp))
    # padded-query rows are garbage in both; compare real tokens only
    for b, n in enumerate([64, 41]):
        np.testing.assert_allclose(
            np.asarray(got[b, :n]), np.asarray(expect[b, :n]),
            rtol=2e-5, atol=2e-5,
        )


def test_ring_gqa_expansion():
    rng = np.random.default_rng(9)
    B, S, H, KV, hd = 1, 32, 8, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    lens = jnp.asarray([30], jnp.int32)
    expect = causal_prefill_attention(q, k, v, lens)
    got = ring_prefill_attention(q, k, v, lens, sp_mesh(4))
    np.testing.assert_allclose(
        np.asarray(got[0, :30]), np.asarray(expect[0, :30]),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_sliding_window_softcap_scale(sp):
    """Gemma-2-style attention (sliding window + tanh softcap + custom
    query scale) through the ring must match the single-device oracle —
    the unlock for Gemma-2 x sp serving (VERDICT r2 next-10)."""
    rng = np.random.default_rng(17 + sp)
    B, S, H, hd = 2, 64, 4, 32
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    lens = jnp.asarray([64, 37], jnp.int32)
    window, softcap, scale = 8, 50.0, 16.0 ** -0.5

    expect = causal_prefill_attention(
        q, k, v, lens, softcap=softcap,
        window=jnp.asarray(window, jnp.int32), scale=scale,
    )
    got = ring_prefill_attention(
        q, k, v, lens, sp_mesh(sp),
        window=jnp.asarray(window, jnp.int32), softcap=softcap,
        scale=scale,
    )
    for b, n in enumerate([64, 37]):
        np.testing.assert_allclose(
            np.asarray(got[b, :n]), np.asarray(expect[b, :n]),
            rtol=2e-5, atol=2e-5,
        )
    # window=0 means global: must equal the plain causal path
    got_g = ring_prefill_attention(
        q, k, v, lens, sp_mesh(sp), window=jnp.asarray(0, jnp.int32),
    )
    expect_g = causal_prefill_attention(q, k, v, lens)
    np.testing.assert_allclose(
        np.asarray(got_g[0]), np.asarray(expect_g[0]),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("sp", [2, 4])
def test_sp_decode_attention_matches_oracle(sp):
    """sp-sharded decode attention (parallel/sp_decode.py): partial
    flash attention per pool shard + LSE merge must equal the
    single-device paged oracle, with the token write landing on the
    owning shard and every other shard writing its local trash."""
    from vgate_tpu.ops.attention import paged_decode_attention
    from vgate_tpu.parallel.sp_decode import sp_decode_attention_and_write

    rng = np.random.default_rng(31 + sp)
    B, H, KV, hd, ps = 3, 4, 2, 32, 4
    P = 16 * sp  # divisible pool with room for 3x6 distinct pages
    pages_per_seq = 6
    k_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    # page tables draw from NON-reserved ids spread across shards
    shard = P // sp
    reserved = {i * shard for i in range(sp)}
    candidates = [p for p in range(P) if p not in reserved]
    pt = jnp.asarray(
        rng.choice(candidates, size=(B, pages_per_seq), replace=False),
        jnp.int32,
    )
    positions = jnp.asarray([5, 11, 21], jnp.int32)
    seq_lens = positions + 1
    page_ids = pt[jnp.arange(B), positions // ps]
    page_off = positions % ps
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    k_t = jnp.asarray(rng.normal(size=(B, KV, hd)), jnp.float32)
    v_t = jnp.asarray(rng.normal(size=(B, KV, hd)), jnp.float32)

    # oracle: plain write + single-device paged attention
    ko = k_pages.at[:, page_ids, page_off].set(
        jnp.transpose(k_t, (1, 0, 2))
    )
    vo = v_pages.at[:, page_ids, page_off].set(
        jnp.transpose(v_t, (1, 0, 2))
    )
    expect = paged_decode_attention(q, ko, vo, pt, seq_lens)

    got, k_out, v_out = sp_decode_attention_and_write(
        q, k_t, v_t, k_pages, v_pages, page_ids, page_off, pt, seq_lens,
        sp_mesh(sp),
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )
    # the owning shard's page holds the token; non-reserved other pages
    # are untouched
    for b in range(B):
        gp, off = int(page_ids[b]), int(page_off[b])
        np.testing.assert_allclose(
            np.asarray(k_out[:, gp, off]),
            np.asarray(k_t[b].astype(jnp.float32)),
            rtol=1e-6, atol=1e-6,
        )


def test_ring_rejects_indivisible_seq():
    mesh = sp_mesh(4)
    q = jnp.zeros((1, 30, 4, 16))
    with pytest.raises(ValueError):
        ring_prefill_attention(q, q, q, jnp.asarray([30]), mesh)


@pytest.mark.parametrize("sp", [2, 4])
def test_sp_suffix_attention_matches_oracle(sp):
    """sp-sharded suffix prefill (prefix caching on an sp pool): each
    shard writes its owned suffix pages + computes blockwise partials
    over its resident ctx slice; the LSE merge must equal the
    single-device paged_suffix_attention oracle after a plain write."""
    from vgate_tpu.ops.attention import paged_suffix_attention
    from vgate_tpu.parallel.sp_decode import sp_suffix_attention_and_write

    rng = np.random.default_rng(47 + sp)
    B, S, H, KV, hd, ps = 2, 16, 4, 2, 32, 4
    n_suffix_pages = S // ps
    P = 24 * sp
    k_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    shard = P // sp
    reserved = {i * shard for i in range(sp)}
    candidates = [p for p in range(P) if p not in reserved]
    # prefix: 2 pages resident; suffix: up to n_suffix_pages fresh pages
    prefix_pages = 2
    ctx_pages = prefix_pages + n_suffix_pages
    all_pages = rng.choice(
        candidates, size=(B, ctx_pages), replace=False
    ).astype(np.int32)
    ctx_pt = jnp.asarray(all_pages)
    suffix_pt = jnp.asarray(all_pages[:, prefix_pages:])
    prefix_lens = jnp.asarray([prefix_pages * ps] * B, jnp.int32)
    suffix_lens = jnp.asarray([S, S - 5], jnp.int32)
    total_lens = prefix_lens + suffix_lens
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k_s = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v_s = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)

    # oracle: plain suffix write + single-device suffix attention
    k_w = jnp.transpose(
        k_s.reshape(B, n_suffix_pages, ps, KV, hd), (3, 0, 1, 2, 4)
    )
    v_w = jnp.transpose(
        v_s.reshape(B, n_suffix_pages, ps, KV, hd), (3, 0, 1, 2, 4)
    )
    ko = k_pages.at[:, suffix_pt].set(k_w)
    vo = v_pages.at[:, suffix_pt].set(v_w)
    expect = paged_suffix_attention(
        q, ko, vo, ctx_pt, prefix_lens, total_lens
    )

    got, k_out, v_out = sp_suffix_attention_and_write(
        q, k_s, v_s, k_pages, v_pages, suffix_pt, ctx_pt,
        prefix_lens, total_lens, sp_mesh(sp),
    )
    for b in range(B):
        n = int(suffix_lens[b])
        np.testing.assert_allclose(
            np.asarray(got[b, :n]), np.asarray(expect[b, :n]),
            rtol=2e-5, atol=2e-5,
        )
    # suffix pages hold the fresh KV on their owners
    np.testing.assert_allclose(
        np.asarray(k_out[:, suffix_pt]), np.asarray(k_w),
        rtol=1e-6, atol=1e-6,
    )


@pytest.mark.parametrize("sp", [2])
def test_sp_suffix_window_softcap_matches_oracle(sp):
    """Sliding-window + softcap (Gemma-2 shape) through the sp suffix
    path must match the single-device oracle."""
    from vgate_tpu.ops.attention import paged_suffix_attention
    from vgate_tpu.parallel.sp_decode import sp_suffix_attention_and_write

    rng = np.random.default_rng(99)
    B, S, H, KV, hd, ps = 1, 8, 2, 1, 16, 4
    n_suffix_pages = S // ps
    P = 16 * sp
    window, softcap, scale = 6, 30.0, 0.3
    k_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    shard = P // sp
    candidates = [p for p in range(P) if p not in {i * shard for i in range(sp)}]
    prefix_pages = 1
    all_pages = rng.choice(
        candidates, size=(B, prefix_pages + n_suffix_pages), replace=False
    ).astype(np.int32)
    ctx_pt = jnp.asarray(all_pages)
    suffix_pt = jnp.asarray(all_pages[:, prefix_pages:])
    prefix_lens = jnp.asarray([prefix_pages * ps], jnp.int32)
    suffix_lens = jnp.asarray([S], jnp.int32)
    total_lens = prefix_lens + suffix_lens
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k_s = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v_s = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    k_w = jnp.transpose(
        k_s.reshape(B, n_suffix_pages, ps, KV, hd), (3, 0, 1, 2, 4)
    )
    v_w = jnp.transpose(
        v_s.reshape(B, n_suffix_pages, ps, KV, hd), (3, 0, 1, 2, 4)
    )
    ko = k_pages.at[:, suffix_pt].set(k_w)
    vo = v_pages.at[:, suffix_pt].set(v_w)
    win = jnp.asarray(window, jnp.int32)
    expect = paged_suffix_attention(
        q, ko, vo, ctx_pt, prefix_lens, total_lens,
        softcap=softcap, window=win, scale=scale,
    )
    got, _, _ = sp_suffix_attention_and_write(
        q, k_s, v_s, k_pages, v_pages, suffix_pt, ctx_pt,
        prefix_lens, total_lens, sp_mesh(sp),
        window=win, softcap=softcap, scale=scale,
    )
    np.testing.assert_allclose(
        np.asarray(got[0]), np.asarray(expect[0]), rtol=2e-5, atol=2e-5
    )
