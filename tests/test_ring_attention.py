"""Ring attention (sequence parallel) vs single-device attention on the
virtual 8-device CPU mesh (SURVEY.md sections 2.2 / 5.7)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.config import load_config
from vgate_tpu.ops.attention import causal_prefill_attention
from vgate_tpu.parallel.mesh import build_mesh
from vgate_tpu.parallel.ring_attention import ring_prefill_attention


def sp_mesh(sp):
    return build_mesh(load_config(tpu={"dp": 1, "ep": 1, "sp": sp, "tp": 1,
                                       "num_devices": sp}).tpu)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_full_attention(sp):
    rng = np.random.default_rng(sp)
    B, S, H, hd = 2, 64, 4, 32
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    lens = jnp.asarray([64, 41], jnp.int32)

    expect = causal_prefill_attention(q, k, v, lens)
    got = ring_prefill_attention(q, k, v, lens, sp_mesh(sp))
    # padded-query rows are garbage in both; compare real tokens only
    for b, n in enumerate([64, 41]):
        np.testing.assert_allclose(
            np.asarray(got[b, :n]), np.asarray(expect[b, :n]),
            rtol=2e-5, atol=2e-5,
        )


def test_ring_gqa_expansion():
    rng = np.random.default_rng(9)
    B, S, H, KV, hd = 1, 32, 8, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    lens = jnp.asarray([30], jnp.int32)
    expect = causal_prefill_attention(q, k, v, lens)
    got = ring_prefill_attention(q, k, v, lens, sp_mesh(4))
    np.testing.assert_allclose(
        np.asarray(got[0, :30]), np.asarray(expect[0, :30]),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("sp", [2, 4])
def test_ring_sliding_window_softcap_scale(sp):
    """Gemma-2-style attention (sliding window + tanh softcap + custom
    query scale) through the ring must match the single-device oracle —
    the unlock for Gemma-2 x sp serving (VERDICT r2 next-10)."""
    rng = np.random.default_rng(17 + sp)
    B, S, H, hd = 2, 64, 4, 32
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    lens = jnp.asarray([64, 37], jnp.int32)
    window, softcap, scale = 8, 50.0, 16.0 ** -0.5

    expect = causal_prefill_attention(
        q, k, v, lens, softcap=softcap,
        window=jnp.asarray(window, jnp.int32), scale=scale,
    )
    got = ring_prefill_attention(
        q, k, v, lens, sp_mesh(sp),
        window=jnp.asarray(window, jnp.int32), softcap=softcap,
        scale=scale,
    )
    for b, n in enumerate([64, 37]):
        np.testing.assert_allclose(
            np.asarray(got[b, :n]), np.asarray(expect[b, :n]),
            rtol=2e-5, atol=2e-5,
        )
    # window=0 means global: must equal the plain causal path
    got_g = ring_prefill_attention(
        q, k, v, lens, sp_mesh(sp), window=jnp.asarray(0, jnp.int32),
    )
    expect_g = causal_prefill_attention(q, k, v, lens)
    np.testing.assert_allclose(
        np.asarray(got_g[0]), np.asarray(expect_g[0]),
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("sp", [2, 4])
def test_sp_decode_attention_matches_oracle(sp):
    """sp-sharded decode attention (parallel/sp_decode.py): partial
    flash attention per pool shard + LSE merge must equal the
    single-device paged oracle, with the token write landing on the
    owning shard and every other shard writing its local trash."""
    from vgate_tpu.ops.attention import paged_decode_attention
    from vgate_tpu.parallel.sp_decode import sp_decode_attention_and_write

    rng = np.random.default_rng(31 + sp)
    B, H, KV, hd, ps = 3, 4, 2, 32, 4
    P = 16 * sp  # divisible pool with room for 3x6 distinct pages
    pages_per_seq = 6
    k_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    # page tables draw from NON-reserved ids spread across shards
    shard = P // sp
    reserved = {i * shard for i in range(sp)}
    candidates = [p for p in range(P) if p not in reserved]
    pt = jnp.asarray(
        rng.choice(candidates, size=(B, pages_per_seq), replace=False),
        jnp.int32,
    )
    positions = jnp.asarray([5, 11, 21], jnp.int32)
    seq_lens = positions + 1
    page_ids = pt[jnp.arange(B), positions // ps]
    page_off = positions % ps
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    k_t = jnp.asarray(rng.normal(size=(B, KV, hd)), jnp.float32)
    v_t = jnp.asarray(rng.normal(size=(B, KV, hd)), jnp.float32)

    # oracle: plain write + single-device paged attention
    ko = k_pages.at[:, page_ids, page_off].set(
        jnp.transpose(k_t, (1, 0, 2))
    )
    vo = v_pages.at[:, page_ids, page_off].set(
        jnp.transpose(v_t, (1, 0, 2))
    )
    expect = paged_decode_attention(q, ko, vo, pt, seq_lens)

    got, k_out, v_out = sp_decode_attention_and_write(
        q, k_t, v_t, k_pages, v_pages, page_ids, page_off, pt, seq_lens,
        sp_mesh(sp),
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )
    # the owning shard's page holds the token; non-reserved other pages
    # are untouched
    for b in range(B):
        gp, off = int(page_ids[b]), int(page_off[b])
        np.testing.assert_allclose(
            np.asarray(k_out[:, gp, off]),
            np.asarray(k_t[b].astype(jnp.float32)),
            rtol=1e-6, atol=1e-6,
        )


def test_ring_rejects_indivisible_seq():
    mesh = sp_mesh(4)
    q = jnp.zeros((1, 30, 4, 16))
    with pytest.raises(ValueError):
        ring_prefill_attention(q, q, q, jnp.asarray([30]), mesh)
