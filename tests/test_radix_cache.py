"""Radix-tree prefix cache (vgate_tpu/runtime/radix_cache.py): unit
coverage for match/split/COW/insert/evict, plus the seeded randomized
invariant test the subsystem is gated on — interleaved
admit/commit/finish/release/evict/trim sequences must never free a page
that is still referenced, never index a physical page twice, and keep
the allocator's page accounting exact (truly-free + used + cached ==
allocatable) at every step.  Pure host-side, fast tier."""

import random

import pytest

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.runtime.kv_cache import PageAllocator
from vgate_tpu.runtime.kv_swap import KVSwapManager
from vgate_tpu.runtime.radix_cache import RadixCache
from vgate_tpu.runtime.sequence import Sequence, SeqStatus

PS = 4
PAGE_BYTES = 64


def make(num_pages=64, swap_budget_pages=0, **kw):
    # the page-id -> content fake executor is shared with the manager's
    # own suite (one definition of the executor contract under test)
    from test_kv_swap import FakeDevice

    alloc = PageAllocator(num_pages)
    kw.setdefault("cow_min_tokens", 2)
    rx = RadixCache(alloc, PS, **kw)
    alloc.set_reclaimer(rx)
    if swap_budget_pages:
        mgr = KVSwapManager(
            swap_budget_pages * PAGE_BYTES, PAGE_BYTES, FakeDevice()
        )
        rx.attach_swap(mgr)
    return alloc, rx


# ------------------------------------------------------------------ unit


def test_match_walks_longest_prefix_and_locks():
    alloc, rx = make()
    toks = list(range(100, 116))  # 4 full pages
    pages = alloc.allocate(4)
    node = rx.insert(toks, pages)
    assert node is not None and rx.total_inserted_pages == 4
    alloc.release(pages)  # finish-time style: seq refs drop right away
    # full-prefix request (plus a tail so limit allows all 4 pages)
    m = rx.match(toks + [1, 2])
    assert m is not None and m.pages == pages
    assert m.node is not None and m.node.lock_ref >= 1
    # locked nodes are not reclaimable
    assert rx.evictable_pages() == 0
    alloc.release(m.pages)
    rx.unlock(m)
    assert rx.evictable_pages() == 4


def test_match_caps_below_full_prompt():
    """A prompt EQUAL to an indexed stream must keep >= 1 token for the
    suffix prefill to sample from."""
    alloc, rx = make()
    toks = list(range(50, 58))  # 2 pages
    pages = alloc.allocate(2)
    rx.insert(toks, pages)
    m = rx.match(list(toks))  # limit = 7 -> only page 0 matchable
    assert m is not None and len(m.pages) == 1
    alloc.release(m.pages)
    rx.unlock(m)


def test_split_at_partial_match_point():
    alloc, rx = make()
    toks = list(range(1, 17))  # one node, 4 pages
    pages = alloc.allocate(4)
    rx.insert(toks, pages)
    nodes_before = rx.total_nodes
    # diverge at page 2 -> the 4-page run must split into 2 + 2
    probe = toks[:8] + [91, 92, 93, 94, 95]
    m = rx.match(probe)
    assert m is not None and m.pages == pages[:2]
    assert rx.total_nodes == nodes_before + 1
    # the indexed content is unchanged: a full-stream request still
    # matches across the split boundary
    alloc.release(m.pages)
    rx.unlock(m)
    m2 = rx.match(toks + [7])
    assert m2 is not None and m2.pages == pages
    alloc.release(m2.pages)
    rx.unlock(m2)


def test_cow_tail_on_mid_page_divergence():
    alloc, rx = make()
    toks = list(range(1, 17))
    pages = alloc.allocate(4)
    rx.insert(toks, pages)
    # shares 2 pages + 2 tokens of page 2
    m = rx.match(toks[:10] + [88] * 6)
    assert m is not None and len(m.pages) == 2
    assert m.cow_tokens == 2 and m.cow_src == pages[2]
    # the COW source node stays locked until the copy is dispatched
    assert m.cow_node is not None and m.cow_node.lock_ref >= 1
    rx.release_cow(m)
    assert m.cow_node is None
    alloc.release(m.pages)
    rx.unlock(m)


def test_cow_respects_min_tokens():
    alloc, rx = make(cow_min_tokens=3)
    toks = list(range(1, 17))
    pages = alloc.allocate(4)
    rx.insert(toks, pages)
    m = rx.match(toks[:10] + [88] * 6)  # only 2 shared in-page tokens
    assert m is not None and m.cow_tokens == 0 and m.cow_src is None
    alloc.release(m.pages)
    rx.unlock(m)


def test_insert_dedups_existing_prefix():
    alloc, rx = make()
    toks = list(range(1, 13))
    a = alloc.allocate(3)
    assert rx.insert(toks, a) is not None
    assert rx.total_inserted_pages == 3
    # a same-wave duplicate's private pages are NOT adopted
    b = alloc.allocate(3)
    assert rx.insert(toks, b) is None
    assert rx.total_inserted_pages == 3
    assert set(rx.pages_in_tree()) == set(a)
    # extending the stream adopts only the new tail
    c = alloc.allocate(2)
    assert rx.insert(toks + [77, 78, 79, 80], a + c[:1]) is not None
    assert rx.total_inserted_pages == 4
    alloc.release(b)
    alloc.release(c)
    alloc.release(a)


def test_eviction_lru_leaves_first_and_cascades():
    alloc, rx = make(num_pages=32)
    streams = []
    for s in range(3):
        toks = [s * 100 + i for i in range(8)]
        pages = alloc.allocate(2)
        rx.insert(toks, pages)
        streams.append((toks, pages))
        alloc.release(pages)
    # touch stream 0 so it is most-recently-used
    m = rx.match(streams[0][0] + [1])
    alloc.release(m.pages)
    rx.unlock(m)
    freed = rx.evict(2)
    assert freed == 2
    # the oldest untouched stream went first; stream 0 survives
    m0 = rx.match(streams[0][0] + [1])
    assert m0 is not None
    alloc.release(m0.pages)
    rx.unlock(m0)


def test_insert_suspended_serves_hits_only():
    alloc, rx = make()
    toks = list(range(1, 13))
    a = alloc.allocate(3)
    rx.insert(toks, a)
    rx.insert_suspended = True
    b = alloc.allocate(3)
    assert rx.insert([9] * 12, b) is None  # no new content indexed
    m = rx.match(toks + [5])  # hits still served
    assert m is not None and m.pages
    alloc.release(m.pages)
    rx.unlock(m)
    alloc.release(a)
    alloc.release(b)


def test_trim_to_watermark_counts_pressure():
    alloc, rx = make(num_pages=16)
    toks = list(range(1, 41))
    pages = alloc.allocate(10)
    rx.insert(toks, pages)
    alloc.release(pages)
    hold = alloc.allocate(4)  # truly free now 1
    assert alloc.num_truly_free == 1
    rx.trim_to_watermark(6)
    assert alloc.num_truly_free >= 6
    assert rx.total_evictions["pressure"] >= 5
    alloc.release(hold)


def test_probe_counts_evictable_without_mutating():
    alloc, rx = make()
    toks = list(range(1, 17))
    pages = alloc.allocate(4)
    rx.insert(toks, pages)
    alloc.release(pages)
    full, evictable = rx.probe(toks + [1])
    assert (full, evictable) == (4, 4)
    m = rx.match(toks + [1])
    full2, evictable2 = rx.probe(toks + [1])
    assert (full2, evictable2) == (4, 0)  # locked now
    alloc.release(m.pages)
    rx.unlock(m)


def test_commit_pin_keeps_running_pages_unreclaimable():
    """A RUNNING sequence's prompt pages adopted at commit time must
    not count as reclaimable until the sequence releases — otherwise
    num_free overstates what allocate() can obtain and eviction strips
    tree references without freeing anything."""
    alloc, rx = make(num_pages=16)
    toks = list(range(1, 17))
    pages = alloc.allocate(4)  # the sequence's own refs
    node = rx.insert(toks, pages)
    assert node is not None
    rx.lock_node(node)  # scheduler.commit_prefill
    assert rx.evictable_pages() == 0
    assert alloc.num_free == alloc.num_truly_free
    # eviction pressure mid-flight cannot touch the pinned subtree
    assert rx.evict(4) == 0
    assert set(rx.pages_in_tree()) == set(pages)
    # release path (scheduler._radix_unlock + page release)
    rx.unlock_node(node)
    alloc.release(pages)
    assert rx.evictable_pages() == 4
    # now the tree holds the last reference and num_free is honest
    got = alloc.allocate(alloc.num_free)
    assert got is not None
    alloc.release(got)


# ------------------------------------------- randomized invariant drill


def _check_invariants(alloc, rx, live):
    free_set = set(alloc._free)
    ref_set = set(alloc._refs)
    allocatable = set(range(alloc.num_pages)) - alloc.reserved
    # a free page is never referenced; together they cover the pool
    assert not (free_set & ref_set), free_set & ref_set
    assert free_set | ref_set == allocatable
    assert all(r > 0 for r in alloc._refs.values())
    # no physical page indexed twice (pages_in_tree asserts internally)
    tree_pages = rx.pages_in_tree()
    assert set(tree_pages) <= ref_set
    # exact refcount accounting: holders = owning sequences + the tree
    holders = {}
    for seq in live:
        for p in seq["pages"]:
            holders[p] = holders.get(p, 0) + 1
    for p in tree_pages:
        holders[p] = holders.get(p, 0) + 1
    assert holders == dict(alloc._refs), (holders, dict(alloc._refs))
    # the page accounting identity the stats surface reports
    assert (
        alloc.num_truly_free + alloc.num_used + alloc.num_cached
        == alloc.num_allocatable
    )
    # evictable pages really are the lock-free subtrees
    assert alloc.num_cached == rx.evictable_pages()
    # lock accounting is EXACT: every node's lock_ref equals the live
    # handles — match paths AND commit-time insert pins — whose deepest
    # node sits in its subtree (splits must not orphan shares — the
    # chain-walk regression)
    expected = {}

    def count_chain(node):
        while node is not None and node is not rx.root:
            expected[id(node)] = expected.get(id(node), 0) + 1
            node = node.parent

    for seq in live:
        m = seq["match"]
        if m is not None and m.node is not None:
            count_chain(m.node)
        if seq.get("insert_node") is not None:
            count_chain(seq["insert_node"])
    dfs_evictable = 0
    stack = [rx.root]
    while stack:
        node = stack.pop()
        for child in node.children.values():
            assert child.lock_ref == expected.get(id(child), 0), (
                child.pages, child.lock_ref, expected.get(id(child), 0)
            )
            if child.lock_ref == 0:
                dfs_evictable += len(child.pages)
                # num_free honesty: a lock-free node's pages have the
                # tree as their LAST holder, so evicting them genuinely
                # frees memory (the commit-time-pin regression: an
                # unpinned insert of a RUNNING sequence's pages counted
                # seq-referenced pages as reclaimable)
                for p in child.pages:
                    assert alloc.refcount(p) == 1, (p, child.pages)
            stack.append(child)
    # the incrementally-maintained count never drifts from the truth
    assert rx.evictable_pages() == dfs_evictable
    # host swap tier invariants (when attached): a node holds device
    # pages XOR a host ticket; children of a swapped node are swapped;
    # the pool's byte accounting equals exactly the live tickets
    if rx.swap is not None:
        mgr = rx.swap
        swapped_nodes = 0
        ticket_pages = 0
        live_tickets = set()
        stack = [rx.root]
        while stack:
            node = stack.pop()
            for child in node.children.values():
                assert not (child.pages and child.swapped is not None), (
                    "page simultaneously device-resident and swapped"
                )
                assert child.pages or child.swapped is not None, (
                    "non-root node with neither pages nor a ticket"
                )
                if child.swapped is not None:
                    swapped_nodes += 1
                    ticket_pages += child.swapped.num_pages
                    live_tickets.add(id(child.swapped))
                    assert all(
                        g.swapped is not None
                        for g in child.children.values()
                    ), "resident node below a host-swapped prefix"
                stack.append(child)
        assert rx._swapped_nodes == swapped_nodes
        # every tree ticket is registered, every registered prefix
        # ticket is in the tree, and bytes == sum of swapped pages
        assert live_tickets == set(mgr._prefix_lru.keys())
        seq_bytes = sum(
            t.nbytes for _, t in mgr._seq_tickets.values()
        )
        assert (
            mgr.used_bytes
            == ticket_pages * mgr.page_bytes + seq_bytes
        )


@pytest.mark.parametrize(
    "seed,swap_pages",
    [(0, 0), (1, 0), (2, 0), (0, 24), (1, 24), (2, 24)],
)
def test_randomized_interleaving_invariants(seed, swap_pages):
    """The subsystem gate.  With ``swap_pages`` the host swap tier
    rides along: evict/trim DEMOTE lock-free leaves into the pool,
    admit's match PROMOTES them back, and a host_squeeze op (a fake
    preemption swap-out) forces capacity drops of prefix tickets — the
    invariant check asserts exact byte accounting, pages-XOR-ticket
    per node, and the unchanged refcount/lock identities across
    demote->promote cycles."""
    rng = random.Random(seed)
    alloc, rx = make(num_pages=48, swap_budget_pages=swap_pages)
    bases = [
        [rng.randrange(3, 99) for _ in range(rng.randrange(8, 40))]
        for _ in range(6)
    ]
    live = []  # {"tokens", "pages", "match"}

    def admit():
        base = rng.choice(bases)
        keep = rng.randrange(0, len(base) + 1)
        tokens = base[:keep] + [
            rng.randrange(3, 99)
            for _ in range(rng.randrange(2, 20))
        ]
        m = rx.match(tokens)
        matched = m.pages if m is not None else []
        need = -(-len(tokens) // PS) - len(matched)
        own = alloc.allocate(need)
        if own is None:  # rollback, exactly like the scheduler
            alloc.release(list(matched))
            if m is not None:
                rx.unlock(m)
            return
        live.append(
            {
                "tokens": tokens,
                "pages": list(matched) + own,
                "match": m,
                "insert_node": None,
            }
        )
        # commit (post-dispatch): insert the full prompt pages; a node
        # adopting this RUNNING sequence's pages is pinned until finish
        # (scheduler.commit_prefill -> _radix_unlock)
        if m is not None:
            rx.release_cow(m)
        n_full = len(tokens) // PS
        if n_full:
            node = rx.insert(
                tokens[: n_full * PS], live[-1]["pages"][:n_full]
            )
            if node is not None:
                rx.lock_node(node)
                live[-1]["insert_node"] = node

    def finish():
        if not live:
            return
        seq = live.pop(rng.randrange(len(live)))
        if rng.random() < 0.5:
            # decode growth + finish-time insert of generated content
            gen = [rng.randrange(3, 99) for _ in range(rng.randrange(1, 9))]
            total = len(seq["tokens"]) + len(gen)
            extra = -(-total // PS) - len(seq["pages"])
            if extra > 0:
                got = alloc.allocate(extra)
                if got is None:
                    got = []
                seq["pages"] += got
            n_full = (total - 1) // PS
            n_full = min(n_full, len(seq["pages"]))
            if n_full > 0:
                rx.insert(
                    (seq["tokens"] + gen)[: n_full * PS],
                    seq["pages"][:n_full],
                )
        if seq["match"] is not None:
            rx.unlock(seq["match"])
        if seq["insert_node"] is not None:
            rx.unlock_node(seq["insert_node"])
        alloc.release(seq["pages"])

    def evict():
        rx.evict(rng.randrange(1, 6))

    def trim():
        rx.trim_to_watermark(rng.randrange(1, 10))

    def host_squeeze():
        # a fake preemption swap-out claims host-pool room (dropping
        # prefix tickets LRU-first), then its owner settles — the
        # transient exercises the capacity-discard and sweep paths
        s = Sequence(
            prompt_ids=[1, 2, 3], params=SamplingParams(max_tokens=4)
        )
        s.status = SeqStatus.RUNNING
        s.pages = list(range(900, 900 + rng.randrange(1, 9)))
        if rx.swap.swap_out_seq(s, s.pages):
            s.reset_for_swap()
            if rng.random() < 0.7:
                rx.swap.discard_for(s, "settled")
            else:
                s.fail(RuntimeError("gone"))  # left for the sweep

    ops = [admit, admit, finish, evict, trim]
    if swap_pages:
        ops.append(host_squeeze)
    for _ in range(400):
        rng.choice(ops)()
        _check_invariants(alloc, rx, live)
    while live:
        finish()
        _check_invariants(alloc, rx, live)
    # drain: everything left is reclaimable; the pool returns whole
    got = alloc.allocate(alloc.num_free)
    assert got is not None
    alloc.release(got)
    assert alloc.num_truly_free == alloc.num_allocatable


# -------------------------------------------- int8 KV x radix sharing


def test_int8_cow_and_shared_page_scale_consistency():
    """Radix sharing over an int8 pool (kv_cache.dtype: int8): the
    per-slot quantization scales live in page-indexed pools, so they
    travel with shared pages for free and the COW copy duplicates them
    with the data.  Engine-level contract: (a) a mid-page divergence
    fires COW and the diverged request is greedy-identical to a cold
    int8 engine; (b) re-running the ORIGINAL prompt after the
    divergence still matches its first output exactly — the shared
    page's data+scales were not perturbed by the COW'd sibling."""
    import jax

    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    def cfg(prefix_cache):
        return load_config(
            model={
                "model_id": "tiny-dense", "engine_type": "jax_tpu",
                "dtype": "float32", "max_model_len": 96,
            },
            kv_cache={"dtype": "int8"},
            tpu={
                "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
                "kv_num_pages": 96, "kv_page_size": PS,
                "max_batch_slots": 4, "prefill_buckets": [8, 16, 32],
                "use_pallas": False,
                "prefix_cache": {
                    "enabled": prefix_cache, "cow_min_tokens": 2,
                },
            },
            scheduler={"max_queue_size": 16},
            logging={"level": "ERROR"},
        )

    greedy = SamplingParams(max_tokens=8, temperature=0.0)
    base = [7, 3, 9, 4, 11, 6, 2, 13, 5, 8, 12, 10, 14, 9]
    ids_a = base
    ids_b = base[:10] + [21, 22, 23, 24]  # 2 full pages + 2 in-page

    cached = EngineCore(cfg(True), devices=jax.devices()[:1])
    plain = EngineCore(cfg(False), devices=jax.devices()[:1])
    cached.start()
    plain.start()
    try:
        assert cached.geometry.kv_dtype == "int8"
        sa = cached.submit_tokens(list(ids_a), greedy)
        assert sa.done_event.wait(timeout=300)
        cow0 = cached.radix_cache.total_cow_copies
        sb = cached.submit_tokens(list(ids_b), greedy)
        assert sb.done_event.wait(timeout=300)
        assert cached.radix_cache.total_cow_copies > cow0, "COW never fired"
        # (b) shared page unperturbed: the original prompt replays to
        # its own first output through the shared (scaled) pages
        sa2 = cached.submit_tokens(list(ids_a), greedy)
        assert sa2.done_event.wait(timeout=300)
        assert list(sa2.generated_ids) == list(sa.generated_ids)
        # (a) cold-path identity for both shapes
        pa = plain.submit_tokens(list(ids_a), greedy)
        pb = plain.submit_tokens(list(ids_b), greedy)
        assert pa.done_event.wait(timeout=300)
        assert pb.done_event.wait(timeout=300)
        assert list(sa.generated_ids) == list(pa.generated_ids)
        assert list(sb.generated_ids) == list(pb.generated_ids)
    finally:
        cached.stop()
        plain.stop()
