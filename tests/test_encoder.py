"""Encoder (BERT/bge family) parity vs HF torch + tokenizer unit tests."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.models.encoder import (
    encode_forward,
    encoder_params_from_torch_state_dict,
    init_encoder_params,
)
from vgate_tpu.models.specs import TINY_ENCODER
from vgate_tpu.runtime.tokenizer import ByteTokenizer, get_tokenizer

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def test_encoder_parity_with_hf_bert():
    spec = TINY_ENCODER
    config = transformers.BertConfig(
        vocab_size=spec.vocab_size,
        hidden_size=spec.hidden_size,
        num_hidden_layers=spec.num_layers,
        num_attention_heads=spec.num_heads,
        intermediate_size=spec.intermediate_size,
        max_position_embeddings=spec.max_position_embeddings,
        hidden_act="gelu",
    )
    torch.manual_seed(0)
    model = transformers.BertModel(config, add_pooling_layer=False).eval()
    params = encoder_params_from_torch_state_dict(spec, model.state_dict())

    rng = np.random.default_rng(0)
    B, S = 2, 12
    lens = [12, 8]
    tokens = np.zeros((B, S), np.int64)
    mask = np.zeros((B, S), np.int64)
    for b, n in enumerate(lens):
        tokens[b, :n] = rng.integers(3, spec.vocab_size, size=n)
        mask[b, :n] = 1

    with torch.no_grad():
        hf = model(
            input_ids=torch.tensor(tokens),
            attention_mask=torch.tensor(mask),
        ).last_hidden_state.float().numpy()

    ours = encode_forward(
        params,
        spec,
        jnp.asarray(tokens, jnp.int32),
        jnp.asarray(mask, jnp.int32),
        normalize=False,
    )
    # compare the CLS hidden state (what pooling consumes)
    np.testing.assert_allclose(
        np.asarray(ours), hf[:, 0], rtol=2e-4, atol=2e-4
    )


def test_encoder_padding_invariance():
    """Extending padding must not change real-token outputs."""
    spec = TINY_ENCODER
    params = init_encoder_params(spec, jax.random.PRNGKey(0), jnp.float32)
    ids = np.asarray([[7, 8, 9, 0, 0, 0, 0, 0]], np.int32)
    mask = np.asarray([[1, 1, 1, 0, 0, 0, 0, 0]], np.int32)
    short = encode_forward(params, spec, jnp.asarray(ids[:, :4]),
                           jnp.asarray(mask[:, :4]))
    long = encode_forward(params, spec, jnp.asarray(ids), jnp.asarray(mask))
    np.testing.assert_allclose(
        np.asarray(short), np.asarray(long), atol=1e-5
    )


class TestByteTokenizer:
    def test_roundtrip(self):
        tok = ByteTokenizer(TINY_ENCODER)
        text = "hello wörld! 你好"
        assert tok.decode(tok.encode(text)) == text

    def test_specials_excluded_from_decode(self):
        tok = ByteTokenizer(TINY_ENCODER)
        ids = tok.encode("ab")
        assert tok.decode([tok.eos_id] + ids + [tok.eos_id, 300]) == "ab"

    def test_fallback_selection(self):
        tok = get_tokenizer(TINY_ENCODER, tokenizer_path=None)
        assert isinstance(tok, ByteTokenizer)

    def test_eos_within_vocab(self):
        tok = ByteTokenizer(TINY_ENCODER)
        assert 0 <= tok.eos_id < TINY_ENCODER.vocab_size
