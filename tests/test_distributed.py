"""Multi-host lifecycle smoke tests (SURVEY.md section 5.8; VERDICT r1
missing-5: initialize_distributed must be part of engine startup and the
multi-process path must demonstrably work).

The 2-process test launches real subprocesses that join a
``jax.distributed`` coordinator on localhost and run a cross-process psum
over a global CPU mesh — the same wiring a v5e-16 two-host pod uses, minus
the ICI.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

from vgate_tpu.parallel import mesh as mesh_mod


def test_engine_startup_calls_initialize_distributed(monkeypatch):
    """EngineCore.__init__ must run the multi-host join (a no-op single
    host) — the lifecycle hook the round-1 review found dead."""
    import jax

    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    calls = []
    monkeypatch.setattr(
        "vgate_tpu.runtime.engine_core.initialize_distributed",
        lambda *a, **k: calls.append(True),
    )
    config = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1,
            "kv_num_pages": 16, "kv_page_size": 4, "max_batch_slots": 2,
            "prefill_buckets": [8], "use_pallas": False,
        },
        logging={"level": "WARNING"},
    )
    EngineCore(config, devices=jax.devices()[:1])
    assert calls


def test_initialize_distributed_single_host_noop():
    """Without a coordinator env, initialization is a safe no-op."""
    mesh_mod._distributed_initialized = False
    try:
        mesh_mod.initialize_distributed()  # must not raise or hang
        assert mesh_mod._distributed_initialized
    finally:
        mesh_mod._distributed_initialized = True


_WORKER = textwrap.dedent(
    """
    import os, sys
    pid, port = int(sys.argv[1]), sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from vgate_tpu.parallel.mesh import initialize_distributed

    initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4  # 2 local x 2 processes

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from vgate_tpu.parallel._compat import shard_map

    mesh = Mesh(np.array(jax.devices()).reshape(4), ("dp",))
    f = jax.jit(
        shard_map(
            lambda a: jax.lax.psum(a, "dp"),
            mesh=mesh, in_specs=P("dp"), out_specs=P(),
        )
    )
    out = f(jnp.arange(8.0))
    total = float(np.asarray(out)[0])
    assert total == 0 + 2 + 4 + 6, total
    print(f"DIST_OK pid={pid} psum={total}")
    """
)


_TP_WORKER = textwrap.dedent(
    """
    import functools, os, sys
    pid, port = int(sys.argv[1]), sys.argv[2]
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from vgate_tpu.parallel.mesh import initialize_distributed

    initialize_distributed(
        coordinator_address=f"127.0.0.1:{port}",
        num_processes=2,
        process_id=pid,
    )
    assert jax.process_count() == 2

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from vgate_tpu.models.decoder import decode_forward, init_params
    from vgate_tpu.models.specs import TINY_DENSE as spec
    from vgate_tpu.parallel.mesh import MESH_AXES
    from vgate_tpu.parallel.sharding import kv_pspec, named, shard_params

    # tp axis strides ACROSS the two processes: global order is
    # [p0d0, p0d1, p1d0, p1d1]; transposing makes each tp pair
    # (p0di, p1di), so every tp collective crosses the gloo transport.
    devs = np.array(jax.devices()).reshape(2, 2).T
    mesh = Mesh(devs.reshape(2, 1, 1, 1, 2), MESH_AXES)  # dp=2, tp=2

    params = init_params(spec, jax.random.PRNGKey(0), jnp.float32)
    sharded = shard_params(params, spec, mesh)

    B, ps, pages_per_seq = 2, 4, 4
    P_pages = 1 + B * pages_per_seq
    kv_shape = (
        spec.num_layers, spec.num_kv_heads, P_pages, ps, spec.head_dim
    )
    kv_shard = named(mesh, kv_pspec(spec, mesh))
    repl = NamedSharding(mesh, P())

    def put(x):
        return jax.device_put(x, repl)

    k_pages = jax.device_put(jnp.zeros(kv_shape, jnp.float32), kv_shard)
    v_pages = jax.device_put(jnp.zeros(kv_shape, jnp.float32), kv_shard)
    page_tables = put(
        jnp.asarray(
            1 + np.arange(B * pages_per_seq).reshape(B, pages_per_seq),
            jnp.int32,
        )
    )
    tokens = put(jnp.asarray([7, 11], jnp.int32))
    positions = put(jnp.asarray([3, 5], jnp.int32))
    active = put(jnp.ones((B,), bool))

    @jax.jit
    def sharded_step(p, t, pos, kp, vp, pt, a):
        logits, kp, vp = decode_forward(p, spec, t, pos, kp, vp, pt, active=a)
        return jax.lax.with_sharding_constraint(
            logits, NamedSharding(mesh, P())
        )

    got = np.asarray(
        sharded_step(
            sharded, tokens, positions, k_pages, v_pages, page_tables,
            active,
        )
    )

    # single-device local oracle (no mesh, unsharded)
    ref, _, _ = decode_forward(
        params, spec, jnp.asarray([7, 11], jnp.int32),
        jnp.asarray([3, 5], jnp.int32),
        jnp.zeros(kv_shape, jnp.float32), jnp.zeros(kv_shape, jnp.float32),
        jnp.asarray(
            1 + np.arange(B * pages_per_seq).reshape(B, pages_per_seq),
            jnp.int32,
        ),
        active=jnp.ones((B,), bool),
    )
    np.testing.assert_allclose(got, np.asarray(ref), rtol=1e-4, atol=1e-4)
    print(f"TP_DECODE_OK pid={pid} argmax={np.argmax(got, -1).tolist()}")
    """
)


def test_two_process_tp_sharded_decode_step(tmp_path):
    """The VERDICT r2 next-9 gap: not just a bare psum, but the engine's
    own decode_forward running tp=2-sharded ACROSS two gloo processes
    (2 virtual CPU devices each), logits pinned to the single-device
    oracle.  This is the numerical core of multi-host serving: Megatron
    pspecs + XLA-inserted cross-process collectives through the real
    model code path (KV page write + paged attention + lm_head)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = tmp_path / "tp_worker.py"
    worker.write_text(_TP_WORKER)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=repo_root,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        assert "TP_DECODE_OK" in out


def test_two_process_cpu_distributed_psum(tmp_path):
    """Two real processes join one jax.distributed coordinator and run a
    cross-process psum over the global device mesh."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(pid), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=repo_root,
        )
        for pid in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
        assert "DIST_OK" in out
