"""Frequency/presence penalties across the sampler, engine, speculative
mode, and HTTP (OpenAI semantics: counts over generated tokens only;
beyond the reference schema, vgate-client/vgate_client/models.py:32-37)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.ops.sampling import apply_penalties
from vgate_tpu.runtime.engine_core import EngineCore

from tests.test_logprobs import engine_config, http_config


def test_apply_penalties_formula():
    logits = jnp.zeros((2, 6), jnp.float32)
    counts = jnp.asarray([[0, 1, 3, 0, 0, 0], [2, 0, 0, 0, 0, 1]],
                         jnp.uint16)
    freq = jnp.asarray([0.5, 1.0], jnp.float32)
    pres = jnp.asarray([0.25, 0.0], jnp.float32)
    out = np.asarray(apply_penalties(logits, counts, freq, pres))
    np.testing.assert_allclose(
        out[0], [0, -0.75, -1.75, 0, 0, 0], atol=1e-6
    )
    np.testing.assert_allclose(out[1], [-2, 0, 0, 0, 0, -1], atol=1e-6)


def _distinct_ratio(ids):
    return len(set(ids)) / max(1, len(ids))


def test_engine_frequency_penalty_suppresses_repeats():
    """Greedy decoding with a huge frequency penalty can never choose the
    same token twice (each choice drops by 100 once used); without
    penalties the random-init model repeats heavily."""
    core = EngineCore(engine_config(), devices=jax.devices()[:1])
    core.start()
    try:
        n = 16
        [plain] = core.generate(
            ["repetition probe"],
            [SamplingParams(max_tokens=n, temperature=0.0)],
        )
        [pen] = core.generate(
            ["repetition probe"],
            [SamplingParams(max_tokens=n, temperature=0.0,
                            frequency_penalty=100.0)],
        )
        assert _distinct_ratio(pen["token_ids"]) == 1.0
        # the penalized run must actually differ from the plain one
        # unless the plain one never repeated (random weights usually do)
        if _distinct_ratio(plain["token_ids"]) < 1.0:
            assert pen["token_ids"] != plain["token_ids"]
    finally:
        core.stop()


def test_engine_penalties_isolated_per_slot():
    """A penalized sequence must not alter its co-batched neighbour."""
    core = EngineCore(engine_config(), devices=jax.devices()[:1])
    core.start()
    try:
        [alone] = core.generate(
            ["neighbour probe"], [SamplingParams(max_tokens=8,
                                                 temperature=0.0)]
        )
        both = core.generate(
            ["neighbour probe", "penalized one"],
            [
                SamplingParams(max_tokens=8, temperature=0.0),
                SamplingParams(max_tokens=8, temperature=0.0,
                               frequency_penalty=100.0),
            ],
        )
        assert both[0]["token_ids"] == alone["token_ids"]
        assert _distinct_ratio(both[1]["token_ids"]) == 1.0
    finally:
        core.stop()


def test_speculative_penalties_match_plain_engine():
    """Penalties under draft-and-verify must produce the same tokens as
    the plain engine (the verify pass threads the evolving histogram
    through every candidate position)."""
    prompts = ["spec pen probe", "second spec pen"]
    params = [
        SamplingParams(max_tokens=12, temperature=0.0,
                       frequency_penalty=100.0),
        SamplingParams(max_tokens=12, temperature=0.0,
                       presence_penalty=50.0),
    ]
    plain = EngineCore(engine_config(), devices=jax.devices()[:1])
    plain.start()
    try:
        base = plain.generate(prompts, params)
    finally:
        plain.stop()
    spec = EngineCore(
        engine_config(speculative_k=3), devices=jax.devices()[:1]
    )
    spec.start()
    try:
        got = spec.generate(prompts, params)
    finally:
        spec.stop()
    for b, g in zip(base, got):
        assert b["token_ids"] == g["token_ids"]
        assert _distinct_ratio(g["token_ids"]) == 1.0


async def test_http_penalties_roundtrip():
    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(http_config())))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "pen http"}],
                "max_tokens": 10,
                "temperature": 0,
                "frequency_penalty": 2.0,
            },
        )
        assert resp.status == 200

        bad = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "x"}],
                "frequency_penalty": 5.0,  # out of the -2..2 range
            },
        )
        assert bad.status == 422
    finally:
        await client.close()


def test_min_tokens_suppresses_model_stops():
    """With min_tokens set, a sequence that would stop early (forced by
    stop_token_ids on its own greedy output) keeps generating to the
    floor; without it, it stops immediately."""
    core = EngineCore(engine_config(), devices=jax.devices()[:1])
    core.start()
    try:
        [base] = core.generate(
            ["min tokens probe"],
            [SamplingParams(max_tokens=12, temperature=0.0)],
        )
        first = base["token_ids"][0]
        # stopping on the very first token => 1-token completion
        [short] = core.generate(
            ["min tokens probe"],
            [SamplingParams(max_tokens=12, temperature=0.0,
                            stop_token_ids=[first])],
        )
        assert short["num_tokens"] == 1
        # with min_tokens=6 the stop id is suppressed until 6 tokens exist
        [floored] = core.generate(
            ["min tokens probe"],
            [SamplingParams(max_tokens=12, temperature=0.0,
                            stop_token_ids=[first], min_tokens=6)],
        )
        assert floored["num_tokens"] >= 6
        assert first not in floored["token_ids"][:6]
    finally:
        core.stop()


def test_min_tokens_speculative_equivalence():
    """min_tokens composes with draft-and-verify: same output as the
    plain engine."""
    params = [SamplingParams(max_tokens=10, temperature=0.0, min_tokens=8)]
    plain = EngineCore(engine_config(), devices=jax.devices()[:1])
    plain.start()
    try:
        base = plain.generate(["spec min probe"], params)
    finally:
        plain.stop()
    spec = EngineCore(
        engine_config(speculative_k=3), devices=jax.devices()[:1]
    )
    spec.start()
    try:
        got = spec.generate(["spec min probe"], params)
    finally:
        spec.stop()
    assert base[0]["token_ids"] == got[0]["token_ids"]


async def test_http_min_tokens_passthrough():
    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(http_config())))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "floor"}],
                "max_tokens": 10,
                "min_tokens": 5,
                "temperature": 0,
            },
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["usage"]["completion_tokens"] >= 5
        bad = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "x"}],
                "min_tokens": -1,
            },
        )
        assert bad.status == 422
    finally:
        await client.close()


def test_min_tokens_above_budget_still_finishes_by_length():
    """min_tokens > max_tokens must not hang: the length finish stays
    live below the floor (review finding — the floor gates only stops)."""
    core = EngineCore(engine_config(), devices=jax.devices()[:1])
    core.start()
    try:
        [r] = core.generate(
            ["over floor probe"],
            [SamplingParams(max_tokens=4, temperature=0.0, min_tokens=50)],
        )
        assert r["finish_reason"] == "length"
        assert r["num_tokens"] == 4
    finally:
        core.stop()


# -------------------------------------------------------- logit_bias

def test_apply_logit_bias_op():
    import numpy as np

    from vgate_tpu.ops.sampling import apply_logit_bias

    logits = jnp.zeros((2, 8), jnp.float32)
    ids = jnp.asarray([[3, 5], [8, 8]], jnp.int32)  # row 1: all padding
    vals = jnp.asarray([[10.0, -10.0], [1.0, 1.0]], jnp.float32)
    out = np.asarray(apply_logit_bias(logits, ids, vals))
    assert out[0, 3] == 10.0 and out[0, 5] == -10.0
    assert np.all(out[1] == 0.0)  # out-of-vocab ids dropped


def test_logit_bias_forces_and_bans_tokens_through_engine():
    """+100 on one token makes greedy pick it every step (including the
    prefill's first token); -100 on the natural argmax bans it for a
    sampled request."""
    core = EngineCore(engine_config(), devices=jax.devices()[:1])
    core.start()
    try:
        forced = core.submit_tokens(
            [3, 4, 5, 6],
            SamplingParams(
                max_tokens=6, temperature=0.0, logit_bias={7: 100.0}
            ),
        )
        assert forced.done_event.wait(300)
        assert list(forced.generated_ids) == [7] * 6

        # ban: find the natural greedy first token, then bias it away
        [base] = core.generate(["ban probe"], [
            SamplingParams(max_tokens=1, temperature=0.0)
        ])
        banned_tok = base["token_ids"][0]
        seq = core.submit_prompt(
            "ban probe",
            SamplingParams(
                max_tokens=4, temperature=0.0,
                logit_bias={banned_tok: -100.0},
            ),
        )
        assert seq.done_event.wait(300)
        assert banned_tok not in seq.generated_ids
    finally:
        core.stop()


def test_logit_bias_with_speculative_rounds():
    """Bias applies at every verify position: a +100 forced token under
    spec decoding still emits only that token."""
    from vgate_tpu.config import load_config

    cfg = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 2, "prefill_buckets": [8],
            "use_pallas": False, "speculative_k": 3,
        },
        logging={"level": "WARNING"},
    )
    core = EngineCore(cfg, devices=jax.devices()[:1])
    core.drafter = lambda seq, k: [7] * k  # drafts the forced token
    core.start()
    try:
        seq = core.submit_tokens(
            [3, 4, 5],
            SamplingParams(
                max_tokens=6, temperature=0.0, logit_bias={7: 100.0}
            ),
        )
        assert seq.done_event.wait(300)
        assert list(seq.generated_ids) == [7] * 6
        assert core.total_spec_accepted > 0  # drafts matched the bias
    finally:
        core.stop()
