"""Process-isolated worker pod (ISSUE 16): wire helpers, fencing,
client plumbing, backend seam selection — and (slow tier) the real
2-worker CPU pod: serve, health/stats shapes, and the acceptance
scenario of a SIGKILLed worker mid-decode with token-identical output.
"""

import os
import signal
import socket
import threading
import time
from types import SimpleNamespace

import pytest

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.errors import (
    PoisonRequestError,
    RetryableError,
    WorkerFencedError,
    WorkerLostError,
)
from vgate_tpu.runtime import rpc
from vgate_tpu.runtime.pod_engine import PodEngine, _Worker
from vgate_tpu.runtime.worker import (
    params_from_wire,
    params_to_wire,
    unwire_error,
    wire_error,
)
from vgate_tpu.runtime.worker_client import WorkerClient


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


# ------------------------------------------------------- wire helpers


def test_params_wire_round_trip():
    p = SamplingParams(
        max_tokens=12,
        min_tokens=4,
        temperature=0.0,
        top_p=0.9,
        logprobs=True,
        top_logprobs=3,
        logit_bias={7: -2.5},
    )
    q = params_from_wire(params_to_wire(p))
    assert q.max_tokens == 12
    assert q.min_tokens == 4
    assert q.temperature == 0.0
    assert q.logprobs is True
    # JSON forces dict keys to strings; the wire decode restores ints
    assert q.logit_bias == {7: -2.5}


def test_params_wire_ignores_unknown_fields():
    raw = params_to_wire(greedy(5))
    raw["from_the_future"] = 1
    assert params_from_wire(raw).max_tokens == 5


@pytest.mark.parametrize(
    "exc",
    [
        WorkerLostError("w0 gone", retry_after=3.0),
        WorkerFencedError("stale epoch"),
        RetryableError("busy", retry_after=0.5),
        PoisonRequestError("quarantined"),
        ValueError("bad dtype"),
    ],
)
def test_error_wire_round_trip(exc):
    back = unwire_error(wire_error(exc))
    assert str(exc) in str(back)
    if isinstance(exc, RetryableError):
        assert isinstance(back, RetryableError)
        assert back.reason == exc.reason
        assert back.retry_after == exc.retry_after


def test_unwire_error_degrades_on_unknown_type():
    back = unwire_error({"type": "NoSuchError", "message": "boom"})
    assert isinstance(back, Exception)
    assert "boom" in str(back)


# ------------------------------------------------------------ fencing


def _bare_pod(current_epoch=3):
    """A PodEngine shell with just enough state for frame dispatch."""
    pod = object.__new__(PodEngine)
    pod._lock = threading.RLock()
    pod._inflight = {}
    pod._handoffs = {}
    pod.fenced_frames = 0
    w = _Worker(0)
    w.epoch = current_epoch
    pod.workers = [w]
    return pod


def test_stale_epoch_frame_discarded_and_counted():
    pod = _bare_pod(current_epoch=3)
    for stale in (1, 2, 4, None, "2"):
        pod._on_frame(0, 2, {"op": "tok", "sid": 1, "t": 5, "e": stale})
    assert pod.fenced_frames == 5
    assert pod._inflight == {}  # nothing acted on


def test_current_epoch_frame_dispatched():
    pod = _bare_pod(current_epoch=3)
    seq = SimpleNamespace(
        _worker_idx=0,
        params=greedy(4),
        logprob_data=[],
        generated_ids=[],
        tokens=[],
        append_token=lambda t: seq.tokens.append(t),
    )
    pod._inflight[9] = seq
    pod._on_frame(0, 3, {"op": "tok", "sid": 9, "t": 42, "e": 3})
    assert seq.tokens == [42]
    assert pod.fenced_frames == 0


def test_frame_for_resubmitted_sequence_ignored():
    # sequence moved to worker 1 after a loss; worker 0's late frame
    # carries the CURRENT epoch (same incarnation) but the wrong owner
    pod = _bare_pod(current_epoch=3)
    seq = SimpleNamespace(_worker_idx=1, tokens=[])
    pod._inflight[9] = seq
    pod._on_frame(0, 3, {"op": "tok", "sid": 9, "t": 42, "e": 3})
    assert seq.tokens == []


# ------------------------------------------------------- worker client


class _FakeWorker:
    """Minimal frame-speaking server on a UDS for WorkerClient tests."""

    def __init__(self, path, behavior):
        self.path = path
        self.behavior = behavior  # fn(conn, frame) -> bool continue
        self.listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.listener.bind(path)
        self.listener.listen(1)
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        conn, _ = self.listener.accept()
        try:
            while True:
                frame = rpc.recv_frame(conn)
                if frame is None:
                    break
                if not self.behavior(conn, frame):
                    break
        except (rpc.FrameError, OSError):
            pass
        finally:
            conn.close()
            self.listener.close()


def _client(path, lost, notes=None, call_timeout=5.0):
    return WorkerClient(
        path,
        epoch=1,
        max_frame_bytes=1 << 20,
        connect_timeout_s=2.0,
        call_timeout_s=call_timeout,
        on_notify=(notes.append if notes is not None else lambda f: None),
        on_lost=lambda exc: lost.append(exc),
        label="t",
    )


def test_client_call_round_trip_and_epoch_stamp(tmp_path):
    seen = {}

    def behavior(conn, frame):
        seen.update(frame)
        rpc.send_frame(
            conn,
            {"op": "reply", "id": frame["id"], "e": 1, "ok": True,
             "data": {"pong": True}},
        )
        return True

    srv = _FakeWorker(str(tmp_path / "w.sock"), behavior)
    lost = []
    c = _client(srv.path, lost)
    assert c.call("ping")["pong"] is True
    assert seen["e"] == 1  # every outbound frame carries the epoch
    assert seen["deadline_s"] == 5.0
    c.close()
    assert lost == []  # deliberate close never fires on_lost


def test_client_typed_error_reply(tmp_path):
    def behavior(conn, frame):
        rpc.send_frame(
            conn,
            {"op": "reply", "id": frame["id"], "e": 1, "ok": False,
             "error": wire_error(WorkerFencedError("stale"))},
        )
        return True

    srv = _FakeWorker(str(tmp_path / "w.sock"), behavior)
    c = _client(srv.path, [])
    with pytest.raises(WorkerFencedError):
        c.call("submit")
    c.close()


def test_client_death_fails_pending_and_fires_on_lost_once(tmp_path):
    def behavior(conn, frame):
        return False  # hang up instead of replying

    srv = _FakeWorker(str(tmp_path / "w.sock"), behavior)
    lost = []
    c = _client(srv.path, lost)
    with pytest.raises(WorkerLostError):
        c.call("ping")
    c.join()
    assert len(lost) == 1
    assert c.dead
    # post-mortem sends are refused typed, not hung
    with pytest.raises(WorkerLostError):
        c.notify("abort", sid=1)


def test_client_call_timeout(tmp_path):
    def behavior(conn, frame):
        return True  # swallow the request, never reply

    srv = _FakeWorker(str(tmp_path / "w.sock"), behavior)
    c = _client(srv.path, [], call_timeout=0.2)
    with pytest.raises(TimeoutError):
        c.call("ping")
    c.close()


def test_client_notifications_routed(tmp_path):
    def behavior(conn, frame):
        rpc.send_frame(conn, {"op": "tok", "sid": 1, "t": 9, "e": 1})
        rpc.send_frame(
            conn,
            {"op": "reply", "id": frame["id"], "e": 1, "ok": True,
             "data": {}},
        )
        return True

    srv = _FakeWorker(str(tmp_path / "w.sock"), behavior)
    notes = []
    c = _client(srv.path, [], notes=notes)
    c.call("ping")
    assert notes and notes[0]["op"] == "tok"
    c.close()


# -------------------------------------------------------- backend seam


class _StubEngine:
    def __init__(self, *a, **k):
        self.spec = SimpleNamespace(name="stub")
        self.mesh = SimpleNamespace(shape={"dp": 1})
        self.geometry = SimpleNamespace(num_pages=1)

    def start(self):
        pass


def _seam_config(workers):
    return load_config(
        model={"model_id": "tiny-dense", "engine_type": "jax_tpu"},
        pod={"workers": workers},
        recovery={"enabled": False},
    )


def test_seam_workers_zero_keeps_inprocess_path(monkeypatch):
    from vgate_tpu.backends import jax_backend

    monkeypatch.setattr(jax_backend, "EngineCore", _StubEngine)
    backend = jax_backend.JaxTPUBackend()
    backend.load_model(_seam_config(workers=0))
    assert isinstance(backend.core, _StubEngine)


def test_seam_workers_selects_pod_engine(monkeypatch):
    from vgate_tpu.backends import jax_backend
    from vgate_tpu.runtime import pod_engine

    monkeypatch.setattr(pod_engine, "PodEngine", _StubEngine)
    backend = jax_backend.JaxTPUBackend()
    backend.load_model(_seam_config(workers=2))
    assert isinstance(backend.core, _StubEngine)


def test_pod_engine_refuses_zero_workers():
    with pytest.raises(ValueError):
        PodEngine(_seam_config(workers=0))


# ------------------------------------------- real pod on CPU (slow tier)


def pod_config(workers=2):
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 128, "kv_page_size": 4, "max_batch_slots": 8,
            "prefill_buckets": [8, 16, 32], "use_pallas": False,
        },
        pod={
            "workers": workers,
            "heartbeat_interval_s": 0.2,
            "heartbeat_timeout_s": 5.0,
        },
        recovery={
            "enabled": True, "max_restarts": 6, "restart_window_s": 120.0,
            "backoff_base_s": 0.02, "backoff_cap_s": 0.2,
            "step_stall_s": 120.0, "compile_grace_s": 600.0,
        },
        scheduler={"max_queue_size": 32},
        logging={"level": "ERROR"},
    )


def wait_for(pred, timeout=60.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


@pytest.mark.slow
def test_pod_serves_and_reports():
    """2-worker pod: boot through the canary gate, serve greedy decodes,
    and present dp-shaped health/stats/pressure with per-worker detail."""
    pod = PodEngine(pod_config())
    pod.start()
    try:
        seqs = [
            pod.submit_tokens([5, 9, 13 + i, 17, 21], greedy(8))
            for i in range(4)
        ]
        for s in seqs:
            assert s.done_event.wait(120)
            assert s.error is None
            assert len(s.generated_ids) == 8
        h = pod.health()
        assert h["state"] == "serving"
        assert h["replicas_alive"] == 2
        assert h["fenced_frames"] == 0
        assert [r["replica"] for r in h["replicas"]] == [0, 1]
        assert all(r["epoch"] == 1 for r in h["replicas"])
        assert all(r["pid"] for r in h["replicas"])
        st = pod.get_stats()
        assert st["decode_tokens"] >= 32
        assert st["mesh"]["workers"] == 2
        assert st["pod"]["transport"] == "uds"
        sig = pod.pressure_signals()
        assert 0.0 < sig["kv_free_ratio"] <= 1.0
    finally:
        pod.stop()


@pytest.mark.slow
def test_worker_sigkill_token_identical():
    """Acceptance: SIGKILL one worker mid-decode → every request
    completes (zero failures), resumed on the survivor, token-identical
    to an undisturbed run; pod goes DEGRADED then back to SERVING after
    the canary-gated respawn."""

    def run(kill):
        pod = PodEngine(pod_config())
        pod.start()
        try:
            seqs = [
                pod.submit_tokens(
                    [5, 9, 13 + i, 17, 21],
                    greedy(16, min_tokens=16),
                )
                for i in range(8)
            ]
            if kill:
                time.sleep(1.0)
                os.kill(pod.workers[0].proc.pid, signal.SIGKILL)
            outs = []
            for s in seqs:
                assert s.done_event.wait(180)
                assert s.error is None, f"5xx-equivalent: {s.error}"
                outs.append(list(s.generated_ids))
            if kill:
                h = pod.health()
                assert h["failovers"] == 1
                assert h["resumed"] >= 1
                assert wait_for(lambda: pod.state.value == "serving", 90)
                h = pod.health()
                assert h["restarts"] == 1
                assert h["replicas"][0]["epoch"] > 1  # new incarnation
            return outs
        finally:
            pod.stop()

    assert run(kill=False) == run(kill=True)
