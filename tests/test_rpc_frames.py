"""Frame-protocol hardening for the gateway ↔ worker RPC plane
(ISSUE 16 satellite: protocol fuzz).

The contract under test (vgate_tpu/runtime/rpc.py): every structural
violation — truncated stream, bad magic, oversized length, undecodable
or non-object payload — raises the typed ``FrameError`` (teardown);
well-formed frames with a wrong fencing epoch raise ``StaleEpochError``
(discard-and-count); and NOTHING the peer can put on the wire makes the
reader hang.  The seeded randomized suite mutates valid frames and
asserts the reader always terminates with a frame, clean EOF, or a
typed error.
"""

import random
import socket
import struct
import threading

import pytest

from vgate_tpu import faults
from vgate_tpu.runtime import rpc

CAP = 64 * 1024


def pair():
    a, b = socket.socketpair()
    # backstop only: a hang in recv_frame fails the test as
    # socket.timeout instead of wedging the suite
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


def feed(data: bytes):
    """One closed-writer socket preloaded with raw bytes."""
    a, b = pair()
    a.sendall(data)
    a.close()
    return b


# --------------------------------------------------------- happy path


def test_round_trip():
    a, b = pair()
    rpc.send_frame(a, {"op": "ping", "id": 1, "e": 3}, CAP)
    assert rpc.recv_frame(b, CAP) == {"op": "ping", "id": 1, "e": 3}
    a.close()
    b.close()


def test_clean_eof_returns_none():
    a, b = pair()
    a.close()
    assert rpc.recv_frame(b, CAP) is None
    b.close()


def test_back_to_back_frames():
    a, b = pair()
    for i in range(5):
        rpc.send_frame(a, {"op": "tok", "t": i, "e": 1}, CAP)
    a.close()
    got = [rpc.recv_frame(b, CAP)["t"] for _ in range(5)]
    assert got == [0, 1, 2, 3, 4]
    assert rpc.recv_frame(b, CAP) is None
    b.close()


# ------------------------------------------------- structural violations


def test_truncated_header():
    b = feed(b"\x56\x47")
    with pytest.raises(rpc.FrameError, match="truncated"):
        rpc.recv_frame(b, CAP)
    b.close()


def test_truncated_payload():
    whole = rpc.encode_frame({"op": "ping", "e": 1}, CAP)
    b = feed(whole[:-3])
    with pytest.raises(rpc.FrameError, match="truncated"):
        rpc.recv_frame(b, CAP)
    b.close()


def test_bad_magic():
    b = feed(struct.pack(">II", 0xDEADBEEF, 4) + b"null")
    with pytest.raises(rpc.FrameError, match="magic"):
        rpc.recv_frame(b, CAP)
    b.close()


def test_oversized_inbound_rejected_before_allocation():
    # length field claims 1 GiB; the reader must refuse from the header
    # alone (never attempt the allocation/read)
    b = feed(struct.pack(">II", rpc.MAGIC, 1 << 30))
    with pytest.raises(rpc.FrameError, match="exceeds cap"):
        rpc.recv_frame(b, CAP)
    b.close()


def test_oversized_outbound_rejected():
    with pytest.raises(rpc.FrameError, match="exceeds cap"):
        rpc.encode_frame({"blob": "x" * (CAP + 1)}, CAP)


def test_garbage_payload():
    raw = b"\xff\xfe\x00garbage"
    b = feed(struct.pack(">II", rpc.MAGIC, len(raw)) + raw)
    with pytest.raises(rpc.FrameError, match="undecodable"):
        rpc.recv_frame(b, CAP)
    b.close()


def test_non_object_payload():
    raw = b"[1,2,3]"
    b = feed(struct.pack(">II", rpc.MAGIC, len(raw)) + raw)
    with pytest.raises(rpc.FrameError, match="JSON object"):
        rpc.recv_frame(b, CAP)
    b.close()


# ------------------------------------------------------- fencing epochs


def test_check_epoch_accepts_current():
    rpc.check_epoch({"op": "tok", "e": 7}, 7)


def test_check_epoch_missing_is_structural():
    with pytest.raises(rpc.FrameError, match="missing fencing epoch"):
        rpc.check_epoch({"op": "tok"}, 7)


def test_check_epoch_stale_is_fencing():
    with pytest.raises(rpc.StaleEpochError) as ei:
        rpc.check_epoch({"op": "tok", "e": 6}, 7)
    assert ei.value.got == 6
    assert ei.value.want == 7


# ------------------------------------------------------- wire fault modes


def test_rpc_send_drop_discards_frame():
    a, b = pair()
    spec = faults.arm("rpc_send", mode="drop", times=1)
    rpc.send_frame(a, {"op": "tok", "t": 1, "e": 1}, CAP)  # dropped
    rpc.send_frame(a, {"op": "tok", "t": 2, "e": 1}, CAP)  # delivered
    assert spec.fired == 1
    assert rpc.recv_frame(b, CAP)["t"] == 2
    a.close()
    b.close()


def test_rpc_send_garble_hits_peer_framing_path():
    a, b = pair()
    faults.arm("rpc_send", mode="garble", times=1)
    rpc.send_frame(a, {"op": "tok", "t": 1, "e": 1}, CAP)
    with pytest.raises(rpc.FrameError):
        rpc.recv_frame(b, CAP)
    a.close()
    b.close()


def test_rpc_recv_drop_consumes_and_delivers_next():
    a, b = pair()
    rpc.send_frame(a, {"op": "tok", "t": 1, "e": 1}, CAP)
    rpc.send_frame(a, {"op": "tok", "t": 2, "e": 1}, CAP)
    spec = faults.arm("rpc_recv", mode="drop", times=1)
    # the dropped frame's bytes are consumed so framing stays intact
    assert rpc.recv_frame(b, CAP)["t"] == 2
    assert spec.fired == 1
    a.close()
    b.close()


def test_rpc_recv_garble_is_framing_violation():
    a, b = pair()
    rpc.send_frame(a, {"op": "tok", "t": 1, "e": 1}, CAP)
    faults.arm("rpc_recv", mode="garble", times=1)
    with pytest.raises(rpc.FrameError):
        rpc.recv_frame(b, CAP)
    a.close()
    b.close()


def test_wire_delay_delivers_after_sleep():
    a, b = pair()
    faults.arm("rpc_send", mode="delay", delay_s=0.01, times=1)
    rpc.send_frame(a, {"op": "tok", "t": 1, "e": 1}, CAP)
    assert rpc.recv_frame(b, CAP)["t"] == 1
    a.close()
    b.close()


# ---------------------------------------------------------- seeded fuzz


def _mutate(rng: random.Random, frame: bytes) -> bytes:
    """One random corruption of a valid frame: byte flips, truncation,
    garbage prefix/suffix, or a rewritten length field."""
    kind = rng.randrange(5)
    data = bytearray(frame)
    if kind == 0:  # flip 1-4 bytes anywhere (header included)
        for _ in range(rng.randint(1, 4)):
            i = rng.randrange(len(data))
            data[i] ^= rng.randint(1, 255)
        return bytes(data)
    if kind == 1:  # truncate
        return bytes(data[: rng.randrange(len(data))])
    if kind == 2:  # garbage prefix (desyncs the stream)
        return bytes(rng.randbytes(rng.randint(1, 16))) + bytes(data)
    if kind == 3:  # garbage suffix (trailing junk after a valid frame)
        return bytes(data) + bytes(rng.randbytes(rng.randint(1, 16)))
    # kind == 4: lie about the length
    length = rng.randrange(0, CAP * 2)
    struct.pack_into(">I", data, 4, length)
    return bytes(data)


def test_fuzz_reader_never_hangs():
    """200 seeded mutations of valid frames: the reader must terminate
    every time — with frames, clean EOF, or FrameError — and never
    socket.timeout (which would mean a hang against a closed writer)."""
    rng = random.Random(0x56471601)
    for i in range(200):
        frame = rpc.encode_frame(
            {
                "op": "tok",
                "sid": i,
                "e": rng.randrange(3),
                "pad": "x" * rng.randrange(64),
            },
            CAP,
        )
        b = feed(_mutate(rng, frame))
        try:
            # drain until EOF: trailing-junk mutations park extra bytes
            # after a valid first frame
            for _ in range(4):
                if rpc.recv_frame(b, CAP) is None:
                    break
        except rpc.FrameError:
            pass
        except socket.timeout:  # pragma: no cover - the failure mode
            pytest.fail(f"reader hung on mutation #{i}")
        finally:
            b.close()


def test_fuzz_wrong_epoch_frames_are_typed():
    """Well-formed frames with randomized epochs: structurally valid,
    so the reader delivers them and ONLY check_epoch complains."""
    rng = random.Random(0xE16)
    for _ in range(50):
        want = rng.randrange(1, 5)
        got = rng.randrange(0, 5)
        a, b = pair()
        rpc.send_frame(a, {"op": "tok", "t": 0, "e": got}, CAP)
        frame = rpc.recv_frame(b, CAP)
        if got == want:
            rpc.check_epoch(frame, want)
        else:
            with pytest.raises(rpc.StaleEpochError):
                rpc.check_epoch(frame, want)
        a.close()
        b.close()


def test_fuzz_concurrent_writer_teardown():
    """A writer that dies mid-frame (socket closed partway through a
    send) must yield FrameError or EOF, never a hang."""
    rng = random.Random(7)
    for _ in range(20):
        a, b = pair()
        frame = rpc.encode_frame({"op": "tok", "pad": "y" * 256, "e": 1}, CAP)
        cut = rng.randrange(1, len(frame))

        def write_and_die(sock=a, n=cut, data=frame):
            sock.sendall(data[:n])
            sock.close()

        t = threading.Thread(target=write_and_die)
        t.start()
        try:
            assert rpc.recv_frame(b, CAP) is None
        except rpc.FrameError:
            pass
        t.join()
        b.close()
