"""Real-checkpoint end-to-end: the safetensors FILE path (VERDICT r1 item 3).

The reference actually loads and serves real weights through vLLM
(vgate/backends/vllm_backend.py:26-37); these tests pin the equivalent
here — a tiny torch model is saved to disk as safetensors and must produce
identical results when served through the file-loading path:

* decoder checkpoint -> params_from_safetensors -> logit parity;
* EngineCore(checkpoint_path=...) serves a greedy completion identical to
  the in-memory-params engine;
* bge-family encoder checkpoint -> Embedder -> embedding parity vs torch;
* a local HF tokenizer fixture exercises the HFTokenizer branch.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.models.specs import TINY_DENSE, TINY_ENCODER
from vgate_tpu.runtime.weights import (
    params_from_safetensors,
    params_from_torch_state_dict,
)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")
safetensors_torch = pytest.importorskip("safetensors.torch")


def _save_checkpoint(model, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    state = {k: v.contiguous() for k, v in model.state_dict().items()}
    safetensors_torch.save_file(
        state, os.path.join(path, "model.safetensors")
    )


def _build_dense():
    config = transformers.Qwen2Config(
        vocab_size=TINY_DENSE.vocab_size,
        hidden_size=TINY_DENSE.hidden_size,
        num_hidden_layers=TINY_DENSE.num_layers,
        num_attention_heads=TINY_DENSE.num_heads,
        num_key_value_heads=TINY_DENSE.num_kv_heads,
        intermediate_size=TINY_DENSE.intermediate_size,
        rope_theta=TINY_DENSE.rope_theta,
        rms_norm_eps=TINY_DENSE.rms_eps,
        tie_word_embeddings=False,
        use_sliding_window=False,
    )
    torch.manual_seed(3)
    return transformers.Qwen2ForCausalLM(config).eval()


def test_safetensors_file_path_matches_state_dict(tmp_path):
    model = _build_dense()
    ckpt = str(tmp_path / "ckpt")
    _save_checkpoint(model, ckpt)

    from_file = params_from_safetensors(TINY_DENSE, ckpt, jnp.float32)
    from_mem = params_from_torch_state_dict(
        TINY_DENSE, model.state_dict(), jnp.float32
    )
    leaves_f, tree_f = jax.tree.flatten(from_file)
    leaves_m, tree_m = jax.tree.flatten(from_mem)
    assert tree_f == tree_m
    for lf, lm in zip(leaves_f, leaves_m):
        np.testing.assert_array_equal(np.asarray(lf), np.asarray(lm))
    # leaves stay on the host: the engine's shard_params does the single
    # device placement (no double-materialization in HBM)
    assert all(isinstance(l, np.ndarray) for l in leaves_f)


def _engine_config(ckpt=None, tokenizer=None):
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "checkpoint_path": ckpt,
            "tokenizer_path": tokenizer,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 4, "prefill_buckets": [8, 16, 32],
            "use_pallas": False,
        },
        logging={"level": "WARNING"},
    )


def test_engine_serves_completion_from_checkpoint(tmp_path):
    from vgate_tpu.runtime.engine_core import EngineCore

    model = _build_dense()
    ckpt = str(tmp_path / "ckpt")
    _save_checkpoint(model, ckpt)

    params = params_from_torch_state_dict(
        TINY_DENSE, model.state_dict(), jnp.float32
    )
    greedy = SamplingParams(max_tokens=8, temperature=0.0)
    prompt = [5, 9, 11, 20]

    core_file = EngineCore(
        _engine_config(ckpt=ckpt), devices=jax.devices()[:1]
    )
    core_file.start()
    try:
        seq = core_file.submit_tokens(prompt, greedy)
        assert seq.done_event.wait(timeout=300)
        file_tokens = list(seq.generated_ids)
    finally:
        core_file.stop()

    core_mem = EngineCore(
        _engine_config(), params=params, devices=jax.devices()[:1]
    )
    core_mem.start()
    try:
        seq = core_mem.submit_tokens(prompt, greedy)
        assert seq.done_event.wait(timeout=300)
        mem_tokens = list(seq.generated_ids)
    finally:
        core_mem.stop()

    assert file_tokens == mem_tokens
    assert len(file_tokens) == 8


def test_embedder_serves_real_checkpoint(tmp_path):
    from vgate_tpu.backends.jax_backend import Embedder

    spec = TINY_ENCODER
    config = transformers.BertConfig(
        vocab_size=spec.vocab_size,
        hidden_size=spec.hidden_size,
        num_hidden_layers=spec.num_layers,
        num_attention_heads=spec.num_heads,
        intermediate_size=spec.intermediate_size,
        max_position_embeddings=spec.max_position_embeddings,
        hidden_act="gelu",
    )
    torch.manual_seed(4)
    model = transformers.BertModel(config, add_pooling_layer=False).eval()
    ckpt = str(tmp_path / "bge")
    _save_checkpoint(model, ckpt)

    emb = Embedder("tiny-encoder", ckpt, jnp.float32)
    text = "hello tpu"
    [vec] = emb.embed([text])

    ids = emb.tokenizer.encode(text)
    full = [emb.tokenizer.bos_id] + ids + [emb.tokenizer.eos_id]
    with torch.no_grad():
        hf = model(
            input_ids=torch.tensor([full], dtype=torch.long),
            attention_mask=torch.ones(
                (1, len(full)), dtype=torch.long
            ),
        ).last_hidden_state[0, 0].float().numpy()
    hf = hf / max(np.linalg.norm(hf), 1e-9)
    np.testing.assert_allclose(np.asarray(vec), hf, rtol=2e-4, atol=2e-4)


def test_hf_tokenizer_local_fixture(tmp_path):
    """The HFTokenizer branch with a hermetic on-disk tokenizer (no
    network): WordLevel vocab saved as tokenizer.json."""
    tokenizers = pytest.importorskip("tokenizers")

    vocab = {"<unk>": 0, "<eos>": 1, "hello": 2, "tpu": 3, "world": 4}
    tok = tokenizers.Tokenizer(
        tokenizers.models.WordLevel(vocab, unk_token="<unk>")
    )
    tok.pre_tokenizer = tokenizers.pre_tokenizers.Whitespace()
    tok_dir = tmp_path / "tok"
    tok_dir.mkdir()
    tok.save(str(tok_dir / "tokenizer.json"))
    (tok_dir / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "eos_token": "<eos>",
        "unk_token": "<unk>",
    }))

    from vgate_tpu.runtime.tokenizer import HFTokenizer, get_tokenizer

    got = get_tokenizer(TINY_DENSE, str(tok_dir))
    assert isinstance(got, HFTokenizer)
    assert got.encode("hello tpu world") == [2, 3, 4]
    assert got.decode([2, 4]) == "hello world"
    assert got.eos_id == 1


async def test_embeddings_http_path_serves_checkpoint(tmp_path):
    """bge-parity through the FULL HTTP path (VERDICT r1 weak-8): a real
    encoder checkpoint behind POST /v1/embeddings returns the same vector
    the HF torch model computes."""
    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    spec = TINY_ENCODER
    config_hf = transformers.BertConfig(
        vocab_size=spec.vocab_size,
        hidden_size=spec.hidden_size,
        num_hidden_layers=spec.num_layers,
        num_attention_heads=spec.num_heads,
        intermediate_size=spec.intermediate_size,
        max_position_embeddings=spec.max_position_embeddings,
        hidden_act="gelu",
    )
    torch.manual_seed(6)
    bert = transformers.BertModel(
        config_hf, add_pooling_layer=False
    ).eval()
    ckpt = str(tmp_path / "bge")
    _save_checkpoint(bert, ckpt)

    config = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "embedding_model_id": "tiny-encoder",
            "embedding_checkpoint_path": ckpt,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 32, "kv_page_size": 4,
            "max_batch_slots": 2, "prefill_buckets": [8],
            "use_pallas": False,
        },
        logging={"level": "WARNING"},
    )
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/embeddings", json={"input": "hello tpu"}
        )
        assert resp.status == 200
        body = await resp.json()
        vec = np.asarray(body["data"][0]["embedding"], np.float32)

        from vgate_tpu.runtime.tokenizer import get_tokenizer

        tok = get_tokenizer(spec, ckpt)
        full = [tok.bos_id] + tok.encode("hello tpu") + [tok.eos_id]
        with torch.no_grad():
            hf = bert(
                input_ids=torch.tensor([full], dtype=torch.long),
                attention_mask=torch.ones(
                    (1, len(full)), dtype=torch.long
                ),
            ).last_hidden_state[0, 0].float().numpy()
        hf = hf / max(np.linalg.norm(hf), 1e-9)
        np.testing.assert_allclose(vec, hf, rtol=2e-4, atol=2e-4)
    finally:
        await client.close()


def test_hf_tokenizer_chat_template(tmp_path):
    """An HF tokenizer shipping a chat template renders /v1/chat prompts
    with it; tokenizers without one return None (gateway falls back to
    Role: content flattening)."""
    tokenizers = pytest.importorskip("tokenizers")

    vocab = {"<unk>": 0, "<eos>": 1, "hello": 2, "tpu": 3}
    tok = tokenizers.Tokenizer(
        tokenizers.models.WordLevel(vocab, unk_token="<unk>")
    )
    tok.pre_tokenizer = tokenizers.pre_tokenizers.Whitespace()
    tok_dir = tmp_path / "tok"
    tok_dir.mkdir()
    tok.save(str(tok_dir / "tokenizer.json"))
    (tok_dir / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "eos_token": "<eos>",
        "unk_token": "<unk>",
        "chat_template": (
            "{% for m in messages %}<|{{ m.role }}|>{{ m.content }}"
            "{% endfor %}<|assistant|>"
        ),
    }))

    from vgate_tpu.runtime.tokenizer import get_tokenizer

    got = get_tokenizer(TINY_DENSE, str(tok_dir))
    rendered = got.apply_chat_template(
        [
            {"role": "system", "content": "be brief"},
            {"role": "user", "content": "hello tpu"},
        ]
    )
    assert rendered == "<|system|>be brief<|user|>hello tpu<|assistant|>"

    # no template -> None (the gateway then flattens)
    (tok_dir / "tokenizer_config.json").write_text(json.dumps({
        "tokenizer_class": "PreTrainedTokenizerFast",
        "eos_token": "<eos>",
        "unk_token": "<unk>",
    }))
    got2 = get_tokenizer(TINY_DENSE, str(tok_dir))
    assert got2.apply_chat_template([{"role": "user", "content": "x"}]) is None
