"""Supervised engine recovery: deterministic fault-injection through the
real EngineCore on CPU (slow tier — engine compiles), covering the ISSUE 1
acceptance criteria:

* transient fault -> supervised restart, in-flight requests fail with the
  retryable 503 type, subsequent requests succeed in the same process;
* poison request -> quarantined, cannot re-crash the next incarnation;
* restart budget exhausted / unrecoverable fault -> DEAD;
* the gateway surfaces SERVING -> RECOVERING -> SERVING through /health
  under concurrent load;
* a chaos-marked randomized run stays live end-to-end.
"""

import asyncio
import threading
import time

import jax
import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu import faults
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.errors import (
    EngineDeadError,
    EngineRecoveringError,
    PoisonRequestError,
    RetryableError,
)
from vgate_tpu.runtime.supervisor import EngineSupervisor, HealthState


def rec_config(recovery=None, **tpu_overrides):
    tpu = {
        "dp": 1,
        "tp": 1,
        "ep": 1,
        "sp": 1,
        "kv_num_pages": 64,
        "kv_page_size": 4,
        "max_batch_slots": 4,
        "prefill_buckets": [8, 16, 32],
        "use_pallas": False,
    }
    tpu.update(tpu_overrides)
    rec = {
        "enabled": True,
        "max_restarts": 5,
        "restart_window_s": 120.0,
        "backoff_base_s": 0.02,
        "backoff_cap_s": 0.2,
        "degraded_probation_s": 0.25,
        "poison_threshold": 2,
        # this file pins the PR-1 FAIL-FAST contract (in-flight work
        # fails with the retryable 503 across a restart); the
        # checkpoint-&-replay default lives in tests/test_resume.py
        "resume_in_flight": False,
    }
    rec.update(recovery or {})
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu=tpu,
        scheduler={"max_queue_size": 16},
        recovery=rec,
        logging={"level": "ERROR"},
    )


def greedy(max_tokens=6):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0)


def wait_for(pred, timeout=90.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def generate_with_retry(sup, prompt, max_tokens=4, attempts=20):
    """Client-style retry loop against the supervisor: retryable errors
    back off briefly; anything else propagates."""
    for _ in range(attempts):
        try:
            return sup.generate([prompt], [greedy(max_tokens)])[0]
        except RetryableError:
            time.sleep(0.1)
    raise AssertionError(f"request never succeeded: {prompt!r}")


def test_transient_fault_restarts_and_serves_again():
    """Transient decode crash: the in-flight request fails with the
    retryable type, the supervisor restarts the core (weights kept), the
    state machine walks SERVING -> RECOVERING -> DEGRADED -> SERVING,
    and the next request succeeds WITHOUT a process restart."""
    sup = EngineSupervisor(rec_config(), devices=jax.devices()[:1])
    sup.start()
    try:
        assert sup.state is HealthState.SERVING
        [ok] = sup.generate(["warmup probe"], [greedy(4)])
        assert ok["num_tokens"] >= 1
        params_leaf_before = jax.tree.leaves(sup.core.params)[0]

        faults.arm("decode_step", mode="raise", kind="transient", times=1)
        seq = sup.submit_tokens([5, 9, 13, 17, 21], greedy(30))
        assert seq.done_event.wait(120)
        assert isinstance(seq.error, EngineRecoveringError)
        assert seq.error.retry_after >= 1.0

        assert wait_for(
            lambda: sup.state in (HealthState.DEGRADED, HealthState.SERVING)
        )
        assert sup.total_restarts == 1
        assert ("serving", "recovering") in sup.transitions
        assert ("recovering", "degraded") in sup.transitions
        # weights were KEPT across the restart (same device buffers)
        params_leaf_after = jax.tree.leaves(sup.core.params)[0]
        assert params_leaf_after is params_leaf_before

        result = generate_with_retry(sup, "after recovery")
        assert result["num_tokens"] >= 1
        # probation expires -> SERVING again
        assert wait_for(lambda: sup.state is HealthState.SERVING, 10)
        assert ("degraded", "serving") in sup.transitions
        assert sup.health()["state"] == "serving"
        assert sup.health()["restarts"] == 1
    finally:
        faults.reset()
        sup.stop()


def test_poison_request_is_quarantined():
    """A request whose prefill keeps crashing the engine is quarantined:
    the restarted incarnation rejects it at submission (400-type error)
    while other requests serve normally."""
    sup = EngineSupervisor(rec_config(), devices=jax.devices()[:1])
    sup.start()
    try:
        poison_ids = [3, 1, 666, 4]
        faults.arm(
            "prefill",
            mode="raise",
            kind="poison",
            times=-1,
            match=lambda ids: ids is not None and 666 in ids,
        )
        seq = sup.submit_tokens(poison_ids, greedy(4))
        assert seq.done_event.wait(120)
        assert isinstance(seq.error, EngineRecoveringError)
        assert wait_for(
            lambda: sup.state in (HealthState.DEGRADED, HealthState.SERVING)
        )
        assert sup.health()["quarantined"] == 1
        with pytest.raises(PoisonRequestError):
            sup.submit_tokens(poison_ids, greedy(4))
        # an innocent request is unaffected (and its prefill passes the
        # armed matcher without firing)
        result = generate_with_retry(sup, "innocent request")
        assert result["num_tokens"] >= 1
        # still only one incarnation lost
        assert sup.total_restarts == 1
    finally:
        faults.reset()
        sup.stop()


def test_repeat_offender_heuristic_quarantines():
    """Without an explicit poison marker, a request in flight across
    `poison_threshold` consecutive transient crashes gets quarantined."""
    sup = EngineSupervisor(
        rec_config(recovery={"poison_threshold": 2, "max_restarts": 10}),
        devices=jax.devices()[:1],
    )
    sup.start()
    try:
        bad_ids = [2, 4, 6, 8]
        for round_no in range(2):
            faults.arm(
                "decode_step", mode="raise", kind="transient", times=1
            )
            seq = sup.submit_tokens(bad_ids, greedy(20))
            assert seq.done_event.wait(120)
            assert seq.status.value == "failed"
            assert wait_for(
                lambda: sup.state
                in (HealthState.DEGRADED, HealthState.SERVING)
            )
        assert sup.health()["quarantined"] == 1
        with pytest.raises(PoisonRequestError):
            sup.submit_tokens(bad_ids, greedy(4))
    finally:
        faults.reset()
        sup.stop()


def test_restart_budget_exhausted_lands_dead():
    """Crashing on every incarnation exhausts the sliding-window restart
    budget: the state machine lands in DEAD, submissions raise the
    dead-engine type, and /health-style introspection reports it."""
    sup = EngineSupervisor(
        rec_config(
            recovery={
                "max_restarts": 1,
                "restart_window_s": 120.0,
                "poison_threshold": 99,  # isolate the budget path
            }
        ),
        devices=jax.devices()[:1],
    )
    sup.start()
    try:
        faults.arm("decode_step", mode="raise", kind="transient", times=-1)

        def poke(i):
            try:
                seq = sup.submit_tokens([7, i + 1, 3], greedy(10))
                seq.done_event.wait(60)
            except (EngineRecoveringError, EngineDeadError):
                pass

        poke(0)  # crash 1 -> restart (budget now full)
        assert wait_for(
            lambda: sup.state
            in (HealthState.DEGRADED, HealthState.SERVING, HealthState.DEAD)
        )
        deadline = time.monotonic() + 90
        while (
            sup.state is not HealthState.DEAD
            and time.monotonic() < deadline
        ):
            poke(1)  # crash 2 -> budget exhausted -> DEAD
            time.sleep(0.05)
        assert sup.state is HealthState.DEAD
        with pytest.raises(EngineDeadError):
            sup.submit_tokens([9, 9, 9], greedy(2))
        health = sup.health()
        assert health["state"] == "dead"
        assert health["alive"] is False
        assert health["ready"] is False
    finally:
        faults.reset()
        sup.stop()


def test_unrecoverable_fault_goes_straight_to_dead():
    sup = EngineSupervisor(rec_config(), devices=jax.devices()[:1])
    sup.start()
    try:
        faults.arm(
            "decode_step", mode="raise", kind="unrecoverable", times=1
        )
        seq = sup.submit_tokens([1, 2, 3, 4], greedy(10))
        assert seq.done_event.wait(120)
        assert wait_for(lambda: sup.state is HealthState.DEAD, 30)
        assert sup.total_restarts == 0
        assert ("recovering", "dead") in sup.transitions
    finally:
        faults.reset()
        sup.stop()


def test_weight_load_fault_fails_first_construction():
    """weight_load faults hit initial construction (there is nothing to
    recover *to* yet): the error propagates to the caller."""
    faults.arm("weight_load", mode="raise", times=1)
    with pytest.raises(faults.InjectedFault):
        EngineSupervisor(rec_config(), devices=jax.devices()[:1])
    faults.reset()


# ----------------------------------------------------------------- gateway


async def _gateway_client(**recovery):
    config_kwargs = dict(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 128, "kv_page_size": 4,
            "max_batch_slots": 4, "prefill_buckets": [16, 32],
            "use_pallas": False,
        },
        scheduler={"max_queue_size": 32},
        recovery={
            "enabled": True,
            "max_restarts": 8,
            "restart_window_s": 120.0,
            "backoff_base_s": 0.02,
            "backoff_cap_s": 0.2,
            "degraded_probation_s": 0.2,
            "poison_threshold": 99,
            # fail-fast contract (see rec_config above); the resume
            # path's gateway behavior is scripts/resume_check.sh
            "resume_in_flight": False,
            **recovery,
        },
        batch={"max_batch_size": 4, "max_wait_time_ms": 5.0},
        cache={"enabled": False},
        logging={"level": "ERROR"},
    )
    from vgate_tpu.server.app import create_app

    config = load_config(**config_kwargs)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    return client


async def test_gateway_recovers_under_concurrent_load():
    """ISSUE 1 acceptance: with a transient decode fault armed, concurrent
    load sees the engine restart; /health transits SERVING -> RECOVERING
    -> SERVING; in-flight requests fail with a retryable 503 carrying
    Retry-After; subsequent requests succeed without a process restart."""
    client = await _gateway_client()
    try:
        body = await (await client.get("/health")).json()
        assert body["engine"]["state"] == "serving"

        faults.arm("decode_step", mode="raise", kind="transient", times=1)

        async def fire(i):
            resp = await client.post(
                "/v1/chat/completions",
                json={
                    "messages": [
                        {"role": "user", "content": f"crash probe {i}"}
                    ],
                    "max_tokens": 24,
                    "min_tokens": 24,
                    "temperature": 0.0,
                },
            )
            return resp.status, dict(resp.headers), await resp.json()

        results = await asyncio.gather(*(fire(i) for i in range(6)))
        shed = [r for r in results if r[0] == 503]
        assert shed, "the armed fault should have failed in-flight work"
        for status, headers, body in results:
            assert status in (200, 503)
            if status == 503:
                assert int(headers["Retry-After"]) >= 1
                assert body["error"]["type"] == "overloaded_error"

        # readiness dips while recovering, then returns; the state
        # machine's walk is recorded in /stats
        async def ready():
            return (await client.get("/health/ready")).status == 200

        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if await ready():
                break
            await asyncio.sleep(0.05)
        assert await ready()

        status, headers, body = await fire(99)
        assert status == 200
        assert body["usage"]["completion_tokens"] == 24

        stats = await (await client.get("/stats")).json()
        sup = stats["engine"]["supervisor"]
        assert sup["restarts"] >= 1
        transitions = [tuple(t) for t in sup["transitions"]]
        assert ("serving", "recovering") in transitions
        assert ("recovering", "degraded") in transitions
        # liveness stayed green the whole time (the pod was never
        # recycled: recovery happened in-process)
        assert (await client.get("/health/live")).status == 200
    finally:
        faults.reset()
        await client.close()


# ------------------------------------------------------------------- chaos


@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_randomized_faults_under_concurrent_load():
    """Chaos mode: randomized raise/delay injections at several points
    under concurrent threaded load.  Invariants: no request hangs (every
    submission resolves or raises), the supervisor never wedges in
    RECOVERING, and serving works after the storm."""
    sup = EngineSupervisor(
        rec_config(
            recovery={
                "max_restarts": 50,
                "restart_window_s": 5.0,
                "backoff_base_s": 0.01,
                "backoff_cap_s": 0.05,
                "poison_threshold": 1000,  # innocents stay admitted
            }
        ),
        devices=jax.devices()[:1],
    )
    sup.start()
    outcomes = []
    lock = threading.Lock()
    try:
        faults.arm(
            "decode_step", mode="raise", kind="transient",
            times=-1, probability=0.08, seed=11,
        )
        faults.arm(
            "prefill", mode="raise", kind="transient",
            times=-1, probability=0.04, seed=13,
        )
        faults.arm(
            "kv_alloc", mode="delay", delay_s=0.002,
            times=-1, probability=0.3, seed=17,
        )

        def worker(i):
            for j in range(4):
                try:
                    seq = sup.submit_tokens(
                        [i + 1, j + 1, (i * 7 + j) % 50 + 1], greedy(6)
                    )
                    finished = seq.done_event.wait(120)
                except (RetryableError, PoisonRequestError) as exc:
                    with lock:
                        outcomes.append(("shed", type(exc).__name__))
                    time.sleep(0.05)
                    continue
                with lock:
                    outcomes.append(
                        ("done" if finished else "hang", seq.status.value)
                    )

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert all(not t.is_alive() for t in threads), "worker hung"
        assert outcomes
        assert not [o for o in outcomes if o[0] == "hang"]

        faults.reset()
        assert wait_for(
            lambda: sup.state
            in (HealthState.SERVING, HealthState.DEGRADED, HealthState.DEAD),
            60,
        )
        if sup.state is not HealthState.DEAD:
            result = generate_with_retry(sup, "after the storm")
            assert result["num_tokens"] >= 1
    finally:
        faults.reset()
        sup.stop()
