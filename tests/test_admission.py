"""Overload protection (ISSUE 4): token-budget admission control,
priority tiers, and the adaptive brownout controller.

Fast tier: unit tests for AdmissionController boundaries (backlog,
would-miss-SLO, KV watermark, per-key cap), TierQueue weighted
dequeue, PressureController hysteresis (fake clock), scheduler
priority admission/preemption, and gateway-level 503/429 + Retry-After
mapping on the dry-run backend.  Slow tier: the synthetic flood —
tier-ordered latency, shed order, bounded backlog, zero 500s.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu import faults
from vgate_tpu.admission import (
    AdmissionController,
    PressureController,
    TierQueue,
    estimate_prompt_tokens,
    tier_rank,
)
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import AdmissionConfig, load_config
from vgate_tpu.errors import (
    ClientQuotaExceededError,
    ServerOverloadedError,
)
from vgate_tpu.runtime.kv_cache import PageAllocator
from vgate_tpu.runtime.scheduler import Scheduler
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.server.app import create_app


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_controller(signals=None, clock=None, **overrides):
    cfg = AdmissionConfig(**overrides)
    return AdmissionController(
        cfg,
        signals=signals,
        clock=clock or FakeClock(),
    )


# ---------------------------------------------------------- admission


def test_backlog_token_boundary():
    ctl = make_controller(
        max_queued_tokens=100,
        max_queued_requests=0,
        tier_fractions={"interactive": 1.0, "standard": 1.0, "batch": 1.0},
    )
    ctl.admit(60)
    with pytest.raises(ServerOverloadedError) as exc:
        ctl.admit(50)
    assert exc.value.shed_reason == "backlog_tokens"
    assert exc.value.reason == "overloaded"  # the 503 body flavor
    ctl.admit(40)  # exactly at the limit is still admitted
    ctl.release(60)
    ctl.admit(50)  # released budget re-opens the door


def test_backlog_request_boundary():
    ctl = make_controller(
        max_queued_tokens=0,
        max_queued_requests=2,
        tier_fractions={"interactive": 1.0, "standard": 1.0, "batch": 1.0},
    )
    ctl.admit(1)
    ctl.admit(1)
    with pytest.raises(ServerOverloadedError) as exc:
        ctl.admit(1)
    assert exc.value.shed_reason == "backlog_requests"
    ctl.release(1)
    ctl.admit(1)


def test_would_miss_slo_rejected_at_the_door():
    ctl = make_controller(
        max_queued_tokens=0, max_queued_requests=0,
        throughput_init_tps=100.0,
    )
    ctl.admit(1000)  # predicted wait is now 10s
    with pytest.raises(ServerOverloadedError) as exc:
        ctl.admit(10, deadline_s=5.0)
    assert exc.value.shed_reason == "would_miss_slo"
    assert exc.value.retry_after >= 1.0
    ctl.admit(10, deadline_s=20.0)  # enough headroom is admitted
    ctl.admit(10)  # no deadline -> the check never applies


def test_kv_watermark_sheds_batch_before_interactive():
    sig = {"kv_free_ratio": 0.07}
    ctl = make_controller(
        signals=lambda: sig,
        max_queued_tokens=0, max_queued_requests=0,
        kv_free_watermark=0.05,
    )
    # default fractions: batch rejects below 0.05/0.6 = 0.083,
    # standard below 0.059, interactive below 0.05
    with pytest.raises(ServerOverloadedError) as exc:
        ctl.admit(10, tier="batch")
    assert exc.value.shed_reason == "kv_pressure"
    assert exc.value.tier == "batch"
    ctl.admit(10, tier="standard")
    ctl.admit(10, tier="interactive")
    sig["kv_free_ratio"] = 0.02  # below every threshold
    with pytest.raises(ServerOverloadedError):
        ctl.admit(10, tier="interactive")


def test_tier_fractions_shed_batch_first_on_backlog():
    ctl = make_controller(max_queued_tokens=100, max_queued_requests=0)
    ctl.admit(70, tier="interactive")
    # batch sees 100 * 0.6 = 60 -> already over; interactive has room
    with pytest.raises(ServerOverloadedError):
        ctl.admit(10, tier="batch")
    ctl.admit(10, tier="interactive")


def test_per_key_inflight_cap():
    ctl = make_controller(
        per_key_max_inflight=1,
        max_queued_tokens=0, max_queued_requests=0,
    )
    rel1 = ctl.acquire_inflight("k1")
    with pytest.raises(ClientQuotaExceededError):
        ctl.acquire_inflight("k1")
    rel2 = ctl.acquire_inflight("k2")  # other keys unaffected
    ctl.acquire_inflight(None)  # keyless traffic is never capped
    rel1()
    ctl.acquire_inflight("k1")
    # the per-key map must not leak emptied entries
    rel2()
    assert "k2" not in ctl._inflight_by_key
    # capacity admission never touches the per-key map, and a per-key
    # rejection never pollutes the shed-rate EWMA the brownout reads
    ctl.admit(10)
    assert ctl._inflight_by_key.get("k1") == 1
    assert ctl.shed_rate() == 0.0


def test_acquire_inflight_slot_release_idempotent():
    ctl = make_controller(
        per_key_max_inflight=1,
        max_queued_tokens=0, max_queued_requests=0,
    )
    release = ctl.acquire_inflight("k1")
    with pytest.raises(ClientQuotaExceededError):
        ctl.acquire_inflight("k1")
    release()
    release()  # double release must not go negative
    ctl.acquire_inflight("k1")


def test_resolve_tier_field_key_and_cap():
    ctl = make_controller(key_tiers={"kb": "batch", "ki": "interactive"})
    assert ctl.resolve_tier(None, None) == "standard"
    assert ctl.resolve_tier("interactive", None) == "interactive"
    assert ctl.resolve_tier(None, "kb") == "batch"
    # the key's tier CAPS the request's claim...
    assert ctl.resolve_tier("interactive", "kb") == "batch"
    # ...but a request may still downgrade itself
    assert ctl.resolve_tier("batch", "ki") == "batch"
    assert ctl.resolve_tier(None, "unmapped-key") == "standard"


def test_disabled_controller_admits_but_still_accounts():
    ctl = make_controller(enabled=False, max_queued_tokens=1)
    ctl.admit(500)
    ctl.admit(500)
    assert ctl.get_stats()["queued_tokens"] == 1000
    ctl.release(500)
    assert ctl.get_stats()["queued_tokens"] == 500


def test_throughput_ewma_follows_completions():
    clock = FakeClock()
    ctl = make_controller(
        clock=clock, throughput_init_tps=100.0, throughput_alpha=0.5
    )
    clock.advance(2.0)
    ctl.observe_completion(1000)  # 500 tok/s window
    stats = ctl.get_stats()
    assert stats["throughput_tps"] == pytest.approx(300.0)  # 0.5 mix


def test_throughput_ewma_ignores_idle_time():
    """Regression: a trickle workload (long idle between completions)
    must not drag the capacity estimate toward offered load — stale
    windows are discarded and the window re-anchors on the idle->busy
    edge."""
    clock = FakeClock()
    ctl = make_controller(
        clock=clock, throughput_init_tps=400.0, throughput_alpha=0.5,
        max_queued_tokens=0, max_queued_requests=0,
    )
    for _ in range(5):
        clock.advance(60.0)  # a minute idle
        ctl.admit(100)       # idle->busy edge re-anchors the window
        clock.advance(2.0)
        ctl.release(100)
        ctl.observe_completion(100)  # 50 tok/s over the BUSY window
    # samples reflect the 2s busy windows (50 tps), never 100/62s
    assert ctl.get_stats()["throughput_tps"] > 49.0


def test_estimate_prompt_tokens():
    assert estimate_prompt_tokens("") == 1
    assert estimate_prompt_tokens("x" * 400) == 100


# ---------------------------------------------------------- tier queue


class _Req:
    def __init__(self, tier, i):
        self.tier_rank = tier_rank(tier)
        self.i = i

    def __repr__(self):
        return f"{self.tier_rank}:{self.i}"


def test_tier_queue_weighted_take():
    q = TierQueue(weights={"interactive": 2, "standard": 1, "batch": 1})
    for i in range(4):
        q.append(_Req("interactive", i))
    for i in range(2):
        q.append(_Req("standard", i))
    for i in range(2):
        q.append(_Req("batch", i))
    assert len(q) == 8
    got = q.take(4)
    # one fill cycle: 2 interactive, 1 standard, 1 batch
    assert [r.tier_rank for r in got] == [0, 0, 1, 2]
    # next cycle drains the remaining interactive first
    got = q.take(4)
    assert [r.tier_rank for r in got] == [0, 0, 1, 2]
    assert not q


def test_tier_queue_no_starvation_at_default_weights():
    """Regression: interactive weight >= the batch size must not fill
    every cycle alone — lower tiers keep a reserved trickle."""
    q = TierQueue(weights={"interactive": 8, "standard": 4, "batch": 1})
    for i in range(32):
        q.append(_Req("interactive", i))
    q.append(_Req("standard", 0))
    q.append(_Req("batch", 0))
    got = q.take(8)
    ranks = [r.tier_rank for r in got]
    assert ranks.count(0) == 6 and 1 in ranks and 2 in ranks, ranks
    # once the lower tiers drain, interactive fills whole batches again
    assert [r.tier_rank for r in q.take(8)] == [0] * 8


def test_tier_queue_rotates_when_batch_smaller_than_tiers():
    """Regression: a batch size smaller than the number of non-empty
    tiers must rotate service across calls, not re-starve the tail
    tier on every fill cycle."""
    q = TierQueue(weights={"interactive": 8, "standard": 4, "batch": 1})
    for i in range(10):
        q.append(_Req("interactive", i))
        q.append(_Req("standard", i))
        q.append(_Req("batch", i))
    served = []
    for _ in range(6):
        served.extend(r.tier_rank for r in q.take(2))
    assert 2 in served, f"batch starved across 6 tiny batches: {served}"
    assert 1 in served and 0 in served


def test_tier_queue_list_protocol_and_drain_order():
    q = TierQueue()
    a, b, c = _Req("batch", 0), _Req("interactive", 1), _Req("standard", 2)
    for r in (a, b, c):
        q.append(r)
    assert a in q and len(q) == 3
    assert q.depths() == {"interactive": 1, "standard": 1, "batch": 1}
    q.remove(a)
    assert a not in q
    q.append(a)
    assert [r.i for r in q.drain()] == [1, 2, 0]  # tier order
    assert len(q) == 0 and not q


# ------------------------------------------------------------ brownout


def make_pressure(sig, clock, **overrides):
    overrides.setdefault("brownout_update_interval_s", 0.0)
    overrides.setdefault("brownout_hold_s", 10.0)
    cfg = AdmissionConfig(**overrides)
    adm = AdmissionController(cfg, signals=lambda: sig, clock=clock)
    return PressureController(
        cfg, adm, signals=lambda: sig, clock=clock
    )


def test_brownout_engages_immediately_and_releases_with_hysteresis():
    clock = FakeClock()
    sig = {"kv_free_ratio": 1.0}
    pc = make_pressure(sig, clock)
    pc.maybe_update()
    assert pc.level == 0
    # KV collapse: score (2*wm - free)/wm = 2.0 -> straight to level 4
    sig["kv_free_ratio"] = 0.0
    clock.advance(1.0)
    pc.maybe_update()
    assert pc.level == 4
    assert pc.active_steps() == [
        "clamp_max_tokens", "shrink_batch_window",
        "disable_speculative", "bypass_cache_writes",
    ]
    # pressure gone — but the level holds until hold_s elapses below
    # the release threshold, then steps down ONE level per hold period
    sig["kv_free_ratio"] = 1.0
    clock.advance(1.0)
    pc.maybe_update()
    assert pc.level == 4
    clock.advance(5.0)
    pc.maybe_update()
    assert pc.level == 4  # only 5s below; hold is 10s
    clock.advance(6.0)
    pc.maybe_update()
    assert pc.level == 3
    for _ in range(3):
        clock.advance(11.0)
        pc.maybe_update()
    assert pc.level == 0


def test_brownout_flap_resistance():
    clock = FakeClock()
    sig = {"kv_free_ratio": 0.0}
    pc = make_pressure(sig, clock)
    pc.maybe_update()
    assert pc.level == 4
    # score oscillating ABOVE the release threshold never releases
    for free in (0.04, 0.05, 0.04, 0.05, 0.04):
        sig["kv_free_ratio"] = free
        clock.advance(20.0)
        pc.maybe_update()
        assert pc.level == 4


def test_brownout_degradation_knobs():
    clock = FakeClock()
    sig = {"kv_free_ratio": 1.0}
    pc = make_pressure(
        sig, clock, brownout_max_tokens=128, brownout_wait_ms=10.0
    )
    assert pc.clamp_max_tokens(512) == 512
    assert pc.effective_wait_ms(50.0) == 50.0
    assert not pc.spec_disabled and not pc.cache_write_bypass
    sig["kv_free_ratio"] = 0.0
    clock.advance(1.0)
    pc.maybe_update()
    assert pc.clamp_max_tokens(512) == 128
    assert pc.effective_wait_ms(50.0) == 10.0
    assert pc.spec_disabled and pc.cache_write_bypass
    brief = pc.brief()
    assert brief["level"] == 4 and brief["steps"]


def test_brownout_transition_hook_fires():
    clock = FakeClock()
    sig = {"kv_free_ratio": 0.0}
    seen = []
    cfg = AdmissionConfig(brownout_update_interval_s=0.0)
    adm = AdmissionController(cfg, signals=lambda: sig, clock=clock)
    pc = PressureController(
        cfg, adm, signals=lambda: sig, clock=clock,
        on_transition=lambda **kw: seen.append(kw),
    )
    pc.maybe_update()
    assert seen and seen[0]["level"] == 4 and seen[0]["prev"] == 0


# -------------------------------------------- scheduler priority tiers


def _seq(n_prompt=4, priority=1, max_tokens=8):
    return Sequence(
        prompt_ids=list(range(2, 2 + n_prompt)),
        params=SamplingParams(max_tokens=max_tokens, priority=priority),
    )


def _sched(num_pages=32, slots=4):
    alloc = PageAllocator(num_pages)
    return Scheduler(
        allocator=alloc,
        max_slots=slots,
        page_size=4,
        prefill_buckets=[8, 16],
        max_model_len=64,
        max_queue_size=16,
    ), alloc


def test_scheduler_admits_higher_tier_first():
    sched, _ = _sched(slots=1)
    batch = _seq(priority=2)
    interactive = _seq(priority=0)
    sched.add(batch)  # batch arrived FIRST
    sched.add(interactive)
    plan = sched.try_admit()
    assert plan is not None and plan.seq is interactive
    # slot now occupied; batch stays queued
    assert list(sched.waiting) == [batch]


def test_scheduler_fifo_within_tier():
    sched, _ = _sched(slots=2)
    first = _seq(priority=1)
    second = _seq(priority=1)
    sched.add(first)
    sched.add(second)
    assert sched.try_admit().seq is first
    assert sched.try_admit().seq is second


def test_scheduler_preempts_lowest_tier_first():
    # two resident sequences; pages exhausted -> the BATCH one is the
    # victim even though the interactive one is younger
    sched, alloc = _sched(num_pages=5, slots=2)  # 4 usable pages
    batch = _seq(n_prompt=8, priority=2)  # 2 pages
    sched.add(batch)
    assert sched.try_admit().seq is batch
    interactive = _seq(n_prompt=8, priority=0)  # 2 pages, younger
    sched.add(interactive)
    assert sched.try_admit().seq is interactive
    assert alloc.num_free == 0
    for seq in (batch, interactive):
        for t in range(5):
            seq.append_token(100 + t)  # fill to a page boundary
    assert sched.prepare_decode(sched.running, horizon=4)
    assert batch.status is SeqStatus.WAITING  # preempted
    assert interactive.status is SeqStatus.RUNNING


def test_scheduler_reaps_aborted_behind_bypassed_head():
    """Regression: with priority selection admitting AROUND the head,
    an aborted sequence parked behind a bypassed lower-tier head must
    still settle (head-only reaping would leak it — and the gateway's
    admission backlog charge — forever)."""
    sched, _ = _sched(slots=1)
    head_batch = _seq(priority=2)
    aborted = _seq(priority=1)
    interactive = _seq(priority=0)
    for s in (head_batch, aborted, interactive):
        sched.add(s)
    aborted.request_abort()
    plan = sched.try_admit()  # admits interactive AROUND the head
    assert plan is not None and plan.seq is interactive
    # the aborted mid-queue sequence settled, not just got skipped
    assert aborted.status is SeqStatus.FINISHED
    assert aborted.finish_reason == "abort"
    assert list(sched.waiting) == [head_batch]
    assert sched.total_aborted == 1


# ------------------------------------------------------------- gateway


async def _client(**overrides):
    overrides.setdefault("model", {"engine_type": "dry_run"})
    overrides.setdefault(
        "batch", {"max_batch_size": 8, "max_wait_time_ms": 10.0}
    )
    overrides.setdefault("logging", {"level": "WARNING"})
    config = load_config(**overrides)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    return client


def _body(i=0, **extra):
    return {
        "messages": [{"role": "user", "content": f"overload probe {i}"}],
        "max_tokens": 8,
        "temperature": 0.0,
        **extra,
    }


async def test_overload_503_with_retry_after_and_reason():
    faults.arm("backend_generate", mode="delay", delay_s=0.4, times=-1)
    client = await _client(
        admission={
            "max_queued_requests": 1,
            "tier_fractions": {
                "interactive": 1.0, "standard": 1.0, "batch": 1.0,
            },
        },
    )
    try:
        # distinct prompts so nothing dedups/caches; the first occupies
        # the single admission slot behind the armed 400ms delay
        tasks = [
            asyncio.ensure_future(
                client.post("/v1/chat/completions", json=_body(i))
            )
            for i in range(3)
        ]
        resps = await asyncio.gather(*tasks)
        statuses = sorted(r.status for r in resps)
        assert statuses[0] == 200 and statuses[-1] == 503, statuses
        for r in resps:
            if r.status == 503:
                assert "Retry-After" in r.headers
                body = await r.json()
                assert body["error"]["reason"] == "overloaded"
                assert body["error"]["type"] == "overloaded_error"
    finally:
        faults.reset()
        await client.close()


async def test_per_key_cap_429_with_retry_after():
    faults.arm("backend_generate", mode="delay", delay_s=0.4, times=-1)
    client = await _client(admission={"per_key_max_inflight": 1})
    try:
        headers = {"Authorization": "Bearer key-a"}
        tasks = [
            asyncio.ensure_future(
                client.post(
                    "/v1/chat/completions",
                    json=_body(i),
                    headers=headers,
                )
            )
            for i in range(2)
        ]
        # a different key is not affected by key-a's cap
        other = asyncio.ensure_future(
            client.post(
                "/v1/chat/completions",
                json=_body(9),
                headers={"Authorization": "Bearer key-b"},
            )
        )
        resps = await asyncio.gather(*tasks)
        statuses = sorted(r.status for r in resps)
        assert statuses == [200, 429], statuses
        for r in resps:
            if r.status == 429:
                assert "Retry-After" in r.headers
                body = await r.json()
                assert body["error"]["type"] == "rate_limit_error"
        assert (await other).status == 200
        # regression: the cap charges the CLIENT request once — an n=3
        # fan-out under cap 1 is one slot, not three (must be 200)
        resp = await client.post(
            "/v1/chat/completions",
            json=_body("fanout", n=3, temperature=0.7, seed=7),
            headers=headers,
        )
        assert resp.status == 200, await resp.text()
    finally:
        faults.reset()
        await client.close()


async def test_priority_field_validated_and_accepted():
    client = await _client()
    try:
        resp = await client.post(
            "/v1/chat/completions", json=_body(priority="bogus")
        )
        assert resp.status == 422
        resp = await client.post(
            "/v1/chat/completions", json=_body(priority="interactive")
        )
        assert resp.status == 200
    finally:
        await client.close()


async def test_key_tier_mapping_caps_batch_key(monkeypatch):
    # a batch-mapped key is shed at the batch thresholds even when it
    # claims interactive
    sig = {"kv_free_ratio": 0.07}
    client = await _client(
        admission={
            "key_tiers": {"cheap-key": "batch"},
            "kv_free_watermark": 0.05,
        },
    )
    try:
        batcher = client.server.app["batcher"]
        monkeypatch.setattr(
            batcher.admission, "_signals", lambda: sig
        )
        resp = await client.post(
            "/v1/chat/completions",
            json=_body(priority="interactive"),
            headers={"Authorization": "Bearer cheap-key"},
        )
        assert resp.status == 503
        assert (await resp.json())["error"]["reason"] == "overloaded"
        # an unmapped key at the same KV level sails through
        resp = await client.post(
            "/v1/chat/completions",
            json=_body(1, priority="interactive"),
            headers={"Authorization": "Bearer other-key"},
        )
        assert resp.status == 200
    finally:
        await client.close()


async def test_health_and_stats_surface_pressure():
    client = await _client()
    try:
        resp = await client.get("/health")
        body = await resp.json()
        assert body["pressure"]["level"] == 0
        assert body["pressure"]["steps"] == []
        await client.post("/v1/chat/completions", json=_body())
        resp = await client.get("/stats")
        stats = await resp.json()
        adm = stats["admission"]
        assert adm["enabled"] is True
        assert adm["admitted"] >= 1
        assert "pressure" in adm and "queue_depths" in adm
        assert set(adm["queue_depths"]) == {
            "interactive", "standard", "batch",
        }
    finally:
        await client.close()


async def test_cache_hit_needs_no_admission_budget():
    client = await _client(
        admission={"max_queued_requests": 4},
    )
    try:
        body = _body()
        assert (
            await client.post("/v1/chat/completions", json=body)
        ).status == 200
        # exhaust the admission budget entirely...
        batcher = client.server.app["batcher"]
        for _ in range(10):
            batcher.admission._queued_requests = 99
        # ...a cache-servable repeat still answers
        resp = await client.post("/v1/chat/completions", json=body)
        assert resp.status == 200
        assert (await resp.json())["cached"] is True
    finally:
        await client.close()


# ------------------------------------------------------ synthetic flood


@pytest.mark.slow
async def test_flood_tier_latency_ordering():
    """10x flood, admission unlimited: weighted dequeue alone must give
    interactive lower completion latency than batch."""
    faults.arm("backend_generate", mode="delay", delay_s=0.05, times=-1)
    client = await _client(
        batch={"max_batch_size": 8, "max_wait_time_ms": 10.0},
        admission={"max_queued_tokens": 0, "max_queued_requests": 0},
    )
    try:
        import time as _time

        async def fire(i, tier):
            t0 = _time.perf_counter()
            resp = await client.post(
                "/v1/chat/completions",
                json=_body(f"{tier}-{i}", priority=tier),
            )
            await resp.read()
            return resp.status, _time.perf_counter() - t0

        tiers = ["interactive", "batch"]
        results = await asyncio.gather(
            *[
                fire(i, tiers[i % 2])
                for i in range(32)
            ]
        )
        inter = [d for i, (s, d) in enumerate(results) if i % 2 == 0]
        batch = [d for i, (s, d) in enumerate(results) if i % 2 == 1]
        assert all(s == 200 for s, _ in results)
        inter_p99 = sorted(inter)[int(len(inter) * 0.99) - 1]
        batch_p99 = sorted(batch)[int(len(batch) * 0.99) - 1]
        assert inter_p99 < batch_p99, (inter_p99, batch_p99)
    finally:
        faults.reset()
        await client.close()


@pytest.mark.slow
async def test_flood_shed_order_and_bounded_backlog():
    """10x flood against tight budgets: batch sheds before interactive,
    the queued-token backlog stays bounded, zero 500s, and every
    request gets an answer."""
    faults.arm("backend_generate", mode="delay", delay_s=0.05, times=-1)
    max_tokens_budget = 300
    client = await _client(
        # batch size 8 keeps interactive dominant in the weighted
        # dequeue (tiny batches flatten the weights toward round-robin
        # via the per-tier reserve; that path is unit-tested above)
        batch={"max_batch_size": 8, "max_wait_time_ms": 10.0},
        admission={
            "max_queued_tokens": max_tokens_budget,
            "max_queued_requests": 0,
        },
    )
    try:
        peak = {"tokens": 0}

        async def watch():
            while True:
                stats = await (await client.get("/stats")).json()
                peak["tokens"] = max(
                    peak["tokens"],
                    stats["admission"]["queued_tokens"],
                )
                await asyncio.sleep(0.02)

        watcher = asyncio.ensure_future(watch())

        async def fire(i, tier):
            resp = await client.post(
                "/v1/chat/completions",
                json=_body(f"{tier}-{i}", priority=tier),
            )
            await resp.read()
            return tier, resp.status

        results = await asyncio.gather(
            *[
                fire(i, tier)
                for tier in ("interactive", "standard", "batch")
                for i in range(20)
            ]
        )
        watcher.cancel()
        by_tier = {"interactive": [], "standard": [], "batch": []}
        for tier, status in results:
            by_tier[tier].append(status)
        assert all(
            s in (200, 503) for ss in by_tier.values() for s in ss
        ), by_tier
        shed = {
            t: sum(1 for s in ss if s == 503)
            for t, ss in by_tier.items()
        }
        # strict-priority shedding: batch first, interactive last
        assert shed["batch"] >= shed["standard"] >= shed["interactive"]
        assert shed["batch"] > 0
        # bounded backlog: the interactive tier's full budget is the cap
        assert peak["tokens"] <= max_tokens_budget
        # the server is still healthy afterwards
        assert (await client.get("/health/ready")).status == 200
    finally:
        faults.reset()
        await client.close()
