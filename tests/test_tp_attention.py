"""Tensor-parallel Pallas-kernel wrappers (parallel/tp_attention.py) vs
single-device oracles, interpret-mode kernels on the virtual CPU mesh.

The hazard under test: a pallas_call has no GSPMD partition rule, so
under a tp-sharded jit it would be replicated (all-gathering the KV
pool); the wrappers run it per shard with the head dims split.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.config import load_config
from vgate_tpu.parallel.mesh import build_mesh


def tp_mesh(tp):
    return build_mesh(
        load_config(
            tpu={"dp": 1, "ep": 1, "sp": 1, "tp": tp, "num_devices": tp}
        ).tpu,
        devices=jax.devices()[:tp],
    )


@pytest.mark.parametrize("tp", [2, 4])
def test_tp_decode_wrapper_matches_oracle(tp):
    from vgate_tpu.ops.attention import paged_decode_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
    )
    from vgate_tpu.parallel.tp_attention import (
        tp_divisible,
        tp_paged_decode_attention,
    )

    if jax.device_count() < tp:
        pytest.skip("needs devices")
    rng = np.random.default_rng(tp)
    B, H, KV, hd, ps, pages_per_seq = 3, 8, 4, 128, 16, 4
    P_ = 1 + B * pages_per_seq
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    k_pages = jnp.asarray(
        rng.normal(size=(KV, P_, ps, hd)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.normal(size=(KV, P_, ps, hd)), jnp.float32
    )
    pt = jnp.asarray(
        rng.permutation(np.arange(1, P_))[: B * pages_per_seq].reshape(
            B, pages_per_seq
        ),
        jnp.int32,
    )
    seq_lens = jnp.asarray([5, 33, 64], jnp.int32)
    mesh = tp_mesh(tp)
    assert tp_divisible(mesh, H, KV)

    expect = paged_decode_attention(
        q, k_pages, v_pages, pt, seq_lens
    )
    kernel = functools.partial(
        paged_decode_attention_pallas, interpret=True
    )
    got = tp_paged_decode_attention(
        kernel, mesh, q, k_pages, v_pages, pt, seq_lens
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_tp_decode_wrapper_window_and_layer():
    from vgate_tpu.ops.attention import paged_decode_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
    )
    from vgate_tpu.parallel.tp_attention import tp_paged_decode_attention

    if jax.device_count() < 2:
        pytest.skip("needs devices")
    rng = np.random.default_rng(7)
    B, H, KV, hd, ps, pages_per_seq, L = 2, 4, 2, 128, 16, 4, 3
    P_ = 1 + B * pages_per_seq
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    kL = jnp.asarray(
        rng.normal(size=(L, KV, P_, ps, hd)), jnp.float32
    )
    vL = jnp.asarray(
        rng.normal(size=(L, KV, P_, ps, hd)), jnp.float32
    )
    pt = jnp.asarray(
        rng.permutation(np.arange(1, P_))[: B * pages_per_seq].reshape(
            B, pages_per_seq
        ),
        jnp.int32,
    )
    seq_lens = jnp.asarray([40, 61], jnp.int32)
    w = jnp.asarray(16, jnp.int32)
    layer = jnp.asarray(1, jnp.int32)
    mesh = tp_mesh(2)

    expect = paged_decode_attention(
        q, kL, vL, pt, seq_lens, window=w, layer=layer, softcap=25.0
    )
    kernel = functools.partial(
        paged_decode_attention_pallas, interpret=True, softcap=25.0
    )
    got = tp_paged_decode_attention(
        kernel, mesh, q, kL, vL, pt, seq_lens, window=w, layer=layer
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_tp_flash_prefill_wrapper_matches_oracle():
    from vgate_tpu.ops.attention import causal_prefill_attention
    from vgate_tpu.ops.pallas.flash_prefill import (
        flash_prefill_attention_pallas,
    )
    from vgate_tpu.parallel.tp_attention import (
        tp_flash_prefill_attention,
    )

    if jax.device_count() < 2:
        pytest.skip("needs devices")
    rng = np.random.default_rng(9)
    B, S, H, KV, hd = 2, 128, 4, 2, 128
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    seq_lens = jnp.asarray([S, S - 37], jnp.int32)
    mesh = tp_mesh(2)

    expect = causal_prefill_attention(q, k, v, seq_lens)
    kernel = functools.partial(
        flash_prefill_attention_pallas, interpret=True
    )
    got = tp_flash_prefill_attention(kernel, mesh, q, k, v, seq_lens)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_decode_forward_tp_mesh_selects_wrapped_kernel():
    """Full decode_forward under a tp=2 mesh with use_pallas=True must
    route attention through the tp wrapper (patched to interpret mode)
    and match the jnp path bit-for-bit in logits ordering."""
    if jax.device_count() < 2:
        pytest.skip("needs devices")
    from vgate_tpu.models.decoder import decode_forward, init_params
    from vgate_tpu.models.specs import TINY_DENSE
    from vgate_tpu.parallel.sharding import (
        kv_pspec,
        named,
        shard_params,
    )

    import unittest.mock as mock

    from vgate_tpu.ops.pallas import paged_attention as pa

    spec = TINY_DENSE  # H=4, KV=2: divisible by tp=2
    mesh = tp_mesh(2)
    B, ps, pages_per_seq = 2, 4, 4
    num_pages = 1 + B * pages_per_seq
    params = shard_params(
        init_params(spec, jax.random.PRNGKey(0), jnp.float32), spec, mesh
    )
    shape = (spec.num_layers, spec.num_kv_heads, num_pages, ps,
             spec.head_dim)
    kv_sh = named(mesh, kv_pspec(spec, mesh))
    k = jax.device_put(jnp.zeros(shape, jnp.float32), kv_sh)
    v = jax.device_put(jnp.zeros(shape, jnp.float32), kv_sh)
    pt = jnp.asarray(
        np.arange(B * pages_per_seq, dtype=np.int32).reshape(B, -1) + 1
    )
    tokens = jnp.asarray([7, 11], jnp.int32)
    positions = jnp.asarray([3, 9], jnp.int32)
    active = jnp.ones((B,), bool)

    expect, _, _ = decode_forward(
        params, spec, tokens, positions, k, v, pt, active=active,
        use_pallas=False, mesh=mesh,
    )

    real = pa.paged_decode_attention_pallas
    calls = []

    def interp(*a, **kw):
        kw["interpret"] = True
        calls.append(1)
        return real(*a, **kw)

    k2 = jax.device_put(jnp.zeros(shape, jnp.float32), kv_sh)
    v2 = jax.device_put(jnp.zeros(shape, jnp.float32), kv_sh)
    with mock.patch.object(
        pa, "paged_decode_attention_pallas", side_effect=interp
    ):
        got, _, _ = decode_forward(
            params, spec, tokens, positions, k2, v2, pt, active=active,
            use_pallas=True, mesh=mesh,
        )
    assert calls, "tp mesh + use_pallas must reach the wrapped kernel"
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-4, atol=2e-4
    )


def test_tp_wrapper_with_blocked_kernel():
    """decode_block_slots > 1 composes with tp: the blocked kernel runs
    per shard inside the wrapper."""
    if jax.device_count() < 2:
        pytest.skip("needs devices")
    from vgate_tpu.ops.attention import paged_decode_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas_blocked,
    )
    from vgate_tpu.parallel.tp_attention import tp_paged_decode_attention

    rng = np.random.default_rng(21)
    B, H, KV, hd, ps, pages_per_seq = 4, 4, 2, 128, 16, 4
    P_ = 1 + B * pages_per_seq
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    k_pages = jnp.asarray(rng.normal(size=(KV, P_, ps, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(KV, P_, ps, hd)), jnp.float32)
    pt = jnp.asarray(
        rng.permutation(np.arange(1, P_))[: B * pages_per_seq].reshape(
            B, pages_per_seq
        ),
        jnp.int32,
    )
    seq_lens = jnp.asarray([5, 33, 64, 17], jnp.int32)
    mesh = tp_mesh(2)

    expect = paged_decode_attention(q, k_pages, v_pages, pt, seq_lens)
    kernel = functools.partial(
        paged_decode_attention_pallas_blocked, interpret=True,
        block_slots=2,
    )
    got = tp_paged_decode_attention(
        kernel, mesh, q, k_pages, v_pages, pt, seq_lens
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )
