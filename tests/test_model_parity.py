"""Numerical parity vs the HF torch reference implementations.

The reference framework trusts vLLM for model correctness; this framework
owns the models, so parity is pinned here: tiny Qwen2 (dense) and Mixtral
(MoE) configs run through transformers' torch implementations and through
our JAX decoder with identical weights, comparing logits in fp32.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.models.decoder import decode_forward, prefill_forward
from vgate_tpu.models.specs import TINY_DENSE, TINY_GEMMA2, TINY_MOE
from vgate_tpu.runtime.weights import params_from_torch_state_dict

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

PAGE = 16


def _build_hf_dense():
    config = transformers.Qwen2Config(
        vocab_size=TINY_DENSE.vocab_size,
        hidden_size=TINY_DENSE.hidden_size,
        num_hidden_layers=TINY_DENSE.num_layers,
        num_attention_heads=TINY_DENSE.num_heads,
        num_key_value_heads=TINY_DENSE.num_kv_heads,
        intermediate_size=TINY_DENSE.intermediate_size,
        rope_theta=TINY_DENSE.rope_theta,
        rms_norm_eps=TINY_DENSE.rms_eps,
        tie_word_embeddings=False,
        use_sliding_window=False,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(config).eval()
    return model


import dataclasses

# Llama-3 family: same decoder skeleton as Qwen2 minus qkv bias, with its
# own rope/eps; Mistral dense: the Mixtral attention/MLP without experts.
TINY_LLAMA = dataclasses.replace(
    TINY_DENSE, name="tiny-llama", qkv_bias=False, rms_eps=1e-5,
    rope_theta=500_000.0,
)
TINY_MISTRAL = dataclasses.replace(
    TINY_DENSE, name="tiny-mistral", qkv_bias=False, rms_eps=1e-5,
    rope_theta=1_000_000.0,
)
# Llama-3.1 family: Llama-3 plus the long-context rope frequency scaling
TINY_LLAMA31 = dataclasses.replace(
    TINY_DENSE, name="tiny-llama31", qkv_bias=False, rms_eps=1e-5,
    rope_theta=500_000.0, max_position_embeddings=256,
    rope_scaling_factor=8.0, rope_low_freq_factor=1.0,
    rope_high_freq_factor=4.0,
    # orig_max=64 places the 32.4-wavelength frequency pair inside the
    # interpolation band (high=16, low=64), so the smoothed branch of
    # the llama3 rule is exercised against HF, not just the two
    # keep//factor extremes
    rope_original_max_pos=64,
)


def _build_hf_llama():
    config = transformers.LlamaConfig(
        vocab_size=TINY_LLAMA.vocab_size,
        hidden_size=TINY_LLAMA.hidden_size,
        num_hidden_layers=TINY_LLAMA.num_layers,
        num_attention_heads=TINY_LLAMA.num_heads,
        num_key_value_heads=TINY_LLAMA.num_kv_heads,
        intermediate_size=TINY_LLAMA.intermediate_size,
        rope_theta=TINY_LLAMA.rope_theta,
        rms_norm_eps=TINY_LLAMA.rms_eps,
        tie_word_embeddings=False,
        attention_bias=False,
    )
    torch.manual_seed(2)
    return transformers.LlamaForCausalLM(config).eval()


def _build_hf_llama31():
    config = transformers.LlamaConfig(
        vocab_size=TINY_LLAMA31.vocab_size,
        hidden_size=TINY_LLAMA31.hidden_size,
        num_hidden_layers=TINY_LLAMA31.num_layers,
        num_attention_heads=TINY_LLAMA31.num_heads,
        num_key_value_heads=TINY_LLAMA31.num_kv_heads,
        intermediate_size=TINY_LLAMA31.intermediate_size,
        rope_theta=TINY_LLAMA31.rope_theta,
        rms_norm_eps=TINY_LLAMA31.rms_eps,
        max_position_embeddings=TINY_LLAMA31.max_position_embeddings,
        tie_word_embeddings=False,
        attention_bias=False,
        rope_scaling={
            "rope_type": "llama3",
            "factor": TINY_LLAMA31.rope_scaling_factor,
            "low_freq_factor": TINY_LLAMA31.rope_low_freq_factor,
            "high_freq_factor": TINY_LLAMA31.rope_high_freq_factor,
            "original_max_position_embeddings": (
                TINY_LLAMA31.rope_original_max_pos
            ),
        },
    )
    torch.manual_seed(5)
    return transformers.LlamaForCausalLM(config).eval()


def _build_hf_mistral():
    config = transformers.MistralConfig(
        vocab_size=TINY_MISTRAL.vocab_size,
        hidden_size=TINY_MISTRAL.hidden_size,
        num_hidden_layers=TINY_MISTRAL.num_layers,
        num_attention_heads=TINY_MISTRAL.num_heads,
        num_key_value_heads=TINY_MISTRAL.num_kv_heads,
        intermediate_size=TINY_MISTRAL.intermediate_size,
        rope_theta=TINY_MISTRAL.rope_theta,
        rms_norm_eps=TINY_MISTRAL.rms_eps,
        tie_word_embeddings=False,
        sliding_window=None,
    )
    torch.manual_seed(3)
    return transformers.MistralForCausalLM(config).eval()


def _build_hf_gemma2():
    # eager attention: the HF sdpa path skips attention-logit softcapping,
    # which Gemma-2 parity requires
    config = transformers.Gemma2Config(
        vocab_size=TINY_GEMMA2.vocab_size,
        hidden_size=TINY_GEMMA2.hidden_size,
        num_hidden_layers=TINY_GEMMA2.num_layers,
        num_attention_heads=TINY_GEMMA2.num_heads,
        num_key_value_heads=TINY_GEMMA2.num_kv_heads,
        head_dim=TINY_GEMMA2.head_dim,
        intermediate_size=TINY_GEMMA2.intermediate_size,
        rope_theta=TINY_GEMMA2.rope_theta,
        rms_norm_eps=TINY_GEMMA2.rms_eps,
        attn_logit_softcapping=TINY_GEMMA2.attn_softcap,
        final_logit_softcapping=TINY_GEMMA2.final_softcap,
        query_pre_attn_scalar=TINY_GEMMA2.query_scale,
        sliding_window=TINY_GEMMA2.sliding_window,
        hidden_activation="gelu_pytorch_tanh",
        tie_word_embeddings=True,
        attention_bias=False,
        attn_implementation="eager",
    )
    # our spec alternates even=sliding/odd=global; HF must agree
    assert [t == "sliding_attention" for t in config.layer_types] == [
        w > 0 for w in TINY_GEMMA2.layer_windows
    ]
    torch.manual_seed(4)
    return transformers.Gemma2ForCausalLM(config).eval()


def _build_hf_moe():
    config = transformers.MixtralConfig(
        vocab_size=TINY_MOE.vocab_size,
        hidden_size=TINY_MOE.hidden_size,
        num_hidden_layers=TINY_MOE.num_layers,
        num_attention_heads=TINY_MOE.num_heads,
        num_key_value_heads=TINY_MOE.num_kv_heads,
        intermediate_size=TINY_MOE.intermediate_size,
        rope_theta=TINY_MOE.rope_theta,
        rms_norm_eps=TINY_MOE.rms_eps,
        num_local_experts=TINY_MOE.num_experts,
        num_experts_per_tok=TINY_MOE.experts_per_token,
        tie_word_embeddings=False,
    )
    torch.manual_seed(1)
    model = transformers.MixtralForCausalLM(config).eval()
    return model


def _empty_cache(spec, num_pages, pages_per_seq, batch):
    k_pages = jnp.zeros(
        (spec.num_layers, spec.num_kv_heads, num_pages, PAGE, spec.head_dim),
        jnp.float32,
    )
    v_pages = jnp.zeros_like(k_pages)
    # page 0 is the trash page; real pages start at 1
    page_tables = (
        np.arange(batch * pages_per_seq, dtype=np.int32).reshape(
            batch, pages_per_seq
        )
        + 1
    )
    return k_pages, v_pages, jnp.asarray(page_tables)


def _hf_last_logits(model, token_rows):
    outs = []
    with torch.no_grad():
        for row in token_rows:
            ids = torch.tensor([row], dtype=torch.long)
            logits = model(ids).logits[0, -1].float().numpy()
            outs.append(logits)
    return np.stack(outs)


@pytest.mark.parametrize(
    "spec,builder,seed",
    [
        (TINY_DENSE, _build_hf_dense, 0),
        (TINY_MOE, _build_hf_moe, 1),
        (TINY_LLAMA, _build_hf_llama, 2),
        (TINY_MISTRAL, _build_hf_mistral, 3),
        (TINY_GEMMA2, _build_hf_gemma2, 4),
        (TINY_LLAMA31, _build_hf_llama31, 5),
    ],
    ids=[
        "qwen2-dense", "mixtral-moe", "llama3", "mistral", "gemma2",
        "llama31-rope-scaled",
    ],
)
def test_prefill_logits_match_hf(spec, builder, seed):
    qkv_bias = spec.qkv_bias
    model = builder()
    # Mixtral has no qkv bias; our spec flag must agree with HF's arch.
    assert (
        any("q_proj.bias" in k for k in model.state_dict())
        == qkv_bias
    )
    params = params_from_torch_state_dict(spec, model.state_dict())

    rng = np.random.default_rng(seed)
    lens = [12, 7]
    B, S = len(lens), PAGE
    tokens = np.zeros((B, S), dtype=np.int32)
    rows = []
    for b, n in enumerate(lens):
        row = rng.integers(2, spec.vocab_size, size=n)
        tokens[b, :n] = row
        rows.append(row.tolist())

    k_pages, v_pages, page_tables = _empty_cache(spec, 1 + B, 1, B)
    logits, _, _ = prefill_forward(
        params,
        spec,
        jnp.asarray(tokens),
        jnp.asarray(lens, jnp.int32),
        k_pages,
        v_pages,
        page_tables,
    )
    ours = np.asarray(logits, np.float32)
    theirs = _hf_last_logits(model, rows)
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_decode_step_matches_hf():
    model = _build_hf_dense()
    spec = TINY_DENSE
    params = params_from_torch_state_dict(spec, model.state_dict())

    rng = np.random.default_rng(7)
    n = 10
    row = rng.integers(2, spec.vocab_size, size=n + 1).tolist()
    prompt, extra_token = row[:n], row[n]

    B, S = 1, PAGE
    tokens = np.zeros((B, S), dtype=np.int32)
    tokens[0, :n] = prompt
    k_pages, v_pages, page_tables = _empty_cache(spec, 2, 1, B)
    _, k_pages, v_pages = prefill_forward(
        params,
        spec,
        jnp.asarray(tokens),
        jnp.asarray([n], jnp.int32),
        k_pages,
        v_pages,
        page_tables,
    )
    logits, k_pages, v_pages = decode_forward(
        params,
        spec,
        jnp.asarray([extra_token], jnp.int32),
        jnp.asarray([n], jnp.int32),  # position of the new token
        k_pages,
        v_pages,
        page_tables,
        active=jnp.asarray([True]),
    )
    ours = np.asarray(logits, np.float32)
    theirs = _hf_last_logits(model, [row])
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_gemma2_decode_step_matches_hf():
    """Decode parity across the sliding-window boundary: the prompt is
    longer than the window (8), so layer 0's decode attention must drop
    the oldest tokens exactly like HF's sliding mask."""
    model = _build_hf_gemma2()
    spec = TINY_GEMMA2
    params = params_from_torch_state_dict(spec, model.state_dict())

    rng = np.random.default_rng(11)
    n = 12  # > sliding_window = 8
    row = rng.integers(2, spec.vocab_size, size=n + 1).tolist()
    prompt, extra_token = row[:n], row[n]

    B, S = 1, PAGE
    tokens = np.zeros((B, S), dtype=np.int32)
    tokens[0, :n] = prompt
    k_pages, v_pages, page_tables = _empty_cache(spec, 2, 1, B)
    _, k_pages, v_pages = prefill_forward(
        params,
        spec,
        jnp.asarray(tokens),
        jnp.asarray([n], jnp.int32),
        k_pages,
        v_pages,
        page_tables,
    )
    logits, k_pages, v_pages = decode_forward(
        params,
        spec,
        jnp.asarray([extra_token], jnp.int32),
        jnp.asarray([n], jnp.int32),
        k_pages,
        v_pages,
        page_tables,
        active=jnp.asarray([True]),
    )
    ours = np.asarray(logits, np.float32)
    theirs = _hf_last_logits(model, [row])
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_decode_inactive_slot_does_not_corrupt_cache():
    spec = TINY_DENSE
    from vgate_tpu.models.decoder import init_params

    params = init_params(spec, jax.random.PRNGKey(0), jnp.float32)
    B = 2
    k_pages, v_pages, page_tables = _empty_cache(spec, 1 + B, 1, B)
    tokens = jnp.asarray(np.full((B, PAGE), 3, np.int32))
    _, k_pages, v_pages = prefill_forward(
        params, spec, tokens, jnp.asarray([4, 4], jnp.int32),
        k_pages, v_pages, page_tables,
    )
    snapshot = np.asarray(k_pages[:, :, 2])  # slot 1's page
    # slot 1 inactive: its write must go to trash page 0, not page 2
    _, k_pages, _ = decode_forward(
        params, spec,
        jnp.asarray([5, 5], jnp.int32),
        jnp.asarray([4, 4], jnp.int32),
        k_pages, v_pages, page_tables,
        active=jnp.asarray([True, False]),
    )
    after = np.asarray(k_pages[:, :, 2])
    np.testing.assert_array_equal(snapshot, after)
