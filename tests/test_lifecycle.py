"""End-to-end request deadlines, cancellation propagation and graceful
drain (ISSUE 2).

Fast tier: gateway/batcher/scheduler behavior on the dry-run backend —
timeout parsing, CancelToken mechanics, queued-request cancellation,
drain admission/readiness semantics, and the partial-result cache
regression.  Slow tier (real jax engine on the tiny model): the three
acceptance scenarios — (a) a client disconnect mid-generation frees the
sequence's KV pages and scheduler slot within a tick, (b) a 50 ms
deadline against a slow fault-injected backend 504s without failing its
batchmates, (c) SIGTERM under load completes every in-flight request
while /health/ready reports draining throughout.
"""

import asyncio
import json
import os
import signal
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu import faults
from vgate_tpu.backends.base import GenerationResult
from vgate_tpu.batcher import RequestBatcher
from vgate_tpu.config import load_config
from vgate_tpu.engine import VGTEngine
from vgate_tpu.errors import (
    ClientDisconnectError,
    DeadlineExceededError,
    ServerDrainingError,
)
from vgate_tpu.lifecycle import CancelToken, DrainController, all_of
from vgate_tpu.server.app import create_app

JAX_TINY = dict(
    model={
        "model_id": "tiny-dense",
        "engine_type": "jax_tpu",
        "dtype": "float32",
        "max_model_len": 64,
    },
    tpu={
        "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
        "kv_num_pages": 128, "kv_page_size": 4,
        "max_batch_slots": 4, "prefill_buckets": [16, 32],
        "use_pallas": False,
    },
    scheduler={"max_queue_size": 32},
)


async def _client(**overrides):
    overrides.setdefault("model", {"engine_type": "dry_run"})
    overrides.setdefault(
        "batch", {"max_batch_size": 8, "max_wait_time_ms": 10.0}
    )
    overrides.setdefault("logging", {"level": "WARNING"})
    config = load_config(**overrides)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    return client


def _chat_body(i=0, **extra):
    return {
        "messages": [{"role": "user", "content": f"lifecycle probe {i}"}],
        "max_tokens": 8,
        "temperature": 0.0,
        **extra,
    }


async def _warm(client, n=1):
    """Fire n concurrent tiny requests so the engine compiles the
    [B=n, bucket] batched-prefill and decode programs the timed tests
    use — a first-contact XLA compile (seconds on CPU) mid-test would
    stall the engine tick past the deadlines being asserted."""
    responses = await asyncio.gather(
        *(
            client.post(
                "/v1/chat/completions",
                json=_chat_body(i, max_tokens=2, min_tokens=2),
            )
            for i in range(n)
        )
    )
    assert [r.status for r in responses] == [200] * n


# --------------------------------------------------------------- fast tier


def test_cancel_token_runs_callbacks_once_and_late():
    token = CancelToken()
    fired = []
    token.add_callback(lambda: fired.append("early"))
    assert token.cancel("client_disconnect") is True
    assert token.cancel("client_disconnect") is False  # one-shot
    token.add_callback(lambda: fired.append("late"))  # runs inline
    assert fired == ["early", "late"]
    assert token.cancelled and token.reason == "client_disconnect"


def test_all_of_fires_only_when_every_member_cancelled():
    """Dedup-group cancellation semantics: the shared generation aborts
    only when EVERY duplicate requester is gone."""
    t1, t2 = CancelToken(), CancelToken()
    combined = all_of([t1, t2])
    t1.cancel("client_disconnect")
    assert not combined.cancelled  # t2's client is still waiting
    t2.cancel("client_disconnect")
    assert combined.cancelled
    # a member that can never cancel makes the group uncancellable
    assert all_of([CancelToken(), None]) is None
    assert all_of([]) is None
    # single-member group degenerates to the member itself
    t3 = CancelToken()
    assert all_of([t3]) is t3


async def test_dedup_group_sends_composite_cancel_token(dry_config):
    """The batcher hands the backend a GROUP-level token: one duplicate
    requester disconnecting must not cancel it while its twin waits."""
    engine = VGTEngine(dry_config)
    batcher = RequestBatcher(engine, dry_config)
    await batcher.start()
    seen = {}

    class RecordingBackend:
        async def generate_settled_async(
            self, prompts, params, cancel_tokens=None
        ):
            seen["tokens"] = cancel_tokens
            return [
                GenerationResult(text="done", num_tokens=4)
                for _ in prompts
            ]

    engine.backend = RecordingBackend()
    try:
        t1, t2 = CancelToken(), CancelToken()
        first, second = await asyncio.gather(
            batcher.submit("twin prompt", max_tokens=4, temperature=0.0,
                           cancel_token=t1),
            batcher.submit("twin prompt", max_tokens=4, temperature=0.0,
                           cancel_token=t2),
        )
        assert first["text"] == second["text"] == "done"
        assert len(seen["tokens"]) == 1  # deduped into one group
        combined = seen["tokens"][0]
        t1.cancel("client_disconnect")
        assert not combined.cancelled
        t2.cancel("client_disconnect")
        assert combined.cancelled
    finally:
        await batcher.stop()


def test_scheduler_sheds_waiting_request_past_deadline():
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.runtime.kv_cache import PageAllocator
    from vgate_tpu.runtime.scheduler import Scheduler
    from vgate_tpu.runtime.sequence import Sequence

    sched = Scheduler(
        allocator=PageAllocator(16),
        max_slots=0,  # nothing can admit: the seq must shed in queue
        page_size=4,
        prefill_buckets=[16],
        max_model_len=64,
    )
    seq = Sequence(
        prompt_ids=[1, 2, 3],
        params=SamplingParams(max_tokens=4, timeout_s=0.01),
    )
    sched.add(seq)
    time.sleep(0.03)
    assert sched.try_admit() is None
    assert seq.status.value == "failed"
    assert isinstance(seq.error, DeadlineExceededError)
    assert sched.total_deadline_shed == 1


async def test_timeout_header_invalid_is_422():
    client = await _client()
    try:
        for bad in ("nan-seconds", "-1", "0"):
            resp = await client.post(
                "/v1/chat/completions",
                json=_chat_body(),
                headers={"X-Request-Timeout": bad},
            )
            assert resp.status == 422, bad
    finally:
        await client.close()


async def test_timeout_header_and_body_accepted():
    client = await _client()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json=_chat_body(timeout=5.0),
            headers={"X-Request-Timeout": "10"},
        )
        assert resp.status == 200
    finally:
        await client.close()


async def test_cancel_token_dequeues_queued_request():
    """A queued request whose client disconnects leaves the batch queue
    immediately and fails with the typed ClientDisconnectError."""
    config = load_config(
        model={"engine_type": "dry_run"},
        # park the queue: nothing fires for 60s at batch size 64
        batch={"max_batch_size": 64, "max_wait_time_ms": 60000.0},
        logging={"level": "WARNING"},
    )
    engine = VGTEngine(config)
    batcher = RequestBatcher(engine, config)
    await batcher.start()
    try:
        token = CancelToken()
        task = asyncio.ensure_future(
            batcher.submit("park me", cancel_token=token)
        )
        await asyncio.sleep(0.05)
        assert len(batcher._queue) == 1
        token.cancel("client_disconnect")
        with pytest.raises(ClientDisconnectError):
            await asyncio.wait_for(task, 2.0)
        assert len(batcher._queue) == 0
    finally:
        await batcher.stop()


async def test_result_cache_never_stores_partial_results(dry_config):
    """Regression (ISSUE 2 satellite): a cancelled/deadline-shed batch
    result (finish_reason "abort"/"deadline") must never enter the
    ResultCache — the next identical request gets a FULL generation."""
    engine = VGTEngine(dry_config)
    batcher = RequestBatcher(engine, dry_config)
    await batcher.start()

    class FlakyBackend:
        mode = "abort"

        async def generate_settled_async(
            self, prompts, params, cancel_tokens=None
        ):
            if self.mode == "abort":
                return [
                    GenerationResult(
                        text="par", num_tokens=2, finish_reason="abort"
                    )
                    for _ in prompts
                ]
            return [
                GenerationResult(
                    text="the full completion",
                    num_tokens=8,
                    finish_reason="stop",
                )
                for _ in prompts
            ]

    engine.backend = FlakyBackend()
    try:
        first = await batcher.submit("same prompt", max_tokens=8,
                                     temperature=0.0)
        assert first["finish_reason"] == "abort"
        engine.backend.mode = "stop"
        second = await batcher.submit("same prompt", max_tokens=8,
                                      temperature=0.0)
        # a cached partial would come back cached=True with text "par"
        assert second["cached"] is False
        assert second["finish_reason"] == "stop"
        assert second["text"] == "the full completion"
        # completed results still cache as before
        third = await batcher.submit("same prompt", max_tokens=8,
                                     temperature=0.0)
        assert third["cached"] is True
    finally:
        await batcher.stop()


async def test_drain_rejects_admission_and_flips_ready():
    """begin_drain: ready → 503 "draining" (+Retry-After), live stays
    200, new chat/embeddings admissions shed 503, batcher rejects with
    the retryable typed error."""
    client = await _client()
    app = client.server.app
    try:
        done = []
        app["drain"].on_complete = lambda: done.append(True)
        app["drain"].begin()
        resp = await client.get("/health/ready")
        assert resp.status == 503
        body = await resp.json()
        assert body["engine"]["state"] == "draining"
        assert "Retry-After" in resp.headers
        resp = await client.get("/health")
        assert resp.status == 503
        assert (await resp.json())["status"] == "draining"
        resp = await client.get("/health/live")
        assert resp.status == 200
        resp = await client.post("/v1/chat/completions", json=_chat_body())
        assert resp.status == 503
        assert "Retry-After" in resp.headers
        resp = await client.post("/v1/embeddings", json={"input": "x"})
        assert resp.status == 503
        with pytest.raises(ServerDrainingError):
            await app["batcher"].submit("direct")
        assert await app["drain"].wait_drained(5.0)
        assert done == [True]
    finally:
        await client.close()


async def test_drain_completes_inflight_dry_run():
    """In-flight requests complete through the drain (zero drops) while
    admission is already shedding — the drain_check.sh scenario
    in-process."""
    faults.arm(
        "backend_generate", mode="delay", delay_s=0.3, times=-1
    )
    client = await _client(
        batch={"max_batch_size": 64, "max_wait_time_ms": 30.0},
    )
    app = client.server.app
    try:
        done = []
        app["drain"].on_complete = lambda: done.append(True)
        inflight = [
            asyncio.ensure_future(
                client.post("/v1/chat/completions", json=_chat_body(i))
            )
            for i in range(4)
        ]
        # wait until all 4 are actually in flight (a fixed sleep races
        # the event loop under full-suite load: drain would flip
        # readiness before the POSTs reach the handler and shed them)
        deadline = time.monotonic() + 5.0
        while (
            app["drain"].inflight() < 4
            and time.monotonic() < deadline
        ):
            await asyncio.sleep(0.02)
        assert app["drain"].inflight() == 4
        app["drain"].begin()
        resp = await client.get("/health/ready")
        assert resp.status == 503
        responses = await asyncio.gather(*inflight)
        assert [r.status for r in responses] == [200] * 4
        assert await app["drain"].wait_drained(5.0)
        assert done == [True]
        assert app["drain"].aborted_stragglers == 0
    finally:
        await client.close()


# --------------------------------------------------------------- slow tier


async def _raw_disconnecting_post(host, port, body: dict, after_s: float):
    """Open a raw TCP connection, POST, then close the socket after
    ``after_s`` — a REAL mid-request client disconnect (TestClient
    cancellation may return the connection to its pool instead)."""
    payload = json.dumps(body).encode()
    reader, writer = await asyncio.open_connection(host, port)
    writer.write(
        (
            "POST /v1/chat/completions HTTP/1.1\r\n"
            f"Host: {host}\r\n"
            "Content-Type: application/json\r\n"
            f"Content-Length: {len(payload)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode()
        + payload
    )
    await writer.drain()
    await asyncio.sleep(after_s)
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass


async def _assert_disconnect_frees_resources(get_stats, chat, host, port):
    """Shared body for the two disconnect transports: warm up, slow the
    decode, disconnect mid-generation, assert the abort released the
    slot and KV pages promptly."""
    resp_status = await chat(_chat_body(max_tokens=2, min_tokens=2))
    assert resp_status == 200
    # warm the chunk-8 decode ladder the 48-token request below uses
    # (different prompt so it can't cache-hit); without this a
    # first-contact XLA compile can block the engine tick for seconds
    # right when the abort should land
    resp_status = await chat(_chat_body(7, max_tokens=48, min_tokens=48))
    assert resp_status == 200
    # ~0.2s per decode-chunk dispatch → a 48-token request runs for
    # seconds, far past the 0.4s disconnect below
    faults.arm("decode_step", mode="delay", delay_s=0.2, times=-1)
    await _raw_disconnecting_post(
        host, port, _chat_body(max_tokens=48, min_tokens=48), after_s=0.4
    )
    # the abort must land within ~a decode tick (0.2s chunks here, plus
    # watcher/cancellation latency) — 8s is generous; completing
    # naturally instead would leave aborted == 0 and fail below
    deadline = time.perf_counter() + 8.0
    sched = None
    while time.perf_counter() < deadline:
        sched = (await get_stats())["engine"]["scheduler"]
        if (
            sched["running"] == 0
            and sched["used_pages"] == 0
            and sched["aborted"] >= 1
        ):
            break
        await asyncio.sleep(0.05)
    assert sched is not None
    assert sched["running"] == 0, sched
    assert sched["used_pages"] == 0, sched
    assert sched["aborted"] >= 1, sched


@pytest.mark.slow
async def test_client_disconnect_frees_kv_and_slot_production_mode():
    """(a) Production server semantics (handler_cancellation=False, the
    aiohttp default under run_app): the DISCONNECT WATCHER notices the
    closed transport and fires the CancelToken — slot and KV pages free
    within a tick."""
    import aiohttp
    from aiohttp import web as aioweb

    config = load_config(
        **JAX_TINY,
        batch={"max_batch_size": 8, "max_wait_time_ms": 10.0},
        logging={"level": "WARNING"},
    )
    runner = aioweb.AppRunner(create_app(config))
    await runner.setup()
    site = aioweb.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    try:
        async with aiohttp.ClientSession() as session:

            async def chat(body):
                async with session.post(
                    f"{base}/v1/chat/completions", json=body
                ) as resp:
                    await resp.read()
                    return resp.status

            async def get_stats():
                async with session.get(f"{base}/stats") as resp:
                    return await resp.json()

            await _assert_disconnect_frees_resources(
                get_stats, chat, "127.0.0.1", port
            )
    finally:
        faults.reset()
        await runner.cleanup()


@pytest.mark.slow
async def test_client_disconnect_frees_kv_and_slot_cancellation_mode():
    """(a') The same disconnect under handler_cancellation=True (what
    TestServer enables): aiohttp cancels the handler task, and
    batcher.submit's CancelledError path fires the token instead of the
    watcher.  Same observable outcome: resources free within a tick."""
    client = await _client(**JAX_TINY)
    try:

        async def chat(body):
            resp = await client.post("/v1/chat/completions", json=body)
            await resp.read()
            return resp.status

        async def get_stats():
            return await (await client.get("/stats")).json()

        await _assert_disconnect_frees_resources(
            get_stats, chat, str(client.server.host), client.server.port
        )
    finally:
        faults.reset()
        await client.close()


@pytest.mark.slow
async def test_deadline_504_without_failing_batchmates():
    """(b) A 50 ms deadline against a slow fault-injected backend gets a
    504 with partial-tokens metadata while its batchmate completes."""
    client = await _client(**JAX_TINY)
    try:
        await _warm(client, 1)
        # warm the EXACT program variants the timed pair compiles —
        # B=2 prefill plus the chunk-8/4/2/1 decode ladder with the
        # min_tokens masking arrays — so no first-contact XLA compile
        # (seconds on CPU) can stall the tick past the 50ms deadline.
        # Different prompts (i=3,4) than the timed pair: identical
        # bodies would let the timed requests cache-hit these results.
        warm_pair = await asyncio.gather(
            client.post(
                "/v1/chat/completions",
                json=_chat_body(3, max_tokens=40, min_tokens=40),
            ),
            client.post(
                "/v1/chat/completions",
                json=_chat_body(4, max_tokens=3, min_tokens=3),
            ),
        )
        assert [r.status for r in warm_pair] == [200, 200]
        faults.arm("decode_step", mode="delay", delay_s=0.1, times=-1)
        doomed, patient = await asyncio.gather(
            client.post(
                "/v1/chat/completions",
                json=_chat_body(1, max_tokens=40, min_tokens=40),
                headers={"X-Request-Timeout": "0.05"},
            ),
            client.post(
                "/v1/chat/completions",
                json=_chat_body(2, max_tokens=3, min_tokens=3),
            ),
        )
        assert doomed.status == 504
        err = (await doomed.json())["error"]
        assert err["type"] == "timeout_error"
        assert "partial_tokens" in err
        assert patient.status == 200
        body = await patient.json()
        assert body["usage"]["completion_tokens"] == 3
        # the shed freed the doomed request's residency
        stats = await (await client.get("/stats")).json()
        sched = stats["engine"]["scheduler"]
        assert sched["running"] == 0 and sched["used_pages"] == 0
        assert sched["deadline_shed"] >= 1
    finally:
        faults.reset()
        await client.close()


@pytest.mark.slow
async def test_sigterm_drain_completes_every_inflight_request():
    """(c) SIGTERM under load: every in-flight request completes, and
    /health/ready returns 503 ("draining") throughout the drain."""
    client = await _client(
        **JAX_TINY,
        batch={"max_batch_size": 8, "max_wait_time_ms": 10.0},
    )
    app = client.server.app
    try:
        await _warm(client, 1)
        await _warm(client, 4)  # the load's B=4 prefill shape
        faults.arm("decode_step", mode="delay", delay_s=0.05, times=-1)
        done = []
        app["drain"].on_complete = lambda: done.append(True)
        inflight = [
            asyncio.ensure_future(
                client.post(
                    "/v1/chat/completions",
                    json=_chat_body(i, max_tokens=6 + i, min_tokens=6 + i),
                )
            )
            for i in range(4)
        ]
        await asyncio.sleep(0.2)  # sequences decoding
        # the REAL signal path: _on_startup registered drain.begin
        assert app.get("drain_signal_installed")
        os.kill(os.getpid(), signal.SIGTERM)
        # ready must report draining for the WHOLE drain window
        ready_seen = []
        for _ in range(3):
            resp = await client.get("/health/ready")
            ready_seen.append(
                (resp.status, (await resp.json())["engine"]["state"])
            )
            await asyncio.sleep(0.05)
        assert all(s == (503, "draining") for s in ready_seen), ready_seen
        responses = await asyncio.gather(*inflight)
        assert [r.status for r in responses] == [200] * 4
        for i, r in enumerate(responses):
            body = await r.json()
            assert body["usage"]["completion_tokens"] == 6 + i
        assert await app["drain"].wait_drained(10.0)
        assert done == [True]
        assert app["drain"].aborted_stragglers == 0
    finally:
        faults.reset()
        await client.close()


@pytest.mark.slow
async def test_abort_by_seq_id_sheds_within_a_tick():
    """EngineCore.abort(seq_id) — the request-scoped abort surface:
    marks exactly the target sequence, which sheds (slot + KV pages
    freed, finish_reason "abort") within a tick of the engine thread
    picking up the command."""
    from vgate_tpu.backends.base import SamplingParams

    config = load_config(**JAX_TINY, logging={"level": "WARNING"})
    engine = VGTEngine(config)
    try:
        core = engine.backend.core  # EngineSupervisor delegates to core
        warm = core.submit_prompt(
            "warm it up first", SamplingParams(max_tokens=2, temperature=0.0)
        )
        warm.done_event.wait(120)
        faults.arm("decode_step", mode="delay", delay_s=0.2, times=-1)
        seq = core.submit_prompt(
            "abort me by id please",
            SamplingParams(max_tokens=40, min_tokens=40, temperature=0.0),
        )
        bystander = core.submit_prompt(
            "leave me decoding",
            SamplingParams(max_tokens=6, min_tokens=6, temperature=0.0),
        )
        await asyncio.sleep(0.3)
        core.abort(seq.seq_id)
        deadline = time.perf_counter() + 8.0
        while time.perf_counter() < deadline and not seq.done_event.is_set():
            await asyncio.sleep(0.05)
        assert seq.done_event.is_set()
        assert seq.finish_reason == "abort"
        while (
            time.perf_counter() < deadline
            and not bystander.done_event.is_set()
        ):
            await asyncio.sleep(0.05)
        assert bystander.finish_reason in ("stop", "length")
        assert bystander.num_output_tokens == 6
        sched = engine.backend.get_stats()["scheduler"]
        assert sched["running"] == 0 and sched["used_pages"] == 0
    finally:
        faults.reset()
        engine.shutdown()


@pytest.mark.slow
async def test_drain_timeout_aborts_stragglers_cleanly():
    """Past lifecycle.drain_timeout_s the drain aborts stragglers: their
    responses settle (finish_reason "abort", no hang) and the drain
    still completes."""
    client = await _client(
        **JAX_TINY,
        lifecycle={"drain_timeout_s": 0.3, "drain_poll_ms": 20.0},
    )
    app = client.server.app
    try:
        await _warm(client, 1)
        await _warm(client, 2)  # the straggler pair's B=2 prefill shape
        faults.arm("decode_step", mode="delay", delay_s=0.2, times=-1)
        done = []
        app["drain"].on_complete = lambda: done.append(True)
        inflight = [
            asyncio.ensure_future(
                client.post(
                    "/v1/chat/completions",
                    json=_chat_body(i, max_tokens=40, min_tokens=40),
                )
            )
            for i in range(2)
        ]
        await asyncio.sleep(0.3)  # decoding, will outlive the 0.3s window
        app["drain"].begin()
        responses = await asyncio.gather(*inflight)
        # aborted mid-generation but SETTLED: 200 with partial text and
        # finish_reason "abort", never a dropped connection
        for r in responses:
            assert r.status == 200
            body = await r.json()
            assert body["choices"][0]["finish_reason"] == "abort"
        assert await app["drain"].wait_drained(10.0)
        assert done == [True]
        assert app["drain"].aborted_stragglers >= 1
        stats = await (await client.get("/stats")).json()
        sched = stats["engine"]["scheduler"]
        assert sched["running"] == 0 and sched["used_pages"] == 0
    finally:
        faults.reset()
        await client.close()
