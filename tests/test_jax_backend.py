"""JaxTPUBackend integration: async generate, streaming, embeddings and the
full gateway serving the tiny model (the reference's tier-3 in-process
integration strategy, applied to the first-party engine)."""

import asyncio

import numpy as np
import pytest

from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.backends.jax_backend import JaxTPUBackend
from vgate_tpu.config import load_config, set_config
from vgate_tpu.server.app import create_app

TINY = dict(
    model={
        "model_id": "tiny-dense",
        "engine_type": "jax_tpu",
        "dtype": "float32",
        "max_model_len": 64,
        "embedding_model_id": "tiny-encoder",
    },
    tpu={
        "dp": 1,
        "tp": 0,  # absorb the submesh => tp=2
        "ep": 1,
        "sp": 1,
        "num_devices": 2,  # 2 of the 8 virtual CPU devices (speed)
        "kv_num_pages": 64,
        "kv_page_size": 4,
        "max_batch_slots": 4,
        "prefill_buckets": [8, 16, 32],
        "use_pallas": False,
    },
    batch={"max_batch_size": 4, "max_wait_time_ms": 5.0},
    logging={"level": "WARNING"},
)


@pytest.fixture(scope="module")
def backend():
    config = load_config(**TINY)
    set_config(config)
    b = JaxTPUBackend()
    b.load_model(config)
    yield b
    b.shutdown()
    from vgate_tpu.config import reset_config

    reset_config()


def test_sync_generate_protocol(backend):
    params = backend.create_sampling_params(max_tokens=5, temperature=0.0)
    results = backend.generate(["one", "two"], [params, params])
    assert len(results) == 2
    for r in results:
        assert 1 <= r.num_tokens <= 5
        assert r.metrics["ttft"] > 0
        assert r.finish_reason in ("stop", "length")


def test_multichip_mesh_used(backend):
    # conftest forces 8 virtual CPU devices; a 2-device tp submesh is used
    stats = backend.get_stats()
    assert stats["mesh"]["tp"] == 2


async def test_generate_async(backend):
    params = SamplingParams(max_tokens=4, temperature=0.0)
    results = await backend.generate_async(["async probe"], [params])
    assert results[0].num_tokens >= 1


async def test_generate_async_concurrent_interleaves(backend):
    params = SamplingParams(max_tokens=6, temperature=0.0)
    out = await asyncio.gather(
        backend.generate_async(["c1"], [params]),
        backend.generate_async(["c2"], [params]),
        backend.generate_async(["c3"], [params]),
    )
    assert all(batch[0].num_tokens >= 1 for batch in out)


async def test_stream_async_yields_deltas(backend):
    params = SamplingParams(max_tokens=5, temperature=0.0)
    pieces = []
    async for delta in backend.stream_async("stream probe", params):
        pieces.append(delta)
    full = "".join(pieces)
    [direct] = backend.generate(
        ["stream probe"], [SamplingParams(max_tokens=5, temperature=0.0)]
    )
    assert full == direct.text


def test_embed_shapes_and_normalization(backend):
    vecs = backend.embed(["first text", "second longer text here"])
    arr = np.asarray(vecs)
    assert arr.shape == (2, 64)  # tiny-encoder hidden size
    np.testing.assert_allclose(np.linalg.norm(arr, axis=1), 1.0, atol=1e-3)
    # deterministic
    again = np.asarray(backend.embed(["first text"]))[0]
    np.testing.assert_allclose(arr[0], again, atol=1e-5)


def test_embed_distinguishes_inputs(backend):
    vecs = np.asarray(backend.embed(["aaaa bbbb", "totally different"]))
    assert np.abs(vecs[0] - vecs[1]).max() > 1e-3


def test_device_health(backend):
    health = backend.device_health()
    assert health["alive"] is True
    assert health["num_devices"] == 2


async def test_gateway_end_to_end_with_jax_engine():
    config = load_config(**TINY)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "hi engine"}],
                "max_tokens": 5,
                "temperature": 0.0,
            },
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["usage"]["completion_tokens"] >= 1
        assert body["choices"][0]["finish_reason"] in ("stop", "length")

        # embeddings through the real encoder
        resp = await client.post("/v1/embeddings", json={"input": "vector me"})
        body = await resp.json()
        assert len(body["data"][0]["embedding"]) == 64

        # stats expose engine internals
        stats = await (await client.get("/stats")).json()
        assert stats["engine"]["prefills"] >= 1
        assert stats["engine"]["mesh"]["tp"] == 2

        # health reports device liveness
        health = await (await client.get("/health")).json()
        assert health["device"]["alive"] is True

        # device profiler capture while serving (SURVEY.md section 5.1)
        import os
        import tempfile

        out_dir = tempfile.mkdtemp(prefix="vgt_prof_test_")
        resp = await client.post(
            "/v1/profile",
            json={"duration_ms": 100, "out_dir": out_dir},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["trace_dir"] == out_dir
        assert body["files"] >= 1  # .xplane.pb trace written
        assert os.path.isdir(out_dir)
    finally:
        await client.close()


async def test_stream_async_reports_finish_reason(backend):
    """on_finish delivers the true finish reason (max_tokens => length)."""
    reasons = []
    params = SamplingParams(max_tokens=3, temperature=0.0)
    async for _ in backend.stream_async(
        "finish reason probe", params, on_finish=reasons.append
    ):
        pass
    assert reasons == ["length"]


def test_vllm_backend_selectable_and_fails_clearly_without_wheel():
    """The optional comparison backend (reference: vLLM/SGLang side by
    side in one bench table) is a first-class engine_type that fails
    with an actionable error in images without a vllm wheel."""
    import pytest

    from vgate_tpu.config import load_config
    from vgate_tpu.engine import _create_backend

    backend = _create_backend("vllm")
    assert type(backend).__name__ == "VLLMBackend"
    cfg = load_config(
        model={"engine_type": "vllm", "model_id": "tiny-dense"},
        logging={"level": "WARNING"},
    )
    assert cfg.model.engine_type == "vllm"
    try:
        import vllm  # noqa: F401

        has_vllm = True
    except ImportError:
        has_vllm = False
    if not has_vllm:
        with pytest.raises(RuntimeError, match="vllm"):
            backend.load_model(cfg)


def test_sglang_backend_selectable_and_fails_clearly_without_wheel():
    """The SGLang half of the reference's comparison pair
    (backends/sglang_backend.py) is selectable and fails with an
    actionable error in images without an sglang wheel."""
    import pytest

    from vgate_tpu.config import load_config
    from vgate_tpu.engine import _create_backend

    backend = _create_backend("sglang")
    assert type(backend).__name__ == "SGLangBackend"
    cfg = load_config(
        model={"engine_type": "sglang", "model_id": "tiny-dense"},
        logging={"level": "WARNING"},
    )
    assert cfg.model.engine_type == "sglang"
    try:
        import sglang  # noqa: F401

        has_sglang = True
    except ImportError:
        has_sglang = False
    if not has_sglang:
        with pytest.raises(RuntimeError, match="sglang"):
            backend.load_model(cfg)
