"""In-flight request survival (ISSUE 5): sequence checkpoint & replay
across engine restarts, the hang watchdog, and dp replica failover.

Fast tier: pure-logic units on fake clocks/cores — checkpoint/restore
round-trip, watchdog classification (stall vs compile grace), the
containment partition (checkpoint vs max_resume_attempts vs abort),
replay-excludes-poison, and the scheduler's replay queue-full bypass.

Slow tier (real tiny-dense engine on CPU): the three acceptance
scenarios — crash replay token-identical to an uninterrupted run, stall
detected/recovered/replayed, and dp failover redistribution.
"""

import queue
import threading
import time
from collections import deque
from types import SimpleNamespace

import jax
import pytest

from vgate_tpu import faults, metrics
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.errors import (
    EngineStalledError,
    PoisonRequestError,
    ResumeExhaustedError,
)
from vgate_tpu.runtime.engine_core import EngineCore
from vgate_tpu.runtime.kv_cache import PageAllocator
from vgate_tpu.runtime.scheduler import EngineBusyError, Scheduler
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.runtime.supervisor import (
    EngineSupervisor,
    HealthState,
    classify_heartbeat,
)


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


def wait_for(pred, timeout=120.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ------------------------------------------------- checkpoint round-trip


def test_checkpoint_restore_round_trip():
    seq = Sequence(
        prompt_ids=[1, 2, 3],
        params=greedy(16, timeout_s=30.0),
        request_id="req-1",
    )
    seq.append_token(7)
    seq.append_token(9)
    cp = seq.checkpoint()
    assert cp.prompt_ids == [1, 2, 3]
    assert cp.generated_ids == [7, 9]
    assert cp.request_id == "req-1"
    assert cp.deadline_t == seq.deadline_t  # absolute: original budget

    restored = Sequence.from_checkpoint(cp)
    # prefill-continue: prompt + partial as the prefill, decode resumes
    # at the next position
    assert restored.prompt_ids == [1, 2, 3, 7, 9]
    assert restored.generated_ids == [7, 9]
    assert restored.orig_prompt_len == 3
    assert restored.status is SeqStatus.WAITING
    assert restored.resume_count == cp.resume_count + 1
    # deadline stays anchored — no fresh budget on restore
    assert restored.deadline_t == seq.deadline_t
    # RNG continuation contract: the sampler draws from
    # (seed, step=num_generated), so the restored step index continues
    # exactly where the original stopped
    assert restored.num_generated == seq.num_generated
    # the loggable summary never carries token content, and the cheap
    # live-object form (no token-list copies; what last_resume records)
    # must agree with it field for field
    d = cp.as_dict()
    assert d["generated_tokens"] == 2 and "prompt_ids" not in d
    assert seq.checkpoint_summary() == d


def test_prepare_resume_folds_generation_and_bumps_epoch():
    seq = Sequence(prompt_ids=[4, 5], params=greedy(8))
    seq.status = SeqStatus.RUNNING
    seq.slot = 1
    seq.pages = [3, 9]
    seq.append_token(11)
    old_epoch = seq.preempt_count
    seq.prepare_resume()
    assert seq.status is SeqStatus.WAITING
    assert seq.prompt_ids == [4, 5, 11] and seq.output_ids == []
    assert seq.generated_ids == [11]
    assert seq.pages == [] and seq.slot is None
    assert seq.resume_count == 1
    # the epoch bump discards a stalled thread's late readbacks
    assert seq.preempt_count == old_epoch + 1
    # same future object: a client blocked on done_event keeps waiting
    assert not seq.done_event.is_set()


# ------------------------------------------------ watchdog classification


def test_watchdog_classifies_stall_on_fake_clock():
    hb = {"t": 100.0, "kind": "decode", "compiling": False}
    verdict = classify_heartbeat(
        hb, now=100.0 + 7.5, step_stall_s=5.0, compile_grace_s=600.0
    )
    assert verdict is not None
    assert verdict["phase"] == "decode"
    assert verdict["stalled_s"] == pytest.approx(7.5)
    assert verdict["limit_s"] == 5.0
    # within the threshold: healthy
    assert (
        classify_heartbeat(hb, 104.9, 5.0, 600.0) is None
    )


def test_watchdog_compile_grace_not_tripped():
    """A first-compile pause (compiling=True beat) gets compile_grace_s,
    not step_stall_s — the regression that cost five straight bench
    rounds (VERDICT.md) was exactly a long Mosaic compile being
    indistinguishable from a hang."""
    hb = {"t": 0.0, "kind": "prefill", "compiling": True}
    # way past step_stall_s but inside the compile grace: NOT a stall
    assert classify_heartbeat(hb, 120.0, 5.0, 600.0) is None
    # past even the compile grace: a wedged compile IS a stall
    verdict = classify_heartbeat(hb, 700.0, 5.0, 600.0)
    assert verdict is not None and verdict["compiling"] is True
    assert verdict["limit_s"] == 600.0


def test_watchdog_disabled_and_empty_heartbeat():
    hb = {"t": 0.0, "compiling": False}
    assert classify_heartbeat(hb, 1e9, 0.0, 600.0) is None  # disabled
    assert classify_heartbeat(None, 1e9, 5.0, 600.0) is None


def test_stall_fault_point_registered():
    spec = faults.arm("stall", mode="delay", delay_s=0.0, times=1)
    faults.check("stall")
    assert spec.fired == 1
    faults.reset()


# ------------------------------------- containment partition (fake core)


def _bare_core(resume=True, max_attempts=3, supervised=True):
    """An EngineCore shell with exactly the state _contain_fatal touches
    — no devices, no weights, no thread."""
    core = EngineCore.__new__(EngineCore)
    core.flight = SimpleNamespace(
        record_tick=lambda *a, **k: None,
        crash_snapshot=lambda exc=None: {"error": str(exc)},
        enabled=False,
    )
    core.scheduler = SimpleNamespace(
        running=[], waiting=deque(), slots=[None] * 4
    )
    core._submit_q = queue.Queue()
    core._evac_q = queue.Queue()
    core._pending_chunks = []
    core._checkpointed = []
    core._resume_losses = 0
    core._fatal = None
    core._fatal_suspects = []
    core._crash_snapshot = None
    core._running = True
    core._stalled = False
    core._resume_enabled = resume
    core._max_resume_attempts = max_attempts
    core.on_fatal = (lambda exc: None) if supervised else None
    core._heartbeat = {"t": time.monotonic(), "compiling": False}
    core._wakeup = threading.Event()
    core._contain_lock = threading.Lock()
    core._readback_lock = threading.Lock()
    core._containment_done = False
    return core


def _running_seq(prompt, tokens=()):
    seq = Sequence(prompt_ids=list(prompt), params=greedy(16))
    seq.status = SeqStatus.RUNNING
    seq.slot = 0
    for t in tokens:
        seq.append_token(t)
    return seq


def test_containment_checkpoints_resumable_sequences():
    core = _bare_core()
    running = _running_seq([1, 2, 3], tokens=(9,))
    waiting = Sequence(prompt_ids=[4, 5], params=greedy(4))
    core.scheduler.running.append(running)
    core.scheduler.waiting.append(waiting)
    core._contain_fatal(RuntimeError("boom"))
    kept = core.take_checkpointed()
    assert len(kept) == 2
    assert kept[0] is running and kept[1] is waiting
    assert all(s.status is SeqStatus.WAITING for s in kept)
    assert running.prompt_ids == [1, 2, 3, 9]  # folded
    assert all(s.resume_count == 1 for s in kept)
    assert not running.done_event.is_set()  # still owed, NOT failed
    assert core._fatal is not None
    # second take is empty (the replayer claimed them)
    assert core.take_checkpointed() == []


def test_containment_is_first_entry_only():
    """A stalled engine thread that wakes after the watchdog's
    containment typically raises against the swept state and lands in
    the loop's except handler — the second _contain_fatal must be a
    no-op, or it would overwrite the checkpoint (dropping the
    sequences awaiting replay) and double-fire on_fatal."""
    fired = []
    core = _bare_core()
    core.on_fatal = lambda exc: fired.append(exc)
    seq = _running_seq([1, 2, 3], tokens=(9,))
    core.scheduler.running.append(seq)
    first = EngineStalledError("wedged", stalled_s=9.0, phase="decode")
    assert core.declare_stalled(first) is True
    assert len(fired) == 1
    # the woken thread's secondary exception must change nothing
    assert core._contain_fatal(RuntimeError("woke into swept state")) \
        is False
    assert core._fatal is first
    assert len(fired) == 1
    assert core.take_checkpointed() == [seq]  # checkpoint preserved


def test_containment_gives_up_after_max_resume_attempts():
    core = _bare_core(max_attempts=2)
    tired = _running_seq([1, 2, 3], tokens=(9,))
    tired.resume_count = 2  # already rode through two restarts
    fresh = _running_seq([4, 5, 6])
    core.scheduler.running.extend([tired, fresh])
    core._contain_fatal(RuntimeError("boom"))
    assert core.take_checkpointed() == [fresh]
    assert tired.status is SeqStatus.FAILED
    assert isinstance(tired.error, ResumeExhaustedError)
    assert tired.error.retry_after >= 1.0  # typed 503 + Retry-After


def test_containment_does_not_checkpoint_aborted_or_unsupervised():
    # aborted: the client is gone — no one to resume for
    core = _bare_core()
    gone = _running_seq([1, 2, 3])
    gone.request_abort()
    core.scheduler.running.append(gone)
    core._contain_fatal(RuntimeError("boom"))
    assert core.take_checkpointed() == []
    assert gone.status is SeqStatus.FAILED
    # unsupervised (no on_fatal): the dp-router containment contract —
    # fail raw, never checkpoint into a void
    core = _bare_core(supervised=False)
    seq = _running_seq([1, 2, 3])
    core.scheduler.running.append(seq)
    core._contain_fatal(RuntimeError("boom"))
    assert core.take_checkpointed() == []
    assert seq.status is SeqStatus.FAILED


def test_declare_stalled_contains_off_thread():
    core = _bare_core()
    seq = _running_seq([1, 2, 3], tokens=(7,))
    core.scheduler.running.append(seq)
    exc = EngineStalledError("wedged", stalled_s=9.0, phase="decode")
    assert core.declare_stalled(exc) is True
    assert core._fatal is exc and core._stalled and not core._running
    assert core.take_checkpointed() == [seq]
    # idempotent: a second declaration (or one racing a real crash)
    # reports False and changes nothing
    assert core.declare_stalled(exc) is False


# ------------------------------------------- replay policy (fake cores)


class _FakeReplayCore:
    def __init__(self, fail=False):
        self.submitted = []
        self.ticks = []
        self._fail = fail
        self._fatal = None
        self.scheduler = SimpleNamespace(waiting=[], running=[])
        self.flight = SimpleNamespace(
            record_tick=lambda *a, **k: self.ticks.append(k)
        )

    def submit_existing(self, seq):
        if self._fail:
            raise RuntimeError("submit refused")
        self.submitted.append(seq)


def _bare_supervisor(quarantine=()):
    sup = EngineSupervisor.__new__(EngineSupervisor)
    # the real __init__ builds the RLock guarding the fields declared
    # in supervisor.VGT_LOCK_GUARDS; _replay acquires it
    sup._lock = threading.RLock()
    sup._quarantine = set(quarantine)
    sup._restart_times = []
    sup._recovery = SimpleNamespace(
        backoff_base_s=0.25, backoff_cap_s=30.0
    )
    sup.total_resumed = 0
    sup.total_lost = 0
    sup._pending_resume = []
    sup.last_resume = None
    return sup


def test_replay_excludes_quarantined_poison():
    poison_ids = [3, 1, 666, 4]
    sup = _bare_supervisor(
        quarantine={faults.fingerprint(poison_ids)}
    )
    poison = Sequence(prompt_ids=list(poison_ids), params=greedy(8))
    innocent = Sequence(prompt_ids=[7, 8, 9], params=greedy(8))
    for s in (poison, innocent):
        s.prepare_resume()
    sup._pending_resume = [poison, innocent]
    core = _FakeReplayCore()
    sup._replay(core)
    assert core.submitted == [innocent]
    assert poison.status is SeqStatus.FAILED
    assert isinstance(poison.error, PoisonRequestError)
    assert sup.total_resumed == 1 and sup.total_lost == 1
    # one `resume` flight tick per replayed sequence
    assert len(core.ticks) == 1
    assert core.ticks[0]["seq_id"] == innocent.seq_id
    assert core.ticks[0]["attempt"] == 1


def test_replay_quarantine_keys_on_original_prompt():
    """The fold (prompt += generated) must NOT change the quarantine
    identity: fingerprints key on the ORIGINAL prompt."""
    poison_ids = [3, 1, 666, 4]
    sup = _bare_supervisor(
        quarantine={faults.fingerprint(poison_ids)}
    )
    seq = Sequence(prompt_ids=list(poison_ids), params=greedy(8))
    seq.status = SeqStatus.RUNNING
    seq.append_token(42)  # fold will change prompt_ids
    seq.prepare_resume()
    assert seq.prompt_ids != poison_ids
    sup._pending_resume = [seq]
    core = _FakeReplayCore()
    sup._replay(core)
    assert core.submitted == []
    assert isinstance(seq.error, PoisonRequestError)


def test_replay_resubmit_failure_fails_typed():
    sup = _bare_supervisor()
    seq = Sequence(prompt_ids=[1, 2], params=greedy(4))
    seq.prepare_resume()
    sup._pending_resume = [seq]
    sup._replay(_FakeReplayCore(fail=True))
    assert seq.status is SeqStatus.FAILED
    assert getattr(seq.error, "retry_after", None) is not None
    assert sup.total_lost == 1


def test_dp_redistribute_excludes_quarantined():
    """dp failover must not hand a poison-quarantined request to a
    surviving replica — that would serially kill the survivors."""
    from vgate_tpu.runtime.dp_engine import ReplicatedEngine

    eng = ReplicatedEngine.__new__(ReplicatedEngine)
    survivor = _FakeReplayCore()
    survivor._fatal = None
    dead = SimpleNamespace(_fatal=RuntimeError("dead"))
    eng.replicas = [dead, survivor]
    eng._topology_lock = threading.RLock()
    eng._draining = set()
    eng._corrupt = set()
    eng._recovery = SimpleNamespace(
        backoff_base_s=0.05, backoff_cap_s=0.2
    )
    eng._restart_times = []
    eng.total_failovers = 0
    eng.total_resumed = 0
    eng.total_lost = 0
    poison_ids = [3, 1, 666, 4]
    eng._quarantine = {faults.fingerprint(poison_ids)}
    poison = Sequence(prompt_ids=list(poison_ids), params=greedy(8))
    innocent = Sequence(prompt_ids=[7, 8, 9], params=greedy(8))
    for s in (poison, innocent):
        s.prepare_resume()
    eng._redistribute(0, [poison, innocent])
    assert survivor.submitted == [innocent]
    assert isinstance(poison.error, PoisonRequestError)
    assert eng.total_lost == 1 and eng.total_resumed == 1
    # redistribution's resume tick carries the source replica
    assert survivor.ticks[0]["from_replica"] == 0


# ------------------------------------------ scheduler replay admission


def _scheduler(max_queue=2):
    return Scheduler(
        allocator=PageAllocator(16),
        max_slots=2,
        page_size=4,
        prefill_buckets=[8],
        max_model_len=32,
        max_queue_size=max_queue,
    )


def test_scheduler_add_replayed_bypasses_queue_full():
    sched = _scheduler(max_queue=1)
    sched.add(Sequence(prompt_ids=[1], params=greedy(4)))
    fresh = Sequence(prompt_ids=[2], params=greedy(4))
    with pytest.raises(EngineBusyError):
        sched.add(fresh)
    replayed = Sequence(prompt_ids=[3], params=greedy(4))
    replayed.prepare_resume()
    sched.add(replayed)  # already admitted once; still owed
    assert replayed in sched.waiting


# ===================================================== engine acceptance
#
# Real tiny-dense engine on CPU (compile-heavy): the three ISSUE 5
# acceptance scenarios.  Slow tier, chaos_check.sh runs them.


def rec_config(recovery=None, dp=1, **tpu_overrides):
    tpu = {
        "dp": dp,
        "tp": 1,
        "ep": 1,
        "sp": 1,
        "num_devices": dp,
        "kv_num_pages": 128,
        "kv_page_size": 4,
        "max_batch_slots": 8,
        "prefill_buckets": [8, 16, 32],
        "use_pallas": False,
    }
    tpu.update(tpu_overrides)
    rec = {
        "enabled": True,
        "max_restarts": 6,
        "restart_window_s": 120.0,
        "backoff_base_s": 0.02,
        "backoff_cap_s": 0.2,
        "degraded_probation_s": 0.25,
        "poison_threshold": 99,
        "resume_in_flight": True,
        "max_resume_attempts": 3,
        "step_stall_s": 120.0,
        "compile_grace_s": 600.0,
    }
    rec.update(recovery or {})
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu=tpu,
        scheduler={"max_queue_size": 32},
        recovery=rec,
        logging={"level": "ERROR"},
    )


@pytest.mark.slow
def test_crash_replay_token_identical():
    """Acceptance A: 8 in-flight greedy generations ride an armed
    decode_step fatal to 8 successful completions (no 503), each
    token-identical to an uninterrupted run, with `resume` flight
    ticks and the resumed counter at 8."""
    sup = EngineSupervisor(rec_config(), devices=jax.devices()[:1])
    sup.start()
    try:
        prompts = [[5, 9, 13 + i, 17, 21] for i in range(8)]
        baseline = []
        for p in prompts:
            seq = sup.submit_tokens(p, greedy(12))
            assert seq.done_event.wait(180)
            baseline.append(list(seq.generated_ids))

        resumed_before = metrics.RESUMED_SEQUENCES._value.get()
        # a short armed stall-delay (well under step_stall_s) holds the
        # first tick-with-work long enough that all 8 submissions are
        # enqueued BEFORE the decode fault can fire — deterministically
        # 8 in flight at the crash
        faults.arm("stall", mode="delay", delay_s=0.3, times=1)
        faults.arm("decode_step", mode="raise", kind="transient", times=1)
        seqs = [sup.submit_tokens(p, greedy(12)) for p in prompts]
        for seq, want in zip(seqs, baseline):
            assert seq.done_event.wait(240), "request hung across restart"
            assert seq.status is SeqStatus.FINISHED, seq.error
            assert list(seq.generated_ids) == want
            assert seq.resume_count >= 1
        assert sup.total_resumed == 8
        assert (
            metrics.RESUMED_SEQUENCES._value.get() - resumed_before == 8
        )
        resume_ticks = [
            t for t in sup.core.flight.ticks() if t["kind"] == "resume"
        ]
        assert len(resume_ticks) == 8
        assert sup.last_resume["checkpointed"] == 8
        assert sup.last_resume["replayed"] == 8
        health = sup.health()
        assert health["resumed"] == 8 and health["lost"] == 0
    finally:
        faults.reset()
        sup.stop()


@pytest.mark.slow
def test_stall_watchdog_detects_and_replays():
    """Acceptance B: an armed stall (delay > step_stall_s) is detected
    by the watchdog, classified as EngineStalledError, recovered via
    the supervisor, and the in-flight generation replays token-
    identical — while ordinary serving (first compiles included, which
    run under compile_grace_s) never trips it."""
    sup = EngineSupervisor(
        rec_config(recovery={"step_stall_s": 0.6}),
        devices=jax.devices()[:1],
    )
    sup.start()
    try:
        # first-contact compiles run WAY past step_stall_s=0.6 on CPU;
        # the compiling-aware beats must keep the watchdog quiet
        warm = sup.submit_tokens([5, 9, 13], greedy(12))
        assert warm.done_event.wait(180)
        base = sup.submit_tokens([3, 7, 11, 15], greedy(12))
        assert base.done_event.wait(180)
        assert sup.total_stalls == 0, "compile pause misread as stall"

        faults.arm("stall", mode="delay", delay_s=3.0, times=1)
        seq = sup.submit_tokens([3, 7, 11, 15], greedy(12))
        assert seq.done_event.wait(240), "request hung across stall"
        assert seq.status is SeqStatus.FINISHED, seq.error
        assert list(seq.generated_ids) == list(base.generated_ids)
        assert sup.total_stalls == 1
        assert "EngineStalledError" in sup.last_fatal
        assert any(
            t["kind"] == "stall" for t in sup.last_crash.get("ticks", [])
        )
        assert wait_for(
            lambda: sup.state
            in (HealthState.DEGRADED, HealthState.SERVING)
        )
    finally:
        faults.reset()
        sup.stop()


@pytest.mark.slow
def test_resume_exhausted_gives_up_typed():
    """A request that keeps riding crashes is given up on after
    max_resume_attempts with the typed retryable 503 — not replayed
    forever against a crash-looping engine."""
    sup = EngineSupervisor(
        rec_config(
            recovery={"max_resume_attempts": 1, "max_restarts": 10}
        ),
        devices=jax.devices()[:1],
    )
    sup.start()
    try:
        warm = sup.submit_tokens([5, 9, 13], greedy(4))
        assert warm.done_event.wait(180)
        faults.arm("decode_step", mode="raise", kind="transient", times=2)
        seq = sup.submit_tokens([2, 4, 6, 8], greedy(12))
        assert seq.done_event.wait(240)
        # crash 1: checkpoint+replay (attempt 1); crash 2: give up
        assert seq.status is SeqStatus.FAILED
        assert isinstance(seq.error, ResumeExhaustedError)
        # the loss folds into supervisor accounting on the watcher
        # thread once it processes the second crash
        assert wait_for(lambda: sup.total_lost >= 1, 60)
    finally:
        faults.reset()
        sup.stop()


@pytest.mark.slow
def test_resume_disabled_keeps_failfast_contract():
    """recovery.resume_in_flight=False restores PR 1 semantics: the
    in-flight request fails with the retryable 503 type."""
    from vgate_tpu.errors import EngineRecoveringError

    sup = EngineSupervisor(
        rec_config(recovery={"resume_in_flight": False}),
        devices=jax.devices()[:1],
    )
    sup.start()
    try:
        warm = sup.submit_tokens([5, 9, 13], greedy(4))
        assert warm.done_event.wait(180)
        faults.arm("decode_step", mode="raise", kind="transient", times=1)
        seq = sup.submit_tokens([2, 4, 6], greedy(12))
        assert seq.done_event.wait(240)
        assert seq.status is SeqStatus.FAILED
        assert isinstance(seq.error, EngineRecoveringError)
    finally:
        faults.reset()
        sup.stop()


@pytest.mark.slow
def test_dp_failover_redistributes_and_recovers():
    """Acceptance C: with dp=2, a fatal on one replica redistributes
    its checkpointed residents to the survivor (all complete), /health
    shows the replica detail, and the repair thread's rebuild restores
    SERVING."""
    from vgate_tpu.runtime.dp_engine import ReplicatedEngine

    eng = ReplicatedEngine(rec_config(dp=2), devices=jax.devices()[:2])
    eng.start()
    try:
        for i in range(4):  # warm both replicas
            s = eng.submit_tokens([5, 9, 13 + i], greedy(6))
            assert s.done_event.wait(300)
        assert eng.state is HealthState.SERVING
        health = eng.health()
        assert health["dp"] == 2 and len(health["replicas"]) == 2

        faults.arm("decode_step", mode="raise", kind="transient", times=1)
        seqs = [
            eng.submit_tokens([3, 7, 11 + i], greedy(10))
            for i in range(6)
        ]
        for seq in seqs:
            assert seq.done_event.wait(300), "request hung in failover"
            assert seq.status is SeqStatus.FINISHED, seq.error
        assert eng.total_failovers >= 1
        assert eng.total_resumed >= 1
        # repair rebuilds the dead replica -> full complement again
        assert wait_for(
            lambda: eng.state is HealthState.SERVING, 180
        ), eng.health()
        assert eng.health()["replicas_alive"] == 2
        s = eng.submit_tokens([2, 4, 6], greedy(4))
        assert s.done_event.wait(300)
        assert s.status is SeqStatus.FINISHED
    finally:
        faults.reset()
        eng.stop()
