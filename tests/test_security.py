"""Auth + rate limiting (reference: tests/test_security.py:37-120 window math,
:169-320 middleware behavior via in-process test client)."""

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu.config import load_config
from vgate_tpu.security import RateLimiter, build_security_middleware


class TestRateLimiterWindow:
    def test_allows_under_limit(self):
        rl = RateLimiter(requests_per_minute=3)
        for _ in range(3):
            allowed, _ = rl.check("k", now=100.0)
            assert allowed

    def test_blocks_over_limit(self):
        rl = RateLimiter(requests_per_minute=2)
        rl.check("k", now=100.0)
        rl.check("k", now=101.0)
        allowed, headers = rl.check("k", now=102.0)
        assert not allowed
        assert headers["X-RateLimit-Remaining"] == "0"
        assert int(headers["Retry-After"]) >= 1

    def test_window_slides(self):
        rl = RateLimiter(requests_per_minute=1, window_s=60.0)
        assert rl.check("k", now=100.0)[0]
        assert not rl.check("k", now=130.0)[0]
        assert rl.check("k", now=161.0)[0]  # first entry expired

    def test_per_key_limits(self):
        rl = RateLimiter(requests_per_minute=1, per_key_limits={"vip": 100})
        assert rl.limit_for("vip") == 100
        assert rl.limit_for("other") == 1

    def test_keys_are_independent(self):
        rl = RateLimiter(requests_per_minute=1)
        assert rl.check("a", now=1.0)[0]
        assert rl.check("b", now=1.0)[0]
        assert not rl.check("a", now=2.0)[0]

    def test_headers_report_remaining(self):
        rl = RateLimiter(requests_per_minute=5)
        _, headers = rl.check("k", now=1.0)
        assert headers["X-RateLimit-Limit"] == "5"
        assert headers["X-RateLimit-Remaining"] == "4"

    def test_stale_keys_are_swept(self):
        """Regression: every distinct key used to leak a dict entry
        forever — the sweep must drop keys whose whole window expired,
        while keys with live timestamps survive."""
        rl = RateLimiter(requests_per_minute=10, window_s=60.0)
        for i in range(500):
            rl.check(f"rotating-{i}", now=100.0)
        rl.check("steady", now=100.0)
        assert len(rl._windows) == 501
        # a check one window later triggers the sweep; only keys with
        # in-window activity remain
        rl.check("steady", now=161.0)
        assert set(rl._windows) == {"steady"}
        assert len(rl._windows["steady"]) == 1  # old stamp evicted too

    def test_windows_are_deques(self):
        """The per-key window must not be an O(n)-pop list."""
        from collections import deque

        rl = RateLimiter(requests_per_minute=3)
        rl.check("k", now=1.0)
        assert isinstance(rl._windows["k"], deque)

    def test_sweep_preserves_over_limit_state(self):
        rl = RateLimiter(requests_per_minute=2, window_s=60.0)
        rl.check("k", now=100.0)
        rl.check("k", now=140.0)
        # sweep fires (>= window since _last_sweep=0) but the key's
        # recent stamps survive and still count against the limit
        allowed, _ = rl.check("k", now=150.0)
        assert not allowed
        assert rl.get_stats()["k"] == 2


def _secured_app(config):
    async def ok(request):
        return web.json_response({"ok": True})

    app = web.Application(middlewares=[build_security_middleware(config)])
    app.router.add_get("/v1/thing", ok)
    app.router.add_get("/health", ok)
    return app


async def _client(config):
    client = TestClient(TestServer(_secured_app(config)))
    await client.start_server()
    return client


SEC_CONFIG = dict(
    security={"enabled": True, "api_keys": ["sk-good"]},
    rate_limit={"enabled": True, "requests_per_minute": 2},
)


async def test_missing_key_is_401():
    client = await _client(load_config(**SEC_CONFIG))
    try:
        resp = await client.get("/v1/thing")
        assert resp.status == 401
        body = await resp.json()
        assert body["error"]["type"] == "authentication_error"
    finally:
        await client.close()


async def test_invalid_key_is_401():
    client = await _client(load_config(**SEC_CONFIG))
    try:
        resp = await client.get(
            "/v1/thing", headers={"Authorization": "Bearer sk-bad"}
        )
        assert resp.status == 401
    finally:
        await client.close()


async def test_valid_key_passes_with_headers():
    client = await _client(load_config(**SEC_CONFIG))
    try:
        resp = await client.get(
            "/v1/thing", headers={"Authorization": "Bearer sk-good"}
        )
        assert resp.status == 200
        assert resp.headers["X-RateLimit-Limit"] == "2"
    finally:
        await client.close()


async def test_rate_limit_429_with_retry_after():
    client = await _client(load_config(**SEC_CONFIG))
    try:
        headers = {"Authorization": "Bearer sk-good"}
        await client.get("/v1/thing", headers=headers)
        await client.get("/v1/thing", headers=headers)
        resp = await client.get("/v1/thing", headers=headers)
        assert resp.status == 429
        assert "Retry-After" in resp.headers
        body = await resp.json()
        assert body["error"]["type"] == "rate_limit_error"
    finally:
        await client.close()


async def test_exempt_paths_skip_auth():
    client = await _client(load_config(**SEC_CONFIG))
    try:
        resp = await client.get("/health")
        assert resp.status == 200
    finally:
        await client.close()


async def test_security_disabled_passes_everything():
    client = await _client(load_config())
    try:
        resp = await client.get("/v1/thing")
        assert resp.status == 200
    finally:
        await client.close()
