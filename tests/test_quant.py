"""int8 weight-only quantization: roundtrip accuracy, memory, and the
quantized engine end-to-end."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.models.decoder import init_params, prefill_forward
from vgate_tpu.models.specs import TINY_DENSE, TINY_MOE
from vgate_tpu.ops.quant import (
    QTensor,
    quantize_decoder_params,
    quantize_stacked,
    quantize_tensor,
    weighted_einsum,
)


def test_quantize_roundtrip_error_small():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(64, 128)) * 0.02, jnp.float32)
    qt = quantize_tensor(w)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (128,)
    deq = qt.q.astype(jnp.float32) * qt.scale
    rel = np.abs(np.asarray(deq - w)).max() / np.abs(np.asarray(w)).max()
    assert rel < 0.01  # <1% of the weight range per element


def test_quantize_stacked_per_layer_scales():
    rng = np.random.default_rng(1)
    w = np.zeros((2, 8, 16), np.float32)
    w[0] = rng.normal(size=(8, 16)) * 0.01
    w[1] = rng.normal(size=(8, 16)) * 10.0  # very different magnitude
    qt = quantize_stacked(jnp.asarray(w))
    assert qt.scale.shape == (2, 16)
    # layer 1's scale must be ~1000x layer 0's
    assert float(qt.scale[1].mean() / qt.scale[0].mean()) > 100


def test_weighted_einsum_matches_dense():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 32)) * 0.02, jnp.float32)
    dense = weighted_einsum("bd,dh->bh", x, w)
    quant = weighted_einsum("bd,dh->bh", x, quantize_tensor(w))
    err = np.abs(np.asarray(dense - quant)).max()
    assert err < np.abs(np.asarray(dense)).max() * 0.02


def test_quantized_prefill_close_to_fp32():
    spec = TINY_DENSE
    params = init_params(spec, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_decoder_params(params, spec)
    B, S = 1, 16
    tokens = jnp.asarray(np.full((B, S), 7, np.int32))
    lens = jnp.asarray([10], jnp.int32)
    k = jnp.zeros((spec.num_layers, spec.num_kv_heads, 2, 16, spec.head_dim),
                  jnp.float32)
    v = jnp.zeros_like(k)
    pt = jnp.asarray([[1]], jnp.int32)
    ref, _, _ = prefill_forward(params, spec, tokens, lens, k, v, pt)
    k2, v2 = jnp.zeros_like(k), jnp.zeros_like(v)
    got, _, _ = prefill_forward(qparams, spec, tokens, lens, k2, v2, pt)
    # logits agree in ranking-relevant magnitude
    diff = np.abs(np.asarray(ref) - np.asarray(got)).max()
    spread = np.asarray(ref).std()
    assert diff < spread  # quantization noise well under logit spread


def test_quantized_weights_halve_memory():
    spec = TINY_DENSE
    params = init_params(spec, jax.random.PRNGKey(0), jnp.bfloat16)
    qparams = quantize_decoder_params(params, spec)
    def nbytes(tree):
        return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))
    dense_proj = nbytes(params["layers"]["q"]["w"])
    quant_proj = nbytes(qparams["layers"]["q"]["w"])
    assert quant_proj < dense_proj * 0.6  # int8 vs bf16 + small scales


def test_moe_quantization_close_to_fp32():
    """MoE expert weights quantize per (layer, expert, out-channel); the
    int8 logits must stay well within the fp32 logit spread."""
    import numpy as np

    from vgate_tpu.models.decoder import prefill_forward

    spec = TINY_MOE
    params = init_params(spec, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_decoder_params(params, spec)
    # expert weights became QTensor with per-expert scales
    qw = qparams["layers"]["gate"]["w"]
    assert qw.scale.shape == (
        spec.num_layers, spec.num_experts, spec.intermediate_size
    )

    B, S, ps = 1, 8, 4
    n_pages = 1 + B * (S // ps)
    shape = (spec.num_layers, spec.num_kv_heads, n_pages, ps, spec.head_dim)
    tokens = jnp.asarray(np.arange(S)[None, :] % spec.vocab_size, jnp.int32)
    seq_lens = jnp.asarray([S], jnp.int32)
    pt = jnp.asarray(np.arange(S // ps)[None, :] + 1, jnp.int32)

    def run(p):
        logits, _, _ = prefill_forward(
            p, spec, tokens, seq_lens,
            jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32), pt,
        )
        return np.asarray(logits)

    ref, quant = run(params), run(qparams)
    spread = float(ref.max() - ref.min())
    assert float(np.abs(ref - quant).max()) < 0.1 * spread


def test_quantized_engine_end_to_end():
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    config = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "quantization": "int8",
        },
        tpu={"dp": 1, "tp": 1, "ep": 1, "sp": 1, "kv_num_pages": 64,
             "kv_page_size": 4, "max_batch_slots": 2,
             "prefill_buckets": [16]},
        logging={"level": "WARNING"},
    )
    core = EngineCore(config, devices=jax.devices()[:1])
    core.start()
    try:
        [result] = core.generate(
            ["quantized probe"], [SamplingParams(max_tokens=4, temperature=0.0)]
        )
        assert result["num_tokens"] >= 1
        assert isinstance(core.params["layers"]["q"]["w"], QTensor)
    finally:
        core.stop()


def test_int4_roundtrip_and_memory():
    """int4 stores two values per byte (PackedQTensor) — jnp.int4 arrays
    cannot cross a jit boundary on the TPU runtime — and the pack/unpack
    pair is exact for values in [-7, 7]."""
    from vgate_tpu.ops.quant import (
        PackedQTensor,
        pack_int4,
        unpack_int4,
    )

    rng = np.random.default_rng(3)
    # pack/unpack roundtrip is exact
    vals = jnp.asarray(
        rng.integers(-7, 8, size=(6, 10, 32)), jnp.int8
    )
    np.testing.assert_array_equal(
        np.asarray(unpack_int4(pack_int4(vals))), np.asarray(vals)
    )

    w = jnp.asarray(rng.normal(size=(64, 128)) * 0.02, jnp.float32)
    qt = quantize_tensor(w, bits=4)
    assert isinstance(qt, PackedQTensor)
    assert str(qt.q_packed.dtype) == "uint8"
    assert qt.q_packed.shape == (32, 128)  # half the in-dim: 2 per byte
    deq = unpack_int4(qt.q_packed).astype(jnp.float32) * qt.scale
    rel = np.abs(np.asarray(deq - w)).max() / np.abs(np.asarray(w)).max()
    assert rel < 0.08  # 4-bit: ~1/15 of range per channel


def test_int4_weighted_einsum_close():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 32)) * 0.02, jnp.float32)
    dense = weighted_einsum("bd,dh->bh", x, w)
    quant = weighted_einsum("bd,dh->bh", x, quantize_tensor(w, bits=4))
    err = np.abs(np.asarray(dense - quant)).max()
    assert err < np.abs(np.asarray(dense)).max() * 0.15


def test_int4_engine_end_to_end():
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    config = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "quantization": "int4",
        },
        tpu={"dp": 1, "tp": 1, "ep": 1, "sp": 1, "kv_num_pages": 64,
             "kv_page_size": 4, "max_batch_slots": 2,
             "prefill_buckets": [16]},
        logging={"level": "WARNING"},
    )
    core = EngineCore(config, devices=jax.devices()[:1])
    core.start()
    try:
        [result] = core.generate(
            ["int4 probe"], [SamplingParams(max_tokens=4, temperature=0.0)]
        )
        assert result["num_tokens"] >= 1
        qw = core.params["layers"]["q"]["w"]
        assert str(qw.q_packed.dtype) == "uint8"
    finally:
        core.stop()


def test_bad_quantization_value_rejected():
    from vgate_tpu.config import load_config

    with pytest.raises(Exception):
        load_config(model={"quantization": "fp8"})


def test_quantized_gemma2_engine_smoke():
    """int8 weight-only quantization composes with the Gemma-2 family
    (sandwich norms pass through untouched; GeGLU/q-scale/softcap run on
    dequantized projections)."""
    import jax

    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    config = load_config(
        model={
            "model_id": "tiny-gemma2",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "quantization": "int8",
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 2, "prefill_buckets": [8],
            "use_pallas": False,
        },
        scheduler={"max_queue_size": 8},
        logging={"level": "WARNING"},
    )
    core = EngineCore(config, devices=jax.devices()[:1])
    core.start()
    try:
        [r] = core.generate(
            ["quantized gemma probe"],
            [SamplingParams(max_tokens=12, temperature=0.0)],
        )
        assert r["num_tokens"] == 12 or r["finish_reason"] == "stop"
    finally:
        core.stop()


# ------------------------------------------------------- int8_native (W8A8)


def test_int8_native_einsum_close_to_dequant():
    """The native s8 x s8 -> s32 path adds per-token activation
    quantization on top of weight quantization; its result must stay
    within a small relative error of the dequant reference path."""
    from vgate_tpu.ops.quant import int8_native_einsum

    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(6, 64)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(64, 48)) * 0.05, jnp.float32)
    qt = quantize_tensor(w)
    ref = weighted_einsum("...d,dh->...h", x, qt)
    got = int8_native_einsum("...d,dh->...h", x, qt, jnp.bfloat16)
    assert got.dtype == jnp.bfloat16
    scale = np.abs(np.asarray(ref, np.float32)).max()
    err = np.abs(np.asarray(ref, np.float32) - np.asarray(got, np.float32))
    assert err.max() < scale * 0.04


def test_int8_native_w4a8_close_to_dequant():
    """W4A8: packed int4 nibble planes contract as int8 against the
    quantized activation halves — two native GEMMs, same semantics as
    packed_einsum * scale."""
    from vgate_tpu.ops.quant import int8_native_einsum, quantize_tensor

    rng = np.random.default_rng(8)
    x = jnp.asarray(rng.normal(size=(4, 64)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(64, 32)) * 0.05, jnp.float32)
    qt = quantize_tensor(w, bits=4)
    ref = weighted_einsum("...d,dh->...h", x, qt)
    got = int8_native_einsum("...d,dh->...h", x, qt, jnp.bfloat16)
    scale = np.abs(np.asarray(ref, np.float32)).max()
    err = np.abs(np.asarray(ref, np.float32) - np.asarray(got, np.float32))
    assert err.max() < scale * 0.06


def test_weighted_einsum_int8_native_flag_dispatch():
    """int8_native routes eligible 2D contractions through the native
    path (result differs slightly from dequant due to activation
    quantization) and leaves ineligible shapes on the jnp path."""
    from vgate_tpu.ops.quant import quantize_stacked

    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(64, 32)) * 0.05, jnp.float32)
    qt = quantize_tensor(w)
    a = weighted_einsum("...d,dh->...h", x, qt, int8_native=True)
    b = weighted_einsum("...d,dh->...h", x, qt)
    assert not np.allclose(np.asarray(a), np.asarray(b), atol=0)
    scale = np.abs(np.asarray(b)).max()
    assert np.abs(np.asarray(a) - np.asarray(b)).max() < scale * 0.04
    # stacked (3D) weights and expert einsums are ineligible for the
    # native path (same eligibility seam as the fused kernels)
    from vgate_tpu.ops.quant import _use_quant_kernel

    ws = quantize_stacked(jnp.asarray(rng.normal(size=(2, 8, 16)), jnp.float32))
    assert not _use_quant_kernel("lbd,ldh->lbh", ws)
    assert not _use_quant_kernel("ecd,edf->ecf", ws)
    assert _use_quant_kernel("...d,dh->...h", qt)


def test_int8_native_engine_end_to_end():
    """A quantized engine with tpu.int8_native serves tokens and stays
    numerically sane (same harness as test_quantized_engine_end_to_end)."""
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    cfg = load_config(
        model={
            "model_id": "tiny-dense",
            "dtype": "float32",
            "max_model_len": 64,
            "quantization": "int8",
        },
        tpu={
            "platform": "cpu",
            "use_pallas": False,
            "int8_native": True,
            "kv_num_pages": 64,
            "kv_page_size": 4,
            "max_batch_slots": 2,
            "prefill_buckets": [16],
        },
    )
    core = EngineCore(cfg, devices=jax.devices()[:1])
    assert core.spec.int8_native
    core.start()
    try:
        seq = core.submit_tokens(
            [3, 5, 7, 11], SamplingParams(max_tokens=6, temperature=0.0)
        )
        assert seq.done_event.wait(300)
        assert seq.num_output_tokens == 6
    finally:
        core.stop()


def test_int4_native_engine_end_to_end():
    """W4A8: an int4-quantized engine with tpu.int8_native serves tokens
    (nibble planes contract as int8 on the native path; packed bytes in
    HBM)."""
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    config = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "quantization": "int4",
        },
        tpu={"dp": 1, "tp": 1, "ep": 1, "sp": 1, "int8_native": True,
             "kv_num_pages": 64, "kv_page_size": 4, "max_batch_slots": 2,
             "prefill_buckets": [16]},
        logging={"level": "WARNING"},
    )
    core = EngineCore(config, devices=jax.devices()[:1])
    assert core.spec.int8_native
    core.start()
    try:
        [result] = core.generate(
            ["w4a8 probe"], [SamplingParams(max_tokens=4, temperature=0.0)]
        )
        assert result["num_tokens"] >= 1
        assert str(core.params["layers"]["q"]["w"].q_packed.dtype) == "uint8"
    finally:
        core.stop()


def test_int8_native_sp_engine_end_to_end():
    """int8_native under an sp=2 mesh: the native-path GEMMs are pure
    jnp and must auto-partition through the ring-prefill / sp-decode
    programs (the claim config.py makes for tpu.int8_native)."""
    import jax as _jax

    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    if _jax.device_count() < 2:
        pytest.skip("needs >=2 devices")
    config = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "quantization": "int8",
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 2,
            "num_devices": 2, "int8_native": True,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 2, "prefill_buckets": [16, 32],
            "use_pallas": False,
        },
        logging={"level": "WARNING"},
    )
    core = EngineCore(config, devices=_jax.devices()[:2])
    assert core.spec.int8_native
    core.start()
    try:
        long_prompt = " ".join(["w8a8"] * 24)
        [r] = core.generate(
            [long_prompt], [SamplingParams(max_tokens=6, temperature=0.0)]
        )
        assert r["num_tokens"] >= 1
        assert core.get_stats()["mesh"]["sp"] == 2
    finally:
        core.stop()


def test_moe_int8_native_close_to_dequant():
    """Expert GEMMs on the native s8xs8->s32 path must track the dequant
    expert path within activation-quant noise (per-(expert,row) scales)."""
    import dataclasses

    from vgate_tpu.models.decoder import prefill_forward

    spec = TINY_MOE
    params = init_params(spec, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_decoder_params(params, spec)

    B, S, ps = 1, 8, 4
    n_pages = 1 + B * (S // ps)
    shape = (spec.num_layers, spec.num_kv_heads, n_pages, ps, spec.head_dim)
    tokens = jnp.asarray(np.arange(S)[None, :] % spec.vocab_size, jnp.int32)
    seq_lens = jnp.asarray([S], jnp.int32)
    pt = jnp.asarray(np.arange(S // ps)[None, :] + 1, jnp.int32)

    def run(p, sp):
        logits, _, _ = prefill_forward(
            p, sp, tokens, seq_lens,
            jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32), pt,
        )
        return np.asarray(logits)

    ref = run(qparams, spec)
    native = run(qparams, dataclasses.replace(spec, int8_native=True))
    spread = float(ref.max() - ref.min())
    assert float(np.abs(ref - native).max()) < 0.1 * spread
    assert not np.array_equal(ref, native)  # the native path actually ran


def test_moe_int8_native_engine_end_to_end():
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.config import load_config
    from vgate_tpu.runtime.engine_core import EngineCore

    config = load_config(
        model={
            "model_id": "tiny-moe",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "quantization": "int8",
        },
        tpu={"dp": 1, "tp": 1, "ep": 1, "sp": 1, "int8_native": True,
             "kv_num_pages": 64, "kv_page_size": 4, "max_batch_slots": 2,
             "prefill_buckets": [16]},
        logging={"level": "WARNING"},
    )
    core = EngineCore(config, devices=jax.devices()[:1])
    assert core.spec.int8_native
    core.start()
    try:
        [result] = core.generate(
            ["moe w8a8 probe"], [SamplingParams(max_tokens=4, temperature=0.0)]
        )
        assert result["num_tokens"] >= 1
    finally:
        core.stop()


def test_moe_int4_native_close_to_dequant():
    """W4A8 experts: packed-int4 expert weights on the native path must
    track the packed dequant path within activation-quant noise (the
    [E, D/2, F] nibble split + [E, 1, out] scale broadcast)."""
    import dataclasses

    from vgate_tpu.models.decoder import prefill_forward

    spec = TINY_MOE
    params = init_params(spec, jax.random.PRNGKey(0), jnp.float32)
    qparams = quantize_decoder_params(params, spec, bits=4)

    B, S, ps = 1, 8, 4
    n_pages = 1 + B * (S // ps)
    shape = (spec.num_layers, spec.num_kv_heads, n_pages, ps, spec.head_dim)
    tokens = jnp.asarray(np.arange(S)[None, :] % spec.vocab_size, jnp.int32)
    seq_lens = jnp.asarray([S], jnp.int32)
    pt = jnp.asarray(np.arange(S // ps)[None, :] + 1, jnp.int32)

    def run(p, sp):
        logits, _, _ = prefill_forward(
            p, sp, tokens, seq_lens,
            jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32), pt,
        )
        return np.asarray(logits)

    ref = run(qparams, spec)
    native = run(qparams, dataclasses.replace(spec, int8_native=True))
    spread = float(ref.max() - ref.min())
    assert float(np.abs(ref - native).max()) < 0.12 * spread
    assert not np.array_equal(ref, native)
