"""Durable request journal + idempotency keys (ISSUE 20 tentpole).

Covers the admission state machine (fresh → pending → settled/failed),
replay-vs-duplicate-vs-await semantics, crash durability (torn tail,
truncated-mid-record fuzz), compaction, and retention expiry.
"""

import json
import os
import random

import pytest

from vgate_tpu.errors import DuplicateRequestError
from vgate_tpu.runtime.journal import (
    FAILED,
    PENDING,
    SETTLED,
    RequestJournal,
)

SNAP = {"model": "m", "prompt": "p", "submit": {"max_tokens": 4}}
RESULT = {"id": "cmpl-1", "choices": [{"text": "hello"}]}


def _path(tmp_path):
    return str(tmp_path / "journal.jsonl")


# ------------------------------------------------------- state machine


def test_fresh_then_duplicate_then_replay(tmp_path):
    j = RequestJournal(_path(tmp_path))
    outcome, result = j.begin("k1", "r1", "/v1/completions", SNAP)
    assert (outcome, result) == ("fresh", None)
    # same key, same lifetime, still pending → typed duplicate
    with pytest.raises(DuplicateRequestError):
        j.begin("k1", "r2", "/v1/completions", SNAP)
    j.settle("k1", RESULT)
    outcome, result = j.begin("k1", "r3", "/v1/completions", SNAP)
    assert outcome == "replay"
    assert result == RESULT  # the IDENTICAL stored body, zero recompute
    j.close()


def test_failed_key_released_for_fresh_run(tmp_path):
    j = RequestJournal(_path(tmp_path))
    j.begin("k1", "r1", "/v1/completions", SNAP)
    j.fail("k1")
    outcome, _ = j.begin("k1", "r2", "/v1/completions", SNAP)
    assert outcome == "fresh"  # a failure is not replayed
    j.close()


def test_in_memory_mode_no_path():
    j = RequestJournal(None)
    assert j.begin("k", "r", "/v1/completions", SNAP)[0] == "fresh"
    with pytest.raises(DuplicateRequestError):
        j.begin("k", "r", "/v1/completions", SNAP)
    j.settle("k", RESULT)
    assert j.begin("k", "r", "/v1/completions", SNAP) == ("replay", RESULT)
    j.close()


def test_retention_expired_settle_treated_fresh(tmp_path):
    j = RequestJournal(_path(tmp_path), retention_s=0.0)
    j.begin("k", "r", "/v1/completions", SNAP)
    j.settle("k", RESULT)
    # retention 0: instantly past the replay window
    assert j.begin("k", "r", "/v1/completions", SNAP)[0] == "fresh"
    j.close()


# ----------------------------------------------------- restart semantics


def test_restart_pending_is_inherited_await(tmp_path):
    path = _path(tmp_path)
    j = RequestJournal(path)
    j.begin("k1", "r1", "/v1/completions", SNAP)
    j.close()  # crash between accept and settle

    j2 = RequestJournal(path)
    pending = j2.pending()
    assert [r.key for r in pending] == ["k1"]
    assert pending[0].inherited
    assert pending[0].snapshot == SNAP
    # a retry of an inherited pending key WAITS (the original attempt
    # died with the predecessor — a 409 would dead-end the client)
    assert j2.begin("k1", "r1", "/v1/completions", SNAP) == ("await", None)
    # the startup replay settles it; the poll then serves
    j2.settle("k1", RESULT)
    assert j2.begin("k1", "r1", "/v1/completions", SNAP) == (
        "replay", RESULT,
    )
    j2.close()


def test_restart_settled_replays_identically(tmp_path):
    path = _path(tmp_path)
    j = RequestJournal(path)
    j.begin("k1", "r1", "/v1/chat/completions", SNAP)
    j.settle("k1", RESULT)
    j.close()

    j2 = RequestJournal(path)
    assert j2.begin("k1", "r1", "/v1/chat/completions", SNAP) == (
        "replay", RESULT,
    )
    j2.close()


def test_torn_tail_dropped_and_recovered(tmp_path):
    path = _path(tmp_path)
    j = RequestJournal(path)
    j.begin("k1", "r1", "/v1/completions", SNAP)
    j.settle("k1", RESULT)
    j.close()
    # simulate a crash mid-append: half a record, no newline
    with open(path, "ab") as fh:
        fh.write(b'{"op":"accept","key":"k2","request')

    j2 = RequestJournal(path)
    assert j2.stats()["torn_tail_recovered"]
    assert j2.lookup("k1").state == SETTLED
    assert j2.lookup("k2") is None
    # the rewrite leaves a clean boundary: appends + reload still work
    j2.begin("k3", "r3", "/v1/completions", SNAP)
    j2.close()
    j3 = RequestJournal(path)
    assert j3.lookup("k3").state == PENDING
    j3.close()


def test_corruption_mid_file_raises(tmp_path):
    path = _path(tmp_path)
    j = RequestJournal(path)
    j.begin("k1", "r1", "/v1/completions", SNAP)
    j.close()
    with open(path, "ab") as fh:
        fh.write(b"garbage not json\n")
        fh.write(
            json.dumps({
                "op": "accept", "key": "k2", "request_id": "r2",
                "endpoint": "/v1/completions", "snapshot": {}, "t": 1.0,
            }).encode() + b"\n"
        )
    with pytest.raises(RuntimeError, match="corrupt"):
        RequestJournal(path)


def test_truncation_fuzz_never_crashes(tmp_path):
    """Seeded fuzz (ISSUE 20 satellite): truncate the journal at every
    kind of byte offset a crash can leave and assert the loader either
    recovers (dropping at most the torn tail) or raises the typed
    corruption error — never a hang, never an unhandled exception."""
    rng = random.Random(2020)
    path = _path(tmp_path)
    j = RequestJournal(path)
    for i in range(20):
        j.begin(f"k{i}", f"r{i}", "/v1/completions", SNAP)
        if i % 2 == 0:
            j.settle(f"k{i}", {"i": i})
    j.close()
    blob = open(path, "rb").read()
    assert len(blob) > 200
    for _ in range(60):
        cut = rng.randrange(1, len(blob))
        with open(path, "wb") as fh:
            fh.write(blob[:cut])
        j2 = RequestJournal(path)
        # whatever survived is internally consistent: every settled
        # record still carries its result body
        for rec in j2._records.values():
            if rec.state == SETTLED:
                assert rec.result is not None
            assert rec.inherited
        j2.close()


# ------------------------------------------------------------ compaction


def test_compaction_drops_failed_keeps_pending(tmp_path):
    path = _path(tmp_path)
    j = RequestJournal(path, max_bytes=1)  # compact on every append
    j.begin("pend", "r1", "/v1/completions", SNAP)
    j.begin("done", "r2", "/v1/completions", SNAP)
    j.settle("done", RESULT)
    j.begin("dead", "r3", "/v1/completions", SNAP)
    j.fail("dead")
    j.close()

    j2 = RequestJournal(path)
    assert j2.lookup("pend").state == PENDING
    assert j2.lookup("done").state == SETTLED
    assert j2.lookup("dead") is None  # failed records compact away
    j2.close()
    # FAILED constant is part of the public surface even though
    # compaction removes those records from disk
    assert FAILED == "failed"


def test_compaction_bounds_file_size(tmp_path):
    path = _path(tmp_path)
    j = RequestJournal(path, max_bytes=4096, retention_s=0.0)
    for i in range(200):
        j.begin(f"k{i}", f"r{i}", "/v1/completions", SNAP)
        j.fail(f"k{i}")
    j.close()
    assert os.path.getsize(path) < 4096 * 2
