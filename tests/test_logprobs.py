"""OpenAI-style logprobs through the sampler, engine, and cache key
(beyond the reference's API surface — its schema has no logprobs field,
vgate-client/vgate_client/models.py:32-37)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.cache import ResultCache
from vgate_tpu.config import load_config
from vgate_tpu.ops.sampling import sample_tokens, sample_tokens_with_logprobs
from vgate_tpu.runtime.engine_core import EngineCore


def test_sampler_logprobs_are_log_softmax_of_raw_logits():
    rng = np.random.default_rng(5)
    B, V = 4, 64
    logits = jnp.asarray(rng.normal(size=(B, V)) * 3, jnp.float32)
    temps = jnp.asarray([0.0, 0.0, 0.9, 0.9], jnp.float32)
    ones = jnp.ones((B,), jnp.float32)
    zeros = jnp.zeros((B,), jnp.int32)
    key = jax.random.PRNGKey(0)
    toks, lp, tids, tlps = sample_tokens_with_logprobs(
        logits, temps, ones, zeros, key, num_top=5
    )
    ref = np.asarray(jax.nn.log_softmax(logits, axis=-1))
    toks_np = np.asarray(toks)
    for b in range(B):
        # chosen logprob is the raw log-softmax at the chosen token
        np.testing.assert_allclose(
            float(lp[b]), ref[b, toks_np[b]], rtol=1e-5, atol=1e-5
        )
        # top list is the top-5 of the raw distribution, sorted desc
        expect_ids = np.argsort(-ref[b])[:5]
        np.testing.assert_array_equal(np.asarray(tids[b]), expect_ids)
        np.testing.assert_allclose(
            np.asarray(tlps[b]), ref[b, expect_ids], rtol=1e-5, atol=1e-5
        )
    # greedy rows choose the argmax == first top entry
    assert toks_np[0] == int(np.asarray(tids[0, 0]))
    # and the sampled token matches plain sample_tokens exactly
    plain = sample_tokens(logits, temps, ones, zeros, key)
    np.testing.assert_array_equal(toks_np, np.asarray(plain))


def test_cache_key_distinguishes_logprob_requests():
    base = dict(temperature=0.0, top_p=1.0, max_tokens=8)
    a = ResultCache.make_key("p", **base)
    b = ResultCache.make_key("p", **base, logprobs=(True, 3))
    c = ResultCache.make_key("p", **base, logprobs=(True, 0))
    assert len({a, b, c}) == 3


def engine_config(**tpu_overrides):
    tpu = {
        "dp": 1, "tp": 1, "ep": 1, "sp": 1,
        "kv_num_pages": 64, "kv_page_size": 4,
        "max_batch_slots": 4, "prefill_buckets": [8, 16],
        "use_pallas": False,
    }
    tpu.update(tpu_overrides)
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu=tpu,
        scheduler={"max_queue_size": 16},
        logging={"level": "WARNING"},
    )


@pytest.fixture(scope="module")
def lp_engine():
    core = EngineCore(engine_config(), devices=jax.devices()[:1])
    core.start()
    yield core
    core.stop()


def test_engine_returns_aligned_logprobs(lp_engine):
    [r] = lp_engine.generate(
        ["logprob probe"],
        [SamplingParams(max_tokens=9, temperature=0.0, logprobs=True,
                        top_logprobs=3)],
    )
    lps = r["logprobs"]
    assert len(lps) == r["num_tokens"] == len(r["token_ids"])
    for entry, tid in zip(lps, r["token_ids"]):
        assert entry["token_id"] == tid
        assert entry["logprob"] <= 0.0
        assert len(entry["top_logprobs"]) == 3
        # greedy: the chosen token IS the most likely alternative
        assert entry["top_logprobs"][0]["token_id"] == tid
        # alternatives are sorted descending
        alt = [t["logprob"] for t in entry["top_logprobs"]]
        assert alt == sorted(alt, reverse=True)
        assert isinstance(entry["token"], str)


def test_logprobs_do_not_change_tokens(lp_engine):
    """The logprobs program variant must sample identically to the plain
    one (same sampler core, same keys)."""
    prompt = "variant parity probe"
    [plain] = lp_engine.generate(
        [prompt], [SamplingParams(max_tokens=8, temperature=0.0)]
    )
    [with_lp] = lp_engine.generate(
        [prompt],
        [SamplingParams(max_tokens=8, temperature=0.0, logprobs=True)],
    )
    assert plain["token_ids"] == with_lp["token_ids"]
    assert "logprobs" not in plain
    assert len(with_lp["logprobs"]) == 8
    # logprobs=True without top_logprobs: empty alternatives list
    assert with_lp["logprobs"][0]["top_logprobs"] == []


def test_mixed_batch_only_requesters_get_logprobs(lp_engine):
    results = lp_engine.generate(
        ["mixed one", "mixed two"],
        [
            SamplingParams(max_tokens=6, temperature=0.0, logprobs=True,
                           top_logprobs=2),
            SamplingParams(max_tokens=6, temperature=0.0),
        ],
    )
    assert len(results[0]["logprobs"]) == 6
    assert "logprobs" not in results[1]


def test_speculative_engine_logprobs_full_length():
    core = EngineCore(
        engine_config(speculative_k=3), devices=jax.devices()[:1]
    )
    core.start()
    try:
        [r] = core.generate(
            ["spec logprob probe"],
            [SamplingParams(max_tokens=10, temperature=0.0, logprobs=True,
                            top_logprobs=2)],
        )
        assert len(r["logprobs"]) == r["num_tokens"] == 10
        for entry, tid in zip(r["logprobs"], r["token_ids"]):
            assert entry["token_id"] == tid
            assert entry["top_logprobs"][0]["token_id"] == tid  # greedy
    finally:
        core.stop()


# ------------------------------------------------------------- HTTP path

def http_config():
    """Gateway config for the in-process HTTP tests (the engine half
    matches engine_config(); num_devices pinned for app-created cores)."""
    tpu = {
        "dp": 1, "tp": 1, "ep": 1, "sp": 1,
        "num_devices": 1,
        "kv_num_pages": 64, "kv_page_size": 4,
        "max_batch_slots": 4, "prefill_buckets": [8, 16],
        "use_pallas": False, "platform": "cpu",
    }
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu=tpu,
        batch={"max_batch_size": 4, "max_wait_time_ms": 5.0},
        logging={"level": "WARNING"},
    )


async def test_http_logprobs_roundtrip():
    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(http_config())))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "lp http"}],
                "max_tokens": 5,
                "temperature": 0,
                "logprobs": True,
                "top_logprobs": 2,
            },
        )
        assert resp.status == 200
        body = await resp.json()
        content = body["choices"][0]["logprobs"]["content"]
        assert len(content) == body["usage"]["completion_tokens"]
        assert content[0]["logprob"] <= 0
        assert len(content[0]["top_logprobs"]) == 2

        # top_logprobs out of range is a schema error
        bad = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "x"}],
                "top_logprobs": 50,
            },
        )
        assert bad.status == 422

        # without the flag: no logprobs block
        plain = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "lp http"}],
                "max_tokens": 5,
                "temperature": 0,
            },
        )
        assert (await plain.json())["choices"][0]["logprobs"] is None
    finally:
        await client.close()


async def test_http_streaming_logprobs():
    """SSE chunks carry logprobs entries; their concatenation covers every
    generated token."""
    import json as jsonlib

    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(http_config())))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "stream lp"}],
                "max_tokens": 6,
                "temperature": 0,
                "stream": True,
                "logprobs": True,
                "top_logprobs": 2,
            },
        )
        assert resp.status == 200
        raw = (await resp.read()).decode()
        entries = []
        for line in raw.splitlines():
            if not line.startswith("data: ") or line == "data: [DONE]":
                continue
            body = jsonlib.loads(line[6:])
            lp = body["choices"][0].get("logprobs")
            if lp:
                entries.extend(lp["content"])
        assert len(entries) == 6
        assert all(e["logprob"] <= 0 for e in entries)
        assert all(len(e["top_logprobs"]) == 2 for e in entries)
    finally:
        await client.close()


async def test_http_n_choices():
    """n>1 returns n independent choices (the variant salt defeats
    dedup/caching); greedy choices coincide, seeded sampled ones use
    seed+i and may diverge; n>1 + stream is rejected."""
    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(http_config())))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "n choices"}],
                "max_tokens": 5,
                "temperature": 0,
                "n": 3,
            },
        )
        assert resp.status == 200
        body = await resp.json()
        choices = body["choices"]
        assert [c["index"] for c in choices] == [0, 1, 2]
        # greedy: all three identical
        assert len({c["message"]["content"] for c in choices}) == 1
        assert body["usage"]["completion_tokens"] == 15  # summed

        bad = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "x"}],
                "n": 2,
                "stream": True,
            },
        )
        assert bad.status == 422

        over = await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}], "n": 20},
        )
        assert over.status == 422
    finally:
        await client.close()


async def test_http_text_completions():
    """Legacy /v1/completions: string and list prompts, echo, n, and
    token-level logprobs via the integer `logprobs` field."""
    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(http_config())))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/completions",
            json={"prompt": "complete me", "max_tokens": 5,
                  "temperature": 0, "logprobs": 2},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["object"] == "text_completion"
        [choice] = body["choices"]
        assert choice["finish_reason"] in ("stop", "length")
        lp = choice["logprobs"]  # the LEGACY schema, not chat's content[]
        assert len(lp["tokens"]) == len(lp["token_logprobs"]) == 5
        # legacy top_logprobs is {token_string: lp}; the byte-fallback
        # tokenizer can decode distinct ids to the same string, so the
        # dict may collapse below the requested 2 but never exceed it
        assert 1 <= len(lp["top_logprobs"][0]) <= 2
        assert lp["text_offset"][0] == 0
        assert lp["text_offset"][1] == len(lp["tokens"][0])

        # logprobs=0: per-token logprobs with zero alternatives (legacy
        # semantics -- 0 is not "off")
        resp0 = await client.post(
            "/v1/completions",
            json={"prompt": "complete me", "max_tokens": 3,
                  "temperature": 0, "logprobs": 0},
        )
        lp0 = (await resp0.json())["choices"][0]["logprobs"]
        assert len(lp0["token_logprobs"]) == 3
        assert lp0["top_logprobs"] == [{}, {}, {}]

        # stream is explicitly rejected on the legacy endpoint
        bad_stream = await client.post(
            "/v1/completions",
            json={"prompt": "x", "stream": True},
        )
        assert bad_stream.status == 422

        # list prompt + n>1 + echo
        resp = await client.post(
            "/v1/completions",
            json={"prompt": ["alpha", "beta"], "max_tokens": 3,
                  "temperature": 0, "n": 2, "echo": True},
        )
        body = await resp.json()
        assert [c["index"] for c in body["choices"]] == [0, 1, 2, 3]
        assert body["choices"][0]["text"].startswith("alpha")
        assert body["choices"][2]["text"].startswith("beta")
        assert body["usage"]["completion_tokens"] == 12

        bad = await client.post("/v1/completions", json={"prompt": []})
        assert bad.status == 422
    finally:
        await client.close()


async def test_max_completion_tokens_precedence():
    """max_completion_tokens wins over max_tokens on an engine that
    actually honors the budget."""
    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(http_config())))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "alias wins"}],
                "max_tokens": 40,
                "max_completion_tokens": 3,
                "temperature": 0,
            },
        )
        body = await resp.json()
        assert body["usage"]["completion_tokens"] == 3

        zero = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "x"}],
                "max_completion_tokens": 0,
            },
        )
        assert zero.status == 422  # ge=1: rejected, not silently coerced
    finally:
        await client.close()


async def test_best_of_returns_highest_mean_logprob():
    """Legacy best_of: the server generates best_of candidates and
    returns the n with the highest mean token logprob.  Seeded sampling
    makes the candidate set reproducible, so the best_of=4,n=1 answer
    must be the argmax of the best_of=4,n=4 candidates."""
    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(http_config())))
    await client.start_server()
    try:
        base = {
            "prompt": "best of probe",
            "max_tokens": 5,
            "min_tokens": 5,
            "temperature": 1.0,
            "seed": 11,
            "logprobs": 0,
        }
        all4 = await client.post(
            "/v1/completions", json={**base, "n": 4, "best_of": 4}
        )
        assert all4.status == 200
        cands = (await all4.json())["choices"]
        assert len(cands) == 4

        def mean_lp(c):
            lps = c["logprobs"]["token_logprobs"]
            return sum(lps) / len(lps)

        best_text = max(cands, key=mean_lp)["text"]

        picked = await client.post(
            "/v1/completions", json={**base, "n": 1, "best_of": 4}
        )
        assert picked.status == 200
        body = await picked.json()
        assert len(body["choices"]) == 1
        assert body["choices"][0]["text"] == best_text

        # the client did ask for logprobs here, so they must survive
        assert body["choices"][0]["logprobs"] is not None

        # best_of < n is invalid
        bad = await client.post(
            "/v1/completions", json={**base, "n": 4, "best_of": 2}
        )
        assert bad.status == 422

        # without logprobs requested, ranking stays internal
        quiet = await client.post(
            "/v1/completions",
            json={
                "prompt": "best of quiet",
                "max_tokens": 4,
                "temperature": 1.0,
                "seed": 3,
                "n": 1,
                "best_of": 3,
            },
        )
        assert quiet.status == 200
        qbody = await quiet.json()
        assert qbody["choices"][0].get("logprobs") is None
    finally:
        await client.close()


async def test_best_of_usage_counts_discarded_candidates():
    """usage.completion_tokens covers ALL best_of generations, not just
    the returned choices (the discarded candidates burned compute)."""
    from aiohttp.test_utils import TestClient, TestServer

    from vgate_tpu.server.app import create_app

    client = TestClient(TestServer(create_app(http_config())))
    await client.start_server()
    try:
        resp = await client.post(
            "/v1/completions",
            json={
                "prompt": "usage of the discarded",
                "max_tokens": 4,
                "min_tokens": 4,
                "temperature": 1.0,
                "seed": 9,
                "n": 1,
                "best_of": 3,
            },
        )
        assert resp.status == 200
        body = await resp.json()
        assert len(body["choices"]) == 1
        # 3 candidates x exactly 4 tokens each (min_tokens pins it)
        assert body["usage"]["completion_tokens"] == 12
    finally:
        await client.close()
