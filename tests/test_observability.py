"""Logging formatters, metric registration and tracing fallbacks
(reference: tests/test_observability.py:39-238)."""

import json
import logging

from vgate_tpu import metrics
from vgate_tpu.config import load_config
from vgate_tpu.logging_config import (
    ConsoleFormatter,
    JSONFormatter,
    LogContext,
    get_logger,
    setup_logging,
)
from vgate_tpu.tracing import get_current_trace_id, get_tracer, init_tracing


def _record(msg="hello", **extra):
    record = logging.LogRecord(
        name="test", level=logging.INFO, pathname=__file__, lineno=1,
        msg=msg, args=(), exc_info=None,
    )
    for key, val in extra.items():
        setattr(record, key, val)
    return record


def test_json_formatter_fields():
    out = json.loads(JSONFormatter().format(_record()))
    assert out["message"] == "hello"
    assert out["level"] == "INFO"
    assert out["logger"] == "test"
    assert "timestamp" in out


def test_json_formatter_merges_extra_data():
    out = json.loads(
        JSONFormatter().format(_record(extra_data={"batch_size": 4}))
    )
    assert out["batch_size"] == 4


def test_json_formatter_exception():
    try:
        raise ValueError("boom")
    except ValueError:
        import sys

        record = _record()
        record.exc_info = sys.exc_info()
    out = json.loads(JSONFormatter().format(record))
    assert "ValueError: boom" in out["exception"]


def test_console_formatter_contains_level_and_message():
    out = ConsoleFormatter().format(_record())
    assert "INFO" in out and "hello" in out


def test_setup_logging_json(capsys):
    setup_logging(load_config(logging={"format": "json", "level": "DEBUG"}))
    root = logging.getLogger()
    assert isinstance(root.handlers[0].formatter, JSONFormatter)
    assert root.level == logging.DEBUG


def test_log_context_binds_fields(caplog):
    logger = get_logger("ctxtest")
    ctx = LogContext(logger, request_id="r1")
    with caplog.at_level(logging.INFO, logger="ctxtest"):
        ctx.info("did thing", step=2)
    record = caplog.records[-1]
    assert record.extra_data == {"request_id": "r1", "step": 2}


def test_metric_reregistration_is_idempotent():
    """Re-importing the metrics module must not raise
    (reference: vgate/metrics.py:26-44)."""
    import importlib

    importlib.reload(metrics)
    assert metrics.REQUEST_COUNT is not None


def test_metric_names_have_namespace():
    sample_names = []
    for metric in (
        metrics.REQUEST_COUNT,
        metrics.BATCH_SIZE,
        metrics.CACHE_HITS,
        metrics.TTFT,
        metrics.KV_PAGES_IN_USE,
    ):
        sample_names.append(metric._name)
    assert all(name.startswith("vgt_") for name in sample_names)


def test_render_metrics_prometheus_and_openmetrics():
    body, ctype = metrics.render_metrics("")
    assert b"vgt_" in body
    assert "text/plain" in ctype
    body_om, ctype_om = metrics.render_metrics("application/openmetrics-text")
    assert "openmetrics" in ctype_om
    assert b"# EOF" in body_om


def test_init_app_info():
    metrics.init_app_info("1.2.3", "test-model", "dry_run")
    body, _ = metrics.render_metrics("")
    assert b'version="1.2.3"' in body


def test_tracer_is_noop_without_sdk():
    """Span call sites must work unconditionally (reference: tracing.py:97-108)."""
    init_tracing(load_config(tracing={"enabled": False}))
    tracer = get_tracer("t")
    with tracer.start_as_current_span("span") as span:
        span.set_attribute("k", "v")
    assert get_current_trace_id() is None


def test_tracing_enabled_without_sdk_degrades():
    assert init_tracing(load_config(tracing={"enabled": True})) is False


def test_bound_request_fields_fall_back_into_log_records():
    """Engine-thread log records carry the owning request's
    request_id/trace_id via the thread-local binding (ISSUE 3
    satellite) when no OTel span is active."""
    from vgate_tpu.logging_config import bound_request

    with bound_request("req-77", "aa" * 16):
        out = json.loads(JSONFormatter().format(_record()))
        assert out["request_id"] == "req-77"
        assert out["trace_id"] == "aa" * 16
        console = ConsoleFormatter().format(_record())
        assert "req-77" in console and "aaaaaaaa" in console
    # binding is scoped: gone after the context exits
    out = json.loads(JSONFormatter().format(_record()))
    assert "request_id" not in out and "trace_id" not in out


def test_bound_request_nesting_restores_previous_binding():
    from vgate_tpu.logging_config import bound_request

    with bound_request("outer", None):
        with bound_request("inner", None):
            out = json.loads(JSONFormatter().format(_record()))
            assert out["request_id"] == "inner"
        out = json.loads(JSONFormatter().format(_record()))
        assert out["request_id"] == "outer"


def test_exemplar_helpers_accept_explicit_trace_id():
    """TTFT/TPOT/step-time are observed off the request thread; the
    helpers must take the captured trace id (ISSUE 3 satellite)."""
    tid = "bb" * 16
    metrics.observe_with_exemplar(metrics.TTFT, 0.01, trace_id=tid)
    metrics.observe_with_exemplar(
        metrics.ENGINE_STEP_TIME.labels(kind="decode"), 0.02, trace_id=tid
    )
    metrics.inc_with_exemplar(
        metrics.REQUEST_COUNT.labels(
            method="GET", endpoint="/x", status=200
        ),
        trace_id=tid,
    )
    body, _ = metrics.render_metrics("application/openmetrics-text")
    assert f'trace_id="{tid}"'.encode() in body
