"""Speculative decoding: drafter, verify-step math, and engine rounds
(runtime/speculative.py, models/decoder.py spec_verify_forward).

The load-bearing invariant is greedy exactness: with drafts verified
against the model's own argmax, the emitted tokens are identical to plain
autoregressive decoding no matter what the drafter proposes.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.runtime.engine_core import EngineCore
from vgate_tpu.runtime.speculative import count_accepted, ngram_draft


# ------------------------------------------------------------- drafter

def test_ngram_draft_finds_most_recent_repetition():
    #        0  1  2  3  4  5  6  7
    ids = [5, 6, 9, 5, 6, 7, 5, 6]
    # final bigram (5, 6) recurred at 3..4 (recent) and 0..1 (older);
    # recency wins -> continuation after index 4 is [7, 5, 6]
    assert ngram_draft(ids, k=3, ngram=2) == [7, 5, 6]
    assert ngram_draft(ids, k=1, ngram=2) == [7]


def test_ngram_draft_no_match_or_short_history():
    assert ngram_draft([1, 2, 3, 4], k=3, ngram=2) == []
    assert ngram_draft([1, 2], k=3, ngram=2) == []
    assert ngram_draft([], k=3, ngram=2) == []
    assert ngram_draft([1, 2, 3], k=0, ngram=2) == []


def test_ngram_draft_truncates_at_history_end():
    ids = [8, 9, 1, 8, 9]
    # match at 0..1, only one token follows before the key itself
    assert ngram_draft(ids, k=4, ngram=2) == [1, 8, 9]


# ------------------------------------------------------- accept counting

def test_count_accepted_runs():
    model = jnp.asarray([[7, 8, 9, 1], [7, 8, 9, 1], [7, 8, 9, 1]])
    toks = jnp.asarray(
        [
            [0, 7, 8, 9],  # all 3 drafts match -> 3
            [0, 7, 5, 9],  # first matches, second wrong -> 1
            [0, 1, 8, 9],  # first wrong -> 0
        ]
    )
    lens = jnp.asarray([4, 4, 4], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(count_accepted(model, toks, lens)), [3, 1, 0]
    )


def test_count_accepted_respects_input_len():
    # same matching drafts, but only 1 is real (input_len 2)
    model = jnp.asarray([[7, 8, 9, 1]])
    toks = jnp.asarray([[0, 7, 8, 9]])
    np.testing.assert_array_equal(
        np.asarray(
            count_accepted(model, toks, jnp.asarray([2], jnp.int32))
        ),
        [1],
    )
    # no drafts at all
    np.testing.assert_array_equal(
        np.asarray(
            count_accepted(model, toks, jnp.asarray([1], jnp.int32))
        ),
        [0],
    )


# ------------------------------------------------- verify-forward parity

def test_spec_verify_logits_match_stepwise_decode():
    """The multi-token verify pass must produce, at every position, the
    same logits as feeding those tokens one decode step at a time."""
    from vgate_tpu.models.decoder import (
        decode_forward, init_params, prefill_forward, spec_verify_forward,
    )
    from vgate_tpu.models.specs import TINY_DENSE as spec

    ps, n_pages_per_seq = 4, 8
    B, S = 2, 4
    params = init_params(spec, jax.random.PRNGKey(3), jnp.float32)
    P = 1 + B * n_pages_per_seq
    k_pages = jnp.zeros(
        (spec.num_layers, spec.num_kv_heads, P, ps, spec.head_dim),
        jnp.float32,
    )
    v_pages = jnp.zeros_like(k_pages)
    pt = jnp.asarray(
        1 + np.arange(B * n_pages_per_seq, dtype=np.int32).reshape(
            B, n_pages_per_seq
        )
    )
    rng = np.random.default_rng(9)
    prompt_lens = [6, 9]
    prompts = np.zeros((B, ps * 4), np.int32)
    for b, n in enumerate(prompt_lens):
        prompts[b, :n] = rng.integers(2, spec.vocab_size, size=n)
    _, k_pages, v_pages = prefill_forward(
        params, spec, jnp.asarray(prompts),
        jnp.asarray(prompt_lens, jnp.int32), k_pages, v_pages,
        pt[:, :4],
    )
    cand = rng.integers(2, spec.vocab_size, size=(B, S)).astype(np.int32)
    positions0 = jnp.asarray(prompt_lens, jnp.int32)  # next position
    # ---- verify pass over all S candidates at once
    ver_logits, _, _ = spec_verify_forward(
        params, spec, jnp.asarray(cand), positions0,
        jnp.full((B,), S, jnp.int32), k_pages, v_pages, pt,
        active=jnp.asarray([True, True]),
    )
    # ---- oracle: the same tokens stepped one decode at a time
    kp, vp = k_pages, v_pages
    for j in range(S):
        step_logits, kp, vp = decode_forward(
            params, spec, jnp.asarray(cand[:, j]), positions0 + j,
            kp, vp, pt, active=jnp.asarray([True, True]),
        )
        np.testing.assert_allclose(
            np.asarray(ver_logits[:, j]), np.asarray(step_logits),
            rtol=2e-4, atol=2e-4, err_msg=f"position {j}",
        )


# --------------------------------------------------------- engine rounds

def spec_config(k=3, **tpu_overrides):
    tpu = {
        "dp": 1, "tp": 1, "ep": 1, "sp": 1,
        "kv_num_pages": 64, "kv_page_size": 4,
        "max_batch_slots": 4, "prefill_buckets": [8, 16],
        "use_pallas": False,
        "speculative_k": k,
    }
    tpu.update(tpu_overrides)
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu=tpu,
        scheduler={"max_queue_size": 16},
        logging={"level": "WARNING"},
    )


def greedy(n=10):
    return SamplingParams(max_tokens=n, temperature=0.0)


def test_speculative_engine_matches_plain_greedy():
    """Whatever the n-gram drafter proposes, greedy output must be
    token-for-token identical to the non-speculative engine (verified
    drafts can only accelerate, never change, the sequence)."""
    prompts = [
        "repeat repeat repeat repeat",  # n-gram friendly
        "one two three four",
        "zzz",
    ]
    plain = EngineCore(spec_config(k=0), devices=jax.devices()[:1])
    plain.start()
    try:
        base = plain.generate(prompts, [greedy(12)] * 3)
    finally:
        plain.stop()

    spec_core = EngineCore(spec_config(k=3), devices=jax.devices()[:1])
    spec_core.start()
    try:
        got = spec_core.generate(prompts, [greedy(12)] * 3)
        stats = spec_core.get_stats()
    finally:
        spec_core.stop()
    for b, g in zip(base, got):
        assert b["token_ids"] == g["token_ids"]
        assert b["finish_reason"] == g["finish_reason"]
    assert stats["speculative"]["k"] == 3


def test_oracle_drafter_accepts_and_saves_steps():
    """With a drafter that knows the true continuation, every round
    accepts k drafts: the run finishes in ~n/(k+1) verify rounds and the
    stats record full acceptance."""
    prompts = ["oracle probe"]
    n = 12
    plain = EngineCore(spec_config(k=0), devices=jax.devices()[:1])
    plain.start()
    try:
        [base] = plain.generate(prompts, [greedy(n)])
    finally:
        plain.stop()
    truth = base["token_ids"]

    core = EngineCore(spec_config(k=3), devices=jax.devices()[:1])

    def oracle(seq, k):
        done = seq.num_generated
        return truth[done : done + k]

    core.drafter = oracle
    core.start()
    try:
        steps_before = core.total_steps
        [got] = core.generate(prompts, [greedy(n)])
        rounds = core.total_steps - steps_before
        stats = core.get_stats()
    finally:
        core.stop()
    assert got["token_ids"] == truth
    # 12 tokens: prefill gives 1, then ceil(11 / 4) = 3 verify rounds
    assert rounds <= 4, f"expected <=4 verify rounds, ran {rounds}"
    assert stats["speculative"]["accepted"] >= 6


def test_speculative_respects_exact_budget_and_temperature():
    """max_tokens is exact under multi-accept rounds, and temperature>0
    sequences (now drafting + rejection-verifying) still produce the
    full budget."""
    core = EngineCore(spec_config(k=3), devices=jax.devices()[:1])
    core.start()
    try:
        results = core.generate(
            ["budget probe", "sampled seq"],
            [greedy(7), SamplingParams(max_tokens=7, temperature=0.8,
                                       seed=11)],
        )
        stats = core.get_stats()["scheduler"]
    finally:
        core.stop()
    for r in results:
        assert r["num_tokens"] == 7
        assert r["finish_reason"] == "length"
    assert stats["running"] == 0


def test_speculative_rejects_pp():
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    cfg = spec_config(k=2, pp=2, num_devices=2)
    with pytest.raises(ValueError, match="speculative"):
        EngineCore(cfg, devices=jax.devices()[:2])


def test_speculative_sp_matches_plain_greedy():
    """Speculation on an sp=2 sharded pool (r4: the verify step rides
    sp_multitok_attention_and_write; the r3 gate is gone).  Greedy
    output must be token-identical to the plain sp=2 engine AND the
    sp=1 speculative engine, no matter what the drafter proposes — an
    injected fixed drafter guarantees the sp verify program runs with
    real (mostly wrong) drafts every round."""
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    prompt = [4, 9, 2, 4, 9, 2, 4, 9, 2, 4, 9, 2]
    outs = {}
    for label, k, sp, n_dev in (
        ("plain-sp2", 0, 2, 2),
        ("spec-sp1", 3, 1, 1),
        ("spec-sp2", 3, 2, 2),
    ):
        cfg = spec_config(k=k, sp=sp, num_devices=n_dev)
        core = EngineCore(cfg, devices=jax.devices()[:n_dev])
        if k:
            core.drafter = lambda seq, kk: [4, 9, 2][:kk]
        core.start()
        try:
            seq = core.submit_tokens(prompt, greedy(12))
            assert seq.done_event.wait(300)
            outs[label] = list(seq.generated_ids)
            if k:
                assert core.total_spec_drafted > 0
        finally:
            core.stop()
    assert outs["plain-sp2"] == outs["spec-sp2"] == outs["spec-sp1"]


def test_speculative_with_prefix_cache_sharing():
    """Speculation and automatic prefix caching compose: the second
    request prefix-hits the first one's pages, then decodes
    speculatively — verify KV writes must land in its OWN pages, never
    corrupting the shared prefix."""
    cfg = spec_config(k=3, prefix_cache=True, kv_page_size=4)
    core = EngineCore(cfg, devices=jax.devices()[:1])
    core.start()
    try:
        # identical 2-page-aligned prompt => second request shares pages
        prompt = "shared prefix prompt body"
        [a] = core.generate([prompt], [greedy(10)])
        [b] = core.generate([prompt], [greedy(10)])
        stats = core.get_stats()["scheduler"]
        assert a["token_ids"] == b["token_ids"]
        assert stats["running"] == 0
    finally:
        core.stop()


# ------------------------------------------- rejection sampling (temp>0)

def _tv_distance(counts: np.ndarray, p: np.ndarray) -> float:
    emp = counts / counts.sum()
    return 0.5 * float(np.abs(emp - p).sum())


def test_verify_and_sample_preserves_distribution():
    """The load-bearing exactness property: at a draft-verification
    position the emitted token must be EXACTLY p-distributed —
    P(emit = t) = p(t) (accept) and P(emit = x != t)
    = (1 - p(t)) * p(x) / (1 - p(t)) = p(x) (reject + residual
    resample).  Checked empirically by total-variation distance over a
    12-token vocab with every row drawing from its own key."""
    from vgate_tpu.ops.sampling import verify_and_sample

    V, R = 12, 8192
    base = np.linspace(1.0, -1.5, V).astype(np.float32)
    logits = jnp.broadcast_to(jnp.asarray(base), (R, V))
    p = np.exp(base) / np.exp(base).sum()
    draft_tok = 3
    ones = jnp.ones((R,), jnp.float32)
    zeros_i = jnp.zeros((R,), jnp.int32)

    toks, accept, _ = verify_and_sample(
        logits,
        jnp.full((R,), draft_tok, jnp.int32),
        jnp.zeros((R,), bool),
        ones, ones, zeros_i,
        jax.random.PRNGKey(7),
    )
    counts = np.bincount(np.asarray(toks), minlength=V)
    assert _tv_distance(counts, p) < 0.035
    # acceptance rate must match p(draft)
    acc_rate = float(np.asarray(accept).mean())
    assert abs(acc_rate - p[draft_tok]) < 0.03
    # every rejection emitted something OTHER than the draft
    rejected_draws = np.asarray(toks)[~np.asarray(accept)]
    assert not (rejected_draws == draft_tok).any()

    # bonus rows (no draft): plain p-distributed sample, never "accepted"
    toks_b, accept_b, _ = verify_and_sample(
        logits,
        jnp.full((R,), draft_tok, jnp.int32),
        jnp.ones((R,), bool),
        ones, ones, zeros_i,
        jax.random.PRNGKey(8),
    )
    assert not np.asarray(accept_b).any()
    counts_b = np.bincount(np.asarray(toks_b), minlength=V)
    assert _tv_distance(counts_b, p) < 0.035


def test_verify_and_sample_respects_topk_mask():
    """With top_k=2 the sampling distribution is the renormalized top-2;
    verification must be exact w.r.t. THAT distribution: a draft outside
    the mask is never accepted, and emissions stay inside the mask."""
    from vgate_tpu.ops.sampling import verify_and_sample

    V, R = 10, 4096
    base = np.linspace(2.0, -2.0, V).astype(np.float32)
    logits = jnp.broadcast_to(jnp.asarray(base), (R, V))
    masked_p = np.exp(base[:2]) / np.exp(base[:2]).sum()
    ones = jnp.ones((R,), jnp.float32)
    top_k2 = jnp.full((R,), 2, jnp.int32)

    # draft token 5 is outside top-2: always rejected, emission ~ top-2
    toks, accept, _ = verify_and_sample(
        logits, jnp.full((R,), 5, jnp.int32), jnp.zeros((R,), bool),
        ones, ones, top_k2, jax.random.PRNGKey(9),
    )
    assert not np.asarray(accept).any()
    arr = np.asarray(toks)
    assert set(np.unique(arr)) <= {0, 1}
    counts = np.bincount(arr, minlength=2)[:2]
    assert _tv_distance(counts, masked_p) < 0.04

    # draft token 1 (inside the mask): acceptance rate = masked p(1)
    _, accept1, _ = verify_and_sample(
        logits, jnp.full((R,), 1, jnp.int32), jnp.zeros((R,), bool),
        ones, ones, top_k2, jax.random.PRNGKey(10),
    )
    assert abs(float(np.asarray(accept1).mean()) - masked_p[1]) < 0.03


def test_verify_and_sample_greedy_rows_match_argmax():
    from vgate_tpu.ops.sampling import verify_and_sample

    V, R = 8, 16
    rng = np.random.default_rng(4)
    logits = jnp.asarray(rng.normal(size=(R, V)).astype(np.float32))
    am = np.asarray(jnp.argmax(logits, axis=-1))
    draft = jnp.asarray((am + np.arange(R) % 2) % V, jnp.int32)  # half match
    toks, accept, _ = verify_and_sample(
        logits, draft, jnp.zeros((R,), bool),
        jnp.zeros((R,), jnp.float32), jnp.ones((R,), jnp.float32),
        jnp.zeros((R,), jnp.int32), jax.random.PRNGKey(11),
    )
    np.testing.assert_array_equal(np.asarray(toks), am)
    np.testing.assert_array_equal(
        np.asarray(accept), np.asarray(draft) == am
    )


def test_sampled_requests_draft_through_engine():
    """temperature>0 sequences now draft (the r2 engine silently skipped
    them): with an always-proposing drafter the drafted counter must
    grow for a sampled request, and the run completes with the exact
    budget (acceptance is probabilistic; drafting is not)."""
    core = EngineCore(spec_config(k=3), devices=jax.devices()[:1])
    core.drafter = lambda seq, k: [7] * k
    core.start()
    try:
        seq = core.submit_tokens(
            [1, 2, 3, 1, 2, 3, 1, 2, 3, 1, 2, 3],
            SamplingParams(max_tokens=10, temperature=0.9, seed=5),
        )
        assert seq.done_event.wait(300)
        assert core.total_spec_drafted > 0
        assert seq.num_output_tokens == 10
    finally:
        core.stop()


def test_seeded_sampled_reproducible_under_speculation():
    """A seeded sampled request reproduces token-for-token across runs
    of the speculative engine (acceptance + resample noise derive from
    (seed, step) only)."""
    outs = []
    for _ in range(2):
        core = EngineCore(spec_config(k=3), devices=jax.devices()[:1])
        core.start()
        try:
            [r] = core.generate(
                ["seeded spec repro probe probe probe"],
                [SamplingParams(max_tokens=12, temperature=0.8, seed=42)],
            )
            outs.append(r["token_ids"])
        finally:
            core.stop()
    assert outs[0] == outs[1]


def test_builtin_drafter_proposes_through_engine():
    """The engine's own n-gram drafter must actually fire: a token-level
    repeating prompt guarantees the final bigram recurs, so at least one
    round drafts (acceptance is up to the model)."""
    core = EngineCore(spec_config(k=3), devices=jax.devices()[:1])
    core.start()
    try:
        seq = core.submit_tokens(
            [1, 2, 3, 1, 2, 3, 1, 2, 3, 1, 2, 3], greedy(12)
        )
        assert seq.done_event.wait(300)
        assert core.total_spec_drafted > 0
    finally:
        core.stop()


# ------------------------------------------------- draft-model drafting

def test_draft_model_drafter_standalone():
    """DraftModelDrafter proposes k in-vocab tokens from a windowed
    greedy scan of its own (tiny) model."""
    from vgate_tpu.runtime.speculative import DraftModelDrafter

    d = DraftModelDrafter(
        "tiny-dense", k_max=4, dtype=jnp.float32, window=32
    )

    class _Seq:
        prompt_ids = [5, 9, 13]
        output_ids = [21]

    toks = d.draft_for(_Seq(), 4)
    assert len(toks) == 4
    assert all(0 <= t < d.spec.vocab_size for t in toks)
    assert d.draft_for(_Seq(), 0) == []
    # k below k_max slices the same compiled program's output
    assert d.draft_for(_Seq(), 2) == toks[:2]


def test_draft_model_engine_matches_plain_and_accepts():
    """A same-architecture, same-seed drafter IS the target model: greedy
    output must stay token-identical to the plain engine (the verify
    invariant) and acceptance must be high (the drafter's windowed
    forward equals the target's for sequences shorter than the window).
    """
    prompts = ["one two three four", "zzz"]
    n = 12
    plain = EngineCore(spec_config(k=0), devices=jax.devices()[:1])
    plain.start()
    try:
        base = plain.generate(prompts, [greedy(n)] * 2)
    finally:
        plain.stop()

    cfg = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "draft_model_id": "tiny-dense",
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 4, "prefill_buckets": [8, 16],
            "use_pallas": False,
            "speculative_k": 3, "draft_window": 32,
        },
        scheduler={"max_queue_size": 16},
        logging={"level": "WARNING"},
    )
    core = EngineCore(cfg, devices=jax.devices()[:1])
    assert core.draft_model is not None
    core.start()
    try:
        got = core.generate(prompts, [greedy(n)] * 2)
        stats = core.get_stats()
    finally:
        core.stop()
    for b, g in zip(base, got):
        assert b["token_ids"] == g["token_ids"]
    spec_stats = stats["speculative"]
    assert spec_stats["drafter"] == "draft-model:tiny-dense"
    assert spec_stats["drafted"] > 0
    assert spec_stats["acceptance_rate"] > 0.6, spec_stats


def test_draft_model_falls_back_to_ngram_on_mesh():
    """Model-parallel meshes keep n-gram drafting (the drafter is a
    single-device program); the engine must not crash, just warn."""
    if jax.device_count() < 2:
        pytest.skip("needs >=2 devices")
    cfg = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
            "draft_model_id": "tiny-dense",
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 2, "num_devices": 2,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 4, "prefill_buckets": [8, 16],
            "use_pallas": False, "speculative_k": 2,
        },
        logging={"level": "ERROR"},
    )
    core = EngineCore(cfg, devices=jax.devices()[:2])
    assert core.draft_model is None
    assert core.drafter == core._ngram_drafter
