"""Pallas kernels vs their jnp twins (interpret mode on CPU — SURVEY.md
section 4: kernel unit tests comparing Pallas outputs vs jnp reference)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.ops.attention import paged_decode_attention
from vgate_tpu.ops.pallas.paged_attention import paged_decode_attention_pallas


def make_case(B=4, H=8, KV=2, hd=128, ps=16, pages_per_seq=16, seed=0,
              lens=None):
    rng = np.random.default_rng(seed)
    P = 1 + B * pages_per_seq
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    k_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    page_tables = jnp.asarray(
        rng.permutation(np.arange(1, P))[: B * pages_per_seq].reshape(
            B, pages_per_seq
        ),
        jnp.int32,
    )
    if lens is None:
        lens = rng.integers(1, pages_per_seq * ps, size=B)
    seq_lens = jnp.asarray(lens, jnp.int32)
    return q, k_pages, v_pages, page_tables, seq_lens


@pytest.mark.parametrize(
    "lens",
    [
        None,  # random lengths
        [1, 16, 17, 128],  # page-boundary edges
        [255, 256, 200, 3],  # chunk-boundary edges (chunk=128 tokens)
    ],
)
def test_paged_decode_kernel_matches_jnp(lens):
    q, k_pages, v_pages, page_tables, seq_lens = make_case(
        lens=lens, seed=1 if lens is None else 2
    )
    expect = paged_decode_attention(q, k_pages, v_pages, page_tables, seq_lens)
    got = paged_decode_attention_pallas(
        q, k_pages, v_pages, page_tables, seq_lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_paged_decode_kernel_gqa_group_mapping():
    """H=8, KV=4 (G=2): each group must read its own kv head."""
    q, k_pages, v_pages, page_tables, seq_lens = make_case(
        B=2, H=8, KV=4, pages_per_seq=8, seed=3
    )
    expect = paged_decode_attention(q, k_pages, v_pages, page_tables, seq_lens)
    got = paged_decode_attention_pallas(
        q, k_pages, v_pages, page_tables, seq_lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_paged_decode_kernel_bf16():
    q, k_pages, v_pages, page_tables, seq_lens = make_case(seed=4)
    q = q.astype(jnp.bfloat16)
    k_pages = k_pages.astype(jnp.bfloat16)
    v_pages = v_pages.astype(jnp.bfloat16)
    expect = paged_decode_attention(q, k_pages, v_pages, page_tables, seq_lens)
    got = paged_decode_attention_pallas(
        q, k_pages, v_pages, page_tables, seq_lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32),
        np.asarray(expect, np.float32),
        rtol=2e-2,
        atol=2e-2,
    )


# ---------------------------------------------------------- flash prefill

def _prefill_case(B=2, S=256, H=4, KV=2, hd=128, seed=0, lens=None):
    from vgate_tpu.ops.attention import causal_prefill_attention

    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    if lens is None:
        lens = rng.integers(1, S + 1, size=B)
    seq_lens = jnp.asarray(lens, jnp.int32)
    expect = causal_prefill_attention(q, k, v, seq_lens)
    return q, k, v, seq_lens, expect


@pytest.mark.parametrize("lens", [None, [1, 256], [255, 130]])
def test_flash_prefill_kernel_matches_oracle(lens):
    from vgate_tpu.ops.pallas.flash_prefill import (
        flash_prefill_attention_pallas,
    )

    q, k, v, seq_lens, expect = _prefill_case(
        lens=lens, seed=7 if lens is None else 8
    )
    got = flash_prefill_attention_pallas(
        q, k, v, seq_lens, block_q=128, block_k=128, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_flash_prefill_kernel_serving_bucket_1024():
    """Parity at a serving-sized bucket (VERDICT r1 item 2)."""
    from vgate_tpu.ops.pallas.flash_prefill import (
        flash_prefill_attention_pallas,
    )

    q, k, v, seq_lens, expect = _prefill_case(
        B=1, S=1024, H=2, KV=1, hd=64, seed=9, lens=[900]
    )
    got = flash_prefill_attention_pallas(
        q, k, v, seq_lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_flash_prefill_kernel_gqa_and_offset():
    """GQA group mapping + chunked-prefill q_offset: a 128-row query chunk
    at global offset 128 must reproduce rows [128:256] of the full pass."""
    from vgate_tpu.ops.pallas.flash_prefill import (
        flash_prefill_attention_pallas,
    )

    q, k, v, seq_lens, expect = _prefill_case(
        B=1, S=256, H=8, KV=4, seed=10, lens=[256]
    )
    got = flash_prefill_attention_pallas(
        q[:, 128:], k, v, seq_lens,
        q_offsets=jnp.asarray([128], jnp.int32),
        block_q=128, block_k=128, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect[:, 128:]), rtol=2e-5, atol=2e-5
    )


def test_paged_decode_kernel_sliding_window_matches_jnp():
    """Gemma-2 local attention in the kernel: window mask + below-window
    chunk skip must equal the jnp twin's windowed gather, including a
    window that starts mid-chunk and one beyond a chunk boundary."""
    q, k_pages, v_pages, page_tables, seq_lens = make_case(
        lens=[200, 255, 64, 3], seed=5
    )
    for win in (16, 100, 130):  # mid-page, mid-chunk, cross-chunk
        w = jnp.asarray(win, jnp.int32)
        expect = paged_decode_attention(
            q, k_pages, v_pages, page_tables, seq_lens, window=w
        )
        got = paged_decode_attention_pallas(
            q, k_pages, v_pages, page_tables, seq_lens, window=w,
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5,
            err_msg=f"window={win}",
        )
    # window=0 (global layers of a sliding-window model) == no window
    got0 = paged_decode_attention_pallas(
        q, k_pages, v_pages, page_tables, seq_lens,
        window=jnp.asarray(0, jnp.int32), interpret=True,
    )
    expect0 = paged_decode_attention(
        q, k_pages, v_pages, page_tables, seq_lens
    )
    np.testing.assert_allclose(
        np.asarray(got0), np.asarray(expect0), rtol=2e-5, atol=2e-5
    )


def test_paged_decode_kernel_softcap_and_scale_match_jnp():
    """Score softcapping and the decoupled query scale (Gemma-2's
    query_pre_attn_scalar) in the kernel vs the jnp twin."""
    q, k_pages, v_pages, page_tables, seq_lens = make_case(seed=6)
    expect = paged_decode_attention(
        q, k_pages, v_pages, page_tables, seq_lens,
        softcap=50.0, scale=0.25,
    )
    got = paged_decode_attention_pallas(
        q, k_pages, v_pages, page_tables, seq_lens,
        softcap=50.0, scale=0.25, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_flash_prefill_kernel_window_softcap_scale():
    """Gemma-2 prefill in the kernel: sliding window (with the dead-block
    skip), score softcap and the decoupled query scale vs the jnp twin."""
    from vgate_tpu.ops.attention import flash_prefill_attention
    from vgate_tpu.ops.pallas.flash_prefill import (
        flash_prefill_attention_pallas,
    )

    rng = np.random.default_rng(31)
    B, S, H, KV, hd = 2, 512, 4, 2, 128
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    lens = jnp.asarray([301, 512], jnp.int32)
    # window smaller than a k-block (128) AND spanning blocks
    # compare only rows < seq_len: once a window applies, padding rows
    # (q_pos >= seq_len + window) have NO valid keys, and fully-masked
    # rows are garbage-by-design in both implementations (the engine
    # discards them); real rows must match exactly
    valid = np.arange(S)[None, :] < np.asarray(lens)[:, None]  # [B, S]
    for win in (48, 200):
        w = jnp.asarray(win, jnp.int32)
        expect = flash_prefill_attention(
            q, k, v, lens, block_k=128, window=w, softcap=50.0, scale=0.05
        )
        got = flash_prefill_attention_pallas(
            q, k, v, lens, block_q=128, block_k=128, interpret=True,
            window=w, softcap=50.0, scale=0.05,
        )
        np.testing.assert_allclose(
            np.asarray(got)[valid], np.asarray(expect)[valid],
            rtol=2e-5, atol=2e-5, err_msg=f"window={win}",
        )
    # window=0 == global
    got0 = flash_prefill_attention_pallas(
        q, k, v, lens, block_q=128, block_k=128, interpret=True,
        window=jnp.asarray(0, jnp.int32),
    )
    expect0 = flash_prefill_attention(q, k, v, lens, block_k=128)
    np.testing.assert_allclose(
        np.asarray(got0), np.asarray(expect0), rtol=2e-5, atol=2e-5
    )


def test_paged_multitok_kernel_matches_suffix_attention():
    """The speculative-verify kernel vs the jnp suffix path: S candidate
    rows per slot, varying input_lens, window on/off, softcap+scale."""
    from vgate_tpu.ops.attention import paged_suffix_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_multitok_attention_pallas,
    )

    rng = np.random.default_rng(41)
    B, S, H, KV, hd, ps, n_pages = 3, 4, 4, 2, 32, 4, 16
    P = 1 + B * n_pages
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    pt = jnp.asarray(
        1 + np.arange(B * n_pages, dtype=np.int32).reshape(B, n_pages)
    )
    positions0 = jnp.asarray([10, 37, 0], jnp.int32)
    input_lens = jnp.asarray([4, 2, 1], jnp.int32)
    total = positions0 + input_lens

    cases = [
        dict(softcap=0.0, window=None, scale=None),
        dict(softcap=30.0, window=jnp.asarray(16, jnp.int32), scale=0.1),
        dict(softcap=0.0, window=jnp.asarray(0, jnp.int32), scale=None),
    ]
    valid = np.arange(S)[None, :] < np.asarray(input_lens)[:, None]
    for case in cases:
        expect = paged_suffix_attention(
            q, k_pages, v_pages, pt, positions0, total, **case
        )
        got = paged_multitok_attention_pallas(
            q, k_pages, v_pages, pt, positions0, input_lens,
            interpret=True, **case,
        )
        np.testing.assert_allclose(
            np.asarray(got)[valid], np.asarray(expect)[valid],
            rtol=2e-5, atol=2e-5, err_msg=str(case),
        )


def test_paged_multitok_kernel_single_row_matches_decode_kernel():
    """With S=1 the multi-token kernel degenerates to the decode kernel."""
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_multitok_attention_pallas,
    )

    q, k_pages, v_pages, page_tables, seq_lens = make_case(
        lens=[9, 33, 64, 128], seed=42
    )
    B, H, hd = q.shape
    expect = paged_decode_attention_pallas(
        q, k_pages, v_pages, page_tables, seq_lens, interpret=True
    )
    got = paged_multitok_attention_pallas(
        q[:, None], k_pages, v_pages, page_tables, seq_lens - 1,
        jnp.ones((B,), jnp.int32), interpret=True,
    )[:, 0]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


# ------------------------------------------------- fused int4 dequant GEMM

def _int4_case(lead, in_dim, out, seed=0):
    from vgate_tpu.ops.quant import quantize_tensor

    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.normal(size=(in_dim, out)), jnp.float32)
    qt = quantize_tensor(w, bits=4)  # PackedQTensor
    x = jnp.asarray(rng.normal(size=(*lead, in_dim)), jnp.float32)
    return x, qt


@pytest.mark.parametrize(
    "lead,in_dim,out",
    [
        ((4,), 64, 128),       # tiny decode-shaped
        ((2, 8), 64, 64),      # prefill-shaped leading dims
        ((12,), 256, 128),     # multi-in-tile accumulation (T_in=128 x 2)
    ],
)
def test_int4_matmul_kernel_matches_packed_einsum(lead, in_dim, out):
    from vgate_tpu.ops.pallas.quant_matmul import int4_matmul_pallas
    from vgate_tpu.ops.quant import packed_einsum

    x, qt = _int4_case(lead, in_dim, out)
    expect = packed_einsum("...d,dh->...h", x, qt) * qt.scale
    got = int4_matmul_pallas(
        x, qt.q_packed, qt.scale, interpret=True
    )
    assert got.shape == (*lead, out)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-4, atol=2e-4
    )


def test_int4_matmul_kernel_f32_out_and_ragged_rows():
    """lm_head shape class: f32 accumulation/output and a row count that
    is not a multiple of the row tile (padding path)."""
    from vgate_tpu.ops.pallas.quant_matmul import int4_matmul_pallas
    from vgate_tpu.ops.quant import packed_einsum

    x, qt = _int4_case((5,), 64, 128, seed=3)
    xb = x.astype(jnp.bfloat16)
    expect = (
        packed_einsum(
            "...d,dv->...v", xb, qt,
            preferred_element_type=jnp.float32,
        )
        * qt.scale
    )
    got = int4_matmul_pallas(
        xb, qt.q_packed, qt.scale, out_dtype=jnp.float32,
        interpret=True,
    )
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-2, atol=2e-2
    )


def test_quant_kernel_gate_dispatch(monkeypatch):
    """weighted_einsum routes 2D packed weights through the kernel when
    the per-call ``quant_kernel`` flag (threaded from
    ModelSpec.quant_kernel) is on, and the results agree with the jnp
    path."""
    from vgate_tpu.ops import quant

    x, qt = _int4_case((4,), 64, 128, seed=5)
    base = quant.weighted_einsum("...d,dh->...h", x, qt)
    called = {}

    import vgate_tpu.ops.pallas.quant_matmul as qm

    real_kernel = qm.int4_matmul_pallas

    def fake_kernel(xx, qp, sc, out_dtype=None):
        called["yes"] = True
        return real_kernel(
            xx, qp, sc, out_dtype=out_dtype, interpret=True
        )

    monkeypatch.setattr(qm, "int4_matmul_pallas", fake_kernel)
    got = quant.weighted_einsum("...d,dh->...h", x, qt, quant_kernel=True)
    assert called.get("yes")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(base), rtol=2e-4, atol=2e-4
    )
    # default-off: no kernel call without the flag
    called.clear()
    quant.weighted_einsum("...d,dh->...h", x, qt)
    assert not called
    # expert (3D) weights never take the kernel, flag or not
    from vgate_tpu.ops.quant import quantize_expert_stacked

    rng = np.random.default_rng(6)
    we = jnp.asarray(rng.normal(size=(2, 3, 16, 32)), jnp.float32)
    qe = quantize_expert_stacked(we, bits=4)
    assert not quant._use_quant_kernel("ecd,edf->ecf", qe)


def test_paged_decode_kernel_layer_indexed():
    """Carry-threaded decode passes the FULL stacked [L, KV, P, ps, hd]
    pool plus a layer index; the kernel's layer-indexed DMA must match
    slicing that layer out first (interpret mode)."""
    L = 3
    q, k_pages, v_pages, page_tables, seq_lens = make_case(
        B=2, H=4, KV=2, hd=128, ps=16, pages_per_seq=4, seed=12,
        lens=[17, 55],
    )
    rng = np.random.default_rng(13)
    stacked_k = jnp.asarray(
        rng.normal(size=(L, *k_pages.shape)), jnp.float32
    )
    stacked_v = jnp.asarray(
        rng.normal(size=(L, *v_pages.shape)), jnp.float32
    )
    for layer in range(L):
        expect = paged_decode_attention_pallas(
            q, stacked_k[layer], stacked_v[layer], page_tables, seq_lens,
            interpret=True,
        )
        got = paged_decode_attention_pallas(
            q, stacked_k, stacked_v, page_tables, seq_lens,
            layer=jnp.asarray(layer, jnp.int32), interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expect), rtol=1e-5, atol=1e-5,
            err_msg=f"layer {layer}",
        )


def test_multitok_kernel_layer_indexed():
    """Carry-threaded spec verify: the multitok kernel with the stacked
    pool + layer index must match slicing the layer out first."""
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_multitok_attention_pallas,
    )

    L, B, S, H, KV, hd, ps, pps = 2, 2, 4, 4, 2, 128, 16, 4
    rng = np.random.default_rng(21)
    P = 1 + B * pps
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    stacked_k = jnp.asarray(
        rng.normal(size=(L, KV, P, ps, hd)), jnp.float32
    )
    stacked_v = jnp.asarray(
        rng.normal(size=(L, KV, P, ps, hd)), jnp.float32
    )
    pt = jnp.asarray(
        1 + np.arange(B * pps).reshape(B, pps), jnp.int32
    )
    pos0 = jnp.asarray([9, 30], jnp.int32)
    in_lens = jnp.asarray([4, 2], jnp.int32)
    for layer in range(L):
        expect = paged_multitok_attention_pallas(
            q, stacked_k[layer], stacked_v[layer], pt, pos0, in_lens,
            interpret=True,
        )
        got = paged_multitok_attention_pallas(
            q, stacked_k, stacked_v, pt, pos0, in_lens,
            layer=jnp.asarray(layer, jnp.int32), interpret=True,
        )
        # rows past input_lens are unspecified; compare valid rows only
        for b in range(B):
            n = int(in_lens[b])
            np.testing.assert_allclose(
                np.asarray(got[b, :n]), np.asarray(expect[b, :n]),
                rtol=1e-5, atol=1e-5, err_msg=f"layer {layer} b {b}",
            )


@pytest.mark.parametrize(
    "lead,in_dim,out",
    [((4,), 64, 128), ((2, 8), 128, 64), ((5,), 256, 128)],
)
def test_int8_matmul_kernel_matches_einsum(lead, in_dim, out):
    """int8 fused-dequant kernel vs the jnp QTensor einsum path."""
    from vgate_tpu.ops.pallas.quant_matmul import int8_matmul_pallas
    from vgate_tpu.ops.quant import quantize_tensor

    rng = np.random.default_rng(31)
    w = jnp.asarray(rng.normal(size=(in_dim, out)), jnp.float32)
    qt = quantize_tensor(w, bits=8)
    x = jnp.asarray(rng.normal(size=(*lead, in_dim)), jnp.float32)
    expect = jnp.einsum("...d,dh->...h", x, qt.q.astype(x.dtype)) * qt.scale
    got = int8_matmul_pallas(x, qt.q, qt.scale, interpret=True)
    assert got.shape == (*lead, out)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-4, atol=2e-4
    )


def test_int8_kernel_gate_dispatch(monkeypatch):
    """weighted_einsum routes 2D int8 QTensors through the kernel when
    quant_kernel is set, and never for stacked (3D) weights."""
    from vgate_tpu.ops import quant

    rng = np.random.default_rng(32)
    w = jnp.asarray(rng.normal(size=(64, 128)), jnp.float32)
    qt = quant.quantize_tensor(w, bits=8)
    x = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
    base = quant.weighted_einsum("...d,dh->...h", x, qt)
    called = {}

    import vgate_tpu.ops.pallas.quant_matmul as qm

    real = qm.int8_matmul_pallas

    def fake(xx, qq, sc, out_dtype=None):
        called["yes"] = True
        return real(xx, qq, sc, out_dtype=out_dtype, interpret=True)

    monkeypatch.setattr(qm, "int8_matmul_pallas", fake)
    got = quant.weighted_einsum("...d,dh->...h", x, qt, quant_kernel=True)
    assert called.get("yes")
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(base), rtol=2e-4, atol=2e-4
    )
    ws = quant.quantize_stacked(
        jnp.asarray(rng.normal(size=(2, 16, 32)), jnp.float32), bits=8
    )
    assert not quant._use_quant_kernel("...d,dh->...h", ws)


def test_suffix_prefill_pallas_matches_jnp():
    """prefill_suffix_forward(use_pallas=True) routes the context
    attention through the multitok kernel (the chunked/long-context
    prefill hot path); logits and KV must match the jnp suffix path
    for both page-aligned prefixes and varying suffix lengths."""
    from vgate_tpu.models.decoder import (
        init_params, prefill_forward, prefill_suffix_forward,
    )
    from vgate_tpu.models.specs import TINY_DENSE as spec

    ps, pps, B = 16, 4, 2  # kernel-friendly page size
    params = init_params(spec, jax.random.PRNGKey(5), jnp.float32)
    P = 1 + B * pps
    shape = (spec.num_layers, spec.num_kv_heads, P, ps, spec.head_dim)
    k0 = jnp.zeros(shape, jnp.float32)
    v0 = jnp.zeros(shape, jnp.float32)
    pt = jnp.asarray(
        1 + np.arange(B * pps).reshape(B, pps), jnp.int32
    )
    rng = np.random.default_rng(6)
    # resident prefix: one full page per row
    prefix = jnp.asarray(
        rng.integers(2, spec.vocab_size, (B, ps)), jnp.int32
    )
    _, kf, vf = prefill_forward(
        params, spec, prefix, jnp.full((B,), ps, jnp.int32), k0, v0,
        pt[:, :1],
    )
    S = 16  # suffix bucket
    sfx = jnp.asarray(
        rng.integers(2, spec.vocab_size, (B, S)), jnp.int32
    )
    args = (
        params, spec, sfx, jnp.full((B,), ps, jnp.int32),
        jnp.asarray([S, 5], jnp.int32), kf, vf, pt[:, 1:2], pt[:, :2],
    )
    import unittest.mock as mock

    from vgate_tpu.ops.pallas import paged_attention as pa

    real = pa.paged_multitok_attention_pallas

    def interp(*a, **kw):
        kw["interpret"] = True
        return real(*a, **kw)

    expect = prefill_suffix_forward(*args, use_pallas=False)
    with mock.patch.object(
        pa, "paged_multitok_attention_pallas", side_effect=interp
    ):
        got = prefill_suffix_forward(*args, use_pallas=True)
    np.testing.assert_allclose(
        np.asarray(got[0]), np.asarray(expect[0]), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(got[1]), np.asarray(expect[1]), rtol=1e-5, atol=1e-5
    )


# ---------------------------------------- multi-slot blocked decode kernel

@pytest.mark.parametrize(
    "lens",
    [
        None,  # random lengths (mixed chunk counts within a block)
        [1, 16, 255, 256],  # page/chunk boundary edges in ONE block
    ],
)
def test_blocked_decode_kernel_matches_jnp(lens):
    """The multi-slot blocked kernel (block_slots sequences per program,
    RESULTS_r3 decision-tree item 4) must match the jnp oracle for
    mixed-length blocks where the fori_loop runs to the block max."""
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas_blocked,
    )

    q, k_pages, v_pages, page_tables, seq_lens = make_case(
        B=4, lens=lens, seed=11 if lens is None else 12
    )
    expect = paged_decode_attention(
        q, k_pages, v_pages, page_tables, seq_lens
    )
    got = paged_decode_attention_pallas_blocked(
        q, k_pages, v_pages, page_tables, seq_lens, interpret=True,
        block_slots=2,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_blocked_decode_kernel_window_and_softcap():
    """Sliding window + softcap through the blocked kernel: per-slot
    window starts differ inside one block (lo_block = min)."""
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas_blocked,
    )

    q, k_pages, v_pages, page_tables, seq_lens = make_case(
        B=4, lens=[40, 200, 96, 130], seed=13
    )
    w = jnp.asarray(64, jnp.int32)
    expect = paged_decode_attention(
        q, k_pages, v_pages, page_tables, seq_lens, window=w,
        softcap=30.0,
    )
    got = paged_decode_attention_pallas_blocked(
        q, k_pages, v_pages, page_tables, seq_lens, interpret=True,
        block_slots=2, window=w, softcap=30.0,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_blocked_decode_kernel_layer_indexed_and_fallback():
    """Layer-indexed pools ride the blocked kernel too; B not divisible
    by block_slots falls back to the per-slot kernel."""
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas_blocked,
    )

    q, k_pages, v_pages, page_tables, seq_lens = make_case(
        B=2, lens=[33, 97], seed=14
    )
    L = 3
    rng = np.random.default_rng(15)
    kL = jnp.asarray(
        rng.normal(size=(L,) + k_pages.shape), jnp.float32
    )
    vL = jnp.asarray(
        rng.normal(size=(L,) + v_pages.shape), jnp.float32
    )
    expect = paged_decode_attention(
        q, kL, vL, page_tables, seq_lens, layer=jnp.asarray(1)
    )
    got = paged_decode_attention_pallas_blocked(
        q, kL, vL, page_tables, seq_lens, interpret=True,
        block_slots=2, layer=jnp.asarray(1),
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )
    # B=3 % block_slots=2 -> falls back (still correct)
    q3, k3, v3, pt3, sl3 = make_case(B=3, H=8, KV=2, lens=[5, 60, 100],
                                     seed=16, pages_per_seq=8)
    expect3 = paged_decode_attention(q3, k3, v3, pt3, sl3)
    got3 = paged_decode_attention_pallas_blocked(
        q3, k3, v3, pt3, sl3, interpret=True, block_slots=2,
    )
    np.testing.assert_allclose(
        np.asarray(got3), np.asarray(expect3), rtol=2e-5, atol=2e-5
    )
