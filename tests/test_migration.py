"""Planned live migration — unit tier (no engine builds).

Covers the pure pieces of ISSUE 8: the Sequence fold/epoch semantics of
``prepare_migrate``, the hysteresis/rate-limit rebalancing policy on a
fake clock, the kv-dtype placement gate (both directions), the shared
``replay_into`` pipeline's migrate flavor, the scheduler's
evacuate/bypass behavior, and the dp=1 supervisor's deliberate refusal.
Engine-level drain/rebalance acceptance lives in tests/test_dp_engine.py
(slow tier) and scripts/migrate_check.sh.
"""

import queue
import threading
import time
from collections import deque
from types import SimpleNamespace

import pytest

from vgate_tpu import metrics
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.errors import MigrationRefusedError, PoisonRequestError
from vgate_tpu.runtime.dp_engine import (
    RebalancePolicy,
    ReplicatedEngine,
    _structural,
)
from vgate_tpu.runtime.engine_core import EngineCore, replay_into
from vgate_tpu.runtime.kv_cache import PageAllocator
from vgate_tpu.runtime.scheduler import Scheduler
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.runtime.supervisor import EngineSupervisor


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


# --------------------------------------------------- sequence semantics


def test_prepare_migrate_folds_without_spending_resume_budget():
    seq = Sequence(prompt_ids=[4, 5], params=greedy(8))
    seq.status = SeqStatus.RUNNING
    seq.slot = 1
    seq.pages = [3, 9]
    seq.append_token(11)
    old_epoch = seq.preempt_count
    seq.prepare_migrate()
    # same fold/epoch contract as prepare_resume ...
    assert seq.status is SeqStatus.WAITING
    assert seq.prompt_ids == [4, 5, 11] and seq.output_ids == []
    assert seq.pages == [] and seq.slot is None
    assert seq.preempt_count == old_epoch + 1
    assert not seq.done_event.is_set()
    # ... but the crash-resume budget is untouched: a rolling deploy
    # must never eat into the restarts a request may later survive
    assert seq.migrate_count == 1
    assert seq.resume_count == 0


def test_resume_metrics_carries_both_flavors():
    seq = Sequence(prompt_ids=[1], params=greedy())
    assert seq.resume_metrics() == {}
    seq.migrate_count = 2
    assert seq.resume_metrics() == {"migrated": 2.0}
    seq.resume_count = 1
    assert seq.resume_metrics() == {"resumed": 1.0, "migrated": 2.0}


def test_checkpoint_round_trip_preserves_migrate_count():
    seq = Sequence(prompt_ids=[1, 2, 3], params=greedy(16))
    seq.append_token(7)
    seq.migrate_count = 1
    cp = seq.checkpoint()
    assert cp.migrate_count == 1
    restored = Sequence.from_checkpoint(cp)
    assert restored.migrate_count == 1
    # the loggable summary stays in lockstep with the pure-data form
    assert seq.checkpoint_summary() == cp.as_dict()


# ----------------------------------------------------- scheduler pieces


def _scheduler(max_queue=2, num_pages=16):
    return Scheduler(
        allocator=PageAllocator(num_pages),
        max_slots=2,
        page_size=4,
        prefill_buckets=[8, 16],
        max_model_len=32,
        max_queue_size=max_queue,
    )


def test_scheduler_add_migrated_bypasses_queue_full():
    sched = _scheduler(max_queue=1)
    sched.add(Sequence(prompt_ids=[1, 2], params=greedy()))
    fresh = Sequence(prompt_ids=[3, 4], params=greedy())
    with pytest.raises(Exception):
        sched.add(fresh)
    moved = Sequence(prompt_ids=[5, 6], params=greedy())
    moved.migrate_count = 1
    sched.add(moved)  # already admitted once on the source replica
    assert moved in sched.waiting


def test_scheduler_evacuate_releases_without_settling():
    sched = _scheduler()
    sched.add(Sequence(prompt_ids=[1, 2, 3], params=greedy()))
    plan = sched.try_admit()
    seq = plan.seq
    assert seq.status is SeqStatus.RUNNING and seq.pages
    free_before = sched.allocator.num_free
    sched.evacuate(seq)
    # residency freed this tick; the future is still open (nothing
    # settled — the sequence finishes wherever it is replayed)
    assert sched.slots[plan.slot] is None
    assert sched.allocator.num_free > free_before
    assert not seq.done_event.is_set()
    assert sched.total_finished == 0 and sched.total_aborted == 0
    # waiting-queue evacuation just dequeues
    queued = Sequence(prompt_ids=[4, 5], params=greedy())
    sched.add(queued)
    sched.evacuate(queued)
    assert queued not in sched.waiting
    assert not queued.done_event.is_set()


# ------------------------------------------------- replay_into flavors


class _FakeReplayCore:
    def __init__(self, fail=False):
        self.fail = fail
        self.submitted = []
        self.ticks = []
        self.flight = SimpleNamespace(
            record_tick=lambda kind, **f: self.ticks.append((kind, f))
        )

    def submit_existing(self, seq):
        if self.fail:
            raise RuntimeError("refused")
        self.submitted.append(seq)


def _metric_value(counter):
    return counter._value.get()  # prometheus_client internal, test-only


def test_replay_into_migrate_kind_records_migrate_not_resume():
    core = _FakeReplayCore()
    seq = Sequence(prompt_ids=[1, 2], params=greedy())
    seq.prepare_migrate()
    before = _metric_value(metrics.RESUMED_SEQUENCES)
    outcome = replay_into(
        core, seq, set(), kind="migrate", reason="drain"
    )
    assert outcome == "replayed"
    assert core.submitted == [seq]
    assert core.ticks and core.ticks[0][0] == "migrate"
    assert core.ticks[0][1]["reason"] == "drain"
    assert core.ticks[0][1]["attempt"] == 1  # migrate_count, not resume
    # vgt_resumed_sequences is the CRASH counter; migrations have their
    # own vgt_migrations{reason} owned by the dp caller
    assert _metric_value(metrics.RESUMED_SEQUENCES) == before


def test_replay_into_default_kind_still_counts_resume():
    core = _FakeReplayCore()
    seq = Sequence(prompt_ids=[1, 2], params=greedy())
    seq.prepare_resume()
    before = _metric_value(metrics.RESUMED_SEQUENCES)
    assert replay_into(core, seq, set()) == "replayed"
    assert core.ticks[0][0] == "resume"
    assert _metric_value(metrics.RESUMED_SEQUENCES) == before + 1


def test_replay_into_quarantine_applies_to_migration_too():
    core = _FakeReplayCore()
    seq = Sequence(prompt_ids=[1, 2], params=greedy())
    seq.prepare_migrate()
    from vgate_tpu import faults

    fp = faults.fingerprint([1, 2])
    outcome = replay_into(core, seq, {fp}, kind="migrate")
    assert outcome == "quarantined"
    assert isinstance(seq.error, PoisonRequestError)


# ------------------------------------------- kv-dtype placement gate


def _bare_dp():
    return ReplicatedEngine.__new__(ReplicatedEngine)


def _fake_core(kv_dtype, fatal=None):
    return SimpleNamespace(
        _fatal=fatal,
        geometry=SimpleNamespace(kv_dtype=kv_dtype),
    )


def test_placement_refuses_int8_source_into_bf16_fleet():
    dp = _bare_dp()
    src = _fake_core("int8")
    with pytest.raises(MigrationRefusedError) as exc:
        dp._check_placement(src, [_fake_core("bf16")])
    assert "kv-dtype mismatch" in str(exc.value)


def test_placement_refuses_bf16_source_into_int8_fleet():
    dp = _bare_dp()
    src = _fake_core("bf16")
    with pytest.raises(MigrationRefusedError):
        dp._check_placement(src, [_fake_core("int8")])


def test_placement_filters_to_matching_dtype_targets():
    dp = _bare_dp()
    src = _fake_core("int8")
    match, mismatch = _fake_core("int8"), _fake_core("bf16")
    assert dp._check_placement(src, [mismatch, match]) == [match]


def test_placement_refuses_with_no_live_target():
    dp = _bare_dp()
    with pytest.raises(MigrationRefusedError) as exc:
        dp._check_placement(_fake_core("bf16"), [])
    assert "no eligible target" in str(exc.value)
    with pytest.raises(MigrationRefusedError):
        dp._check_placement(
            _fake_core("bf16"),
            [_fake_core("bf16", fatal=RuntimeError("dead"))],
        )


# ------------------------------------------------- rebalancing policy


def _policy(clock, **overrides):
    cfg = load_config(migration=overrides).migration
    return RebalancePolicy(cfg, clock=lambda: clock[0])


HOT = {"kv_free_ratio": 0.05, "engine_queue_depth": 0}
IDLE = {"kv_free_ratio": 0.9, "engine_queue_depth": 0}
WARM = {"kv_free_ratio": 0.4, "engine_queue_depth": 1}


def test_rebalance_policy_hysteresis_on_fake_clock():
    clock = [0.0]
    p = _policy(clock, rebalance_hold_s=10.0, rebalance_cooldown_s=30.0)
    sig = {0: HOT, 1: IDLE}
    assert p.observe(sig) is None  # first hot tick: hold starts
    clock[0] = 9.9
    assert p.observe(sig) is None  # not ripe
    clock[0] = 10.1
    assert p.observe(sig) == (0, 1)  # sustained pressure -> move
    clock[0] = 15.0
    assert p.observe(sig) is None  # rate limit: cooldown
    clock[0] = 41.0
    assert p.observe(sig) == (0, 1)  # cooldown slid


def test_rebalance_policy_never_flaps():
    clock = [0.0]
    p = _policy(clock, rebalance_hold_s=10.0, rebalance_cooldown_s=30.0)
    # pressure that flaps on/off faster than the hold can never ripen
    for t in range(0, 100, 5):
        clock[0] = float(t)
        sig = {0: HOT if (t // 5) % 2 == 0 else IDLE, 1: IDLE}
        assert p.observe(sig) is None


def test_rebalance_policy_requires_an_idle_target():
    clock = [0.0]
    p = _policy(clock, rebalance_hold_s=0.0)
    # both replicas busy: moving work just moves the pressure around
    assert p.observe({0: HOT, 1: WARM}) is None
    clock[0] = 1.0
    assert p.observe({0: HOT, 1: IDLE}) == (0, 1)


def test_rebalance_policy_queue_depth_counts_as_hot():
    clock = [0.0]
    p = _policy(clock, rebalance_hold_s=0.0, hot_queue_depth=4)
    deep = {"kv_free_ratio": 0.8, "engine_queue_depth": 5}
    clock[0] = 1.0
    assert p.observe({0: deep, 1: IDLE}) == (0, 1)


def test_rebalance_policy_drops_state_for_absent_replicas():
    clock = [0.0]
    p = _policy(clock, rebalance_hold_s=10.0)
    p.observe({0: HOT, 1: IDLE})
    # replica 0 stops reporting (drained/removed) past ripeness ...
    clock[0] = 20.0
    p.observe({1: IDLE})
    # ... and must NOT fire the moment it reappears: the hold restarts
    clock[0] = 21.0
    assert p.observe({0: HOT, 1: IDLE}) is None


# ------------------------------------------------- dp=1 refusal


def test_supervisor_refuses_evacuation():
    sup = EngineSupervisor.__new__(EngineSupervisor)
    with pytest.raises(MigrationRefusedError) as exc:
        sup.evacuate()
    assert "dp=1" in str(exc.value)


# --------------------------------------- evacuation command plumbing


def test_fail_pending_evacuations_unblocks_waiters():
    """A caller blocked in evacuate() while the engine dies must get a
    prompt typed error, not a full timeout."""
    core = EngineCore.__new__(EngineCore)
    core._fatal = None
    core._evac_q = queue.Queue()
    core._wakeup = threading.Event()
    results = {}

    def call():
        try:
            core.evacuate(None, timeout=10.0)
        except RuntimeError as exc:
            results["error"] = exc

    t = threading.Thread(target=call)
    t.start()
    deadline = time.monotonic() + 5
    while core._evac_q.empty() and time.monotonic() < deadline:
        time.sleep(0.01)
    core._fail_pending_evacuations(RuntimeError("boom"))
    t.join(timeout=5)
    assert not t.is_alive()
    assert "unavailable for evacuation" in str(results["error"])


# ------------------------------------------------ merged flight writer


def test_merged_flight_records_pod_tick_once():
    """The batcher's overload hook writes through backend.core.flight;
    on a dp pod that is the merged view, which must accept the tick and
    land it on exactly one live recorder (the drill surfaced this as an
    AttributeError + a dropped tick on every brownout transition)."""
    from vgate_tpu.observability.flight import FlightRecorder
    from vgate_tpu.runtime.dp_engine import _MergedFlight

    replicas = [
        SimpleNamespace(flight=FlightRecorder()) for _ in range(2)
    ]
    merged = _MergedFlight(replicas)
    merged.record_tick("overload", level=3, prev=0)
    ticks = merged.ticks()
    assert [t["kind"] for t in ticks] == ["overload"]
    assert ticks[0]["level"] == 3


def test_evacuation_timeout_is_not_treated_as_replica_death():
    """MigrationError subclasses RuntimeError; _evacuate_all must let
    the timeout propagate instead of swallowing it into the dead-
    replica claim path — remove_replica would otherwise proceed to
    stop() a replica still full of live sequences."""
    from vgate_tpu.errors import MigrationError

    dp = _bare_dp()
    dp._mig = load_config().migration

    class _TimingOutCore:
        _fatal = None

        def evacuate(self, seq_ids, reason, timeout):
            raise MigrationError("evacuation did not complete")

    dp._alive_override = None
    with pytest.raises(MigrationError):
        dp._evacuate_all(_TimingOutCore(), "drain")


def test_cancelled_evacuation_is_never_executed():
    """A timed-out caller cancels its _EvacRequest; the engine thread
    must skip it entirely — executing it later would strand the
    evacuated sequences with no waiter to place them."""
    core = EngineCore.__new__(EngineCore)
    core._fatal = None
    core._evac_q = queue.Queue()
    core._wakeup = threading.Event()
    with pytest.raises(Exception) as exc:
        core.evacuate(None, timeout=0.05)
    assert "did not complete" in str(exc.value)
    # the stale request is still queued but marked cancelled: the
    # engine-thread pass must drop it without calling _evacuate_now
    # (which would explode on this bare core if reached)
    assert core._evac_q.qsize() == 1
    core._process_evacuations()
    assert core._evac_q.qsize() == 0


def test_rebalance_failed_move_releases_cooldown():
    """A decision whose execution moved nothing must not burn the full
    rebalance cooldown — the pressured replica stays eligible."""
    clock = [0.0]
    pol = _policy(clock, rebalance_hold_s=10, rebalance_cooldown_s=300)
    for _ in range(3):
        clock[0] += 6
        decision = pol.observe({0: HOT, 1: IDLE})
    assert decision == (0, 1)
    # executor found no victims: without the release, the next ripe
    # tick would be suppressed for rebalance_cooldown_s
    pol.note_move_failed()
    clock[0] += 6
    assert pol.observe({0: HOT, 1: IDLE}) == (0, 1)


def test_claim_dead_places_as_resume_not_migrate():
    """Sequences a planned drain claims from a CRASHED replica were
    folded by prepare_resume — they must replay as resumes (resumed
    counter, resume tick) so provenance flags and metrics agree."""
    dp = _bare_dp()
    dp.total_resumed = 0
    dp.total_migrated = 0
    dp.total_lost = 0
    dp._quarantine = set()
    dp._recovery = SimpleNamespace(backoff_base_s=0.05, backoff_cap_s=0.2)
    dp._restart_times = []
    target = _FakeReplayCore()
    target._fatal = None
    target.geometry = SimpleNamespace(kv_dtype=None)
    target.scheduler = SimpleNamespace(waiting=[], running=[])
    seq = Sequence(prompt_ids=[1, 2], params=greedy())
    seq.prepare_resume()
    before = dp.total_migrated
    moved, lost, _ = dp._place([seq], [target], "drain", 0, kind="resume")
    assert (moved, lost) == (1, 0)
    assert dp.total_resumed == 1
    assert dp.total_migrated == before
    assert target.ticks[0][0] == "resume"


def test_rebalance_folds_victims_back_when_cold_dies():
    """The rebalance target dying between decision and placement must
    not 503 healthy requests — they fold back into the hot replica."""
    dp = _bare_dp()
    dp._mig = load_config().migration
    dp.total_lost = 0
    dp._policy = RebalancePolicy(dp._mig)
    seq = Sequence(prompt_ids=[1, 2], params=greedy())
    seq.status = SeqStatus.RUNNING
    for t in range(dp._mig.min_generated_tokens):
        seq.append_token(t)
    hot = _FakeReplayCore()
    hot._fatal = None
    hot.geometry = SimpleNamespace(kv_dtype=None)
    hot.scheduler = SimpleNamespace(running=[seq], waiting=[])
    hot.evacuate = lambda ids, reason, timeout: [seq]
    cold = SimpleNamespace(
        _fatal=RuntimeError("died"),
        geometry=SimpleNamespace(kv_dtype=None),
    )
    assert dp._rebalance(hot, cold, 0) is None
    assert hot.submitted == [seq]          # back where it was running
    assert dp.total_lost == 0
    assert not seq.done_event.is_set()     # client still streaming
    assert dp._policy._last_move_t is None  # cooldown released


# ------------------------------------- structural-op concurrency fixes


def test_alive_requires_running_loop():
    """A cleanly-stopped core (remove_replica teardown) has _fatal None
    but no engine loop: migrating into it would strand the sequence in
    a queue nothing drains while metrics count a successful move."""
    assert ReplicatedEngine._alive(
        SimpleNamespace(_fatal=None, _running=True)
    )
    assert not ReplicatedEngine._alive(
        SimpleNamespace(_fatal=None, _running=False)
    )
    assert not ReplicatedEngine._alive(
        SimpleNamespace(_fatal=RuntimeError("x"), _running=True)
    )


def test_structural_ops_hold_the_lock_for_their_full_duration():
    """Drain/undrain/add/remove must fully serialize — the last-replica
    guard and index-keyed draining marks are only sound when no other
    structural op interleaves with the long evacuation phase (which
    releases _topology_lock on purpose)."""
    for name in (
        "drain_replica", "undrain_replica", "add_replica",
        "remove_replica",
    ):
        assert hasattr(getattr(ReplicatedEngine, name), "__wrapped__")
    dp = _bare_dp()
    dp._structural_lock = threading.RLock()
    order = []

    @_structural
    def slow(self):
        order.append("slow-in")
        time.sleep(0.2)
        order.append("slow-out")

    @_structural
    def fast(self):
        order.append("fast-in")
        order.append("fast-out")

    t = threading.Thread(target=slow, args=(dp,))
    t.start()
    deadline = time.monotonic() + 2
    while "slow-in" not in order and time.monotonic() < deadline:
        time.sleep(0.005)
    fast(dp)
    t.join()
    assert order == ["slow-in", "slow-out", "fast-in", "fast-out"]


def test_health_gauge_counts_alive_draining_replica():
    """vgt_dp_replicas_alive has ONE definition (liveness, not rotation
    membership): a planned drain must not sawtooth the gauge between
    /health scrapes and repair-sweep ticks or fire VgtDpReplicaDown."""
    dp = _bare_dp()
    dp._topology_lock = threading.RLock()
    dp.replicas = [
        SimpleNamespace(_fatal=None, _running=True) for _ in range(2)
    ]
    dp._draining = {0}
    dp._corrupt = set()
    dp._failover_enabled = True
    dp._restart_times = []
    dp._quarantine = set()
    dp._recovery = SimpleNamespace(
        restart_window_s=300.0, max_restarts=3
    )
    dp._integrity_cfg = SimpleNamespace(enabled=False)
    dp.total_failovers = dp.total_restarts = dp.total_stalls = 0
    dp.total_resumed = dp.total_migrated = dp.total_lost = 0
    h = dp.health()
    assert h["replicas_alive"] == 2        # drained-but-alive counts
    assert h["replicas"][0]["state"] == "draining"
    assert h["state"] == "degraded"        # the drain shows here...
    assert metrics.DP_REPLICAS_ALIVE._value.get() == 2  # ...not here


def test_place_folds_back_into_alive_source_when_targets_die():
    """A drain whose targets all die mid-op must fold residents back
    into the still-alive source (requeued), not 503 them as lost."""
    dp = _bare_dp()
    dp.total_lost = 0
    dp.total_migrated = 0
    dp._quarantine = set()
    dp._recovery = SimpleNamespace(backoff_base_s=0.05, backoff_cap_s=0.2)
    dp._restart_times = []
    source = _FakeReplayCore()
    source._fatal = None
    source._running = True
    source.geometry = SimpleNamespace(kv_dtype=None)
    dead_target = SimpleNamespace(
        _fatal=RuntimeError("died mid-drain"),
        _running=True,
        geometry=SimpleNamespace(kv_dtype=None),
    )
    seq = Sequence(prompt_ids=[1, 2], params=greedy())
    seq.prepare_migrate()
    moved, lost, requeued = dp._place(
        [seq], [dead_target], "drain", 0, fallback=source
    )
    assert (moved, lost, requeued) == (0, 0, 1)
    assert source.submitted == [seq]       # back on the source
    assert dp.total_lost == 0
    assert not seq.done_event.is_set()     # client still streaming


def test_dead_source_gate_falls_back_when_listed_targets_are_dead():
    """drain/remove of a DEAD replica must reach _fallback_targets when
    every non-draining sibling is ALSO dead — not only when the target
    list is empty — so an alive draining survivor still takes the
    claimed checkpoint (matching _redistribute)."""
    dp = _bare_dp()
    dp._topology_lock = threading.RLock()
    dead_src = SimpleNamespace(_fatal=RuntimeError("src"), _running=True)
    dead_sib = SimpleNamespace(_fatal=RuntimeError("sib"), _running=True)
    survivor = SimpleNamespace(_fatal=None, _running=True)
    dp.replicas = [dead_src, dead_sib, survivor]
    dp._draining = {2}
    calls = {}

    def fake_fallback(idx, core):
        calls["fallback"] = idx
        return [survivor]

    def fake_evacuate_all(core, reason):
        return [], "resume"

    def fake_place(seqs, targets, reason, idx, kind, fallback=None):
        calls["targets"] = targets
        return 0, 0, 0

    dp._fallback_targets = fake_fallback
    dp._evacuate_all = fake_evacuate_all
    dp._place = fake_place
    dp._drain_and_place(0, "drain")
    assert calls["fallback"] == 0
    assert calls["targets"] == [survivor]
    dp._draining.discard(0)
