"""Silent-corruption defense (vgate_tpu/integrity.py): sentinels,
weight checksums, canary keeper, corrupt classification, and the
supervisor's reload-on-corrupt rebuild mode (fake cores — fast tier;
the end-to-end drill lives in scripts/integrity_check.sh and the
slow-marked test at the bottom)."""

import threading
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vgate_tpu import faults, integrity
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.errors import IntegrityError, RetryableError
from vgate_tpu.runtime.sequence import Sequence, SeqStatus
from vgate_tpu.runtime.supervisor import (
    EngineSupervisor,
    HealthState,
    classify_fatal,
)


@pytest.fixture(autouse=True)
def _reset_faults():
    faults.reset()
    yield
    faults.reset()


def _icfg(**over):
    cfg = load_config(integrity=over) if over else load_config()
    return cfg.integrity


def greedy(max_tokens=8, temperature=0.0):
    return SamplingParams(max_tokens=max_tokens, temperature=temperature)


# ------------------------------------------------------- classification


def test_integrity_error_is_corrupt_and_retryable():
    exc = IntegrityError("bad bits", kind="checksum_mismatch")
    assert classify_fatal(exc) == "corrupt"
    assert isinstance(exc, RetryableError)
    assert exc.reason == "corrupt"


def test_injected_corrupt_kind_classifies_corrupt():
    faults.arm("decode_step", mode="raise", kind="corrupt", times=1)
    with pytest.raises(faults.InjectedFault) as exc_info:
        faults.check("decode_step")
    assert classify_fatal(exc_info.value) == "corrupt"


def test_new_fault_points_registered():
    for point in ("weight_corrupt", "logit_corrupt"):
        assert point in faults.FAULT_POINTS
        spec = faults.arm(point, mode="corrupt", times=1)
        assert spec.point == point
    faults.reset()


def test_take_corrupt_consumes_charge():
    faults.arm("weight_corrupt", mode="corrupt", times=1)
    assert faults.take_corrupt("weight_corrupt") is True
    assert faults.take_corrupt("weight_corrupt") is False  # exhausted
    assert faults.take_corrupt("logit_corrupt") is False  # never armed


# ------------------------------------------------------------- digests


def _tiny_tree():
    key = jax.random.PRNGKey(0)
    return {
        "embed": jax.random.normal(key, (16, 8), jnp.float32),
        "layers": {
            "q": {"w": jax.random.normal(key, (2, 8, 8), jnp.bfloat16)},
            "norm": jnp.ones((2, 8), jnp.float32),
        },
    }


def test_tree_digests_stable_and_bitflip_sensitive():
    tree = _tiny_tree()
    d1 = integrity.tree_digests(tree)
    d2 = integrity.tree_digests(jax.tree.map(lambda x: x + 0, tree))
    assert d1 == d2 and len(d1) == 3
    # flip ONE element's low bit: exactly that leaf's digest changes
    flipped = dict(tree)
    w = tree["layers"]["q"]["w"]
    bits = jax.lax.bitcast_convert_type(w, jnp.uint16)
    bits = bits.at[0, 0, 0].set(bits[0, 0, 0] ^ 1)
    flipped["layers"] = {
        "q": {"w": jax.lax.bitcast_convert_type(bits, jnp.bfloat16)},
        "norm": tree["layers"]["norm"],
    }
    d3 = integrity.tree_digests(flipped)
    changed = [k for k in d1 if d1[k] != d3[k]]
    assert len(changed) == 1 and "q" in changed[0]


def test_host_and_device_digests_agree():
    arr = np.arange(64, dtype=np.float32).reshape(8, 8)
    assert integrity.host_leaf_digest(arr) == integrity.leaf_digest(
        jnp.asarray(arr)
    )


def test_digest_positional_sensitivity():
    a = np.asarray([1.0, 2.0], np.float32)
    b = np.asarray([2.0, 1.0], np.float32)
    assert integrity.host_leaf_digest(a) != integrity.host_leaf_digest(b)


def test_checksum_roundtrip_sharded_quantized_int8():
    """The serving-shaped round trip: a real (tiny) decoder tree,
    int8-quantized and device-placed — baseline digests verify clean,
    and a bit flipped in the QUANTIZED data leaf is caught."""
    from vgate_tpu.models.decoder import init_params
    from vgate_tpu.models.specs import spec_for_model_id
    from vgate_tpu.ops.quant import quantize_decoder_params

    spec = spec_for_model_id("tiny-dense")
    params = init_params(spec, jax.random.PRNGKey(1), jnp.float32)
    qparams = quantize_decoder_params(params, spec, bits=8)
    qparams = jax.device_put(qparams, jax.devices()[0])

    verifier = integrity.WeightVerifier(_icfg(sweep_leaves_per_tick=4))
    verifier.record(qparams)
    assert verifier.verify_all(qparams) is None
    # drive chunked sweeps through one full clean pass
    verifier._next_pass_t = 0.0
    for _ in range(64):
        assert verifier.verify_chunk(qparams) is None
        if verifier.sweeps_completed:
            break
    assert verifier.sweeps_completed == 1
    assert verifier.mismatches == 0
    # corrupt one quantized projection leaf on device
    corrupted = jax.tree_util.tree_map(lambda x: x, qparams)
    corrupted["layers"]["q"]["w"] = jax.tree_util.tree_map(
        integrity._bitflip_leaf, corrupted["layers"]["q"]["w"]
    )
    mismatch = verifier.verify_all(corrupted)
    assert mismatch is not None and "q" in mismatch["leaf"]
    # the budgeted sweep finds it too
    verifier._cursor = 0
    verifier._next_pass_t = 0.0
    found = None
    for _ in range(64):
        found = verifier.verify_chunk(corrupted)
        if found:
            break
    assert found is not None


# ----------------------------------------------------------- sentinels


def test_logit_guard_flag_bits():
    rows = jnp.asarray(
        [
            [1.0, -2.0, 3.0],  # clean
            [jnp.nan, 0.0, 1.0],  # nonfinite
            [0.0, 0.0, 0.0],  # all-zero
            [1.0e6, 0.0, -1.0],  # saturated
        ],
        jnp.float32,
    )
    flags = np.asarray(integrity.logit_guard(rows, 1.0e4))
    assert flags[0] == 0
    assert flags[1] & integrity.FLAG_NONFINITE
    assert flags[2] & integrity.FLAG_ZERO
    assert flags[3] & integrity.FLAG_SATURATED


def _row_seq(slot, temperature=0.0, output_ids=()):
    seq = Sequence(
        prompt_ids=[1, 2, 3],
        params=SamplingParams(max_tokens=64, temperature=temperature),
    )
    seq.status = SeqStatus.RUNNING
    seq.slot = slot
    seq.output_ids = list(output_ids)
    return seq


def test_sentinel_token_range_trips():
    scanner = integrity.SentinelScanner(_icfg(), vocab_size=100)
    seq = _row_seq(0)
    sampled = np.asarray([[5], [999]], np.int32).T  # [chunk=2, B=1]? shape
    sampled = np.asarray([[5, 0], [999, 0]], np.int32)  # [chunk=2, B=2]
    trips = scanner.scan_decode(sampled, None, [(seq, 0)], chunk=2)
    assert [k for k, _ in trips] == ["token_range"]
    assert scanner.trips["token_range"] == 1


def test_sentinel_flags_attribute_per_sequence():
    scanner = integrity.SentinelScanner(_icfg(), vocab_size=100)
    clean, poisoned = _row_seq(0), _row_seq(1)
    sampled = np.zeros((1, 2), np.int32)
    flags = np.asarray([0, integrity.FLAG_NONFINITE], np.uint8)
    trips = scanner.scan_decode(
        sampled, flags, [(clean, 0), (poisoned, 1)], chunk=1
    )
    assert len(trips) == 1
    assert trips[0][0] == "logit_nonfinite"
    assert trips[0][1] is poisoned


def test_entropy_collapse_only_for_sampled_generations():
    cfg = _icfg(entropy_window=8)
    scanner = integrity.SentinelScanner(cfg, vocab_size=100)
    history = [7] * 8
    sampled = np.full((2, 1), 7, np.int32)
    greedy_seq = _row_seq(0, temperature=0.0, output_ids=history)
    assert (
        scanner.scan_decode(sampled, None, [(greedy_seq, 0)], 2) == []
    )
    hot_seq = _row_seq(0, temperature=1.0, output_ids=history)
    trips = scanner.scan_decode(sampled, None, [(hot_seq, 0)], 2)
    assert [k for k, _ in trips] == ["entropy_collapse"]


def test_engine_integrity_scan_raises_with_attribution():
    eng = integrity.EngineIntegrity(_icfg(), vocab_size=100)
    seq = _row_seq(3)
    seq.request_id = "req-77"
    flags = np.zeros(8, np.uint8)
    flags[3] = integrity.FLAG_ZERO
    with pytest.raises(IntegrityError) as exc_info:
        eng.scan_decode(np.zeros((1, 8), np.int32), flags, [(seq, 3)], 1)
    err = exc_info.value
    assert err.integrity_kind == "logit_zero"
    assert err.sequences[0]["request_id"] == "req-77"
    assert classify_fatal(err) == "corrupt"


def test_scan_clean_chunk_is_silent():
    eng = integrity.EngineIntegrity(_icfg(), vocab_size=100)
    seq = _row_seq(0)
    assert eng.scan_decode(
        np.ones((2, 4), np.int32), np.zeros(4, np.uint8), [(seq, 0)], 2
    ) == []


def test_entropy_collapse_is_soft_per_sequence():
    """Entropy collapse is model-degeneration-shaped evidence: the
    engine must fail ONLY the attributed sequence (soft trip), never
    classify the replica corrupt and reload weights."""
    eng = integrity.EngineIntegrity(
        _icfg(entropy_window=8), vocab_size=100
    )
    hot = _row_seq(0, temperature=1.0, output_ids=[7] * 8)
    soft = eng.scan_decode(
        np.full((2, 1), 7, np.int32), None, [(hot, 0)], 2
    )  # must NOT raise
    assert len(soft) == 1
    kind, seq, exc = soft[0]
    assert kind == "entropy_collapse" and seq is hot
    assert isinstance(exc, IntegrityError)


def test_hard_trip_attribution_carries_fingerprint():
    eng = integrity.EngineIntegrity(_icfg(), vocab_size=100)
    seq = _row_seq(2)
    flags = np.zeros(4, np.uint8)
    flags[2] = integrity.FLAG_NONFINITE
    with pytest.raises(IntegrityError) as exc_info:
        eng.scan_decode(np.zeros((1, 4), np.int32), flags, [(seq, 2)], 1)
    fp = exc_info.value.sequences[0]["fingerprint"]
    assert fp == faults.fingerprint(seq.prompt_ids)


# -------------------------------------------------------------- canary


class _FakeCanaryCore:
    """submit_existing + deterministic 'generation' for CanaryKeeper."""

    def __init__(self, reply):
        self.reply = list(reply)
        self.spec = SimpleNamespace(vocab_size=100)
        self.submitted = []

    def submit_existing(self, seq):
        assert seq.canary, "canary probes must be marked canary"
        self.submitted.append(seq)
        for t in self.reply:
            seq.append_token(t)
        seq.finish("stop")


def test_canary_records_then_verifies_then_catches_mismatch():
    keeper = integrity.CanaryKeeper(_icfg())
    good = _FakeCanaryCore([4, 5, 6])
    first = keeper.check(good)
    assert first["ok"] and first["recorded"]
    second = keeper.check(_FakeCanaryCore([4, 5, 6]))
    assert second["ok"] and not second["recorded"]
    assert keeper.passes == 1
    bad = keeper.check(_FakeCanaryCore([4, 5, 0]))
    assert bad["ok"] is False
    assert keeper.failures == 1
    assert keeper.expected == integrity.canary_fingerprint([4, 5, 6])


def test_canary_probe_error_counts_as_failure():
    keeper = integrity.CanaryKeeper(_icfg())

    class _Dead:
        spec = SimpleNamespace(vocab_size=100)

        def submit_existing(self, seq):
            raise RuntimeError("engine is dead")

    result = keeper.check(_Dead())
    assert result["ok"] is False and "error" in result
    assert keeper.failures == 1


def test_canary_prompt_ids_deterministic_and_in_vocab():
    ids = integrity.canary_prompt_ids(100, 8)
    assert ids == integrity.canary_prompt_ids(100, 8)
    assert all(0 <= t < 100 for t in ids)


# ----------------------- supervisor rebuild-mode selection (fake core)


class _FakeFatalCore:
    def __init__(self, exc):
        self._fatal = exc
        self._fatal_suspects = []
        self.flight = None
        self.scheduler = SimpleNamespace(waiting=[], running=[])

    def take_checkpointed(self):
        return []

    def take_resume_losses(self):
        return 0


class _FakeNewCore:
    def __init__(self):
        self.started = False
        self.stopped = False
        self.on_fatal = None
        self._fatal = None

    def start(self):
        self.started = True

    def stop(self):
        self.stopped = True


def _bare_supervisor(integrity_enabled=True, canary=None):
    sup = EngineSupervisor.__new__(EngineSupervisor)
    cfg = load_config()
    sup.config = cfg
    sup._recovery = cfg.recovery.model_copy(
        update={"backoff_base_s": 0.0, "backoff_cap_s": 0.0}
    )
    sup._integrity_cfg = cfg.integrity.model_copy(
        update={"enabled": integrity_enabled}
    )
    sup._devices = None
    sup._lock = threading.RLock()
    sup._state = HealthState.SERVING
    sup._degraded_since = None
    sup._time_in_degraded = 0.0
    sup._restart_times = []
    sup._quarantine = set()
    sup._suspect_counts = {}
    sup._stopping = False
    sup._pending_resume = []
    sup._canary = canary
    sup.quarantined_corrupt = False
    sup.total_corrupt_reloads = 0
    sup.total_canary_failures = 0
    sup.last_integrity = None
    sup.last_resume = None
    sup.last_crash = None
    sup.last_fatal = None
    sup.transitions = []
    sup.total_crashes = 0
    sup.total_restarts = 0
    sup.total_stalls = 0
    sup.total_resumed = 0
    sup.total_lost = 0
    return sup


def _run_crash(sup, exc, monkeypatch, new_cores=None):
    """Drive _handle_crash with rebuild_core captured; returns the
    recorded (reload_weights, new_core) per rebuild attempt."""
    import vgate_tpu.runtime.supervisor as sup_mod

    calls = []
    cores = list(new_cores or [])

    def fake_rebuild(old, config, devices, reload_weights=False):
        core = cores.pop(0) if cores else _FakeNewCore()
        calls.append((reload_weights, core))
        return core

    monkeypatch.setattr(sup_mod, "rebuild_core", fake_rebuild)
    sup.core = _FakeFatalCore(exc)
    sup._handle_crash()
    return calls


def test_supervisor_transient_keeps_weights(monkeypatch):
    sup = _bare_supervisor()
    calls = _run_crash(sup, RuntimeError("boom"), monkeypatch)
    assert len(calls) == 1
    reload_weights, core = calls[0]
    assert reload_weights is False
    assert core.started and not core.stopped
    assert sup.quarantined_corrupt is False
    assert sup.total_corrupt_reloads == 0
    assert sup.state in (HealthState.DEGRADED, HealthState.SERVING)


def test_supervisor_corrupt_reloads_weights(monkeypatch):
    sup = _bare_supervisor()
    exc = IntegrityError("flipped bits", kind="checksum_mismatch")
    calls = _run_crash(sup, exc, monkeypatch)
    assert len(calls) == 1
    reload_weights, core = calls[0]
    assert reload_weights is True
    assert core.started
    assert sup.total_corrupt_reloads == 1
    assert sup.quarantined_corrupt is False  # cleared: no canary gate
    assert sup.last_integrity["kind"] == "checksum_mismatch"


def test_supervisor_corrupt_inert_when_integrity_disabled(monkeypatch):
    sup = _bare_supervisor(integrity_enabled=False)
    exc = IntegrityError("flipped bits", kind="checksum_mismatch")
    calls = _run_crash(sup, exc, monkeypatch)
    assert [r for r, _ in calls] == [False]  # weights kept, PR-8 behavior


def test_supervisor_kept_verify_failure_escalates_to_reload(monkeypatch):
    """A transient crash whose kept tree fails rebuild-time checksum
    verification must escalate THAT recovery to a reload."""
    import vgate_tpu.runtime.supervisor as sup_mod

    sup = _bare_supervisor()
    calls = []

    def fake_rebuild(old, config, devices, reload_weights=False):
        calls.append(reload_weights)
        if not reload_weights:
            raise IntegrityError("verify failed", kind="checksum_mismatch")
        return _FakeNewCore()

    monkeypatch.setattr(sup_mod, "rebuild_core", fake_rebuild)
    sup.core = _FakeFatalCore(RuntimeError("boom"))
    sup._handle_crash()
    assert calls == [False, True]
    assert sup.quarantined_corrupt is False
    assert sup.total_corrupt_reloads == 1


class _ScriptedKeeper:
    def __init__(self, verdicts):
        self.verdicts = list(verdicts)
        self.checked = []

    def check(self, core, context="probe"):
        ok = self.verdicts.pop(0)
        self.checked.append((core, context))
        return {"ok": ok, "recorded": False, "context": context}

    def stats(self):
        return {}


def test_supervisor_corrupt_replica_rejoins_only_after_canary(monkeypatch):
    """First post-reload canary fails -> that incarnation is torn down
    and the reload retries; the second passes -> quarantine lifts."""
    keeper = _ScriptedKeeper([False, True])
    sup = _bare_supervisor(canary=keeper)
    exc = IntegrityError("sentinel", kind="logit_nonfinite")
    calls = _run_crash(sup, exc, monkeypatch)
    assert [r for r, _ in calls] == [True, True]
    first, second = calls[0][1], calls[1][1]
    assert first.started and first.stopped  # failed canary: torn down
    assert second.started and not second.stopped
    assert sup.quarantined_corrupt is False
    assert sup.total_canary_failures == 1
    # counted per reload REBUILD (tracks vgt_corrupt_reloads): both
    # attempts reloaded weights
    assert sup.total_corrupt_reloads == 2
    # both probes ran against the post-reload incarnations
    assert [c for c, _ in keeper.checked] == [first, second]


def test_supervisor_corrupt_never_counts_poison_streaks(monkeypatch):
    """Checksum/canary corruption is the hardware's fault: innocent
    residents must never accumulate poison streaks from it."""
    sup = _bare_supervisor()
    sup.core = _FakeFatalCore(None)
    sup.core._fatal_suspects = [("fp-innocent", 0)]
    exc = IntegrityError("flipped bits", kind="checksum_mismatch")
    sup._update_quarantine(exc, "corrupt")
    assert sup._suspect_counts == {}
    assert sup._quarantine == set()


def test_supervisor_sentinel_attribution_feeds_poison_streak():
    """A request that deterministically trips the logit sentinel must
    be containable: its ATTRIBUTED fingerprint runs the repeat-offender
    streak (threshold crashes -> quarantined), while co-resident
    innocents accrue nothing."""
    sup = _bare_supervisor()
    sup._recovery = sup._recovery.model_copy(
        update={"poison_threshold": 2}
    )
    bad_fp, innocent_fp = "fp-naan", "fp-innocent"
    exc = IntegrityError(
        "sentinel", kind="logit_nonfinite",
        sequences=[{"fingerprint": bad_fp, "seq_id": 1}],
    )
    for _ in range(2):
        sup.core = _FakeFatalCore(None)
        sup.core._fatal_suspects = [(bad_fp, 0), (innocent_fp, 0)]
        sup._update_quarantine(exc, "corrupt")
    assert bad_fp in sup._quarantine
    assert innocent_fp not in sup._quarantine


def test_dp_sentinel_attribution_feeds_corrupt_streak():
    """The dp twin of the supervisor streak: attributed fingerprints
    accumulate across corrupt sentinel fatals and quarantine at
    poison_threshold; unattributed residents accrue nothing."""
    from vgate_tpu.runtime.dp_engine import ReplicatedEngine

    dp = ReplicatedEngine.__new__(ReplicatedEngine)
    dp._quarantine = set()
    dp._corrupt_streaks = {}
    dp._recovery = SimpleNamespace(poison_threshold=2)
    bad_fp, innocent_fp = "fp-naan", "fp-innocent"
    exc = IntegrityError(
        "sentinel", kind="logit_nonfinite",
        sequences=[{"fingerprint": bad_fp, "seq_id": 1}],
    )
    core = SimpleNamespace(
        _fatal=exc,
        _fatal_suspects=[(bad_fp, 0), (innocent_fp, 0)],
    )
    dp._update_quarantine(core)
    assert bad_fp not in dp._quarantine  # one trip: streak only
    dp._update_quarantine(core)
    assert bad_fp in dp._quarantine
    assert innocent_fp not in dp._quarantine


def test_restart_budget_remaining_helper():
    from vgate_tpu.runtime.supervisor import restart_budget_remaining

    rec = SimpleNamespace(max_restarts=3, restart_window_s=300.0)
    now = 1000.0
    assert restart_budget_remaining([], rec, now) == 3
    assert restart_budget_remaining([999.0, 998.0], rec, now) == 1
    assert restart_budget_remaining([999.0] * 9, rec, now) == 0
    assert restart_budget_remaining([600.0], rec, now) == 3  # aged out


# ------------------------------------------------- health surfacing


def test_health_reports_restarts_remaining_and_integrity():
    sup = _bare_supervisor()
    sup.core = _FakeFatalCore(None)
    now = time.monotonic()
    sup._restart_times = [now, now]  # 2 of max 3 burned
    health = sup.health()
    assert health["restarts_remaining"] == 1
    assert health["integrity"]["quarantined_corrupt"] is False
    # outside the window the budget replenishes
    sup._restart_times = [now - 10_000]
    assert sup.health()["restarts_remaining"] == 3


def test_health_restarts_remaining_floor_zero():
    sup = _bare_supervisor()
    sup.core = _FakeFatalCore(None)
    now = time.monotonic()
    sup._restart_times = [now] * 10
    assert sup.health()["restarts_remaining"] == 0


# ------------------------------------- end-to-end drill (slow tier)


def _engine_config(**integrity_over):
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 2, "prefill_buckets": [16],
        },
        recovery={"backoff_base_s": 0.01, "backoff_cap_s": 0.05},
        integrity={
            "sweep_interval_s": 0.01,
            "sweep_leaves_per_tick": 64,
            **integrity_over,
        },
        logging={"level": "WARNING"},
    )


@pytest.mark.slow
def test_weight_corrupt_detect_reload_canary_end_to_end():
    """The tentpole loop on a real (tiny) engine: arm weight_corrupt →
    the idle sweep bit-flips and then detects the shard → the
    supervisor reloads weights (not weights-kept) → the post-reload
    canary passes → serving resumes with output identical to
    pre-corruption."""
    sup = EngineSupervisor(_engine_config(), devices=jax.devices()[:1])
    sup.start()
    try:
        params = [SamplingParams(max_tokens=8, temperature=0.0)]
        [before] = sup.generate(["integrity drill"], list(params))
        baseline_digests = dict(
            sup.core.integrity.verifier.baseline
        )
        faults.arm("weight_corrupt", mode="corrupt", times=1)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sup.total_corrupt_reloads >= 1 and sup.state in (
                HealthState.DEGRADED, HealthState.SERVING
            ):
                break
            time.sleep(0.05)
        assert sup.total_corrupt_reloads >= 1, (
            f"corrupt reload never happened: state={sup.state}, "
            f"last_fatal={sup.last_fatal}"
        )
        assert sup.quarantined_corrupt is False
        assert sup.last_integrity["kind"] == "checksum_mismatch"
        # the reloaded tree matches the original (same seed/checkpoint)
        assert dict(sup.core.integrity.verifier.baseline) == (
            baseline_digests
        )
        [after] = sup.generate(["integrity drill"], list(params))
        assert after["token_ids"] == before["token_ids"]
        stats = sup.get_stats()
        assert stats["supervisor"]["integrity"]["corrupt_reloads"] >= 1
    finally:
        sup.stop()
        faults.reset()


@pytest.mark.slow
def test_logit_corrupt_sentinel_discards_chunk_end_to_end():
    """Sentinel path: scramble the logit-guard flags mid-decode — the
    poisoned chunk is discarded (no garbage delivered), the engine
    fatals corrupt, the supervisor reloads, and the in-flight request
    completes token-identical via checkpoint/replay."""
    sup = EngineSupervisor(_engine_config(), devices=jax.devices()[:1])
    sup.start()
    try:
        params = SamplingParams(
            max_tokens=24, min_tokens=24, temperature=0.0
        )
        [before] = sup.generate(["sentinel drill"], [params])
        faults.arm("logit_corrupt", mode="corrupt", times=1)
        [res] = sup.generate(["sentinel drill"], [params])
        # the replayed result must be token-identical: every delivered
        # token predates the discarded chunk or came from the reloaded
        # core — never from corrupt logits
        assert res["token_ids"] == before["token_ids"]
        assert res["metrics"].get("resumed", 0) >= 1
        assert sup.total_corrupt_reloads >= 1
        assert sup.last_integrity["kind"].startswith("logit_")
    finally:
        sup.stop()
        faults.reset()
