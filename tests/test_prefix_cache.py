"""Automatic prefix caching: content-hashed KV page sharing + suffix-only
prefill (runtime/kv_cache.py PageAllocator, runtime/scheduler.py matching,
models/decoder.py prefill_suffix_forward).  The capability vLLM provides
opaquely to the reference; here it is first-party and tested."""

import jax
import numpy as np
import pytest

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.runtime.kv_cache import PageAllocator
from vgate_tpu.runtime.scheduler import Scheduler
from vgate_tpu.runtime.sequence import Sequence

PS = 4  # page size used throughout


# --------------------------------------------------------------- allocator


def test_allocator_register_lookup_refcount():
    alloc = PageAllocator(8)
    [p] = alloc.allocate(1)
    alloc.register(p, 123)
    # lookup takes a new reference
    assert alloc.lookup(123) == p
    assert alloc.lookup(999) is None
    # two holders: one release keeps the page live
    alloc.release([p])
    assert alloc.num_cached == 0  # still referenced by the lookup
    alloc.release([p])
    # now parked as evictable cached content, still reusable
    assert alloc.num_cached == 1
    assert alloc.lookup(123) == p
    alloc.release([p])


def test_allocator_evicts_lru_cached_pages():
    alloc = PageAllocator(4)  # pages 1..3
    pages = alloc.allocate(3)
    for i, p in enumerate(pages):
        alloc.register(p, 100 + i)
    alloc.release(pages)  # all parked, LRU order 1,2,3
    assert alloc.num_cached == 3
    assert alloc.num_free == 3  # evictable counts as allocatable
    got = alloc.allocate(2)  # evicts the two oldest
    assert got is not None
    assert alloc.prefix_evictions == 2
    # the evicted hashes are gone; the survivor still resolves
    surviving = [h for h in (100, 101, 102) if alloc.lookup(h) is not None]
    assert len(surviving) == 1


def test_allocator_oversubscription_still_fails():
    alloc = PageAllocator(4)
    assert alloc.allocate(4) is None  # only 3 usable pages
    pages = alloc.allocate(3)
    assert alloc.allocate(1) is None
    alloc.release(pages)


# --------------------------------------------------------------- scheduler


def make_sched(num_pages=64, prefix_cache=True, slots=4):
    alloc = PageAllocator(num_pages)
    return Scheduler(
        allocator=alloc,
        max_slots=slots,
        page_size=PS,
        prefill_buckets=[8, 16, 32],
        max_model_len=64,
        max_queue_size=16,
        prefix_cache=prefix_cache,
    ), alloc


def seq_of(ids, max_tokens=8):
    return Sequence(
        prompt_ids=list(ids), params=SamplingParams(max_tokens=max_tokens)
    )


def register(alloc, plan):
    """What the engine does after dispatching the plan's program."""
    for page, h in plan.register_hashes or ():
        alloc.register(page, h)


def test_scheduler_matches_shared_prefix():
    sched, alloc = make_sched()
    prompt = list(range(2, 2 + 11))  # 11 tokens -> 2 full pages + partial
    a = seq_of(prompt)
    sched.add(a)
    plan_a = sched.try_admit()
    assert plan_a.cached_len == 0
    # the two full pages are handed back for post-dispatch registration
    # (registering at admission would let a same-tick reader's program
    # dispatch ahead of this writer's)
    assert len(plan_a.register_hashes) == 2
    register(alloc, plan_a)

    b = seq_of(prompt)  # identical prompt
    sched.add(b)
    plan_b = sched.try_admit()
    assert plan_b.cached_len == 2 * PS
    assert b.pages[:2] == a.pages[:2]  # shared ids
    assert b.pages[2] != a.pages[2]  # own partial page
    assert plan_b.bucket == 8  # buckets the 3-token suffix, not the prompt
    assert sched.total_prefix_hit_tokens == 2 * PS

    # releasing one sequence must not free the shared pages for the other
    sched.remove(a)
    assert alloc.lookup is not None
    c = seq_of(prompt + [99])
    sched.add(c)
    plan_c = sched.try_admit()
    assert plan_c.cached_len == 2 * PS  # still matches via b / cache


def test_scheduler_never_matches_entire_prompt():
    """A fully page-aligned identical prompt keeps its last page un-matched
    so the suffix prefill has at least one real token to sample from."""
    sched, _ = make_sched()
    prompt = list(range(2, 2 + 8))  # exactly 2 pages
    a = seq_of(prompt)
    sched.add(a)
    register(sched.allocator, sched.try_admit())
    b = seq_of(prompt)
    sched.add(b)
    plan_b = sched.try_admit()
    assert plan_b.cached_len == PS  # only the first page matched
    assert b.pages[0] == a.pages[0]
    assert b.pages[1] != a.pages[1]


def test_scheduler_disabled_no_sharing():
    sched, alloc = make_sched(prefix_cache=False)
    prompt = list(range(2, 2 + 11))
    a = seq_of(prompt)
    sched.add(a)
    plan_a = sched.try_admit()
    assert plan_a.cached_len == 0 and not plan_a.register_hashes
    b = seq_of(prompt)
    sched.add(b)
    plan_b = sched.try_admit()
    assert plan_b.cached_len == 0
    assert set(a.pages).isdisjoint(b.pages)


# ------------------------------------------------------------------ engine


def engine_config(prefix_cache=True):
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 64, "kv_page_size": PS,
            "max_batch_slots": 4, "prefill_buckets": [8, 16, 32],
            "use_pallas": False, "prefix_cache": prefix_cache,
        },
        scheduler={"max_queue_size": 16},
        logging={"level": "WARNING"},
    )


@pytest.fixture(scope="module")
def engines():
    from vgate_tpu.runtime.engine_core import EngineCore

    cached = EngineCore(engine_config(True), devices=jax.devices()[:1])
    plain = EngineCore(engine_config(False), devices=jax.devices()[:1])
    cached.start()
    plain.start()
    yield cached, plain
    cached.stop()
    plain.stop()


def greedy(n=8):
    return SamplingParams(max_tokens=n, temperature=0.0)


def test_engine_prefix_hit_matches_uncached_output(engines):
    """Greedy output through the suffix-prefill path must equal the
    cold-path output exactly (same KV, fewer FLOPs)."""
    cached, plain = engines
    base = [7, 3, 9, 4, 11, 6, 2, 13, 5, 8, 12, 10, 14]  # 13 tokens
    [cold] = cached.generate(["x"], [greedy(1)])  # warm the engine
    [a1] = cached.generate([" ".join(map(str, base))], [greedy()])
    hit0 = cached.scheduler.total_prefix_hit_tokens
    [a2] = cached.generate([" ".join(map(str, base))], [greedy()])
    assert cached.scheduler.total_prefix_hit_tokens > hit0  # hit happened
    [p] = plain.generate([" ".join(map(str, base))], [greedy()])
    assert a1["token_ids"] == p["token_ids"]
    assert a2["token_ids"] == p["token_ids"]


def test_engine_shared_prefix_divergent_suffixes(engines):
    """Two prompts sharing a long prefix but different endings: the second
    reuses prefix pages yet produces its own correct continuation."""
    cached, plain = engines
    prefix = "alpha beta gamma delta epsilon zeta eta theta"
    p1 = prefix + " one"
    p2 = prefix + " two"
    [c1] = cached.generate([p1], [greedy()])
    [c2] = cached.generate([p2], [greedy()])
    [u1] = plain.generate([p1], [greedy()])
    [u2] = plain.generate([p2], [greedy()])
    assert c1["token_ids"] == u1["token_ids"]
    assert c2["token_ids"] == u2["token_ids"]
    assert c1["token_ids"] != c2["token_ids"] or len(c1["token_ids"]) == 0


def test_engine_stats_surface_prefix_cache(engines):
    cached, _ = engines
    stats = cached.get_stats()["scheduler"]["prefix_cache"]
    assert stats["enabled"] is True
    assert stats["hit_tokens"] > 0


def test_engine_same_wave_identical_prompts_correct(engines):
    """Two identical prompts admitted in ONE wave: the second must NOT
    read pages whose writer program hasn't dispatched (registration is
    deferred until after dispatch), so both produce correct output."""
    cached, plain = engines
    prompt = "wave one two three four five six seven eight nine"
    seqs = [
        cached.submit_prompt(prompt, greedy()) for _ in range(2)
    ]
    for s in seqs:
        assert s.done_event.wait(timeout=300)
    [ref] = plain.generate([prompt], [greedy()])
    for s in seqs:
        assert list(s.generated_ids) == ref["token_ids"]


def test_preemption_of_one_sharer_spares_shared_pages():
    """Preempting a sequence that shares prefix pages must only drop its
    reference: the surviving sharer's KV stays resident and its greedy
    output is unchanged."""
    sched, alloc = make_sched(num_pages=64)
    prompt = list(range(2, 2 + 11))
    a = seq_of(prompt)
    sched.add(a)
    register(alloc, sched.try_admit())
    b = seq_of(prompt)
    sched.add(b)
    plan_b = sched.try_admit()
    assert plan_b.cached_len == 2 * PS
    shared = list(b.pages[:2])

    used_before = alloc.num_used
    sched._preempt(a)  # a's refs drop; shared pages must survive for b
    assert all(p in b.pages for p in shared)
    # b still holds them: not evictable, not free
    assert alloc.num_used < used_before
    got = alloc.allocate(alloc.num_free)  # drain everything allocatable
    assert got is not None
    assert not set(got) & set(shared)  # shared pages were never handed out
    alloc.release(got)


def test_prefix_cache_survives_engine_preemption_pressure():
    """End-to-end: a KV pool small enough to force preemptions, prefix
    cache on — greedy outputs still match the uncached engine."""
    from vgate_tpu.runtime.engine_core import EngineCore

    def run(prefix_cache):
        config = load_config(
            model={
                "model_id": "tiny-dense",
                "engine_type": "jax_tpu",
                "dtype": "float32",
                "max_model_len": 64,
            },
            tpu={
                "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
                # tight pool: 13 usable pages for 3 sequences needing ~15
                "kv_num_pages": 14, "kv_page_size": PS,
                "max_batch_slots": 3, "prefill_buckets": [8, 16, 32],
                "use_pallas": False, "prefix_cache": prefix_cache,
            },
            scheduler={"max_queue_size": 16},
            logging={"level": "ERROR"},
        )
        core = EngineCore(config, devices=jax.devices()[:1])
        core.start()
        try:
            prompts = [
                "shared long prefix words " + tail
                for tail in ("alpha", "beta", "gamma")
            ]
            out = core.generate(
                prompts, [SamplingParams(max_tokens=10, temperature=0.0)] * 3
            )
            return [r["token_ids"] for r in out], core.get_stats()
        finally:
            core.stop()

    cached_out, cached_stats = run(True)
    plain_out, _ = run(False)
    assert cached_out == plain_out
    # the pool really was tight (otherwise the test proves nothing)
    assert (
        cached_stats["scheduler"]["preemptions"] > 0
        or cached_stats["scheduler"]["prefix_cache"]["evictions"] > 0
    )


# ------------------------------------------------- radix: COW + multi-turn


def radix_config(prefix_cache=True, **pc_overrides):
    pc = {"enabled": prefix_cache, "cow_min_tokens": 2, **pc_overrides}
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 96,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 96, "kv_page_size": PS,
            "max_batch_slots": 4, "prefill_buckets": [8, 16, 32],
            "use_pallas": False, "prefix_cache": pc,
        },
        scheduler={"max_queue_size": 16},
        logging={"level": "ERROR"},
    )


@pytest.fixture(scope="module")
def radix_engines():
    from vgate_tpu.runtime.engine_core import EngineCore

    cached = EngineCore(radix_config(True), devices=jax.devices()[:1])
    plain = EngineCore(radix_config(False), devices=jax.devices()[:1])
    cached.start()
    plain.start()
    yield cached, plain
    cached.stop()
    plain.stop()


def test_engine_cow_partial_page_identity(radix_engines):
    """A prompt diverging INSIDE a shared page takes the copy-on-write
    path (device page copy + unaligned suffix prefill) and must still
    produce exactly the cold-path greedy output."""
    cached, plain = radix_engines
    base = [7, 3, 9, 4, 11, 6, 2, 13, 5, 8, 12, 10, 14, 9]
    ids_a = base
    ids_b = base[:10] + [21, 22, 23, 24]  # 2 full pages + 2 in-page
    sa = cached.submit_tokens(list(ids_a), greedy())
    assert sa.done_event.wait(timeout=300)
    cow0 = cached.radix_cache.total_cow_copies
    sb = cached.submit_tokens(list(ids_b), greedy())
    assert sb.done_event.wait(timeout=300)
    assert cached.radix_cache.total_cow_copies > cow0, "COW never fired"
    pa = plain.submit_tokens(list(ids_a), greedy())
    pb = plain.submit_tokens(list(ids_b), greedy())
    assert pa.done_event.wait(timeout=300)
    assert pb.done_event.wait(timeout=300)
    assert list(sa.generated_ids) == list(pa.generated_ids)
    assert list(sb.generated_ids) == list(pb.generated_ids)


def test_engine_multi_turn_generated_reuse(radix_engines):
    """Turn N+1 re-sends turn N's prompt AND answer: the radix tree
    indexes generated pages at finish, so the next turn's hit covers
    (nearly) the whole previous transcript — the flat chain could only
    ever match the previous PROMPT pages."""
    cached, plain = radix_engines
    t1 = [31, 32, 33, 34, 35, 36, 37, 38, 39, 40, 41]
    s1 = cached.submit_tokens(list(t1), greedy())
    assert s1.done_event.wait(timeout=300)
    answer = list(s1.generated_ids)
    # next turn: transcript (minus the final token, whose KV was never
    # written) + new user text
    t2 = t1 + answer + [51, 52, 53, 54, 55]
    hit0 = cached.scheduler.total_prefix_hit_tokens
    s2 = cached.submit_tokens(list(t2), greedy())
    assert s2.done_event.wait(timeout=300)
    hit = cached.scheduler.total_prefix_hit_tokens - hit0
    # the hit must reach INTO the generated region: more than the
    # prompt-only pages the flat chain would serve
    flat_max = (len(t1) // PS) * PS
    assert hit > flat_max, (hit, flat_max)
    p2 = plain.submit_tokens(list(t2), greedy())
    assert p2.done_event.wait(timeout=300)
    assert list(s2.generated_ids) == list(p2.generated_ids)


def test_engine_radix_stats_surface(radix_engines):
    cached, _ = radix_engines
    stats = cached.get_stats()["scheduler"]["prefix_cache"]
    assert stats["mode"] == "radix"
    assert stats["inserted_pages"] > 0
    assert "evictions_pressure" in stats and "cow_copies" in stats
