"""Workload-lab (vgate_tpu/loadlab) fast tier: arrival-process
statistics + the open-loop property, SLO grader math, scenario YAML
round-trips, artifact schema stability, compare-tool regression
detection, and a seconds-scale dry-run smoke of the full sweep loop."""

import asyncio
import json
import os

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu.loadlab import arrivals, compare, slo, workload
from vgate_tpu.loadlab.driver import Sample, classify_http_error, drive_cell
from vgate_tpu.loadlab.runner import (
    hist_delta,
    parse_histograms,
    run_scenario_async,
)
from vgate_tpu.loadlab.scenario import (
    ArrivalSpec,
    ChaosSpec,
    Scenario,
    SLOSpec,
    TrafficMix,
    bundled_scenarios,
    load_scenario,
)

# ---------------------------------------------------------------- arrivals


def test_poisson_mean_rate_and_determinism():
    rate, dur = 40.0, 25.0
    a = arrivals.poisson(rate, dur, seed=7)
    # n ~ Poisson(1000): +-12% is ~4 sigma — deterministic given the seed
    assert 0.88 * rate * dur < len(a) < 1.12 * rate * dur
    assert a == sorted(a) and a[0] >= 0 and a[-1] < dur
    assert a == arrivals.poisson(rate, dur, seed=7)
    assert a != arrivals.poisson(rate, dur, seed=8)


def test_constant_arrivals_evenly_spaced():
    a = arrivals.constant(10.0, 2.0)
    assert len(a) == 20
    gaps = {round(b - x, 9) for x, b in zip(a, a[1:])}
    assert gaps == {0.1}


def test_bursty_preserves_mean_rate_and_modulates():
    rate, dur = 20.0, 60.0
    a = arrivals.bursty(rate, dur, seed=3, on_s=2.0, off_s=4.0,
                        burst_mult=3.0)
    assert 0.85 * rate * dur < len(a) < 1.15 * rate * dur
    # density inside on-windows must exceed off-windows
    on = sum(1 for t in a if (t % 6.0) < 2.0)
    off = len(a) - on
    assert on / 2.0 > 1.5 * (off / 4.0)


def test_bursty_clamps_burst_mult():
    # burst_mult > cycle/on would need a negative off rate; the clamp
    # keeps the process well-defined (everything lands in on-windows)
    a = arrivals.bursty(10.0, 30.0, seed=1, on_s=2.0, off_s=4.0,
                        burst_mult=100.0)
    assert all((t % 6.0) < 2.0 for t in a)


def test_unknown_process_raises():
    with pytest.raises(ValueError):
        arrivals.generate("uniform", 1.0, 1.0, 0)


async def test_open_loop_sends_independent_of_slow_responder():
    """THE property: a server answering in 400ms must not delay sends
    planned 20ms apart — arrival timestamps are precomputed and every
    fire task sleeps to its own absolute due time."""

    async def slow_chat(request):
        await asyncio.sleep(0.4)
        return web.json_response({
            "object": "chat.completion",
            "choices": [{"index": 0,
                         "message": {"role": "assistant", "content": "x"},
                         "finish_reason": "stop"}],
            "usage": {"prompt_tokens": 1, "completion_tokens": 1,
                      "total_tokens": 2},
        })

    app = web.Application()
    app.router.add_post("/v1/chat/completions", slow_chat)
    server = TestServer(app)
    await server.start_server()
    try:
        base = str(server.make_url("")).rstrip("/")
        n = 15
        plan = [
            workload.PlannedRequest(
                offset_s=0.02 * i,
                endpoint="/v1/chat/completions",
                body={"messages": [{"role": "user", "content": "hi"}],
                      "max_tokens": 4},
                tier="standard", shape="chat", stream=False, index=i,
            )
            for i in range(n)
        ]
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        samples = await drive_cell(base, plan, timeout_s=10.0)
        wall = loop.time() - t0
    finally:
        await server.close()
    assert len(samples) == n
    assert all(s.ok for s in samples), [s.kind for s in samples]
    # closed-loop (await each 400ms response before the next send)
    # would need n * 0.4 = 6s; open-loop needs ~(0.28s spread + 0.4s)
    assert wall < 2.5, f"driver serialized sends: wall={wall:.2f}s"
    # every send left on time even though every response was in flight
    assert max(s.send_lag_s for s in samples) < 0.2


# ------------------------------------------------------------------ grader


def _sample(tier="interactive", ok=True, ttft=0.1, tpot=0.01, e2e=0.5,
            kind=None, lag=0.0):
    return Sample(
        tier=tier, shape="chat", offset_s=0.0,
        kind=kind or ("ok" if ok else "http_503_overloaded"),
        ok=ok, status=200 if ok else 503,
        ttft_s=ttft if ok else None, tpot_s=tpot if ok else None,
        e2e_s=e2e, tokens=8 if ok else 0, send_lag_s=lag,
    )


def test_goodput_boundaries():
    spec = SLOSpec(ttft_ms=100.0)
    at = _sample(ttft=0.100)        # exactly at the bound: good
    over = _sample(ttft=0.1001)     # over: not good
    shed = _sample(ok=False)        # typed error: never good
    assert slo.meets_slo(at, spec)
    assert not slo.meets_slo(over, spec)
    assert not slo.meets_slo(shed, spec)
    # no spec for the tier -> availability goodput (ok == good)
    assert slo.meets_slo(over, None)
    cell = slo.grade_cell(
        [at, over, shed], {"interactive": spec}, qps=3.0, duration_s=1.0
    )
    t = cell["tiers"]["interactive"]
    assert t["n"] == 3 and t["ok"] == 2 and t["slo_met"] == 1
    assert t["goodput"] == pytest.approx(1 / 3, abs=1e-4)
    assert t["errors"] == {"http_503_overloaded": 1}
    assert cell["overall"]["goodput"] == pytest.approx(1 / 3, abs=1e-4)
    assert cell["unhandled_errors"] == 0 and cell["valid"]


def test_missing_ttft_fails_a_ttft_slo():
    # an "ok" sample that somehow produced no first token cannot meet a
    # TTFT bound; a sample with no tpot (single-token) passes tpot
    spec = SLOSpec(ttft_ms=100.0, tpot_ms=10.0)
    no_ttft = _sample(ttft=None)
    single_tok = _sample(tpot=None)
    assert not slo.meets_slo(no_ttft, spec)
    assert slo.meets_slo(single_tok, spec)


def test_send_lag_invalidates_cell():
    bad = [_sample(lag=0.5) for _ in range(10)]
    cell = slo.grade_cell(bad, {}, qps=1.0, duration_s=1.0)
    assert not cell["valid"]


def test_knee_detection():
    cells = [(1.0, 1.0), (2.0, 1.0), (4.0, 0.9), (8.0, 0.4)]
    assert slo.max_goodput_qps(cells) == 4.0
    # delivered good qps: 1, 2, 3.6, 3.2 -> knee at 4
    assert slo.knee_qps(cells) == 4.0
    assert slo.max_goodput_qps([(1.0, 0.5)]) is None
    assert slo.knee_qps([]) is None


def test_percentiles_nearest_rank():
    vals = [float(i) for i in range(1, 101)]
    assert slo.percentile(vals, 0.50) == 50.0
    assert slo.percentile(vals, 0.99) == 99.0
    assert slo.percentile([], 0.5) is None


# ---------------------------------------------------------------- scenario


def test_scenario_yaml_roundtrip(tmp_path):
    s = Scenario(
        name="rt",
        seed=5,
        duration_s=3.0,
        qps_cells=[1.0, 2.0],
        arrival=ArrivalSpec(process="bursty", on_s=1.0, off_s=2.0,
                            burst_mult=2.0),
        mixes=[
            TrafficMix(shape="multi_turn_chat", tier="interactive",
                       weight=2.0, turns=2),
            TrafficMix(shape="embeddings", tier="batch", stream=False),
        ],
        slos={"interactive": SLOSpec(ttft_ms=100, tpot_ms=10)},
        chaos=ChaosSpec(faults="decode_step:raise:times=1", at_s=1.0,
                        cell_index=1),
        server_env={"VGT_LOGGING__LEVEL": "WARNING"},
    )
    p = tmp_path / "rt.yaml"
    p.write_text(s.to_yaml())
    back = load_scenario(str(p))
    assert back.to_dict() == s.to_dict()
    assert back.content_hash() == s.content_hash()
    assert back.chaos.cell_index == 1
    assert back.arrival.process == "bursty"


def test_bundled_scenarios_load():
    names = bundled_scenarios()
    assert "smoke_mixed" in names and "tpu_mixed_sweep" in names
    for name in names:
        s = load_scenario(name)
        assert s.qps_cells and s.mixes
        # every bundled scenario must synthesize a valid plan
        plan = workload.build_plan(s, 0, min(s.qps_cells))
        assert all(
            p.endpoint.startswith("/v1/") for p in plan
        )


def test_scenario_rejects_unknowns():
    with pytest.raises(ValueError):
        TrafficMix(shape="nope")
    with pytest.raises(ValueError):
        TrafficMix(tier="vip")
    with pytest.raises(ValueError):
        Scenario(qps_cells=[])
    with pytest.raises(ValueError):
        Scenario.from_dict({"name": "x", "typo_field": 1})
    with pytest.raises(ValueError):
        Scenario.from_dict(
            {"slos": {"interactive": {"ttft_p99_ms": 5}}}
        )


def test_plan_determinism_and_prefix_sharing():
    s = load_scenario("smoke_mixed")
    p1 = workload.build_plan(s, 0, 4.0)
    p2 = workload.build_plan(s, 0, 4.0)
    assert [(r.offset_s, r.body) for r in p1] == [
        (r.offset_s, r.body) for r in p2
    ]
    # rag requests drawing the same doc share their preamble verbatim
    rag = [r for r in p1 if r.shape == "rag"]
    if len(rag) >= 2:
        systems = [r.body["messages"][0]["content"] for r in rag]
        assert any(
            a == b for i, a in enumerate(systems)
            for b in systems[i + 1:]
        ) or len(set(systems)) == len(systems)


# ---------------------------------------------------- artifact + compare


def _make_lines(goodputs=(1.0, 0.95), scenario_name="art",
                fingerprint="f00"):
    s = Scenario(name=scenario_name, qps_cells=[2.0, 8.0], duration_s=5.0)
    meta = {
        "kind": "meta", "schema": slo.SCHEMA, "scenario": s.name,
        "scenario_hash": s.content_hash(), "seed": s.seed,
        "ts": "2026-08-03T00:00:00Z", "platform": "cpu",
        "device": "cpu", "git_sha": "abc123",
        "config_fingerprint": fingerprint,
        "base_url": "http://x", "slos": {},
    }
    cells = []
    for qps, g in zip(s.qps_cells, goodputs):
        n = 40
        good = int(round(g * n))
        samples = [
            _sample(tier="interactive", ttft=0.05) for _ in range(good)
        ] + [
            _sample(tier="interactive", ok=False) for _ in range(n - good)
        ]
        cell = slo.grade_cell(
            samples, {"interactive": SLOSpec(ttft_ms=200)},
            qps=qps, duration_s=5.0,
        )
        cell["server"] = None
        cells.append(cell)
    summary = slo.summarize(cells)
    return [meta] + cells + [summary]


def test_artifact_schema_stability(tmp_path):
    lines = _make_lines()
    assert slo.validate_lines(lines) == []
    # pinned field lists: additive evolution only
    assert set(slo.META_REQUIRED) <= set(lines[0])
    assert set(slo.CELL_REQUIRED) <= set(lines[1])
    assert set(slo.SUMMARY_REQUIRED) <= set(lines[-1])
    path = str(tmp_path / "a.jsonl")
    slo.write_artifact(path, lines)
    art = slo.load_artifact(path)
    assert art["meta"]["scenario"] == "art"
    assert len(art["cells"]) == 2
    assert art["summary"]["max_goodput_qps"] == 8.0


def test_load_artifact_rejects_foreign_files(tmp_path):
    p = tmp_path / "x.jsonl"
    p.write_text('{"metric": "output_tokens_per_sec_per_chip"}\n')
    with pytest.raises(ValueError):
        slo.load_artifact(str(p))


def test_compare_flags_doctored_goodput_regression(tmp_path):
    old_p = str(tmp_path / "old.jsonl")
    new_p = str(tmp_path / "new.jsonl")
    lines = _make_lines(goodputs=(1.0, 0.95))
    slo.write_artifact(old_p, lines)
    # identical artifacts: clean pass
    slo.write_artifact(new_p, lines)
    assert compare.main([old_p, new_p]) == 0
    # doctor the overload cell's goodput down 0.35: must exit nonzero
    doctored = _make_lines(goodputs=(1.0, 0.60))
    slo.write_artifact(new_p, doctored)
    rc = compare.main([old_p, new_p])
    assert rc == 1
    regs = compare.compare(
        slo.load_artifact(old_p), slo.load_artifact(new_p)
    )
    kinds = {r["kind"] for r in regs}
    assert "goodput_drop" in kinds
    # the knee moved down with the same offered cells -> also flagged
    assert "knee_drop" in kinds


def test_compare_cells_filter_gates_only_listed_cells(tmp_path):
    """--cells restricts the gate to one regime (swap_check.sh gates
    the overload cell of an A/B where the quiet cell's handful of
    samples is pure noise); the summary knee gates are skipped under a
    filter since a partial view cannot see a knee move."""
    old_p = str(tmp_path / "old.jsonl")
    new_p = str(tmp_path / "new.jsonl")
    slo.write_artifact(old_p, _make_lines(goodputs=(1.0, 0.95)))
    # quiet cell (2 qps) collapses, overload cell (8 qps) holds
    slo.write_artifact(new_p, _make_lines(goodputs=(0.5, 0.95)))
    assert compare.main([old_p, new_p]) == 1
    assert compare.main([old_p, new_p, "--cells", "8"]) == 0
    # a regression IN the gated cell still fails under the filter
    slo.write_artifact(new_p, _make_lines(goodputs=(1.0, 0.60)))
    assert compare.main([old_p, new_p, "--cells", "8"]) == 1
    # a filter matching NO common cell is a usage error (exit 2), not
    # a vacuous pass
    assert compare.main([old_p, new_p, "--cells", "15"]) == 2


def test_compare_refuses_config_fingerprint_change(tmp_path):
    # same scenario, env-overridden server (7B vs 1.5B): the scenario
    # hash can't see it but the /stats config fingerprint can
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    slo.write_artifact(a, _make_lines(fingerprint="aaa"))
    slo.write_artifact(b, _make_lines(fingerprint="bbb"))
    assert compare.main([a, b]) == 2
    assert compare.main([a, b, "--allow-config-change"]) == 0


def test_compare_gates_knee_qps_drop(tmp_path):
    # per-cell goodput inside threshold everywhere, but the delivered-
    # goodput knee halves: documented as a gated regression
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    old_lines = _make_lines(goodputs=(1.0, 0.95))
    new_lines = _make_lines(goodputs=(1.0, 0.95))
    new_lines[-1] = dict(new_lines[-1], knee_qps=2.0)
    slo.write_artifact(a, old_lines)
    slo.write_artifact(b, new_lines)
    regs = compare.compare(
        slo.load_artifact(a), slo.load_artifact(b)
    )
    assert any(
        r["kind"] == "knee_drop" and r.get("metric") == "knee_qps"
        for r in regs
    )
    assert compare.main([a, b]) == 1


def test_compare_refuses_cross_scenario(tmp_path):
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    slo.write_artifact(a, _make_lines(scenario_name="one"))
    slo.write_artifact(b, _make_lines(scenario_name="two"))
    assert compare.main([a, b]) == 2
    assert compare.main([a, b, "--allow-cross-scenario"]) == 0


def test_compare_ignores_small_tiers_and_invalid_cells(tmp_path):
    old_lines = _make_lines(goodputs=(1.0, 0.95))
    new_lines = _make_lines(goodputs=(1.0, 0.60))
    # mark the regressed cell invalid (client-side lag): gates nothing
    new_lines[2]["valid"] = False
    # the summary also drops invalid cells from its knee numbers
    new_lines[-1] = slo.summarize(new_lines[1:3])
    a = str(tmp_path / "a.jsonl")
    b = str(tmp_path / "b.jsonl")
    slo.write_artifact(a, old_lines)
    slo.write_artifact(b, new_lines)
    assert compare.main([a, b]) == 0


def test_classify_http_error_taxonomy():
    assert classify_http_error(
        503, {"error": {"reason": "overloaded"}}
    ) == "http_503_overloaded"
    assert classify_http_error(
        503, {"error": {"reason": "recovering"}}
    ) == "http_503_recovering"
    assert classify_http_error(503, None) == "http_503"
    assert classify_http_error(429, {}) == "http_429"
    assert classify_http_error(
        504, {"error": {"metadata": {"partial_tokens": 3}}}
    ) == "http_504_partial"
    assert classify_http_error(504, {"error": {}}) == "http_504"
    assert classify_http_error(418, {}) == "http_418"


def test_parse_histograms_and_delta():
    text = "\n".join([
        "# HELP vgt_time_to_first_token_seconds Time to first token",
        'vgt_time_to_first_token_seconds_bucket{le="0.1"} 3',
        'vgt_time_to_first_token_seconds_bucket{le="1"} 5',
        'vgt_time_to_first_token_seconds_bucket{le="+Inf"} 5',
        "vgt_time_to_first_token_seconds_count 5",
        "vgt_time_to_first_token_seconds_sum 1.5",
        "vgt_time_per_output_token_seconds_count 0",
        "vgt_time_per_output_token_seconds_sum 0",
    ])
    before = parse_histograms("")
    after = parse_histograms(text)
    d = hist_delta(
        before["vgt_time_to_first_token_seconds"],
        after["vgt_time_to_first_token_seconds"],
    )
    assert d["count"] == 5
    assert d["mean_ms"] == pytest.approx(300.0)
    assert d["p99_ms_le"] == pytest.approx(1000.0)


# ------------------------------------------------- dry-run sweep smoke


async def test_loadlab_smoke_dry_run(tmp_path):
    """Seconds-scale end-to-end: a real gateway (dry-run engine) driven
    through one tiny Poisson cell; the artifact must grade per-tier
    goodput, stamp the schema, and report zero unhandled errors."""
    from vgate_tpu.config import load_config
    from vgate_tpu.server.app import create_app

    config = load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 4, "max_wait_time_ms": 5.0},
        logging={"level": "WARNING"},
    )
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    try:
        base = str(client.make_url("")).rstrip("/")
        scenario = Scenario(
            name="ci_smoke",
            duration_s=1.5,
            qps_cells=[8.0],
            mixes=[
                TrafficMix(shape="chat", tier="interactive",
                           prompt_units=6, max_tokens=8, stream=True),
                TrafficMix(shape="embeddings", tier="standard",
                           prompt_units=6, stream=False),
            ],
            slos={"interactive": SLOSpec(ttft_ms=10000)},
            request_timeout_s=15.0,
            warmup_requests=1,
        )
        out = str(tmp_path / "smoke.jsonl")
        result = await run_scenario_async(
            scenario, base, out_path=out,
            platform="cpu", device="test",
            progress=lambda s: None,
        )
    finally:
        await client.close()
    lines = result["lines"]
    assert slo.validate_lines(lines) == []
    art = slo.load_artifact(out)
    assert art["meta"]["platform"] == "cpu"
    cell = art["cells"][0]
    assert cell["offered"] > 0
    assert cell["unhandled_errors"] == 0, cell
    assert "interactive" in cell["tiers"]
    inter = cell["tiers"]["interactive"]
    assert inter["goodput"] is not None and inter["goodput"] > 0
    assert art["summary"]["unhandled_errors"] == 0


async def test_debug_faults_endpoint_gating(monkeypatch):
    """POST /debug/faults arms only with VGT_FAULTS_HTTP=1 (the drills'
    opt-in); DELETE disarms; default is 403."""
    from vgate_tpu import faults
    from vgate_tpu.config import load_config
    from vgate_tpu.server.app import create_app

    config = load_config(
        model={"engine_type": "dry_run"},
        logging={"level": "WARNING"},
    )
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    try:
        monkeypatch.delenv("VGT_FAULTS_HTTP", raising=False)
        resp = await client.post(
            "/debug/faults",
            json={"faults": "decode_step:raise:times=1"},
        )
        assert resp.status == 403
        monkeypatch.setenv("VGT_FAULTS_HTTP", "1")
        resp = await client.post(
            "/debug/faults",
            json={"faults": "decode_step:raise:times=1"},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["armed"] == 1
        assert any(
            s["point"] == "decode_step" for s in body["active"]
        )
        assert faults.is_active()
        resp = await client.get("/debug/faults")
        assert (await resp.json())["armed"]
        resp = await client.delete("/debug/faults")
        assert resp.status == 200
        assert not faults.is_active()
        # bad spec arms nothing but doesn't 500
        resp = await client.post(
            "/debug/faults", json={"faults": "nonsense"}
        )
        assert resp.status == 200
        assert (await resp.json())["armed"] == 0
        # valid JSON that isn't an object is a typed 400, not a 500
        resp = await client.post("/debug/faults", json=[1, 2])
        assert resp.status == 400
        resp = await client.post(
            "/debug/faults", data=b"not json",
            headers={"Content-Type": "application/json"},
        )
        assert resp.status == 400
    finally:
        await client.close()
