"""Test harness.

* Forces JAX onto a virtual 8-device CPU platform BEFORE jax import, so
  sharding/scheduler tests run without TPU hardware (SURVEY.md section 4's
  multi-node strategy: ``xla_force_host_platform_device_count``).
* Runs ``async def`` tests via a tiny pytest hook (no pytest-asyncio in the
  image).
* Resets config + tracing global singletons between tests (reference autouse
  fixture: tests/conftest.py:242-249).
"""

import asyncio
import inspect
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon TPU plugin overrides JAX_PLATFORMS; config.update wins over it.
jax.config.update("jax_platforms", "cpu")

import pytest

# Compile-heavy files (JAX traces many engine/parallel program variants;
# minutes each on a small host).  Everything else is the `fast` tier:
# gateway + scheduler + ops, meant to finish in well under a minute —
# the tier that matches the reference's 97-tests-in-2.73s suite
# (/root/reference/tests; VERDICT r2 weak-5).  Run with:
#   pytest -m fast -q tests/        # quick signal
#   pytest -m slow -q tests/        # engine/parallel compile-heavy tier
SLOW_FILES = {
    "test_distributed",
    "test_dp_engine",
    "test_encoder",
    "test_engine",
    "test_jax_backend",
    "test_logprobs",
    "test_model_parity",
    "test_pallas_kernels",
    "test_penalties",
    "test_pipeline",
    "test_prefix_cache",
    "test_quant",
    "test_recovery",
    "test_ring_attention",
    "test_sharding",
    "test_speculative",
    "test_weights_checkpoint",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.get_closest_marker("slow") or item.get_closest_marker(
            "fast"
        ):
            continue  # explicit per-test tier wins over the file default
        tier = "slow" if item.module.__name__ in SLOW_FILES else "fast"
        item.add_marker(getattr(pytest.mark, tier))


_last_module = [None]


@pytest.fixture(autouse=True)
def _clear_jax_caches_per_file(request):
    """Clear jax's pjit/compile caches at test-FILE boundaries.

    A single-process full-suite run accumulates ~350 tests' worth of
    compiled executables; twice (r5) the XLA CPU compiler segfaulted in
    backend_compile_and_load near the END of such runs (test_speculative,
    after ~340 prior compiles) while every file passes in isolation.
    Bounding cache growth at file granularity keeps one-invocation runs
    viable; per-file recompiles cost little since files rarely share
    program shapes.

    SINGLE-PROCESS ASSUMPTION: the `_last_module` sentinel presumes
    tests arrive in file order within ONE process, which is exactly
    what pytest-xdist breaks — each worker sees an interleaved slice,
    so the sentinel would thrash clear_caches() between nearly every
    test (slow) while doing nothing for the per-process accumulation it
    exists to bound (each xdist worker compiles far fewer programs than
    a full serial run anyway).  Skip the clearing under xdist; the
    tier-1 runner pins `-p no:xdist` (ROADMAP.md) so serial runs keep
    the protection."""
    if os.environ.get("PYTEST_XDIST_WORKER"):
        yield
        return
    mod = request.module.__name__
    if _last_module[0] not in (None, mod):
        jax.clear_caches()
    _last_module[0] = mod
    yield


def pytest_pyfunc_call(pyfuncitem):
    """Run coroutine test functions on a fresh event loop."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


@pytest.fixture(autouse=True)
def _reset_globals(monkeypatch):
    from vgate_tpu import config as config_mod
    from vgate_tpu import faults
    from vgate_tpu import tracing as tracing_mod

    # isolate tests from the repo's sample ./config.yaml
    monkeypatch.setenv("VGT_CONFIG_PATH", "/nonexistent/vgt-test-config.yaml")
    config_mod.reset_config()
    tracing_mod.reset_tracing()
    faults.reset()
    yield
    config_mod.reset_config()
    tracing_mod.reset_tracing()
    # armed faults must never leak across tests (a leaked decode_step
    # fault would crash every later engine test)
    faults.reset()


@pytest.fixture
def dry_config():
    """A config wired for dry-run testing."""
    from vgate_tpu.config import load_config

    return load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 4, "max_wait_time_ms": 10.0},
        logging={"level": "WARNING"},
    )
