"""Test harness.

* Forces JAX onto a virtual 8-device CPU platform BEFORE jax import, so
  sharding/scheduler tests run without TPU hardware (SURVEY.md section 4's
  multi-node strategy: ``xla_force_host_platform_device_count``).
* Runs ``async def`` tests via a tiny pytest hook (no pytest-asyncio in the
  image).
* Resets config + tracing global singletons between tests (reference autouse
  fixture: tests/conftest.py:242-249).
"""

import asyncio
import inspect
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

# The axon TPU plugin overrides JAX_PLATFORMS; config.update wins over it.
jax.config.update("jax_platforms", "cpu")

import pytest


def pytest_pyfunc_call(pyfuncitem):
    """Run coroutine test functions on a fresh event loop."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {
            name: pyfuncitem.funcargs[name]
            for name in pyfuncitem._fixtureinfo.argnames
        }
        asyncio.run(fn(**kwargs))
        return True
    return None


@pytest.fixture(autouse=True)
def _reset_globals(monkeypatch):
    from vgate_tpu import config as config_mod
    from vgate_tpu import tracing as tracing_mod

    # isolate tests from the repo's sample ./config.yaml
    monkeypatch.setenv("VGT_CONFIG_PATH", "/nonexistent/vgt-test-config.yaml")
    config_mod.reset_config()
    tracing_mod.reset_tracing()
    yield
    config_mod.reset_config()
    tracing_mod.reset_tracing()


@pytest.fixture
def dry_config():
    """A config wired for dry-run testing."""
    from vgate_tpu.config import load_config

    return load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 4, "max_wait_time_ms": 10.0},
        logging={"level": "WARNING"},
    )
