"""Fault-injection registry + gateway health/recovery surface (fast tier:
no engine compiles — registry unit tests, dry-run gateway tests, and
batcher shutdown-drain tests)."""

import asyncio

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu import faults
from vgate_tpu.config import load_config
from vgate_tpu.errors import (
    EngineDeadError,
    EngineRecoveringError,
    PoisonRequestError,
    RetryableError,
)
from vgate_tpu.server.app import create_app


# ---------------------------------------------------------------- registry


def test_arm_and_fire_consumes_charges():
    spec = faults.arm("decode_step", mode="raise", times=2)
    with pytest.raises(faults.InjectedFault):
        faults.check("decode_step")
    with pytest.raises(faults.InjectedFault):
        faults.check("decode_step")
    faults.check("decode_step")  # charges exhausted: no-op
    assert spec.fired == 2
    assert spec.times == 0


def test_unknown_point_and_mode_rejected():
    with pytest.raises(ValueError):
        faults.arm("not_a_point")
    with pytest.raises(ValueError):
        faults.arm("decode_step", mode="explode")
    with pytest.raises(ValueError):
        faults.arm("decode_step", kind="weird")


def test_disarm_and_reset():
    faults.arm("prefill", times=-1)
    faults.disarm("prefill")
    faults.check("prefill")  # disarmed: no-op
    faults.arm("kv_alloc", times=-1)
    faults.reset()
    faults.check("kv_alloc")
    assert faults.snapshot() == []


def test_kind_and_fingerprint_carried():
    faults.arm("prefill", kind="poison", times=1)
    with pytest.raises(faults.InjectedFault) as exc_info:
        faults.check("prefill", payload=(1, 2, 3))
    assert exc_info.value.fault_kind == "poison"
    assert exc_info.value.fingerprint == faults.fingerprint((1, 2, 3))


def test_match_targets_one_payload():
    faults.arm(
        "prefill", times=-1, match=lambda ids: ids is not None and 666 in ids
    )
    faults.check("prefill", payload=(1, 2, 3))  # no match: passes
    with pytest.raises(faults.InjectedFault):
        faults.check("prefill", payload=(5, 666))


def test_delay_mode_sleeps_not_raises():
    import time

    faults.arm("backend_generate", mode="delay", delay_s=0.05, times=1)
    start = time.perf_counter()
    faults.check("backend_generate")
    assert time.perf_counter() - start >= 0.04


def test_probability_seeded_deterministic():
    spec = faults.arm(
        "kv_alloc", mode="raise", times=-1, probability=0.5, seed=7
    )
    fired = 0
    for _ in range(200):
        try:
            faults.check("kv_alloc")
        except faults.InjectedFault:
            fired += 1
    assert spec.fired == fired
    assert 60 <= fired <= 140  # ~p=0.5, seeded so stable


def test_corrupt_array_scrambles_and_counts():
    faults.arm("decode_step", mode="corrupt", times=1)
    arr = np.arange(8, dtype=np.int32)
    out = faults.corrupt_array("decode_step", arr)
    assert (out == (arr ^ 0x55)).all()
    # charge consumed: second call is a passthrough
    again = faults.corrupt_array("decode_step", arr)
    assert (again == arr).all()
    # corrupt specs are invisible to check()
    faults.arm("decode_step", mode="corrupt", times=1)
    faults.check("decode_step")


def test_arm_from_env_faults_and_chaos():
    n = faults.arm_from_env(
        {"VGT_FAULTS": "decode_step:raise:kind=poison:times=3,"
                       "kv_alloc:delay:delay=0.01"}
    )
    assert n == 2
    snap = {s["point"]: s for s in faults.snapshot()}
    assert snap["decode_step"]["kind"] == "poison"
    assert snap["decode_step"]["times"] == 3
    assert snap["kv_alloc"]["mode"] == "delay"
    faults.reset()
    n = faults.arm_from_env({"VGT_CHAOS": "0.1"})
    assert n == len(faults.FAULT_POINTS)
    assert all(s["probability"] == 0.1 for s in faults.snapshot())


def test_arm_from_env_bad_entries_ignored():
    n = faults.arm_from_env(
        {"VGT_FAULTS": "garbage,decode_step:raise:times=notanint,"
                       "prefill:raise"}
    )
    assert n == 1  # only the well-formed entry armed
    assert faults.snapshot()[0]["point"] == "prefill"


def test_fingerprint_stable_and_distinct():
    assert faults.fingerprint([1, 2, 3]) == faults.fingerprint((1, 2, 3))
    assert faults.fingerprint([1, 2, 3]) != faults.fingerprint([1, 2, 4])


def test_check_raises_injected_fault_for_scalar_payloads():
    """kv_alloc probes with an int payload and weight_load with a path
    string; a raise-mode fault there must still produce InjectedFault
    (with its kind intact), never a fingerprint TypeError."""
    faults.arm("kv_alloc", mode="raise", kind="unrecoverable", times=1)
    with pytest.raises(faults.InjectedFault) as exc_info:
        faults.check("kv_alloc", payload=5)
    assert exc_info.value.fault_kind == "unrecoverable"
    faults.arm("weight_load", mode="raise", times=1)
    with pytest.raises(faults.InjectedFault):
        faults.check("weight_load", payload="/models/ckpt")
    assert faults.fingerprint(None) != faults.fingerprint(5)


# ------------------------------------------------------------ error types


def test_error_taxonomy():
    assert isinstance(EngineRecoveringError("x"), RetryableError)
    assert isinstance(EngineDeadError("x"), RetryableError)
    assert EngineRecoveringError("x", retry_after=0.01).retry_after >= 1.0
    assert EngineDeadError("x").retry_after == 30.0
    assert not isinstance(PoisonRequestError("x"), RetryableError)


# --------------------------------------------------------------- gateway


async def _client(**overrides):
    overrides.setdefault("model", {"engine_type": "dry_run"})
    overrides.setdefault(
        "batch", {"max_batch_size": 4, "max_wait_time_ms": 5.0}
    )
    overrides.setdefault("logging", {"level": "WARNING"})
    config = load_config(**overrides)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    return client


async def test_health_always_reports_engine_state():
    """Satellite: /health carries engine state + queue depth even for
    backends without device_health (the dry-run backend has neither a
    device nor a supervisor)."""
    client = await _client()
    try:
        body = await (await client.get("/health")).json()
        assert body["status"] == "ok"
        assert body["engine"]["state"] == "serving"
        assert body["engine"]["alive"] is True
        assert "queue_depth" in body["engine"]
        assert "batcher_pending" in body["engine"]
    finally:
        await client.close()


async def test_liveness_readiness_split():
    client = await _client()
    try:
        live = await client.get("/health/live")
        ready = await client.get("/health/ready")
        assert live.status == 200
        assert ready.status == 200
        # simulate the health state machine positions the supervisor
        # drives on a real engine
        backend = client.server.app["engine"].backend
        backend.serving_state = lambda: "recovering"
        ready = await client.get("/health/ready")
        assert ready.status == 503
        assert "Retry-After" in ready.headers
        live = await client.get("/health/live")
        assert live.status == 200  # recovering is alive
        backend.serving_state = lambda: "dead"
        assert (await client.get("/health/ready")).status == 503
        assert (await client.get("/health/live")).status == 503
        assert (await client.get("/health")).status == 503
    finally:
        await client.close()


async def test_batcher_rejects_fast_while_recovering():
    """Satellite + tentpole: while RECOVERING the batcher sheds at
    admission with a retryable 503 + Retry-After instead of queuing into
    a dead engine; quarantined prompts map to 400."""
    client = await _client()
    try:
        backend = client.server.app["engine"].backend
        backend.serving_state = lambda: "recovering"
        resp = await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "hi"}]},
        )
        assert resp.status == 503
        assert int(resp.headers["Retry-After"]) >= 1
        body = await resp.json()
        assert body["error"]["type"] == "overloaded_error"
        backend.serving_state = lambda: "dead"
        resp = await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "hi"}]},
        )
        assert resp.status == 503
        assert int(resp.headers["Retry-After"]) >= 1
        backend.serving_state = lambda: "serving"
        resp = await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "hi"}]},
        )
        assert resp.status == 200
    finally:
        await client.close()


async def test_cache_hit_serves_while_recovering():
    """A cache-servable request needs no engine: the fail-fast gate sits
    below the cache lookup, so hits keep serving through recovery."""
    client = await _client()
    try:
        req = {
            "messages": [{"role": "user", "content": "cache me"}],
            "temperature": 0.5,
        }
        first = await client.post("/v1/chat/completions", json=req)
        assert first.status == 200
        backend = client.server.app["engine"].backend
        backend.serving_state = lambda: "recovering"
        second = await client.post("/v1/chat/completions", json=req)
        assert second.status == 200
        assert (await second.json())["cached"] is True
        # a novel request is still shed
        miss = await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "novel"}]},
        )
        assert miss.status == 503
    finally:
        await client.close()


async def test_poison_request_maps_to_400():
    client = await _client()
    try:
        batcher = client.server.app["batcher"]

        async def poisoned_submit(*args, **kwargs):
            raise PoisonRequestError("request abc is quarantined")

        batcher.submit = poisoned_submit
        resp = await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "boom"}]},
        )
        assert resp.status == 400
        body = await resp.json()
        assert body["error"]["type"] == "invalid_request_error"
        assert "quarantined" in body["error"]["message"]
    finally:
        await client.close()


# ------------------------------------------------------- batcher shutdown


class _DeadBackend:
    """Backend whose engine is already dead: every generate fails."""

    def create_sampling_params(self, **kwargs):
        from vgate_tpu.backends.base import SamplingParams

        return SamplingParams(**kwargs)

    def generate(self, prompts, params):
        raise RuntimeError("engine is dead")


class _DeadEngine:
    def __init__(self, config):
        self.config = config
        self.backend = _DeadBackend()


async def test_stop_resolves_queue_drained_into_dead_engine():
    """Satellite fix: stop() must resolve EVERY pending future even when
    the engine is dead and the queue exceeds one batch — leftover
    requests previously hung forever."""
    from vgate_tpu.batcher import RequestBatcher

    config = load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 2, "max_wait_time_ms": 10_000.0},
        cache={"enabled": False},
        logging={"level": "ERROR"},
    )
    from vgate_tpu.batcher import BatchRequest

    batcher = RequestBatcher(_DeadEngine(config), config)
    # enqueue directly (no start(), no size trigger): only stop() can
    # resolve these, and 5 > max_batch_size forces the drain LOOP
    loop = asyncio.get_running_loop()
    futs = []
    for i in range(5):
        fut = loop.create_future()
        futs.append(fut)
        batcher._queue.append(
            BatchRequest(
                request_id=f"r{i}",
                prompt=f"prompt {i}",
                params=batcher.engine.backend.create_sampling_params(),
                cache_key=f"k{i}",
                future=fut,
            )
        )
    await batcher.stop()
    settled = await asyncio.wait_for(
        asyncio.gather(*futs, return_exceptions=True), timeout=5
    )
    assert len(settled) == 5
    assert all(isinstance(r, RuntimeError) for r in settled)
    assert not batcher._queue


async def test_stop_fails_leftover_futures_explicitly():
    """The belt-and-braces leftover sweep: a request still queued after
    the drain + loop-cancel (e.g. a racer that slipped in between) gets
    an explicit retryable error, never a forever-pending future."""
    from vgate_tpu.batcher import BatchRequest, RequestBatcher

    config = load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 4, "max_wait_time_ms": 10_000.0},
        logging={"level": "ERROR"},
    )
    batcher = RequestBatcher(_DeadEngine(config), config)
    fut = asyncio.get_running_loop().create_future()
    request = BatchRequest(
        request_id="r1",
        prompt="late",
        params=batcher.engine.backend.create_sampling_params(),
        cache_key="k",
        future=fut,
    )

    # simulate the race: the drain loop sees an empty queue; the request
    # lands while stop() awaits the cancelled batch loop, so only the
    # leftover sweep can resolve it
    batcher._running = True
    batcher._loop_task = asyncio.get_running_loop().create_task(
        asyncio.sleep(60)
    )
    asyncio.get_running_loop().call_soon(batcher._queue.append, request)
    await batcher.stop()
    with pytest.raises(EngineRecoveringError):
        await asyncio.wait_for(fut, timeout=2)
