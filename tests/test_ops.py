"""Op-level unit tests: sampling semantics, rope, norms, attention masks."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.ops.attention import causal_prefill_attention, paged_decode_attention
from vgate_tpu.ops.norms import layer_norm, rms_norm
from vgate_tpu.ops.rope import apply_rope
from vgate_tpu.ops.sampling import sample_tokens


def test_rms_norm_matches_formula():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)), jnp.float32)
    w = jnp.asarray(np.random.default_rng(1).normal(size=(8,)), jnp.float32)
    out = np.asarray(rms_norm(x, w, eps=1e-6))
    xn = np.asarray(x)
    expect = xn / np.sqrt((xn**2).mean(-1, keepdims=True) + 1e-6) * np.asarray(w)
    np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-5)


def test_layer_norm_zero_mean_unit_var():
    x = jnp.asarray(np.random.default_rng(0).normal(size=(3, 16)), jnp.float32)
    out = np.asarray(
        layer_norm(x, jnp.ones((16,)), jnp.zeros((16,)))
    )
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1.0, atol=1e-2)


def test_rope_preserves_norm_and_zero_position_identity():
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(1, 4, 2, 16)), jnp.float32
    )
    pos = jnp.asarray([[0, 1, 2, 3]])
    out = apply_rope(x, pos)
    # rotation preserves norms
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )
    # position 0 is identity
    np.testing.assert_allclose(
        np.asarray(out[0, 0]), np.asarray(x[0, 0]), atol=1e-6
    )


def test_rope_relative_property():
    """<rope(q,m), rope(k,n)> depends only on m-n."""
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(1, 1, 1, 32)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 1, 1, 32)), jnp.float32)

    def dot_at(m, n):
        qm = apply_rope(q, jnp.asarray([[m]]))
        kn = apply_rope(k, jnp.asarray([[n]]))
        return float(jnp.sum(qm * kn))

    assert abs(dot_at(5, 3) - dot_at(12, 10)) < 1e-3


def test_causal_attention_ignores_padding_and_future():
    rng = np.random.default_rng(0)
    B, S, H, hd = 1, 8, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    out1 = causal_prefill_attention(q, k, v, jnp.asarray([5]))
    # mutating padded keys (>=5) must not change outputs at positions < 5
    k2 = k.at[:, 5:].set(99.0)
    v2 = v.at[:, 5:].set(-99.0)
    out2 = causal_prefill_attention(q, k2, v2, jnp.asarray([5]))
    np.testing.assert_allclose(
        np.asarray(out1[:, :5]), np.asarray(out2[:, :5]), atol=1e-5
    )


def test_paged_decode_matches_contiguous_attention():
    """Paged gather attention == plain attention over the same context."""
    rng = np.random.default_rng(1)
    B, H, KV, hd, ps = 2, 4, 2, 16, 4
    ctx_lens = [6, 3]
    n_pages_per_seq = 2
    P = 1 + B * n_pages_per_seq
    k_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    page_tables = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    out = np.asarray(
        paged_decode_attention(
            q, k_pages, v_pages, page_tables, jnp.asarray(ctx_lens)
        )
    )
    # naive per-slot computation
    for b in range(B):
        n = ctx_lens[b]
        k = np.asarray(k_pages[:, np.asarray(page_tables[b])])
        k = k.reshape(KV, -1, hd).transpose(1, 0, 2)[:n]
        v = np.asarray(v_pages[:, np.asarray(page_tables[b])])
        v = v.reshape(KV, -1, hd).transpose(1, 0, 2)[:n]
        k = np.repeat(k, H // KV, axis=1)
        v = np.repeat(v, H // KV, axis=1)
        qb = np.asarray(q[b])  # [H, hd]
        scores = np.einsum("hd,thd->ht", qb, k) / np.sqrt(hd)
        probs = np.exp(scores - scores.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        expect = np.einsum("ht,thd->hd", probs, v)
        np.testing.assert_allclose(out[b], expect, rtol=1e-4, atol=1e-5)


# --- sampling ---


def _uniform_logits(v=64):
    return jnp.zeros((1, v), jnp.float32)


def test_greedy_when_temperature_zero():
    logits = jnp.asarray(
        np.random.default_rng(0).normal(size=(4, 100)), jnp.float32
    )
    tokens = sample_tokens(
        logits,
        temperature=jnp.zeros((4,)),
        top_p=jnp.ones((4,)),
        top_k=jnp.zeros((4,), jnp.int32),
        key=jax.random.PRNGKey(0),
    )
    np.testing.assert_array_equal(
        np.asarray(tokens), np.asarray(jnp.argmax(logits, -1))
    )


def test_top_k_restricts_support():
    logits = jnp.asarray([[10.0, 9.0, 8.0] + [0.0] * 61])
    seen = set()
    for i in range(50):
        tok = sample_tokens(
            logits,
            temperature=jnp.asarray([5.0]),
            top_p=jnp.asarray([1.0]),
            top_k=jnp.asarray([2], jnp.int32),
            key=jax.random.PRNGKey(i),
        )
        seen.add(int(tok[0]))
    assert seen <= {0, 1}


def test_top_p_restricts_support():
    # one dominant token: top_p=0.5 keeps only it
    logits = jnp.asarray([[10.0] + [0.0] * 63])
    for i in range(20):
        tok = sample_tokens(
            logits,
            temperature=jnp.asarray([1.0]),
            top_p=jnp.asarray([0.5]),
            top_k=jnp.asarray([0], jnp.int32),
            key=jax.random.PRNGKey(i),
        )
        assert int(tok[0]) == 0


def test_per_slot_params_are_independent():
    """Slot 0 greedy, slot 1 high-temp: slot 0 must stay deterministic."""
    logits = jnp.asarray(
        np.tile(np.random.default_rng(2).normal(size=(1, 128)), (2, 1)),
        jnp.float32,
    )
    argmax = int(jnp.argmax(logits[0]))
    randoms = set()
    for i in range(30):
        toks = sample_tokens(
            logits,
            temperature=jnp.asarray([0.0, 3.0]),
            top_p=jnp.asarray([1.0, 1.0]),
            top_k=jnp.asarray([0, 0], jnp.int32),
            key=jax.random.PRNGKey(i),
        )
        assert int(toks[0]) == argmax
        randoms.add(int(toks[1]))
    assert len(randoms) > 3  # slot 1 actually samples


def test_sampling_distribution_roughly_matches():
    probs_target = np.array([0.6, 0.3, 0.1])
    logits = jnp.asarray([np.log(probs_target)], jnp.float32)
    counts = np.zeros(3)
    N = 400
    for i in range(N):
        tok = sample_tokens(
            jnp.tile(logits, (1, 1)),
            temperature=jnp.asarray([1.0]),
            top_p=jnp.asarray([1.0]),
            top_k=jnp.asarray([0], jnp.int32),
            key=jax.random.PRNGKey(i),
        )
        counts[int(tok[0])] += 1
    freq = counts / N
    np.testing.assert_allclose(freq, probs_target, atol=0.08)


def test_flash_prefill_matches_naive_oracle():
    """Blockwise online-softmax prefill == the [S,S]-materializing oracle
    (which it replaces as the engine's default path)."""
    from vgate_tpu.ops.attention import flash_prefill_attention

    rng = np.random.default_rng(11)
    B, S, H, KV, hd = 2, 64, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    lens = jnp.asarray([37, 64], jnp.int32)
    expect = causal_prefill_attention(q, k, v, lens)
    got = flash_prefill_attention(q, k, v, lens, block_k=16)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_flash_prefill_chunked_offset_matches_full():
    """A query chunk at global offset h attending over history+chunk keys
    must equal the same rows of the full-sequence computation."""
    from vgate_tpu.ops.attention import flash_prefill_attention

    rng = np.random.default_rng(12)
    B, S, H, KV, hd = 1, 64, 4, 2, 16
    hist = 32
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    lens = jnp.asarray([S], jnp.int32)
    full = causal_prefill_attention(q, k, v, lens)
    chunk = flash_prefill_attention(
        q[:, hist:], k, v, lens, block_k=16,
        q_offset=jnp.asarray([hist], jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(chunk), np.asarray(full[:, hist:]), rtol=2e-5, atol=2e-5
    )


def test_flash_prefill_peak_memory_beats_naive():
    """The blockwise path's compiled temp footprint must stay well under the
    naive path's O(S^2) score materialization at a serving-sized bucket."""
    from vgate_tpu.ops.attention import flash_prefill_attention

    B, S, H, KV, hd = 1, 2048, 8, 2, 64
    args = [
        jnp.zeros((B, S, H, hd), jnp.float32),
        jnp.zeros((B, S, KV, hd), jnp.float32),
        jnp.zeros((B, S, KV, hd), jnp.float32),
        jnp.asarray([S], jnp.int32),
    ]

    def temp_bytes(fn):
        mem = jax.jit(fn).lower(*args).compile().memory_analysis()
        if mem is None:
            pytest.skip("memory_analysis unavailable on this backend")
        return mem.temp_size_in_bytes

    naive = temp_bytes(causal_prefill_attention)
    flash = temp_bytes(flash_prefill_attention)
    # naive materializes [B,H,S,S] scores+probs (~268 MB here); blockwise
    # holds one [B,S,block,H] slab (~16 MB)
    assert flash < naive / 4, (flash, naive)


def test_moe_dispatch_is_ragged():
    """The MoE dispatch must be sort/scatter-based: no intermediate of size
    O(T*E*capacity) may appear in the jaxpr (the one-hot dispatch/combine
    tensors it replaces were [T, E, C]; VERDICT r1 weak-3)."""
    import jax

    from vgate_tpu.models.decoder import _moe_mlp, init_params
    from vgate_tpu.models.specs import TINY_MOE

    spec = TINY_MOE
    params = init_params(spec, jax.random.PRNGKey(0), jnp.float32)
    lp = jax.tree.map(lambda x: x[0], params["layers"])  # one layer slice

    T, D = 512, spec.hidden_size
    E, K = spec.num_experts, spec.experts_per_token
    capacity = max(4, int((T * K / E) * 2.0 + 0.5))
    x = jax.random.normal(jax.random.PRNGKey(1), (T, D), jnp.float32)

    jaxpr = jax.make_jaxpr(lambda x: _moe_mlp(x, lp, spec))(x)
    tec = T * E * capacity
    big = [
        v.aval.shape
        for eqn in jaxpr.jaxpr.eqns
        for v in eqn.outvars
        if hasattr(v.aval, "shape")
        and int(np.prod(v.aval.shape or (1,))) >= tec
    ]
    assert not big, f"dense dispatch-sized intermediates present: {big}"

    # and the ragged path matches a direct per-token loop reference
    def dense_reference(x):
        router = jax.nn.softmax(
            x @ lp["router"].astype(jnp.float32), axis=-1
        )
        vals, idx = jax.lax.top_k(router, K)
        vals = vals / vals.sum(-1, keepdims=True)
        out = np.zeros((T, D), np.float32)
        xn = np.asarray(x)
        for t in range(T):
            for j in range(K):
                e = int(idx[t, j])
                g = xn[t] @ np.asarray(lp["gate"]["w"][e])
                u = xn[t] @ np.asarray(lp["up"]["w"][e])
                h = (jax.nn.silu(g) * u) @ np.asarray(lp["down"]["w"][e])
                out[t] += float(vals[t, j]) * np.asarray(h)
        return out

    got = np.asarray(_moe_mlp(x, lp, spec))
    want = dense_reference(x)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_sliding_window_flash_matches_oracle_and_drops_old_keys():
    """Gemma-2 local attention: the blockwise path with a window must match
    the [S,S] oracle given the same window, and differ from global
    attention once S exceeds the window (old keys really are dropped)."""
    from vgate_tpu.ops.attention import flash_prefill_attention

    rng = np.random.default_rng(21)
    B, S, H, KV, hd = 2, 64, 4, 2, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, KV, hd)), jnp.float32)
    lens = jnp.asarray([41, 64], jnp.int32)
    win = jnp.asarray(16, jnp.int32)
    expect = causal_prefill_attention(q, k, v, lens, window=win)
    got = flash_prefill_attention(q, k, v, lens, block_k=16, window=win)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )
    # window=0 means global: matches the plain oracle
    got_global = flash_prefill_attention(
        q, k, v, lens, block_k=16, window=jnp.asarray(0, jnp.int32)
    )
    np.testing.assert_allclose(
        np.asarray(got_global),
        np.asarray(causal_prefill_attention(q, k, v, lens)),
        rtol=2e-5, atol=2e-5,
    )
    # and a real window changes rows past it
    assert not np.allclose(np.asarray(got)[0, 40], np.asarray(got_global)[0, 40])


def test_paged_decode_window_matches_truncated_context():
    """Decode-step local attention over paged KV == global attention over a
    context manually truncated to the last `window` tokens."""
    from vgate_tpu.ops.attention import paged_decode_attention

    rng = np.random.default_rng(22)
    B, H, KV, hd, ps, n_pages = 2, 4, 2, 16, 4, 8
    ctx = ps * n_pages  # 32
    seq_lens = jnp.asarray([29, 32], jnp.int32)
    win = 12
    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    k_pages = jnp.asarray(
        rng.normal(size=(KV, 1 + B * n_pages, ps, hd)), jnp.float32
    )
    v_pages = jnp.asarray(
        rng.normal(size=(KV, 1 + B * n_pages, ps, hd)), jnp.float32
    )
    pt = jnp.asarray(
        1 + np.arange(B * n_pages, dtype=np.int32).reshape(B, n_pages)
    )
    got = paged_decode_attention(
        q, k_pages, v_pages, pt, seq_lens, window=jnp.asarray(win, jnp.int32)
    )
    # oracle: zero out everything outside the window by faking seq_lens and
    # shifting -- rebuild contiguous K/V and mask by hand
    k_flat = np.moveaxis(
        np.asarray(k_pages)[:, np.asarray(pt)].reshape(KV, B, ctx, hd), 0, 2
    )
    v_flat = np.moveaxis(
        np.asarray(v_pages)[:, np.asarray(pt)].reshape(KV, B, ctx, hd), 0, 2
    )
    scale = hd ** -0.5
    for b in range(B):
        L = int(seq_lens[b])
        lo = max(0, L - win)
        kk = np.repeat(k_flat[b, lo:L], H // KV, axis=1)  # [w, H, hd]
        vv = np.repeat(v_flat[b, lo:L], H // KV, axis=1)
        scores = np.einsum("hd,thd->ht", np.asarray(q)[b], kk) * scale
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        expect_b = np.einsum("ht,thd->hd", p, vv)
        np.testing.assert_allclose(
            np.asarray(got)[b], expect_b, rtol=2e-5, atol=2e-5
        )


# ------------------------------------------- carry-threaded KV parity

def test_kv_carry_parity_all_forwards():
    """tpu.kv_carry (A/B handle; default OFF — measured 5.2x decode
    regression on v5e, RESULTS_r4.md) must be numerically
    identical to the r2 xs/ys threading across decode, prefill and
    suffix-prefill, for a global-attention family AND the sliding-window
    /softcap family (the carry paths use mixed scalar/slice/array
    indexed writes and layer-flattened gathers — this pins them)."""
    import numpy as np

    from vgate_tpu.models.decoder import (
        decode_forward, init_params, prefill_forward,
        prefill_suffix_forward,
    )
    from vgate_tpu.models.specs import TINY_DENSE, TINY_GEMMA2

    for spec in (TINY_DENSE, TINY_GEMMA2):
        ps, pps, B, S = 4, 8, 2, 16
        params = init_params(spec, jax.random.PRNGKey(3), jnp.float32)
        P = 1 + B * pps
        shape = (spec.num_layers, spec.num_kv_heads, P, ps, spec.head_dim)
        k0 = jnp.zeros(shape, jnp.float32)
        v0 = jnp.zeros(shape, jnp.float32)
        pt = jnp.asarray(
            1 + np.arange(B * pps).reshape(B, pps), jnp.int32
        )
        rng = np.random.default_rng(0)
        toks = jnp.asarray(
            rng.integers(2, spec.vocab_size, (B, S)), jnp.int32
        )
        lens = jnp.asarray([14, 9], jnp.int32)

        def pin(a, b, msg):
            for x, y, nm in zip(a, b, ("logits", "k", "v")):
                np.testing.assert_allclose(
                    np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-5,
                    err_msg=f"{spec.name} {msg} {nm}",
                )

        pin(
            prefill_forward(
                params, spec, toks, lens, k0, v0, pt[:, : S // ps],
                kv_carry=False,
            ),
            prefill_forward(
                params, spec, toks, lens, k0, v0, pt[:, : S // ps],
                kv_carry=True,
            ),
            "prefill",
        )

        # resident prefix of 8 tokens, then the suffix pass both ways
        _, kf, vf = prefill_forward(
            params, spec, toks[:, :8], jnp.asarray([8, 8], jnp.int32),
            k0, v0, pt[:, :2],
        )
        args = (
            params, spec, toks[:, 8:], jnp.asarray([8, 8], jnp.int32),
            jnp.asarray([6, 4], jnp.int32), kf, vf, pt[:, 2:4],
            pt[:, :4],
        )
        pin(
            prefill_suffix_forward(*args, kv_carry=False),
            prefill_suffix_forward(*args, kv_carry=True),
            "suffix",
        )

        dargs = (
            params, spec, jnp.asarray([7, 11], jnp.int32),
            jnp.asarray([8, 8], jnp.int32), kf, vf, pt,
        )
        pin(
            decode_forward(
                *dargs, active=jnp.asarray([True, True]), kv_carry=False
            ),
            decode_forward(
                *dargs, active=jnp.asarray([True, True]), kv_carry=True
            ),
            "decode",
        )


def test_kv_carry_parity_spec_verify():
    """Carry vs xs/ys parity for the speculative verify forward (valid
    candidate rows only — rows past input_lens are unspecified)."""
    import numpy as np

    from vgate_tpu.models.decoder import (
        init_params, prefill_forward, spec_verify_forward,
    )
    from vgate_tpu.models.specs import TINY_DENSE as spec

    ps, pps, B, S = 4, 8, 2, 4
    params = init_params(spec, jax.random.PRNGKey(3), jnp.float32)
    P = 1 + B * pps
    shape = (spec.num_layers, spec.num_kv_heads, P, ps, spec.head_dim)
    k0 = jnp.zeros(shape, jnp.float32)
    v0 = jnp.zeros(shape, jnp.float32)
    pt = jnp.asarray(1 + np.arange(B * pps).reshape(B, pps), jnp.int32)
    rng = np.random.default_rng(2)
    prompts = jnp.asarray(
        rng.integers(2, spec.vocab_size, (B, 8)), jnp.int32
    )
    _, kf, vf = prefill_forward(
        params, spec, prompts, jnp.asarray([8, 6], jnp.int32), k0, v0,
        pt[:, :2],
    )
    cand = jnp.asarray(
        rng.integers(2, spec.vocab_size, (B, S)), jnp.int32
    )
    args = (
        params, spec, cand, jnp.asarray([8, 6], jnp.int32),
        jnp.asarray([4, 2], jnp.int32), kf, vf, pt,
    )
    a = spec_verify_forward(
        *args, active=jnp.asarray([True, True]), kv_carry=False
    )
    b = spec_verify_forward(
        *args, active=jnp.asarray([True, True]), kv_carry=True
    )
    in_lens = [4, 2]
    for bb in range(B):
        n = in_lens[bb]
        np.testing.assert_allclose(
            np.asarray(a[0][bb, :n]), np.asarray(b[0][bb, :n]),
            rtol=1e-5, atol=1e-5,
        )
