"""Config layering tests (reference: tests/test_config.py:227-272 asserts
priority env > yaml > default; same matrix here plus the TPU section)."""

import os

import pytest

from vgate_tpu.config import (
    VGTConfig,
    get_config,
    load_config,
    reset_config,
    set_config,
)


def test_defaults():
    cfg = VGTConfig()
    assert cfg.server.port == 8000
    assert cfg.model.engine_type == "jax_tpu"
    assert cfg.batch.max_batch_size == 8
    assert cfg.batch.max_wait_time_ms == 50.0
    assert cfg.cache.enabled is True
    assert cfg.tpu.kv_page_size == 32  # measured best (RESULTS_r4.md)
    assert cfg.tpu.max_batch_slots == 32


def test_yaml_overrides(tmp_path):
    path = tmp_path / "config.yaml"
    path.write_text(
        "server:\n  port: 9001\nbatch:\n  max_batch_size: 16\n"
        "tpu:\n  tp: 4\n"
    )
    cfg = load_config(str(path))
    assert cfg.server.port == 9001
    assert cfg.batch.max_batch_size == 16
    assert cfg.tpu.tp == 4
    # untouched defaults survive the merge
    assert cfg.cache.max_size == 1024


def test_env_overrides_beat_yaml(tmp_path, monkeypatch):
    path = tmp_path / "config.yaml"
    path.write_text("server:\n  port: 9001\n")
    monkeypatch.setenv("VGT_SERVER__PORT", "9002")
    monkeypatch.setenv("VGT_CACHE__ENABLED", "false")
    monkeypatch.setenv("VGT_TPU__PREFILL_BUCKETS", "[64, 128]")
    cfg = load_config(str(path))
    assert cfg.server.port == 9002
    assert cfg.cache.enabled is False
    assert cfg.tpu.prefill_buckets == [64, 128]


def test_init_overrides_beat_env(monkeypatch):
    monkeypatch.setenv("VGT_SERVER__PORT", "9002")
    cfg = load_config(server={"port": 9003})
    assert cfg.server.port == 9003


def test_engine_type_validation():
    with pytest.raises(ValueError):
        load_config(model={"engine_type": "cuda"})


def test_dtype_validation():
    with pytest.raises(ValueError):
        load_config(model={"dtype": "float64"})


def test_singleton_and_reset():
    a = get_config()
    assert get_config() is a
    reset_config()
    b = get_config()
    assert b is not a
    custom = load_config(server={"port": 1234})
    set_config(custom)
    assert get_config().server.port == 1234


def test_config_path_env(tmp_path, monkeypatch):
    path = tmp_path / "alt.yaml"
    path.write_text("server:\n  port: 7777\n")
    monkeypatch.setenv("VGT_CONFIG_PATH", str(path))
    cfg = load_config()
    assert cfg.server.port == 7777
