"""Continuous-batching scheduler unit tests (host-only, no JAX)."""

import pytest

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.runtime.kv_cache import KVGeometry, PageAllocator
from vgate_tpu.runtime.scheduler import (
    DecodePlan,
    EngineBusyError,
    PrefillPlan,
    Scheduler,
)
from vgate_tpu.runtime.sequence import Sequence, SeqStatus


def make_sched(num_pages=32, slots=4, page_size=4, buckets=(8, 16), max_len=64,
               queue=8):
    alloc = PageAllocator(num_pages)
    return Scheduler(
        allocator=alloc,
        max_slots=slots,
        page_size=page_size,
        prefill_buckets=list(buckets),
        max_model_len=max_len,
        max_queue_size=queue,
    ), alloc


def seq_of(n_prompt, max_tokens=8):
    return Sequence(
        prompt_ids=list(range(2, 2 + n_prompt)),
        params=SamplingParams(max_tokens=max_tokens),
    )


def test_allocator_all_or_nothing():
    alloc = PageAllocator(4)  # pages 1..3 usable
    assert alloc.num_free == 3
    assert alloc.allocate(4) is None
    pages = alloc.allocate(3)
    assert sorted(pages) == [1, 2, 3]
    alloc.release(pages)
    assert alloc.num_free == 3


def test_allocator_rejects_bad_release():
    alloc = PageAllocator(4)
    with pytest.raises(ValueError):
        alloc.release([0])  # trash page must never be released


def test_kv_geometry():
    geom = KVGeometry(
        num_layers=2, num_pages=9, page_size=4, kv_heads=2, head_dim=8,
        max_model_len=32,
    )
    assert geom.pages_per_seq == 8
    assert geom.total_tokens == 32  # trash page excluded


def test_prefill_admission_and_bucketing():
    sched, alloc = make_sched()
    seq = seq_of(n_prompt=5)
    sched.add(seq)
    plan = sched.schedule()
    assert isinstance(plan, PrefillPlan)
    assert plan.bucket == 8  # 5 -> bucket 8
    assert len(seq.pages) == 2  # ceil(5/4)
    assert seq.status is SeqStatus.RUNNING
    assert alloc.num_used == 2


def test_decode_after_prefill():
    sched, _ = make_sched()
    seq = seq_of(4)
    sched.add(seq)
    sched.schedule()
    seq.append_token(9)  # engine appends prefill token
    plan = sched.schedule()
    assert isinstance(plan, DecodePlan)
    assert plan.seqs == [seq]


def test_prefill_priority_over_decode():
    sched, _ = make_sched()
    a = seq_of(4)
    sched.add(a)
    sched.schedule()
    a.append_token(1)
    b = seq_of(4)
    sched.add(b)
    plan = sched.schedule()
    assert isinstance(plan, PrefillPlan)
    assert plan.seq is b


def test_page_allocated_on_boundary_crossing():
    sched, alloc = make_sched(page_size=4)
    seq = seq_of(4)  # exactly one page
    sched.add(seq)
    sched.schedule()
    assert len(seq.pages) == 1
    seq.append_token(1)  # position 4 -> needs page 2
    plan = sched.schedule()
    assert isinstance(plan, DecodePlan)
    assert len(seq.pages) == 2


def test_queue_full_sheds_load():
    sched, _ = make_sched(queue=2)
    sched.add(seq_of(4))
    sched.add(seq_of(4))
    with pytest.raises(EngineBusyError):
        sched.add(seq_of(4))


def test_oversized_prompt_rejected():
    sched, _ = make_sched(max_len=16)
    with pytest.raises(ValueError):
        sched.add(seq_of(20))


def test_preemption_frees_youngest():
    # 5 usable pages, two seqs of 2 pages each -> 1 free page
    sched, alloc = make_sched(num_pages=6, page_size=4)
    old = seq_of(8)
    sched.add(old)
    sched.schedule()
    old.append_token(1)
    young = seq_of(8)
    sched.add(young)
    sched.schedule()
    young.append_token(1)
    assert alloc.num_free == 1
    # old crosses a page boundary (uses the last page), then young crosses:
    # allocator is empty -> young (the newest) must be preempted
    for _ in range(4):
        old.append_token(1)
        young.append_token(1)
        plan = sched.schedule()
        assert isinstance(plan, (DecodePlan, PrefillPlan))
        if young.status is SeqStatus.WAITING:
            break
    assert young.status is SeqStatus.WAITING
    assert young.preempt_count == 1
    assert young.slot is None
    assert sched.total_preemptions == 1
    # preempted seq keeps its generated tokens for recompute
    assert young.num_prompt_tokens > 8


def test_remove_releases_everything():
    sched, alloc = make_sched()
    seq = seq_of(6)
    sched.add(seq)
    sched.schedule()
    used = alloc.num_used
    assert used > 0
    sched.remove(seq)
    assert alloc.num_used == 0
    assert sched.slots[0] is None


def test_impossible_prompt_fails_instead_of_deadlocking():
    sched, _ = make_sched(num_pages=2, page_size=4, max_len=64)
    seq = seq_of(30)  # needs 8 pages, only 1 usable
    sched.add(seq)
    plan = sched.schedule()
    assert plan is None
    assert seq.status is SeqStatus.FAILED


def test_idle_returns_none():
    sched, _ = make_sched()
    assert sched.schedule() is None
    assert not sched.has_work()


def test_prepare_decode_horizon_allocates_ahead():
    """horizon=k must reserve pages covering positions pos..pos+k-1 (the
    engine's chunked-decode contract, engine_core.py:_tick)."""
    sched, alloc = make_sched(page_size=4)
    seq = seq_of(4, max_tokens=16)  # fills exactly one page
    sched.add(seq)
    sched.schedule()  # admit: 1 page for the 4 prompt tokens
    assert len(seq.pages) == 1
    seq.append_token(9)  # first (prefill) token -> pos 4, page 2 territory
    assert sched.prepare_decode([seq], horizon=6)
    # positions 4..9 span pages 1 and 2 -> 3 pages total... pos 4..9 -> 2 more
    assert len(seq.pages) == 3  # ceil((4+6)/4)


def test_prepare_decode_horizon_capped_by_budget():
    """A sequence with 1 token of budget left must not allocate horizon
    pages for steps that will be discarded as overshoot."""
    sched, alloc = make_sched(page_size=4)
    seq = seq_of(4, max_tokens=2)
    sched.add(seq)
    sched.schedule()
    seq.append_token(9)  # 1 generated, budget leaves 1 more
    used_before = alloc.num_used
    assert sched.prepare_decode([seq], horizon=8)
    # only the page holding pos 4 (already needed for the kept step) counts
    assert alloc.num_used == used_before + 1
    assert len(seq.pages) == 2


def test_admission_deadline_sheds_stale_requests():
    """scheduler.admission_deadline_ms: queued requests older than the
    deadline are failed with AdmissionDeadlineExceeded instead of admitted
    (SURVEY.md section 5.3 load shedding); fresh requests still admit."""
    import time

    from vgate_tpu.runtime.scheduler import AdmissionDeadlineExceeded

    alloc = PageAllocator(32)
    sched = Scheduler(
        allocator=alloc,
        max_slots=4,
        page_size=4,
        prefill_buckets=[8],
        max_model_len=64,
        max_queue_size=8,
        admission_deadline_ms=50.0,
    )
    stale = seq_of(4)
    stale.arrival_t = time.perf_counter() - 1.0  # 1s in queue
    fresh = seq_of(4)
    sched.add(stale)
    sched.add(fresh)
    plan = sched.try_admit()
    assert stale.status is SeqStatus.FAILED
    assert isinstance(stale.error, AdmissionDeadlineExceeded)
    assert isinstance(stale.error, EngineBusyError)  # maps to HTTP 503
    assert plan is not None and plan.seq is fresh
    assert sched.total_deadline_shed == 1
    assert sched.get_stats()["deadline_shed"] == 1


def test_admission_deadline_spares_preempted():
    """A preempted sequence re-queued past the deadline must NOT be shed:
    it was already admitted once and holds generated tokens."""
    import time

    alloc = PageAllocator(32)
    sched = Scheduler(
        allocator=alloc,
        max_slots=4,
        page_size=4,
        prefill_buckets=[8],
        max_model_len=64,
        max_queue_size=8,
        admission_deadline_ms=50.0,
    )
    seq = seq_of(4)
    sched.add(seq)
    sched.try_admit()
    seq.append_token(9)
    sched._preempt(seq)
    seq.arrival_t = time.perf_counter() - 1.0
    plan = sched.try_admit()
    assert plan is not None and plan.seq is seq
    assert sched.total_deadline_shed == 0


def test_auto_num_pages_dtype_and_hbm_aware():
    """fp32 KV halves the page budget of bf16; hbm_bytes scales it
    (VERDICT r1 weak-6)."""
    from vgate_tpu.models.specs import TINY_DENSE
    from vgate_tpu.runtime.kv_cache import auto_num_pages

    class FakeTPU:
        platform = "tpu"

        @staticmethod
        def memory_stats():
            return None

    common = dict(
        spec=TINY_DENSE, page_size=16, hbm_utilization=0.5,
        device=FakeTPU(), params_bytes=0, hard_cap=1 << 40,
    )
    bf16 = auto_num_pages(dtype_bytes=2, **common)
    fp32 = auto_num_pages(dtype_bytes=4, **common)
    assert fp32 == bf16 // 2
    double = auto_num_pages(
        dtype_bytes=2, hbm_bytes=32 * 1024**3, **common
    )
    assert double == bf16 * 2


def _pressure_sched(num_pages=32, max_slots=2, page_size=4):
    from vgate_tpu.runtime.kv_cache import PageAllocator
    from vgate_tpu.runtime.scheduler import Scheduler

    return Scheduler(
        allocator=PageAllocator(num_pages),
        max_slots=max_slots,
        page_size=page_size,
        prefill_buckets=[8, 16],
        max_model_len=32,
    )


def test_has_admissible_waiting_distinguishes_blockers():
    """The admission-pressure predicate is true only when the head of
    the queue could ACTUALLY be admitted: free slot AND allocatable
    pages.  Page exhaustion must read as not-admissible (the engine
    keys chunk shrinking off this — shrinking buys nothing when
    admission is blocked on pages)."""
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.runtime.sequence import Sequence

    sched = _pressure_sched(num_pages=9, max_slots=2, page_size=4)
    assert not sched.has_admissible_waiting()  # empty queue

    sp = SamplingParams(max_tokens=4, temperature=0.0)
    sched.add(Sequence(prompt_ids=[1] * 8, params=sp))  # needs 2 pages
    assert sched.has_admissible_waiting()

    # drain the pool: 8 allocatable pages (1 reserved) -> take 7
    held = sched.allocator.allocate(7)
    assert held is not None
    assert not sched.has_admissible_waiting()  # pages exhausted
    sched.allocator.release(held)
    assert sched.has_admissible_waiting()

    # saturate slots
    sched.slots[0] = object()
    sched.slots[1] = object()
    assert not sched.has_admissible_waiting()
    sched.slots[0] = sched.slots[1] = None

    # an aborted head is skipped; the next live prompt decides
    sched.waiting[0].abort_requested = True
    assert not sched.has_admissible_waiting()  # only entry is aborted
    sched.add(Sequence(prompt_ids=[2] * 4, params=sp))
    assert sched.has_admissible_waiting()


def test_has_admissible_waiting_counts_evictable_matched_pages():
    """A matched prefix page parked in the evictable LRU counts toward
    num_free, but admission would REVIVE it out of that pool — the
    predicate must not double-count it as both free and matched."""
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.runtime.kv_cache import PageAllocator
    from vgate_tpu.runtime.scheduler import Scheduler

    alloc = PageAllocator(8)  # pages 1..7 allocatable
    sched = Scheduler(
        allocator=alloc, max_slots=2, page_size=4,
        prefill_buckets=[8, 16], max_model_len=32, prefix_cache=True,
    )
    sp = SamplingParams(max_tokens=4, temperature=0.0)
    from vgate_tpu.runtime.sequence import Sequence

    seq = Sequence(prompt_ids=[3] * 12, params=sp)  # needs 3 pages
    # make its first full page resident-and-evictable: register a page
    # under the prompt's first chain hash, then release it to refcount 0
    chain = sched._prefix_chain(seq)
    [page] = alloc.allocate(1)
    alloc.register(page, chain[0])
    alloc.release([page])
    assert alloc.is_evictable(page)

    sched.add(seq)
    # pool state: 7 allocatable, 6 truly free + 1 evictable-matched.
    # needs 3 pages total, 1 matched -> allocate(2) vs 6 free: fine
    assert sched.has_admissible_waiting()

    # drain free pages so only the evictable matched page + 1 remain:
    # allocate(5) leaves num_free = 2 (1 free + 1 evictable-matched);
    # naive math says needed 2 <= 2, but admission revives the matched
    # page first, leaving just 1 allocatable for the 2-page remainder
    held = alloc.allocate(5)
    assert held is not None
    assert alloc.num_free == 2
    assert not sched.has_admissible_waiting()
