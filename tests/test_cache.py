"""LRU cache semantics (reference: tests/test_cache.py:45-148)."""

from vgate_tpu.cache import ResultCache


def test_key_stability():
    k1 = ResultCache.make_key("hello", 0.7, 0.95, 100)
    k2 = ResultCache.make_key("hello", 0.7, 0.95, 100)
    assert k1 == k2
    assert len(k1) == 16


def test_key_sensitivity():
    base = ResultCache.make_key("hello", 0.7, 0.95, 100)
    assert ResultCache.make_key("hello!", 0.7, 0.95, 100) != base
    assert ResultCache.make_key("hello", 0.8, 0.95, 100) != base
    assert ResultCache.make_key("hello", 0.7, 0.9, 100) != base
    assert ResultCache.make_key("hello", 0.7, 0.95, 101) != base
    assert ResultCache.make_key("hello", 0.7, 0.95, 100, top_k=5) != base


async def test_get_put_roundtrip():
    cache = ResultCache(max_size=4)
    assert await cache.get("k") is None
    await cache.put("k", {"text": "v"})
    assert (await cache.get("k"))["text"] == "v"


async def test_lru_eviction_order():
    cache = ResultCache(max_size=2)
    await cache.put("a", 1)
    await cache.put("b", 2)
    assert await cache.get("a") == 1  # touch a => b becomes LRU
    await cache.put("c", 3)
    assert await cache.get("b") is None
    assert await cache.get("a") == 1
    assert await cache.get("c") == 3


async def test_disabled_cache():
    cache = ResultCache(max_size=4, enabled=False)
    await cache.put("k", 1)
    assert await cache.get("k") is None
    assert cache.get_stats()["enabled"] is False


async def test_stats():
    cache = ResultCache(max_size=1)
    await cache.put("a", 1)
    await cache.get("a")
    await cache.get("missing")
    await cache.put("b", 2)  # evicts a
    stats = cache.get_stats()
    assert stats["hits"] == 1
    assert stats["misses"] == 1
    assert stats["evictions"] == 1
    assert stats["size"] == 1
    assert 0 < stats["hit_rate"] < 1


async def test_clear():
    cache = ResultCache(max_size=4)
    await cache.put("a", 1)
    await cache.clear()
    assert await cache.get("a") is None
