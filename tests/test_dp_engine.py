"""Data-parallel serving: replica engines + router (SURVEY.md section 2.2
row 1; VERDICT r1 missing-6: dp must do per-replica batch work, not
replicate compute)."""

import jax
import pytest

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.runtime.dp_engine import ReplicatedEngine


def dp_config(dp=2, recovery=None, **tpu_overrides):
    tpu = {
        "dp": dp,
        "tp": 1,
        "ep": 1,
        "sp": 1,
        "num_devices": dp,
        "kv_num_pages": 64,
        "kv_page_size": 4,
        "max_batch_slots": 4,
        "prefill_buckets": [8, 16, 32],
        "use_pallas": False,
    }
    tpu.update(tpu_overrides)
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu=tpu,
        scheduler={"max_queue_size": 16},
        recovery=recovery or {},
        logging={"level": "WARNING"},
    )


@pytest.fixture(scope="module")
def dp_engine():
    engine = ReplicatedEngine(dp_config(dp=2), devices=jax.devices()[:2])
    engine.start()
    yield engine
    engine.stop()


def greedy(max_tokens=6):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0)


def test_dp_engine_builds_disjoint_replicas(dp_engine):
    assert len(dp_engine.replicas) == 2
    d0 = set(d.id for d in dp_engine.replicas[0].mesh.devices.flat)
    d1 = set(d.id for d in dp_engine.replicas[1].mesh.devices.flat)
    assert d0.isdisjoint(d1)
    # each replica's mesh is dp=1: its batch is private per-shard work
    assert dp_engine.replicas[0].mesh.shape["dp"] == 1


def test_dp_routing_spreads_load(dp_engine):
    """Concurrent UNRELATED requests (distinct first pages, so prefix
    affinity doesn't bind them) land on BOTH replicas."""
    prompts = [f"{i}{i}{i}{i} dp probe {i}" for i in range(6)]
    results = dp_engine.generate(prompts, [greedy()] * 6)
    assert all(r["num_tokens"] >= 1 for r in results)
    per_replica = [
        core.get_stats()["prefills"] for core in dp_engine.replicas
    ]
    assert all(n > 0 for n in per_replica), per_replica


def test_dp_matches_single_engine_greedy(dp_engine):
    """Greedy output is replica-independent: identical weights (same init
    seed), identical decode — routing must not change results."""
    [a] = dp_engine.generate(["dp determinism"], [greedy()])
    [b] = dp_engine.generate(["dp determinism"], [greedy()])
    assert a["token_ids"] == b["token_ids"]
    # run enough to hit both replicas with the same prompt
    outs = dp_engine.generate(["dp determinism"] * 4, [greedy()] * 4)
    assert all(o["token_ids"] == a["token_ids"] for o in outs)


def test_dp_stats_aggregate(dp_engine):
    stats = dp_engine.get_stats()
    assert stats["dp"] == 2
    assert len(stats["replicas"]) == 2
    assert stats["prefills"] == sum(
        r["prefills"] for r in stats["replicas"]
    )
    assert stats["mesh"]["dp"] == 2
    # perf attribution aggregates across replicas (_MergedFlight-style)
    assert stats["perf"]["enabled"] is True
    assert stats["perf"]["ticks"] == sum(
        r["perf"]["ticks"] for r in stats["replicas"]
    )
    snap = dp_engine.perf_snapshot()
    assert snap["enabled"] is True
    assert [r["replica"] for r in snap["replicas"]] == [0, 1]
    assert snap["totals"]["tokens"] > 0
    assert snap["totals"]["compiles"]
    health = dp_engine.device_health()
    assert health["alive"] is True
    assert health["replicas"] == 2


def test_dp_backend_integration():
    """JaxTPUBackend transparently builds the replicated engine at dp>1."""
    from vgate_tpu.backends.jax_backend import JaxTPUBackend

    backend = JaxTPUBackend()
    backend.load_model(dp_config(dp=2))
    try:
        assert isinstance(backend.core, ReplicatedEngine)
        [r] = backend.generate(["backend dp"], [greedy(4)])
        assert r.num_tokens >= 1
    finally:
        backend.shutdown()


def test_dp_prefix_affinity_routing():
    """Requests sharing a prompt prefix stick to one replica (its private
    prefix cache gets the hits); unrelated prompts still spread."""
    engine = ReplicatedEngine(
        dp_config(dp=2, prefix_cache=True), devices=jax.devices()[:2]
    )
    engine.start()
    try:
        shared = list(range(10, 26))  # 16 tokens, >= 1 full page
        # sequential submission: same-wave requests can't share (hash
        # registration is deferred past dispatch), so hits require the
        # earlier request's prefill to have been dispatched
        for i in range(4):
            seq = engine.submit_tokens(shared + [100 + i], greedy(2))
            assert seq.done_event.wait(timeout=300)
        hits = [
            core.scheduler.total_prefix_hit_tokens
            for core in engine.replicas
        ]
        admitted = [
            core.scheduler.total_admitted for core in engine.replicas
        ]
        # all four landed on ONE replica and the later ones hit its cache
        assert sorted(admitted) == [0, 4]
        assert max(hits) > 0
    finally:
        engine.stop()


def test_dp_x_sp_replicas_shard_their_pools():
    """dp x sp composes: each replica's submesh carries sp=2, its KV
    pool shards over sp inside the replica, and greedy output is
    token-identical to a plain single engine."""
    if jax.device_count() < 4:
        pytest.skip("needs >=4 devices")
    prompt = [5 + (i % 13) for i in range(20)]

    single = ReplicatedEngine(
        dp_config(dp=1, sp=1, num_devices=1, prefill_buckets=[8, 32]),
        devices=jax.devices()[:1],
    )
    single.start()
    try:
        a = single.submit_tokens(prompt, greedy(6))
        assert a.done_event.wait(300)
        want = list(a.generated_ids)
    finally:
        single.stop()

    engine = ReplicatedEngine(
        dp_config(dp=2, sp=2, num_devices=4, prefill_buckets=[8, 32]),
        devices=jax.devices()[:4],
    )
    engine.start()
    try:
        for core in engine.replicas:
            assert dict(core.mesh.shape).get("sp") == 2
            assert "sp" in str(core.k_pages.sharding.spec)
        seqs = [
            engine.submit_tokens(prompt[:-1] + [90 + i], greedy(6))
            for i in range(4)
        ]
        for s in seqs:
            assert s.done_event.wait(300)
        b = engine.submit_tokens(prompt, greedy(6))
        assert b.done_event.wait(300)
        assert list(b.generated_ids) == want
    finally:
        engine.stop()


def long_greedy(n=40):
    # min_tokens pins the decode length: random-init tiny-dense hits
    # eos within a handful of tokens, and these tests need sequences
    # still mid-decode when the migration fires
    return SamplingParams(max_tokens=n, min_tokens=n, temperature=0.0)


def _wait_generated(seq, n, timeout=120.0):
    import time

    deadline = time.monotonic() + timeout
    while seq.num_generated < n and time.monotonic() < deadline:
        time.sleep(0.02)
    return seq.num_generated >= n


def test_dp_drain_live_migrates_then_elastic_remove_add():
    """ISSUE 8 acceptance: drain replica 0 mid-decode — its resident
    moves to replica 1 with ZERO client-visible failures and a
    token-identical completion; health reports DEGRADED with per-replica
    drain detail until undrain; then the elastic path removes the
    replica entirely (scale_down migration) and adds it back on the
    banked device slice."""
    from vgate_tpu.runtime.sequence import SeqStatus

    engine = ReplicatedEngine(dp_config(dp=2), devices=jax.devices()[:2])
    engine.start()
    try:
        seq = engine.replicas[0].submit_tokens(
            list(range(1, 9)), long_greedy()
        )
        assert _wait_generated(seq, 4)
        out = engine.drain_replica(0)
        assert out["migrated"] >= 1 and out["lost"] == 0, out
        assert seq.done_event.wait(timeout=300)
        assert seq.status is SeqStatus.FINISHED, seq.error
        assert seq.migrate_count == 1
        assert seq.resume_count == 0  # planned move spends no budget
        assert seq.resume_metrics() == {"migrated": 1.0}
        # it finished on the SURVIVOR
        assert engine.replicas[1].scheduler.total_finished >= 1

        health = engine.health()
        assert health["state"] == "degraded"
        assert health["draining"] == [0]
        assert health["replicas"][0]["state"] == "draining"
        assert health["migrated"] >= 1
        stats = engine.get_stats()
        assert stats["migration"]["migrated"] >= 1

        # token identity: an undisturbed run of the same prompt on the
        # survivor reproduces the migrated output exactly
        ref = engine.replicas[1].submit_tokens(
            list(range(1, 9)), long_greedy()
        )
        assert ref.done_event.wait(timeout=300)
        assert list(ref.generated_ids) == list(seq.generated_ids)

        # new placements route around the draining replica
        probe = engine.submit_prompt("drain probe", greedy(2))
        assert probe.done_event.wait(timeout=300)
        assert engine.replicas[0].scheduler.total_admitted == 1  # only seq

        # rejoin: undrain restores SERVING
        engine.undrain_replica(0)
        assert engine.health()["state"] == "serving"

        # elastic dp: remove replica 0 (drain + migrate + teardown,
        # slice banked), then grow back onto the banked slice
        mover = engine.replicas[0].submit_tokens(
            list(range(11, 19)), long_greedy()
        )
        assert _wait_generated(mover, 4)
        removed = engine.remove_replica(0)
        assert removed["dp"] == 1 and removed["migrated"] >= 1, removed
        assert mover.done_event.wait(timeout=300)
        assert mover.status is SeqStatus.FINISHED, mover.error
        assert mover.migrate_count == 1
        assert len(engine.replicas) == 1
        added = engine.add_replica()
        assert added["dp"] == 2
        assert engine.health()["state"] == "serving"
        tail = engine.submit_prompt("post scale-up", greedy(2))
        assert tail.done_event.wait(timeout=300)
        assert tail.status is SeqStatus.FINISHED, tail.error
    finally:
        engine.stop()


def test_dp_rebalance_moves_long_decode_off_pressured_replica():
    """The rebalance policy moves >= 1 resident off a pressured replica
    to an idle sibling with no client-visible error, and the cooldown
    stops it from immediately moving again (engine-level no-flap; the
    fake-clock hysteresis contract is pinned in test_migration.py).

    Poll-with-deadline (the PR-8 lifecycle deflake pattern): the victim
    decode races the move — under full-suite load the gap between
    "seq has >= min_generated tokens" and the evacuation landing can
    stretch past the sequence FINISHING (evacuate then finds no victim
    and the policy holds), which made this flake while passing in
    isolation.  Each attempt submits a fresh victim and a fresh no-hold
    policy, so one attempt's cooldown/hysteresis state cannot starve
    the next; per-attempt semantics are unchanged."""
    import time

    from vgate_tpu.runtime.dp_engine import RebalancePolicy
    from vgate_tpu.runtime.sequence import SeqStatus

    engine = ReplicatedEngine(dp_config(dp=2), devices=jax.devices()[:2])
    engine.start()
    try:
        # deterministic policy per attempt: no hold (hysteresis is
        # unit-pinned on a fake clock), long cooldown so at most ONE
        # move can fire within an attempt
        mig = load_config(
            migration={
                "rebalance_hold_s": 0.0,
                "rebalance_cooldown_s": 3600.0,
            }
        ).migration
        engine.replicas[0].pressure_signals = lambda: {
            "kv_free_ratio": 0.02, "engine_queue_depth": 0,
        }
        engine.replicas[1].pressure_signals = lambda: {
            "kv_free_ratio": 0.95, "engine_queue_depth": 0,
        }
        deadline = time.monotonic() + 120.0
        moved = seq = None
        while time.monotonic() < deadline:
            engine._policy = RebalancePolicy(mig)
            seq = engine.replicas[0].submit_tokens(
                list(range(21, 29)), long_greedy()
            )
            # older than migration.min_generated_tokens so it is movable
            assert _wait_generated(seq, 10)
            moved = engine.maybe_rebalance()
            if moved is not None and moved["moved"] >= 1:
                break
            # the victim finished under our feet (or the evacuation
            # raced its last chunk): let it settle, retry fresh
            moved = None
            assert seq.done_event.wait(timeout=300)
        assert moved is not None and moved["moved"] >= 1, (
            "no rebalance landed within the deadline"
        )
        assert moved["lost"] == 0
        # rate limit: the very next tick must hold (cooldown)
        assert engine.maybe_rebalance() is None
        assert seq.done_event.wait(timeout=300)
        assert seq.status is SeqStatus.FINISHED, seq.error
        assert seq.migrate_count == 1
        assert engine.replicas[1].scheduler.total_finished >= 1
        assert engine.total_migrated >= 1
    finally:
        engine.stop()


def test_dp_routes_around_dead_replica():
    """Engine-fatal on one replica (SURVEY 5.3 failure containment):
    new requests ride the surviving replica; health reports degraded
    but serving-capable; all-dead surfaces the fatal.  Repair is OFF
    here (recovery.enabled False) — this pins the pure routing
    contract; failover + rebuild live in tests/test_resume.py."""
    engine = ReplicatedEngine(
        dp_config(dp=2, recovery={"enabled": False}),
        devices=jax.devices()[:2],
    )
    engine.start()
    try:
        victim = engine.replicas[0]
        victim._fatal = RuntimeError("injected device loss")
        for i in range(4):
            seq = engine.submit_tokens(
                [20 + i, 7, 9, 11, 13], greedy(3)
            )
            assert seq.done_event.wait(timeout=300)
            assert seq.num_output_tokens == 3
        assert engine.replicas[1].scheduler.total_admitted >= 4
        health = engine.device_health()
        assert health["alive"] and health["replicas_alive"] == 1

        engine.replicas[1]._fatal = RuntimeError("second loss")
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="engine is dead"):
            engine.submit_tokens([1, 2, 3, 4], greedy(2))
        assert not engine.device_health()["alive"]
    finally:
        for core in engine.replicas:
            core._fatal = None  # let stop() run cleanly
        engine.stop()
