"""Parameterized live-load suite: concurrent requests through the running
gateway, asserting batching efficiency from the server's own metrics.

The pytest sibling of ``scripts/test_concurrent.py`` — the reference
ships both a script and a parameterized live-server suite
(/root/reference/tests/test_batching.py:63-130); this closes the pytest
half (VERDICT r2 missing-5).  Runs in-process against the dry-run engine
(tier: fast) and against the real jax engine on the tiny model.
"""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu.config import load_config
from vgate_tpu.server.app import create_app


async def _client(**overrides):
    overrides.setdefault("model", {"engine_type": "dry_run"})
    overrides.setdefault(
        "batch", {"max_batch_size": 4, "max_wait_time_ms": 20.0}
    )
    overrides.setdefault("logging", {"level": "WARNING"})
    config = load_config(**overrides)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    return client


async def _fire(client, i, max_tokens=8):
    resp = await client.post(
        "/v1/chat/completions",
        json={
            "messages": [{"role": "user", "content": f"load probe {i}"}],
            "max_tokens": max_tokens,
            "temperature": 0.0,
        },
    )
    body = await resp.json()
    return resp.status, body


@pytest.mark.parametrize("n_requests", [4, 10, 16])
async def test_concurrent_load_batches_efficiently(n_requests):
    """N concurrent unique requests: all succeed with their own budget,
    and the batcher aggregates them into fewer than N batches (the
    reference's batching-efficiency assertion, from live /stats instead
    of stdout parsing)."""
    client = await _client()
    try:
        before = (await (await client.get("/stats")).json())["batcher"]
        results = await asyncio.gather(
            *(_fire(client, i) for i in range(n_requests))
        )
        after = (await (await client.get("/stats")).json())["batcher"]
    finally:
        await client.close()
    for status, body in results:
        assert status == 200
        assert body["usage"]["completion_tokens"] == 8
    new_requests = after["total_requests"] - before["total_requests"]
    new_batches = after["total_batches"] - before["total_batches"]
    assert new_requests == n_requests
    assert 0 < new_batches < n_requests  # aggregation actually happened


async def test_concurrent_load_dedups_identical_requests():
    """Identical deterministic requests dedup into one generation (the
    reference's cache/dedup live check)."""
    client = await _client()
    try:
        results = await asyncio.gather(
            *(
                _fire(client, 0)  # same body every time
                for _ in range(6)
            )
        )
        stats = await (await client.get("/stats")).json()
    finally:
        await client.close()
    assert all(status == 200 for status, _ in results)
    texts = {body["choices"][0]["message"]["content"] for _, body in results}
    assert len(texts) == 1
    assert (
        stats["cache"]["hits"] + stats["batcher"]["total_deduplicated"] >= 1
    )


@pytest.mark.slow  # real-engine compiles; keep out of the fast tier
@pytest.mark.parametrize("n_requests", [6])
async def test_concurrent_load_real_engine(n_requests):
    """The same live-load shape through the REAL continuous-batching
    engine (tiny model, CPU): per-request budgets honored under
    concurrency, no slot/page leaks afterwards."""
    client = await _client(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 128, "kv_page_size": 4,
            "max_batch_slots": 4, "prefill_buckets": [16, 32],
            "use_pallas": False,
        },
        scheduler={"max_queue_size": 32},
    )
    try:
        async def fire_exact(i):
            # min_tokens pins the exact budget: random-init weights may
            # greedily emit a stop token early otherwise
            resp = await client.post(
                "/v1/chat/completions",
                json={
                    "messages": [
                        {"role": "user", "content": f"load probe {i}"}
                    ],
                    "max_tokens": 3 + i,
                    "min_tokens": 3 + i,
                    "temperature": 0.0,
                },
            )
            return resp.status, await resp.json()

        results = await asyncio.gather(
            *(fire_exact(i) for i in range(n_requests))
        )
        stats = await (await client.get("/stats")).json()
    finally:
        await client.close()
    for i, (status, body) in enumerate(results):
        assert status == 200
        assert body["usage"]["completion_tokens"] == 3 + i
    sched = stats["engine"]["scheduler"]
    assert sched["running"] == 0
    assert sched["used_pages"] == 0
