"""Pipeline parallelism: layer-stack sharding over pp with a GPipe relay
(parallel/pipeline.py; SURVEY.md section 2.2 row 3 — absent in the
reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vgate_tpu.config import load_config
from vgate_tpu.models.decoder import (
    decode_forward,
    init_params,
    prefill_forward,
)
from vgate_tpu.models.specs import TINY_DENSE
from vgate_tpu.parallel.mesh import build_mesh
from vgate_tpu.parallel.sharding import kv_pspec, named, shard_params


def pp_mesh(pp=2, tp=1):
    cfg = load_config(
        tpu={"dp": 1, "pp": pp, "ep": 1, "sp": 1, "tp": tp,
             "num_devices": pp * tp}
    ).tpu
    return build_mesh(cfg, devices=jax.devices()[: pp * tp])


def tiny_spec(pp):
    """TINY_DENSE, deepened when pp needs more layers than its 2."""
    if TINY_DENSE.num_layers % pp == 0:
        return TINY_DENSE
    import dataclasses

    return dataclasses.replace(
        TINY_DENSE, name=f"tiny-dense-{pp}l", num_layers=pp
    )


def setup(mesh, B=4, ps=4, pages_per_seq=4, spec=TINY_DENSE):
    params = shard_params(
        init_params(spec, jax.random.PRNGKey(0), jnp.float32), spec, mesh
    )
    num_pages = 1 + B * pages_per_seq
    shape = (spec.num_layers, spec.num_kv_heads, num_pages, ps,
             spec.head_dim)
    kv_sh = named(mesh, kv_pspec(spec, mesh))
    k = jax.device_put(jnp.zeros(shape, jnp.float32), kv_sh)
    v = jax.device_put(jnp.zeros(shape, jnp.float32), kv_sh)
    pt = jnp.asarray(
        np.arange(B * pages_per_seq, dtype=np.int32).reshape(B, -1) + 1
    )
    return spec, params, k, v, pt


def reference_single(spec, B, ps, pages_per_seq, fn):
    """Run the same computation on a single device for parity."""
    params = init_params(spec, jax.random.PRNGKey(0), jnp.float32)
    num_pages = 1 + B * pages_per_seq
    shape = (spec.num_layers, spec.num_kv_heads, num_pages, ps,
             spec.head_dim)
    k = jnp.zeros(shape, jnp.float32)
    v = jnp.zeros(shape, jnp.float32)
    pt = jnp.asarray(
        np.arange(B * pages_per_seq, dtype=np.int32).reshape(B, -1) + 1
    )
    return fn(params, k, v, pt)


@pytest.mark.parametrize("pp,tp", [(2, 1), (4, 1), (2, 2)])
def test_pp_prefill_then_decode_matches_single_device(pp, tp):
    """Prefill + one decode step through the pipeline must match the
    single-device forward bit-for-bit in logits ordering (same math,
    different schedule) within fp tolerance — including the KV the
    pipeline wrote."""
    if tp > 1 and not hasattr(jax, "shard_map"):
        pytest.skip(
            "pp x tp composition: shard_map manual over pp with tp "
            "left auto lowers axis_index to PartitionId, which this "
            "jax/XLA rejects as UNIMPLEMENTED for SPMD partitioning; "
            "pp-only runs (and toolchains shipping jax.shard_map) are "
            "covered"
        )
    mesh = pp_mesh(pp, tp)
    B, ps, pages_per_seq = 4, 4, 4
    S = 8
    spec, params, k, v, pt = setup(
        mesh, B, ps, pages_per_seq, spec=tiny_spec(pp)
    )
    tokens = jnp.asarray(
        (np.arange(B * S).reshape(B, S) * 7 + 3) % spec.vocab_size,
        jnp.int32,
    )
    seq_lens = jnp.asarray([S, S - 1, S - 3, 2], jnp.int32)

    def run(p, kk, vv, ptab):
        logits, kk, vv = prefill_forward(
            p, spec, tokens, seq_lens, kk, vv, ptab[:, : S // ps],
            mesh=mesh if p is params else None,
        )
        # decode one step from each sequence's current position
        next_tok = jnp.argmax(logits, -1).astype(jnp.int32)
        d_logits, kk, vv = decode_forward(
            p, spec, next_tok, seq_lens, kk, vv, ptab,
            active=jnp.ones((B,), bool),
            mesh=mesh if p is params else None,
        )
        return logits, d_logits

    got_p, got_d = run(params, k, v, pt)
    want_p, want_d = reference_single(
        spec, B, ps, pages_per_seq,
        lambda p, kk, vv, ptab: run(p, kk, vv, ptab),
    )
    np.testing.assert_allclose(
        np.asarray(got_p), np.asarray(want_p), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(got_d), np.asarray(want_d), rtol=2e-4, atol=2e-4
    )


def test_pp_gemma2_matches_single_device():
    """Gemma-2 through the relay (r4: per-layer windows + softcap/scale
    + embed scale threaded into the stage scan).  tiny-gemma2's 2 layers
    split one-per-stage at pp=2: stage 0 holds the SLIDING layer, stage
    1 the global one — exactly the per-stage window plumbing under
    test."""
    from vgate_tpu.models.specs import TINY_GEMMA2

    mesh = pp_mesh(2, 1)
    B, ps, pages_per_seq = 4, 4, 4
    S = 16  # crosses the 8-token sliding window
    spec, params, k, v, pt = setup(
        mesh, B, ps, pages_per_seq, spec=TINY_GEMMA2
    )
    tokens = jnp.asarray(
        (np.arange(B * S).reshape(B, S) * 7 + 3) % spec.vocab_size,
        jnp.int32,
    )
    seq_lens = jnp.asarray([S, S - 1, S - 5, 10], jnp.int32)

    def run(p, kk, vv, ptab):
        logits, kk, vv = prefill_forward(
            p, spec, tokens, seq_lens, kk, vv, ptab[:, : S // ps],
            mesh=mesh if p is params else None,
        )
        next_tok = jnp.argmax(logits, -1).astype(jnp.int32)
        d_logits, kk, vv = decode_forward(
            p, spec, next_tok, seq_lens, kk, vv, ptab,
            active=jnp.ones((B,), bool),
            mesh=mesh if p is params else None,
        )
        return logits, d_logits

    got_p, got_d = run(params, k, v, pt)
    want_p, want_d = reference_single(
        spec, B, ps, pages_per_seq,
        lambda p, kk, vv, ptab: run(p, kk, vv, ptab),
    )
    np.testing.assert_allclose(
        np.asarray(got_p), np.asarray(want_p), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(got_d), np.asarray(want_d), rtol=2e-4, atol=2e-4
    )


def test_pp_microbatch_fallback_indivisible_batch():
    """B=3 with pp=2 falls back to M=1 (single microbatch relay)."""
    mesh = pp_mesh(2, 1)
    B, ps, pages_per_seq, S = 3, 4, 4, 8
    spec, params, k, v, pt = setup(mesh, B, ps, pages_per_seq)
    tokens = jnp.asarray(np.full((B, S), 5), jnp.int32)
    logits, k, v = prefill_forward(
        params, spec, tokens, jnp.asarray([S] * B, jnp.int32),
        k, v, pt[:, : S // ps], mesh=mesh,
    )
    assert logits.shape == (B, spec.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_pp_engine_end_to_end():
    """The full engine serves through a pp=2 mesh: greedy output matches
    the pp=1 engine on the same prompts."""
    from vgate_tpu.backends.base import SamplingParams
    from vgate_tpu.runtime.engine_core import EngineCore

    def run_engine(pp, n_dev):
        config = load_config(
            model={
                "model_id": "tiny-dense",
                "engine_type": "jax_tpu",
                "dtype": "float32",
                "max_model_len": 64,
            },
            tpu={
                "dp": 1, "pp": pp, "tp": 1, "ep": 1, "sp": 1,
                "num_devices": n_dev,
                "kv_num_pages": 64, "kv_page_size": 4,
                "max_batch_slots": 4, "prefill_buckets": [8, 16],
                "use_pallas": False,
            },
            scheduler={"max_queue_size": 16},
            logging={"level": "WARNING"},
        )
        core = EngineCore(config, devices=jax.devices()[:n_dev])
        core.start()
        try:
            return core.generate(
                ["pipeline parity probe", "second prompt"],
                [SamplingParams(max_tokens=6, temperature=0.0)] * 2,
            )
        finally:
            core.stop()

    pp2 = run_engine(2, 2)
    pp1 = run_engine(1, 1)
    for a, b in zip(pp2, pp1):
        assert a["token_ids"] == b["token_ids"]
