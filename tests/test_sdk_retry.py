"""SDK stream-open retry (ISSUE 16 satellite: the streaming bugfix).

``_request`` always retried connection-level failures; ``_stream`` did
not — a gateway restart or a dying worker's connection reset at stream
OPEN surfaced as a raw httpx error even though re-running the request
was perfectly safe.  The fix retries refused/reset/garbage-answered
opens (and 429/5xx answers) with the existing equal-jitter backoff, and
NEVER retries once the first event has been yielded: a partial token
stream is non-idempotent, so mid-stream failures must propagate.
"""

import sys
from pathlib import Path

import httpx
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "vgate_tpu_client"))

from vgate_tpu_client import VGT, AsyncVGT  # noqa: E402
from vgate_tpu_client.exceptions import (  # noqa: E402
    ConnectionError as SDKConnectionError,
    DeadlineExceeded,
)

SSE = (
    b'data: {"chunk": 1}\n\n'
    b'data: {"chunk": 2}\n\n'
    b"data: [DONE]\n\n"
)


@pytest.fixture(autouse=True)
def _no_sleep(monkeypatch):
    monkeypatch.setattr(
        "vgate_tpu_client.client._retry_delay", lambda *a, **k: 0.0
    )
    monkeypatch.setattr("time.sleep", lambda s: None)


def make_client(handler, **kwargs) -> VGT:
    client = VGT(base_url="http://testserver", **kwargs)
    client._http = httpx.Client(
        base_url="http://testserver", transport=httpx.MockTransport(handler)
    )
    return client


def make_async_client(handler, **kwargs) -> AsyncVGT:
    client = AsyncVGT(base_url="http://testserver", **kwargs)
    client._http = httpx.AsyncClient(
        base_url="http://testserver", transport=httpx.MockTransport(handler)
    )
    return client


def sse_response():
    return httpx.Response(
        200, content=SSE, headers={"content-type": "text/event-stream"}
    )


def test_stream_open_connect_refused_retried():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            raise httpx.ConnectError("connection refused", request=request)
        return sse_response()

    client = make_client(handler)
    chunks = list(client._stream("/v1/chat/completions", {}))
    assert [c["chunk"] for c in chunks] == [1, 2]
    assert len(calls) == 2


def test_stream_open_reset_retried():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            raise httpx.ReadError("connection reset by peer", request=request)
        return sse_response()

    client = make_client(handler)
    assert len(list(client._stream("/v1/chat/completions", {}))) == 2
    assert len(calls) == 2


def test_stream_open_incomplete_read_retried():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            raise httpx.RemoteProtocolError(
                "peer closed connection without sending complete message",
                request=request,
            )
        return sse_response()

    client = make_client(handler)
    assert len(list(client._stream("/v1/chat/completions", {}))) == 2


def test_stream_open_503_retried_with_retry_after():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            return httpx.Response(
                503,
                json={"error": {"message": "draining"}},
                headers={"Retry-After": "0"},
            )
        return sse_response()

    client = make_client(handler)
    assert len(list(client._stream("/v1/chat/completions", {}))) == 2
    assert len(calls) == 2


def test_stream_open_504_not_retried():
    calls = []

    def handler(request):
        calls.append(1)
        return httpx.Response(
            504, json={"error": {"message": "deadline", "type": "deadline"}}
        )

    client = make_client(handler)
    with pytest.raises(DeadlineExceeded):
        list(client._stream("/v1/chat/completions", {}))
    assert len(calls) == 1  # the same request would blow the same budget


def test_stream_retries_exhausted_typed():
    calls = []

    def handler(request):
        calls.append(1)
        raise httpx.ConnectError("connection refused", request=request)

    client = make_client(handler, max_retries=2)
    with pytest.raises(SDKConnectionError):
        list(client._stream("/v1/chat/completions", {}))
    assert len(calls) == 3  # initial + 2 retries


def test_midstream_failure_never_retried():
    """The non-idempotency guard: once a token chunk has been yielded,
    a connection failure must propagate — a silent replay would hand
    the caller duplicated tokens."""
    calls = []

    def content():
        yield b'data: {"chunk": 1}\n\n'
        raise httpx.ReadError("connection reset mid-stream")

    def handler(request):
        calls.append(1)
        return httpx.Response(
            200,
            content=content(),
            headers={"content-type": "text/event-stream"},
        )

    client = make_client(handler)
    got = []
    with pytest.raises(SDKConnectionError):
        for chunk in client._stream("/v1/chat/completions", {}):
            got.append(chunk)
    assert got == [{"chunk": 1}]
    assert len(calls) == 1  # no second attempt


async def test_async_stream_open_retried():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            raise httpx.ConnectError("connection refused", request=request)
        return sse_response()

    client = make_async_client(handler)
    chunks = [c async for c in client._stream("/v1/chat/completions", {})]
    assert [c["chunk"] for c in chunks] == [1, 2]
    assert len(calls) == 2


async def test_async_midstream_failure_never_retried():
    calls = []

    async def content():
        yield b'data: {"chunk": 1}\n\n'
        raise httpx.ReadError("connection reset mid-stream")

    def handler(request):
        calls.append(1)
        return httpx.Response(
            200,
            content=content(),
            headers={"content-type": "text/event-stream"},
        )

    client = make_async_client(handler)
    got = []
    with pytest.raises(SDKConnectionError):
        async for chunk in client._stream("/v1/chat/completions", {}):
            got.append(chunk)
    assert got == [{"chunk": 1}]
    assert len(calls) == 1
