"""SDK stream-open retry (ISSUE 16 satellite: the streaming bugfix).

``_request`` always retried connection-level failures; ``_stream`` did
not — a gateway restart or a dying worker's connection reset at stream
OPEN surfaced as a raw httpx error even though re-running the request
was perfectly safe.  The fix retries refused/reset/garbage-answered
opens (and 429/5xx answers) with the existing equal-jitter backoff, and
NEVER retries once the first event has been yielded: a partial token
stream is non-idempotent, so mid-stream failures must propagate.
"""

import sys
from pathlib import Path

import httpx
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "vgate_tpu_client"))

from vgate_tpu_client import VGT, AsyncVGT  # noqa: E402
from vgate_tpu_client.exceptions import (  # noqa: E402
    ConnectionError as SDKConnectionError,
    DeadlineExceeded,
)

SSE = (
    b'data: {"chunk": 1}\n\n'
    b'data: {"chunk": 2}\n\n'
    b"data: [DONE]\n\n"
)


@pytest.fixture(autouse=True)
def _no_sleep(monkeypatch):
    monkeypatch.setattr(
        "vgate_tpu_client.client._retry_delay", lambda *a, **k: 0.0
    )
    monkeypatch.setattr("time.sleep", lambda s: None)


def make_client(handler, **kwargs) -> VGT:
    client = VGT(base_url="http://testserver", **kwargs)
    client._http = httpx.Client(
        base_url="http://testserver", transport=httpx.MockTransport(handler)
    )
    return client


def make_async_client(handler, **kwargs) -> AsyncVGT:
    client = AsyncVGT(base_url="http://testserver", **kwargs)
    client._http = httpx.AsyncClient(
        base_url="http://testserver", transport=httpx.MockTransport(handler)
    )
    return client


def sse_response():
    return httpx.Response(
        200, content=SSE, headers={"content-type": "text/event-stream"}
    )


def test_stream_open_connect_refused_retried():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            raise httpx.ConnectError("connection refused", request=request)
        return sse_response()

    client = make_client(handler)
    chunks = list(client._stream("/v1/chat/completions", {}))
    assert [c["chunk"] for c in chunks] == [1, 2]
    assert len(calls) == 2


def test_stream_open_reset_retried():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            raise httpx.ReadError("connection reset by peer", request=request)
        return sse_response()

    client = make_client(handler)
    assert len(list(client._stream("/v1/chat/completions", {}))) == 2
    assert len(calls) == 2


def test_stream_open_incomplete_read_retried():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            raise httpx.RemoteProtocolError(
                "peer closed connection without sending complete message",
                request=request,
            )
        return sse_response()

    client = make_client(handler)
    assert len(list(client._stream("/v1/chat/completions", {}))) == 2


def test_stream_open_503_retried_with_retry_after():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            return httpx.Response(
                503,
                json={"error": {"message": "draining"}},
                headers={"Retry-After": "0"},
            )
        return sse_response()

    client = make_client(handler)
    assert len(list(client._stream("/v1/chat/completions", {}))) == 2
    assert len(calls) == 2


def test_stream_open_504_not_retried():
    calls = []

    def handler(request):
        calls.append(1)
        return httpx.Response(
            504, json={"error": {"message": "deadline", "type": "deadline"}}
        )

    client = make_client(handler)
    with pytest.raises(DeadlineExceeded):
        list(client._stream("/v1/chat/completions", {}))
    assert len(calls) == 1  # the same request would blow the same budget


def test_stream_retries_exhausted_typed():
    calls = []

    def handler(request):
        calls.append(1)
        raise httpx.ConnectError("connection refused", request=request)

    client = make_client(handler, max_retries=2)
    with pytest.raises(SDKConnectionError):
        list(client._stream("/v1/chat/completions", {}))
    assert len(calls) == 3  # initial + 2 retries


def test_midstream_failure_never_retried():
    """The non-idempotency guard: once a token chunk has been yielded,
    a connection failure must propagate — a silent replay would hand
    the caller duplicated tokens."""
    calls = []

    def content():
        yield b'data: {"chunk": 1}\n\n'
        raise httpx.ReadError("connection reset mid-stream")

    def handler(request):
        calls.append(1)
        return httpx.Response(
            200,
            content=content(),
            headers={"content-type": "text/event-stream"},
        )

    client = make_client(handler)
    got = []
    with pytest.raises(SDKConnectionError):
        for chunk in client._stream("/v1/chat/completions", {}):
            got.append(chunk)
    assert got == [{"chunk": 1}]
    assert len(calls) == 1  # no second attempt


async def test_async_stream_open_retried():
    calls = []

    def handler(request):
        calls.append(1)
        if len(calls) == 1:
            raise httpx.ConnectError("connection refused", request=request)
        return sse_response()

    client = make_async_client(handler)
    chunks = [c async for c in client._stream("/v1/chat/completions", {})]
    assert [c["chunk"] for c in chunks] == [1, 2]
    assert len(calls) == 2


async def test_async_midstream_failure_never_retried():
    calls = []

    async def content():
        yield b'data: {"chunk": 1}\n\n'
        raise httpx.ReadError("connection reset mid-stream")

    def handler(request):
        calls.append(1)
        return httpx.Response(
            200,
            content=content(),
            headers={"content-type": "text/event-stream"},
        )

    client = make_async_client(handler)
    got = []
    with pytest.raises(SDKConnectionError):
        async for chunk in client._stream("/v1/chat/completions", {}):
            got.append(chunk)
    assert got == [{"chunk": 1}]
    assert len(calls) == 1


# --------------------------------------------------------- idempotency keys
# (ISSUE 20 satellite: the SDK half of gateway crash survivability.)
# Non-streaming generation POSTs auto-mint an Idempotency-Key; a
# connection-failure retry resends the SAME key (the server may have
# journaled the request before the socket died, so the retry replays
# instead of recomputing); a status-code retry (429/5xx) mints a NEW
# key (the server answered — the old key settled as failed).

CHAT_BODY = {
    "id": "cmpl-1",
    "object": "chat.completion",
    "choices": [
        {
            "index": 0,
            "message": {"role": "assistant", "content": "hi"},
            "finish_reason": "stop",
        }
    ],
}


def test_idempotency_key_minted_on_chat():
    keys = []

    def handler(request):
        keys.append(request.headers.get("Idempotency-Key"))
        return httpx.Response(200, json=CHAT_BODY)

    client = make_client(handler)
    client.chat.create([{"role": "user", "content": "x"}])
    assert len(keys) == 1 and keys[0]
    assert client.last_idempotency_key == keys[0]


def test_connection_failure_retry_reuses_key():
    keys = []

    def handler(request):
        keys.append(request.headers.get("Idempotency-Key"))
        if len(keys) == 1:
            raise httpx.ConnectError("connection refused", request=request)
        return httpx.Response(200, json=CHAT_BODY)

    client = make_client(handler)
    client.chat.create([{"role": "user", "content": "x"}])
    assert len(keys) == 2
    assert keys[0] and keys[0] == keys[1]  # SAME key across the retry


def test_status_retry_mints_new_key():
    keys = []

    def handler(request):
        keys.append(request.headers.get("Idempotency-Key"))
        if len(keys) == 1:
            return httpx.Response(503, json={"error": {"message": "shed"}})
        return httpx.Response(200, json=CHAT_BODY)

    client = make_client(handler)
    client.chat.create([{"role": "user", "content": "x"}])
    assert len(keys) == 2
    assert keys[0] and keys[1] and keys[0] != keys[1]  # fresh key


def test_new_request_mints_new_key():
    keys = []

    def handler(request):
        keys.append(request.headers.get("Idempotency-Key"))
        return httpx.Response(200, json=CHAT_BODY)

    client = make_client(handler)
    client.chat.create([{"role": "user", "content": "x"}])
    client.chat.create([{"role": "user", "content": "x"}])
    assert len(keys) == 2
    assert keys[0] != keys[1]  # one key per LOGICAL request, not per client


def test_replayed_flag_surfaces():
    def handler(request):
        return httpx.Response(200, json={**CHAT_BODY, "replayed": True})

    client = make_client(handler)
    completion = client.chat.create([{"role": "user", "content": "x"}])
    assert completion.replayed is True


def test_stream_sends_no_idempotency_key():
    keys = []

    def handler(request):
        keys.append(request.headers.get("Idempotency-Key"))
        return sse_response()

    client = make_client(handler)
    list(client._stream("/v1/chat/completions", {}))
    assert keys == [None]  # partial streams are not replayable


async def test_async_connection_failure_retry_reuses_key():
    keys = []

    def handler(request):
        keys.append(request.headers.get("Idempotency-Key"))
        if len(keys) == 1:
            raise httpx.ConnectError("connection refused", request=request)
        return httpx.Response(200, json=CHAT_BODY)

    client = make_async_client(handler)
    await client.chat.create([{"role": "user", "content": "x"}])
    assert len(keys) == 2 and keys[0] == keys[1]
