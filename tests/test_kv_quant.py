"""int8 paged-KV quantization (ops/kv_quant.py): quantize-on-write /
dequant-on-read numerics, the 2x capacity accounting, Pallas-kernel
parity for the dequant read path, and the engine-level quality bounds
(greedy token identity + logprob drift vs the full-precision oracle on
the CPU test model)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.ops.kv_quant import (
    SCALE_BYTES,
    QuantPages,
    copy_page_prefix,
    dequantize,
    gather_pages,
    is_quantized,
    kv_write,
    quantize,
)
from vgate_tpu.runtime.kv_cache import (
    KVGeometry,
    auto_num_pages,
    make_kv_buffers,
)


# ------------------------------------------------------------- numerics


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(5, 7, 64)) * 3.0, jnp.float32)
    q, s = quantize(x)
    assert q.dtype == jnp.int8
    back = dequantize(q, s)
    # symmetric int8 step is absmax/127 (~0.8% of absmax peak-to-peak);
    # the bf16-stored scale adds its ~0.4% relative rounding on top
    absmax = np.abs(np.asarray(x)).max(axis=-1, keepdims=True)
    err = np.abs(np.asarray(back) - np.asarray(x))
    assert (err <= absmax * (0.5 / 127.0 + 0.005) + 1e-6).all()


def test_quantize_zero_rows_stay_exactly_zero():
    x = jnp.zeros((3, 4, 16), jnp.float32)
    q, s = quantize(x)
    assert np.asarray(q).max() == 0
    assert (np.asarray(s.astype(jnp.float32)) == 1.0).all()
    assert (np.asarray(dequantize(q, s)) == 0.0).all()


def test_kv_write_plain_pool_unchanged():
    pool = jnp.zeros((2, 4, 4, 8), jnp.float32)
    val = jnp.ones((2, 2, 8), jnp.float32)
    out = kv_write(pool, (slice(None), jnp.asarray([1, 2]),
                          jnp.asarray([0, 3])), val)
    assert not is_quantized(out)
    assert np.asarray(out[0, 1, 0]).sum() == 8


def test_kv_write_quant_pool_roundtrips_through_gather():
    rng = np.random.default_rng(1)
    KV, P, ps, hd = 2, 9, 4, 16
    pool = QuantPages(
        jnp.zeros((KV, P, ps, hd), jnp.int8),
        jnp.ones((KV, P, ps), jnp.bfloat16),
    )
    vals = jnp.asarray(rng.normal(size=(KV, P, ps, hd)), jnp.float32)
    pool = kv_write(pool, (slice(None), jnp.arange(P)), vals)
    deq = gather_pages(pool, jnp.arange(P)[None])  # [KV, 1, P, ps, hd]
    absmax = np.abs(np.asarray(vals)).max(axis=-1, keepdims=True)
    err = np.abs(np.asarray(deq[:, 0]) - np.asarray(vals))
    assert (err <= absmax * 0.01 + 1e-6).all()


def test_cow_copy_preserves_scales_with_data():
    """The radix COW copy must carry the per-slot SCALES with the int8
    data: a copied head whose scale came from the destination page
    would dequantize differently for the diverged reader than for the
    sharers of the source page."""
    rng = np.random.default_rng(2)
    KV, P, ps, hd = 2, 6, 4, 8
    vals = jnp.asarray(rng.normal(size=(KV, P, ps, hd)) * 5.0, jnp.float32)
    pool = kv_write(
        QuantPages(
            jnp.zeros((KV, P, ps, hd), jnp.int8),
            jnp.ones((KV, P, ps), jnp.bfloat16),
        ),
        (slice(None), jnp.arange(P)),
        vals,
    )
    keep = jnp.arange(ps) < 3
    out = copy_page_prefix(pool, 2, 4, keep)
    # head: bit-identical data AND scale from the source page
    assert np.array_equal(np.asarray(out.data[:, 4, :3]),
                          np.asarray(pool.data[:, 2, :3]))
    assert np.array_equal(
        np.asarray(out.scale[:, 4, :3].astype(jnp.float32)),
        np.asarray(pool.scale[:, 2, :3].astype(jnp.float32)),
    )
    # tail: untouched
    assert np.array_equal(np.asarray(out.data[:, 4, 3:]),
                          np.asarray(pool.data[:, 4, 3:]))
    assert np.array_equal(
        np.asarray(out.scale[:, 4, 3:].astype(jnp.float32)),
        np.asarray(pool.scale[:, 4, 3:].astype(jnp.float32)),
    )


# ------------------------------------------------------------- capacity


def test_auto_num_pages_int8_yields_at_least_1p9x():
    """The acceptance floor: for the same HBM budget, int8 KV must
    yield >= 1.9x the bf16 page count (1.94x at head_dim 64, 1.97x at
    128 — the bf16 scale keeps the overhead at 2/head_dim)."""
    from types import SimpleNamespace

    from vgate_tpu.models.specs import spec_for_model_id

    dev = SimpleNamespace(platform="tpu")  # no memory_stats -> budget path
    for model_id in (
        "Qwen/Qwen2.5-1.5B-Instruct",
        "Qwen/Qwen2.5-7B-Instruct",
    ):
        spec = spec_for_model_id(model_id)
        common = dict(
            page_size=32, hbm_utilization=0.9, device=dev,
            params_bytes=0, hbm_bytes=16 * 1024 ** 3, hard_cap=10 ** 9,
        )
        bf16 = auto_num_pages(spec, dtype_bytes=2, **common)
        int8 = auto_num_pages(
            spec, dtype_bytes=1, scale_bytes=SCALE_BYTES, **common
        )
        assert int8 / bf16 >= 1.9, (model_id, int8, bf16)


def test_geometry_page_bytes_accounts_for_scales():
    base = dict(num_layers=4, num_pages=8, page_size=16, kv_heads=2,
                head_dim=64, max_model_len=64)
    bf16 = KVGeometry(dtype_bytes=2, **base)
    int8 = KVGeometry(dtype_bytes=1, scale_bytes=2, kv_dtype="int8", **base)
    assert bf16.page_bytes == 2 * 4 * 16 * 2 * 64 * 2
    assert int8.page_bytes == 2 * 4 * 16 * 2 * (64 + 2)
    assert bf16.page_bytes / int8.page_bytes >= 1.9


def test_make_kv_buffers_int8_pool_structure():
    geo = KVGeometry(
        num_layers=2, num_pages=6, page_size=4, kv_heads=2, head_dim=8,
        max_model_len=16, dtype_bytes=1, scale_bytes=2, kv_dtype="int8",
    )
    k, v = make_kv_buffers(geo, jnp.int8)
    assert is_quantized(k) and is_quantized(v)
    assert k.data.shape == (2, 2, 6, 4, 8) and k.data.dtype == jnp.int8
    assert k.scale.shape == (2, 2, 6, 4)
    # zeroed pool dequantizes to exactly 0 (trash-page reads)
    assert np.asarray(
        gather_pages(k, jnp.arange(6)[None])
    ).max() == 0.0


# --------------------------------------------- Pallas dequant read path


def _quant_case(B=4, H=8, KV=2, hd=128, ps=16, n=16, seed=3):
    rng = np.random.default_rng(seed)
    P = 1 + B * n

    def pool(s, scale):
        vals = jnp.asarray(
            np.random.default_rng(s).normal(size=(KV, P, ps, hd)) * scale,
            jnp.float32,
        )
        return kv_write(
            QuantPages(
                jnp.zeros((KV, P, ps, hd), jnp.int8),
                jnp.ones((KV, P, ps), jnp.bfloat16),
            ),
            (slice(None), jnp.arange(P)),
            vals,
        )

    q = jnp.asarray(rng.normal(size=(B, H, hd)), jnp.float32)
    pt = jnp.asarray(
        rng.permutation(np.arange(1, P))[: B * n].reshape(B, n), jnp.int32
    )
    return q, pool(seed + 10, 1.0), pool(seed + 11, 0.7), pt


def test_paged_decode_kernel_dequant_matches_jnp_twin():
    from vgate_tpu.ops.attention import paged_decode_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
    )

    q, kq, vq, pt = _quant_case()
    seq_lens = jnp.asarray([1, 16, 17, 200], jnp.int32)
    expect = paged_decode_attention(q, kq, vq, pt, seq_lens)
    got = paged_decode_attention_pallas(
        q, kq, vq, pt, seq_lens, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_paged_decode_kernel_dequant_layer_indexed():
    """Carry-threaded pools: the scale DMA must compose the layer index
    exactly like the data DMA."""
    from vgate_tpu.ops.attention import paged_decode_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
    )

    q, kq, vq, pt = _quant_case(B=2, n=8)
    seq_lens = jnp.asarray([5, 100], jnp.int32)
    L = 3
    kqL = QuantPages(
        jnp.tile(kq.data[None], (L, 1, 1, 1, 1)),
        jnp.tile(kq.scale[None], (L, 1, 1, 1)),
    )
    vqL = QuantPages(
        jnp.tile(vq.data[None], (L, 1, 1, 1, 1)),
        jnp.tile(vq.scale[None], (L, 1, 1, 1)),
    )
    expect = paged_decode_attention(q, kq, vq, pt, seq_lens)
    got = paged_decode_attention_pallas(
        q, kqL, vqL, pt, seq_lens, layer=jnp.asarray(1), interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5
    )


def test_multitok_kernel_dequant_matches_jnp_twin():
    from vgate_tpu.ops.attention import paged_suffix_attention
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_multitok_attention_pallas,
    )

    rng = np.random.default_rng(4)
    _, kq, vq, pt = _quant_case(seed=4)
    B, S, H, hd = 4, 4, 8, 128
    qs = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    pos0 = jnp.asarray([0, 5, 30, 100], jnp.int32)
    lens = jnp.asarray([1, 3, 4, 2], jnp.int32)
    expect = paged_suffix_attention(qs, kq, vq, pt, pos0, pos0 + lens)
    got = paged_multitok_attention_pallas(
        qs, kq, vq, pt, pos0, lens, interpret=True
    )
    em, gm = np.asarray(expect), np.asarray(got)
    for b in range(B):  # rows past input_lens are unspecified
        np.testing.assert_allclose(
            gm[b, : int(lens[b])], em[b, : int(lens[b])],
            rtol=2e-5, atol=2e-5,
        )


def test_blocked_kernel_falls_back_for_quant_pools():
    from vgate_tpu.ops.pallas.paged_attention import (
        paged_decode_attention_pallas,
        paged_decode_attention_pallas_blocked,
    )

    q, kq, vq, pt = _quant_case(seed=5)
    seq_lens = jnp.asarray([3, 40, 64, 128], jnp.int32)
    per_slot = paged_decode_attention_pallas(
        q, kq, vq, pt, seq_lens, interpret=True
    )
    blocked = paged_decode_attention_pallas_blocked(
        q, kq, vq, pt, seq_lens, interpret=True, block_slots=2
    )
    np.testing.assert_allclose(
        np.asarray(blocked), np.asarray(per_slot), rtol=1e-6, atol=1e-6
    )


# ------------------------------------------------- engine-level quality


def _engine_cfg(kv_dtype, **tpu_overrides):
    tpu = {
        "dp": 1, "tp": 1, "ep": 1, "sp": 1,
        "kv_num_pages": 256, "kv_page_size": 4, "max_batch_slots": 4,
        "prefill_buckets": [8, 16, 32], "use_pallas": False,
    }
    tpu.update(tpu_overrides)
    return load_config(
        model={
            "model_id": "tiny-dense", "engine_type": "jax_tpu",
            "dtype": "float32", "max_model_len": 128,
        },
        kv_cache={"dtype": kv_dtype},
        tpu=tpu,
        scheduler={"max_queue_size": 16},
        logging={"level": "WARNING"},
    )


@pytest.fixture(scope="module")
def quant_vs_oracle():
    """One greedy 80-token generation with logprobs on the full-precision
    pool and on int8 KV, same prompt, shared across the quality tests."""
    from vgate_tpu.runtime.engine_core import EngineCore

    results = {}
    prompt = "the quick brown fox jumps over the lazy dog"
    for mode in ("auto", "int8"):
        core = EngineCore(_engine_cfg(mode), devices=jax.devices()[:1])
        core.start()
        try:
            [r] = core.generate(
                [prompt],
                [SamplingParams(
                    max_tokens=80, temperature=0.0, logprobs=True,
                    top_logprobs=1,
                )],
            )
            results[mode] = (r, core.geometry.kv_dtype)
        finally:
            core.stop()
    return results


def test_int8_engine_reports_dtype(quant_vs_oracle):
    assert quant_vs_oracle["auto"][1] == "f32"
    assert quant_vs_oracle["int8"][1] == "int8"


def test_int8_greedy_token_identity_64_steps(quant_vs_oracle):
    """The acceptance criterion: greedy decode under int8 KV stays
    token-identical to the full-precision oracle for >= 64 steps on
    the CPU test model."""
    oracle = quant_vs_oracle["auto"][0]["token_ids"]
    quant = quant_vs_oracle["int8"][0]["token_ids"]
    horizon = next(
        (i for i, (a, b) in enumerate(zip(oracle, quant)) if a != b),
        min(len(oracle), len(quant)),
    )
    assert horizon >= 64, f"diverged at step {horizon}"


def test_int8_logprob_drift_bounded(quant_vs_oracle):
    """Max drift of the chosen token's logprob over the identical
    prefix: int8 KV perturbs attention outputs by ~0.5% of absmax per
    read; on the tiny model that must stay a small logit effect."""
    oracle = quant_vs_oracle["auto"][0]
    quant = quant_vs_oracle["int8"][0]
    n = 0
    for a, b in zip(oracle["token_ids"], quant["token_ids"]):
        if a != b:
            break
        n += 1
    drift = max(
        abs(a["logprob"] - b["logprob"])
        for a, b in zip(oracle["logprobs"][:n], quant["logprobs"][:n])
    )
    assert drift < 0.25, f"max logprob drift {drift}"


def test_int8_requires_plain_mesh():
    from vgate_tpu.runtime.engine_core import EngineCore

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 cpu devices (conftest sets host platform count)")
    with pytest.raises(ValueError, match="plain mesh"):
        EngineCore(
            _engine_cfg("int8", tp=2, num_devices=2),
            devices=jax.devices()[:2],
        )


def test_checkpoint_kv_dtype_mismatch_refused():
    """A checkpointed sequence stamped with another pool format must be
    refused by submit_existing — failing cleanly (typed 503 via
    replay_into) instead of splicing numerics mid-generation."""
    from vgate_tpu.runtime.engine_core import EngineCore
    from vgate_tpu.runtime.sequence import Sequence

    core = EngineCore(_engine_cfg("int8"), devices=jax.devices()[:1])
    try:
        seq = Sequence(
            prompt_ids=[5, 6, 7],
            params=SamplingParams(max_tokens=4, temperature=0.0),
        )
        seq.kv_dtype = "f32"
        with pytest.raises(ValueError, match="kv dtype"):
            core.submit_existing(seq)
        # matching stamp rides through the gate
        seq2 = Sequence(
            prompt_ids=[5, 6, 7],
            params=SamplingParams(max_tokens=4, temperature=0.0),
        )
        seq2.kv_dtype = "int8"
        core.submit_existing(seq2)  # no engine thread: just enqueued
    finally:
        core.stop()


def test_checkpoint_records_kv_dtype():
    from vgate_tpu.runtime.sequence import Sequence

    seq = Sequence(
        prompt_ids=[1, 2, 3],
        params=SamplingParams(max_tokens=4),
    )
    seq.kv_dtype = "int8"
    cp = seq.checkpoint()
    assert cp.kv_dtype == "int8"
    assert cp.as_dict()["kv_dtype"] == "int8"
    assert seq.checkpoint_summary() == cp.as_dict()
    restored = Sequence.from_checkpoint(cp)
    assert restored.kv_dtype == "int8"


# --------------------------------------------- admission capacity stack


def test_admission_auto_token_budget_scales_with_capacity():
    from vgate_tpu.admission import AdmissionController

    class Cfg:
        enabled = True
        max_queued_tokens = 1000
        auto_token_budget = 2.0
        max_queued_requests = 0
        reject_would_miss_slo = False
        kv_free_watermark = 0.0
        per_key_max_inflight = 0
        key_tiers = {}
        default_tier = "standard"
        tier_fractions = {"standard": 1.0}
        throughput_alpha = 0.3
        throughput_init_tps = 400.0
        prefix_discount = 0.0

    capacity = {"kv_token_capacity": 4000}
    ctl = AdmissionController(Cfg(), signals=lambda: capacity)
    # effective limit = max(1000, 2.0 * 4000) = 8000: a cost the static
    # limit would shed now admits
    ctl.admit(6000)
    stats = ctl.get_stats()
    assert stats["effective_max_queued_tokens"] == 8000
    assert stats["kv_token_capacity"] == 4000
    # int8 halves page bytes -> capacity (and with it the budget) ~2x
    capacity["kv_token_capacity"] = 2000
    from vgate_tpu.errors import ServerOverloadedError

    with pytest.raises(ServerOverloadedError):
        ctl.admit(6000)

    # max_queued_tokens = 0 means UNLIMITED (config.yaml) — the auto
    # budget must never convert the sentinel into a finite cap
    Cfg.max_queued_tokens = 0
    unlimited = AdmissionController(Cfg(), signals=lambda: capacity)
    unlimited.admit(10 * capacity["kv_token_capacity"])
    assert unlimited.get_stats()["effective_max_queued_tokens"] == 0
