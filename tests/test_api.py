"""In-process gateway integration tests with the dry-run engine
(reference tier 3: httpx.ASGITransport tests at tests/test_benchmark.py:98-131;
here via aiohttp's TestClient since the gateway is aiohttp-native)."""

import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu.config import load_config
from vgate_tpu.server.app import create_app


async def _client(**overrides):
    overrides.setdefault("model", {"engine_type": "dry_run"})
    overrides.setdefault(
        "batch", {"max_batch_size": 4, "max_wait_time_ms": 5.0}
    )
    overrides.setdefault("logging", {"level": "WARNING"})
    config = load_config(**overrides)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    return client


async def test_health():
    client = await _client()
    try:
        resp = await client.get("/health")
        assert resp.status == 200
        body = await resp.json()
        assert body["status"] == "ok"
        assert body["engine_type"] == "DryRunBackend"
    finally:
        await client.close()


async def test_chat_completion_roundtrip():
    client = await _client()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [
                    {"role": "system", "content": "You are helpful."},
                    {"role": "user", "content": "Say hi"},
                ],
                "max_tokens": 16,
            },
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["object"] == "chat.completion"
        content = body["choices"][0]["message"]["content"]
        # dry-run echoes the flattened prompt (System:/User:/Assistant:)
        assert "[dry-run] echo:" in content
        assert "System: You are helpful." in content
        assert body["usage"]["completion_tokens"] == 8
        assert "X-Request-ID" in resp.headers
    finally:
        await client.close()


async def test_chat_completion_validation_error():
    client = await _client()
    try:
        resp = await client.post("/v1/chat/completions", json={"messages": []})
        assert resp.status == 422
        resp = await client.post(
            "/v1/chat/completions", json={"wrong": "shape"}
        )
        assert resp.status == 422
    finally:
        await client.close()


async def test_chat_completion_caching_visible():
    client = await _client()
    try:
        req = {
            "messages": [{"role": "user", "content": "cache me"}],
            "temperature": 0.5,
        }
        first = await (await client.post("/v1/chat/completions", json=req)).json()
        second = await (await client.post("/v1/chat/completions", json=req)).json()
        assert first["cached"] is False
        assert second["cached"] is True
    finally:
        await client.close()


async def test_embeddings_endpoint():
    client = await _client()
    try:
        resp = await client.post(
            "/v1/embeddings", json={"input": ["one", "two"]}
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["object"] == "list"
        assert len(body["data"]) == 2
        assert len(body["data"][0]["embedding"]) == 768
        assert body["usage"]["prompt_tokens"] >= 2
    finally:
        await client.close()


async def test_embeddings_single_string():
    client = await _client()
    try:
        resp = await client.post("/v1/embeddings", json={"input": "solo"})
        body = await resp.json()
        assert len(body["data"]) == 1
    finally:
        await client.close()


async def test_models_endpoint():
    client = await _client()
    try:
        body = await (await client.get("/v1/models")).json()
        ids = [m["id"] for m in body["data"]]
        assert any("Qwen" in i for i in ids)
    finally:
        await client.close()


async def test_metrics_endpoint():
    client = await _client()
    try:
        await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "m"}]},
        )
        resp = await client.get("/metrics")
        assert resp.status == 200
        text = await resp.text()
        assert "vgt_requests" in text
    finally:
        await client.close()


async def test_stats_endpoint():
    client = await _client()
    try:
        await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "s"}]},
        )
        body = await (await client.get("/stats")).json()
        assert body["batcher"]["total_requests"] >= 1
        assert "cache" in body and "config" in body
        assert body["config"]["engine_type"] == "dry_run"
    finally:
        await client.close()


async def test_benchmark_endpoint():
    client = await _client()
    try:
        resp = await client.post(
            "/v1/benchmark",
            json={"prompts": ["bench one", "bench two"], "rounds": 2,
                  "max_tokens": 8},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["rounds"] == 2
        assert body["latency_ms"]["p50"] > 0
        assert body["tokens_per_second"] > 0
    finally:
        await client.close()


async def test_streaming_chat():
    client = await _client()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "stream me"}],
                "stream": True,
            },
        )
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        raw = await resp.text()
        assert "data: [DONE]" in raw
        assert "chat.completion.chunk" in raw
    finally:
        await client.close()


async def test_secured_gateway_end_to_end():
    client = await _client(
        security={"enabled": True, "api_keys": ["sk-test"]},
        rate_limit={"enabled": True, "requests_per_minute": 100},
    )
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}]},
        )
        assert resp.status == 401
        resp = await client.post(
            "/v1/chat/completions",
            json={"messages": [{"role": "user", "content": "x"}]},
            headers={"Authorization": "Bearer sk-test"},
        )
        assert resp.status == 200
        # /health stays exempt
        assert (await client.get("/health")).status == 200
    finally:
        await client.close()


async def test_chat_completion_accepts_stop_and_seed():
    """`stop` (bare string or list) and `seed` are part of the request
    schema and of the cache identity — a seeded request must not hit the
    cache entry of an unseeded one."""
    client = await _client()
    try:
        base = {
            "messages": [{"role": "user", "content": "stop/seed probe"}],
            "max_tokens": 8,
        }
        r1 = await client.post("/v1/chat/completions", json=base)
        assert r1.status == 200
        for extra in (
            {"stop": "\n"},
            {"stop": ["\n", "User:"]},
            {"seed": 42},
        ):
            resp = await client.post(
                "/v1/chat/completions", json={**base, **extra}
            )
            assert resp.status == 200
            body = await resp.json()
            # different sampling identity => no cache hit from `base`
            assert body["cached"] is False
        # identical seeded request does hit the cache
        resp = await client.post(
            "/v1/chat/completions", json={**base, "seed": 42}
        )
        assert (await resp.json())["cached"] is True
    finally:
        await client.close()


async def test_request_timeout_returns_504():
    """server.request_timeout_s bounds non-streaming request latency: a
    request still queued past the deadline gets 504, not an open-ended
    wait (VERDICT r1: request_timeout_s was a dead knob)."""
    client = await _client(
        server={"request_timeout_s": 0.05},
        # batch window far beyond the timeout => submit can't complete
        batch={"max_batch_size": 64, "max_wait_time_ms": 60_000.0},
    )
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "too slow"}],
                "max_tokens": 4,
            },
        )
        assert resp.status == 504
        body = await resp.json()
        assert body["error"]["type"] == "timeout_error"
    finally:
        await client.close()


async def test_max_completion_tokens_alias():
    """The current OpenAI name wins over the legacy max_tokens."""
    client = await _client()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "alias"}],
                "max_tokens": 99,
                "max_completion_tokens": 4,
            },
        )
        assert resp.status == 200
        # dry-run backend always emits 8 fake tokens; what we assert is
        # that the alias parses and the request round-trips
        body = await resp.json()
        assert body["choices"][0]["message"]["content"]
    finally:
        await client.close()


async def test_chat_logit_bias_accepted_and_validated():
    """logit_bias rides the OpenAI schema (stringified token-id keys);
    non-numeric keys 422 instead of 500."""
    client = await _client()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4,
                "logit_bias": {"42": 50.0, "7": -100.0},
            },
        )
        assert resp.status == 200

        bad = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "hi"}],
                "logit_bias": {"not-a-token": 1.0},
            },
        )
        assert bad.status == 422
        body = await bad.json()
        assert "logit_bias" in body["error"]["message"]
    finally:
        await client.close()


async def test_stream_options_include_usage():
    """stream_options.include_usage adds a final pre-[DONE] chunk with
    an empty choices list and the request's token usage."""
    client = await _client()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "usage probe"}],
                "max_tokens": 6,
                "stream": True,
                "stream_options": {"include_usage": True},
            },
        )
        assert resp.status == 200
        body = (await resp.read()).decode()
        chunks = [
            json.loads(line[len("data: "):])
            for line in body.splitlines()
            if line.startswith("data: ") and line != "data: [DONE]"
        ]
        usage_chunks = [c for c in chunks if c.get("usage")]
        assert len(usage_chunks) == 1
        u = usage_chunks[0]
        assert u["choices"] == []
        assert u["usage"]["completion_tokens"] >= 1
        assert (
            u["usage"]["total_tokens"]
            == u["usage"]["prompt_tokens"]
            + u["usage"]["completion_tokens"]
        )
        # without the option, no usage chunk appears
        resp2 = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "usage probe"}],
                "max_tokens": 6,
                "stream": True,
            },
        )
        body2 = (await resp2.read()).decode()
        assert '"usage"' not in body2
    finally:
        await client.close()
