"""vgtlint v2 flow-sensitive layer (ISSUE 15): CFG construction
(finally edges, raise-in-except, loop back edges), the dataflow
solver, the lock-order / obligations / epoch-guard checkers on
positive+negative fixtures, and the three seeded-mutation tests that
replay historical review-round bug shapes against COPIES of the real
runtime modules:

* PR-11 — host-pool bytes double-refunded on the sweep-then-settle
  path (obligations R002);
* PR-2 — a future created, then left unsettled on one exception arm
  (obligations R001);
* a synthetic ``_topology_lock``-inside-``_structural_lock`` order
  inversion in the real dp_engine (lock-order L001 + cycle L002).
"""

import ast
import os
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from vgate_tpu.analysis import runner as lint_runner  # noqa: E402
from vgate_tpu.analysis.cfg import BACK, EXC, build_cfg  # noqa: E402
from vgate_tpu.analysis.checkers import checkers_by_name  # noqa: E402
from vgate_tpu.analysis.dataflow import forward  # noqa: E402


def _write(root, relpath, text):
    path = os.path.join(str(root), relpath)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        fh.write(textwrap.dedent(text))
    return path


def _run(root, checker_names, only=None):
    by_name = checkers_by_name()
    return lint_runner.run(
        str(root), [by_name[n] for n in checker_names], only=only
    )


def _rules(result):
    return sorted({v.rule for v in result.violations})


def _cfg_of(src):
    tree = ast.parse(textwrap.dedent(src))
    fn = tree.body[0]
    return build_cfg(fn)


def _reachable(cfg, start, goal, kinds=None):
    """Path existence over the CFG, optionally restricted to edge
    kinds."""
    seen, stack = set(), [start]
    while stack:
        node = stack.pop()
        if node is goal:
            return True
        if id(node) in seen:
            continue
        seen.add(id(node))
        for succ, kind in node.succs:
            if kinds is None or kind in kinds:
                stack.append(succ)
    return False


# ------------------------------------------------------------- CFG shape


def test_cfg_finally_edge():
    """Both the normal path and the exception path route through the
    finally body; the exception still escapes afterwards."""
    cfg = _cfg_of(
        """
        def f(self):
            try:
                work()
            finally:
                cleanup()
            after()
        """
    )
    fin = next(n for n in cfg.nodes if _src(n) == "cleanup()")
    work = next(n for n in cfg.nodes if _src(n) == "work()")
    after = next(n for n in cfg.nodes if _src(n) == "after()")
    # normal: work -> finally -> after
    assert _reachable(cfg, work, fin)
    assert _reachable(cfg, fin, after)
    # exceptional: work's exc edge leads to the finally, and the
    # finally's exit can continue to raise_exit (the exception is not
    # swallowed)
    exc_succs = [s for s, k in work.succs if k == EXC]
    assert exc_succs and all(
        _reachable(cfg, s, fin) or s is fin for s in exc_succs
    )
    assert _reachable(cfg, fin, cfg.raise_exit)


def test_cfg_raise_in_except_flows_out_not_to_sibling():
    cfg = _cfg_of(
        """
        def f(self):
            try:
                work()
            except ValueError:
                raise
            except KeyError:
                other()
            after()
        """
    )
    re_raise = next(n for n in cfg.nodes if n.label == "raise")
    other = next(n for n in cfg.nodes if _src(n) == "other()")
    # the re-raise escapes the function; it does NOT enter the sibling
    # handler
    assert _reachable(cfg, re_raise, cfg.raise_exit)
    assert not _reachable(cfg, re_raise, other)
    # narrow handlers: the try body's exception can also escape both
    work = next(n for n in cfg.nodes if _src(n) == "work()")
    assert _reachable(cfg, work, cfg.raise_exit, kinds=(EXC,))


def test_cfg_loop_back_edge():
    cfg = _cfg_of(
        """
        def f(self, items):
            for x in items:
                use(x)
            done()
        """
    )
    backs = cfg.back_edges()
    assert len(backs) == 1
    src, dst = backs[0]
    assert _src(src) == "use(x)"
    assert dst.label == "loop"
    # continue also produces a back edge
    cfg2 = _cfg_of(
        """
        def f(self, items):
            while items:
                if skip():
                    continue
                use(items)
        """
    )
    assert any(
        s.label == "continue" for s, _ in cfg2.back_edges()
    )


def test_cfg_broad_handler_swallows_escape():
    cfg = _cfg_of(
        """
        def f(self):
            try:
                work()
            except Exception:
                handle()
            after()
        """
    )
    work = next(n for n in cfg.nodes if _src(n) == "work()")
    # with a broad handler, the try body's exception cannot reach
    # raise_exit without passing through the handler
    handle = next(n for n in cfg.nodes if _src(n) == "handle()")
    for succ, kind in work.succs:
        if kind == EXC:
            assert _reachable(cfg, succ, handle)


def _src(node):
    stmt = node.stmt
    if stmt is None:
        return ""
    try:
        return ast.unparse(stmt).strip()
    except Exception:  # pragma: no cover
        return ""


# ------------------------------------------------------------- dataflow


def test_dataflow_must_join_over_branches():
    """Must-analysis (AND-join): a guard on only one branch does not
    dominate the join point; on both branches it does."""
    cfg = _cfg_of(
        """
        def f(self, c):
            if c:
                guard()
            else:
                other()
            sink()
        """
    )

    def transfer(node, fact, kind):
        return True if _src(node) == "guard()" else fact

    facts = forward(cfg, False, transfer, lambda a, b: a and b)
    sink = next(n for n in cfg.nodes if _src(n) == "sink()")
    assert facts[sink] is False  # one arm lacks the guard

    cfg2 = _cfg_of(
        """
        def f(self, c):
            if c:
                guard()
            else:
                guard()
            sink()
        """
    )

    def transfer2(node, fact, kind):
        return True if _src(node) == "guard()" else fact

    facts2 = forward(cfg2, False, transfer2, lambda a, b: a and b)
    sink2 = next(n for n in cfg2.nodes if _src(n) == "sink()")
    assert facts2[sink2] is True


def test_dataflow_loop_fixpoint_terminates():
    cfg = _cfg_of(
        """
        def f(self, items):
            n = 0
            for x in items:
                n = step(n)
            return n
        """
    )
    counter = {"calls": 0}

    def transfer(node, fact, kind):
        counter["calls"] += 1
        return fact | {_src(node)} if node.stmt is not None else fact

    facts = forward(cfg, frozenset(), transfer, lambda a, b: a | b)
    assert cfg.exit in facts
    assert counter["calls"] < 500


# ------------------------------------------------------------ lock-order


_LOCK_REGISTRY = """
VGT_LOCK_ALIASES = {}
VGT_LOCK_ORDER = {
    "Mgr._outer_lock->Mgr._inner_lock": "outer wraps inner by design",
}
"""


@pytest.fixture
def lock_project(tmp_path):
    _write(
        tmp_path, "vgate_tpu/analysis/lock_order.py", _LOCK_REGISTRY
    )
    return tmp_path


def test_lock_order_declared_edge_is_clean(lock_project):
    _write(
        lock_project,
        "vgate_tpu/mgr.py",
        """
        import threading

        class Mgr:
            def __init__(self):
                self._outer_lock = threading.Lock()
                self._inner_lock = threading.Lock()

            def ok(self):
                with self._outer_lock:
                    with self._inner_lock:
                        pass
        """,
    )
    result = _run(lock_project, ["lock-order"])
    assert result.ok, [v.render() for v in result.violations]


def test_lock_order_undeclared_edge_and_cycle(lock_project):
    _write(
        lock_project,
        "vgate_tpu/mgr.py",
        """
        import threading

        class Mgr:
            def __init__(self):
                self._outer_lock = threading.Lock()
                self._inner_lock = threading.Lock()

            def inverted(self):
                with self._inner_lock:
                    with self._outer_lock:
                        pass
        """,
    )
    result = _run(lock_project, ["lock-order"])
    rules = _rules(result)
    assert "L001" in rules  # inner->outer never declared
    assert "L002" in rules  # declared outer->inner + observed inverse
    l1 = next(v for v in result.violations if v.rule == "L001")
    assert l1.symbol == "Mgr._inner_lock->Mgr._outer_lock"
    assert "vgate_tpu/mgr.py" == l1.path


def test_lock_order_cross_method_and_component_resolution(lock_project):
    """The edge is derived through calls: holding _outer_lock while
    calling a method (own class, then a VGT_COMPONENTS component)
    whose transitive closure acquires another lock."""
    _write(
        lock_project,
        "vgate_tpu/mgr.py",
        """
        import threading

        VGT_COMPONENTS = {"helper": "Helper"}

        class Helper:
            def poke(self):
                with self._h_lock:
                    pass

        class Mgr:
            def __init__(self):
                self._outer_lock = threading.Lock()
                self._inner_lock = threading.Lock()
                self._h_lock = threading.Lock()
                self.helper = Helper()

            def _take_inner(self):
                with self._inner_lock:
                    pass

            def chained(self):
                with self._outer_lock:
                    self._take_inner()     # declared edge: ok
                    self.helper.poke()     # L001: outer->Helper._h_lock
        """,
    )
    result = _run(lock_project, ["lock-order"])
    symbols = {v.symbol for v in result.violations if v.rule == "L001"}
    assert symbols == {"Mgr._outer_lock->Helper._h_lock"}


def test_lock_order_wrapper_registry(lock_project):
    _write(
        lock_project,
        "vgate_tpu/mgr.py",
        """
        import functools
        import threading

        VGT_LOCK_WRAPPERS = {"_serialized": "_outer_lock"}

        def _serialized(fn):
            @functools.wraps(fn)
            def wrapper(self, *a, **kw):
                with self._outer_lock:
                    return fn(self, *a, **kw)
            return wrapper

        class Mgr:
            def __init__(self):
                self._outer_lock = threading.Lock()
                self._inner_lock = threading.Lock()
                self._extra_lock = threading.Lock()

            @_serialized
            def op(self):
                with self._inner_lock:   # declared outer->inner: ok
                    pass

            @_serialized
            def bad(self):
                with self._extra_lock:   # L001: outer->extra undeclared
                    pass
        """,
    )
    result = _run(lock_project, ["lock-order"])
    symbols = {v.symbol for v in result.violations if v.rule == "L001"}
    assert symbols == {"Mgr._outer_lock->Mgr._extra_lock"}


def test_lock_order_stale_registry_entry(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/analysis/lock_order.py",
        """
        VGT_LOCK_ALIASES = {}
        VGT_LOCK_ORDER = {
            "Mgr._outer_lock->Mgr._typo_lock": "stale entry",
        }
        """,
    )
    _write(
        tmp_path,
        "vgate_tpu/mgr.py",
        """
        import threading

        class Mgr:
            def __init__(self):
                self._outer_lock = threading.Lock()
        """,
    )
    result = _run(tmp_path, ["lock-order"])
    assert _rules(result) == ["L003"]
    assert "_typo_lock" in result.violations[0].message


def test_lock_order_wrapper_typo_is_loud(lock_project):
    _write(
        lock_project,
        "vgate_tpu/mgr.py",
        """
        import threading

        VGT_LOCK_WRAPPERS = {"_serialized": "_outer_lock"}

        class Mgr:
            def __init__(self):
                self._outer_lock = threading.Lock()
                self._inner_lock = threading.Lock()
        """,
    )
    result = _run(lock_project, ["lock-order"])
    # the decorator named in the registry is never defined
    assert _rules(result) == ["L004"]


def test_lock_order_alias_canonicalizes(tmp_path):
    """Two names for one runtime lock object never produce an edge
    between themselves, and edges derived through either name land on
    the canonical one."""
    _write(
        tmp_path,
        "vgate_tpu/analysis/lock_order.py",
        """
        VGT_LOCK_ALIASES = {"Swap._lock": "Core._readback_lock"}
        VGT_LOCK_ORDER = {}
        """,
    )
    _write(
        tmp_path,
        "vgate_tpu/core.py",
        """
        import threading

        VGT_COMPONENTS = {"swap": "Swap"}

        class Swap:
            def park(self):
                with self._lock:
                    pass

        class Core:
            def __init__(self):
                self._readback_lock = threading.Lock()
                self.swap = Swap()
                self.swap._lock = self._readback_lock

            def fold(self):
                with self._readback_lock:
                    self.swap.park()   # same lock: reentrancy, no edge
        """,
    )
    result = _run(tmp_path, ["lock-order"])
    assert result.ok, [v.render() for v in result.violations]


# ----------------------------------------------------------- obligations


_OBL_BUDGET = """
VGT_OBLIGATIONS = {
    "budget": {
        "acquire": ("self._charge",),
        "release": ("self._refund",),
        "transfer_assign": ("self._registry",),
    },
}
"""

_OBL_FUTURE = """
VGT_OBLIGATIONS = {
    "future": {
        "acquire": ("*.create_future",),
        "release": ("*.set_result", "*.set_exception", "*.cancel"),
        "transfer": ("*.add_done_callback",),
    },
}
"""


def test_obligation_leak_on_exception_arm(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        _OBL_BUDGET
        + textwrap.dedent("""
        class M:
            def leaky(self, n):
                self._charge(n)
                self.work(n)          # raises -> charge leaks
                self._refund(n)
        """),
    )
    result = _run(tmp_path, ["obligations"])
    assert [v.rule for v in result.violations] == ["R001"]
    assert "exception path" in result.violations[0].message


def test_obligation_clean_try_finally_and_transfer(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        textwrap.dedent("""
        VGT_OBLIGATIONS = {
            "budget": {
                "acquire": ("self._charge",),
                "release": ("self._refund",),
                "transfer_assign": ("self._registry",),
            },
            "future": {
                "acquire": ("*.create_future",),
                "release": ("*.set_result", "*.set_exception"),
                "transfer": ("*.add_done_callback",),
            },
        }
        """)
        + textwrap.dedent("""
        class M:
            def fin(self, n):
                self._charge(n)
                try:
                    self.work(n)
                finally:
                    self._refund(n)

            def parked(self, key, ticket, n):
                self._charge(n)
                self._registry[key] = ticket

            def handed_off(self, loop):
                fut = loop.create_future()
                fut.add_done_callback(self.done)
                return fut

            def settle(self, fut, out):
                fut.set_result(out)
        """),
    )
    result = _run(tmp_path, ["obligations"])
    assert result.ok, [v.render() for v in result.violations]


def test_obligation_unsettled_future_exception_arm(tmp_path):
    """The PR-2 bug shape as a fixture: settled on the happy path,
    silently dropped when the exception arm returns."""
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        _OBL_FUTURE
        + textwrap.dedent("""
        class M:
            def run(self, loop):
                fut = loop.create_future()
                try:
                    out = self.work()
                    fut.set_result(out)
                except Exception:
                    self.log()
                    return None
                return fut
        """),
    )
    result = _run(tmp_path, ["obligations"])
    rules = [v.rule for v in result.violations]
    assert "R001" in rules
    leak = next(v for v in result.violations if v.rule == "R001")
    assert "future" in leak.symbol


def test_obligation_double_release(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        _OBL_BUDGET
        + textwrap.dedent("""
        class M:
            def double(self, ticket, n):
                self._charge(n)
                self._refund(ticket.nbytes)
                self._refund(ticket.nbytes)   # R002
        """),
    )
    result = _run(tmp_path, ["obligations"])
    assert "R002" in _rules(result)


def test_obligation_release_loop_is_not_double_release(tmp_path):
    """Per-item release loops rebind their loop target each iteration
    — the R002 key dies at the back edge, so no false positive."""
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        _OBL_BUDGET
        + textwrap.dedent("""
        class M:
            def sweep(self, dead, n):
                self._charge(n)
                try:
                    for ticket in dead:
                        self._refund(ticket.nbytes)
                finally:
                    self._refund(n)
        """),
    )
    result = _run(tmp_path, ["obligations"])
    assert "R002" not in _rules(result), [
        v.render() for v in result.violations
    ]


def test_obligation_stale_registry_pattern(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        """
        VGT_OBLIGATIONS = {
            "budget": {
                "acquire": ("self._chrage",),   # typo'd
                "release": ("self._refund",),
            },
        }

        class M:
            def ok(self, n):
                self._charge(n)
                self._refund(n)
        """,
    )
    result = _run(tmp_path, ["obligations"])
    assert "R003" in _rules(result)


# ----------------------------------------------------------- epoch-guard


_EPOCH_HEADER = """
VGT_EPOCH_GUARDS = {
    "append_token": {"lock": "_readback_lock",
                     "epoch": "preempt_count"},
}
"""


def test_epoch_guard_clean_shape(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        _EPOCH_HEADER
        + textwrap.dedent("""
        class Core:
            def fold(self, seqs, tokens):
                with self._readback_lock:
                    for seq, epoch in seqs:
                        if seq.preempt_count != epoch:
                            continue
                        seq.append_token(tokens[seq.slot])
        """),
    )
    result = _run(tmp_path, ["epoch-guard"])
    assert result.ok, [v.render() for v in result.violations]


def test_epoch_guard_missing_lock_and_compare(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        _EPOCH_HEADER
        + textwrap.dedent("""
        class Core:
            def bare(self, seq, token):
                seq.append_token(token)      # G001 + G002

            def locked_only(self, seq, token):
                with self._readback_lock:
                    seq.append_token(token)  # G002 (no epoch check)

            def one_arm(self, seq, token, fresh):
                with self._readback_lock:
                    if fresh:
                        if seq.preempt_count != 0:
                            return
                        seq.append_token(token)   # dominated: ok
                    else:
                        seq.append_token(token)   # G002: path skips it
        """),
    )
    result = _run(tmp_path, ["epoch-guard"])
    by_rule = {}
    for v in result.violations:
        by_rule.setdefault(v.rule, []).append(v.symbol)
    assert sorted(by_rule) == ["G001", "G002"]
    assert by_rule["G001"] == ["Core.bare:append_token:lock"]
    assert sorted(by_rule["G002"]) == [
        "Core.bare:append_token:epoch",
        "Core.locked_only:append_token:epoch",
        "Core.one_arm:append_token:epoch",
    ]


def test_epoch_guard_stale_entry(tmp_path):
    _write(
        tmp_path,
        "vgate_tpu/mod.py",
        """
        VGT_EPOCH_GUARDS = {
            "append_tokne": {"lock": "_readback_lock",
                             "epoch": "preempt_count"},
        }

        class Core:
            def fold(self, seq):
                with self._readback_lock:
                    if seq.preempt_count != 0:
                        return
                    seq.append_token(1)
        """,
    )
    result = _run(tmp_path, ["epoch-guard"])
    assert "G003" in _rules(result)


# ------------------------------------------- seeded historical mutations


def _copy_real(tmp_path, *relpaths):
    for rel in relpaths:
        src = os.path.join(REPO_ROOT, rel)
        with open(src) as fh:
            _write(tmp_path, rel, fh.read())


def test_seeded_pr11_double_refund_fires_r002(tmp_path):
    """PR-11's review-round bug: the stale sweep discarded a ticket
    (refund #1) and the settle hook refunded it again.  Replayed as a
    single-path shape appended to a COPY of the real kv_swap.py: the
    unmutated copy is clean, the mutation fires R002."""
    _copy_real(tmp_path, "vgate_tpu/runtime/kv_swap.py")
    clean = _run(tmp_path, ["obligations"])
    assert clean.ok, [v.render() for v in clean.violations]

    with open(
        os.path.join(tmp_path, "vgate_tpu/runtime/kv_swap.py"), "a"
    ) as fh:
        fh.write(
            "\n\ndef _seeded_sweep_then_settle(self, seq):\n"
            "    entry = self._seq_tickets.pop(seq.seq_id, None)\n"
            "    if entry is not None:\n"
            "        self._count_discard(entry[1], 'settled')\n"
            "        self._refund(entry[1].nbytes)\n"
        )
    mutated = _run(tmp_path, ["obligations"])
    assert [v.rule for v in mutated.violations] == ["R002"]
    v = mutated.violations[0]
    assert "host-pool-bytes" in v.symbol
    assert "_seeded_sweep_then_settle" in v.symbol


def test_seeded_pr2_unsettled_future_fires_r001(tmp_path):
    """PR-2's review-round bug: an exception arm in the batcher left
    the request future unsettled (client hangs forever).  Appended to
    a COPY of the real batcher.py."""
    _copy_real(tmp_path, "vgate_tpu/batcher.py")
    clean = _run(tmp_path, ["obligations"])
    assert clean.ok, [v.render() for v in clean.violations]

    with open(os.path.join(tmp_path, "vgate_tpu/batcher.py"), "a") as fh:
        fh.write(
            "\n\nasync def _seeded_exception_arm(self, prompt):\n"
            "    fut = asyncio.get_running_loop().create_future()\n"
            "    try:\n"
            "        out = await self._run_batch_inference([prompt])\n"
            "        fut.set_result(out)\n"
            "    except Exception:\n"
            "        logger.error('batch failed')\n"
            "        return None\n"
            "    return fut\n"
        )
    mutated = _run(tmp_path, ["obligations"])
    rules = [v.rule for v in mutated.violations]
    assert "R001" in rules
    assert all(
        "_seeded_exception_arm" in v.symbol for v in mutated.violations
    )
    leak = next(v for v in mutated.violations if v.rule == "R001")
    assert "request-future" in leak.symbol


def test_seeded_lock_inversion_fires_l001_and_cycle(tmp_path):
    """A synthetic ``_topology_lock``-inside-``_structural_lock``
    INVERSION seeded into a copy of the real dp_engine.py (inside the
    class, so lock qualification matches the declared registry): the
    undeclared reverse edge fires L001 and, unioned with the declared
    structural->topology edge, a cycle fires L002."""
    _copy_real(
        tmp_path,
        "vgate_tpu/runtime/dp_engine.py",
        "vgate_tpu/analysis/lock_order.py",
    )
    clean = _run(tmp_path, ["lock-order"])
    assert clean.ok, [v.render() for v in clean.violations]

    path = os.path.join(tmp_path, "vgate_tpu/runtime/dp_engine.py")
    with open(path) as fh:
        src = fh.read()
    anchor = "    def _pick_replica("
    assert anchor in src
    seeded = (
        "    def _seeded_inversion(self):\n"
        "        with self._topology_lock:\n"
        "            with self._structural_lock:\n"
        "                pass\n\n"
    )
    with open(path, "w") as fh:
        fh.write(src.replace(anchor, seeded + anchor, 1))
    mutated = _run(tmp_path, ["lock-order"])
    rules = _rules(mutated)
    assert rules == ["L001", "L002"], [
        v.render() for v in mutated.violations
    ]
    l1 = next(v for v in mutated.violations if v.rule == "L001")
    assert l1.symbol == (
        "ReplicatedEngine._topology_lock->"
        "ReplicatedEngine._structural_lock"
    )
    l2 = next(v for v in mutated.violations if v.rule == "L002")
    assert "_structural_lock" in l2.symbol
    assert "_topology_lock" in l2.symbol


# ------------------------------------------------------------ repo truth


def test_real_registries_are_declared():
    """The contracts this PR applies to the runtime stay declared —
    deleting a registry would silently disable its checker."""
    import vgate_tpu.analysis.lock_order as lo
    from vgate_tpu.runtime import dp_engine, kv_swap
    from vgate_tpu import batcher
    from vgate_tpu.server import app
    import vgate_tpu.runtime.engine_core as ec

    assert lo.declared_edges()  # at least the dp edges
    assert dp_engine.VGT_LOCK_WRAPPERS == {
        "_structural": "_structural_lock"
    }
    assert "host-pool-bytes" in kv_swap.VGT_OBLIGATIONS
    assert "admission-backlog" in batcher.VGT_OBLIGATIONS
    assert "request-future" in batcher.VGT_OBLIGATIONS
    assert "inflight-slot" in app.VGT_OBLIGATIONS
    assert "append_token" in ec.VGT_EPOCH_GUARDS


def test_github_format_output(tmp_path, capsys):
    import importlib.util

    _write(
        tmp_path,
        "vgate_tpu/server/h.py",
        "import time\n\nasync def a(r):\n    time.sleep(1)\n",
    )
    spec = importlib.util.spec_from_file_location(
        "vgt_lint_cli_gh",
        os.path.join(REPO_ROOT, "scripts", "vgt_lint.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # the CLI pins its project root to the real repo; drive the
    # formatter through runner results instead
    by_name = checkers_by_name()
    result = lint_runner.run(
        str(tmp_path), [by_name["async-blocking"]]
    )
    assert not result.ok
    # reuse the CLI's formatting contract by emulating one line
    v = result.violations[0]
    line = (
        f"::error file={v.path},line={max(1, v.line)},"
        f"title=vgt-lint {v.checker}/{v.rule}::{v.message}"
    )
    assert line.startswith("::error file=vgate_tpu/server/h.py,line=4")
    assert "async-blocking/A001" in line
