"""Client SDK tests: httpx.MockTransport contract tests (reference pattern:
vgate-client/tests/test_client.py monkeypatched responses) plus a live
in-process round-trip against the dry-run gateway."""

import json
import sys
from pathlib import Path

import httpx
import pytest

sys.path.insert(0, str(Path(__file__).parent.parent / "vgate_tpu_client"))

from vgate_tpu_client import (  # noqa: E402
    AsyncVGT,
    AuthenticationError,
    DeadlineExceeded,
    RateLimitError,
    ServerError,
    VGT,
)

CHAT_RESPONSE = {
    "id": "chatcmpl-test",
    "object": "chat.completion",
    "created": 123,
    "model": "test-model",
    "choices": [
        {
            "index": 0,
            "message": {"role": "assistant", "content": "hello there"},
            "finish_reason": "stop",
        }
    ],
    "usage": {"prompt_tokens": 3, "completion_tokens": 2, "total_tokens": 5},
    "cached": False,
    "metrics": {"ttft": 0.01},
}


def make_client(handler, **kwargs) -> VGT:
    client = VGT(base_url="http://testserver", **kwargs)
    client._http = httpx.Client(
        base_url="http://testserver", transport=httpx.MockTransport(handler)
    )
    return client


def test_chat_create_roundtrip():
    def handler(request):
        assert request.url.path == "/v1/chat/completions"
        body = json.loads(request.content)
        assert body["messages"][0]["content"] == "hi"
        return httpx.Response(200, json=CHAT_RESPONSE)

    client = make_client(handler)
    result = client.chat.create([{"role": "user", "content": "hi"}])
    assert result.choices[0].message.content == "hello there"
    assert result.usage.total_tokens == 5


def test_api_key_header_sent():
    seen = {}

    def handler(request):
        seen["auth"] = request.headers.get("Authorization")
        return httpx.Response(200, json=CHAT_RESPONSE)

    client = make_client(handler, api_key="sk-secret")
    client.chat.create([{"role": "user", "content": "x"}])
    assert seen["auth"] == "Bearer sk-secret"


def test_401_raises_authentication_error():
    def handler(request):
        return httpx.Response(
            401,
            json={"error": {"message": "Missing API key",
                            "type": "authentication_error"}},
        )

    client = make_client(handler)
    with pytest.raises(AuthenticationError) as err:
        client.chat.create([{"role": "user", "content": "x"}])
    assert err.value.status_code == 401


def test_429_retries_then_succeeds(monkeypatch):
    calls = {"n": 0}

    def handler(request):
        calls["n"] += 1
        if calls["n"] == 1:
            return httpx.Response(
                429,
                headers={"Retry-After": "0", "X-RateLimit-Limit": "2",
                         "X-RateLimit-Remaining": "0"},
                json={"error": {"message": "limited",
                                "type": "rate_limit_error"}},
            )
        return httpx.Response(200, json=CHAT_RESPONSE)

    client = make_client(handler, max_retries=2)
    result = client.chat.create([{"role": "user", "content": "x"}])
    assert calls["n"] == 2
    assert result.id == "chatcmpl-test"


def test_429_exhausted_raises_with_retry_after():
    def handler(request):
        return httpx.Response(
            429,
            headers={"Retry-After": "0"},
            json={"error": {"message": "limited", "type": "rate_limit_error"}},
        )

    client = make_client(handler, max_retries=1)
    with pytest.raises(RateLimitError) as err:
        client.chat.create([{"role": "user", "content": "x"}])
    assert err.value.retry_after == 0.0


def test_5xx_retries_then_raises():
    calls = {"n": 0}

    def handler(request):
        calls["n"] += 1
        return httpx.Response(500, json={"error": {"message": "boom"}})

    client = make_client(handler, max_retries=1)
    with pytest.raises(ServerError):
        client.chat.create([{"role": "user", "content": "x"}])
    assert calls["n"] == 2


def test_rate_limit_info_recorded():
    def handler(request):
        return httpx.Response(
            200,
            headers={"X-RateLimit-Limit": "60", "X-RateLimit-Remaining": "41"},
            json=CHAT_RESPONSE,
        )

    client = make_client(handler)
    client.chat.create([{"role": "user", "content": "x"}])
    assert client.last_rate_limit.limit == 60
    assert client.last_rate_limit.remaining == 41


def test_embeddings_resource():
    def handler(request):
        return httpx.Response(
            200,
            json={
                "object": "list",
                "data": [{"object": "embedding", "index": 0,
                          "embedding": [0.1, 0.2]}],
                "model": "bge",
                "usage": {"prompt_tokens": 2, "completion_tokens": 0,
                          "total_tokens": 2},
            },
        )

    client = make_client(handler)
    result = client.embeddings.create("hello")
    assert result.data[0].embedding == [0.1, 0.2]


def test_context_manager():
    with make_client(lambda r: httpx.Response(200, json={"status": "ok",
                                                         "version": "1"})) as c:
        assert c.health().status == "ok"


async def test_async_client_live_roundtrip():
    """AsyncVGT against a live in-process dry-run gateway (socket included)."""
    from aiohttp.test_utils import TestServer

    from vgate_tpu.config import load_config
    from vgate_tpu.server.app import create_app

    config = load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 4, "max_wait_time_ms": 5.0},
        logging={"level": "WARNING"},
    )
    server = TestServer(create_app(config))
    await server.start_server()
    try:
        async with AsyncVGT(base_url=str(server.make_url("/"))) as client:
            health = await client.health()
            assert health.status == "ok"
            completion = await client.chat.create(
                [{"role": "user", "content": "live ping"}], max_tokens=8
            )
            assert "[dry-run] echo:" in completion.choices[0].message.content
            emb = await client.embeddings.create(["a", "b"])
            assert len(emb.data) == 2
            stats = await client.stats()
            assert stats["batcher"]["total_requests"] >= 1
            # SSE streaming end-to-end
            chunks = []
            stream = await client.chat.create(
                [{"role": "user", "content": "stream"}], stream=True
            )
            async for chunk in stream:
                chunks.append(chunk)
            assert chunks[0]["object"] == "chat.completion.chunk"
            assert any(
                c["choices"][0]["finish_reason"] == "stop" for c in chunks
            )
    finally:
        await server.close()


def test_chat_create_sends_new_sampling_fields():
    """The SDK forwards the full sampling surface: logprobs, n,
    penalties, min_tokens, stop_token_ids."""
    seen = {}

    def handler(request):
        seen.update(json.loads(request.content))
        resp = dict(CHAT_RESPONSE)
        resp["choices"] = [
            {
                "index": 0,
                "message": {"role": "assistant", "content": "x"},
                "finish_reason": "stop",
                "logprobs": {"content": [{"token": "x", "logprob": -0.5}]},
            }
        ]
        return httpx.Response(200, json=resp)

    client = make_client(handler)
    result = client.chat.create(
        [{"role": "user", "content": "hi"}],
        logprobs=True,
        top_logprobs=3,
        n=2,
        frequency_penalty=0.5,
        presence_penalty=0.25,
        min_tokens=4,
        stop_token_ids=[7, 9],
    )
    assert seen["logprobs"] is True
    assert seen["top_logprobs"] == 3
    assert seen["n"] == 2
    assert seen["frequency_penalty"] == 0.5
    assert seen["presence_penalty"] == 0.25
    assert seen["min_tokens"] == 4
    assert seen["stop_token_ids"] == [7, 9]
    assert result.choices[0].logprobs["content"][0]["logprob"] == -0.5


def test_timeout_kwarg_sets_header():
    """chat.create(timeout=...) sends X-Request-Timeout (server-side
    deadline) plus a per-request transport timeout with margin."""
    seen = {}

    def handler(request):
        seen["header"] = request.headers.get("X-Request-Timeout")
        seen["timeout"] = request.extensions.get("timeout")
        return httpx.Response(200, json=CHAT_RESPONSE)

    client = make_client(handler)
    client.chat.create([{"role": "user", "content": "hi"}], timeout=2.5)
    assert seen["header"] == "2.5"
    # transport timeout = deadline + margin, so the server's typed 504
    # beats the socket timeout even when an engine tick stalls the shed
    # (margin must exceed the server's ~30s engine-shed grace)
    assert seen["timeout"]["read"] == pytest.approx(37.5)


def test_embeddings_timeout_kwarg_sets_header():
    seen = {}

    def handler(request):
        seen["header"] = request.headers.get("X-Request-Timeout")
        return httpx.Response(
            200,
            json={
                "object": "list",
                "data": [],
                "model": "bge",
                "usage": {"prompt_tokens": 0, "completion_tokens": 0,
                          "total_tokens": 0},
            },
        )

    client = make_client(handler)
    client.embeddings.create("hello", timeout=1.0)
    assert seen["header"] == "1.0"


def test_504_maps_to_deadline_exceeded_without_retry():
    """A 504 raises the typed DeadlineExceeded carrying the server's
    partial-generation metadata, and is NOT retried — the same request
    would blow the same budget."""
    calls = {"n": 0}

    def handler(request):
        calls["n"] += 1
        return httpx.Response(
            504,
            json={
                "error": {
                    "message": "deadline passed mid-generation",
                    "type": "timeout_error",
                    "partial_tokens": 17,
                    "partial_text": "the partial...",
                }
            },
        )

    client = make_client(handler, max_retries=2)
    with pytest.raises(DeadlineExceeded) as err:
        client.chat.create(
            [{"role": "user", "content": "x"}], timeout=0.05
        )
    assert calls["n"] == 1  # no retry on deadline
    assert err.value.status_code == 504
    assert err.value.partial_tokens == 17
    assert err.value.partial_text == "the partial..."


def test_completions_resource_roundtrip():
    def handler(request):
        assert request.url.path == "/v1/completions"
        body = json.loads(request.content)
        assert body["prompt"] == "complete this"
        assert body["echo"] is True
        return httpx.Response(
            200,
            json={
                "id": "cmpl-1",
                "object": "text_completion",
                "created": 1,
                "model": "m",
                "choices": [
                    {"index": 0, "text": "complete this — done",
                     "finish_reason": "stop"}
                ],
                "usage": {"prompt_tokens": 2, "completion_tokens": 3,
                          "total_tokens": 5},
            },
        )

    client = make_client(handler)
    result = client.completions.create(
        "complete this", echo=True, max_tokens=3
    )
    assert result["choices"][0]["text"].startswith("complete this")


# --- overload protection (ISSUE 4): typed 503s, priority, jittered backoff ---


def test_503_overloaded_is_typed_with_retry_after():
    from vgate_tpu_client import ServerOverloadedError

    def handler(request):
        return httpx.Response(
            503,
            headers={"Retry-After": "7"},
            json={
                "error": {
                    "message": "server overloaded (backlog_tokens)",
                    "type": "overloaded_error",
                    "reason": "overloaded",
                }
            },
        )

    client = make_client(handler, max_retries=0)
    with pytest.raises(ServerOverloadedError) as err:
        client.chat.create([{"role": "user", "content": "x"}])
    assert err.value.status_code == 503
    assert err.value.retry_after == 7.0


def test_503_kv_capacity_is_typed_with_retry_after():
    """ISSUE 12: the engine's KV-exhausted failure surfaces as a typed
    503 with body reason "kv_capacity" and the SDK maps it — clients
    retry against a less-loaded replica instead of treating an opaque
    500 as a server bug."""
    from vgate_tpu_client import KVCapacityError, ServerOverloadedError

    def handler(request):
        return httpx.Response(
            503,
            headers={"Retry-After": "5"},
            json={
                "error": {
                    "message": "KV pages exhausted: the sequence's "
                    "grown context cannot fit the pool even alone",
                    "type": "unavailable_error",
                    "reason": "kv_capacity",
                }
            },
        )

    client = make_client(handler, max_retries=0)
    with pytest.raises(KVCapacityError) as err:
        client.chat.create([{"role": "user", "content": "x"}])
    assert err.value.status_code == 503
    assert err.value.retry_after == 5.0
    assert not isinstance(err.value, ServerOverloadedError)


def test_503_draining_stays_plain_server_error():
    from vgate_tpu_client import ServerOverloadedError

    def handler(request):
        return httpx.Response(
            503,
            headers={"Retry-After": "2"},
            json={
                "error": {
                    "message": "server is draining for shutdown",
                    "type": "overloaded_error",
                    "reason": "draining",
                }
            },
        )

    client = make_client(handler, max_retries=0)
    with pytest.raises(ServerError) as err:
        client.chat.create([{"role": "user", "content": "x"}])
    assert not isinstance(err.value, ServerOverloadedError)


def test_priority_kwarg_rides_the_payload():
    seen = {}

    def handler(request):
        seen[request.url.path] = json.loads(request.content)
        if request.url.path == "/v1/embeddings":
            return httpx.Response(
                200,
                json={"object": "list", "data": [], "model": "m",
                      "usage": {"prompt_tokens": 0,
                                "completion_tokens": 0,
                                "total_tokens": 0}},
            )
        if request.url.path == "/v1/completions":
            return httpx.Response(
                200, json={"choices": [], "usage": {}}
            )
        return httpx.Response(200, json=CHAT_RESPONSE)

    client = make_client(handler)
    client.chat.create(
        [{"role": "user", "content": "x"}], priority="interactive"
    )
    client.completions.create("x", priority="batch")
    client.embeddings.create("x", priority="standard")
    assert seen["/v1/chat/completions"]["priority"] == "interactive"
    assert seen["/v1/completions"]["priority"] == "batch"
    assert seen["/v1/embeddings"]["priority"] == "standard"
    # omitted priority never reaches the wire (exclude_none)
    client.chat.create([{"role": "user", "content": "x"}])
    assert "priority" not in seen["/v1/chat/completions"]


def test_backoff_is_jittered_and_honors_retry_after():
    from vgate_tpu_client.client import _retry_delay

    # no server hint: equal jitter inside (base/2, base]
    delays = {_retry_delay(1) for _ in range(64)}
    assert all(1.0 <= d <= 2.0 for d in delays)
    assert len(delays) > 1, "backoff must not be deterministic"
    # retried clients must not synchronize into storms
    assert len({_retry_delay(2) for _ in range(64)}) > 1
    # Retry-After is the MINIMUM, jitter only stretches it
    delays = [_retry_delay(0, retry_after=4.0) for _ in range(64)]
    assert all(d >= 4.0 for d in delays)
    assert max(delays) > 4.0


def test_retry_sleep_uses_jitter(monkeypatch):
    import vgate_tpu_client.client as client_mod

    sleeps = []
    monkeypatch.setattr(
        client_mod.time, "sleep", lambda s: sleeps.append(s)
    )
    calls = {"n": 0}

    def handler(request):
        calls["n"] += 1
        if calls["n"] == 1:
            return httpx.Response(
                503,
                json={"error": {"message": "recovering",
                                "type": "overloaded_error",
                                "reason": "recovering"}},
            )
        return httpx.Response(200, json=CHAT_RESPONSE)

    client = make_client(handler, max_retries=1)
    result = client.chat.create([{"role": "user", "content": "x"}])
    assert result.id == "chatcmpl-test"
    # no Retry-After header -> equal-jitter from the attempt number
    assert len(sleeps) == 1 and 0.5 <= sleeps[0] <= 1.0
