"""Batcher semantics: lifecycle, triggers, dedup, per-request params, error
containment (reference: tests/test_batcher.py:94-242, tests/test_cache.py:261-303)."""

import asyncio
from typing import List, Sequence

import pytest

from vgate_tpu.backends.base import GenerationResult, SamplingParams
from vgate_tpu.batcher import RequestBatcher
from vgate_tpu.config import load_config


class CountingBackend:
    """Instrumented fake backend (reference test pattern:
    tests/test_batcher.py:29-56)."""

    def __init__(self, delay: float = 0.0, fail: bool = False):
        self.calls: List[List[str]] = []
        self.params_seen: List[List[SamplingParams]] = []
        self.delay = delay
        self.fail = fail

    def load_model(self, model_config):
        pass

    def create_sampling_params(self, **kw):
        return SamplingParams(**kw)

    def generate(self, prompts: Sequence[str], params: Sequence[SamplingParams]):
        if self.fail:
            raise RuntimeError("backend exploded")
        self.calls.append(list(prompts))
        self.params_seen.append(list(params))
        return [
            GenerationResult(
                text=f"out:{p}",
                token_ids=[1, 2, 3],
                num_tokens=3,
                prompt_tokens=len(p.split()),
                metrics={"ttft": 0.01, "gen_time": 0.02, "tpot": 0.005},
            )
            for p in prompts
        ]

    def shutdown(self):
        pass


class FakeEngine:
    def __init__(self, backend, config):
        self.backend = backend
        self.config = config


def make_batcher(config=None, backend=None):
    config = config or load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 4, "max_wait_time_ms": 10.0},
    )
    backend = backend or CountingBackend()
    return RequestBatcher(FakeEngine(backend, config), config), backend


async def test_lifecycle():
    batcher, _ = make_batcher()
    await batcher.start()
    assert batcher.get_metrics()["running"] is True
    await batcher.stop()
    assert batcher.get_metrics()["running"] is False


async def test_single_request_via_timer():
    batcher, backend = make_batcher()
    await batcher.start()
    try:
        result = await batcher.submit("hello world")
        assert result["text"] == "out:hello world"
        assert result["cached"] is False
        assert len(backend.calls) == 1
    finally:
        await batcher.stop()


async def test_size_trigger_batches_together():
    config = load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 4, "max_wait_time_ms": 5000.0},
    )
    batcher, backend = make_batcher(config)
    await batcher.start()
    try:
        results = await asyncio.gather(
            *[batcher.submit(f"p{i}") for i in range(4)]
        )
        assert len(results) == 4
        # one batch of 4, despite the long timer
        assert len(backend.calls) == 1
        assert sorted(backend.calls[0]) == ["p0", "p1", "p2", "p3"]
    finally:
        await batcher.stop()


async def test_in_batch_dedup():
    """3 identical prompts => 1 inference (reference: tests/test_cache.py:261-279)."""
    config = load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 3, "max_wait_time_ms": 5000.0},
        cache={"enabled": False},
    )
    batcher, backend = make_batcher(config)
    await batcher.start()
    try:
        results = await asyncio.gather(
            *[
                batcher.submit("same prompt", request_id=f"req-{i}")
                for i in range(3)
            ]
        )
        assert all(r["text"] == "out:same prompt" for r in results)
        assert len(backend.calls) == 1
        assert backend.calls[0] == ["same prompt"]
        assert batcher.get_metrics()["total_deduplicated"] == 2
        # deduped followers share the computation but keep their OWN ids
        assert sorted(r["request_id"] for r in results) == [
            "req-0", "req-1", "req-2",
        ]
    finally:
        await batcher.stop()


async def test_mixed_dedup():
    """5 requests, 3 unique => 3 inferences (reference: tests/test_cache.py:281-303)."""
    config = load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 5, "max_wait_time_ms": 5000.0},
        cache={"enabled": False},
    )
    batcher, backend = make_batcher(config)
    await batcher.start()
    try:
        prompts = ["a", "b", "a", "c", "b"]
        await asyncio.gather(*[batcher.submit(p) for p in prompts])
        assert len(backend.calls) == 1
        assert sorted(backend.calls[0]) == ["a", "b", "c"]
    finally:
        await batcher.stop()


async def test_cache_hit_fast_path():
    batcher, backend = make_batcher()
    await batcher.start()
    try:
        first = await batcher.submit("cached prompt")
        assert first["cached"] is False
        second = await batcher.submit("cached prompt")
        assert second["cached"] is True
        assert len(backend.calls) == 1
        assert batcher.get_metrics()["total_cache_hits"] == 1
    finally:
        await batcher.stop()


async def test_per_request_sampling_params_survive_batching():
    """The reference quirk (batcher.py:271: first request's temp applies to
    all) must NOT reproduce: each request keeps its own params."""
    config = load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 2, "max_wait_time_ms": 5000.0},
        cache={"enabled": False},
    )
    batcher, backend = make_batcher(config)
    await batcher.start()
    try:
        await asyncio.gather(
            batcher.submit("x", temperature=0.1),
            batcher.submit("y", temperature=0.9),
        )
        params = backend.params_seen[0]
        temps = sorted(p.temperature for p in params)
        assert temps == [0.1, 0.9]
    finally:
        await batcher.stop()


async def test_batch_error_fails_all_futures():
    batcher, _ = make_batcher(backend=CountingBackend(fail=True))
    await batcher.start()
    try:
        results = await asyncio.gather(
            batcher.submit("a"),
            batcher.submit("b"),
            return_exceptions=True,
        )
        assert all(isinstance(r, RuntimeError) for r in results)
    finally:
        await batcher.stop()


async def test_server_survives_batch_error():
    backend = CountingBackend(fail=True)
    batcher, _ = make_batcher(backend=backend)
    await batcher.start()
    try:
        with pytest.raises(RuntimeError):
            await batcher.submit("boom")
        backend.fail = False
        result = await batcher.submit("recovered")
        assert result["text"] == "out:recovered"
    finally:
        await batcher.stop()


async def test_concurrent_load():
    """20-way concurrency (reference: tests/test_batcher.py:214-229)."""
    batcher, backend = make_batcher()
    await batcher.start()
    try:
        results = await asyncio.gather(
            *[batcher.submit(f"p{i % 7}") for i in range(20)]
        )
        assert len(results) == 20
        stats = batcher.get_metrics()
        assert stats["total_requests"] == 20
        # batching must have collapsed 20 requests into fewer inferences
        assert len(backend.calls) < 20
    finally:
        await batcher.stop()


async def test_graceful_shutdown_drains_queue():
    config = load_config(
        model={"engine_type": "dry_run"},
        batch={"max_batch_size": 64, "max_wait_time_ms": 60000.0},
    )
    batcher, backend = make_batcher(config)
    await batcher.start()
    task = asyncio.create_task(batcher.submit("pending"))
    await asyncio.sleep(0.05)  # let it enqueue
    await batcher.stop()
    result = await asyncio.wait_for(task, timeout=2)
    assert result["text"] == "out:pending"


class SettledBackend(CountingBackend):
    """Backend with the settled path: prompts containing "FAIL" return an
    exception object in place of their result."""

    async def generate_settled_async(self, prompts, params):
        self.calls.append(list(prompts))
        out = []
        for p in prompts:
            if "FAIL" in p:
                out.append(RuntimeError(f"shed:{p}"))
            else:
                out.append(
                    GenerationResult(
                        text=f"out:{p}", token_ids=[1], num_tokens=1,
                        prompt_tokens=1,
                        metrics={"ttft": 0.01, "gen_time": 0.02,
                                 "tpot": 0.005},
                    )
                )
        return out


async def test_settled_failure_does_not_poison_batch():
    """One failed request in a batch (e.g. deadline shed) fails only its
    own future; co-batched requests keep their completions."""
    batcher, backend = make_batcher(backend=SettledBackend())
    await batcher.start()
    try:
        ok_task = asyncio.ensure_future(batcher.submit("good prompt"))
        bad_task = asyncio.ensure_future(batcher.submit("FAIL prompt"))
        ok2_task = asyncio.ensure_future(batcher.submit("also good"))
        ok = await ok_task
        ok2 = await ok2_task
        with pytest.raises(RuntimeError, match="shed:FAIL prompt"):
            await bad_task
        assert ok["text"] == "out:good prompt"
        assert ok2["text"] == "out:also good"
        # the failed group was not cached: resubmitting re-runs inference
        with pytest.raises(RuntimeError):
            await batcher.submit("FAIL prompt")
    finally:
        await batcher.stop()


async def test_submit_timeout_dequeues_abandoned_request():
    """A request that times out while still queued is removed from the
    queue — abandoned work must not occupy a future batch."""
    config = load_config(
        model={"engine_type": "dry_run"},
        # huge window + batch size: nothing fires without a manual trigger
        batch={"max_batch_size": 64, "max_wait_time_ms": 60_000.0},
    )
    batcher, backend = make_batcher(config=config)
    await batcher.start()
    try:
        with pytest.raises(asyncio.TimeoutError):
            await batcher.submit("abandoned", timeout_s=0.05)
        assert len(batcher._queue) == 0
        assert batcher.get_metrics()["pending_requests"] == 0
    finally:
        await batcher.stop()
