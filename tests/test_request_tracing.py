"""Cross-thread request tracing (ISSUE 3 tentpole 1): the span tree a
request produces, verified with the SDK-less in-memory recorder
(observability/memtrace.py).

Fast tier: memtrace mechanics + the dry-run gateway's end-to-end trace
(approximate engine phases emitted by the batcher).  Slow tier: the
real EngineCore's exact phase spans across the engine-thread boundary.
"""

import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.observability.memtrace import MemorySpanRecorder
from vgate_tpu.observability.reqtrace import RequestMeta, RequestTrace
from vgate_tpu.server.app import create_app
from vgate_tpu.tracing import capture_context, context_trace_id, get_tracer


# ---------------------------------------------------------------- memtrace


def test_memtrace_records_parented_spans():
    rec = MemorySpanRecorder().install()
    tracer = get_tracer("t")
    with tracer.start_as_current_span("parent"):
        with tracer.start_as_current_span("child"):
            pass
    parent = rec.spans("parent")[0]
    child = rec.spans("child")[0]
    assert child.trace_id_hex == parent.trace_id_hex
    assert child.parent_span_id_hex == parent.span_id_hex
    assert parent.parent_span_id_hex is None
    assert parent.end_time is not None and child.end_time is not None


def test_memtrace_capture_context_crosses_explicit_parenting():
    rec = MemorySpanRecorder().install()
    tracer = get_tracer("t")
    with tracer.start_as_current_span("root"):
        ctx = capture_context()
    assert context_trace_id(ctx) == rec.spans("root")[0].trace_id_hex
    # a span created later, off-context, still parents on the capture
    span = tracer.start_span("late", context=ctx)
    span.end()
    late = rec.spans("late")[0]
    assert late.parent_span_id_hex == rec.spans("root")[0].span_id_hex


def test_request_trace_noops_without_context():
    # no ctx => no emission, but identity fields survive for records
    tr = RequestTrace(RequestMeta(request_id="r1", trace_ctx=None))
    tr.start("queue")
    tr.end("queue")
    tr.event("anything")
    tr.close()
    assert tr.request_id == "r1"
    assert tr.trace_id is None


def test_request_trace_emits_phases_under_recorder():
    rec = MemorySpanRecorder().install()
    tracer = get_tracer("t")
    with tracer.start_as_current_span("root"):
        meta = RequestMeta(request_id="r2", trace_ctx=capture_context())
    tr = RequestTrace(meta)
    tr.start("queue")
    tr.end("queue")
    tr.start("prefill", bucket=128)
    tr.event("xla_compile")
    tr.end("prefill")
    tr.start("decode")
    tr.close()
    root = rec.spans("root")[0]
    names = {s.name for s in rec.finished_spans()}
    assert {"engine.queue", "engine.prefill", "engine.decode"} <= names
    for span in rec.finished_spans():
        if span.name.startswith("engine."):
            assert span.trace_id_hex == root.trace_id_hex
            assert span.parent_span_id_hex == root.span_id_hex
    prefill = rec.spans("engine.prefill")[0]
    assert prefill.attributes["bucket"] == 128
    assert prefill.attributes["request.id"] == "r2"
    assert any(e[0] == "xla_compile" for e in prefill.events)


# ------------------------------------------------- dry-run gateway (fast)


async def _client(**overrides):
    overrides.setdefault("model", {"engine_type": "dry_run"})
    overrides.setdefault(
        "batch", {"max_batch_size": 4, "max_wait_time_ms": 5.0}
    )
    overrides.setdefault("logging", {"level": "WARNING"})
    config = load_config(**overrides)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    return client


async def test_dry_run_request_produces_single_engine_span_tree():
    """ISSUE 3 acceptance: one trace per request, with queue/prefill/
    decode spans that are children of the HTTP request span — under the
    dry-run backend, with no OTel SDK installed."""
    rec = MemorySpanRecorder().install()
    client = await _client()
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "trace me"}],
                "max_tokens": 8,
            },
        )
        assert resp.status == 200
    finally:
        await client.close()
    http_spans = rec.spans("POST /v1/chat/completions")
    assert len(http_spans) == 1
    http = http_spans[0]
    phases = {
        name: rec.spans(f"engine.{name}")
        for name in ("queue", "prefill", "decode")
    }
    for name, spans in phases.items():
        assert len(spans) == 1, f"expected one engine.{name} span"
        span = spans[0]
        # children of the HTTP request span, in the same (single) trace
        assert span.trace_id_hex == http.trace_id_hex
        assert span.parent_span_id_hex == http.span_id_hex
        assert span.attributes.get("approximate") is True
        assert span.end_time is not None
    # ordering: queue ends at/before prefill start, prefill before decode
    assert (
        phases["queue"][0].end_time
        <= phases["prefill"][0].end_time
        <= phases["decode"][0].end_time
    )
    # batcher.submit is a sibling of the engine phases, same trace
    submit = rec.spans("batcher.submit")[0]
    assert submit.trace_id_hex == http.trace_id_hex
    assert submit.parent_span_id_hex == http.span_id_hex


async def test_observability_disabled_emits_no_engine_spans():
    rec = MemorySpanRecorder().install()
    client = await _client(observability={"enabled": False})
    try:
        resp = await client.post(
            "/v1/chat/completions",
            json={
                "messages": [{"role": "user", "content": "no spans"}],
                "max_tokens": 8,
            },
        )
        assert resp.status == 200
    finally:
        await client.close()
    engine_spans = [
        s for s in rec.spans() if s.name.startswith("engine.")
    ]
    assert engine_spans == []
    # the HTTP + batcher spans still exist (tracing itself is separate)
    assert rec.spans("POST /v1/chat/completions")


# --------------------------------------------- real engine (slow tier)


def _engine_config():
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 4, "prefill_buckets": [8, 16, 32],
            "use_pallas": False,
        },
        recovery={"enabled": False},
        logging={"level": "ERROR"},
    )


@pytest.mark.slow
def test_engine_emits_exact_phase_spans_across_thread_boundary():
    from vgate_tpu.runtime.engine_core import EngineCore

    rec = MemorySpanRecorder().install()
    core = EngineCore(_engine_config())
    core.start()
    try:
        tracer = get_tracer("t")
        with tracer.start_as_current_span("http-request"):
            meta = RequestMeta(
                request_id="req-engine", trace_ctx=capture_context()
            )
        seq = core.submit_tokens(
            [5, 6, 7],
            SamplingParams(max_tokens=4, temperature=0.0),
            meta=meta,
        )
        assert seq.done_event.wait(timeout=300)
    finally:
        core.stop()
    root = rec.spans("http-request")[0]
    for name in ("engine.queue", "engine.prefill", "engine.decode"):
        spans = rec.spans(name)
        assert spans, f"missing {name}"
        assert spans[0].trace_id_hex == root.trace_id_hex
        assert spans[0].parent_span_id_hex == root.span_id_hex
        assert spans[0].end_time is not None
        assert spans[0].attributes.get("request.id") == "req-engine"
    prefill = rec.spans("engine.prefill")[0]
    assert prefill.attributes["bucket"] >= 4
    # flight recorder stamped the same identity
    record = core.flight.find_request("req-engine")
    assert record is not None
    assert record["status"] == "finished"
    assert record["trace_id"] == root.trace_id_hex
    assert record["prefill_s"] >= 0.0 and record["decode_s"] >= 0.0


@pytest.mark.slow
def test_backend_settled_path_emits_detokenize_span():
    import asyncio

    from vgate_tpu.backends.jax_backend import JaxTPUBackend

    rec = MemorySpanRecorder().install()
    backend = JaxTPUBackend()
    config = _engine_config()
    backend.load_model(config)
    try:
        tracer = get_tracer("t")
        with tracer.start_as_current_span("http-request"):
            meta = RequestMeta(
                request_id="req-detok", trace_ctx=capture_context()
            )

        async def run():
            return await backend.generate_settled_async(
                ["hello engine"],
                [SamplingParams(max_tokens=4, temperature=0.0)],
                request_meta=[meta],
            )

        results = asyncio.run(run())
        assert not isinstance(results[0], BaseException)

        # the SSE streaming path bypasses the batcher; request_meta
        # crosses the seam directly and stamps the flight record with
        # the gateway request id
        with tracer.start_as_current_span("http-stream"):
            stream_meta = RequestMeta(
                request_id="req-stream", trace_ctx=capture_context()
            )

        async def run_stream():
            out = []
            async for piece in backend.stream_async(
                "stream tracing probe",
                SamplingParams(max_tokens=3, temperature=0.0),
                request_meta=stream_meta,
            ):
                out.append(piece)
            return out

        assert asyncio.run(run_stream())
        record = backend.core.flight.find_request("req-stream")
        assert record is not None and record["status"] == "finished"
    finally:
        backend.shutdown()
    root = rec.spans("http-request")[0]
    detok = rec.spans("engine.detokenize")
    assert detok and detok[0].trace_id_hex == root.trace_id_hex
    stream_root = rec.spans("http-stream")[0]
    stream_engine = [
        s
        for s in rec.spans()
        if s.name.startswith("engine.")
        and s.trace_id_hex == stream_root.trace_id_hex
    ]
    assert {s.name for s in stream_engine} >= {
        "engine.queue", "engine.prefill", "engine.decode",
    }
