"""EngineCore integration tests: the full continuous-batching loop on a CPU
device with the tiny dense model (SURVEY.md section 4: CPU-backed jax tests
for scheduler/engine logic)."""

import asyncio
import threading

import numpy as np
import pytest

import jax

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.runtime.engine_core import EngineCore


def tiny_config(**tpu_overrides):
    tpu = {
        "dp": 1,
        "tp": 1,
        "ep": 1,
        "sp": 1,
        "kv_num_pages": 64,
        "kv_page_size": 4,
        "max_batch_slots": 4,
        "prefill_buckets": [8, 16, 32],
        "use_pallas": False,
    }
    tpu.update(tpu_overrides)
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu=tpu,
        scheduler={"max_queue_size": 16},
        logging={"level": "WARNING"},
    )


@pytest.fixture(scope="module")
def engine():
    core = EngineCore(tiny_config(), devices=jax.devices()[:1])
    core.start()
    yield core
    core.stop()


def greedy(max_tokens=8):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0)


def test_generate_single(engine):
    [result] = engine.generate(["hello world"], [greedy(6)])
    assert result["num_tokens"] >= 1
    assert result["num_tokens"] <= 6
    assert result["finish_reason"] in ("stop", "length")
    assert result["metrics"]["ttft"] > 0
    assert isinstance(result["text"], str)


def test_generate_is_deterministic_greedy(engine):
    [a] = engine.generate(["determinism probe"], [greedy(8)])
    [b] = engine.generate(["determinism probe"], [greedy(8)])
    assert a["token_ids"] == b["token_ids"]


def test_generate_batch_matches_single(engine):
    """Continuous batching must not change greedy results: running three
    prompts together equals running each alone."""
    prompts = ["alpha beta", "gamma", "delta epsilon zeta"]
    together = engine.generate(prompts, [greedy(6)] * 3)
    alone = [engine.generate([p], [greedy(6)])[0] for p in prompts]
    for t, a in zip(together, alone):
        assert t["token_ids"] == a["token_ids"]


def test_max_tokens_respected(engine):
    [result] = engine.generate(["count tokens"], [greedy(3)])
    assert result["num_tokens"] <= 3


def test_concurrent_submission_from_threads(engine):
    results = {}

    def worker(i):
        results[i] = engine.generate([f"prompt {i}"], [greedy(5)])[0]

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert len(results) == 6
    assert all(r["num_tokens"] >= 1 for r in results.values())


def test_stats_surface(engine):
    engine.generate(["stats probe"], [greedy(2)])
    stats = engine.get_stats()
    assert stats["prefills"] >= 1
    assert stats["steps"] >= 1
    assert stats["scheduler"]["finished"] >= 1
    assert stats["kv_token_capacity"] > 0
    assert stats["mesh"]["tp"] == 1


def test_device_health(engine):
    health = engine.device_health()
    assert health["alive"] is True
    assert health["num_devices"] == 1


def test_long_generation_crosses_pages(engine):
    """page_size=4: a 20-token generation crosses several page boundaries."""
    [result] = engine.generate(["page crossing probe"], [greedy(20)])
    if result["finish_reason"] == "length":
        assert result["num_tokens"] == 20 or result["num_tokens"] >= 1


def test_preemption_preserves_greedy_output():
    """A pool small enough to force preemption must still produce exactly
    the same greedy tokens (recompute correctness)."""
    baseline_core = EngineCore(
        tiny_config(kv_num_pages=64), devices=jax.devices()[:1]
    )
    baseline_core.start()
    prompts = ["preempt probe one", "preempt probe two", "preempt pr three"]
    try:
        expect = baseline_core.generate(prompts, [greedy(10)] * 3)
    finally:
        baseline_core.stop()

    # 14 usable pages; 3 seqs × (prompt ~2 pages + 10 tokens) ≈ 15+ pages
    tight_core = EngineCore(
        tiny_config(kv_num_pages=15), devices=jax.devices()[:1]
    )
    tight_core.start()
    try:
        got = tight_core.generate(prompts, [greedy(10)] * 3)
        assert tight_core.scheduler.total_preemptions >= 1
        for e, g in zip(expect, got):
            assert e["token_ids"] == g["token_ids"]
    finally:
        tight_core.stop()


def test_engine_queue_full_fails_cleanly():
    core = EngineCore(tiny_config(), devices=jax.devices()[:1])
    # engine NOT started: fill the queue beyond max_queue_size
    try:
        seqs = [
            core.submit_tokens([3, 4, 5], greedy(2)) for _ in range(20)
        ]
        core.start()
        for seq in seqs:
            seq.done_event.wait(timeout=120)
        failed = [s for s in seqs if s.error is not None]
        ok = [s for s in seqs if s.error is None]
        assert len(ok) == 16  # max_queue_size
        assert all("queue full" in str(s.error) for s in failed)
    finally:
        core.stop()


def test_streaming_callback_order(engine):
    tokens = []
    seq = engine.submit_prompt(
        "stream probe", greedy(5), stream_cb=tokens.append
    )
    seq.done_event.wait(timeout=120)
    assert tokens == seq.generated_ids
