"""EngineCore integration tests: the full continuous-batching loop on a CPU
device with the tiny dense model (SURVEY.md section 4: CPU-backed jax tests
for scheduler/engine logic)."""

import asyncio
import threading

import numpy as np
import pytest

import jax

from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.config import load_config
from vgate_tpu.runtime.engine_core import EngineCore


def tiny_config(**tpu_overrides):
    tpu = {
        "dp": 1,
        "tp": 1,
        "ep": 1,
        "sp": 1,
        "kv_num_pages": 64,
        "kv_page_size": 4,
        "max_batch_slots": 4,
        "prefill_buckets": [8, 16, 32],
        "use_pallas": False,
    }
    tpu.update(tpu_overrides)
    return load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu=tpu,
        scheduler={"max_queue_size": 16},
        logging={"level": "WARNING"},
    )


@pytest.fixture(scope="module")
def engine():
    core = EngineCore(tiny_config(), devices=jax.devices()[:1])
    core.start()
    yield core
    core.stop()


def greedy(max_tokens=8):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0)


def test_generate_single(engine):
    [result] = engine.generate(["hello world"], [greedy(6)])
    assert result["num_tokens"] >= 1
    assert result["num_tokens"] <= 6
    assert result["finish_reason"] in ("stop", "length")
    assert result["metrics"]["ttft"] > 0
    assert isinstance(result["text"], str)


def test_generate_is_deterministic_greedy(engine):
    [a] = engine.generate(["determinism probe"], [greedy(8)])
    [b] = engine.generate(["determinism probe"], [greedy(8)])
    assert a["token_ids"] == b["token_ids"]


def test_generate_batch_matches_single(engine):
    """Continuous batching must not change greedy results: running three
    prompts together equals running each alone."""
    prompts = ["alpha beta", "gamma", "delta epsilon zeta"]
    together = engine.generate(prompts, [greedy(6)] * 3)
    alone = [engine.generate([p], [greedy(6)])[0] for p in prompts]
    for t, a in zip(together, alone):
        assert t["token_ids"] == a["token_ids"]


def test_max_tokens_respected(engine):
    [result] = engine.generate(["count tokens"], [greedy(3)])
    assert result["num_tokens"] <= 3


def test_concurrent_submission_from_threads(engine):
    results = {}

    def worker(i):
        results[i] = engine.generate([f"prompt {i}"], [greedy(5)])[0]

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert len(results) == 6
    assert all(r["num_tokens"] >= 1 for r in results.values())


def test_stats_surface(engine):
    engine.generate(["stats probe"], [greedy(2)])
    stats = engine.get_stats()
    assert stats["prefills"] >= 1
    assert stats["steps"] >= 1
    assert stats["scheduler"]["finished"] >= 1
    assert stats["kv_token_capacity"] > 0
    assert stats["mesh"]["tp"] == 1


def test_device_health(engine):
    health = engine.device_health()
    assert health["alive"] is True
    assert health["num_devices"] == 1


def test_long_generation_crosses_pages(engine):
    """page_size=4: a 20-token generation crosses several page boundaries."""
    [result] = engine.generate(["page crossing probe"], [greedy(20)])
    if result["finish_reason"] == "length":
        assert result["num_tokens"] == 20 or result["num_tokens"] >= 1


def test_preemption_preserves_greedy_output():
    """Recompute correctness: a preempted-and-resumed sequence must produce
    exactly what a fresh request for its folded prompt would produce.

    The assertion deliberately replays the victim inside the SAME engine
    (same compiled programs).  Comparing against a *differently shaped*
    engine (e.g. a bigger KV pool, or per-step instead of chunked decode)
    is not bitwise-stable: XLA emits different programs and random-init
    logits sit close enough to ties that greedy argmax can legitimately
    flip on ulp-level differences.  What preemption must guarantee is that
    recompute == fresh-restart-with-the-folded-prompt, and that is exact.
    """
    # 14 usable pages; 3 seqs × (prompt ~2 pages + 10 tokens) ≈ 15+ pages.
    # decode_chunk=1 keeps every decode step in the SAME compiled program
    # regardless of batch composition — with larger chunks the victim's
    # resumed steps can run in a different chunk-length program than the
    # solo replay walks, reintroducing the ulp hazard described above.
    tight_core = EngineCore(
        tiny_config(kv_num_pages=15, decode_chunk=1),
        devices=jax.devices()[:1],
    )
    tight_core.start()
    prompts = ["preempt probe one", "preempt probe two", "preempt pr three"]
    try:
        seqs = [tight_core.submit_prompt(p, greedy(10)) for p in prompts]
        for seq in seqs:
            assert seq.done_event.wait(timeout=300)
        assert tight_core.scheduler.total_preemptions >= 1
        for seq in seqs:
            assert seq.num_output_tokens == 10
            assert seq.finish_reason == "length"

        victims = [s for s in seqs if s.preempt_count >= 1]
        assert victims, "preemption happened but no victim recorded"
        seq = victims[0]
        folded = seq.num_prompt_tokens - seq.orig_prompt_len
        assert 0 < folded < 10  # preempted mid-generation
        # the folded prefix is exactly the tokens generated pre-preemption
        assert (
            seq.prompt_ids[seq.orig_prompt_len:]
            == seq.generated_ids[:folded]
        )

        # replay: fresh request = folded prompt, budget = remaining tokens.
        # The pool is empty now, so the replay prefills+decodes through the
        # same programs the recompute path used -> must match exactly.
        replay = tight_core.submit_tokens(
            list(seq.prompt_ids), greedy(10 - folded)
        )
        assert replay.done_event.wait(timeout=300)
        assert replay.generated_ids == seq.generated_ids[folded:]
    finally:
        tight_core.stop()


def test_decode_signature_includes_preempt_epoch(engine):
    """A victim re-admitted into the same freed slot with the same page
    count must NOT match the pre-preemption signature cache — its device
    tokens/positions are stale (advisor finding r1: dispatching against
    them corrupts the sequence silently)."""
    from vgate_tpu.runtime.sequence import Sequence

    seq = Sequence(prompt_ids=[1, 2, 3], params=greedy(4))
    seq.slot = 0
    seq.pages = [1, 2]
    sig_before = engine._decode_signature([seq])

    seq.output_ids = [7]
    seq.reset_for_recompute()
    # re-admission lands it back in the same slot with an identical
    # page-count footprint (horizon-inflated count == pre-preemption count)
    seq.slot = 0
    seq.pages = [1, 2]
    assert engine._decode_signature([seq]) != sig_before


def test_preemption_under_chunked_pipeline_is_clean():
    """Preemption while chunks are in flight (decode_chunk>1, pipeline 2):
    every sequence still finishes with its exact budget and no pages leak.
    Exercises the signature-cache invalidation paths in _tick.

    min_tokens pins the full 10-token budget: on random-init weights
    greedy argmax occasionally lands on EOS mid-generation, which used
    to flip finish_reason to "stop" under full-suite ordering (flaky
    since PR 12) — the invariant under test is preemption cleanliness
    (exact budget, zero leaks), not where a random model stops."""
    core = EngineCore(
        tiny_config(kv_num_pages=15, decode_chunk=4, decode_pipeline=2),
        devices=jax.devices()[:1],
    )
    core.start()
    try:
        prompts = ["pipeline one", "pipeline two", "pipeline number three"]
        params = [
            SamplingParams(max_tokens=10, min_tokens=10, temperature=0.0)
            for _ in prompts
        ]
        seqs = [
            core.submit_prompt(p, sp) for p, sp in zip(prompts, params)
        ]
        for seq in seqs:
            assert seq.done_event.wait(timeout=300)
        assert core.scheduler.total_preemptions >= 1
        for seq in seqs:
            assert seq.num_output_tokens == 10
            assert seq.finish_reason == "length"
        stats = core.get_stats()["scheduler"]
        assert stats["running"] == 0
        assert stats["used_pages"] == 0
    finally:
        core.stop()


def test_decode_flows_during_prefill_burst():
    """With prefill_admit_limit set, a burst of new prompts must not stall
    a resident decoding sequence: its tokens keep arriving interleaved with
    the burst's first tokens (VERDICT r1 item 2 'done' criterion)."""
    import time as _time

    core = EngineCore(
        tiny_config(
            max_batch_slots=16,
            kv_num_pages=256,
            decode_chunk=4,
            prefill_admit_limit=1,
        ),
        devices=jax.devices()[:1],
    )
    core.start()
    events = []  # (kind, t) appended from engine thread callbacks
    try:
        long_seq = core.submit_prompt(
            "resident decoder", greedy(48),
            stream_cb=lambda tok: events.append(
                ("decode", _time.perf_counter())
            ),
        )
        # wait until the resident sequence is producing
        deadline = _time.perf_counter() + 120
        while not events and _time.perf_counter() < deadline:
            _time.sleep(0.01)
        assert events, "resident sequence never started"

        burst = []
        for i in range(8):
            first_done = []

            def cb(tok, first_done=first_done):
                if not first_done:
                    first_done.append(True)
                    events.append(("first", _time.perf_counter()))

            burst.append(
                core.submit_prompt(f"burst prompt {i}", greedy(2), cb)
            )
        for seq in burst:
            assert seq.done_event.wait(timeout=300)
        assert long_seq.done_event.wait(timeout=300)

        firsts = [t for kind, t in events if kind == "first"]
        assert len(firsts) == 8
        window = [
            kind for kind, t in events
            if min(firsts) < t < max(firsts)
        ]
        assert "decode" in window, (
            "resident sequence made no progress during the prefill burst: "
            f"{events}"
        )
    finally:
        core.stop()


def test_engine_queue_full_fails_cleanly():
    core = EngineCore(tiny_config(), devices=jax.devices()[:1])
    # engine NOT started: fill the queue beyond max_queue_size
    try:
        seqs = [
            core.submit_tokens([3, 4, 5], greedy(2)) for _ in range(20)
        ]
        core.start()
        for seq in seqs:
            seq.done_event.wait(timeout=120)
        failed = [s for s in seqs if s.error is not None]
        ok = [s for s in seqs if s.error is None]
        assert len(ok) == 16  # max_queue_size
        assert all("queue full" in str(s.error) for s in failed)
    finally:
        core.stop()


def test_streaming_callback_order(engine):
    tokens = []
    seq = engine.submit_prompt(
        "stream probe", greedy(5), stream_cb=tokens.append
    )
    seq.done_event.wait(timeout=120)
    assert tokens == seq.generated_ids


def test_chunk_overshoot_discarded(engine):
    """decode_chunk=8 with max_tokens that's not a chunk multiple: the
    overshoot steps the chunk ran past the budget must be discarded."""
    for budget in (3, 5, 9):
        [r] = engine.generate(["overshoot probe"], [greedy(budget)])
        assert r["num_tokens"] <= budget
        assert len(r["token_ids"]) == r["num_tokens"]


def test_eos_mid_chunk_truncates():
    """A sequence whose EOS lands mid-chunk stops there; trailing steps of
    the chunk are discarded and the slot is freed."""
    core = EngineCore(tiny_config(decode_chunk=8), devices=jax.devices()[:1])
    core.start()
    try:
        # probe an unconstrained greedy run to learn the token stream
        [probe] = core.generate(["eos mid chunk probe"], [greedy(12)])
        assert probe["num_tokens"] >= 4
        # declare the 3rd generated token to be EOS and rerun
        fake_eos = probe["token_ids"][2]
        real_eos = core.tokenizer.eos_id
        core.tokenizer.eos_id = fake_eos
        try:
            [r] = core.generate(["eos mid chunk probe"], [greedy(12)])
        finally:
            core.tokenizer.eos_id = real_eos
        first_eos = probe["token_ids"].index(fake_eos)
        assert r["finish_reason"] == "stop"
        assert r["token_ids"] == probe["token_ids"][: first_eos + 1]
        assert not core.scheduler.running
    finally:
        core.stop()


def test_decode_chunk_ladder_compiles_powers_of_two():
    core = EngineCore(
        tiny_config(decode_chunk=8), devices=jax.devices()[:1]
    )
    core.start()
    try:
        core.generate(["ladder probe"], [greedy(16)])
        # keys are (chunk_len, penalties_active, min_tokens_width)
        lens = {k[0] for k in core._compiled_chunks}
        assert lens <= {1, 2, 4, 8}
        assert max(lens) == 8
        assert all(k[1] is False and k[2] is None
                   for k in core._compiled_chunks)
    finally:
        core.stop()


def test_page_growth_does_not_rebuild_state():
    """Pages growing mid-generation (same membership) must refresh only the
    page-table upload, not drain the pipeline and rebuild device state —
    otherwise the depth-2 pipeline collapses at every page boundary."""
    core = EngineCore(
        tiny_config(decode_chunk=4, decode_pipeline=2),
        devices=jax.devices()[:1],
    )
    core.start()
    try:
        # 40 tokens across page_size=4 -> ~10 page-boundary crossings
        [r] = core.generate(["rebuild probe"], [greedy(40)])
        assert r["num_tokens"] >= 30
        # one rebuild at admission; page growth must not add more
        assert core.total_state_rebuilds == 1
    finally:
        core.stop()


def test_moe_engine_end_to_end_expert_parallel():
    """The MoE decoder serves through the full continuous-batching engine
    with experts sharded over the ep axis (SURVEY.md section 2.2: EP is a
    first-class strategy the reference lacks entirely)."""
    n = min(2, jax.device_count())
    config = load_config(
        model={
            "model_id": "tiny-moe",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": n, "sp": 1,
            "num_devices": n,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 2, "prefill_buckets": [16],
            "use_pallas": False,
        },
        scheduler={"max_queue_size": 8},
        logging={"level": "WARNING"},
    )
    core = EngineCore(config, devices=jax.devices()[:n])
    core.start()
    try:
        results = core.generate(
            ["moe serving probe", "second expert route"],
            [greedy(6)] * 2,
        )
        for r in results:
            assert r["num_tokens"] >= 1
            assert r["finish_reason"] in ("stop", "length")
        assert core.get_stats()["mesh"]["ep"] == n
    finally:
        core.stop()


def test_sp_x_tp_end_to_end():
    """sp x tp (the natural multi-chip long-context mesh, e.g. v5e-8 as
    sp4 x tp2): the sp shard bodies run per (sp, tp) shard on local
    heads (r4: tp-aware specs in parallel/sp_decode.py _tp_axis).
    Greedy output must be token-identical to the single-device engine."""
    if jax.device_count() < 4:
        pytest.skip("needs >=4 devices")

    def cfg(sp, tp, n_dev):
        return load_config(
            model={"model_id": "tiny-dense", "engine_type": "jax_tpu",
                   "dtype": "float32", "max_model_len": 64},
            tpu={"dp": 1, "tp": tp, "ep": 1, "sp": sp,
                 "num_devices": n_dev,
                 "kv_num_pages": 64, "kv_page_size": 4,
                 "max_batch_slots": 2, "prefill_buckets": [16, 32],
                 "use_pallas": False},
            scheduler={"max_queue_size": 8},
            logging={"level": "WARNING"},
        )

    prompt_ids = [5 + (i % 21) for i in range(26)]
    outs = []
    for sp, tp, n_dev in ((1, 1, 1), (2, 2, 4)):
        core = EngineCore(cfg(sp, tp, n_dev), devices=jax.devices()[:n_dev])
        core.start()
        try:
            seq = core.submit_tokens(prompt_ids, greedy(8))
            assert seq.done_event.wait(300)
            outs.append(list(seq.generated_ids))
        finally:
            core.stop()
    assert outs[0] == outs[1]


def test_moe_ep_x_sp_end_to_end():
    """ep x sp composes: the sp shard_map covers only attention + the
    KV write, so the MoE FFN's ep dispatch stays under jit auto
    sharding.  Greedy output must be token-identical to the ep=1/sp=1
    engine."""
    if jax.device_count() < 4:
        pytest.skip("needs >=4 devices")

    def cfg(ep, sp, n_dev):
        return load_config(
            model={
                "model_id": "tiny-moe",
                "engine_type": "jax_tpu",
                "dtype": "float32",
                "max_model_len": 64,
            },
            tpu={
                "dp": 1, "tp": 1, "ep": ep, "sp": sp,
                "num_devices": n_dev,
                "kv_num_pages": 64, "kv_page_size": 4,
                "max_batch_slots": 2, "prefill_buckets": [16, 32],
                "use_pallas": False,
            },
            scheduler={"max_queue_size": 8},
            logging={"level": "WARNING"},
        )

    prompt_ids = [3 + (i % 19) for i in range(24)]
    outs = []
    for ep, sp, n_dev in ((1, 1, 1), (2, 2, 4)):
        core = EngineCore(cfg(ep, sp, n_dev), devices=jax.devices()[:n_dev])
        core.start()
        try:
            seq = core.submit_tokens(prompt_ids, greedy(8))
            assert seq.done_event.wait(300)
            outs.append(list(seq.generated_ids))
            if sp > 1:
                assert "sp" in str(core.k_pages.sharding.spec)
        finally:
            core.stop()
    assert outs[0] == outs[1]


def test_sp_engine_long_prefill_end_to_end():
    """Sequence-parallel serving: with sp=2 the engine's prefill runs ring
    attention over the sp axis (SURVEY.md section 5.7 long-context path) and
    decode continues normally."""
    if jax.device_count() < 2:
        pytest.skip("needs >=2 devices")
    config = load_config(
        model={
            "model_id": "tiny-dense",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 2,
            "num_devices": 2,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 2, "prefill_buckets": [16, 32],
            "use_pallas": False,
        },
        scheduler={"max_queue_size": 8},
        logging={"level": "WARNING"},
    )
    core = EngineCore(config, devices=jax.devices()[:2])
    core.start()
    try:
        # a prompt long enough to span several sp shards of the 32 bucket
        long_prompt = " ".join(["ring"] * 24)
        [r] = core.generate([long_prompt], [greedy(8)])
        assert r["num_tokens"] >= 1
        assert core.get_stats()["mesh"]["sp"] == 2
    finally:
        core.stop()


def test_sp_engine_gemma2_sliding_window():
    """Gemma-2 (sliding-window + softcap + sandwich norms) under sp=2:
    ring prefill composes the per-layer window mask with the block-
    position masks, so greedy output must be token-identical to the
    sp=1 engine (VERDICT r2 next-10: the guard is gone)."""
    if jax.device_count() < 2:
        pytest.skip("needs >=2 devices")

    def gemma_cfg(sp, n_dev):
        return load_config(
            model={
                "model_id": "tiny-gemma2",
                "engine_type": "jax_tpu",
                "dtype": "float32",
                "max_model_len": 64,
            },
            tpu={
                "dp": 1, "tp": 1, "ep": 1, "sp": sp,
                "num_devices": n_dev,
                "kv_num_pages": 64, "kv_page_size": 4,
                "max_batch_slots": 2, "prefill_buckets": [16, 32],
                "use_pallas": False,
            },
            scheduler={"max_queue_size": 8},
            logging={"level": "WARNING"},
        )

    # prompt long enough to cross the 8-token sliding window AND span
    # both sp shards of the 32 bucket
    prompt_ids = [2 + (i % 37) for i in range(30)]
    outs = []
    for sp, n_dev in ((1, 1), (2, 2)):
        core = EngineCore(gemma_cfg(sp, n_dev), devices=jax.devices()[:n_dev])
        core.start()
        try:
            seq = core.submit_tokens(prompt_ids, greedy(10))
            assert seq.done_event.wait(300)
            outs.append(list(seq.generated_ids))
        finally:
            core.stop()
    assert outs[0] == outs[1]


def test_sp_decode_token_identical_and_capacity_sharded():
    """Decode now runs sp-SHARDED (VERDICT r2 partial-22): greedy output
    across multiple decode page boundaries must be token-identical to
    the sp=1 engine, and the KV pool must actually shard over sp (the
    long-context capacity relief) with per-shard trash pages reserved."""
    if jax.device_count() < 4:
        pytest.skip("needs >=4 devices")

    def cfg(sp, n_dev):
        return load_config(
            model={
                "model_id": "tiny-dense",
                "engine_type": "jax_tpu",
                "dtype": "float32",
                "max_model_len": 64,
            },
            tpu={
                "dp": 1, "tp": 1, "ep": 1, "sp": sp,
                "num_devices": n_dev,
                "kv_num_pages": 64, "kv_page_size": 4,
                "max_batch_slots": 2, "prefill_buckets": [16],
                "use_pallas": False,
            },
            scheduler={"max_queue_size": 8},
            logging={"level": "WARNING"},
        )

    prompt_ids = [3 + (i % 29) for i in range(14)]
    outs = []
    for sp, n_dev in ((1, 1), (4, 4)):
        core = EngineCore(cfg(sp, n_dev), devices=jax.devices()[:n_dev])
        if sp > 1:
            # pool sharded over sp + one reserved trash page per shard
            assert core.allocator.reserved == frozenset({0, 16, 32, 48})
            from jax.sharding import PartitionSpec as P

            assert core.k_pages.sharding.spec == P(
                None, None, "sp", None, None
            )
        core.start()
        try:
            # 20 generated tokens: crosses several 4-token page
            # boundaries, so decode allocates pages on multiple shards
            seq = core.submit_tokens(prompt_ids, greedy(20))
            assert seq.done_event.wait(300)
            outs.append(list(seq.generated_ids))
        finally:
            core.stop()
    assert outs[0] == outs[1]


def test_sp_bucket_divisibility_enforced():
    config = load_config(
        model={"model_id": "tiny-dense", "engine_type": "jax_tpu",
               "dtype": "float32", "max_model_len": 60},
        tpu={"dp": 1, "tp": 1, "ep": 1, "sp": 4, "num_devices": 4,
             "kv_num_pages": 64, "kv_page_size": 2,
             "max_batch_slots": 2, "prefill_buckets": [6],
             "use_pallas": False},
        logging={"level": "WARNING"},
    )
    if jax.device_count() < 4:
        pytest.skip("needs >=4 devices")
    with pytest.raises(ValueError, match="not divisible by sp"):
        EngineCore(config, devices=jax.devices()[:4])


def test_stop_string_truncates(engine):
    """A stop string terminates the sequence with finish_reason "stop" and
    the final text is truncated before the match (VERDICT r1 missing-4; the
    reference passes stop to vLLM, vgate/backends/vllm_backend.py:39-46)."""
    # probe the greedy stream to learn its text, then pick a mid-text
    # substring as the stop string
    [probe] = engine.generate(["stop string probe"], [greedy(10)])
    text = probe["text"]
    assert len(text) >= 4
    mid = len(text) // 2
    stop = text[mid : mid + 2]
    prefix = text[:mid]
    assert stop and stop not in prefix  # make the probe site unambiguous
    [r] = engine.generate(
        ["stop string probe"],
        [SamplingParams(max_tokens=10, temperature=0.0, stop=[stop])],
    )
    assert r["finish_reason"] == "stop"
    assert stop not in r["text"]
    assert r["text"] == text[: text.index(stop)]


def test_stop_string_mid_chunk_frees_slot():
    """Stop detection happens at chunk readback; the slot must be freed."""
    core = EngineCore(tiny_config(decode_chunk=8), devices=jax.devices()[:1])
    core.start()
    try:
        [probe] = core.generate(["stop chunk probe"], [greedy(12)])
        stop = probe["text"][1:3]
        [r] = core.generate(
            ["stop chunk probe"],
            [SamplingParams(max_tokens=12, temperature=0.0, stop=[stop])],
        )
        assert r["finish_reason"] == "stop"
        assert stop not in r["text"]
        assert not core.scheduler.running
    finally:
        core.stop()


def test_seed_reproducible_across_runs(engine):
    """Same seed at temperature>0 => identical tokens, independent of the
    engine's global step counter (the key is a function of (seed, token
    index) only)."""
    sp = lambda: SamplingParams(max_tokens=8, temperature=1.0, seed=1234)
    [a] = engine.generate(["seeded sampling probe"], [sp()])
    # perturb the global step counter with an unrelated request
    engine.generate(["interleaved other work"], [greedy(4)])
    [b] = engine.generate(["seeded sampling probe"], [sp()])
    assert a["token_ids"] == b["token_ids"]


def test_seed_independent_of_batch_composition(engine):
    """A seeded request gives the same tokens alone or batched with
    unseeded neighbours (per-slot keys, not one key per step)."""
    sp = SamplingParams(max_tokens=6, temperature=1.0, seed=77)
    [alone] = engine.generate(["batch seeded probe"], [sp])
    batched = engine.generate(
        ["noise one", "batch seeded probe", "noise two"],
        [
            SamplingParams(max_tokens=6, temperature=1.0),
            SamplingParams(max_tokens=6, temperature=1.0, seed=77),
            SamplingParams(max_tokens=6, temperature=1.0),
        ],
    )
    assert batched[1]["token_ids"] == alone["token_ids"]


def test_different_seeds_diverge(engine):
    """Different seeds at temperature>0 should (overwhelmingly) differ."""
    outs = []
    for seed in (1, 2, 3):
        [r] = engine.generate(
            ["divergence probe"],
            [SamplingParams(max_tokens=8, temperature=1.0, seed=seed)],
        )
        outs.append(tuple(r["token_ids"]))
    assert len(set(outs)) > 1


def test_gemma2_engine_end_to_end_across_window():
    """The Gemma-2 family (sliding-window + softcap attention, sandwich
    norms, tied embeddings) serves through the full continuous-batching
    engine, generating past the sliding window (8) so decode steps beyond
    the window exercise the local-attention mask over paged KV."""
    config = load_config(
        model={
            "model_id": "tiny-gemma2",
            "engine_type": "jax_tpu",
            "dtype": "float32",
            "max_model_len": 64,
        },
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1,
            "num_devices": 1,
            "kv_num_pages": 64, "kv_page_size": 4,
            "max_batch_slots": 2, "prefill_buckets": [8],
            # use_pallas left ON: the kernels take Gemma's
            # window/softcap/scale natively, so the engine keeps them on
            # wherever the platform supports Pallas (TPU)
            "use_pallas": True,
        },
        scheduler={"max_queue_size": 8},
        logging={"level": "WARNING"},
    )
    core = EngineCore(config, devices=jax.devices()[:1])
    # kernels on real TPU, jnp twins elsewhere — platform is the only gate
    assert core.use_pallas == (jax.devices()[0].platform == "tpu")
    core.start()
    try:
        results = core.generate(
            ["sliding window probe", "second gemma request"],
            [greedy(16)] * 2,  # prompt+output crosses the 8-token window
        )
        for r in results:
            assert r["num_tokens"] >= 1
            assert r["finish_reason"] in ("stop", "length")
            assert np.all(np.isfinite(r.get("ttft", 0.0)))
    finally:
        core.stop()


def test_pp_engine_gemma2_sliding_window():
    """Gemma-2 (sliding-window + softcap + embed scale) through the
    pipeline relay: per-layer windows thread the stage scan and
    softcap/scale ride the attention partials (parallel/pipeline.py,
    r4 — the r3 rejection is gone).  Greedy output must be
    token-identical to the pp=1 engine."""
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")

    def cfg(pp, n_dev):
        return load_config(
            model={
                "model_id": "tiny-gemma2",
                "engine_type": "jax_tpu",
                "dtype": "float32",
                "max_model_len": 64,
            },
            tpu={
                "dp": 1, "tp": 1, "ep": 1, "sp": 1, "pp": pp,
                "num_devices": n_dev,
                "kv_num_pages": 64, "kv_page_size": 4,
                "max_batch_slots": 2, "prefill_buckets": [8, 32],
                "use_pallas": False,
            },
            scheduler={"max_queue_size": 8},
            logging={"level": "WARNING"},
        )

    # prompt crosses the tiny-gemma2 sliding window so the local-layer
    # masks matter, and decode runs well past it
    prompt_ids = [2 + (i % 37) for i in range(30)]
    outs = []
    for pp, n_dev in ((1, 1), (2, 2)):
        core = EngineCore(cfg(pp, n_dev), devices=jax.devices()[:n_dev])
        core.start()
        try:
            seq = core.submit_tokens(prompt_ids, greedy(10))
            assert seq.done_event.wait(300)
            outs.append(list(seq.generated_ids))
        finally:
            core.stop()
    assert outs[0] == outs[1]


def test_stop_token_ids_finish(engine):
    """A token in stop_token_ids ends the sequence with finish_reason
    "stop" (the id-level sibling of stop strings)."""
    # discover what the model greedily emits, then stop on its 3rd token
    [base] = engine.generate(["stop id probe"], [greedy(8)])
    assert len(base["token_ids"]) >= 4
    target = base["token_ids"][2]
    [stopped] = engine.generate(
        ["stop id probe"],
        [SamplingParams(max_tokens=8, temperature=0.0,
                        stop_token_ids=[target])],
    )
    assert stopped["finish_reason"] == "stop"
    assert stopped["token_ids"][: 3] == base["token_ids"][: 3]
    assert len(stopped["token_ids"]) == 3


# ----------------------------------------------------- chunked prefill

def chunked_cfg(prefill_chunk, **model_overrides):
    model = {
        "model_id": "tiny-dense",
        "engine_type": "jax_tpu",
        "dtype": "float32",
        "max_model_len": 64,
    }
    model.update(model_overrides)
    return load_config(
        model=model,
        tpu={
            "dp": 1, "tp": 1, "ep": 1, "sp": 1, "num_devices": 1,
            "kv_num_pages": 128, "kv_page_size": 4,
            "max_batch_slots": 2, "prefill_buckets": [8, 16],
            "use_pallas": False,
            "prefill_chunk": prefill_chunk,
        },
        scheduler={"max_queue_size": 8},
        logging={"level": "WARNING"},
    )


def test_chunked_prefill_token_identical_to_whole_prompt():
    """A 40-token prompt with a 16-token chunk cap runs three serial
    suffix passes (16+16+8); greedy output must be token-identical to
    the unchunked engine, seeded sampled output too (the final chunk
    carries the real sampling params)."""
    prompt_ids = [3 + (i % 31) for i in range(40)]
    outs = []
    for chunk in (0, 16):
        core = EngineCore(chunked_cfg(chunk), devices=jax.devices()[:1])
        if chunk:
            # ladder capped at the chunk size
            assert core.scheduler.prefill_buckets[-1] == chunk
        core.start()
        try:
            g = core.submit_tokens(prompt_ids, greedy(10))
            s = core.submit_tokens(
                prompt_ids[::-1],
                SamplingParams(max_tokens=8, temperature=0.8, seed=13),
            )
            assert g.done_event.wait(300) and s.done_event.wait(300)
            outs.append(
                (list(g.generated_ids), list(s.generated_ids))
            )
        finally:
            core.stop()
    assert outs[0] == outs[1]


def test_chunked_prefill_with_prefix_cache_hit():
    """Chunked prefill composes with automatic prefix caching: the
    second identical prompt starts its chunks after the cached pages
    and produces identical greedy output."""
    cfg = chunked_cfg(16)
    assert cfg.tpu.prefix_cache
    core = EngineCore(cfg, devices=jax.devices()[:1])
    core.start()
    try:
        prompt_ids = [5 + (i % 17) for i in range(40)]
        a = core.submit_tokens(prompt_ids, greedy(8))
        assert a.done_event.wait(300)
        hits_before = core.scheduler.total_prefix_hit_tokens
        b = core.submit_tokens(prompt_ids, greedy(8))
        assert b.done_event.wait(300)
        assert list(a.generated_ids) == list(b.generated_ids)
        assert core.scheduler.total_prefix_hit_tokens > hits_before
        stats = core.scheduler.get_stats()
        assert stats["running"] == 0
    finally:
        core.stop()


def test_chunked_prefill_rejects_pp():
    """pp still reshapes the prompt pass incompatibly (sp no longer
    does: chunks ride the sp-capable suffix program, RESULTS_r4)."""
    if jax.device_count() < 2:
        pytest.skip("needs 2 devices")
    cfg = load_config(
        model={"model_id": "tiny-dense", "engine_type": "jax_tpu",
               "dtype": "float32", "max_model_len": 64},
        tpu={"dp": 1, "tp": 1, "ep": 1, "sp": 1, "pp": 2,
             "num_devices": 2,
             "kv_num_pages": 64, "kv_page_size": 4,
             "max_batch_slots": 2, "prefill_buckets": [16],
             "use_pallas": False, "prefill_chunk": 16},
        logging={"level": "WARNING"},
    )
    with pytest.raises(ValueError, match="prefill_chunk"):
        EngineCore(cfg, devices=jax.devices()[:2])


def _sp_prefix_cfg(sp, n_dev, prefill_chunk=0):
    return load_config(
        model={"model_id": "tiny-dense", "engine_type": "jax_tpu",
               "dtype": "float32", "max_model_len": 64},
        tpu={"dp": 1, "tp": 1, "ep": 1, "sp": sp, "num_devices": n_dev,
             "kv_num_pages": 64, "kv_page_size": 4,
             "max_batch_slots": 2, "prefill_buckets": [16, 32],
             "use_pallas": False, "prefill_chunk": prefill_chunk},
        scheduler={"max_queue_size": 8},
        logging={"level": "WARNING"},
    )


def test_sp_prefix_cache_hit_end_to_end():
    """Prefix caching now composes with sp (VERDICT r3 next-7): on an
    sp=2 pool the second identical prompt rides the sp-sharded suffix
    program (sp_suffix_attention_and_write), records a prefix hit, and
    produces output token-identical to the sp=1 engine."""
    if jax.device_count() < 2:
        pytest.skip("needs >=2 devices")
    prompt_ids = [7 + (i % 23) for i in range(28)]
    outs = []
    for sp, n_dev in ((1, 1), (2, 2)):
        cfg = _sp_prefix_cfg(sp, n_dev)
        core = EngineCore(cfg, devices=jax.devices()[:n_dev])
        assert core.prefix_cache_enabled
        core.start()
        try:
            a = core.submit_tokens(prompt_ids, greedy(8))
            assert a.done_event.wait(300)
            hits_before = core.scheduler.total_prefix_hit_tokens
            b = core.submit_tokens(prompt_ids, greedy(8))
            assert b.done_event.wait(300)
            assert list(a.generated_ids) == list(b.generated_ids)
            assert core.scheduler.total_prefix_hit_tokens > hits_before
            outs.append(list(b.generated_ids))
        finally:
            core.stop()
    assert outs[0] == outs[1]


def test_sp_chunked_prefill_end_to_end():
    """Chunked prefill under sp=2: long prompts run page-aligned suffix
    chunks through the sp-sharded suffix program; greedy output is
    token-identical to the sp=1 chunked engine."""
    if jax.device_count() < 2:
        pytest.skip("needs >=2 devices")
    prompt_ids = [3 + (i % 29) for i in range(44)]
    outs = []
    for sp, n_dev in ((1, 1), (2, 2)):
        core = EngineCore(
            _sp_prefix_cfg(sp, n_dev, prefill_chunk=16),
            devices=jax.devices()[:n_dev],
        )
        core.start()
        try:
            seq = core.submit_tokens(prompt_ids, greedy(8))
            assert seq.done_event.wait(300)
            outs.append(list(seq.generated_ids))
        finally:
            core.stop()
    assert outs[0] == outs[1]


# ------------------------------------------------------ client aborts

def test_abort_running_sequence_frees_resources():
    """request_abort on a RUNNING sequence: the engine finishes it with
    reason "abort" at its next tick, frees slot+pages, and co-resident
    sequences complete untouched."""
    core = EngineCore(
        tiny_config(decode_chunk=1), devices=jax.devices()[:1]
    )
    core.start()
    try:
        victim = core.submit_tokens([3] * 12, greedy(40))
        mate = core.submit_tokens([9] * 12, greedy(10))
        # cancel as soon as the first token lands (decode_chunk=1 on the
        # CPU-pinned test mesh steps in milliseconds, so the remaining
        # 39-token budget cannot complete inside this tight poll)
        import time as _t

        for _ in range(2000):
            if victim.num_output_tokens >= 1:
                break
            _t.sleep(0.005)
        assert victim.num_output_tokens >= 1
        victim.request_abort()
        assert victim.done_event.wait(120)
        assert victim.finish_reason == "abort"
        assert victim.num_output_tokens < 40  # stopped early
        assert mate.done_event.wait(300)
        assert mate.num_output_tokens == 10
        stats = core.scheduler.get_stats()
        assert stats["aborted"] == 1
        assert stats["running"] == 0
        assert stats["used_pages"] == 0
    finally:
        core.stop()


def test_abort_waiting_sequence_drops_at_queue_head():
    """A queued (not yet admitted) sequence whose client cancelled is
    dropped when it reaches the queue head, never prefilled."""
    core = EngineCore(
        tiny_config(max_batch_slots=1), devices=jax.devices()[:1]
    )
    core.start()
    try:
        runner = core.submit_tokens([3] * 8, greedy(8))
        queued = core.submit_tokens([5] * 8, greedy(8))
        queued.request_abort()
        assert queued.done_event.wait(300)
        assert queued.finish_reason == "abort"
        assert queued.num_output_tokens == 0
        assert runner.done_event.wait(300)
        assert runner.num_output_tokens == 8
        assert core.scheduler.get_stats()["aborted"] == 1
    finally:
        core.stop()


def test_stream_disconnect_aborts_sequence():
    """Closing the SSE token stream mid-generation (client disconnect)
    aborts the underlying sequence instead of decoding to completion."""
    import asyncio

    from vgate_tpu.backends.jax_backend import JaxTPUBackend

    backend = JaxTPUBackend()
    backend.load_model(tiny_config(decode_chunk=1, num_devices=1))
    try:
        async def run():
            agen = backend.stream_async(
                "stream abort probe",
                SamplingParams(max_tokens=40, temperature=0.0),
            )
            await agen.__anext__()  # first delta arrived
            await agen.aclose()  # client went away

        asyncio.run(run())
        core = backend.core
        deadline = 120
        import time as _t

        t0 = _t.perf_counter()
        while (
            core.scheduler.get_stats()["running"] > 0
            and _t.perf_counter() - t0 < deadline
        ):
            _t.sleep(0.05)
        stats = core.scheduler.get_stats()
        assert stats["running"] == 0
        assert stats["used_pages"] == 0
        assert stats["aborted"] == 1
    finally:
        backend.shutdown()


def test_pick_chunk_caps_under_admission_pressure():
    """With prompts waiting AND a free slot, the next decode chunk caps
    at decode_chunk/8 so the loop returns to admission quickly; with no
    free slot (or an empty queue) full-size chunks are kept."""
    from vgate_tpu.runtime.sequence import Sequence

    core = EngineCore(
        tiny_config(decode_chunk=32, max_batch_slots=2),
        devices=jax.devices()[:1],
    )
    try:
        seq = Sequence(prompt_ids=[1, 2, 3], params=greedy(40))
        seq.output_ids = [5]
        seq.generated_ids = [5]
        # idle queue: full chunk
        assert core._pick_chunk([seq]) == 32
        # waiting prompt + free slot: capped to decode_chunk/8 = 4
        core.scheduler.waiting.append(
            Sequence(prompt_ids=[7], params=greedy(4))
        )
        assert core._pick_chunk([seq]) == 4
        # waiting prompt but slots saturated: full chunk again
        core.scheduler.slots[0] = seq
        core.scheduler.slots[1] = Sequence(
            prompt_ids=[8], params=greedy(4)
        )
        assert core._pick_chunk([seq]) == 32
    finally:
        core.stop()


def test_stream_async_reports_usage():
    """The real engine's token stream delivers usage through on_usage
    (the OpenAI stream_options.include_usage plumbing)."""
    import asyncio

    from vgate_tpu.backends.jax_backend import JaxTPUBackend

    backend = JaxTPUBackend()
    backend.load_model(tiny_config(num_devices=1))
    try:
        seen = {}

        async def run():
            agen = backend.stream_async(
                "usage stream probe",
                SamplingParams(max_tokens=5, temperature=0.0),
                on_usage=lambda u: seen.update(u),
            )
            async for _ in agen:
                pass

        asyncio.run(run())
        assert seen["completion_tokens"] >= 1
        assert (
            seen["total_tokens"]
            == seen["prompt_tokens"] + seen["completion_tokens"]
        )
    finally:
        backend.shutdown()


def test_chunked_prefill_carries_logprobs():
    """The final chunk of a chunked prefill delegates to the suffix
    group, so a long prompt's request-level logprobs must come back
    aligned with every generated token."""
    core = EngineCore(chunked_cfg(16), devices=jax.devices()[:1])
    core.start()
    try:
        seq = core.submit_tokens(
            [3 + (i % 13) for i in range(40)],
            SamplingParams(
                max_tokens=6, temperature=0.0, logprobs=True,
                top_logprobs=3,
            ),
        )
        assert seq.done_event.wait(300)
        assert seq.num_output_tokens == len(seq.logprob_data) == 6
        entries = core.logprob_entries(seq)
        assert len(entries) == 6
        for e in entries:
            assert e["logprob"] <= 0.0
            assert len(e["top_logprobs"]) == 3
    finally:
        core.stop()


def test_engine_fatal_fails_inflight_and_rejects_new():
    """A fatal engine-loop error (SURVEY 5.3) must fail EVERY owed
    future — in-flight, waiting, and still-queued submissions — free
    the slots, and reject new submissions with "engine is dead": the
    containment contract the dp router builds on.  The fault is
    injected BEFORE submission so no finish race exists; the queued
    sequence exercises the submit-queue drain (a client blocked on it
    would otherwise hang forever)."""
    from vgate_tpu.runtime.sequence import SeqStatus

    core = EngineCore(tiny_config(), devices=jax.devices()[:1])
    core.start()
    try:
        boom = RuntimeError("injected loop fault")

        def bad_tick():
            raise boom

        core._tick = bad_tick
        core._wakeup.set()
        try:
            seq = core.submit_tokens([5, 9, 13, 17], greedy(40))
        except RuntimeError:
            seq = None  # loop died before the submit: rejected, correct
        if seq is not None:
            # queued (or admitted) before the loop died: the fatal path
            # must fail it — a hang here is the submit-queue-drain bug
            assert seq.done_event.wait(60)
            assert seq.status is SeqStatus.FAILED
            assert seq.error is boom
        assert all(s is None for s in core.scheduler.slots)
        with pytest.raises(RuntimeError, match="engine is dead"):
            core.submit_tokens([1, 2, 3], greedy(2))
    finally:
        core._fatal = None
        core.stop()


def test_submit_fatal_toctou_drain():
    """If the engine dies between submit_tokens' fatal check and its
    queue put, the fatal handler's drain has already run and will never
    see the new sequence — the post-put re-check must drain/fail it and
    raise instead of leaving the client hung on done_event (ADVICE r4,
    engine_core.py submit_tokens)."""
    from vgate_tpu.runtime.sequence import SeqStatus

    core = EngineCore(tiny_config(), devices=jax.devices()[:1])
    boom = RuntimeError("died mid-submit")
    real_put = core._submit_q.put

    def racing_put(seq):
        real_put(seq)
        core._fatal = boom  # the loop died right as the put landed

    core._submit_q.put = racing_put
    with pytest.raises(RuntimeError, match="engine is dead"):
        core.submit_tokens([1, 2, 3], greedy(2))
    assert core._submit_q.empty()
