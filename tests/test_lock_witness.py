"""Runtime lock-witness unit tests (ISSUE 15): zero-cost-off
construction, chain recording against the declared order's transitive
closure, reentrancy, strict mode, and the incrementally-written
report the drills assert on."""

import json
import os
import threading

import pytest

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)

import vgate_tpu.analysis.lock_order as lock_order
from vgate_tpu.analysis import witness
from vgate_tpu.analysis.witness import WitnessLock, named_lock


@pytest.fixture(autouse=True)
def _clean_witness(monkeypatch):
    witness.reset()
    yield
    witness.reset()


@pytest.fixture
def declared(monkeypatch):
    """Declared order A->B, B->C (closure implies A->C)."""
    monkeypatch.setattr(
        lock_order,
        "VGT_LOCK_ORDER",
        {
            "X.a_lock->X.b_lock": "test",
            "X.b_lock->X.c_lock": "test",
        },
    )
    monkeypatch.setattr(lock_order, "VGT_LOCK_ALIASES", {})
    witness.reset()  # drop the cached closure
    return monkeypatch


def _lk(name, reentrant=False):
    base = threading.RLock() if reentrant else threading.Lock()
    return WitnessLock(name, base)


def test_named_lock_is_plain_when_off(monkeypatch):
    monkeypatch.delenv("VGT_LOCK_WITNESS", raising=False)
    lk = named_lock("X.a_lock")
    assert not isinstance(lk, WitnessLock)
    # the plain lock works as a context manager
    with lk:
        pass
    rlk = named_lock("X.b_lock", reentrant=True)
    assert not isinstance(rlk, WitnessLock)
    with rlk:
        with rlk:
            pass


def test_named_lock_wraps_when_armed(monkeypatch):
    monkeypatch.setenv("VGT_LOCK_WITNESS", "1")
    lk = named_lock("X.a_lock")
    assert isinstance(lk, WitnessLock)


def test_declared_chain_is_clean(declared):
    a, b, c = _lk("X.a_lock"), _lk("X.b_lock"), _lk("X.c_lock")
    with a:
        with b:
            with c:
                pass
    rep = witness.report()
    assert rep["undeclared"] == []
    observed = {(e["outer"], e["inner"]) for e in rep["edges"]}
    # the chain witnesses the closure edge a->c too — implied by the
    # declared a->b->c, so still clean
    assert observed == {
        ("X.a_lock", "X.b_lock"),
        ("X.b_lock", "X.c_lock"),
        ("X.a_lock", "X.c_lock"),
    }
    witness.assert_clean()


def test_undeclared_inversion_is_caught(declared):
    a, b = _lk("X.a_lock"), _lk("X.b_lock")
    with b:
        with a:
            pass
    rep = witness.report()
    assert [(e["outer"], e["inner"]) for e in rep["undeclared"]] == [
        ("X.b_lock", "X.a_lock")
    ]
    assert rep["undeclared"][0]["chain"] == "X.b_lock->X.a_lock"
    with pytest.raises(AssertionError):
        witness.assert_clean()


def test_reentrant_reacquire_records_no_edge(declared):
    a = _lk("X.a_lock", reentrant=True)
    b = _lk("X.b_lock")
    with a:
        with b:
            with a:  # re-acquire of an already-held lock: no b->a edge
                pass
    assert witness.undeclared() == []


def test_strict_mode_raises_at_the_acquisition(declared):
    a = WitnessLock("X.a_lock", threading.Lock(), strict=True)
    b = WitnessLock("X.b_lock", threading.Lock(), strict=True)
    with pytest.raises(RuntimeError, match="undeclared lock order"):
        with b:
            with a:
                pass
    # the failed acquisition still recorded the evidence
    assert witness.undeclared() == [("X.b_lock", "X.a_lock")]


def test_aliases_canonicalize_at_construction(declared, monkeypatch):
    monkeypatch.setattr(
        lock_order, "VGT_LOCK_ALIASES", {"Y.swap_lock": "X.b_lock"}
    )
    witness.reset()
    a = _lk("X.a_lock")
    aliased = _lk("Y.swap_lock")  # canonicalizes to X.b_lock
    assert aliased.name == "X.b_lock"
    with a:
        with aliased:
            pass
    assert witness.undeclared() == []


def test_report_written_incrementally(declared, monkeypatch, tmp_path):
    out = tmp_path / "witness.json"
    monkeypatch.setenv("VGT_LOCK_WITNESS_OUT", str(out))
    a, b = _lk("X.a_lock"), _lk("X.b_lock")
    with a:
        with b:
            pass
    # written at edge time, not only at exit — a kill -9'd drill
    # server must still leave a current report
    rep = json.loads(out.read_text())
    assert {(e["outer"], e["inner"]) for e in rep["edges"]} == {
        ("X.a_lock", "X.b_lock")
    }
    assert rep["undeclared"] == []


def test_acquire_release_surface(declared):
    """The wrapper must honor the full lock surface the runtime uses:
    bounded acquire(timeout=), release, locked()."""
    a = _lk("X.a_lock")
    assert a.acquire(timeout=1.0) is True
    assert a.locked()
    a.release()
    assert not a.locked()
    # failed non-blocking acquire does not corrupt the held stack
    other_thread_holds = threading.Event()
    done = threading.Event()

    def holder():
        a.acquire()
        other_thread_holds.set()
        done.wait(5)
        a.release()

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert other_thread_holds.wait(5)
    assert a.acquire(blocking=False) is False
    done.set()
    t.join(5)
    # after the holder released, we can take it again
    assert a.acquire(timeout=5) is True
    a.release()


def test_disabled_witness_writes_no_report(tmp_path):
    """A process with VGT_LOCK_WITNESS_OUT inherited but the witness
    DISABLED must not write an (empty) report — the drills'
    assert_witness_clean reads a report as proof the witness ran, so
    an empty file from a disabled run would pass vacuously.  Checked
    in a subprocess because registration happens at import."""
    import subprocess
    import sys

    out = tmp_path / "witness.json"
    for env_val, expect_file in (("0", False), ("1", True)):
        if out.exists():
            out.unlink()
        proc = subprocess.run(
            [sys.executable, "-c", "import vgate_tpu.analysis.witness"],
            env={
                "PATH": os.environ.get("PATH", ""),
                "PYTHONPATH": REPO_ROOT,
                "VGT_LOCK_WITNESS": env_val,
                "VGT_LOCK_WITNESS_OUT": str(out),
            },
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode == 0, proc.stderr
        assert out.exists() is expect_file, (env_val, proc.stderr)


def test_real_registry_parses_and_is_acyclic():
    edges = lock_order.declared_edges()
    # the dp edges this PR declares exist and the graph is acyclic
    assert (
        "ReplicatedEngine._structural_lock",
        "ReplicatedEngine._topology_lock",
    ) in edges
    # Kahn: all nodes eliminated => acyclic
    nodes = {n for e in edges for n in e}
    indeg = {n: 0 for n in nodes}
    for _, b in edges:
        indeg[b] += 1
    queue = [n for n in nodes if indeg[n] == 0]
    seen = 0
    while queue:
        n = queue.pop()
        seen += 1
        for a, b in edges:
            if a == n:
                indeg[b] -= 1
                if indeg[b] == 0:
                    queue.append(b)
    assert seen == len(nodes), "declared lock order has a cycle"
