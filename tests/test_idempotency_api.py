"""Gateway idempotency end-to-end on the dry-run engine (ISSUE 20):
keyed requests journal + replay token-identically, duplicate in-flight
keys 409 typed, ineligible shapes bypass the journal, and a successor
gateway resubmits a predecessor's accepted-but-unsettled records at
startup."""

import asyncio
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from vgate_tpu.config import load_config
from vgate_tpu.runtime.journal import PENDING, SETTLED, RequestJournal
from vgate_tpu.server.app import create_app

KEY = "Idempotency-Key"


async def _client(**overrides):
    overrides.setdefault("model", {"engine_type": "dry_run"})
    overrides.setdefault(
        "batch", {"max_batch_size": 4, "max_wait_time_ms": 5.0}
    )
    overrides.setdefault("logging", {"level": "WARNING"})
    config = load_config(**overrides)
    client = TestClient(TestServer(create_app(config)))
    await client.start_server()
    return client


CHAT = {
    "messages": [{"role": "user", "content": "Say hi"}],
    "max_tokens": 8,
}


async def test_same_key_replays_identical_body():
    client = await _client()
    try:
        r1 = await client.post(
            "/v1/chat/completions", json=CHAT, headers={KEY: "k-1"}
        )
        assert r1.status == 200
        body1 = await r1.json()
        assert "replayed" not in body1

        r2 = await client.post(
            "/v1/chat/completions", json=CHAT, headers={KEY: "k-1"}
        )
        assert r2.status == 200
        body2 = await r2.json()
        assert body2.pop("replayed") is True
        # token-identical, zero recompute: the SAME body, id and all
        assert body2 == body1
    finally:
        await client.close()


async def test_unkeyed_requests_bypass_journal():
    client = await _client()
    try:
        r1 = await client.post("/v1/chat/completions", json=CHAT)
        r2 = await client.post("/v1/chat/completions", json=CHAT)
        assert r1.status == r2.status == 200
        assert (await r1.json())["id"] != (await r2.json())["id"]
        assert client.server.app["journal"].stats()["records"] == 0
    finally:
        await client.close()


async def test_duplicate_inflight_key_409_typed():
    client = await _client()
    try:
        journal = client.server.app["journal"]
        # a same-lifetime pending key (the original attempt is mid-
        # flight on this very gateway)
        journal.begin("k-dup", "r0", "/v1/chat/completions", {"x": 1})
        resp = await client.post(
            "/v1/chat/completions", json=CHAT, headers={KEY: "k-dup"}
        )
        assert resp.status == 409
        body = await resp.json()
        assert body["error"]["type"] == "duplicate_request_error"
        assert body["error"]["reason"] == "duplicate_request"
        assert "Retry-After" in resp.headers
    finally:
        await client.close()


async def test_multi_sample_request_not_journaled():
    client = await _client()
    try:
        payload = {**CHAT, "n": 2, "temperature": 0.5, "seed": 7}
        r1 = await client.post(
            "/v1/chat/completions", json=payload, headers={KEY: "k-n2"}
        )
        assert r1.status == 200
        # no snapshot → no journal record → a retry runs fresh
        assert client.server.app["journal"].lookup("k-n2") is None
    finally:
        await client.close()


async def test_embeddings_keyed_replay():
    client = await _client()
    try:
        payload = {"input": ["hello world"]}
        r1 = await client.post(
            "/v1/embeddings", json=payload, headers={KEY: "k-emb"}
        )
        assert r1.status == 200
        body1 = await r1.json()
        r2 = await client.post(
            "/v1/embeddings", json=payload, headers={KEY: "k-emb"}
        )
        body2 = await r2.json()
        assert body2.pop("replayed") is True
        assert body2["data"] == body1["data"]
    finally:
        await client.close()


async def test_journal_survives_restart_and_serves_retry(tmp_path):
    """The full crash story on one journal file: gateway A journals a
    completed request and dies; gateway B loads the file and serves a
    retry of the key verbatim, zero recompute."""
    path = str(tmp_path / "journal.jsonl")
    a = await _client(gateway={"journal_path": path})
    try:
        r1 = await a.post(
            "/v1/completions",
            json={"prompt": "hi", "max_tokens": 4},
            headers={KEY: "k-surv"},
        )
        assert r1.status == 200
        body1 = await r1.json()
    finally:
        await a.close()

    b = await _client(gateway={"journal_path": path})
    try:
        r2 = await b.post(
            "/v1/completions",
            json={"prompt": "hi", "max_tokens": 4},
            headers={KEY: "k-surv"},
        )
        assert r2.status == 200
        body2 = await r2.json()
        assert body2.pop("replayed") is True
        assert body2 == body1
    finally:
        await b.close()


async def test_startup_resubmits_inherited_pending(tmp_path):
    """Gateway A died between accept and settle.  Gateway B's startup
    replay resubmits the snapshot through admission and settles the
    record — a retry (or nobody at all) finds the promise kept."""
    path = str(tmp_path / "journal.jsonl")
    pre = RequestJournal(path)
    pre.begin(
        "k-pend", "req-orig", "/v1/completions",
        {
            "model": "m",
            "prompt": "resurrect me",
            "submit": {"max_tokens": 4, "temperature": 0.0},
        },
    )
    pre.close()  # crash before settle

    b = await _client(gateway={"journal_path": path})
    try:
        journal = b.server.app["journal"]
        rec = journal.lookup("k-pend")
        assert rec is not None and rec.inherited
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if journal.lookup("k-pend").state != PENDING:
                break
            await asyncio.sleep(0.05)
        rec = journal.lookup("k-pend")
        assert rec.state == SETTLED
        # the retry now serves the resubmitted generation
        resp = await b.post(
            "/v1/completions",
            json={"prompt": "resurrect me", "max_tokens": 4},
            headers={KEY: "k-pend"},
        )
        assert resp.status == 200
        body = await resp.json()
        assert body["replayed"] is True
        assert body["choices"][0]["text"]
    finally:
        await b.close()


async def test_startup_fails_unreplayable_pending(tmp_path):
    """An inherited pending embeddings record has no replayable shape:
    startup releases the key as failed (counted), and a retry runs
    fresh instead of hanging on the await loop."""
    path = str(tmp_path / "journal.jsonl")
    pre = RequestJournal(path)
    pre.begin("k-emb-pend", "req-e", "/v1/embeddings", {"inputs": ["x"]})
    pre.close()

    b = await _client(gateway={"journal_path": path})
    try:
        journal = b.server.app["journal"]
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if journal.lookup("k-emb-pend").state != PENDING:
                break
            await asyncio.sleep(0.05)
        assert journal.lookup("k-emb-pend").state == "failed"
        resp = await b.post(
            "/v1/embeddings",
            json={"input": ["x"]},
            headers={KEY: "k-emb-pend"},
        )
        assert resp.status == 200
        assert "replayed" not in await resp.json()
    finally:
        await b.close()
