"""Mesh construction + TP sharding semantics on the virtual 8-device CPU
mesh (SURVEY.md section 4's multi-chip strategy; section 2.2 checklist)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from vgate_tpu.config import load_config
from vgate_tpu.models.decoder import init_params
from vgate_tpu.models.specs import TINY_DENSE, TINY_MOE
from vgate_tpu.parallel.mesh import MESH_AXES, build_mesh, resolve_plan
from vgate_tpu.parallel.sharding import (
    kv_pspec,
    param_pspecs,
    shard_params,
)


def tpu_cfg(**kw):
    return load_config(tpu=kw).tpu


class TestMeshPlan:
    def test_auto_axis_absorbs_devices(self):
        plan = resolve_plan(tpu_cfg(tp=0, dp=1), num_devices=8)
        assert plan.tp == 8 and plan.num_devices == 8

    def test_mixed_axes(self):
        plan = resolve_plan(tpu_cfg(dp=2, tp=0), num_devices=8)
        assert (plan.dp, plan.tp) == (2, 4)

    def test_expert_axis(self):
        plan = resolve_plan(tpu_cfg(dp=1, ep=4, tp=2), num_devices=8)
        assert (plan.ep, plan.tp) == (4, 2)

    def test_mismatch_raises(self):
        with pytest.raises(ValueError):
            resolve_plan(tpu_cfg(dp=3, tp=1), num_devices=8)

    def test_two_auto_axes_raise(self):
        with pytest.raises(ValueError):
            resolve_plan(tpu_cfg(dp=0, tp=0), num_devices=8)

    def test_build_mesh_axis_names(self):
        mesh = build_mesh(tpu_cfg(tp=0))
        assert mesh.axis_names == MESH_AXES
        assert mesh.shape["tp"] == 8

    def test_submesh_via_num_devices(self):
        mesh = build_mesh(tpu_cfg(tp=0, num_devices=4))
        assert mesh.devices.size == 4


class TestParamShardings:
    def test_attention_heads_shard_on_tp(self):
        mesh = build_mesh(tpu_cfg(tp=0))  # tp=8
        pspecs = param_pspecs(TINY_DENSE, mesh)
        # q_dim=64 divisible by 8 -> sharded on last dim
        assert pspecs["layers"]["q"]["w"] == P(None, None, "tp")
        assert pspecs["layers"]["o"]["w"] == P(None, "tp", None)
        assert pspecs["layers"]["gate"]["w"] == P(None, None, "tp")
        assert pspecs["layers"]["down"]["w"] == P(None, "tp", None)
        assert pspecs["embed"] == P("tp", None)
        # pp=1 -> the layer axis stays unsharded (trailing-None spec is
        # semantically P())
        assert pspecs["layers"]["input_norm"] == P(None, None)

    def test_indivisible_dims_replicate(self):
        # kv_dim = 2*16 = 32; on tp=8: 32 % 8 == 0 -> sharded. On a mesh of
        # tp=8 with head count 4 (q_dim=64): fine. Make kv indivisible via
        # a 3-way check instead: vocab 512 % 8 == 0 -> sharded; so test the
        # degenerate mesh (tp=1) where nothing shards.
        mesh = build_mesh(tpu_cfg(tp=1, dp=0))
        pspecs = param_pspecs(TINY_DENSE, mesh)
        assert pspecs["layers"]["q"]["w"] == P(None, None, None)

    def test_moe_experts_shard_on_ep(self):
        mesh = build_mesh(tpu_cfg(ep=4, tp=2))
        pspecs = param_pspecs(TINY_MOE, mesh)
        assert pspecs["layers"]["gate"]["w"] == P(None, "ep", None, "tp")
        assert pspecs["layers"]["down"]["w"] == P(None, "ep", "tp", None)
        assert pspecs["layers"]["router"] == P(None, None, None)

    def test_kv_pages_shard_only_on_kv_heads(self):
        mesh = build_mesh(tpu_cfg(tp=2, dp=0))
        spec = kv_pspec(TINY_DENSE, mesh)  # kv_heads=2 % 2 == 0
        assert spec == P(None, "tp", None, None, None)

    def test_shard_params_places_on_mesh(self):
        mesh = build_mesh(tpu_cfg(tp=0))
        params = init_params(TINY_DENSE, jax.random.PRNGKey(0), jnp.float32)
        sharded = shard_params(params, TINY_DENSE, mesh)
        qw = sharded["layers"]["q"]["w"]
        assert len(qw.sharding.device_set) == 8
        # sharded dim is split 8 ways
        shard_shape = qw.sharding.shard_shape(qw.shape)
        assert shard_shape[-1] == qw.shape[-1] // 8


def test_tp8_decode_step_runs_sharded():
    """One real decode step jitted over a full 8-way tp mesh: XLA must
    partition and insert collectives, and the result must match tp=1."""
    from vgate_tpu.models.decoder import decode_forward
    from vgate_tpu.parallel.sharding import named

    spec = TINY_DENSE
    params_host = init_params(spec, jax.random.PRNGKey(0), jnp.float32)
    B, ps, n_pages = 4, 4, 17

    def build_inputs():
        k = jnp.zeros((spec.num_layers, spec.num_kv_heads, n_pages, ps,
                       spec.head_dim), jnp.float32)
        v = jnp.zeros_like(k)
        pt = jnp.asarray(
            np.arange(B * 4, dtype=np.int32).reshape(B, 4) + 1
        )
        tokens = jnp.asarray([5, 6, 7, 8], jnp.int32)
        positions = jnp.asarray([0, 1, 2, 3], jnp.int32)
        return k, v, pt, tokens, positions

    # single-device reference
    k, v, pt, tokens, positions = build_inputs()
    ref_logits, _, _ = decode_forward(
        params_host, spec, tokens, positions, k, v, pt,
        active=jnp.ones((B,), bool),
    )

    # 8-way tp
    mesh = build_mesh(tpu_cfg(tp=0))
    params = shard_params(params_host, spec, mesh)
    kv_sharding = named(mesh, kv_pspec(spec, mesh))
    k, v, pt, tokens, positions = build_inputs()
    k = jax.device_put(k, kv_sharding)
    v = jax.device_put(v, kv_sharding)

    import functools

    step = jax.jit(
        functools.partial(decode_forward, spec=spec),
    )
    logits, k_out, _ = step(
        params, tokens=tokens, positions=positions, k_pages=k, v_pages=v,
        page_tables=pt, active=jnp.ones((B,), bool),
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), rtol=2e-4, atol=2e-4
    )
    # KV stayed sharded on the kv-head axis
    assert len(k_out.sharding.device_set) == 8
