"""CI guardrail (ISSUE 3 satellite): monitoring assets must only
reference metrics vgate_tpu/metrics.py defines, and every vgt_ metric
must carry a documentation string.  Fast tier so the tier-1 flow
enforces it."""

import os
import sys

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(__file__)), "scripts"),
)

import metrics_lint  # noqa: E402


def test_repo_monitoring_assets_pass_lint(capsys):
    assert metrics_lint.main() == 0
    assert "OK" in capsys.readouterr().out


def test_lint_catches_undefined_metric(tmp_path, monkeypatch, capsys):
    bad = tmp_path / "alerts.yml"
    bad.write_text(
        "groups:\n  - name: g\n    rules:\n"
        "      - alert: A\n        expr: vgt_totally_made_up_total > 0\n"
    )
    monkeypatch.setattr(metrics_lint, "MONITORING_FILES", (str(bad),))
    assert metrics_lint.main() == 1
    err = capsys.readouterr().err
    assert "vgt_totally_made_up_total" in err


def test_lint_understands_exposition_suffixes():
    defined, families = metrics_lint.defined_metric_names()
    # counter family + _total alias
    assert "vgt_requests" in defined and "vgt_requests_total" in defined
    # histogram expositions
    assert "vgt_request_latency_seconds_bucket" in defined
    assert "vgt_time_to_first_token_seconds_sum" in defined
    # gauges stay bare
    assert "vgt_kv_pages_in_use" in defined
    # every vgt_ family is documented (the repo invariant)
    assert families and all(doc.strip() for _, doc in families)
