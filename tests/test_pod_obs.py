"""Pod-scope distributed observability (ISSUE 18): W3C traceparent
codec over the RPC plane, the merged ``_PodFlight`` view (worker/epoch
stamping, fenced incarnations, handoff-ledger grafting), /debug/pod
payload shape, build fingerprint, RPC-plane metrics — and (slow tier)
the real 2-worker CPU pod producing ONE trace across three processes
plus a fenced flight timeline after a SIGKILL.
"""

import os
import signal
import threading
import time
from types import SimpleNamespace

import pytest

from vgate_tpu import metrics, tracing
from vgate_tpu.backends.base import SamplingParams
from vgate_tpu.observability.memtrace import MemorySpanRecorder
from vgate_tpu.observability.reqtrace import RequestMeta
from vgate_tpu.runtime.pod_engine import (
    PodEngine,
    _HandoffRec,
    _PodFlight,
    _Worker,
    _pc_to_ns,
)

from tests.test_worker_pod import greedy, pod_config, wait_for


# ------------------------------------------------- traceparent codec


def test_traceparent_round_trip_preserves_identity():
    rec = MemorySpanRecorder().install()
    tracer = tracing.get_tracer("t")
    with tracer.start_as_current_span("POST /v1/completions"):
        ctx = tracing.capture_context()
        header = tracing.context_to_traceparent(ctx)
    root = rec.spans("POST /v1/completions")[0]
    assert header == f"00-{root.trace_id_hex}-{root.span_id_hex}-01"
    back = tracing.context_from_traceparent(header)
    assert tracing.context_trace_id(back) == root.trace_id_hex
    # the worker-side half: a span opened under the decoded context
    # parents onto the gateway's span — one trace, two processes
    child = tracer.start_span("engine.queue", context=back)
    child.end()
    span = rec.spans("engine.queue")[0]
    assert span.trace_id_hex == root.trace_id_hex
    assert span.parent_span_id_hex == root.span_id_hex


@pytest.mark.parametrize(
    "bad",
    [
        None,
        "",
        "junk",
        "00-only-three",
        "00-a-b-c-d-e",  # too many segments
        "01-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01",
        "00-zzzz651916cd43dd8448eb211c80319c-b7ad6b7169203331-01",
        "00-0af7651916cd43dd8448eb211c80319c-b7ad6b71692033-01",
        "00-" + "0" * 32 + "-" + "1" * 16 + "-01",  # invalid trace id
        "00-" + "1" * 32 + "-" + "0" * 16 + "-01",  # invalid span id
    ],
)
def test_traceparent_malformed_inputs_decode_to_none(bad):
    # a worker must never fail a submit over a bad trace header
    assert tracing.context_from_traceparent(bad) is None


def test_traceparent_none_context_encodes_to_none():
    assert tracing.context_to_traceparent(None) is None


def test_pc_to_ns_anchors_perf_counter_on_wall_clock():
    ns = _pc_to_ns(time.perf_counter())
    assert abs(ns - time.time_ns()) < 100_000_000  # within 100ms


# ------------------------------------------------- merged pod flight


class _FakeClient:
    """Answers the flight/requests verbs from canned replies (or raises
    to model an unreachable worker)."""

    def __init__(self, replies):
        self.replies = replies

    def call(self, verb, timeout=None, **kw):
        reply = self.replies[verb]
        if isinstance(reply, Exception):
            raise reply
        return reply


def _flight_worker(idx, epoch, t, rid=None):
    ticks = [{"n": 0, "t": t, "kind": "decode", "batch": 1}]
    completed = []
    if rid is not None:
        completed.append(
            {
                "request_id": rid,
                "seq_id": 100 + idx,
                "arrival_t": t - 1.0,
                "queue_s": 0.01,
                "status": "finished",
            }
        )
    return SimpleNamespace(
        idx=idx,
        epoch=epoch,
        alive=True,
        client=_FakeClient(
            {
                "flight": {"ticks": ticks, "stats": {"ticks_recorded": 1}},
                "requests": {"live": [], "completed": completed},
            }
        ),
    )


def _flight_pod(workers):
    pod = SimpleNamespace(
        config=SimpleNamespace(
            observability=SimpleNamespace(enabled=True)
        ),
        _lock=threading.RLock(),
        _flight_cache={},
        _req_ledger={},
        workers=workers,
    )
    pod._alive_workers = lambda: [w for w in workers if w.alive]
    return pod


def test_pod_flight_merges_stamps_and_sorts():
    w0 = _flight_worker(0, 1, t=10.0)
    w1 = _flight_worker(1, 3, t=11.0)
    fl = _PodFlight(_flight_pod([w0, w1]))
    fl.record_tick("overload", level="shed")
    ticks = fl.ticks()
    # wall-time merge: worker ticks first, the gateway event (t = now)
    # last, each stamped with its origin
    assert [t["worker"] for t in ticks] == [0, 1, "gateway"]
    assert ticks[0]["epoch"] == 1
    assert ticks[1]["epoch"] == 3
    assert not any(t.get("fenced") for t in ticks)
    assert ticks[-1]["kind"] == "overload"


def test_pod_flight_keeps_dead_incarnation_epoch_marked():
    w0 = _flight_worker(0, 1, t=10.0, rid="r-dead")
    w1 = _flight_worker(1, 1, t=11.0)
    pod = _flight_pod([w0, w1])
    fl = _PodFlight(pod)
    fl.ticks()  # primes the per-slot cache
    w0.alive = False  # SIGKILL / heartbeat fencing
    ticks = fl.ticks()
    dead = [t for t in ticks if t["worker"] == 0]
    assert dead, "dead incarnation's timeline must survive"
    assert all(t["fenced"] and t["epoch"] == 1 for t in dead)
    # the surviving worker stays unfenced
    assert not any(t.get("fenced") for t in ticks if t["worker"] == 1)
    # its request record survives fenced too
    rec = fl.find_request("r-dead")
    assert rec is not None and rec["fenced"] and rec["epoch"] == 1


def test_pod_flight_fences_cached_view_of_older_epoch():
    w0 = _flight_worker(0, 1, t=10.0)
    pod = _flight_pod([w0])
    fl = _PodFlight(pod)
    fl.ticks()  # cache holds the epoch-1 view
    # the slot respawned (epoch bump) but its live fetch fails — the
    # cached snapshot belongs to the PREVIOUS incarnation
    w0.epoch = 2
    w0.client = _FakeClient(
        {"flight": OSError("unreachable"), "requests": OSError("x")}
    )
    ticks = fl.ticks()
    assert ticks and all(
        t["fenced"] and t["epoch"] == 1 for t in ticks
    )


def test_pod_flight_grafts_gateway_ledger_onto_records():
    w0 = _flight_worker(0, 1, t=10.0, rid="r1")
    pod = _flight_pod([w0])
    pod._req_ledger["r1"] = {
        "transfer_s": 0.25,
        "handoff": "ok",
        "prefill_worker": 0,
        "decode_worker": 1,
    }
    fl = _PodFlight(pod)
    rec = fl.find_request("r1")
    assert rec["transfer_s"] == 0.25
    assert rec["handoff"] == "ok"
    assert (rec["prefill_worker"], rec["decode_worker"]) == (0, 1)
    assert fl.requests()[0]["transfer_s"] == 0.25
    # lookups work by seq_id and find nothing for unknown idents
    assert fl.find_request("100")["request_id"] == "r1"
    assert fl.find_request("no-such") is None


def test_pod_flight_newest_attempt_wins_across_workers():
    w0 = _flight_worker(0, 1, t=10.0, rid="r1")
    w1 = _flight_worker(1, 1, t=50.0, rid="r1")
    fl = _PodFlight(_flight_pod([w0, w1]))
    assert fl.find_request("r1")["worker"] == 1


def test_pod_flight_get_stats_shape():
    fl = _PodFlight(_flight_pod([_flight_worker(0, 2, t=1.0)]))
    st = fl.get_stats()
    assert st["enabled"] is True
    assert st["workers"] == [
        {"worker": 0, "epoch": 2, "fenced": False, "ticks_recorded": 1}
    ]


def test_pod_flight_disabled_recorder_drops_gateway_ticks():
    pod = _flight_pod([])
    pod.config.observability.enabled = False
    fl = _PodFlight(pod)
    fl.record_tick("overload")
    assert fl.enabled is False
    assert fl.ticks() == []


# ------------------------------------------------- gateway req ledger


def _ledger_shell(cap=4):
    pod = object.__new__(PodEngine)
    pod._lock = threading.RLock()
    pod._req_ledger = {}
    pod._ledger_cap = cap
    return pod


def test_ledger_note_merges_and_ignores_anonymous():
    pod = _ledger_shell()
    pod._ledger_note("r1", transfer_s=0.5)
    pod._ledger_note("r1", handoff="ok")
    assert pod._req_ledger["r1"] == {"transfer_s": 0.5, "handoff": "ok"}
    pod._ledger_note(None, handoff="ok")  # no request id → no entry
    assert len(pod._req_ledger) == 1


def test_ledger_note_evicts_fifo_at_cap():
    pod = _ledger_shell(cap=3)
    for i in range(5):
        pod._ledger_note(f"r{i}", handoff="ok")
    assert list(pod._req_ledger) == ["r2", "r3", "r4"]


# ----------------------------------------------------- /debug surfaces


def _debug_shell():
    pod = object.__new__(PodEngine)
    pod._lock = threading.RLock()
    pod._inflight = {}
    pod._orphans = []
    pod._handoffs = {}
    pod._restart_times = []
    pod.fenced_frames = 3
    pod._last_crash = None
    pod._pod_cfg = SimpleNamespace(transport="uds")
    pod._roles = ["prefill", "decode"]
    pod._roles_active = True
    pod.total_handoffs = 5
    pod.total_handoff_fallbacks = 1
    pod.total_handoff_failed = 0
    pod.total_adopted = 0
    pod.total_orphans_found = 0
    pod.total_orphans_expired = 0
    pod.adopted_request_ids = {}
    w0, w1 = _Worker(0), _Worker(1)
    w0.epoch, w0.state = 2, "serving"
    w0.last_fatal = "SIGKILL"
    w0.last_ping = {
        "pressure": {"engine_queue_depth": 1, "running": 2},
        "beat": {"age_s": 0.1234, "compiling": False},
    }
    w1.epoch, w1.state = 1, "serving"
    pod.workers = [w0, w1]
    return pod


def test_pod_debug_payload_shape():
    pod = _debug_shell()
    pod._inflight = {
        7: SimpleNamespace(_worker_idx=0),
        8: SimpleNamespace(_worker_idx=0),
        9: SimpleNamespace(_worker_idx=1),
    }
    rec = _HandoffRec(7, SimpleNamespace(request_id="r9"), 0, 2)
    rec.pages, rec.nbytes, rec.attempts = 4, 4096, 1
    pod._handoffs[7] = rec
    out = pod.pod_debug()
    assert out["transport"] == "uds"
    assert out["roles"] == ["prefill", "decode"]
    assert out["inflight"] == 3 and out["orphans"] == 0
    assert out["fenced_frames"] == 3
    w0, w1 = out["workers"]
    assert (w0["replica"], w0["epoch"], w0["role"]) == (0, 2, "prefill")
    assert (w0["state"], w0["inflight"]) == ("serving", 2)
    assert w0["last_fatal"] == "SIGKILL"
    assert w0["beat_age_s"] == 0.123 and w0["compiling"] is False
    assert (w0["queue_depth"], w0["running"]) == (1, 2)
    assert (w1["replica"], w1["inflight"]) == (1, 1)
    assert "last_fatal" not in w1
    ho = out["handoffs"]
    assert (ho["completed"], ho["fallback_monolithic"]) == (5, 1)
    row = ho["table"][0]
    assert (row["sid"], row["request_id"]) == (7, "r9")
    assert row["state"] == "PREFILLING"
    assert (row["prefill"], row["prefill_epoch"]) == (0, 2)
    assert row["target"] is None  # no decode target picked yet
    assert (row["pages"], row["nbytes"], row["attempts"]) == (4, 4096, 1)
    assert row["age_s"] >= 0.0
    assert out["last_crash"] is None


def test_build_fingerprint_fields():
    fp = metrics.build_fingerprint()
    assert set(fp) == {"version", "git_sha", "jax"}
    assert all(isinstance(v, str) and v for v in fp.values())


def test_rpc_plane_metrics_registered():
    from prometheus_client import REGISTRY

    for name in (
        "vgt_rpc_call_seconds",
        "vgt_rpc_bytes",
        "vgt_pod_heartbeat_age_seconds",
        "vgt_pod_worker_inflight",
        "vgt_handoff_state_seconds",
    ):
        assert name in REGISTRY._names_to_collectors, name
    before = REGISTRY.get_sample_value(
        "vgt_rpc_call_seconds_count", {"verb": "ping"}
    ) or 0.0
    metrics.RPC_CALL_SECONDS.labels(verb="ping").observe(0.001)
    after = REGISTRY.get_sample_value(
        "vgt_rpc_call_seconds_count", {"verb": "ping"}
    )
    assert after == before + 1


# -------------------------------------------- loadlab pod perf column


def test_loadlab_perf_delta_lands_pod_block():
    from vgate_tpu.loadlab.runner import perf_delta

    def snap(completed, fallbacks, window=None):
        return {
            "enabled": True,
            "totals": {
                "ticks": 10,
                "tokens": 100,
                "wall_s": 1.0,
                "phase_seconds": {"host": 0.1},
                "compiles": {},
                "compile_seconds": 0.0,
            },
            "window": window or {},
            "pod": {
                "workers": 3,
                "workers_alive": 3,
                "handoffs": {
                    "completed": completed,
                    "fallback_monolithic": fallbacks,
                    "failed": 0,
                },
            },
        }

    out = perf_delta(snap(2, 0), snap(9, 1))
    assert out["pod"]["workers"] == 3
    assert out["pod"]["workers_alive"] == 3
    assert out["pod"]["handoffs"]["completed"] == 7
    assert out["pod"]["handoffs"]["fallback_monolithic"] == 1
    assert out["pod"]["handoffs"]["failed"] == 0


def test_loadlab_perf_delta_without_pod_block():
    from vgate_tpu.loadlab.runner import perf_delta

    snap = {
        "enabled": True,
        "totals": {
            "ticks": 1,
            "tokens": 1,
            "wall_s": 1.0,
            "phase_seconds": {},
            "compiles": {},
            "compile_seconds": 0.0,
        },
        "window": {},
    }
    assert "pod" not in perf_delta(snap, snap)


# ------------------------------------------- real pod on CPU (slow tier)


@pytest.mark.slow
def test_pod_single_trace_across_processes(monkeypatch):
    """Acceptance core: one request produces ONE trace — the gateway's
    span is the root, and the worker process's engine spans (shipped
    back over the ``spans`` verb) carry the same trace id and parent
    onto it.  The merged flight view finds the request by its id, and
    /debug/pod reports the live topology."""
    monkeypatch.setenv("VGT_MEMTRACE", "1")  # workers inherit the env
    rec = MemorySpanRecorder().install()
    pod = PodEngine(pod_config())
    pod.start()
    try:
        tracer = tracing.get_tracer("vgate_tpu.server")
        with tracer.start_as_current_span("POST /v1/completions"):
            meta = RequestMeta(
                request_id="req-obs-1",
                trace_ctx=tracing.capture_context(),
            )
            seq = pod.submit_tokens(
                [5, 9, 13, 17, 21], greedy(8), meta=meta
            )
        assert seq.done_event.wait(120)
        assert seq.error is None
        root = rec.spans("POST /v1/completions")[0]

        worker_spans = pod.collect_spans()
        ours = [
            s for s in worker_spans if s["trace_id"] == root.trace_id_hex
        ]
        names = {s["name"] for s in ours}
        assert {"engine.queue", "engine.prefill", "engine.decode"} <= names
        # every span in the trace ultimately parents onto the gateway
        # HTTP span: parent ids resolve within the trace or to the root
        ids = {s["span_id"] for s in ours} | {root.span_id_hex}
        assert all(s["parent_span_id"] in ids for s in ours)
        assert any(
            s["parent_span_id"] == root.span_id_hex for s in ours
        )
        assert all(isinstance(s["worker"], int) for s in ours)

        found = pod.flight.find_request("req-obs-1")
        assert found is not None
        assert found["request_id"] == "req-obs-1"
        assert found["worker"] in (0, 1) and found["epoch"] == 1
        assert not found.get("fenced")

        dbg = pod.pod_debug()
        assert len(dbg["workers"]) == 2
        assert all(w["state"] == "serving" for w in dbg["workers"])
        assert dbg["handoffs"]["table"] == []
    finally:
        pod.stop()


@pytest.mark.slow
def test_pod_flight_survives_worker_sigkill_epoch_marked():
    """After a SIGKILL the dead incarnation's cached timeline stays in
    the merged flight view, epoch-stamped and marked fenced, and the
    gateway synthesizes a crash snapshot for /stats."""
    pod = PodEngine(pod_config())
    pod.start()
    try:
        seqs = [
            pod.submit_tokens([5, 9, 13 + i, 17, 21], greedy(8))
            for i in range(4)
        ]
        for s in seqs:
            assert s.done_event.wait(120)
            assert s.error is None
        # prime the per-slot cache — the post-mortem merges from it
        ticks = pod.flight.ticks()
        assert any(t["worker"] == 0 for t in ticks)

        os.kill(pod.workers[0].proc.pid, signal.SIGKILL)
        assert wait_for(
            lambda: pod.get_stats().get("last_crash") is not None, 60
        )
        merged = pod.flight.ticks()
        dead = [
            t for t in merged if t["worker"] == 0 and t.get("fenced")
        ]
        assert dead, "dead incarnation's ticks must stay inspectable"
        assert all(t["epoch"] == 1 for t in dead)

        crash = pod.get_stats()["last_crash"]
        assert "WorkerLost" in crash["error"]
        assert crash["worker"] == 0 and crash["epoch"] == 1
        assert isinstance(crash["ticks"], list)
    finally:
        pod.stop()
