"""Worker orphan mode + gateway re-adoption (ISSUE 20 tentpole).

Drives ``WorkerServer`` verbs directly on a bare shell (no engine, no
jax import) plus the real ``serve()`` accept loop on a loopback
listener — covering the adoption handshake fencing, the buffered-frame
replay ordering contract, the registry records, the grace-0
byte-identical exit-on-EOF regression, and seeded fuzz of the
handshake (stale-epoch re-hello, double adopt, concurrent replay) with
the invariants: typed errors or fenced frames, never a hang, never a
duplicate token.
"""

import json
import os
import random
import socket
import threading
import time
from types import SimpleNamespace

import pytest

from vgate_tpu.errors import WorkerFencedError
from vgate_tpu.runtime import rpc
from vgate_tpu.runtime import worker as worker_mod
from vgate_tpu.runtime.worker import WorkerServer, _Entry


def bare_worker(tmp_path=None, epoch=1, grace=5.0):
    """A WorkerServer shell with just enough state for the orphan /
    adoption surface — engine untouched (an engine call would raise
    AttributeError, which doubles as the 'never touch the engine on a
    fenced frame' assertion)."""
    w = object.__new__(WorkerServer)
    w.epoch = epoch
    w.index = 0
    w.max_frame_bytes = 1 << 20
    w.registry_dir = str(tmp_path) if tmp_path is not None else None
    w.address = "127.0.0.1:0"
    w.orphan_grace_s = grace
    w._orphan_lock = threading.Lock()
    w._orphan_frames = []
    w._orphan_tok_count = 0
    w._orphan_buffering = False
    w._orphaned = False
    w._orphan_deadline = None
    w._adoptions = 0
    w._exit_reason = None
    w._exit_recorded = False
    w._started_t = time.time()
    w._seq_lock = threading.Lock()
    w._seqs = {}
    w._send_lock = threading.Lock()
    import queue

    w._send_q = queue.Queue(maxsize=4096)
    w._conn = None
    w._stopping = threading.Event()
    w._fenced_rejects = 0
    w._state = lambda: "serving"
    # capture what would hit the wire, stamped like _enqueue_wire stamps
    w.sent = []
    w._enqueue_wire = lambda frame: w.sent.append({**frame, "e": w.epoch})
    return w


def entry(sid, request_id, num_generated):
    return _Entry(
        sid,
        SimpleNamespace(request_id=request_id, num_generated=num_generated),
    )


# ------------------------------------------------------ handshake fencing


def test_adopt_stale_epoch_fenced():
    w = bare_worker(epoch=5)
    with pytest.raises(WorkerFencedError):
        w._verb_adopt({"op": "adopt", "e": 4})
    with pytest.raises(WorkerFencedError):
        w._verb_adopt({"op": "adopt", "e": 5})  # not strictly newer
    with pytest.raises(ValueError):
        w._verb_adopt({"op": "adopt"})  # no epoch at all
    assert w.epoch == 5 and w._adoptions == 0


def test_double_adopt_second_fenced():
    """Two successors racing for one orphan: adoption is serialized on
    the reader thread (adopt is a fast verb), so the first bump wins
    and the replayed/equal epoch of the loser is fenced typed."""
    w = bare_worker(epoch=1)
    out = w._verb_adopt({"op": "adopt", "e": 2})
    assert out["epoch"] == 2 and out["adoptions"] == 1
    with pytest.raises(WorkerFencedError):
        w._verb_adopt({"op": "adopt", "e": 2})
    # a genuinely fresher successor still can take over
    assert w._verb_adopt({"op": "adopt", "e": 3})["epoch"] == 3


def test_dispatch_exempts_handshake_but_fences_work_verbs():
    w = bare_worker(epoch=7)
    # orphan_status with an epoch this incarnation has never seen is
    # answered (the successor probes BEFORE it adopts)
    w._dispatch({"op": "orphan_status", "id": 1, "e": 99})
    reply = w.sent[-1]
    assert reply["ok"] and reply["data"]["epoch"] == 7
    # a work verb with the same stale epoch is fenced, engine untouched
    w._dispatch({"op": "submit", "id": 2, "e": 99})
    reply = w.sent[-1]
    assert reply["ok"] is False
    assert reply["error"]["type"] == "WorkerFencedError"
    assert w._fenced_rejects == 1


def test_adopt_reports_delivered_tokens():
    """Adopt-time progress counts tokens DELIVERED to the predecessor:
    total generated minus tok frames still in the orphan buffer — the
    successor pads to this and the flush replay appends the rest, so
    the reconciled count is the true total (no double count)."""
    w = bare_worker(epoch=1)
    w._seqs = {7: entry(7, "req-7", 5), 9: entry(9, "req-9", 2)}
    w._orphan_buffering = True
    for t in (103, 104):
        w._enqueue({"op": "tok", "sid": 7, "t": t})
    w._enqueue({"op": "done", "sid": 9, "text": "done"})
    out = w._verb_adopt({"op": "adopt", "e": 2})
    by_sid = {i["sid"]: i for i in out["inflight"]}
    assert by_sid[7]["generated_tokens"] == 3  # 5 total - 2 buffered
    assert by_sid[9]["generated_tokens"] == 2  # done is not a tok frame
    assert by_sid[7]["request_id"] == "req-7"
    assert out["buffered_frames"] == 3


# ----------------------------------------------------- buffered replay


def test_orphan_flush_replays_in_order_with_adopted_epoch():
    w = bare_worker(epoch=1)
    w._orphan_buffering = True
    for t in range(4):
        w._enqueue({"op": "tok", "sid": 1, "t": 100 + t})
    w._enqueue({"op": "done", "sid": 1, "text": "x"})
    assert w.sent == []  # buffered, nothing hit the wire
    w._verb_adopt({"op": "adopt", "e": 6})
    w.sent.clear()
    w._verb_orphan_flush({"op": "orphan_flush"})
    assert [f["op"] for f in w.sent] == ["tok"] * 4 + ["done"]
    assert [f["t"] for f in w.sent[:4]] == [100, 101, 102, 103]
    # frames are buffered UN-encoded so replay carries the SUCCESSOR's
    # epoch — a frame stamped with the dead gateway's epoch would be
    # fenced by the very gateway that asked for it
    assert all(f["e"] == 6 for f in w.sent)
    assert w._orphan_buffering is False
    # post-flush frames go straight to the wire
    w._enqueue({"op": "tok", "sid": 1, "t": 104})
    assert w.sent[-1]["t"] == 104


def test_orphan_ring_drops_oldest_tok_keeps_done(monkeypatch):
    monkeypatch.setattr(worker_mod, "_ORPHAN_BUF_MAX", 8)
    w = bare_worker()
    w._orphan_buffering = True
    w._enqueue({"op": "done", "sid": 2, "text": "early"})
    for t in range(20):
        w._enqueue({"op": "tok", "sid": 1, "t": t})
    w._verb_orphan_flush({"op": "orphan_flush"})
    toks = [f["t"] for f in w.sent if f["op"] == "tok"]
    assert toks == list(range(12, 20))  # newest 8 survive, in order
    # the done frame (full text) is never sacrificed to the ring
    assert [f["sid"] for f in w.sent if f["op"] == "done"] == [2]


def test_flush_vs_concurrent_enqueue_fuzz():
    """Seeded fuzz: the engine thread keeps emitting tok frames while
    the successor's orphan_flush drains the buffer.  The drain-loop
    contract: every token reaches the wire exactly once, in order — a
    concurrently-enqueued frame can never jump ahead of a buffered
    one."""
    for seed in range(8):
        rng = random.Random(seed)
        w = bare_worker(epoch=1)
        w._orphan_buffering = True
        total = 200
        pre = rng.randrange(0, total)
        for t in range(pre):
            w._enqueue({"op": "tok", "sid": 1, "t": t})

        def emit(start=pre):
            for t in range(start, total):
                w._enqueue({"op": "tok", "sid": 1, "t": t})
                if t % 17 == 0:
                    time.sleep(0)

        w.epoch = 2  # adopted
        emitter = threading.Thread(target=emit)
        emitter.start()
        w._verb_orphan_flush({"op": "orphan_flush"})
        emitter.join(10)
        assert not emitter.is_alive(), "hang: emitter never finished"
        # anything still buffered after the join is a bug: flush
        # dropped the buffering flag only once the buffer was empty,
        # and the emitter had finished by then
        w._verb_orphan_flush({"op": "orphan_flush"})
        toks = [f["t"] for f in w.sent if f["op"] == "tok"]
        assert toks == list(range(total)), f"seed {seed}: {toks[:10]}..."


def test_adopt_handshake_fuzz_typed_never_hangs():
    """Seeded fuzz of the handshake via _dispatch: random interleave of
    adopts (random epochs around the current one), stale re-hellos, and
    status probes.  Invariants: every call gets a reply (no hang), the
    epoch never moves backwards, an adopt succeeds iff strictly newer,
    and failures are the typed fence."""
    rng = random.Random(2020)
    w = bare_worker(epoch=3)
    cid = 0
    for _ in range(300):
        cid += 1
        before = w.epoch
        op = rng.choice(["adopt", "orphan_status", "ping", "submit"])
        e = rng.choice(
            [before - 1, before, before + 1, before + 5, 1, None]
        )
        frame = {"op": op, "id": cid}
        if e is not None:
            frame["e"] = e
        n_sent = len(w.sent)
        w._dispatch(frame)
        assert len(w.sent) == n_sent + 1, f"no reply for {frame}"
        reply = w.sent[-1]
        assert reply["id"] == cid
        if op == "adopt":
            if isinstance(e, int) and e > before:
                assert reply["ok"] and w.epoch == e
            else:
                assert not reply["ok"]
                assert reply["error"]["type"] in (
                    "WorkerFencedError", "ValueError",
                )
                assert w.epoch == before
        elif op == "orphan_status":
            assert reply["ok"]  # exempt: probe always answered
        else:
            # work verbs (ping/submit) with a non-current epoch are
            # fenced; current-epoch ones would touch the missing
            # engine and error typed — either way, a reply, no hang
            if e != w.epoch:
                assert not reply["ok"]
                assert reply["error"]["type"] == "WorkerFencedError"
        assert w.epoch >= before


# ------------------------------------------------------ registry records


def test_registry_orphan_then_adopt_rewrites_status(tmp_path):
    w = bare_worker(tmp_path=tmp_path, epoch=1, grace=30.0)
    w._enter_orphan_mode("gateway_eof")
    rec = json.loads((tmp_path / "w0.json").read_text())
    assert rec["status"] == "orphaned"
    assert rec["pid"] == os.getpid()
    assert rec["epoch"] == 1
    assert 0.0 < rec["grace_remaining_s"] <= 30.0
    assert w._orphan_buffering  # EOF starts buffering immediately

    w._verb_adopt({"op": "adopt", "e": 2})
    rec = json.loads((tmp_path / "w0.json").read_text())
    assert rec["status"] == "serving"
    assert rec["epoch"] == 2
    assert rec["adoptions"] == 1
    assert w._orphaned is False and w._orphan_deadline is None


# ------------------------------------------------- serve() accept loop


def _serving_worker(tmp_path, grace):
    w = bare_worker(tmp_path=tmp_path, epoch=1, grace=grace)
    w.engine = SimpleNamespace(stop=lambda: None)
    # drain()'s checkpoint fold without an engine: canned evacuation
    w._verb_evacuate = lambda frame: {
        "evacuated": [
            {"sid": 3, "request_id": "req-3", "generated_tokens": 4},
        ]
    }
    del w._enqueue_wire  # serve() uses the real sender path
    del w._state
    listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    listener.bind(("127.0.0.1", 0))
    listener.listen(4)
    port = listener.getsockname()[1]
    t = threading.Thread(target=w.serve, args=(listener,), daemon=True)
    t.start()
    return w, t, port


def _connect(port):
    c = socket.create_connection(("127.0.0.1", port), timeout=5)
    c.settimeout(5)
    return c


def _wait(pred, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_grace0_eof_is_exit_through_drain_fold(tmp_path):
    """``pod.orphan_grace_s: 0`` regression pin: gateway EOF ends the
    process exactly as before orphan mode existed (one connection per
    lifetime, no re-accept) — while still routing through drain()'s
    checkpoint fold, leaving the final checkpoint summary and exit
    reason in the registry record."""
    w, t, port = _serving_worker(tmp_path, grace=0.0)
    conn = _connect(port)
    conn.close()  # the gateway dies
    assert _wait(lambda: not t.is_alive())
    rec = json.loads((tmp_path / "w0.json").read_text())
    assert rec["status"] == "exited"
    assert rec["exit_reason"] == "gateway_eof"
    assert rec["checkpoints"] == [
        {"sid": 3, "request_id": "req-3", "generated_tokens": 4},
    ]
    # byte-identical contract: the listener is gone, no successor can
    # re-accept a grace-0 worker
    with pytest.raises(OSError):
        _connect(port)


def test_grace_eof_orphans_then_successor_adopts(tmp_path):
    """grace > 0: EOF enters orphan mode, the listener stays open, a
    successor re-accepts, probes (orphan_status), adopts with a bumped
    epoch, and flushes — the full re-adoption handshake over a real
    socket."""
    w, t, port = _serving_worker(tmp_path, grace=60.0)
    conn = _connect(port)
    conn.close()
    assert _wait(lambda: w._orphaned)
    assert t.is_alive()

    succ = _connect(port)
    assert _wait(lambda: w._conn is not None)

    def call(frame):
        succ.sendall(rpc.encode_frame(frame, w.max_frame_bytes))
        reply = rpc.recv_frame(succ, w.max_frame_bytes)
        assert reply is not None and reply["op"] == "reply"
        return reply

    probe = call({"op": "orphan_status", "id": 1, "e": 99})
    assert probe["ok"] and probe["data"]["orphaned"]

    adopted = call({"op": "adopt", "id": 2, "e": 2})
    assert adopted["ok"] and adopted["data"]["epoch"] == 2
    assert adopted["data"]["was_orphaned"]
    assert w._orphaned is False

    stop = call({"op": "stop", "id": 3, "e": 2})
    assert stop["ok"]
    assert _wait(lambda: not t.is_alive())
    rec = json.loads((tmp_path / "w0.json").read_text())
    assert rec["status"] == "exited"
    assert rec["exit_reason"] == "gateway_stop"
    succ.close()


def test_orphan_grace_expiry_self_terminates(tmp_path):
    w, t, port = _serving_worker(tmp_path, grace=0.3)
    conn = _connect(port)
    conn.close()
    assert _wait(lambda: w._orphaned, 5)
    # nobody adopts: the worker drains itself when the grace expires
    assert _wait(lambda: not t.is_alive(), 15)
    rec = json.loads((tmp_path / "w0.json").read_text())
    assert rec["status"] == "exited"
    assert rec["exit_reason"] == "orphan_expired"


# ------------------------------------------- gateway-side registry scan


def _bare_scan_pod(tmp_path, n=1):
    from vgate_tpu.runtime.pod_engine import PodEngine, _Worker

    pod = object.__new__(PodEngine)
    pod.workers = [_Worker(i) for i in range(n)]
    pod.socket_dir = str(tmp_path)
    pod.total_orphans_found = 0
    pod.total_orphans_expired = 0
    return pod


def _write_rec(tmp_path, idx, **over):
    rec = {
        "pid": os.getpid(),
        "index": idx,
        "epoch": 1,
        "address": "127.0.0.1:19999",
        "status": "orphaned",
        "beat": time.time(),
    }
    rec.update(over)
    (tmp_path / f"w{idx}.json").write_text(json.dumps(rec))
    return rec


def test_scan_registry_classifies_records(tmp_path):
    pod = _bare_scan_pod(tmp_path, n=4)
    _write_rec(tmp_path, 0)  # live pid + fresh beat → adoptable
    _write_rec(tmp_path, 1, status="exited")  # clean post-mortem
    _write_rec(tmp_path, 2, pid=2 ** 22 + 12345)  # pid gone → expired
    _write_rec(tmp_path, 3, beat=time.time() - 3600)  # beat stale
    # slot 3's stale-beat pid must not be OUR pid (the scan SIGTERMs
    # wedged-but-breathing orphans); park a disposable process there
    import subprocess

    sleeper = subprocess.Popen(["sleep", "60"])
    _write_rec(tmp_path, 3, pid=sleeper.pid, beat=time.time() - 3600)
    try:
        found = pod._scan_registry()
        assert sorted(found) == [0]
        assert found[0]["status"] == "orphaned"
        assert pod.total_orphans_found == 1
        # dead pid + wedged both count as expired orphan work
        assert pod.total_orphans_expired == 2
        # the wedged one was cleared for a fresh spawn
        assert sleeper.wait(timeout=10) != 0
    finally:
        if sleeper.poll() is None:
            sleeper.kill()


def test_scan_registry_empty_dir_not_a_restart(tmp_path):
    pod = _bare_scan_pod(tmp_path)
    from vgate_tpu import metrics as m

    before = m.GATEWAY_RESTARTS._value.get()
    assert pod._scan_registry() == {}
    assert m.GATEWAY_RESTARTS._value.get() == before


def test_scan_registry_any_record_counts_restart(tmp_path):
    pod = _bare_scan_pod(tmp_path)
    _write_rec(tmp_path, 0, status="exited")
    from vgate_tpu import metrics as m

    before = m.GATEWAY_RESTARTS._value.get()
    assert pod._scan_registry() == {}
    assert m.GATEWAY_RESTARTS._value.get() == before + 1
